"""End-to-end conservation invariants for chaos soaking.

PR 3 built the fault-injection and recovery machinery, and its review
still found failure-path bugs *by hand* — every one of them an
instance of a checkable global law (a finished request vanished across
a step fault; ``drain()`` dropped results it had already collected; a
timeout multiplied by the handle count). This module states those laws
once, as code, so the chaos scheduler (``resilience/chaos.py``) can
assert them after every randomized episode instead of waiting for the
next reviewer to spot the next instance:

- **Request conservation** (:class:`ConservationLedger`): every
  submitted request is delivered to a caller exactly once — via a
  ``step()`` return, a ``recover()`` report, a ``drain()`` return, or
  a successful ``cancel()`` — across any number of step faults and
  recoveries. Never lost, never duplicated, always in a terminal
  state. The serving engine feeds the ledger through its ``auditor``
  hooks at exactly the external delivery boundaries.
- **Greedy token identity** (:func:`token_prefix_violations`): a
  request's delivered tokens are a prefix of the uninjected greedy
  replay of the same prompt — faults and recoveries may shorten output
  (deadline/cancel) but never corrupt it. SPECULATIVE engines are
  audited against the same non-speculative references, so draft
  acceptance and rejected-tail rollback sit under this law too: a
  broken acceptance rule reads as divergence, not as a new invariant.
- **Loss-trajectory continuity** (:func:`loss_trajectory_violations`):
  every (step, loss) a resilient training run reports matches the
  uninjected baseline bit-for-bit, whatever crashes and restores
  happened in between.
- **Checkpoint-generation monotonicity**
  (:func:`checkpoint_monotonic_violations`): the LATEST pointer never
  moves backwards and always names a loadable checkpoint, with torn
  shard files from interrupted saves tolerated.
- **No leaks** (:func:`engine_leak_violations`,
  :func:`page_leak_violations`, :func:`thread_leak_violations`,
  :func:`pending_save_violations`): a quiesced engine holds no slots,
  queue entries, or undelivered requests; every paged-KV refcount is
  back to zero (pages free or cached, reservations returned, no
  stale page-table rows); an episode spawns no surviving non-daemon
  threads and settles every async save handle.

Checkers return a list of human-readable violation strings (empty =
invariant holds) so one episode can report every broken law at once;
``ConservationLedger.check()`` wraps that in a raised
:class:`InvariantViolation` for direct test use. Everything here is
stdlib+engine-state only — no clocks, no randomness — so a violation
is a deterministic function of the episode it audits.
"""
from __future__ import annotations

import threading
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

__all__ = ["InvariantViolation", "ConservationLedger",
           "token_prefix_violations", "engine_leak_violations",
           "page_leak_violations", "router_leak_violations",
           "frontdoor_leak_violations",
           "thread_leak_violations", "pending_save_violations",
           "loss_trajectory_violations",
           "checkpoint_monotonic_violations",
           "timeline_violations"]


class InvariantViolation(AssertionError):
    """A conservation law broke; the message lists every violation."""

    def __init__(self, violations: Sequence[str]):
        self.violations = list(violations)
        super().__init__(
            f"{len(self.violations)} invariant violation(s):\n  - "
            + "\n  - ".join(self.violations))


class ConservationLedger:
    """Double-entry accounting for serving requests.

    Plug into the engine (``ServingEngine(..., auditor=ledger)``): the
    engine calls :meth:`on_submitted` once per accepted ``submit()``
    and :meth:`on_delivered` each time a request surfaces at an
    external boundary (``step`` / ``recover`` / ``drain`` / ``cancel``
    — internal step() calls inside drain() are NOT boundaries).
    :meth:`violations` then audits the books: every submission must
    have exactly one delivery, every delivery a submission, and every
    delivered request a terminal state.

    Mounted at the FRONT DOOR (``serving/frontdoor.py``) the ledger
    additionally audits the admission boundary itself: the front door
    calls :meth:`on_attempt` once per client call and then either
    :meth:`on_submitted` (accepted) or :meth:`on_rejected` (typed
    refusal) — exactly one outcome per attempt, so a request cannot
    vanish between the client and the router.
    """

    def __init__(self):
        self.submitted: Dict[int, object] = {}        # rid -> Request
        self.delivered: Dict[int, List[str]] = {}     # rid -> [via...]
        self.attempts = 0
        self.rejected: List[Tuple[str, str]] = []   # (tenant, reason)

    # -- hooks (the engine calls these) --------------------------------
    def on_attempt(self) -> None:
        self.attempts += 1

    def on_rejected(self, tenant: str = "", reason: str = "") -> None:
        self.rejected.append((tenant, reason))

    def on_submitted(self, req) -> None:
        if req.rid in self.submitted:
            # recorded as a delivery-side anomaly at audit time
            self.delivered.setdefault(req.rid, []).append("resubmit!")
        self.submitted[req.rid] = req

    def on_delivered(self, req, via: str = "step") -> None:
        self.delivered.setdefault(req.rid, []).append(via)

    # -- audit ---------------------------------------------------------
    def violations(self) -> List[str]:
        out = []
        for rid, req in sorted(self.submitted.items()):
            vias = self.delivered.get(rid, [])
            if not vias:
                out.append(
                    f"request {rid} LOST: submitted, reached "
                    f"finished={req.finished} "
                    f"reason={req.finish_reason!r}, never delivered")
            elif len(vias) > 1:
                out.append(
                    f"request {rid} DELIVERED {len(vias)} times "
                    f"(via {vias})")
            if vias and not req.finished:
                out.append(
                    f"request {rid} delivered via {vias} but not in a "
                    f"terminal state (finished=False)")
            if vias and req.finished and req.finish_reason is None:
                out.append(
                    f"request {rid} finished without a finish_reason")
        for rid, vias in sorted(self.delivered.items()):
            if rid not in self.submitted:
                out.append(
                    f"request {rid} delivered via {vias} but never "
                    f"submitted (phantom)")
        # front-door admission law: every attempt gets exactly one
        # outcome (accept | typed reject) — only audited when the
        # boundary reports attempts at all
        if self.attempts:
            outcomes = len(self.submitted) + len(self.rejected)
            if outcomes != self.attempts:
                out.append(
                    f"front door saw {self.attempts} attempts but "
                    f"recorded {len(self.submitted)} accepts + "
                    f"{len(self.rejected)} rejects = {outcomes} "
                    f"outcomes (a request LOST — vanished at the "
                    f"boundary without an audited accept or reject)")
        return out

    def check(self) -> None:
        v = self.violations()
        if v:
            raise InvariantViolation(v)


def timeline_violations(telemetry, requests) -> List[str]:
    """Chaos trace-conservation law: every request the ledger marks
    DELIVERED has a complete merged timeline — a ``router.dispatch``
    span; a ``serving.prefill`` span if it produced tokens; a
    ``serving.decode``/``serving.verify`` span if it produced more
    than one; and, when its spans come from two different worker
    processes, a ``router.failover.rehome`` span linking the lanes.

    The law is loss-aware, not loss-blind: when the telemetry plane
    DETECTED a dropped scrape (``scrape_losses`` carries a degrading
    kind), worker-side span checks are skipped for the episode —
    detection is the requirement; a detected loss must not read as a
    phantom violation — while host-side spans (dispatch, rehome),
    which never cross the scrape, stay mandatory.
    """
    from ..observability.timeline import _HOST_PROCS, _span_rids
    out: List[str] = []
    # ANY recorded loss degrades: a SIGKILLed worker takes its
    # un-scraped buffer with it, and a drain can deliver several
    # steps between scrapes — so even "worker_died" may have eaten
    # spans of a delivered request.
    degraded = bool(telemetry.scrape_losses())
    per: Dict[int, List[dict]] = {}
    for rec in telemetry.aligned_spans():
        for rid in _span_rids(rec):
            per.setdefault(rid, []).append(rec)
    for req in requests:
        recs = per.get(req.rid, [])
        names = {r["name"] for r in recs}
        if "router.dispatch" not in names:
            out.append(
                f"request {req.rid} delivered but the merged timeline "
                f"has no router.dispatch span")
        if degraded:
            continue
        if req.out_tokens and "serving.prefill" not in names:
            out.append(
                f"request {req.rid} delivered {len(req.out_tokens)} "
                f"tokens but the merged timeline has no "
                f"serving.prefill span")
        if len(req.out_tokens) > 1 and not names & {
                "serving.decode", "serving.verify"}:
            out.append(
                f"request {req.rid} delivered {len(req.out_tokens)} "
                f"tokens but the merged timeline has no decode/verify "
                f"span")
        worker_pids = {int(r.get("pid", 0)) for r in recs
                       if str(r.get("proc")) not in _HOST_PROCS}
        if len(worker_pids) >= 2 \
                and "router.failover.rehome" not in names:
            out.append(
                f"request {req.rid} has spans from worker pids "
                f"{sorted(worker_pids)} but no router.failover.rehome "
                f"span links its lanes")
    return out


def token_prefix_violations(
        pairs: Iterable[Tuple[object, Sequence[int]]]) -> List[str]:
    """Greedy token identity vs the uninjected replay.

    ``pairs`` yields ``(request, reference_tokens)`` where
    ``reference_tokens`` is the clean greedy generation for the same
    prompt, at least as long as the request could have produced. A
    normally-finished request (``length``/``eos``) must match the
    reference exactly over its full output; a deadline-cancelled or
    caller-cancelled request may stop early but every token it DID
    deliver must still match (prefix property of greedy decoding:
    token *t* depends only on the prefix, so recovery re-prefills must
    reproduce it bit-for-bit).
    """
    out = []
    for req, ref in pairs:
        got = list(req.out_tokens)
        if len(got) > len(ref):
            out.append(
                f"request {req.rid} emitted {len(got)} tokens, "
                f"reference replay has only {len(ref)}")
            continue
        if got != list(ref[:len(got)]):
            out.append(
                f"request {req.rid} tokens diverged from the "
                f"uninjected replay: got {got}, want "
                f"{list(ref[:len(got)])} "
                f"(reason={req.finish_reason!r})")
        if req.finish_reason == "length" \
                and len(got) != req.max_new_tokens:
            out.append(
                f"request {req.rid} finished 'length' with "
                f"{len(got)}/{req.max_new_tokens} tokens")
    return out


def engine_leak_violations(engine) -> List[str]:
    """A quiesced engine must hold nothing: no leased slots, no queued
    requests, no undelivered terminal requests — and, on a SPECULATIVE
    engine, no draft-proposer state for requests that are no longer in
    a slot (eviction/deadline/cancel/recover must release it, or a
    long-lived engine's proposer index grows without bound). On a
    DISAGGREGATED mesh engine this is also the cross-group law's
    engine half: no request may still hold a KV span staged on the
    prefill group (computed but never installed on the decode pool —
    every handoff must complete or unwind); the decode-group half is
    :func:`page_leak_violations`, which audits the pool the handoff
    targets."""
    out = []
    staged = getattr(engine, "_staged_handoffs", None)
    if staged:
        out.append(
            f"staged KV handoffs for rids {sorted(staged)} never "
            f"installed on the decode group or unwound")
    active = engine.cache.active_slots()
    if active:
        out.append(
            f"leaked slots {active}: "
            f"{[engine.cache.slots[s].rid for s in active]}")
    queued = engine.scheduler.pending()
    if queued:
        out.append(
            f"leaked queue entries {[r.rid for r in queued]}")
    if engine._undelivered:
        out.append(
            f"undelivered terminal requests "
            f"{[r.rid for r in engine._undelivered]}")
    if getattr(engine, "speculative", False):
        live = {engine.cache.slots[s].rid
                for s in engine.cache.active_slots()}
        # EVERY configured proposer is audited, not just the active
        # one: the tuner may have routed requests through either, and
        # the draft proposer additionally leases KV-pool slots whose
        # leak this catches (free_slots exhaustion = silent k=1
        # degrade, invisible to token identity)
        props = getattr(engine, "_proposers", None) \
            or {"ngram": engine.proposer}
        for kind in sorted(props):
            stale = [rid for rid in props[kind].tracked()
                     if rid not in live]
            if stale:
                out.append(
                    f"leaked {kind} draft-proposer state for rids "
                    f"{stale} (request gone, proposer state still "
                    f"held)")
    # chunked-prefill half of the law: a quiesced engine may hold no
    # PREFILLING work — the chunk FIFO must be empty (every chunked
    # admission either finished its final chunk or was unwound) and no
    # per-request local KV buffers may survive (disaggregated chunk
    # prefills stage them until the final-chunk handoff)
    fifo = getattr(engine, "_chunk_fifo", None)
    if fifo:
        out.append(
            f"leaked PREFILLING slots {list(fifo)} in the chunk FIFO "
            f"(mid-prefill request neither finished nor unwound)")
    local = getattr(engine, "_chunk_local", None)
    if local:
        out.append(
            f"leaked chunk-local KV buffers for rids {sorted(local)}")
    # tiered-KV half: a quiesced engine may hold no request staged
    # mid-promotion (dst pages claimed, host payload not installed) —
    # every promotion must commit or unwind through abort_sequence
    promos = getattr(engine, "_staged_promotions", None)
    if promos:
        out.append(
            f"staged KV promotions for rids {sorted(promos)} never "
            f"committed or unwound")
    return out


def page_leak_violations(engine) -> List[str]:
    """No-leaked-pages law for the PAGED KV cache: once an engine
    quiesces (drain/recover complete, no active slots), every page
    refcount must be back to zero — each page is either on the free
    list or parked refcount-0 in the prefix index (cached), the
    reservation budget is fully returned, and no freed slot's page
    table row still points at a page. A violation means some
    failure path (aborted prefill, eviction, deadline cancel,
    recover) dropped a refcount on the floor — exactly the class of
    bug paging adds to the engine's failure surface.

    No-op (empty) for a contiguous-pool engine."""
    cache = engine.cache
    if not getattr(engine, "paged", False):
        return []
    out = []
    import numpy as np
    referenced = np.nonzero(cache.refcnt[1:] > 0)[0] + 1
    if len(referenced):
        out.append(
            f"leaked page refcounts: pages {referenced.tolist()} "
            f"held {cache.refcnt[referenced].tolist()} refs after "
            f"quiesce")
    if cache.committed_pages != 0:
        out.append(
            f"leaked page reservations: committed budget "
            f"{cache.committed_pages} != 0 after quiesce")
    if cache._plans:
        out.append(
            f"leaked admission plans for rids "
            f"{sorted(cache._plans)}")
    exact_cached = sum(1 for n in cache._node_of_page.values()
                       if cache.refcnt[n.page] == 0)
    if exact_cached != cache.cached_page_count():
        out.append(
            f"cached-page counter drifted: maintained "
            f"{cache.cached_page_count()} != scanned {exact_cached}")
    accounted = cache.free_page_count() + exact_cached
    if accounted != cache.num_pages - 1:
        out.append(
            f"page accounting hole: free ({cache.free_page_count()})"
            f" + cached ({exact_cached}) != "
            f"{cache.num_pages - 1} usable pages")
    rows = np.nonzero(cache.page_table.any(axis=1))[0]
    stale = [int(s) for s in rows if cache.slots[s] is None]
    if stale:
        out.append(
            f"freed slots {stale} still hold page-table entries "
            f"{[cache.page_table[s].tolist() for s in stale]}")
    # host/disk tier half of the law, when the cache is tiered: every
    # promotion pin must be returned, every RAM-resident key must be
    # anchored by a live HOST node in the radix tree (an unanchored
    # buffer is host memory nothing can ever promote or evict —
    # the cross-tier leak), and every HOST node must resolve to tier
    # data (a dataless node would promote garbage)
    tier = getattr(cache, "tier", None)
    if tier is not None:
        pins = {k: c for k, c in tier.pin_counts().items() if c}
        if pins:
            out.append(
                f"leaked tier pins after quiesce: "
                f"{[(len(k), c) for k, c in sorted(pins.items())]} "
                f"(key_len, count)")
        host_keys = set()
        stack = [cache._root]
        while stack:
            nd = stack.pop()
            stack.extend(nd.children.values())
            if nd.page < 0:
                host_keys.add(cache._node_key(nd))
        orphans = [k for k in tier.ram_keys() if k not in host_keys]
        if orphans:
            out.append(
                f"orphaned host-tier buffers: {len(orphans)} RAM "
                f"entries (lens {sorted(len(k) for k in orphans)}) "
                f"with no HOST radix node anchoring them")
        dead = [k for k in host_keys if not tier.has(k)]
        if dead:
            out.append(
                f"dataless HOST radix nodes: {len(dead)} nodes "
                f"(lens {sorted(len(k) for k in dead)}) whose tier "
                f"entry is gone — a match would promote garbage")
    return out


def router_leak_violations(router) -> List[str]:
    """Cross-replica no-leak law: a quiesced router tracks nothing
    (its exactly-once in-flight table is empty) and every LIVE replica
    passes the single-engine leak audits — slots, queue entries,
    undelivered terminal requests, and paged-KV refcounts. DEAD
    replicas are exempt from engine/page audits (their pools died with
    the process; what must not leak is REQUESTS, which the in-flight
    table and the conservation ledger audit), but failover must have
    left their host containers empty — a request still sitting in a
    dead replica is a request nobody will ever serve."""
    out = []
    if router._inflight:
        out.append(
            f"router still tracks rids "
            f"{sorted(router._inflight)} after quiesce")
    for rep in router.replicas:
        if rep.state == "dead":
            eng = rep.engine
            stranded = [r.rid for r in eng.scheduler.pending()]
            stranded += [eng.cache.slots[s].rid
                         for s in eng.cache.active_slots()]
            stranded += [r.rid for r in eng._undelivered]
            if stranded:
                out.append(
                    f"dead replica {rep.id} still holds rids "
                    f"{sorted(stranded)} (failover left them behind)")
            continue
        for v in engine_leak_violations(rep.engine):
            out.append(f"replica {rep.id}: {v}")
        for v in page_leak_violations(rep.engine):
            out.append(f"replica {rep.id}: {v}")
    return out


def frontdoor_leak_violations(front) -> List[str]:
    """Boundary no-leak law: once the front door drains, every handle
    was closed out (no client left waiting forever) and every
    tenant's in-flight depth is back to zero."""
    out = []
    if front._handles:
        out.append(
            f"front door still holds handles for rids "
            f"{sorted(front._handles)} after quiesce")
    bad = {t: d for t, d in front._tenant_depth.items() if d != 0}
    if bad:
        out.append(f"tenant depth counters not back to zero: {bad}")
    return out


def thread_leak_violations(before: Iterable[threading.Thread]) \
        -> List[str]:
    """No NEW non-daemon thread may survive an episode (async
    checkpoint writers are daemons and must already be joined via
    ``wait_for_pending_saves``)."""
    known = set(before)
    out = []
    for t in threading.enumerate():
        if t not in known and t.is_alive() and not t.daemon:
            out.append(f"leaked non-daemon thread {t.name!r}")
    return out


def pending_save_violations() -> List[str]:
    """Every async checkpoint save is settled (the episode must call
    ``wait_for_pending_saves`` first; this audits that none raced
    past it)."""
    from ..distributed import checkpoint
    out = []
    for h in checkpoint._pending:
        if not h.done():
            out.append("async save handle still writing after the "
                       "episode settled")
    return out


def loss_trajectory_violations(
        reports: Sequence[dict],
        baseline_losses: Sequence[Tuple[int, float]]) -> List[str]:
    """Every (step, loss) recorded across the episode's run attempts
    (in-process restores AND process relaunches) must match the
    uninjected baseline, and each report must be one clean trajectory
    (strictly increasing steps — restores re-record, they don't
    duplicate)."""
    base = dict(baseline_losses)
    out = []
    for i, rep in enumerate(reports):
        steps = [s for s, _ in rep["losses"]]
        if steps != sorted(set(steps)):
            out.append(
                f"run {i}: loss trajectory not strictly increasing "
                f"({steps})")
        for s, l in rep["losses"]:
            if s not in base:
                out.append(f"run {i}: loss recorded for unknown "
                           f"step {s}")
            elif l != base[s]:
                out.append(
                    f"run {i}: loss at step {s} diverged from the "
                    f"uninjected baseline: {l!r} != {base[s]!r}")
    return out


def checkpoint_monotonic_violations(
        ckpt_dir: str, template_factory,
        latest_history: Sequence[Optional[int]] = (),
        expect_final: Optional[int] = None) -> List[str]:
    """The LATEST pointer never moves backwards and always names a
    loadable checkpoint, whatever torn shard files interrupted saves
    left behind.

    ``template_factory`` builds a fresh state template for
    ``load_state_dict``; ``latest_history`` is the sequence of LATEST
    values the episode observed (None = not yet published) and must be
    non-decreasing; ``expect_final`` pins the final pointer value.
    """
    import os

    from ..distributed.checkpoint import load_state_dict
    out = []
    seen = [s for s in latest_history if s is not None]
    if any(b < a for a, b in zip(seen, seen[1:])):
        out.append(f"LATEST moved backwards: {seen}")
    p = os.path.join(ckpt_dir, "LATEST")
    if not os.path.exists(p):
        out.append(f"no LATEST pointer under {ckpt_dir}")
        return out
    with open(p) as f:
        latest = int(f.read().strip())
    if expect_final is not None and latest != expect_final:
        out.append(f"LATEST == {latest}, expected {expect_final}")
    if seen and latest < seen[-1]:
        out.append(
            f"final LATEST {latest} older than observed {seen[-1]}")
    try:
        tmpl = template_factory()
        load_state_dict(tmpl, os.path.join(ckpt_dir,
                                           f"step_{latest}"))
        if int(tmpl["step"]) != latest:
            out.append(
                f"LATEST checkpoint carries step {tmpl['step']}, "
                f"pointer says {latest}")
    except Exception as e:      # noqa: BLE001 — any load failure is
        out.append(             # exactly what this invariant forbids
            f"LATEST checkpoint step_{latest} failed to load: "
            f"{type(e).__name__}: {e}")
    return out
