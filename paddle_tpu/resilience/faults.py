"""Deliberate fault injection: named fault points, activated on demand.

Crash-only-software discipline: every recovery path in the framework is
exercised by *injecting* the failure it claims to survive, on CPU, in
tier-1 tests — not by waiting for a TPU pod to actually lose a host.
Instrumented layers call ``maybe_fail("<point>")`` at the spots where
real systems die; the call is one cached bool plus one env probe when
no fault is armed, and raises :class:`InjectedFault` (or a
caller-chosen exception type) when one is.

Wired-in points (see docs/RESILIENCE.md for the catalogue):

===========================  ===========================================
``serving.step.decode``      right before the decode-step jit call
``serving.decode.verify``    mid-verify-step (speculative decoding)
``serving.decode.sharded``   mesh engines, before the SHARDED program
``serving.step.prefill``     inside the (re-)prefill program driver
``serving.prefill.paged``    paged prefill, AFTER pages are claimed
``serving.prefill.chunk``    between chunks of a chunked prefill
``serving.kv.handoff``       disaggregated prefill->decode KV handoff
``serving.kv.demote``        tier demotion, BEFORE either tier mutates
``serving.kv.promote``       tier promotion, pages staged, not installed
``router.dispatch``          router submit, before replica binding
``router.health_probe``      inside the per-round replica probe
``frontdoor.stream_write``   writing a token/done event to a client
``frontdoor.client_disconnect``  the client-liveness probe
``cluster.rpc.send``         socket framing, before a frame is written
``cluster.rpc.recv``         socket framing, after a frame header is read
``control.shed/chunk/affinity/scale``  control-plane actuator, per kind
``store.set/get/add/wait``   TCPStore client ops, before the C call
``checkpoint.shard_write``   inside the retried per-file shard write
``checkpoint.commit``        after shards, BEFORE the metadata flip
``watchdog.beat``            heartbeat publish
``io.dataloader.worker``     per task/batch in dataloader workers
``train.step``               ResilientTrainLoop, before step_fn
===========================  ===========================================

Activation is programmatic::

    from paddle_tpu.resilience import faults
    faults.inject("serving.step.decode", times=1, after=3)
    with faults.injected("store.get", times=2, exc=ConnectionError):
        ...

or via ``PTPU_FAULTS`` (inherited by forked dataloader workers)::

    PTPU_FAULTS="serving.step.decode:1@3,io.dataloader.worker:1"
    PTPU_FAULTS="store.get:p0.25~seed7"        # seeded Bernoulli per hit

Grammar: ``point:TIMES[@SKIP]`` fails TIMES times after skipping SKIP
hits; ``point:pRATE~seedSEED`` fails each hit with probability RATE from
a deterministic per-point RNG. Schedules are deterministic: the same
arm + the same hit sequence fires the same faults.

Every evaluation is counted per point (``hits()``) and every raise is
counted per point (``fired()``) and bumped on the
``ptpu_fault_injections_total{point}`` observability counter, so tests
can assert both that a recovery path works *and* that the fault point
it rides is still wired.

stdlib-only on purpose: imported by dataloader worker processes (no jax
post-fork) and by the TCPStore client.
"""
from __future__ import annotations

import contextlib
import os
import random
import threading
from typing import Dict, Optional

__all__ = ["InjectedFault", "maybe_fail", "inject", "clear", "injected",
           "hits", "fired", "reset_counts", "parse_spec",
           "KNOWN_POINTS"]

# The registered fault-point catalogue (must match the call sites and
# the docs/RESILIENCE.md table). The chaos sweep (resilience/chaos.py)
# samples its randomized schedules over THIS tuple, so adding a point
# here (after instrumenting a call site) automatically enrolls it in
# the soak; tests/test_chaos.py asserts the sweep covers every entry.
KNOWN_POINTS = (
    "serving.step.decode",
    # speculative verify step: drafts built, pages claimed/COW'd,
    # the widened program not yet run — recovery must replay
    # token-identically and the page rollback must leak nothing
    "serving.decode.verify",
    # tensor-parallel engines (ServingEngine(mesh=...)): right before
    # the SHARDED decode/verify program — recovery must rebuild the
    # mesh-sharded pools and replay token-identically
    "serving.decode.sharded",
    "serving.step.prefill",
    # mid-prefill on the PAGED cache: pages claimed, table row live,
    # prefill program not yet run — the abort path must return them
    "serving.prefill.paged",
    # chunked prefill: between chunks of a PREFILLING request — slot
    # leased, pages claimed, part of the prompt already written — the
    # unwind must free the pages AND the slot lease and requeue the
    # request (replay re-chunks token-identically)
    "serving.prefill.chunk",
    # speculative draft proposal (one row, pre-forward): the engine
    # must contain the failure to THAT row's step — fall back to k=1,
    # unwind the proposer's per-rid state, never drop the request
    # (the conservation ledger catches the pre-fix request-fatal
    # shape; see _on_draft_fault)
    "serving.spec.draft",
    # sampled-acceptance resampling: first draft rejection, residual
    # distribution about to be sampled — tokens already accepted this
    # step stay appended, the retried step continues from the
    # advanced position (exactly-once delivery, page debt repaid by
    # the emission-loop rollback arm)
    "serving.spec.resample",
    # disaggregated prefill/decode: the KV span is computed on the
    # prefill group but NOT yet installed on the decode pool — the
    # abort path must unwind the half-handed-off request on BOTH
    # groups (page claims returned, staged span dropped)
    "serving.kv.handoff",
    # KV tiering (serving/kv_tier.py): demotion fires BEFORE any
    # state moves device -> host, so a raise leaves both tiers
    # untouched; promotion fires with the request staged in
    # _staged_promotions and fresh device dst pages claimed but no
    # payload installed — the unwind must return the dst pages AND
    # the tier pins (neither tier may leak)
    "serving.kv.demote",
    "serving.kv.promote",
    # router/front-door boundary (serving/router.py, frontdoor.py):
    # dispatch-path crash before a request binds to a replica; health-
    # probe infrastructure failure (must degrade to draining, never
    # lose requests); a client-stream write failing (broken pipe);
    # the client-liveness probe finding the client gone — including
    # MID-prefill, after KV pages are claimed
    "router.dispatch",
    "router.health_probe",
    "frontdoor.stream_write",
    "frontdoor.client_disconnect",
    # cluster RPC framing (distributed/_framing.py): fires inside
    # send_msg / recv_msg wherever the '<Q' framing is used (serving
    # cluster, rpc agent, dist_model_mp). recv fires AFTER the header
    # is consumed — the mid-frame partition case — and both surface as
    # typed ConnectionError (the socket is unusable afterwards).
    "cluster.rpc.send",
    "cluster.rpc.recv",
    # authenticated framing (distributed/_framing.py): fires inside
    # the handshake + per-frame MAC verification — an armed fault is a
    # counted typed AuthError (a ConnectionError), so blips below the
    # RPC retry budget reconnect + re-handshake invisibly and a
    # persistent mismatch exhausts into the ordinary failover
    "cluster.rpc.auth",
    # cross-host KV wire transfer (serving/kv_wire.py): fires inside
    # the per-attempt ship of a disaggregated prefill→decode handoff —
    # a raise is a typed retryable KVWireError; past the transport
    # retry budget it surfaces through _kv_handoff's staged abort path
    # (page claims returned, staged span dropped, request requeued)
    "cluster.kv.wire",
    # shared weight store (serving/weight_store.py): fires inside a
    # worker's digest-verified chunk fetch — a raise is a typed
    # retryable WeightStoreError; the worker retries and NEVER serves
    # silently wrong weights
    "cluster.weights.fetch",
    # control plane (serving/control.py): every actuation kind in the
    # shared Actuator threads its own point — a fired fault is
    # CONTAINED there (the one actuation is suppressed, the data
    # plane keeps its last applied setting, admission fails open), so
    # a sick control plane can only ever degrade the SLO, never the
    # conservation laws
    "control.shed",
    "control.chunk",
    "control.affinity",
    "control.scale",
    "store.set", "store.get", "store.add", "store.wait",
    "checkpoint.shard_write",
    "checkpoint.commit",
    "watchdog.beat",
    "io.dataloader.worker",
    "train.step",
)


class InjectedFault(RuntimeError):
    """The default exception a firing fault point raises."""

    def __init__(self, point: str, hit: int):
        super().__init__(f"injected fault at {point!r} (hit #{hit})")
        self.point = point
        self.hit = hit

    def __reduce__(self):
        # default exception pickling would replay __init__ with the
        # formatted message; these cross the serving-cluster RPC
        # boundary as shipped worker errors
        return type(self), (self.point, self.hit)


class _Rule:
    __slots__ = ("times", "after", "rate", "rng", "exc", "from_env")

    def __init__(self, times=None, after=0, rate=None, seed=None,
                 exc=None, from_env=False):
        if times is None and rate is None:
            times = 1
        self.times = times
        self.after = int(after)
        self.rate = rate
        self.rng = random.Random(seed) if rate is not None else None
        self.exc = exc
        self.from_env = from_env

    def should_fire(self) -> bool:
        if self.rate is not None:
            return self.rng.random() < self.rate
        if self.after > 0:
            self.after -= 1
            return False
        if self.times <= 0:
            return False
        self.times -= 1
        return True

    def make_exc(self, point: str, hit: int) -> BaseException:
        if self.exc is None:
            return InjectedFault(point, hit)
        if isinstance(self.exc, BaseException):
            return self.exc
        try:        # class or factory; fall back to a bare call
            return self.exc(f"injected fault at {point!r} (hit #{hit})")
        except TypeError:
            return self.exc()


_lock = threading.RLock()
_rules: Dict[str, _Rule] = {}
_hits: Dict[str, int] = {}
_fired: Dict[str, int] = {}
_env_cache: Optional[str] = None
# THE disarmed-hot-path flag: True exactly when _rules is empty.
# Every mutation of _rules (inject/clear/injected/_load_env) calls
# _recompute_disarmed(); maybe_fail's fast path reads this one
# cached bool plus one env probe and touches nothing else — no lock,
# no string compare against _env_cache, no dict truthiness walk.
_disarmed = True


def _recompute_disarmed() -> None:
    global _disarmed
    _disarmed = not _rules


def parse_spec(spec: str) -> Dict[str, _Rule]:
    """Parse a ``PTPU_FAULTS`` string into rules (exposed for tests)."""
    out: Dict[str, _Rule] = {}
    for entry in spec.split(","):
        entry = entry.strip()
        if not entry:
            continue
        if ":" not in entry:
            raise ValueError(
                f"bad PTPU_FAULTS entry {entry!r}: expected "
                f"'point:TIMES[@SKIP]' or 'point:pRATE[~seedN]'")
        point, _, arm = entry.rpartition(":")
        if arm.startswith("p"):
            rate_s, _, seed_s = arm[1:].partition("~seed")
            out[point] = _Rule(rate=float(rate_s),
                               seed=int(seed_s) if seed_s else 0,
                               from_env=True)
        else:
            times_s, _, after_s = arm.partition("@")
            out[point] = _Rule(times=int(times_s),
                               after=int(after_s) if after_s else 0,
                               from_env=True)
    return out


def _load_env(env: str) -> None:
    global _env_cache
    with _lock:
        _env_cache = env
        for point in [p for p, r in _rules.items() if r.from_env]:
            del _rules[point]
        try:
            _rules.update(parse_spec(env))
        except ValueError:
            # a malformed env spec must not take the process down from
            # inside an instrumented hot path; it just arms nothing
            pass
        _recompute_disarmed()


def maybe_fail(point: str, **ctx) -> None:
    """Evaluate the fault point; raise if a fault is armed and due.

    ``ctx`` kwargs are for call-site readability only (they document
    what the point guards); the raised exception carries the point name
    and per-point hit number.

    Disarmed cost is a single cached emptiness check (the
    ``_disarmed`` bool, maintained by every rule mutation) plus one
    env probe — no lock, no ``_env_cache`` string compare, no dict
    walk, no counting — because this sits in per-sample dataloader
    and per-op store hot paths (micro-asserted in tests/test_chaos.py:
    the disarmed path never touches ``_lock``). The env probe cannot
    be cached away: ``PTPU_FAULTS`` set mid-process (monkeypatch,
    forked workers) must arm lazily on the very next evaluation. Hit
    counts therefore accumulate only while at least one rule is armed
    (i.e. during chaos sessions, which is when tests assert wiring
    via ``hits()``/``fired()``).
    """
    if _disarmed and not os.environ.get("PTPU_FAULTS"):
        return
    env = os.environ.get("PTPU_FAULTS", "")
    if env != _env_cache:
        _load_env(env)
    if not _rules:
        return
    with _lock:
        _hits[point] = _hits.get(point, 0) + 1
        rule = _rules.get(point)
        if rule is None or not rule.should_fire():
            return
        _fired[point] = _fired.get(point, 0) + 1
        exc = rule.make_exc(point, _fired[point])
    try:        # observability is optional here (forked workers, early
        from ..observability import default_registry   # import paths)
        default_registry().counter(
            "ptpu_fault_injections_total",
            "deliberately injected faults (resilience.faults)",
            labels=("point",)).labels(point=point).inc()
    except Exception:
        pass
    raise exc


def inject(point: str, times: Optional[int] = None, after: int = 0,
           rate: Optional[float] = None, seed: Optional[int] = None,
           exc=None) -> None:
    """Arm a fault at ``point``: fail ``times`` times after skipping
    ``after`` hits, or (``rate``) each hit with seeded probability.
    ``exc`` overrides the raised exception (instance, class, or
    factory)."""
    with _lock:
        _rules[point] = _Rule(times=times, after=after, rate=rate,
                              seed=seed, exc=exc)
        _recompute_disarmed()


def clear(point: Optional[str] = None) -> None:
    """Disarm one point, or every point (``None``)."""
    with _lock:
        if point is None:
            _rules.clear()
        else:
            _rules.pop(point, None)
        _recompute_disarmed()


@contextlib.contextmanager
def injected(point: str, times: Optional[int] = None, after: int = 0,
             rate: Optional[float] = None, seed: Optional[int] = None,
             exc=None):
    """Scoped ``inject``: restores the point's previous rule on exit."""
    with _lock:
        prev = _rules.get(point)
    inject(point, times=times, after=after, rate=rate, seed=seed,
           exc=exc)
    try:
        yield
    finally:
        with _lock:
            if prev is None:
                _rules.pop(point, None)
            else:
                _rules[point] = prev
            _recompute_disarmed()


def hits(point: Optional[str] = None):
    """Evaluation count per point (dict), or for one point (int).
    Counted only while at least one rule is armed (the disarmed hot
    path skips all bookkeeping)."""
    with _lock:
        if point is not None:
            return _hits.get(point, 0)
        return dict(_hits)


def fired(point: Optional[str] = None):
    """Raise count per point (dict), or for one point (int)."""
    with _lock:
        if point is not None:
            return _fired.get(point, 0)
        return dict(_fired)


def reset_counts() -> None:
    with _lock:
        _hits.clear()
        _fired.clear()
