"""Fault-tolerant training driver: watchdog + periodic async
checkpoints + restore-latest-then-continue.

This composes the three pieces the repo already had but never wired
end-to-end (ISSUE 3): ``CommWatchdog.check()`` at step boundaries,
CheckFreq-style frequent low-overhead checkpointing via
``distributed.checkpoint.save_state_dict(async_save=True)``, and — the
part that was missing — an automatic restore-latest-and-continue path
when a step dies, so a transient failure costs ``<= save_every`` steps
of recompute instead of the whole run.

Checkpoint layout is the ElasticManager contract (``step_{n}/`` dirs +
a ``LATEST`` pointer under ``checkpoint_dir``), with one correctness
upgrade: ``LATEST`` flips (atomic ``os.replace``) only after the async
save's writer thread has *completed*, so a crash mid-save can never
leave ``LATEST`` pointing at a torn checkpoint. A job relaunched by the
elastic launcher (``ELASTIC_EXIT_CODE``) therefore resumes from the
same directory this driver writes — in-process recovery and
process-relaunch recovery share one on-disk format.

Contract for ``step_fn(state, step) -> loss``: it must be restartable —
running it again from checkpointed ``state`` reproduces the run (the
chaos test pins loss-curve continuity across an injected mid-run
crash). ``state`` is a (nested) dict whose Tensor/ndarray leaves are
checkpointed in place; non-tensor leaves ride the checkpoint metadata.

    loop = ResilientTrainLoop(step_fn, state, ckpt_dir, save_every=20,
                              watchdog=wd)
    report = loop.run(num_steps=1000)

Peer failures (watchdog) propagate — a dead peer is not survivable from
inside one process; the launcher's relaunch loop (fleet.elastic) owns
that, and this driver's on-start auto-resume completes the circle.
"""
from __future__ import annotations

import os
import time
from typing import Callable, Dict, Optional, Tuple

from . import faults
from .retry import RetryPolicy

__all__ = ["ResilientTrainLoop", "TrainLoopError",
           "RestartLimitExceeded"]


class TrainLoopError(RuntimeError):
    pass


class RestartLimitExceeded(TrainLoopError):
    """More step failures than ``max_recoveries``; chains from the last
    step exception."""


class ResilientTrainLoop:
    def __init__(self, step_fn: Callable, state: Dict,
                 checkpoint_dir: str, *, save_every: int = 50,
                 watchdog=None, max_recoveries: int = 3,
                 recoverable: Tuple = (Exception,),
                 retry_policy: Optional[RetryPolicy] = None,
                 final_save: bool = True,
                 registry=None, flight_recorder=None,
                 time_fn: Callable[[], float] = time.monotonic):
        if save_every < 1:
            raise ValueError(
                f"save_every must be >= 1, got {save_every}")
        self.step_fn = step_fn
        self.state = state
        self.checkpoint_dir = checkpoint_dir
        self.save_every = int(save_every)
        self.watchdog = watchdog
        self.max_recoveries = int(max_recoveries)
        self.recoverable = recoverable
        self.retry_policy = retry_policy
        self.final_save = final_save
        self.now = time_fn
        from ..observability import default_recorder, default_registry
        # `is None`, not truthiness: an empty FlightRecorder is falsy
        self.recorder = flight_recorder if flight_recorder is not None \
            else default_recorder()
        reg = registry if registry is not None else default_registry()
        self.registry = reg
        self._m_steps = reg.counter(
            "ptpu_train_steps_total", "training steps completed")
        self._m_ckpts = reg.counter(
            "ptpu_train_checkpoints_total",
            "checkpoints published (LATEST flipped)")
        self._m_ckpt_fail = reg.counter(
            "ptpu_train_checkpoint_failures_total",
            "async checkpoint saves that errored (LATEST kept)")
        self._m_recoveries = reg.counter(
            "ptpu_train_recoveries_total",
            "step failures absorbed by restore-latest-and-continue")
        # (step, AsyncSaveHandle) of the in-flight async save, if any
        self._pending: Optional[Tuple[int, object]] = None

    # -- checkpoint protocol (ElasticManager layout) -------------------
    def _ckpt_path(self, step: int) -> str:
        return os.path.join(self.checkpoint_dir, f"step_{step}")

    def _wrapped(self, step: int) -> Dict:
        # "step" rides the checkpoint's non-tensor metadata; load fills
        # it back so restore knows how many steps are complete
        return {"state": self.state, "step": int(step)}

    def latest_step(self) -> Optional[int]:
        p = os.path.join(self.checkpoint_dir, "LATEST")
        if not os.path.exists(p):
            return None
        with open(p) as f:
            return int(f.read().strip())

    def _publish(self, step: int) -> None:
        """Atomically flip LATEST — the resume commit point."""
        p = os.path.join(self.checkpoint_dir, "LATEST")
        tmp = p + ".tmp"
        with open(tmp, "w") as f:
            f.write(str(step))
        os.replace(tmp, p)
        self._m_ckpts.inc()

    def _save_async(self, step: int) -> None:
        from ..distributed.checkpoint import save_state_dict
        # one async save in flight at a time: settle (publish or
        # discard) the previous one before starting the next
        self._settle_pending(wait=True)
        os.makedirs(self.checkpoint_dir, exist_ok=True)
        handle = save_state_dict(self._wrapped(step),
                                 self._ckpt_path(step), async_save=True)
        self._pending = (step, handle)

    def _settle_pending(self, wait: bool = False) -> None:
        """Publish the pending async save once its writer finished; a
        failed save is counted and dropped (LATEST keeps pointing at
        the previous good checkpoint — training state in memory is
        fine, the next save point tries again)."""
        if self._pending is None:
            return
        step, handle = self._pending
        if not wait and not handle.done():
            return
        self._pending = None
        try:
            handle.wait()
        except Exception as e:
            self._m_ckpt_fail.inc()
            self.recorder.record("train.ckpt_error", step=step,
                                 error=f"{type(e).__name__}: {e}")
            return
        self._publish(step)

    def restore_latest(self) -> Optional[int]:
        """Load the newest published checkpoint into ``state`` (in
        place) and return its completed-step count, or None."""
        step = self.latest_step()
        if step is None:
            return None
        from ..distributed.checkpoint import load_state_dict
        tmpl = self._wrapped(0)
        load_state_dict(tmpl, self._ckpt_path(step))
        return int(tmpl["step"])

    # -- the driver ----------------------------------------------------
    def _beat_and_check(self) -> None:
        if self.watchdog is None:
            return
        if self.retry_policy is not None:
            self.retry_policy.call(self.watchdog.beat,
                                   op="watchdog.beat")
        else:
            self.watchdog.beat()
        # peer failures propagate: not survivable in-process (the
        # launcher's relaunch loop owns that; on restart, run() resumes
        # from LATEST automatically)
        self.watchdog.check()

    def run(self, num_steps: int) -> Dict:
        """Drive ``step_fn`` to ``num_steps`` completed steps, saving
        every ``save_every`` and auto-resuming from the latest
        published checkpoint on start and after recoverable step
        failures. Returns a report dict (losses, recoveries, restores,
        published checkpoints)."""
        report = {"losses": [], "recoveries": 0, "restores": [],
                  "published": [], "start_step": 0}
        resumed = self.restore_latest()
        step = 0 if resumed is None else resumed
        report["start_step"] = step
        while step < num_steps:
            self._beat_and_check()
            self._settle_pending()
            try:
                faults.maybe_fail("train.step", step=step)
                loss = self.step_fn(self.state, step)
            except self.recoverable as e:
                report["recoveries"] += 1
                self._m_recoveries.inc()
                self.recorder.record(
                    "train.crash", step=step,
                    error=f"{type(e).__name__}: {e}")
                if report["recoveries"] > self.max_recoveries:
                    raise RestartLimitExceeded(
                        f"{report['recoveries']} step failures > "
                        f"max_recoveries={self.max_recoveries}") from e
                # an in-flight async save that completes is a
                # legitimate (newer) restore point — settle it first
                self._settle_pending(wait=True)
                restored = self.restore_latest()
                if restored is None:
                    # nothing to restore to: the crash may have left
                    # `state` torn, so continuing silently would train
                    # on garbage
                    raise TrainLoopError(
                        "step failed before the first checkpoint was "
                        "published; nothing to restore") from e
                step = restored
                # drop losses past the restore point: the replayed
                # steps re-record, and the reported curve stays a
                # single clean trajectory (no duplicate step entries)
                report["losses"] = [(s, l) for s, l in report["losses"]
                                    if s < restored]
                report["restores"].append(restored)
                self.recorder.record("train.restore", step=restored)
                continue
            report["losses"].append((step, float(loss)))
            self._m_steps.inc()
            step += 1
            if step % self.save_every == 0:
                self._save_async(step)
        self._settle_pending(wait=True)
        if self.final_save and self.latest_step() != num_steps:
            from ..distributed.checkpoint import save_state_dict
            handle = save_state_dict(self._wrapped(num_steps),
                                     self._ckpt_path(num_steps),
                                     async_save=False)
            handle.wait()
            self._publish(num_steps)
        report["published"] = self._published_steps()
        return report

    def _published_steps(self):
        latest = self.latest_step()
        steps = []
        if os.path.isdir(self.checkpoint_dir):
            for name in os.listdir(self.checkpoint_dir):
                if name.startswith("step_"):
                    try:
                        steps.append(int(name[5:]))
                    except ValueError:
                        pass
        return sorted(s for s in steps
                      if latest is not None and s <= latest)
