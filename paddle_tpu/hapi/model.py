"""paddle.Model high-level API (reference: python/paddle/hapi/model.py:1472
Model with .prepare/.fit/.evaluate/.predict/.save; DynamicGraphAdapter
:713). Single adapter here: eager + optional jitted train step."""
from __future__ import annotations

import time
from typing import List, Optional

import numpy as np

from ..framework.io import load as fw_load
from ..framework.io import save as fw_save
from ..framework.tensor import Tensor, no_grad
from ..io import DataLoader
from ..metric import Metric
from ..nn.layer_base import Layer
from .callbacks import Callback, ProgBarLogger, config_callbacks

__all__ = ["Model"]


class _InputSpecLike:
    pass


class Model:
    def __init__(self, network: Layer, inputs=None, labels=None):
        self.network = network
        self._optimizer = None
        self._loss = None
        self._metrics: List[Metric] = []
        self.stop_training = False

    # -- setup ------------------------------------------------------------
    def prepare(self, optimizer=None, loss=None, metrics=None,
                amp_configs=None, jit=None):
        """jit=None (auto): on accelerators the train step runs as ONE
        jitted program (forward+grad+update — the reference's to_static
        Engine path); on CPU it stays eager like reference dygraph. Pass
        jit=True/False to force either. Eager fallback also covers
        update=False micro-accumulation."""
        self._optimizer = optimizer
        self._loss = loss
        if jit is None:
            import jax
            jit = jax.default_backend() not in ("cpu",)
        self._jit_pref = bool(jit)
        self._use_jit = self._jit_pref and optimizer is not None \
            and loss is not None
        self._jit_step = None
        self._jit_fwd = None
        if metrics is None:
            self._metrics = []
        elif isinstance(metrics, Metric):
            self._metrics = [metrics]
        else:
            self._metrics = list(metrics)

    # -- core steps -------------------------------------------------------
    def train_batch(self, inputs, labels=None, update=True):
        self.network.train()
        inputs = _to_list(inputs)
        labels = _to_list(labels)
        if getattr(self, "_use_jit", False) and update \
                and not self._pending_grads():
            from ..jit.functional import TrainStep
            if self._jit_step is None or \
                    self._jit_step.num_labels != len(labels):
                self._jit_step = TrainStep(self.network, self._optimizer,
                                           self._loss,
                                           return_outputs=True,
                                           num_labels=len(labels))
            _, outs, comps = self._jit_step(*(inputs + labels))
            for m in self._metrics:
                m.update(m.compute(outs[0], *labels))
            return [float(c) for c in comps], \
                [m.accumulate() for m in self._metrics]
        outputs = self.network(*inputs)
        outs = _to_list(outputs)
        losses = self._loss(*(outs + labels))
        loss_list = _to_list(losses)
        total = loss_list[0]
        for extra in loss_list[1:]:
            total = total + extra
        total.backward()
        if update:
            self._optimizer.step()
            self._optimizer.clear_grad()
        metrics = []
        for m in self._metrics:
            m.update(m.compute(outs[0], *labels))
        return [float(l) for l in loss_list], \
            [m.accumulate() for m in self._metrics]

    def _pending_grads(self) -> bool:
        """True when eager update=False batches left accumulated grads —
        the jitted step computes fresh grads and would drop them, so
        finish the micro-batch group on the eager path."""
        return any(p.grad is not None
                   for p in self.network.parameters()
                   if not p.stop_gradient)

    def _forward(self, *inputs):
        """Eval/predict forward; one jitted program when jit is on."""
        if getattr(self, "_jit_pref", False):
            if self._jit_fwd is None:
                from .. import jit as _jit
                self._jit_fwd = _jit.to_static(self.network)
            return self._jit_fwd(*inputs)
        return self.network(*inputs)

    @no_grad()
    def eval_batch(self, inputs, labels=None):
        self.network.eval()
        inputs = _to_list(inputs)
        labels = _to_list(labels)
        outs = _to_list(self._forward(*inputs))
        losses = _to_list(self._loss(*(outs + labels))) if self._loss \
            else []
        for m in self._metrics:
            m.update(m.compute(outs[0], *labels))
        return [float(l) for l in losses], \
            [m.accumulate() for m in self._metrics]

    @no_grad()
    def predict_batch(self, inputs):
        self.network.eval()
        outs = self._forward(*_to_list(inputs))
        return _to_list(outs)

    # -- loops ------------------------------------------------------------
    def fit(self, train_data=None, eval_data=None, batch_size=1, epochs=1,
            eval_freq=1, log_freq=10, save_dir=None, save_freq=1,
            verbose=2, drop_last=False, shuffle=True, num_workers=0,
            callbacks=None, accumulate_grad_batches=1, num_iters=None):
        train_loader = _as_loader(train_data, batch_size, shuffle,
                                  drop_last, num_workers)
        eval_loader = _as_loader(eval_data, batch_size, False, False,
                                 num_workers) if eval_data is not None \
            else None
        cbks = config_callbacks(callbacks, model=self, epochs=epochs,
                                steps=_safe_len(train_loader),
                                log_freq=log_freq, verbose=verbose,
                                save_dir=save_dir,
                                metrics=["loss"] + self._metrics_names())
        cbks.on_begin("train")
        for epoch in range(epochs):
            cbks.on_epoch_begin(epoch)
            for m in self._metrics:
                m.reset()
            logs = {}
            for step, batch in enumerate(train_loader):
                cbks.on_batch_begin("train", step, logs)
                ins, labs = _split_batch(batch)
                losses, metrics = self.train_batch(ins, labs)
                logs = self._make_logs(losses, metrics)
                logs["step"] = step
                cbks.on_batch_end("train", step, logs)
                if num_iters is not None and step + 1 >= num_iters:
                    break
            if eval_loader is not None and (epoch + 1) % eval_freq == 0:
                cbks.on_begin("eval")
                eval_logs = self.evaluate(eval_loader, verbose=0)
                cbks.on_end("eval", eval_logs)
                logs.update({f"eval_{k}": v for k, v in eval_logs.items()})
            cbks.on_epoch_end(epoch, logs)
            if save_dir and (epoch + 1) % save_freq == 0:
                self.save(f"{save_dir}/{epoch}")
            if self.stop_training:
                break
        cbks.on_end("train", logs)

    def evaluate(self, eval_data, batch_size=1, log_freq=10, verbose=2,
                 num_workers=0, callbacks=None, num_iters=None):
        loader = _as_loader(eval_data, batch_size, False, False, num_workers)
        cbks = None
        if callbacks:
            cbks = config_callbacks(callbacks, model=self, epochs=1,
                                    steps=_safe_len(loader),
                                    log_freq=log_freq, verbose=verbose,
                                    metrics=["loss"]
                                    + self._metrics_names())
            cbks.on_begin("eval")
        for m in self._metrics:
            m.reset()
        total_loss, n = 0.0, 0
        for step, batch in enumerate(loader):
            ins, labs = _split_batch(batch)
            losses, _ = self.eval_batch(ins, labs)
            if losses:
                total_loss += losses[0]
                n += 1
            if num_iters is not None and step + 1 >= num_iters:
                break
        logs = {}
        if n:
            logs["loss"] = total_loss / n
        for m in self._metrics:
            logs[_name_of(m)] = m.accumulate()
        if cbks is not None:
            cbks.on_end("eval", logs)
        return logs

    def predict(self, test_data, batch_size=1, num_workers=0,
                stack_outputs=False, verbose=1, callbacks=None):
        loader = _as_loader(test_data, batch_size, False, False, num_workers)
        outputs = []
        for batch in loader:
            ins, _ = _split_batch(batch, has_labels=False)
            outs = self.predict_batch(ins)
            outputs.append([np.asarray(o._data) for o in outs])
        if stack_outputs:
            n_out = len(outputs[0])
            return [np.concatenate([b[i] for b in outputs])
                    for i in range(n_out)]
        return outputs

    # -- io ---------------------------------------------------------------
    def save(self, path: str, training: bool = True):
        fw_save(self.network.state_dict(), path + ".pdparams")
        if training and self._optimizer is not None:
            fw_save(self._optimizer.state_dict(), path + ".pdopt")

    def load(self, path: str, skip_mismatch: bool = False,
             reset_optimizer: bool = False):
        state = fw_load(path + ".pdparams")
        self.network.set_state_dict(state)
        import os
        if not reset_optimizer and self._optimizer is not None and \
                os.path.exists(path + ".pdopt"):
            self._optimizer.set_state_dict(fw_load(path + ".pdopt"))

    def parameters(self, *args, **kwargs):
        return self.network.parameters()

    def summary(self, input_size=None, dtype=None):
        n_params = sum(p.size for p in self.network.parameters())
        trainable = sum(p.size for p in self.network.parameters()
                        if p.trainable)
        info = {"total_params": n_params, "trainable_params": trainable}
        print(f"Total params: {n_params:,}  (trainable {trainable:,})")
        return info

    # -- helpers ----------------------------------------------------------
    def _metrics_names(self):
        names = []
        for m in self._metrics:
            n = m.name()
            names.extend(n if isinstance(n, list) else [n])
        return names

    def _make_logs(self, losses, metrics):
        logs = {"loss": losses[0] if losses else 0.0}
        for m, v in zip(self._metrics, metrics):
            logs[_name_of(m)] = v
        return logs


def _name_of(m):
    n = m.name()
    return n if isinstance(n, str) else n[0]


def _to_list(x):
    if x is None:
        return []
    if isinstance(x, (list, tuple)):
        return list(x)
    return [x]


def _safe_len(loader):
    try:
        return len(loader)
    except TypeError:
        return None


def _as_loader(data, batch_size, shuffle, drop_last, num_workers):
    if data is None or isinstance(data, DataLoader):
        return data
    return DataLoader(data, batch_size=batch_size, shuffle=shuffle,
                      drop_last=drop_last, num_workers=num_workers)


def _split_batch(batch, has_labels=True):
    if isinstance(batch, (list, tuple)):
        items = list(batch)
        if len(items) == 1:
            return items, []
        # trailing element is the label slot; predict drops it
        return items[:-1], (items[-1:] if has_labels else [])
    return [batch], []
