"""High-level API (reference: python/paddle/hapi/)."""
from .model import Model  # noqa: F401
from . import callbacks  # noqa: F401
