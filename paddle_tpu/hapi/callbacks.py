"""Training callbacks (reference: python/paddle/hapi/callbacks.py —
ProgBarLogger, ModelCheckpoint, LRScheduler, EarlyStopping)."""
from __future__ import annotations

import time
from typing import List, Optional

import numpy as np

__all__ = ["Callback", "ProgBarLogger", "ModelCheckpoint", "LRScheduler",
           "EarlyStopping", "CallbackList", "config_callbacks", "ReduceLROnPlateau", "VisualDL", "WandbCallback"]


class Callback:
    def __init__(self):
        self.model = None
        self.params = {}

    def set_model(self, model):
        self.model = model

    def set_params(self, params):
        self.params = params or {}

    def on_begin(self, mode, logs=None):
        getattr(self, f"on_{mode}_begin", lambda l=None: None)(logs)

    def on_end(self, mode, logs=None):
        getattr(self, f"on_{mode}_end", lambda l=None: None)(logs)

    def on_batch_begin(self, mode, step, logs=None):
        getattr(self, f"on_{mode}_batch_begin",
                lambda s, l=None: None)(step, logs)

    def on_batch_end(self, mode, step, logs=None):
        getattr(self, f"on_{mode}_batch_end",
                lambda s, l=None: None)(step, logs)

    def on_epoch_begin(self, epoch, logs=None):
        pass

    def on_epoch_end(self, epoch, logs=None):
        pass

    def on_train_begin(self, logs=None):
        pass

    def on_train_end(self, logs=None):
        pass

    def on_train_batch_begin(self, step, logs=None):
        pass

    def on_train_batch_end(self, step, logs=None):
        pass


class CallbackList:
    def __init__(self, callbacks: List[Callback]):
        self.callbacks = callbacks

    def _call_all(self, method, *args):
        for cbk in self.callbacks:
            getattr(cbk, method)(*args)

    def set_model(self, model):
        self._call_all("set_model", model)

    def set_params(self, params):
        self._call_all("set_params", params)

    def on_begin(self, mode, logs=None):
        # Callback.on_begin itself routes to on_{mode}_begin
        self._call_all("on_begin", mode, logs)

    def on_end(self, mode, logs=None):
        self._call_all("on_end", mode, logs)

    def on_epoch_begin(self, epoch, logs=None):
        self._call_all("on_epoch_begin", epoch, logs)

    def on_epoch_end(self, epoch, logs=None):
        self._call_all("on_epoch_end", epoch, logs)

    def on_batch_begin(self, mode, step, logs=None):
        self._call_all("on_batch_begin", mode, step, logs)

    def on_batch_end(self, mode, step, logs=None):
        self._call_all("on_batch_end", mode, step, logs)


class ProgBarLogger(Callback):
    def __init__(self, log_freq=1, verbose=2):
        super().__init__()
        self.log_freq = log_freq
        self.verbose = verbose

    def on_train_begin(self, logs=None):
        self._t0 = time.time()

    def on_epoch_begin(self, epoch, logs=None):
        self.epoch = epoch
        self.steps_done = 0
        self._epoch_t0 = time.time()

    def on_train_batch_end(self, step, logs=None):
        self.steps_done += 1
        if self.verbose and step % self.log_freq == 0:
            items = ", ".join(f"{k}: {v:.4f}"
                              for k, v in (logs or {}).items()
                              if isinstance(v, float))
            print(f"Epoch {self.epoch} step {step}: {items}")

    def on_epoch_end(self, epoch, logs=None):
        if self.verbose:
            dt = time.time() - self._epoch_t0
            items = ", ".join(f"{k}: {v:.4f}"
                              for k, v in (logs or {}).items()
                              if isinstance(v, float))
            print(f"Epoch {epoch} done in {dt:.1f}s — {items}")


class ModelCheckpoint(Callback):
    def __init__(self, save_freq=1, save_dir=None):
        super().__init__()
        self.save_freq = save_freq
        self.save_dir = save_dir

    def on_epoch_end(self, epoch, logs=None):
        if self.save_dir and (epoch + 1) % self.save_freq == 0:
            self.model.save(f"{self.save_dir}/{epoch}")


class LRScheduler(Callback):
    def __init__(self, by_step=True, by_epoch=False):
        super().__init__()
        self.by_step = by_step
        self.by_epoch = by_epoch

    def _sched(self):
        opt = getattr(self.model, "_optimizer", None)
        from ..optimizer.lr import LRScheduler as Sched
        lr = getattr(opt, "_learning_rate", None)
        return lr if isinstance(lr, Sched) else None

    def on_train_batch_end(self, step, logs=None):
        if self.by_step:
            s = self._sched()
            if s:
                s.step()

    def on_epoch_end(self, epoch, logs=None):
        if self.by_epoch:
            s = self._sched()
            if s:
                s.step()


class EarlyStopping(Callback):
    def __init__(self, monitor="loss", mode="auto", patience=0, verbose=1,
                 min_delta=0, baseline=None, save_best_model=True):
        super().__init__()
        self.monitor = monitor
        self.patience = patience
        self.min_delta = abs(min_delta)
        self.baseline = baseline
        if mode == "auto":
            mode = "max" if "acc" in monitor else "min"
        self.mode = mode
        self.best = None
        self.wait = 0

    def on_epoch_end(self, epoch, logs=None):
        logs = logs or {}
        cur = logs.get(self.monitor)
        if cur is None:
            cur = logs.get(f"eval_{self.monitor}")
        if cur is None:
            return
        better = (self.best is None or
                  (self.mode == "min" and cur < self.best - self.min_delta)
                  or (self.mode == "max" and cur > self.best +
                      self.min_delta))
        if better:
            self.best = cur
            self.wait = 0
        else:
            self.wait += 1
            if self.wait >= self.patience:
                self.model.stop_training = True


def config_callbacks(callbacks=None, model=None, epochs=None, steps=None,
                     log_freq=2, verbose=2, save_dir=None, metrics=None,
                     mode="train"):
    cbks = list(callbacks) if callbacks else []
    if not any(isinstance(c, ProgBarLogger) for c in cbks) and verbose:
        cbks = [ProgBarLogger(log_freq, verbose=verbose)] + cbks
    if not any(isinstance(c, LRScheduler) for c in cbks):
        cbks = cbks + [LRScheduler()]
    cbk_list = CallbackList(cbks)
    cbk_list.set_model(model)
    cbk_list.set_params({"epochs": epochs, "steps": steps,
                         "verbose": verbose, "metrics": metrics or []})
    return cbk_list


class ReduceLROnPlateau(Callback):
    """Reduce optimizer LR when a monitored metric plateaus
    (hapi/callbacks.py ReduceLROnPlateau)."""

    def __init__(self, monitor="loss", factor=0.1, patience=10,
                 verbose=1, mode="auto", min_delta=1e-4, cooldown=0,
                 min_lr=0.0):
        super().__init__()
        self.monitor = monitor
        self.factor = factor
        self.patience = patience
        self.verbose = verbose
        self.min_delta = min_delta
        self.cooldown = cooldown
        self.min_lr = min_lr
        # auto rule matches EarlyStopping above: maximize only for
        # accuracy-style monitors, minimize everything else
        if mode == "auto":
            self.mode = "max" if "acc" in monitor else "min"
        else:
            self.mode = mode
        self._best = None
        self._wait = 0
        self._cooldown_left = 0

    def _better(self, cur):
        if self._best is None:
            return True
        if self.mode == "min":
            return cur < self._best - self.min_delta
        return cur > self._best + self.min_delta

    def _observe(self, cur):
        improved = self._better(cur)
        if improved:
            self._best = cur  # track best even through cooldown
        if self._cooldown_left > 0:
            self._cooldown_left -= 1
            return
        if improved:
            self._wait = 0
            return
        self._wait += 1
        if self._wait >= self.patience:
            opt = getattr(self.model, "_optimizer", None)
            if opt is not None:
                new_lr = max(opt.get_lr() * self.factor, self.min_lr)
                opt.set_lr(new_lr)
                if self.verbose:
                    print(f"ReduceLROnPlateau: lr -> {new_lr:.3e}")
            self._wait = 0
            self._cooldown_left = self.cooldown

    def _metric_from(self, logs):
        logs = logs or {}
        cur = logs.get(self.monitor, logs.get(f"eval_{self.monitor}"))
        if cur is None:
            return None
        return float(cur[0] if isinstance(cur, (list, tuple)) else cur)

    def on_eval_end(self, logs=None):
        cur = self._metric_from(logs)
        if cur is not None:
            self._saw_eval_event = True
            self._observe(cur)

    def on_epoch_end(self, epoch, logs=None):
        # fallback path: standalone loops that only report merged epoch
        # logs (eval_ prefix). Skipped when the eval event already fired
        # this epoch, so one evaluation is never counted twice.
        if getattr(self, "_saw_eval_event", False):
            self._saw_eval_event = False
            return
        cur = self._metric_from(logs)
        if cur is not None:
            self._observe(cur)


class VisualDL(Callback):
    """Scalar logger (hapi VisualDL callback). The visualdl package is
    not bundled; scalars are appended as JSON lines under ``log_dir`` so
    runs remain inspectable (and visualdl can ingest later)."""

    def __init__(self, log_dir="./log"):
        super().__init__()
        self.log_dir = log_dir
        self._step = {"train": 0, "eval": 0}

    def _write(self, phase, logs):
        import json
        import os
        os.makedirs(self.log_dir, exist_ok=True)
        path = os.path.join(self.log_dir, f"{phase}.jsonl")
        rec = {"step": self._step[phase]}
        for k, v in (logs or {}).items():
            try:
                rec[k] = float(v[0] if isinstance(v, (list, tuple))
                               else v)
            except (TypeError, ValueError):
                continue
        with open(path, "a") as f:
            f.write(json.dumps(rec) + "\n")
        self._step[phase] += 1

    def on_epoch_end(self, epoch, logs=None):
        self._write("train", logs)

    def on_eval_end(self, logs=None):
        self._write("eval", logs)


class WandbCallback(Callback):
    """Weights & Biases logger: delegates when wandb is importable,
    otherwise raises at construction (no silent no-op)."""

    def __init__(self, project=None, **kwargs):
        super().__init__()
        try:
            import wandb
        except ImportError as e:
            raise ImportError(
                "WandbCallback requires the wandb package") from e
        self._run = wandb.init(project=project, **kwargs)

    def on_epoch_end(self, epoch, logs=None):
        self._run.log(dict(logs or {}))
