"""Training callbacks (reference: python/paddle/hapi/callbacks.py —
ProgBarLogger, ModelCheckpoint, LRScheduler, EarlyStopping)."""
from __future__ import annotations

import time
from typing import List, Optional

import numpy as np

__all__ = ["Callback", "ProgBarLogger", "ModelCheckpoint", "LRScheduler",
           "EarlyStopping", "CallbackList", "config_callbacks"]


class Callback:
    def __init__(self):
        self.model = None
        self.params = {}

    def set_model(self, model):
        self.model = model

    def set_params(self, params):
        self.params = params or {}

    def on_begin(self, mode, logs=None):
        getattr(self, f"on_{mode}_begin", lambda l=None: None)(logs)

    def on_end(self, mode, logs=None):
        getattr(self, f"on_{mode}_end", lambda l=None: None)(logs)

    def on_batch_begin(self, mode, step, logs=None):
        getattr(self, f"on_{mode}_batch_begin",
                lambda s, l=None: None)(step, logs)

    def on_batch_end(self, mode, step, logs=None):
        getattr(self, f"on_{mode}_batch_end",
                lambda s, l=None: None)(step, logs)

    def on_epoch_begin(self, epoch, logs=None):
        pass

    def on_epoch_end(self, epoch, logs=None):
        pass

    def on_train_begin(self, logs=None):
        pass

    def on_train_end(self, logs=None):
        pass

    def on_train_batch_begin(self, step, logs=None):
        pass

    def on_train_batch_end(self, step, logs=None):
        pass


class CallbackList:
    def __init__(self, callbacks: List[Callback]):
        self.callbacks = callbacks

    def _call_all(self, method, *args):
        for cbk in self.callbacks:
            getattr(cbk, method)(*args)

    def set_model(self, model):
        self._call_all("set_model", model)

    def set_params(self, params):
        self._call_all("set_params", params)

    def on_begin(self, mode, logs=None):
        self._call_all("on_begin", mode, logs)

    def on_end(self, mode, logs=None):
        self._call_all("on_end", mode, logs)

    def on_epoch_begin(self, epoch, logs=None):
        self._call_all("on_epoch_begin", epoch, logs)

    def on_epoch_end(self, epoch, logs=None):
        self._call_all("on_epoch_end", epoch, logs)

    def on_batch_begin(self, mode, step, logs=None):
        self._call_all("on_batch_begin", mode, step, logs)

    def on_batch_end(self, mode, step, logs=None):
        self._call_all("on_batch_end", mode, step, logs)


class ProgBarLogger(Callback):
    def __init__(self, log_freq=1, verbose=2):
        super().__init__()
        self.log_freq = log_freq
        self.verbose = verbose

    def on_train_begin(self, logs=None):
        self._t0 = time.time()

    def on_epoch_begin(self, epoch, logs=None):
        self.epoch = epoch
        self.steps_done = 0
        self._epoch_t0 = time.time()

    def on_train_batch_end(self, step, logs=None):
        self.steps_done += 1
        if self.verbose and step % self.log_freq == 0:
            items = ", ".join(f"{k}: {v:.4f}"
                              for k, v in (logs or {}).items()
                              if isinstance(v, float))
            print(f"Epoch {self.epoch} step {step}: {items}")

    def on_epoch_end(self, epoch, logs=None):
        if self.verbose:
            dt = time.time() - self._epoch_t0
            items = ", ".join(f"{k}: {v:.4f}"
                              for k, v in (logs or {}).items()
                              if isinstance(v, float))
            print(f"Epoch {epoch} done in {dt:.1f}s — {items}")


class ModelCheckpoint(Callback):
    def __init__(self, save_freq=1, save_dir=None):
        super().__init__()
        self.save_freq = save_freq
        self.save_dir = save_dir

    def on_epoch_end(self, epoch, logs=None):
        if self.save_dir and (epoch + 1) % self.save_freq == 0:
            self.model.save(f"{self.save_dir}/{epoch}")


class LRScheduler(Callback):
    def __init__(self, by_step=True, by_epoch=False):
        super().__init__()
        self.by_step = by_step
        self.by_epoch = by_epoch

    def _sched(self):
        opt = getattr(self.model, "_optimizer", None)
        from ..optimizer.lr import LRScheduler as Sched
        lr = getattr(opt, "_learning_rate", None)
        return lr if isinstance(lr, Sched) else None

    def on_train_batch_end(self, step, logs=None):
        if self.by_step:
            s = self._sched()
            if s:
                s.step()

    def on_epoch_end(self, epoch, logs=None):
        if self.by_epoch:
            s = self._sched()
            if s:
                s.step()


class EarlyStopping(Callback):
    def __init__(self, monitor="loss", mode="auto", patience=0, verbose=1,
                 min_delta=0, baseline=None, save_best_model=True):
        super().__init__()
        self.monitor = monitor
        self.patience = patience
        self.min_delta = abs(min_delta)
        self.baseline = baseline
        if mode == "auto":
            mode = "max" if "acc" in monitor else "min"
        self.mode = mode
        self.best = None
        self.wait = 0

    def on_epoch_end(self, epoch, logs=None):
        logs = logs or {}
        cur = logs.get(self.monitor)
        if cur is None:
            cur = logs.get(f"eval_{self.monitor}")
        if cur is None:
            return
        better = (self.best is None or
                  (self.mode == "min" and cur < self.best - self.min_delta)
                  or (self.mode == "max" and cur > self.best +
                      self.min_delta))
        if better:
            self.best = cur
            self.wait = 0
        else:
            self.wait += 1
            if self.wait >= self.patience:
                self.model.stop_training = True


def config_callbacks(callbacks=None, model=None, epochs=None, steps=None,
                     log_freq=2, verbose=2, save_dir=None, metrics=None,
                     mode="train"):
    cbks = list(callbacks) if callbacks else []
    if not any(isinstance(c, ProgBarLogger) for c in cbks) and verbose:
        cbks = [ProgBarLogger(log_freq, verbose=verbose)] + cbks
    if not any(isinstance(c, LRScheduler) for c in cbks):
        cbks = cbks + [LRScheduler()]
    cbk_list = CallbackList(cbks)
    cbk_list.set_model(model)
    cbk_list.set_params({"epochs": epochs, "steps": steps,
                         "verbose": verbose, "metrics": metrics or []})
    return cbk_list
