"""Re-export of ops.pad for nn.functional (paddle exposes pad in both)."""
from .ops.manipulation import pad  # noqa: F401
