"""Custom C++ op extension builder (reference:
python/paddle/utils/cpp_extension/ — CUDAExtension/CppExtension/load
compile user .cc/.cu with PD_BUILD_OP macros into loadable paddle ops
with autograd integration).

TPU-native shape: device compute belongs in Pallas kernels (see
ops/pallas_ops.py); a custom C++ op here is HOST compute — pre/post
processing, tokenizers, lookup logic — that still composes with the
framework: it runs under jit (XLA host callback via
``jax.pure_callback``), takes/returns ``Tensor`` through the autograd
tape, and participates in backward when a gradient function is
exported.

The C ABI replaces the reference's PD_BUILD_OP macro. Export from your
.cc (extern "C"):

    // forward: inputs are float32 arrays of identical shape; out has
    // the same shape (elementwise-family contract)
    void pd_op_<NAME>(const float** ins, int n_ins, float* out,
                      const int64_t* shape, int ndim);
    // optional backward: fill one input-gradient per input
    void pd_grad_<NAME>(const float** ins, int n_ins,
                        const float* gout, float** gins,
                        const int64_t* shape, int ndim);

``load(name, sources)`` compiles with g++, discovers every pd_op_*
symbol, and returns a module-like object whose attributes are the ops.
The raw ``ctypes.CDLL`` stays available as ``.cdll`` for free-form
native libraries (the csrc/ runtime pattern).
"""
from __future__ import annotations

import ctypes
import os
import subprocess
import tempfile

import numpy as np

__all__ = ["CppExtension", "load", "get_build_directory",
           "CustomOpModule"]

_F32P = ctypes.POINTER(ctypes.c_float)
_F32PP = ctypes.POINTER(_F32P)
_I64P = ctypes.POINTER(ctypes.c_int64)


def get_build_directory():
    d = os.environ.get("PADDLE_TPU_EXTENSION_DIR",
                       os.path.join(tempfile.gettempdir(),
                                    "paddle_tpu_extensions"))
    os.makedirs(d, exist_ok=True)
    return d


class CppExtension:
    def __init__(self, sources, extra_compile_args=None, **kwargs):
        self.sources = list(sources)
        self.extra_compile_args = list(extra_compile_args or [])


def _exported_ops(so_path):
    """pd_op_* / pd_grad_* symbols in the shared object (nm -D)."""
    try:
        out = subprocess.run(["nm", "-D", so_path], check=True,
                             capture_output=True, text=True).stdout
    except (OSError, subprocess.CalledProcessError) as e:
        import warnings
        warnings.warn(
            f"cpp_extension: cannot enumerate symbols of {so_path} "
            f"({e}); no pd_op_* custom ops will be registered — use "
            f".cdll for raw ctypes access", RuntimeWarning)
        return [], []
    fwd, bwd = [], []
    for line in out.splitlines():
        parts = line.split()
        if len(parts) >= 3 and parts[-2] in ("T", "W", "t", "w"):
            sym = parts[-1]
            if sym.startswith("pd_op_"):
                fwd.append(sym[len("pd_op_"):])
            elif sym.startswith("pd_grad_"):
                bwd.append(sym[len("pd_grad_"):])
    return fwd, bwd


class CustomOp:
    """One registered custom op: Tensor-in/Tensor-out, jit-safe,
    differentiable when the library exports pd_grad_<name>."""

    def __init__(self, name, cdll, has_grad):
        self.__name__ = name
        self._fwd = getattr(cdll, "pd_op_" + name)
        self._fwd.restype = None
        self._fwd.argtypes = [_F32PP, ctypes.c_int, _F32P, _I64P,
                              ctypes.c_int]
        self._bwd = None
        if has_grad:
            self._bwd = getattr(cdll, "pd_grad_" + name)
            self._bwd.restype = None
            self._bwd.argtypes = [_F32PP, ctypes.c_int, _F32P, _F32PP,
                                  _I64P, ctypes.c_int]
        self._jax_fn = self._build()

    # -- host callbacks ---------------------------------------------------
    def _ptrs(self, arrs):
        return (_F32P * len(arrs))(*[a.ctypes.data_as(_F32P)
                                     for a in arrs])

    def _run_fwd(self, *arrays):
        arrs = [np.ascontiguousarray(a, np.float32) for a in arrays]
        out = np.empty_like(arrs[0])
        shape = np.asarray(arrs[0].shape or (1,), np.int64)
        self._fwd(self._ptrs(arrs), len(arrs),
                  out.ctypes.data_as(_F32P),
                  shape.ctypes.data_as(_I64P), arrs[0].ndim)
        return out

    def _run_bwd(self, gout, *arrays):
        arrs = [np.ascontiguousarray(a, np.float32) for a in arrays]
        g = np.ascontiguousarray(gout, np.float32)
        gins = [np.zeros_like(a) for a in arrs]
        shape = np.asarray(arrs[0].shape or (1,), np.int64)
        self._bwd(self._ptrs(arrs), len(arrs),
                  g.ctypes.data_as(_F32P), self._ptrs(gins),
                  shape.ctypes.data_as(_I64P), arrs[0].ndim)
        return tuple(gins)

    # -- jax integration --------------------------------------------------
    def _build(self):
        import jax
        import jax.numpy as jnp
        name = self.__name__

        def call(*xs):
            # the C ABI is float32; cast INSIDE the differentiated fn
            # so cotangents chain back to the caller's dtype
            xs = tuple(jnp.asarray(x, jnp.float32) for x in xs)
            if not any(isinstance(x, jax.core.Tracer) for x in xs):
                # eager: run the C function directly on host numpy —
                # no callback machinery (which some PJRT runtimes,
                # e.g. the axon tunnel, do not support)
                return jnp.asarray(
                    self._run_fwd(*[np.asarray(x) for x in xs]))
            spec = jax.ShapeDtypeStruct(xs[0].shape, np.float32)
            return jax.pure_callback(self._run_fwd, spec, *xs)

        # ALWAYS wrap in custom_vjp: a bare pure_callback has no JVP
        # rule, so jax.vjp over it (which apply_op takes whenever an
        # input requires grad) would crash the FORWARD pass even for
        # users who never call backward()
        @jax.custom_vjp
        def op(*xs):
            return call(*xs)

        def fwd(*xs):
            return call(*xs), tuple(jnp.asarray(x, jnp.float32)
                                    for x in xs)

        if self._bwd is None:
            def bwd(res, g):
                raise NotImplementedError(
                    f"custom op {name!r} exports no pd_grad_{name}; "
                    f"it cannot be differentiated")
        else:
            def bwd(res, g):
                if not any(isinstance(x, jax.core.Tracer)
                           for x in (g, *res)):
                    return tuple(
                        jnp.asarray(a) for a in self._run_bwd(
                            np.asarray(g),
                            *[np.asarray(x) for x in res]))
                specs = tuple(jax.ShapeDtypeStruct(x.shape, np.float32)
                              for x in res)
                return jax.pure_callback(self._run_bwd, specs, g, *res)

        op.defvjp(fwd, bwd)
        op.__name__ = name
        return op

    def __call__(self, *xs):
        from ..framework.tensor import Tensor, apply_op
        has_tensor = any(isinstance(x, Tensor) for x in xs)
        xs = tuple(x if isinstance(x, Tensor)
                   else np.asarray(x, np.float32) for x in xs)
        shapes = {tuple(x.shape) for x in xs}
        if len(shapes) > 1:
            raise ValueError(
                f"{self.__name__}: all inputs must share one shape "
                f"(elementwise-family custom op contract)")
        if has_tensor:
            # through the dispatch funnel: tape-recorded like any
            # framework op, so Tensor.backward() reaches pd_grad_*
            return apply_op(self._jax_fn, *xs, _op_name=self.__name__)
        return self._jax_fn(*xs)


class CustomOpModule:
    def __init__(self, cdll, ops):
        self.cdll = cdll
        self._ops = ops
        for name, op in ops.items():
            setattr(self, name, op)

    def __iter__(self):
        return iter(self._ops)

    def operators(self):
        return dict(self._ops)


def load(name, sources, extra_cxx_cflags=None, build_directory=None,
         verbose=False):
    """Compile C++ sources into <name>.so; return a CustomOpModule
    exposing every pd_op_* symbol as a framework op (or, with no such
    symbols, use ``.cdll`` for raw ctypes access)."""
    build_dir = build_directory or get_build_directory()
    out = os.path.join(build_dir, f"{name}.so")
    srcs = [os.path.abspath(s) for s in sources]
    newest_src = max(os.path.getmtime(s) for s in srcs)
    if not (os.path.exists(out) and os.path.getmtime(out) >= newest_src):
        # compile to a tmp and os.replace: a concurrent load() in
        # another process never dlopens a half-written .so (same
        # recipe as utils/native_build.py)
        tmp = f"{out}.{os.getpid()}.tmp"
        cmd = ["g++", "-O2", "-fPIC", "-shared", "-std=c++17",
               *(extra_cxx_cflags or []), "-o", tmp, *srcs]
        if verbose:
            print("[cpp_extension]", " ".join(cmd))
        try:
            subprocess.run(cmd, check=True,
                           capture_output=not verbose)
            os.replace(tmp, out)
        except BaseException:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise
    cdll = ctypes.CDLL(out)
    fwd, bwd = _exported_ops(out)
    ops = {n: CustomOp(n, cdll, has_grad=n in bwd) for n in fwd}
    return CustomOpModule(cdll, ops)
