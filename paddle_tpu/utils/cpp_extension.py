"""Custom C++ op extension builder (reference:
python/paddle/utils/cpp_extension/ — CUDAExtension/CppExtension/load
compiling user .cc/.cu into loadable paddle ops).

TPU-native shape: a custom "op" is (a) a host-side C shared library called
through ctypes for runtime/IO work, or (b) a Pallas kernel for device work.
``load`` compiles C++ sources to a shared object with g++ and returns a
ctypes.CDLL — the same mechanism csrc/ uses (csrc/data_feed.cc)."""
from __future__ import annotations

import ctypes
import os
import subprocess
import tempfile

__all__ = ["CppExtension", "load", "get_build_directory"]


def get_build_directory():
    d = os.environ.get("PADDLE_TPU_EXTENSION_DIR",
                       os.path.join(tempfile.gettempdir(),
                                    "paddle_tpu_extensions"))
    os.makedirs(d, exist_ok=True)
    return d


class CppExtension:
    def __init__(self, sources, extra_compile_args=None, **kwargs):
        self.sources = list(sources)
        self.extra_compile_args = list(extra_compile_args or [])


def load(name, sources, extra_cxx_cflags=None, build_directory=None,
         verbose=False):
    """Compile C++ sources into <name>.so and dlopen it via ctypes."""
    build_dir = build_directory or get_build_directory()
    out = os.path.join(build_dir, f"{name}.so")
    srcs = [os.path.abspath(s) for s in sources]
    newest_src = max(os.path.getmtime(s) for s in srcs)
    if not (os.path.exists(out) and os.path.getmtime(out) >= newest_src):
        cmd = ["g++", "-O2", "-fPIC", "-shared", "-std=c++17",
               *(extra_cxx_cflags or []), "-o", out, *srcs]
        if verbose:
            print("[cpp_extension]", " ".join(cmd))
        subprocess.run(cmd, check=True, capture_output=not verbose)
    return ctypes.CDLL(out)
