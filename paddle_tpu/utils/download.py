"""Pretrained-weight distribution: download + cache.

Reference: python/paddle/utils/download.py (get_weights_path_from_url,
get_path_from_url — DOWNLOAD_RETRY_LIMIT, md5 validation, WEIGHTS_HOME
cache under ~/.cache/paddle) consumed by every vision model's
``model_urls`` table (e.g. python/paddle/vision/models/resnet.py:56).

TPU-native: same contract over urllib; ``file://`` URLs are first-class
(air-gapped clusters stage weights on shared storage), the cache root
honors $PADDLE_TPU_HOME, and md5 mismatches re-download once before
failing loudly.
"""
from __future__ import annotations

import hashlib
import os
import shutil
import urllib.parse
import urllib.request

__all__ = ["get_weights_path_from_url", "get_path_from_url",
           "WEIGHTS_HOME", "DATA_HOME"]

_CACHE_ROOT = os.environ.get(
    "PADDLE_TPU_HOME",
    os.path.join(os.path.expanduser("~"), ".cache", "paddle_tpu"))
WEIGHTS_HOME = os.path.join(_CACHE_ROOT, "weights")
# dataset archives (reference: paddle.dataset.common.DATA_HOME)
DATA_HOME = os.path.join(_CACHE_ROOT, "datasets")

DOWNLOAD_RETRY_LIMIT = 3


def _md5(path: str) -> str:
    h = hashlib.md5()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


def _fetch(url: str, dst: str):
    parsed = urllib.parse.urlparse(url)
    tmp = dst + ".part"
    if parsed.scheme == "file" or parsed.scheme == "":
        src = parsed.path if parsed.scheme == "file" else url
        shutil.copyfile(src, tmp)
    else:
        with urllib.request.urlopen(url, timeout=60) as r, \
                open(tmp, "wb") as f:
            shutil.copyfileobj(r, f)
    os.replace(tmp, dst)


def get_path_from_url(url: str, root_dir: str, md5sum: str = None,
                      check_exist: bool = True,
                      decompress: bool = False) -> str:
    """Download ``url`` into ``root_dir`` (cached by filename), verify
    md5 when given, and return the local path. ``decompress=True``
    additionally extracts zip/tar archives into ``root_dir`` (reference
    download.py decompress flag used by the dataset loaders)."""
    os.makedirs(root_dir, exist_ok=True)
    fname = os.path.basename(urllib.parse.urlparse(url).path) or "weights"
    # cache key includes a hash of the full URL: two different URLs with
    # the same basename must not share a cache entry
    tag = hashlib.sha1(url.encode()).hexdigest()[:10]
    dst = os.path.join(root_dir, f"{tag}_{fname}")
    if check_exist and os.path.exists(dst) and (
            md5sum is None or _md5(dst) == md5sum):
        if decompress:
            _decompress(dst, root_dir)
        return dst
    last_err = None
    for _ in range(DOWNLOAD_RETRY_LIMIT):
        try:
            _fetch(url, dst)
        except Exception as e:  # network hiccup: retry
            last_err = e
            continue
        if md5sum is None or _md5(dst) == md5sum:
            if decompress:
                _decompress(dst, root_dir)
            return dst
        last_err = ValueError(
            f"md5 mismatch for {url}: got {_md5(dst)}, want {md5sum}")
        os.remove(dst)
    raise RuntimeError(
        f"failed to fetch {url} after {DOWNLOAD_RETRY_LIMIT} attempts: "
        f"{last_err}")


def _decompress(path: str, root_dir: str) -> None:
    """Extract a zip/tar archive next to its cache entry (idempotent:
    a marker file records the extracted archive's md5, so a
    re-downloaded/refreshed archive re-extracts instead of silently
    serving the stale tree)."""
    marker = path + ".extracted"
    cur = _md5(path)
    if os.path.exists(marker) and open(marker).read().strip() == cur:
        return
    import tarfile
    import zipfile
    if zipfile.is_zipfile(path):
        with zipfile.ZipFile(path) as z:
            z.extractall(root_dir)
    elif tarfile.is_tarfile(path):
        with tarfile.open(path) as t:
            t.extractall(root_dir, filter="data")
    else:
        raise ValueError(f"not a zip/tar archive: {path}")
    with open(marker, "w") as f:
        f.write(cur)


def get_weights_path_from_url(url: str, md5sum: str = None) -> str:
    """Download model weights into the shared weights cache."""
    return get_path_from_url(url, WEIGHTS_HOME, md5sum)
