"""Shared lazy g++ build for the csrc/ native runtime libraries.

One place for the compile-recipe (temp + atomic rename so concurrent
first-use across processes never dlopens a half-written .so) used by
io.native (data feed), distributed.store (TCPStore), and distributed.ps
(sparse tables). The reference builds its native runtime through a CMake
superbuild (/root/reference/CMakeLists.txt); here each library is one
translation unit compiled on first import.
"""
from __future__ import annotations

import os
import subprocess
from typing import Optional

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
OUT_DIR = os.path.join(REPO_ROOT, "build")


def build_native_so(src_name: str, so_name: str,
                    opt: str = "-O3") -> Optional[str]:
    """Compile csrc/<src_name> to build/<so_name> if stale; returns the
    .so path or None on failure (callers degrade to pure-python paths)."""
    src = os.path.join(REPO_ROOT, "csrc", src_name)
    so = os.path.join(OUT_DIR, so_name)
    try:
        os.makedirs(OUT_DIR, exist_ok=True)
        if os.path.exists(so) and \
                os.path.getmtime(so) >= os.path.getmtime(src):
            return so
    except OSError:  # missing csrc tree etc: degrade, don't raise
        return so if os.path.exists(so) else None
    tmp = f"{so}.{os.getpid()}.tmp"
    cmd = ["g++", opt, "-shared", "-fPIC", "-pthread", "-std=c++17",
           src, "-o", tmp]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=180)
        os.replace(tmp, so)
        return so
    except Exception:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        return None
