"""paddle.utils parity: deprecated decorator, try_import, require_version,
unique_name, dlpack interop (reference: python/paddle/utils/)."""
from __future__ import annotations

import functools
import importlib
import warnings

from . import unique_name  # noqa: F401
from . import dlpack  # noqa: F401
from . import cpp_extension  # noqa: F401

__all__ = ["deprecated", "try_import", "require_version", "run_check",
           "unique_name", "dlpack"]


def deprecated(update_to="", since="", reason="", level=0):
    """Mark an API deprecated (reference:
    python/paddle/utils/deprecated.py) — warns at call time; level>=2
    raises."""

    def decorator(func):
        msg = f"API {func.__module__}.{func.__name__} is deprecated"
        if since:
            msg += f" since {since}"
        if update_to:
            msg += f", use {update_to} instead"
        if reason:
            msg += f". Reason: {reason}"

        @functools.wraps(func)
        def wrapper(*args, **kwargs):
            if level >= 2:
                raise RuntimeError(msg)
            warnings.warn(msg, DeprecationWarning, stacklevel=2)
            return func(*args, **kwargs)

        return wrapper

    return decorator


def try_import(module_name, err_msg=None):
    """Import a soft dependency, raising a helpful error if absent
    (reference: python/paddle/utils/lazy_import.py)."""
    try:
        return importlib.import_module(module_name)
    except ImportError:
        raise ImportError(
            err_msg or f"Failed to import {module_name}. Please install it "
            f"first (pip install {module_name}).")


def require_version(min_version, max_version=None):
    """Check the installed paddle_tpu version is in range (reference:
    python/paddle/utils/__init__.py require_version)."""
    import paddle_tpu

    def _tup(v):
        return tuple(int(p) for p in str(v).split(".")[:3])

    cur = _tup(paddle_tpu.__version__)
    if _tup(min_version) > cur:
        raise Exception(
            f"installed version {paddle_tpu.__version__} < required "
            f"minimum {min_version}")
    if max_version is not None and _tup(max_version) < cur:
        raise Exception(
            f"installed version {paddle_tpu.__version__} > required "
            f"maximum {max_version}")
    return True


def run_check():
    """Sanity-check the install: one matmul on the default device
    (reference: python/paddle/utils/install_check.py)."""
    import jax
    import jax.numpy as jnp
    x = jnp.ones((4, 4), jnp.float32)
    y = (x @ x).block_until_ready()
    assert float(y[0, 0]) == 4.0
    ndev = len(jax.devices())
    print(f"paddle_tpu is installed successfully! "
          f"backend={jax.default_backend()}, {ndev} device(s).")
