"""Unique-name generator (reference: python/paddle/utils/unique_name.py →
base/unique_name.py UniqueNameGenerator): per-prefix counters with
guard/switch support for snapshotting namespaces."""
from __future__ import annotations

import contextlib
import threading

__all__ = ["generate", "switch", "guard"]

_lock = threading.Lock()


class UniqueNameGenerator:
    def __init__(self):
        self.ids = {}

    def __call__(self, key: str) -> str:
        with _lock:
            n = self.ids.get(key, 0)
            self.ids[key] = n + 1
        return f"{key}_{n}"


generator = UniqueNameGenerator()


def generate(key: str) -> str:
    return generator(key)


def switch(new_generator=None):
    global generator
    old = generator
    generator = new_generator or UniqueNameGenerator()
    return old


@contextlib.contextmanager
def guard(new_generator=None):
    old = switch(new_generator)
    try:
        yield
    finally:
        switch(old)
