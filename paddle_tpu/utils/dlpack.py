"""DLPack interop (reference: python/paddle/utils/dlpack.py, C++ side
paddle/fluid/framework/dlpack_tensor.cc). TPU-native: jax.Array already
speaks the DLPack protocol; zero-copy on CPU, device transfer otherwise."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..framework.tensor import Tensor

__all__ = ["to_dlpack", "from_dlpack"]


class _CapsuleHolder:
    """Adapter giving a raw capsule the array-API dlpack protocol (newer
    jax.from_dlpack requires __dlpack__/__dlpack_device__ methods)."""

    def __init__(self, capsule, device):
        self._capsule = capsule
        self._device = device

    def __dlpack__(self, stream=None):
        return self._capsule

    def __dlpack_device__(self):
        return self._device


def to_dlpack(x):
    """Export a Tensor as a DLPack capsule."""
    arr = x._data if isinstance(x, Tensor) else jnp.asarray(x)
    capsule = arr.__dlpack__()
    return _CapsuleHolder(capsule, arr.__dlpack_device__())


def from_dlpack(capsule):
    """Import a DLPack capsule (or any object with __dlpack__) as a
    Tensor."""
    if not hasattr(capsule, "__dlpack__"):
        capsule = _CapsuleHolder(capsule, (1, 0))  # assume kDLCPU
    return Tensor(jnp.from_dlpack(capsule))
