"""paddle_tpu.distributed — the parallelism stack.

Reference surface: python/paddle/distributed/ (148k LoC; SURVEY.md §2.2).
TPU-native architecture: one device mesh + GSPMD/shard_map instead of
process groups; see submodule docstrings for the per-component mapping.
"""
from .env import (ParallelEnv, get_rank, get_world_size, init_parallel_env,
                  is_initialized)
from .process_mesh import ProcessMesh, auto_mesh, get_mesh, set_mesh
from .placements import Partial, Placement, Replicate, Shard
from .api import (DistModel, ShardingStage1, ShardingStage2,
                  ShardingStage3, dtensor_from_fn, get_placements,
                  reshard, shard_layer, shard_optimizer, shard_tensor,
                  to_static, unshard_dtensor)
from .collective import (Group, P2POp, ReduceOp, all_gather,
                         all_gather_object, all_reduce, all_to_all,
                         all_to_all_single, barrier, batch_isend_irecv,
                         broadcast, get_group, irecv, isend, new_group,
                         recv, reduce, reduce_scatter, scatter, send,
                         stream, wait)
from .parallel import DataParallel
from .sharding import group_sharded_parallel, save_group_sharded_model
from . import fleet  # noqa: F401
from . import pipeline  # noqa: F401
from . import pipeline_schedules  # noqa: F401
from . import auto_tuner  # noqa: F401
from . import rpc  # noqa: F401
from . import watchdog  # noqa: F401
from . import ps  # noqa: F401
from . import ps_device_cache  # noqa: F401
from . import fleet_executor  # noqa: F401
from .store import TCPStore  # noqa: F401
from .extras import (alltoall, alltoall_single, gather,  # noqa: F401
                     broadcast_object_list, scatter_object_list,
                     destroy_process_group, get_backend, is_available,
                     gloo_init_parallel_env, gloo_barrier, gloo_release,
                     ParallelMode, ReduceType, DistAttr, Strategy,
                     shard_dataloader, shard_scaler, split,
                     QueueDataset, InMemoryDataset, CountFilterEntry,
                     ProbabilityEntry, ShowClickEntry)
from . import io  # noqa: F401
from .checkpoint import load_state_dict, save_state_dict
from .launch import spawn

__all__ = [
    "ParallelEnv", "get_rank", "get_world_size", "init_parallel_env",
    "is_initialized", "ProcessMesh", "auto_mesh", "get_mesh", "set_mesh",
    "Partial", "Placement", "Replicate", "Shard", "shard_tensor", "reshard",
    "shard_layer", "shard_optimizer", "dtensor_from_fn", "unshard_dtensor",
    "DistModel", "to_static",
    "get_placements", "ShardingStage1", "ShardingStage2", "ShardingStage3",
    "Group", "ReduceOp", "new_group", "get_group", "all_reduce",
    "all_gather", "all_gather_object", "all_to_all", "all_to_all_single",
    "broadcast", "reduce", "reduce_scatter", "scatter", "send", "recv",
    "isend", "irecv", "barrier", "wait", "stream", "DataParallel",
    "group_sharded_parallel", "save_group_sharded_model", "fleet",
    "save_state_dict", "load_state_dict", "spawn",
]
