"""paddle.distributed.io (reference: python/paddle/distributed/io.py —
persistables save/load helpers). The sharded-checkpoint machinery lives
in distributed.checkpoint; this module is the io-surface mirror so
``import paddle.distributed.io`` style code ports unchanged."""
from .checkpoint import load_state_dict, save_state_dict  # noqa: F401


def is_persistable(var) -> bool:
    return bool(getattr(var, "persistable", False))


def save_persistables(executor, dirname, main_program=None,
                      filename=None):
    """Persist a static Program's parameters (distributed/io.py)."""
    from ..static.executor import save as static_save
    from ..static.graph import default_main_program
    import os
    program = main_program or default_main_program()
    path = os.path.join(dirname, filename or "persistables")
    static_save(program, path)


def load_persistables(executor, dirname, main_program=None,
                      filename=None):
    from ..static.executor import load as static_load
    from ..static.graph import default_main_program
    import os
    program = main_program or default_main_program()
    path = os.path.join(dirname, filename or "persistables")
    static_load(program, path)


__all__ = ["save_state_dict", "load_state_dict", "is_persistable",
           "save_persistables", "load_persistables"]
