"""Distributed checkpoint: sharded save / reshard-on-load.

Reference: python/paddle/distributed/checkpoint/save_state_dict.py:145,
load_state_dict.py:467, metadata.py — each rank writes `{rank}_{n}.distcp`
shards + a global Metadata mapping tensor -> (local shape, offset, file);
load reads intersecting shards and reshards to the current placements.

TPU-native: the same contract over jax.Array addressable shards. Every
process writes the shards it owns (dedup: only the lowest-rank replica
writes); metadata records global shape + index ranges; load assembles the
requested region and ``device_put``s with the *target* sharding — loading
under a different mesh/parallelism works by construction. ``async_save``
snapshots to host then writes on a worker thread (reference's async_save).
"""
from __future__ import annotations

import json
import os
import threading
from typing import Any, Dict, Optional

import jax
import numpy as np

from ..framework.tensor import Tensor

__all__ = ["save_state_dict", "load_state_dict"]


def _flatten(state: Dict[str, Any], prefix="") -> Dict[str, Any]:
    flat = {}
    for k, v in state.items():
        key = f"{prefix}.{k}" if prefix else str(k)
        if isinstance(v, dict):
            flat.update(_flatten(v, key))
        else:
            flat[key] = v
    return flat


def _unflatten(flat: Dict[str, Any]) -> Dict[str, Any]:
    out: Dict[str, Any] = {}
    for k, v in flat.items():
        parts = k.split(".")
        d = out
        for p in parts[:-1]:
            d = d.setdefault(p, {})
        d[parts[-1]] = v
    return out


def save_state_dict(state_dict: Dict[str, Any], path: str,
                    process_group=None, coordinator_rank: int = 0,
                    async_save: bool = False):
    os.makedirs(path, exist_ok=True)
    flat = _flatten(state_dict)
    rank = jax.process_index()
    meta: Dict[str, Any] = {"tensors": {}, "non_tensors": {}}
    writes = []

    for key, val in flat.items():
        if isinstance(val, Tensor):
            arr = val._data
        elif isinstance(val, (jax.Array, np.ndarray)):
            arr = val
        else:
            meta["non_tensors"][key] = val
            continue
        entry = {"shape": list(np.shape(arr)),
                 "dtype": str(np.asarray(jax.device_get(
                     arr)).dtype) if not hasattr(arr, "dtype")
                 else str(np.dtype(arr.dtype)), "shards": []}
        if isinstance(arr, jax.Array) and hasattr(arr, "addressable_shards"):
            seen_index = set()
            for i, shard in enumerate(arr.addressable_shards):
                idx = tuple(
                    (0 if s.start is None else s.start,
                     dim if s.stop is None else s.stop)
                    for s, dim in zip(shard.index, np.shape(arr)))
                if idx in seen_index:
                    continue  # dedup replicated shards on this process
                seen_index.add(idx)
                fname = f"{key.replace('/', '_')}.{rank}.{i}.distcp.npy"
                entry["shards"].append({"file": fname,
                                        "index": [list(p) for p in idx]})
                writes.append((os.path.join(path, fname),
                               shard.data))
        else:
            fname = f"{key.replace('/', '_')}.{rank}.0.distcp.npy"
            entry["shards"].append({
                "file": fname,
                "index": [[0, d] for d in np.shape(arr)]})
            writes.append((os.path.join(path, fname), arr))
        meta["tensors"][key] = entry

    def do_write():
        for fpath, data in writes:
            np.save(fpath, np.asarray(jax.device_get(data)))

    if async_save:
        # snapshot to host first (device buffers may be donated later)
        writes = [(f, np.asarray(jax.device_get(d))) for f, d in writes]
        t = threading.Thread(target=do_write, daemon=True)
        t.start()
        _pending.append(t)
    else:
        do_write()

    if rank == coordinator_rank:
        with open(os.path.join(path, f"{rank}.metadata.json"), "w") as f:
            json.dump(meta, f)


_pending = []


def _wait_pending():
    for t in _pending:
        t.join()
    _pending.clear()


def load_state_dict(state_dict: Dict[str, Any], path: str,
                    process_group=None, coordinator_rank: int = 0,
                    offload: bool = False) -> None:
    """Fill ``state_dict`` (a template of Tensors with TARGET shardings)
    in place from the checkpoint at ``path``, resharding as needed."""
    _wait_pending()
    metas = [f for f in os.listdir(path) if f.endswith("metadata.json")]
    if not metas:
        raise FileNotFoundError(f"no metadata.json under {path}")
    meta = {"tensors": {}, "non_tensors": {}}
    for m in metas:
        with open(os.path.join(path, m)) as f:
            part = json.load(f)
        meta["tensors"].update(part.get("tensors", {}))
        meta["non_tensors"].update(part.get("non_tensors", {}))

    flat = _flatten(state_dict)
    for key, target in flat.items():
        if key in meta["non_tensors"]:
            continue
        info = meta["tensors"].get(key)
        if info is None:
            raise KeyError(f"checkpoint missing tensor {key!r}")
        full = np.zeros(info["shape"], dtype=np.dtype(info["dtype"]))
        for shard in info["shards"]:
            data = np.load(os.path.join(path, shard["file"]))
            sl = tuple(slice(a, b) for a, b in shard["index"])
            full[sl] = data
        if isinstance(target, Tensor):
            sharding = getattr(target._data, "sharding", None)
            arr = jax.device_put(full.astype(
                np.dtype(str(np.dtype(target._data.dtype)))), sharding) \
                if sharding is not None else jax.numpy.asarray(full)
            target._data = arr
            target.grad_node = None
        else:
            flat[key] = full
