"""Distributed checkpoint: sharded save / reshard-on-load.

Reference: python/paddle/distributed/checkpoint/save_state_dict.py:145,
load_state_dict.py:467, metadata.py — each rank writes `{rank}_{n}.distcp`
shards + a global Metadata mapping tensor -> (local shape, offset, file);
load reads intersecting shards and reshards to the current placements.

TPU-native: the same contract over jax.Array addressable shards. Every
process writes the shards it owns (global dedup: only the shard whose
``replica_id`` is 0 is written, so replicated params land exactly once
across the whole job) plus its own ``{rank}.metadata.json``; load globs
every rank's metadata, merges the shard lists, and reads ONLY the file
regions intersecting each local device's slice of the *target* sharding
(np.load mmap reads) — loading under a different mesh/parallelism
reshards by construction, without ever materializing the global tensor
in host RAM. A coverage check raises on orphaned/missing shards instead
of silently zero-filling. ``async_save`` snapshots to host then writes
on a worker thread (reference's async_save).

Failure contract (docs/RESILIENCE.md): ``save_state_dict`` returns an
:class:`AsyncSaveHandle` — ``wait()`` re-raises anything the writer
thread hit (async worker exceptions no longer vanish), and the same
error also surfaces at the next ``wait_for_pending_saves()`` /
``load_state_dict()``. An ``atexit`` hook drains pending async saves
before interpreter exit instead of silently dropping them. Individual
shard writes retry transient ``OSError`` through
``resilience.RetryPolicy`` (``io_retry_policy``, swappable); the fault
points ``checkpoint.shard_write`` (inside the retried write) and
``checkpoint.commit`` (after shards, before the metadata flip) make
both the retry path and the commit-point crash contract testable.
"""
from __future__ import annotations

import atexit
import json
import os
import sys
import threading
import time
from typing import Any, Dict, List, Optional

import jax
import numpy as np

from ..framework.tensor import Tensor
from ..resilience.faults import InjectedFault, maybe_fail
from ..resilience.retry import RetryPolicy

__all__ = ["save_state_dict", "load_state_dict", "AsyncSaveHandle",
           "wait_for_pending_saves", "io_retry_policy"]

# shard/metadata writes ride this policy (module-level so deployments
# can swap in a longer-suffering one for flaky network filesystems)
io_retry_policy = RetryPolicy(
    max_attempts=3, base_delay=0.02, max_delay=0.2,
    retry_on=(OSError, InjectedFault))


class AsyncSaveHandle:
    """Completion handle for one ``save_state_dict`` call.

    ``wait()`` blocks until the writer finished and RE-RAISES any
    exception it hit — a failed async save is a caller-visible event,
    not a silently-dropped daemon thread. Synchronous saves return an
    already-done handle for API uniformity.
    """

    def __init__(self):
        self._done = threading.Event()
        self._error: Optional[BaseException] = None
        # once a caller has SEEN the error through wait(), the
        # background drain (wait_for_pending_saves / load) must not
        # re-raise it — a handled save failure would otherwise poison
        # the next unrelated load (e.g. the auto-resume driver's
        # restore-from-previous-checkpoint path)
        self._observed = False

    def done(self) -> bool:
        return self._done.is_set()

    def wait(self, timeout: Optional[float] = None) -> None:
        if not self._done.wait(timeout):
            raise TimeoutError(
                f"checkpoint save still writing after {timeout}s")
        if self._error is not None:
            self._observed = True
            raise self._error

    def _finish(self, error: Optional[BaseException] = None) -> None:
        self._error = error
        self._done.set()


def _flatten(state: Dict[str, Any], prefix="") -> Dict[str, Any]:
    flat = {}
    for k, v in state.items():
        key = f"{prefix}.{k}" if prefix else str(k)
        if isinstance(v, dict):
            flat.update(_flatten(v, key))
        else:
            flat[key] = v
    return flat


def _unflatten(flat: Dict[str, Any]) -> Dict[str, Any]:
    out: Dict[str, Any] = {}
    for k, v in flat.items():
        parts = k.split(".")
        d = out
        for p in parts[:-1]:
            d = d.setdefault(p, {})
        d[parts[-1]] = v
    return out


def save_state_dict(state_dict: Dict[str, Any], path: str,
                    process_group=None, coordinator_rank: int = 0,
                    async_save: bool = False):
    os.makedirs(path, exist_ok=True)
    flat = _flatten(state_dict)
    rank = jax.process_index()
    # overwrite semantics: this rank's previous shard files (from its
    # old metadata) are collected now but only removed AFTER the new
    # save is fully staged and atomically published — a crash mid-save
    # must leave either the complete old or the complete new checkpoint
    # loadable, never neither. A re-save with FEWER processes is caught
    # at load time via world_size.
    old_meta_path = os.path.join(path, f"{rank}.metadata.json")
    old_files = []
    old_gen = -1
    if os.path.exists(old_meta_path):
        try:
            with open(old_meta_path) as f:
                old = json.load(f)
            old_gen = int(old.get("gen", 0))
            for entry in old.get("tensors", {}).values():
                for shard in entry.get("shards", []):
                    old_files.append(shard["file"])
        except (json.JSONDecodeError, OSError, ValueError):
            pass
    # generation tag in every shard filename: a re-save with identical
    # sharding must NOT overwrite the previous save's files in place,
    # or a crash between shard writes and the metadata flip would leave
    # the old metadata pointing at new shard contents (torn state). The
    # flip below is only a commit point if new files are new names.
    gen = old_gen + 1
    meta: Dict[str, Any] = {"tensors": {}, "non_tensors": {},
                            "gen": gen,
                            "world_size": jax.process_count()}
    writes = []

    for key, val in flat.items():
        if isinstance(val, Tensor):
            arr = val._data
        elif isinstance(val, (jax.Array, np.ndarray)):
            arr = val
        else:
            meta["non_tensors"][key] = val
            continue
        entry = {"shape": list(np.shape(arr)),
                 "dtype": str(np.asarray(jax.device_get(
                     arr)).dtype) if not hasattr(arr, "dtype")
                 else str(np.dtype(arr.dtype)), "shards": []}
        if isinstance(arr, jax.Array) and hasattr(arr, "addressable_shards"):
            for i, shard in enumerate(arr.addressable_shards):
                # replica_id is global: exactly one copy of every shard
                # index is written across ALL processes (reference
                # save_state_dict.py dedup_tensor, rank-0-replica rule)
                if shard.replica_id != 0:
                    continue
                idx = tuple(
                    (0 if s.start is None else s.start,
                     dim if s.stop is None else s.stop)
                    for s, dim in zip(shard.index, np.shape(arr)))
                fname = f"{key.replace('/', '_')}.{rank}.{i}.g{gen}.distcp.npy"
                entry["shards"].append({"file": fname,
                                        "index": [list(p) for p in idx]})
                writes.append((os.path.join(path, fname),
                               shard.data))
        else:
            # host-side arrays are identical on every process: only the
            # coordinator writes (the jax.Array branch dedups via
            # replica_id; this is the same rule for np data)
            if rank == coordinator_rank:
                fname = f"{key.replace('/', '_')}.{rank}.0.g{gen}.distcp.npy"
                entry["shards"].append({
                    "file": fname,
                    "index": [[0, d] for d in np.shape(arr)]})
                writes.append((os.path.join(path, fname), arr))
        meta["tensors"][key] = entry

    new_files = {os.path.basename(f) for f, _ in writes}

    def write_one(fpath, data):
        # one staged shard write; transient OSErrors retry through
        # io_retry_policy, and the fault point sits INSIDE the retried
        # body so injected write faults exercise the retry path
        maybe_fail("checkpoint.shard_write", file=fpath)
        tmp = fpath + ".tmp"
        with open(tmp, "wb") as fh:  # np.save would append .npy
            np.save(fh, np.asarray(jax.device_get(data)))
        os.replace(tmp, fpath)

    def do_write():
        # stage everything under temp names, then publish with
        # os.replace (atomic on POSIX): shards first, metadata last —
        # the metadata flip is the commit point. Old shards the new
        # save does not reuse are deleted only after the commit.
        for fpath, data in writes:
            io_retry_policy.call(write_one, fpath, data,
                                 op="checkpoint.shard_write")
        # a crash HERE (new shards staged, metadata still old) must
        # leave the previous generation fully loadable — the torn
        # g{gen} files are invisible to load (only metadata-listed
        # files are read) and the next save's identical names
        # overwrite them
        maybe_fail("checkpoint.commit", path=path)
        # EVERY rank writes its own metadata file: each process only
        # knows about its addressable shards, so a coordinator-only
        # write would orphan every other rank's shard files (load
        # merges the globbed {rank}.metadata.json files)
        meta_tmp = old_meta_path + ".tmp"
        with open(meta_tmp, "w") as f:
            # numpy scalars (np.int32 step counters etc.) land in
            # non_tensors; serialize them as their python values
            json.dump(meta, f,
                      default=lambda o: o.item() if hasattr(o, "item")
                      else str(o))
        os.replace(meta_tmp, old_meta_path)
        for fname in old_files:
            if fname not in new_files:
                try:
                    os.remove(os.path.join(path, fname))
                except OSError:
                    pass

    handle = AsyncSaveHandle()
    if async_save:
        # snapshot to host first (device buffers may be donated later)
        writes = [(f, np.asarray(jax.device_get(d))) for f, d in writes]

        def runner():
            try:
                do_write()
            except BaseException as e:  # captured, surfaced at wait()
                handle._finish(e)
            else:
                handle._finish()

        t = threading.Thread(target=runner, daemon=True)
        t.start()
        _pending.append(handle)
    else:
        try:
            do_write()
        except BaseException as e:
            handle._finish(e)
            raise
        handle._finish()
    return handle


_pending: List[AsyncSaveHandle] = []


def wait_for_pending_saves(timeout: Optional[float] = None) -> None:
    """Block until every in-flight async save finished; re-raise the
    FIRST not-yet-observed writer error (after all have settled, so no
    save is left racing). Errors a caller already saw via
    ``AsyncSaveHandle.wait()`` are considered handled and skipped.
    Called implicitly by ``load_state_dict`` and at interpreter exit.

    ``timeout`` is one TOTAL deadline shared across every pending
    handle — N in-flight saves block for at most ``timeout`` seconds
    overall, not N x timeout. On expiry, handles still writing STAY
    pending (the atexit drain and later calls keep waiting for them)
    and a TimeoutError is raised after the sweep — unless a real
    writer error is also ready, which wins. Each call delivers at most
    ONE error; handles whose error was not delivered stay pending so
    the next call (or load) surfaces them rather than silently
    swallowing all but the first."""
    deadline = None if timeout is None else time.monotonic() + timeout
    first_err = None
    remaining = []
    timed_out = False
    for h in _pending:
        left = None if deadline is None \
            else max(0.0, deadline - time.monotonic())
        if not h._done.wait(left):
            remaining.append(h)
            timed_out = True
            continue
        if h._error is not None and not h._observed:
            if first_err is None:
                h._observed = True
                first_err = h._error
            else:
                remaining.append(h)
    _pending[:] = remaining
    if first_err is not None:
        raise first_err
    if timed_out:
        raise TimeoutError(
            f"checkpoint save still writing after {timeout}s")


_wait_pending = wait_for_pending_saves       # internal alias (pre-PR3)


@atexit.register
def _drain_pending_at_exit():
    # pending async saves must complete before the interpreter tears
    # down (daemon writer threads would otherwise be killed mid-file);
    # unhandled errors print rather than raise — nothing can catch
    # them here, and already-observed ones were the caller's to handle
    for h in list(_pending):
        if not h._done.wait(timeout=60.0):
            print("[checkpoint] async save still writing 60s after "
                  "exit was requested; abandoning it", file=sys.stderr)
        elif h._error is not None and not h._observed:
            print(f"[checkpoint] async save failed at exit: "
                  f"{type(h._error).__name__}: {h._error}",
                  file=sys.stderr)
    _pending.clear()


def load_state_dict(state_dict: Dict[str, Any], path: str,
                    process_group=None, coordinator_rank: int = 0,
                    offload: bool = False) -> None:
    """Fill ``state_dict`` (a template of Tensors with TARGET shardings)
    in place from the checkpoint at ``path``, resharding as needed."""
    _wait_pending()
    metas = [f for f in os.listdir(path) if f.endswith("metadata.json")]
    if not metas:
        raise FileNotFoundError(f"no metadata.json under {path}")
    meta = {"tensors": {}, "non_tensors": {}}
    world_sizes = set()
    for m in metas:
        with open(os.path.join(path, m)) as f:
            part = json.load(f)
        world_sizes.add(part.get("world_size"))
        # merge per-rank metadata: same tensor key appears in several
        # rank files, each contributing its own shard list
        for key, entry in part.get("tensors", {}).items():
            cur = meta["tensors"].setdefault(
                key, {"shape": entry["shape"], "dtype": entry["dtype"],
                      "shards": []})
            if list(cur["shape"]) != list(entry["shape"]):
                raise ValueError(
                    f"inconsistent shapes for {key!r} across rank "
                    f"metadata: {cur['shape']} vs {entry['shape']}")
            cur["shards"].extend(entry["shards"])
        meta["non_tensors"].update(part.get("non_tensors", {}))
    ws = world_sizes - {None}
    if len(ws) > 1 or (ws and len(metas) != next(iter(ws))):
        raise ValueError(
            f"stale checkpoint at {path}: {len(metas)} rank metadata "
            f"files but world_size(s) {sorted(ws)} — was the directory "
            f"re-used by a save with a different process count?")
    for key, entry in meta["tensors"].items():
        _check_no_overlap(key, entry["shards"])

    flat = _flatten(state_dict)
    for key, target in flat.items():
        if key in meta["non_tensors"]:
            _set_nested(state_dict, key, meta["non_tensors"][key])
            continue
        info = meta["tensors"].get(key)
        if info is None:
            raise KeyError(f"checkpoint missing tensor {key!r}")
        shape = tuple(info["shape"])
        if isinstance(target, Tensor):
            tgt_dtype = np.dtype(str(np.dtype(target._data.dtype)))
            sharding = getattr(target._data, "sharding", None)
            if sharding is not None and tuple(target._data.shape) == shape:
                # shard-wise load: each local device reads ONLY the file
                # regions intersecting its slice of the target sharding
                # (memoized — replicated dims map many devices to the
                # same region; read it once)
                idx_map = sharding.addressable_devices_indices_map(shape)
                cache: Dict[Any, np.ndarray] = {}
                bufs = []
                for dev, idx in idx_map.items():
                    region = _normalize_index(idx, shape)
                    ck = tuple(region)
                    if ck not in cache:
                        cache[ck] = _read_region(path, info, region,
                                                 tgt_dtype, key)
                    bufs.append(jax.device_put(cache[ck], dev))
                arr = jax.make_array_from_single_device_arrays(
                    shape, sharding, bufs)
            else:
                full = _read_region(
                    path, info, [(0, d) for d in shape], tgt_dtype, key)
                arr = jax.device_put(full, sharding) \
                    if sharding is not None else jax.numpy.asarray(full)
            target._data = arr
            target.grad_node = None
        else:
            loaded = _read_region(
                path, info, [(0, d) for d in shape],
                np.dtype(info["dtype"]), key)
            if isinstance(target, np.ndarray) and target.shape == shape:
                target[...] = loaded  # in-place keeps aliases coherent
            else:
                _set_nested(state_dict, key, loaded)


def _set_nested(state: Dict[str, Any], key: str, value) -> None:
    """Write a loaded non-Tensor leaf back into the nested state dict."""
    parts = key.split(".")
    d = state
    for p in parts[:-1]:
        d = d[p]
    d[parts[-1]] = value


def _check_no_overlap(key, shards):
    """Merged shard lists must tile without overlap — overlapping
    regions mean two saves' files got mixed in one directory.
    Sweep over dim-0 start offsets keeps this near-linear for the
    common leading-dim sharding instead of all-pairs."""
    order = sorted(range(len(shards)),
                   key=lambda i: [p[0] for p in shards[i]["index"]])
    for oi in range(len(order)):
        i = order[oi]
        a = shards[i]["index"]
        if not a:
            continue
        for oj in range(oi + 1, len(order)):
            j = order[oj]
            b = shards[j]["index"]
            if b[0][0] >= a[0][1]:
                break  # sorted by dim-0 start: no further dim-0 overlap
            if all(max(a0, b0) < min(a1, b1)
                   for (a0, a1), (b0, b1) in zip(a, b)):
                raise ValueError(
                    f"overlapping shards for {key!r}: {a} vs {b} "
                    f"({shards[i]['file']}, {shards[j]['file']}) — "
                    f"stale files from a previous save?")


def _normalize_index(idx, shape):
    """jax device index (tuple of slices, possibly open) -> [(a, b)]."""
    return [(0 if s.start is None else int(s.start),
             d if s.stop is None else int(s.stop))
            for s, d in zip(idx, shape)]


def _read_region(path, info, region, out_dtype, key):
    """Assemble one rectangular region of a checkpointed tensor from the
    intersecting shard files (mmap reads — only the needed bytes move).
    Raises if any part of the region is not covered by a shard."""
    out = np.zeros([b - a for a, b in region], out_dtype)
    want = int(np.prod([b - a for a, b in region], dtype=np.int64))
    got = 0
    for shard in info["shards"]:
        s_idx = shard["index"]
        inter = [(max(a1, a2), min(b1, b2))
                 for (a1, b1), (a2, b2) in zip(region, s_idx)]
        if any(a >= b for a, b in inter):
            continue
        data = np.load(os.path.join(path, shard["file"]), mmap_mode="r")
        src = tuple(slice(a - s0, b - s0)
                    for (a, b), (s0, _) in zip(inter, s_idx))
        dst = tuple(slice(a - r0, b - r0)
                    for (a, b), (r0, _) in zip(inter, region))
        out[dst] = np.asarray(data[src]).astype(out_dtype)
        got += int(np.prod([b - a for a, b in inter], dtype=np.int64))
    if got < want:
        raise ValueError(
            f"checkpoint shards cover only {got}/{want} elements of the "
            f"requested region of {key!r} — missing or orphaned shard "
            f"files (was the checkpoint saved by every rank?)")
    return out
