"""Parameter-server training stack.

Reference: /root/reference/paddle/fluid/distributed/ps/ (brpc PS servers,
memory_sparse_table.cc, sparse_sgd_rule.cc) + python/paddle/distributed/ps
and fleet PS mode (role_maker.py): trillion-parameter sparse embeddings
held in host memory across PS nodes, pulled/pushed per batch by trainers.

TPU-native design: the dense model lives on-chip (XLA); only the sparse
embedding tables need host/parameter-server storage. csrc/ps_table.cc is
the native table engine (deterministic per-key init, server-side SGD /
Adagrad — the sparse_sgd_rule.cc contract); this module provides the
ctypes client/server, a fleet-style role workflow
(init_server/run_server/init_worker/stop_worker), and
``DistributedEmbedding`` — a Layer that pulls rows on forward and pushes
gradients from a backward hook, so a recsys model trains against the PS
while the dense part runs the normal TPU autograd path.
"""
from __future__ import annotations

import ctypes
import os
import threading
from typing import Dict, List, Optional

import numpy as np

from ..framework.tensor import Tensor
from ..nn.layer_base import Layer
from ..utils.native_build import build_native_so

__all__ = ["PsServer", "PsClient", "SparseTable", "SsdSparseTable",
           "GraphTable", "DistributedEmbedding",
           "init_server", "run_server", "init_worker", "stop_worker",
           "get_client"]

_lock = threading.Lock()
_lib = None
_build_failed = False


def _get_lib():
    global _lib, _build_failed
    if _lib is not None or _build_failed:
        return _lib
    with _lock:
        if _lib is not None or _build_failed:
            return _lib
        so = build_native_so("ps_table.cc", "libptps.so")
        if so is None:
            _build_failed = True
            return None
        lib = ctypes.CDLL(so)
        lib.psrv_start.restype = ctypes.c_void_p
        lib.psrv_start.argtypes = [ctypes.c_int]
        lib.psrv_port.restype = ctypes.c_int
        lib.psrv_port.argtypes = [ctypes.c_void_p]
        lib.psrv_stop.argtypes = [ctypes.c_void_p]
        lib.psc_connect.restype = ctypes.c_void_p
        lib.psc_connect.argtypes = [ctypes.c_char_p, ctypes.c_int,
                                    ctypes.c_int]
        lib.psc_close.argtypes = [ctypes.c_void_p]
        lib.psc_create_sparse.restype = ctypes.c_int
        lib.psc_create_sparse.argtypes = [
            ctypes.c_void_p, ctypes.c_uint32, ctypes.c_uint32,
            ctypes.c_int, ctypes.c_float, ctypes.c_float]
        lib.psc_pull_sparse.restype = ctypes.c_int
        lib.psc_pull_sparse.argtypes = [
            ctypes.c_void_p, ctypes.c_uint32,
            ctypes.POINTER(ctypes.c_int64), ctypes.c_uint64,
            ctypes.POINTER(ctypes.c_float), ctypes.c_uint64]
        lib.psc_push_sparse.restype = ctypes.c_int
        lib.psc_push_sparse.argtypes = [
            ctypes.c_void_p, ctypes.c_uint32,
            ctypes.POINTER(ctypes.c_int64), ctypes.c_uint64,
            ctypes.POINTER(ctypes.c_float), ctypes.c_uint64]
        lib.psc_create_dense.restype = ctypes.c_int
        lib.psc_create_dense.argtypes = [
            ctypes.c_void_p, ctypes.c_uint32, ctypes.c_uint64,
            ctypes.c_int, ctypes.c_float]
        lib.psc_pull_dense.restype = ctypes.c_int
        lib.psc_pull_dense.argtypes = [
            ctypes.c_void_p, ctypes.c_uint32,
            ctypes.POINTER(ctypes.c_float), ctypes.c_uint64]
        lib.psc_push_dense.restype = ctypes.c_int
        lib.psc_push_dense.argtypes = [
            ctypes.c_void_p, ctypes.c_uint32,
            ctypes.POINTER(ctypes.c_float), ctypes.c_uint64]
        lib.psc_num_keys.restype = ctypes.c_int64
        lib.psc_num_keys.argtypes = [ctypes.c_void_p, ctypes.c_uint32]
        lib.psc_save.restype = ctypes.c_int
        lib.psc_save.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        lib.psc_load.restype = ctypes.c_int
        lib.psc_load.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        lib.psc_create_sparse_ssd.restype = ctypes.c_int
        lib.psc_create_sparse_ssd.argtypes = [
            ctypes.c_void_p, ctypes.c_uint32, ctypes.c_uint32,
            ctypes.c_int, ctypes.c_float, ctypes.c_float,
            ctypes.c_uint64, ctypes.c_char_p]
        lib.psc_graph_add_edges.restype = ctypes.c_int
        lib.psc_graph_add_edges.argtypes = [
            ctypes.c_void_p, ctypes.c_uint32,
            ctypes.POINTER(ctypes.c_int64),
            ctypes.POINTER(ctypes.c_int64), ctypes.c_uint64]
        lib.psc_graph_sample.restype = ctypes.c_int
        lib.psc_graph_sample.argtypes = [
            ctypes.c_void_p, ctypes.c_uint32,
            ctypes.POINTER(ctypes.c_int64), ctypes.c_uint64,
            ctypes.c_uint32, ctypes.c_uint64,
            ctypes.POINTER(ctypes.c_int64)]
        lib.psc_graph_degree.restype = ctypes.c_int
        lib.psc_graph_degree.argtypes = [
            ctypes.c_void_p, ctypes.c_uint32,
            ctypes.POINTER(ctypes.c_int64), ctypes.c_uint64,
            ctypes.POINTER(ctypes.c_int64)]
        _lib = lib
        return _lib


OPTIMIZERS = {"sgd": 0, "adagrad": 1}


class PsServer:
    """In-process native table server (BrpcPsServer analog)."""

    def __init__(self, port: int = 0):
        lib = _get_lib()
        if lib is None:
            raise RuntimeError("native PS library unavailable (g++ build "
                               "failed); parameter-server mode needs it")
        self._lib = lib
        self._h = lib.psrv_start(port)
        if not self._h:
            raise RuntimeError(f"PsServer: cannot bind port {port}")
        self.port = lib.psrv_port(self._h)

    def stop(self):
        if self._h:
            self._lib.psrv_stop(self._h)
            self._h = None

    def __del__(self):
        try:
            self.stop()
        except Exception:
            pass


class PsClient:
    """Connection to one PS node (BrpcPsClient analog)."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 timeout_s: float = 30.0):
        lib = _get_lib()
        if lib is None:
            raise RuntimeError("native PS library unavailable")
        self._lib = lib
        self._mu = threading.Lock()
        # table_id -> row dim, registered by create_sparse_table; needed
        # to size pull buffers (per-connection, NOT shared across clients)
        self._table_dims: Dict[int, int] = {}
        self._tmp_spills: list = []  # mkstemp'd spill paths we own
        self._h = lib.psc_connect(host.encode(), port,
                                  int(timeout_s * 1000))
        if not self._h:
            raise RuntimeError(f"PsClient: cannot connect {host}:{port}")

    def _handle(self):
        if self._h is None:
            raise RuntimeError("PsClient is closed")
        return self._h

    def close(self):
        with self._mu:
            if self._h:
                self._lib.psc_close(self._h)
                self._h = None
            # unlinking only drops the NAME: a co-located server keeps
            # its open fd (freed on its own fclose), a later reopen by
            # the server recreates the path, and ~Table removes it
            for p in self._tmp_spills:
                try:
                    os.unlink(p)
                except OSError:
                    pass
            self._tmp_spills = []

    # -- tables ------------------------------------------------------------
    def create_sparse_table(self, table_id: int, dim: int,
                            optimizer: str = "sgd", lr: float = 0.01,
                            init_scale: float = 0.05):
        opt = OPTIMIZERS[optimizer]
        with self._mu:
            rc = self._lib.psc_create_sparse(self._handle(), table_id,
                                             dim, opt, lr, init_scale)
        if rc != 0:
            raise RuntimeError(
                f"create_sparse_table({table_id}) failed (an existing "
                f"table with this id and a different dim?)")
        self._table_dims[table_id] = dim

    def create_sparse_ssd_table(self, table_id: int, dim: int,
                                optimizer: str = "sgd",
                                lr: float = 0.01,
                                init_scale: float = 0.05,
                                mem_budget_rows: int = 1 << 20,
                                spill_path: Optional[str] = None):
        """SSD-spill sparse table (reference ssd_sparse_table.cc): only
        ``mem_budget_rows`` hot rows stay in server memory; LRU victims
        — weights AND optimizer state — spill to ``spill_path`` and
        return transparently on access. Same pull/push/save/load API
        as the in-memory table."""
        import tempfile
        opt = OPTIMIZERS[optimizer]
        if spill_path is None:
            # unique per call: a shared /tmp name would let a second
            # server truncate the first one's live spill file
            fd, spill_path = tempfile.mkstemp(
                prefix=f"ps_spill_{table_id}_", suffix=".bin")
            os.close(fd)
            self._tmp_spills.append(spill_path)
        with self._mu:
            rc = self._lib.psc_create_sparse_ssd(
                self._handle(), table_id, dim, opt, lr, init_scale,
                mem_budget_rows, spill_path.encode())
        if rc != 0:
            raise RuntimeError(
                f"create_sparse_ssd_table({table_id}) failed")
        self._table_dims[table_id] = dim

    def graph_add_edges(self, table_id: int, src, dst):
        src = np.ascontiguousarray(src, dtype=np.int64).ravel()
        dst = np.ascontiguousarray(dst, dtype=np.int64).ravel()
        if src.size != dst.size:
            raise ValueError("src/dst length mismatch")
        with self._mu:
            rc = self._lib.psc_graph_add_edges(
                self._handle(), table_id,
                src.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
                dst.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
                src.size)
        if rc != 0:
            raise RuntimeError(f"graph_add_edges({table_id}) failed")

    def graph_sample_neighbors(self, table_id: int, nodes, k: int,
                               seed: int = 0) -> np.ndarray:
        """Uniform-with-replacement neighbor sampling; rows of -1 for
        isolated nodes (reference common_graph_table.cc
        random_sample_neighbors)."""
        nodes = np.ascontiguousarray(nodes, dtype=np.int64).ravel()
        # mirror the server's response-size bound BEFORE allocating:
        # a co-located client must not OOM on the very request the
        # server-side bound rejects
        if k > (1 << 20) or nodes.size * k > (1 << 27):
            raise ValueError(
                f"sample response {nodes.size}x{k} exceeds the "
                f"2^27-element bound; batch the nodes")
        out = np.empty((nodes.size, k), np.int64)
        with self._mu:
            rc = self._lib.psc_graph_sample(
                self._handle(), table_id,
                nodes.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
                nodes.size, k, seed,
                out.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)))
        if rc != 0:
            raise RuntimeError(f"graph_sample({table_id}) failed")
        return out

    def graph_degree(self, table_id: int, nodes) -> np.ndarray:
        nodes = np.ascontiguousarray(nodes, dtype=np.int64).ravel()
        out = np.empty(nodes.size, np.int64)
        with self._mu:
            rc = self._lib.psc_graph_degree(
                self._handle(), table_id,
                nodes.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
                nodes.size,
                out.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)))
        if rc != 0:
            raise RuntimeError(f"graph_degree({table_id}) failed")
        return out

    def pull_sparse(self, table_id: int, keys) -> np.ndarray:
        keys = np.ascontiguousarray(keys, dtype=np.int64).ravel()
        dim = self._table_dims.get(table_id)
        if dim is None:
            raise RuntimeError(
                f"table {table_id} dim unknown to this client; call "
                f"create_sparse_table(table_id, dim, ...) first (it is "
                f"idempotent on the server)")
        out = np.empty((keys.size, dim), np.float32)
        with self._mu:
            rc = self._lib.psc_pull_sparse(
                self._handle(), table_id,
                keys.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
                keys.size,
                out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
                out.size)
        if rc != 0:
            raise RuntimeError(f"pull_sparse({table_id}) failed")
        return out

    def push_sparse(self, table_id: int, keys, grads):
        keys = np.ascontiguousarray(keys, dtype=np.int64).ravel()
        grads = np.ascontiguousarray(grads, dtype=np.float32)
        with self._mu:
            rc = self._lib.psc_push_sparse(
                self._handle(), table_id,
                keys.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
                keys.size,
                grads.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
                grads.size)
        if rc != 0:
            raise RuntimeError(f"push_sparse({table_id}) failed")

    def create_dense_table(self, table_id: int, size: int,
                           optimizer: str = "sgd", lr: float = 0.01):
        with self._mu:
            rc = self._lib.psc_create_dense(self._handle(), table_id,
                                            size, OPTIMIZERS[optimizer],
                                            lr)
        if rc != 0:
            raise RuntimeError(f"create_dense_table({table_id}) failed")

    def pull_dense(self, table_id: int, size: int) -> np.ndarray:
        out = np.empty(size, np.float32)
        with self._mu:
            rc = self._lib.psc_pull_dense(
                self._handle(), table_id,
                out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
                out.size)
        if rc != 0:
            raise RuntimeError(f"pull_dense({table_id}) failed")
        return out

    def push_dense(self, table_id: int, grads):
        grads = np.ascontiguousarray(grads, dtype=np.float32).ravel()
        with self._mu:
            rc = self._lib.psc_push_dense(
                self._handle(), table_id,
                grads.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
                grads.size)
        if rc != 0:
            raise RuntimeError(f"push_dense({table_id}) failed")

    def num_keys(self, table_id: int) -> int:
        with self._mu:
            nk = self._lib.psc_num_keys(self._handle(), table_id)
        if nk < 0:
            raise RuntimeError(f"num_keys({table_id}) failed")
        return int(nk)

    def save(self, path: str):
        with self._mu:
            if self._lib.psc_save(self._handle(), path.encode()) != 0:
                raise RuntimeError(f"PS save({path}) failed")

    def load(self, path: str):
        with self._mu:
            if self._lib.psc_load(self._handle(), path.encode()) != 0:
                raise RuntimeError(f"PS load({path}) failed")

class SparseTable:
    """Handle for one sparse table (memory_sparse_table.cc analog)."""

    _next_id = [0]

    def __init__(self, client: PsClient, dim: int, optimizer: str = "sgd",
                 lr: float = 0.01, init_scale: float = 0.05,
                 table_id: Optional[int] = None):
        if table_id is None:
            table_id = _alloc_table_id()
        self.client = client
        self.table_id = table_id
        self.dim = dim
        client.create_sparse_table(table_id, dim, optimizer, lr,
                                   init_scale)

    def pull(self, keys) -> np.ndarray:
        return self.client.pull_sparse(self.table_id, keys)

    def push(self, keys, grads):
        self.client.push_sparse(self.table_id, keys, grads)

    def num_keys(self) -> int:
        return self.client.num_keys(self.table_id)


def _alloc_table_id() -> int:
    SparseTable._next_id[0] += 1
    return SparseTable._next_id[0]


class SsdSparseTable(SparseTable):
    """Sparse table whose cold rows spill to disk
    (ssd_sparse_table.cc analog): bounded server memory regardless of
    the number of live keys — the mechanism behind the reference's
    trillion-parameter parameter-server claim."""

    def __init__(self, client: PsClient, dim: int,
                 optimizer: str = "sgd", lr: float = 0.01,
                 init_scale: float = 0.05,
                 mem_budget_rows: int = 1 << 20,
                 spill_path: Optional[str] = None,
                 table_id: Optional[int] = None):
        if table_id is None:
            table_id = _alloc_table_id()
        self.client = client
        self.table_id = table_id
        self.dim = dim
        client.create_sparse_ssd_table(table_id, dim, optimizer, lr,
                                       init_scale, mem_budget_rows,
                                       spill_path)


class GraphTable:
    """Adjacency store + uniform neighbor sampling on the PS
    (common_graph_table.cc analog) — the storage side of GNN sampling
    pipelines; the compute side is paddle_tpu.geometric."""

    def __init__(self, client: PsClient,
                 table_id: Optional[int] = None):
        if table_id is None:
            table_id = _alloc_table_id()
        self.client = client
        self.table_id = table_id

    def add_edges(self, src, dst):
        self.client.graph_add_edges(self.table_id, src, dst)

    def sample_neighbors(self, nodes, k: int, seed: int = 0):
        return self.client.graph_sample_neighbors(self.table_id, nodes,
                                                  k, seed)

    def degree(self, nodes):
        return self.client.graph_degree(self.table_id, nodes)


class DistributedEmbedding(Layer):
    """Embedding whose rows live on the parameter server.

    Forward pulls the batch's rows (host -> TPU); backward pushes the
    received row gradients back, where the server applies its optimizer
    rule. The dense model trains through the ordinary optimizer; this
    layer's "update" is entirely server-side — the contract of the
    reference's distributed lookup table
    (python/paddle/distributed/ps/coordinator + c_embedding path).
    """

    def __init__(self, client: PsClient, embedding_dim: int,
                 optimizer: str = "sgd", lr: float = 0.1,
                 init_scale: float = 0.05,
                 table_id: Optional[int] = None):
        super().__init__()
        self.table = SparseTable(client, embedding_dim, optimizer, lr,
                                 init_scale, table_id)
        self.embedding_dim = embedding_dim

    def forward(self, ids: Tensor) -> Tensor:
        ids_np = np.asarray(ids.numpy(), np.int64)
        flat = ids_np.ravel()
        rows = self.table.pull(flat)  # [n, dim]
        out = Tensor(rows.reshape(ids_np.shape + (self.embedding_dim,)),
                     stop_gradient=False)
        table = self.table

        def push_hook(grad: Tensor):
            g = np.asarray(grad.numpy(), np.float32).reshape(
                flat.size, table.dim)
            table.push(flat, g)
            return grad

        if self.training:
            out.register_hook(push_hook)
            out.retain_grads()
        return out


# ---------------------------------------------------------------------------
# fleet-style PS workflow (role_maker.py PADDLE_TRAINING_ROLE contract)
# ---------------------------------------------------------------------------

_state = {"server": None, "client": None}


def _ps_endpoint() -> str:
    eps = os.environ.get("PADDLE_PSERVERS_IP_PORT_LIST", "127.0.0.1:0")
    return eps.split(",")[0]


def init_server(port: Optional[int] = None) -> "PsServer":
    """Start this node's table server (fleet.init_server analog)."""
    if _state["server"] is None:
        if port is None:
            ep = _ps_endpoint()
            port = int(ep.rsplit(":", 1)[1])
        _state["server"] = PsServer(port)
    return _state["server"]


def run_server():
    """Block serving until stop (fleet.run_server analog); the native
    server threads do the work, so this just parks the main thread."""
    srv = init_server()
    try:
        threading.Event().wait()
    except KeyboardInterrupt:
        srv.stop()


def init_worker(host: Optional[str] = None,
                port: Optional[int] = None) -> PsClient:
    """Connect this trainer to the PS (fleet.init_worker analog)."""
    if _state["client"] is None:
        if host is None or port is None:
            ep = _ps_endpoint()
            h, p = ep.rsplit(":", 1)
            host = host or h
            port = port or int(p)
        _state["client"] = PsClient(host, port)
    return _state["client"]


def get_client() -> Optional[PsClient]:
    return _state["client"]


def stop_worker():
    if _state["client"] is not None:
        _state["client"].close()
        _state["client"] = None


def stop_server():
    if _state["server"] is not None:
        _state["server"].stop()
        _state["server"] = None
