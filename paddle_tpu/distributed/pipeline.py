"""SPMD pipeline engine: GPipe-style microbatch pipelining inside ONE
jitted XLA program.

Reference behavior: fleet/meta_parallel/pipeline_parallel.py:575 (1F1B
schedule over NCCL isend/irecv, micro-batch meta exchange). TPU-native
design (SURVEY.md §7 hard part #2 — "no NCCL p2p; implement schedules
inside one jitted program with collective_permute + loop"):

- per-stage parameters are STACKED on a leading stage dim and sharded over
  the ``pipe`` mesh axis, so each stage-rank holds exactly its stage;
- a ``lax.scan`` over M + S - 1 ticks runs every stage in parallel on its
  in-flight microbatch and rotates activations with ``lax.ppermute``
  (the ICI neighbor hop — this is what the torus is for);
- reverse-mode AD through the scan+ppermute yields the backward pipeline
  automatically (cotangents ppermute the opposite direction), so one
  jax.grad gives a full forward/backward schedule. With
  ``jax.remat`` on the stage fn this is activation-checkpointed GPipe;
  bubble fraction (S-1)/(M+S-1) matches the reference's F-then-B.
"""
from __future__ import annotations

from functools import partial
from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from jax import shard_map

__all__ = ["pipeline_forward", "pipeline_train_1f1b",
           "stack_stage_params"]


def stack_stage_params(param_trees):
    """Stack a list of per-stage parameter pytrees along a new leading
    stage dim (host-side helper; shard the result over 'pipe')."""
    return jax.tree.map(lambda *xs: jnp.stack(xs), *param_trees)


def pipeline_forward(stage_fn: Callable, stacked_params: Any,
                     x_micro: jax.Array, mesh: Mesh,
                     axis: str = "pipe", remat: bool = True):
    """Run ``stage_fn(params, x) -> y`` pipelined over the ``axis`` ranks.

    Args:
      stage_fn: one pipeline stage; same signature for every stage.
      stacked_params: pytree, each leaf [S, ...], S = mesh.shape[axis].
      x_micro: [M, mb, ...] microbatched input (M >= S for full util).
    Returns [M, mb, ...] outputs of the last stage.
    """
    S = mesh.shape[axis]
    M = x_micro.shape[0]
    if remat:
        stage_fn = jax.checkpoint(stage_fn)

    def per_rank(params, xs):
        # params leaves arrive [1, ...] (local stage shard) -> squeeze
        params = jax.tree.map(lambda a: a[0], params)
        rank = jax.lax.axis_index(axis)
        T = M + S - 1
        mb_shape = xs.shape[1:]
        state = jnp.zeros(mb_shape, xs.dtype)  # in-flight activation
        out_buf = jnp.zeros((M,) + mb_shape, xs.dtype)

        def tick(carry, t):
            state, out_buf = carry
            # stage 0 ingests microbatch t while t < M
            feed = xs[jnp.minimum(t, M - 1)]
            inp = jnp.where(rank == 0, feed, state)
            y = stage_fn(params, inp)
            # last stage commits finished microbatch t - (S-1)
            done_idx = t - (S - 1)
            commit = (rank == S - 1) & (done_idx >= 0)
            out_buf = jax.lax.cond(
                commit,
                lambda b: jax.lax.dynamic_update_index_in_dim(
                    b, y, jnp.maximum(done_idx, 0), 0),
                lambda b: b, out_buf)
            # rotate activations to the next stage (ring over ICI)
            nxt = jax.lax.ppermute(
                y, axis, [(i, (i + 1) % S) for i in range(S)])
            return (nxt, out_buf), None

        (state, out_buf), _ = jax.lax.scan(tick, (state, out_buf),
                                           jnp.arange(T))
        # share the last stage's outputs with every pipe rank (one
        # broadcast; keeps the result replicated over 'pipe' for the head)
        out = jax.lax.psum(
            jnp.where(rank == S - 1, out_buf, jnp.zeros_like(out_buf)),
            axis)
        return out

    in_specs = (
        jax.tree.map(lambda _: P(axis), stacked_params),
        P(*([None] * x_micro.ndim)),
    )
    out_specs = P(*([None] * x_micro.ndim))
    # map over ONLY the pipe axis: the stage body remains a global-view
    # GSPMD program over the other mesh axes (tp/dp/sep shardings inside
    # stage_fn compose with the pipeline)
    fn = shard_map(per_rank, mesh=mesh, in_specs=in_specs,
                   out_specs=out_specs, axis_names={axis},
                   check_vma=False)
    return fn(stacked_params, x_micro)


def pipeline_train_1f1b(stage_fn: Callable, head_loss_fn: Callable,
                        stacked_params: Any, head_params: Any,
                        x_micro: jax.Array, labels_micro: jax.Array,
                        mesh: Mesh, axis: str = "pipe",
                        stage_aux_weight: float = 0.0,
                        stage_has_aux: bool = None):
    """One-F-one-B pipeline schedule executed ON DEVICE as one jitted
    SPMD program (reference: the dygraph 1F1B runtime of
    fleet/meta_parallel/pipeline_parallel.py:575 and the static
    pipeline_scheduler_pass/pipeline_1f1b.py:39 — there driven by NCCL
    p2p; here one ``lax.scan`` over schedule ticks with
    ``lax.ppermute`` hops).

    Schedule (F and B each one tick): stage ``r`` runs F of microbatch
    ``i`` at tick ``2i + r`` and B of microbatch ``j`` at tick
    ``2j + 2S - 1 - r``; per-rank in-flight forward state is therefore
    at most ``S - r`` microbatches — the 1F1B memory property — so the
    residual ring buffer is ``S`` deep instead of GPipe's ``M``.
    Backward recomputes the stage forward from the saved stage INPUT
    (activation-checkpointed 1F1B, matching the remat convention of the
    GPipe engine above). The loss head runs inside the LAST stage's B
    tick (guarded by ``lax.cond`` so only that rank pays for it), which
    is what lets a full train step — loss, parameter grads, input
    grads — come out of one schedule.

    Args:
      stage_fn(params, x) -> y: one pipeline stage (same for all).
      head_loss_fn(head_params, y, labels) -> scalar mean loss of one
        microbatch.
      stacked_params: pytree, leaves [S, ...], sharded over ``axis``.
      head_params: pytree used by the last stage's loss head.
      x_micro: [M, mb, ...] pipeline inputs (e.g. embedded tokens).
      labels_micro: [M, mb, ...] integer labels.
      stage_aux_weight: weight of the per-stage aux term; with
        ``stage_has_aux`` (defaults to ``stage_aux_weight != 0``)
        ``stage_fn`` returns (y, aux)
        (e.g. an MoE load-balance loss summed over the stage's layers)
        and ``stage_aux_weight * aux`` joins the objective — the vjp is
        seeded with the weight, so balance gradients reach the gates
        through the SAME schedule (this explicit-backward engine is what
        makes MoE+PP composable; the autodiff'd GPipe scan has no side
        channel for it).
    Returns (mean_loss, stacked_param_grads [S, ...], head_grads,
    dx_micro [M, mb, ...]) — dx_micro feeds the embedding backward.
    """
    if stage_has_aux is None:
        stage_has_aux = bool(stage_aux_weight)
    S = mesh.shape[axis]
    M = x_micro.shape[0]
    T_ticks = 2 * M + 2 * S - 2
    mb_shape = x_micro.shape[1:]
    x_dtype = x_micro.dtype

    def per_rank(params, head_p, xs, labels):
        params = jax.tree.map(lambda a: a[0], params)
        rank = jax.lax.axis_index(axis)

        f32 = jnp.float32
        gacc0 = jax.tree.map(lambda a: jnp.zeros(a.shape, f32), params)
        ghead0 = jax.tree.map(lambda a: jnp.zeros(a.shape, f32), head_p)
        carry0 = {
            "fwd_in": jnp.zeros(mb_shape, x_dtype),
            "bwd_in": jnp.zeros(mb_shape, x_dtype),
            "resid": jnp.zeros((S,) + mb_shape, x_dtype),
            "gacc": gacc0,
            "ghead": ghead0,
            "loss": jnp.zeros((), f32),
            "dx_buf": jnp.zeros((M,) + mb_shape, x_dtype),
        }

        def tick(carry, t):
            # schedule predicates for this (tick, rank)
            fi = (t - rank) // 2
            do_f = ((t - rank) >= 0) & ((t - rank) % 2 == 0) & (fi < M)
            bj = (t - (2 * S - 1) + rank) // 2
            do_b = ((t - (2 * S - 1) + rank) >= 0) & \
                   ((t - (2 * S - 1) + rank) % 2 == 0) & (bj < M)
            fi = jnp.clip(fi, 0, M - 1)
            bj = jnp.clip(bj, 0, M - 1)

            # ---- forward slot -------------------------------------
            def run_f(c):
                x_in = jnp.where(rank == 0,
                                 jax.lax.dynamic_index_in_dim(
                                     xs, fi, 0, keepdims=False),
                                 c["fwd_in"])
                y = stage_fn(params, x_in)
                if stage_has_aux:
                    y = y[0]  # fwd slot only routes activations
                c = dict(c)
                c["resid"] = jax.lax.dynamic_update_index_in_dim(
                    c["resid"], x_in, fi % S, 0)
                return c, y

            def skip_f(c):
                return c, c["fwd_in"]

            carry, y_send = jax.lax.cond(do_f, run_f, skip_f, carry)

            # ---- backward slot ------------------------------------
            def run_b(c):
                x_saved = jax.lax.dynamic_index_in_dim(
                    c["resid"], bj % S, 0, keepdims=False)
                if stage_has_aux:
                    (y2, aux2), stage_vjp = jax.vjp(stage_fn, params,
                                                    x_saved)
                else:
                    y2, stage_vjp = jax.vjp(stage_fn, params, x_saved)
                lab = jax.lax.dynamic_index_in_dim(labels, bj, 0,
                                                   keepdims=False)

                def last_rank_seed(_):
                    loss_j, head_vjp = jax.vjp(
                        lambda hp, yy: head_loss_fn(hp, yy, lab),
                        head_p, y2)
                    # seed with 1/M: the schedule accumulates M
                    # per-microbatch MEAN losses, and the reported loss
                    # (and the gpipe baseline's grads) is their mean
                    dhp, dy = head_vjp(jnp.full((), 1.0 / M, f32))
                    return loss_j, dy.astype(x_dtype), dhp

                def other_rank_seed(_):
                    return (jnp.zeros((), f32), c["bwd_in"],
                            jax.tree.map(lambda a: jnp.zeros(
                                a.shape, f32), head_p))

                loss_j, g_out, dhp = jax.lax.cond(
                    rank == S - 1, last_rank_seed, other_rank_seed,
                    operand=None)
                if stage_has_aux:
                    # aux joins the objective with coefficient
                    # stage_aux_weight * (1/M): the loss accumulator is
                    # divided by M at exit, so the tick adds aux2 * w
                    # while the vjp seed carries the full w/M
                    dparams, dx = stage_vjp(
                        (g_out.astype(y2.dtype),
                         jnp.full((), stage_aux_weight / M, f32)))
                    loss_j = loss_j + aux2 * stage_aux_weight
                else:
                    dparams, dx = stage_vjp(g_out.astype(y2.dtype))
                c = dict(c)
                c["gacc"] = jax.tree.map(
                    lambda g, d: g + d.astype(f32), c["gacc"], dparams)
                c["ghead"] = jax.tree.map(
                    lambda g, d: g + d.astype(f32), c["ghead"], dhp)
                c["loss"] = c["loss"] + loss_j
                dxc = dx.astype(x_dtype)
                c["dx_buf"] = jax.lax.cond(
                    rank == 0,
                    lambda b: jax.lax.dynamic_update_index_in_dim(
                        b, dxc, bj, 0),
                    lambda b: b, c["dx_buf"])
                return c, dxc

            def skip_b(c):
                return c, c["bwd_in"]

            carry, dx_send = jax.lax.cond(do_b, run_b, skip_b, carry)

            # ---- ring hops (fwd down, cotangent up) ---------------
            carry["fwd_in"] = jax.lax.ppermute(
                y_send, axis, [(i, (i + 1) % S) for i in range(S)])
            carry["bwd_in"] = jax.lax.ppermute(
                dx_send, axis, [(i, (i - 1) % S) for i in range(S)])
            return carry, None

        carry, _ = jax.lax.scan(tick, carry0, jnp.arange(T_ticks))

        loss = jax.lax.psum(carry["loss"], axis) / M
        ghead = jax.tree.map(lambda g: jax.lax.psum(g, axis),
                             carry["ghead"])
        dx = jax.lax.psum(
            jnp.where(rank == 0, carry["dx_buf"],
                      jnp.zeros_like(carry["dx_buf"])), axis)
        gstacked = jax.tree.map(lambda g: g[None], carry["gacc"])
        return loss, gstacked, ghead, dx

    in_specs = (
        jax.tree.map(lambda _: P(axis), stacked_params),
        jax.tree.map(lambda _: P(), head_params),
        P(*([None] * x_micro.ndim)),
        P(*([None] * labels_micro.ndim)),
    )
    out_specs = (
        P(),
        jax.tree.map(lambda _: P(axis), stacked_params),
        jax.tree.map(lambda _: P(), head_params),
        P(*([None] * x_micro.ndim)),
    )
    fn = shard_map(per_rank, mesh=mesh, in_specs=in_specs,
                   out_specs=out_specs, axis_names={axis},
                   check_vma=False)
    return fn(stacked_params, head_params, x_micro, labels_micro)
