"""SPMD pipeline engine: GPipe-style microbatch pipelining inside ONE
jitted XLA program.

Reference behavior: fleet/meta_parallel/pipeline_parallel.py:575 (1F1B
schedule over NCCL isend/irecv, micro-batch meta exchange). TPU-native
design (SURVEY.md §7 hard part #2 — "no NCCL p2p; implement schedules
inside one jitted program with collective_permute + loop"):

- per-stage parameters are STACKED on a leading stage dim and sharded over
  the ``pipe`` mesh axis, so each stage-rank holds exactly its stage;
- a ``lax.scan`` over M + S - 1 ticks runs every stage in parallel on its
  in-flight microbatch and rotates activations with ``lax.ppermute``
  (the ICI neighbor hop — this is what the torus is for);
- reverse-mode AD through the scan+ppermute yields the backward pipeline
  automatically (cotangents ppermute the opposite direction), so one
  jax.grad gives a full forward/backward schedule. With
  ``jax.remat`` on the stage fn this is activation-checkpointed GPipe;
  bubble fraction (S-1)/(M+S-1) matches the reference's F-then-B.
"""
from __future__ import annotations

from functools import partial
from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from jax import shard_map

__all__ = ["pipeline_forward", "stack_stage_params"]


def stack_stage_params(param_trees):
    """Stack a list of per-stage parameter pytrees along a new leading
    stage dim (host-side helper; shard the result over 'pipe')."""
    return jax.tree.map(lambda *xs: jnp.stack(xs), *param_trees)


def pipeline_forward(stage_fn: Callable, stacked_params: Any,
                     x_micro: jax.Array, mesh: Mesh,
                     axis: str = "pipe", remat: bool = True):
    """Run ``stage_fn(params, x) -> y`` pipelined over the ``axis`` ranks.

    Args:
      stage_fn: one pipeline stage; same signature for every stage.
      stacked_params: pytree, each leaf [S, ...], S = mesh.shape[axis].
      x_micro: [M, mb, ...] microbatched input (M >= S for full util).
    Returns [M, mb, ...] outputs of the last stage.
    """
    S = mesh.shape[axis]
    M = x_micro.shape[0]
    if remat:
        stage_fn = jax.checkpoint(stage_fn)

    def per_rank(params, xs):
        # params leaves arrive [1, ...] (local stage shard) -> squeeze
        params = jax.tree.map(lambda a: a[0], params)
        rank = jax.lax.axis_index(axis)
        T = M + S - 1
        mb_shape = xs.shape[1:]
        state = jnp.zeros(mb_shape, xs.dtype)  # in-flight activation
        out_buf = jnp.zeros((M,) + mb_shape, xs.dtype)

        def tick(carry, t):
            state, out_buf = carry
            # stage 0 ingests microbatch t while t < M
            feed = xs[jnp.minimum(t, M - 1)]
            inp = jnp.where(rank == 0, feed, state)
            y = stage_fn(params, inp)
            # last stage commits finished microbatch t - (S-1)
            done_idx = t - (S - 1)
            commit = (rank == S - 1) & (done_idx >= 0)
            out_buf = jax.lax.cond(
                commit,
                lambda b: jax.lax.dynamic_update_index_in_dim(
                    b, y, jnp.maximum(done_idx, 0), 0),
                lambda b: b, out_buf)
            # rotate activations to the next stage (ring over ICI)
            nxt = jax.lax.ppermute(
                y, axis, [(i, (i + 1) % S) for i in range(S)])
            return (nxt, out_buf), None

        (state, out_buf), _ = jax.lax.scan(tick, (state, out_buf),
                                           jnp.arange(T))
        # share the last stage's outputs with every pipe rank (one
        # broadcast; keeps the result replicated over 'pipe' for the head)
        out = jax.lax.psum(
            jnp.where(rank == S - 1, out_buf, jnp.zeros_like(out_buf)),
            axis)
        return out

    in_specs = (
        jax.tree.map(lambda _: P(axis), stacked_params),
        P(*([None] * x_micro.ndim)),
    )
    out_specs = P(*([None] * x_micro.ndim))
    # map over ONLY the pipe axis: the stage body remains a global-view
    # GSPMD program over the other mesh axes (tp/dp/sep shardings inside
    # stage_fn compose with the pipeline)
    fn = shard_map(per_rank, mesh=mesh, in_specs=in_specs,
                   out_specs=out_specs, axis_names={axis},
                   check_vma=False)
    return fn(stacked_params, x_micro)
