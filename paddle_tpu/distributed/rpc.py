"""paddle.distributed.rpc parity: init_rpc / rpc_sync / rpc_async /
get_worker_info / shutdown.

Reference: python/paddle/distributed/rpc/rpc.py over the brpc C++ agent
(/root/reference/paddle/fluid/distributed/rpc/rpc_agent.cc). TPU-native
design: rendezvous through the native TCPStore (csrc/tcp_store.cc), message
transport over plain TCP sockets with pickled python payloads — RPC in the
reference is a *control-plane* feature (parameter-server control, elastic
coordination), not the tensor data plane (which is XLA collectives), so
python-side serving with a thread pool matches the use while staying
dependency-free.

Only connect to trusted peers: like the reference's agent, payloads are
pickled python objects, so the RPC mesh must live inside one trusted job
(the launcher's private network), never exposed publicly.
"""
from __future__ import annotations

import os
import pickle
import socket
import struct
import threading
from concurrent.futures import Future, ThreadPoolExecutor
from dataclasses import dataclass
from typing import Any, Dict, Optional

from .store import TCPStore

__all__ = ["init_rpc", "rpc_sync", "rpc_async", "get_worker_info",
           "get_all_worker_infos", "get_current_worker_info", "shutdown",
           "WorkerInfo"]


@dataclass(frozen=True)
class WorkerInfo:
    name: str
    rank: int
    ip: str
    port: int


class _RpcAgent:
    def __init__(self, name: str, rank: int, world_size: int,
                 store: TCPStore):
        self.name = name
        self.rank = rank
        self.world_size = world_size
        self.store = store
        # separate pools: inbound serving must never queue behind outbound
        # calls (self-RPC / mutual saturation would deadlock until timeout)
        self._pool = ThreadPoolExecutor(max_workers=8)        # serve side
        self._client_pool = ThreadPoolExecutor(max_workers=8)  # rpc_async
        self._serve_sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._serve_sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._serve_sock.bind(("0.0.0.0", 0))
        self._serve_sock.listen(64)
        self.port = self._serve_sock.getsockname()[1]
        self.ip = os.environ.get("PADDLE_LOCAL_IP", "127.0.0.1")
        self._stop = False
        self._thread = threading.Thread(target=self._serve, daemon=True)
        self._thread.start()
        # publish, then learn everyone
        store.set(f"rpc/{rank}",
                  pickle.dumps(WorkerInfo(name, rank, self.ip, self.port)))
        self.workers: Dict[str, WorkerInfo] = {}
        for r in range(world_size):
            info = pickle.loads(store.get(f"rpc/{r}"))
            if info.name in self.workers:
                raise ValueError(
                    f"duplicate rpc worker name {info.name!r} (ranks "
                    f"{self.workers[info.name].rank} and {info.rank}); "
                    f"names must be unique across ranks")
            self.workers[info.name] = info

    # ---- server side -----------------------------------------------------
    def _serve(self):
        while not self._stop:
            try:
                conn, _ = self._serve_sock.accept()
            except OSError:
                return
            self._pool.submit(self._handle, conn)

    def _handle(self, conn: socket.socket):
        try:
            payload = _recv_msg(conn)
            fn, args, kwargs = pickle.loads(payload)
            try:
                result = (True, fn(*args, **kwargs))
            except Exception as e:  # ship the exception back
                result = (False, e)
            try:
                blob = pickle.dumps(result)
            except Exception as e:
                # unpicklable result/exception: ship a picklable error
                # instead of silently closing (caller would only see
                # ConnectionError with no cause)
                blob = pickle.dumps(
                    (False, RuntimeError(
                        f"rpc result not picklable: {type(e).__name__}: "
                        f"{e}")))
            _send_msg(conn, blob)
        except Exception:
            pass
        finally:
            conn.close()

    # ---- client side -----------------------------------------------------
    def call(self, to: str, fn, args, kwargs, timeout: float) -> Any:
        info = self.workers.get(to)
        if info is None:
            raise ValueError(f"unknown rpc worker {to!r}; known: "
                             f"{sorted(self.workers)}")
        with socket.create_connection((info.ip, info.port),
                                      timeout=timeout if timeout > 0
                                      else None) as s:
            _send_msg(s, pickle.dumps((fn, args or (), kwargs or {})))
            ok, result = pickle.loads(_recv_msg(s))
        if not ok:
            raise result
        return result

    def stop(self):
        self._stop = True
        try:
            self._serve_sock.close()
        except OSError:
            pass
        self._pool.shutdown(wait=False)
        self._client_pool.shutdown(wait=False)


from ._framing import send_msg as _send_msg, recv_msg as _recv_msg, \
    recv_exact as _recv_exact  # shared '<Q' framing (one protocol)


_agent: Optional[_RpcAgent] = None


def init_rpc(name: str, rank: Optional[int] = None,
             world_size: Optional[int] = None,
             master_endpoint: Optional[str] = None):
    """Start this process's RPC agent and rendezvous with peers
    (reference: rpc.py init_rpc — env fallbacks PADDLE_TRAINER_ID /
    PADDLE_TRAINERS_NUM / PADDLE_MASTER)."""
    global _agent
    if _agent is not None:
        raise RuntimeError("rpc already initialized")
    rank = int(os.environ.get("PADDLE_TRAINER_ID", 0)) if rank is None \
        else rank
    world_size = int(os.environ.get("PADDLE_TRAINERS_NUM", 1)) \
        if world_size is None else world_size
    master_endpoint = master_endpoint or \
        os.environ.get("PADDLE_MASTER", "127.0.0.1:0")
    host, port_s = master_endpoint.rsplit(":", 1)
    store = TCPStore(host, int(port_s), is_master=(rank == 0),
                     world_size=world_size)
    if rank == 0 and int(port_s) == 0:
        # ephemeral master port: publish for spawned same-host peers
        os.environ["PADDLE_MASTER"] = f"{host}:{store.port}"
    _agent = _RpcAgent(name, rank, world_size, store)
    store.barrier("rpc_init")
    return _agent


def rpc_sync(to: str, fn, args=None, kwargs=None, timeout: float = 180.0):
    """Blocking remote call; returns fn(*args, **kwargs) run on `to`."""
    if _agent is None:
        raise RuntimeError("call init_rpc first")
    return _agent.call(to, fn, args, kwargs, timeout)


def rpc_async(to: str, fn, args=None, kwargs=None,
              timeout: float = 180.0) -> Future:
    """Non-blocking remote call returning a Future (reference returns a
    FutureWrapper with .wait(); concurrent.futures.Future.result() is the
    python-native equivalent — .wait is aliased)."""
    if _agent is None:
        raise RuntimeError("call init_rpc first")
    fut = _agent._client_pool.submit(_agent.call, to, fn, args, kwargs,
                                     timeout)
    if not hasattr(fut, "wait"):
        fut.wait = fut.result  # paddle API compat
    return fut


def get_worker_info(name: str) -> WorkerInfo:
    if _agent is None:
        raise RuntimeError("call init_rpc first")
    return _agent.workers[name]


def get_all_worker_infos():
    if _agent is None:
        raise RuntimeError("call init_rpc first")
    return sorted(_agent.workers.values(), key=lambda w: w.rank)


def get_current_worker_info() -> WorkerInfo:
    if _agent is None:
        raise RuntimeError("call init_rpc first")
    return _agent.workers[_agent.name]


def shutdown():
    """Graceful: barrier so in-flight work drains, then stop the agent."""
    global _agent
    if _agent is None:
        return
    _agent.store.barrier("rpc_shutdown")
    _agent.stop()
    _agent.store.close()
    _agent = None
