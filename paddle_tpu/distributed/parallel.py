"""DataParallel (reference: python/paddle/distributed/parallel.py:219
DataParallel + C++ EagerReducer bucketed allreduce, reducer.cc:752/:1086).

TPU-native: there is no gradient bucketing/reducer — with the batch sharded
over the ``data`` axis and the loss a global mean, grads ARE the
all-reduced grads (GSPMD inserts one fused reduce per parameter, overlapped
by the XLA scheduler). DataParallel therefore:
- shards input batches over the data axis (scatter),
- replicates parameters across it (sync_params_buffers analog at wrap),
and otherwise passes through to the wrapped layer.
"""
from __future__ import annotations

from typing import Optional

import jax

from ..framework.tensor import Tensor, no_grad
from ..nn.layer_base import Layer
from .api import reshard, shard_tensor
from .placements import Replicate, Shard
from .process_mesh import ProcessMesh, auto_mesh, get_mesh, set_mesh

__all__ = ["DataParallel"]


class DataParallel(Layer):
    def __init__(self, layers: Layer, strategy=None, comm_buffer_size=25,
                 last_comm_buffer_size=1, find_unused_parameters=False,
                 group=None):
        super().__init__()
        self._layers = layers
        mesh = get_mesh()
        if mesh is None:
            mesh = auto_mesh(["data"])
            set_mesh(mesh)
        self.mesh = mesh
        self.axis = "data" if "data" in mesh.dim_names else \
            mesh.dim_names[0]
        # sync_params_buffers analog: replicate params over the data axis
        with no_grad():
            for _, p in layers.named_parameters():
                if getattr(p, "_dist_mesh", None) is None:
                    new = shard_tensor(p, mesh,
                                       [Replicate()] * mesh.ndim)
                    p._data = new._data

    def _shard_batch(self, x):
        if isinstance(x, Tensor) and x.ndim > 0 and \
                x.shape[0] % self.mesh.get_dim_size(self.axis) == 0:
            placements = [Replicate() for _ in range(self.mesh.ndim)]
            placements[self.mesh.dim_names.index(self.axis)] = Shard(0)
            return reshard(x, self.mesh, placements)
        return x

    def forward(self, *inputs, **kwargs):
        inputs = tuple(self._shard_batch(x) for x in inputs)
        return self._layers(*inputs, **kwargs)

    # reference surface ----------------------------------------------------
    def scale_loss(self, loss):
        return loss

    @no_grad()
    def apply_collective_grads(self):
        pass  # grads are already globally reduced (GSPMD)

    def state_dict(self, *args, **kwargs):
        return self._layers.state_dict(*args, **kwargs)

    def set_state_dict(self, state_dict, *args, **kwargs):
        return self._layers.set_state_dict(state_dict, *args, **kwargs)

    @property
    def parameters_(self):
        return self._layers.parameters()
