"""Semi-auto parallel user API: shard_tensor / reshard / shard_layer /
shard_optimizer.

Reference: python/paddle/distributed/auto_parallel/api.py:205 shard_tensor,
:727 reshard, :828 shard_layer, :1613 shard_optimizer. The reference's
DistTensor machinery (InferSpmd -> explicit reshard functions -> local
kernels, dist_api_gen.py:46) collapses on TPU into GSPMD: a sharded Tensor
is just a Tensor whose jax.Array carries a NamedSharding, ops run through
the same apply_op, and XLA propagates shardings + inserts collectives
(SURVEY.md §7: "the reference's InferSpmd ≈ GSPMD propagation — free").
"""
from __future__ import annotations

from typing import Callable, Dict, List, Optional, Sequence

import jax
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec

from ..framework.tensor import Parameter, Tensor, no_grad
from ..nn.layer_base import Layer
from .placements import (Partial, Placement, Replicate, Shard,
                         named_sharding, placements_to_spec,
                         spec_to_placements)
from .process_mesh import ProcessMesh, get_mesh

__all__ = ["shard_tensor", "reshard", "shard_layer", "shard_optimizer",
           "DistModel", "to_static",
           "dtensor_from_fn", "unshard_dtensor", "get_placements",
           "ShardingStage1", "ShardingStage2", "ShardingStage3"]


def shard_tensor(data, mesh: ProcessMesh, placements: Sequence[Placement],
                 dtype=None, place=None, stop_gradient=None) -> Tensor:
    """Place ``data`` on the mesh with the given placements."""
    if isinstance(data, Tensor):
        t = data
    else:
        t = Tensor(data, dtype=dtype)
    sharding = named_sharding(mesh, placements)
    arr = jax.device_put(t._data, sharding)
    out = Parameter(arr) if isinstance(t, Parameter) else Tensor(arr)
    out.stop_gradient = t.stop_gradient if stop_gradient is None \
        else stop_gradient
    out.name = t.name
    _copy_param_attrs(t, out)
    out._dist_mesh = mesh
    out._dist_placements = list(placements)
    return out


def _copy_param_attrs(src, dst):
    for attr in ("optimize_attr", "regularizer", "need_clip"):
        if hasattr(src, attr):
            setattr(dst, attr, getattr(src, attr))


def reshard(x: Tensor, mesh: ProcessMesh,
            placements: Sequence[Placement]) -> Tensor:
    """Change placements (reference: 13 explicit reshard transitions under
    phi/core/distributed/auto_parallel/reshard/ — here one device_put;
    XLA emits the collective: s->r = all_gather, p->r = all_reduce,
    s->s' = all_to_all, r->s = local slice)."""
    if any(isinstance(p, Partial) for p in placements):
        raise NotImplementedError(
            "resharding TO a Partial placement is not supported (matches "
            "the reference, which only supports partial as a source)")
    sharding = named_sharding(mesh, placements)

    # p->r / p->s: reduce the pending partial terms over the partial mesh
    # axes first (reference p_to_r/p_to_s reshard functions; each replica
    # holds a partial contribution, so the reduce combines them). The
    # reduce runs on the SOURCE mesh — that's where the contributions
    # live — before any cross-mesh transfer.
    src = getattr(x, "_dist_placements", None)
    src_mesh = getattr(x, "_dist_mesh", None) or mesh
    partials = [(src_mesh.dim_names[i], p.reduce_type)
                for i, p in enumerate(src or [])
                if isinstance(p, Partial)] if src is not None else []

    def transfer(a):
        if partials:
            from .placements import placements_to_spec
            nonpartial = [Replicate() if isinstance(p, Partial) else p
                          for p in src]
            spec = placements_to_spec(src_mesh, nonpartial)

            def reduce_local(b):
                for ax, rt in partials:
                    if rt == "sum":
                        b = jax.lax.psum(b, ax)
                    elif rt == "avg":
                        b = jax.lax.pmean(b, ax)
                    elif rt == "max":
                        b = jax.lax.pmax(b, ax)
                    elif rt == "min":
                        b = jax.lax.pmin(b, ax)
                    else:
                        raise NotImplementedError(
                            f"partial reduce_type {rt!r}")
                return b

            a = jax.shard_map(reduce_local, mesh=src_mesh.jax_mesh(),
                              in_specs=(spec,), out_specs=spec,
                              check_vma=False)(a)
            if not isinstance(a, jax.core.Tracer) and \
                    src_mesh is not mesh:
                # detach from the source mesh before the cross-mesh put
                a = jax.numpy.asarray(np.asarray(a))
        if isinstance(a, jax.core.Tracer):
            return jax.lax.with_sharding_constraint(a, sharding)
        return jax.device_put(a, sharding)

    if isinstance(x._data, jax.core.Tracer) or x.stop_gradient:
        out = Tensor(transfer(x._data), stop_gradient=x.stop_gradient)
    else:
        # record the transition on the tape so gradients reshard back
        # (the reference registers a grad per reshard function)
        from ..framework.tensor import apply_op
        out = apply_op(transfer, x, _op_name="reshard")
    out._dist_mesh = mesh
    out._dist_placements = list(placements)
    return out


def get_placements(x: Tensor) -> Optional[List[Placement]]:
    if hasattr(x, "_dist_placements"):
        return list(x._dist_placements)
    sharding = getattr(x._data, "sharding", None)
    mesh = get_mesh()
    if sharding is None or mesh is None or not isinstance(
            sharding, NamedSharding):
        return None
    return spec_to_placements(mesh, sharding.spec, x.ndim)


def shard_layer(layer: Layer, process_mesh: ProcessMesh,
                shard_fn: Optional[Callable] = None,
                input_fn: Optional[Callable] = None,
                output_fn: Optional[Callable] = None) -> Layer:
    """Shard every parameter of ``layer`` on the mesh
    (auto_parallel/api.py:828). Default: replicate everything; a shard_fn
    ``(name, layer, mesh) -> None`` may call shard_tensor on params."""
    def _default_shard(name, sublayer, mesh):
        for pname, p in list(sublayer._parameters.items()):
            if p is None or getattr(p, "_dist_mesh", None) is not None:
                continue
            sublayer._parameters[pname] = shard_tensor(
                p, mesh, [Replicate() for _ in range(mesh.ndim)])

    fn = shard_fn or _default_shard
    for name, sub in layer.named_sublayers(include_self=True):
        fn(name, sub, process_mesh)
    if input_fn is not None:
        layer.register_forward_pre_hook(
            lambda l, inputs: input_fn(inputs, process_mesh))
    if output_fn is not None:
        layer.register_forward_post_hook(
            lambda l, inputs, outputs: output_fn(outputs, process_mesh))
    return layer


class _ShardingStage:
    """Marker passed to shard_optimizer (auto_parallel/api.py:1613
    ShardingStage1/2/3 pass-through): which axis shards optimizer state
    (stage1/2) or parameters (stage3)."""

    def __init__(self, axis_name: str = "dp", mesh: Optional[ProcessMesh] = None):
        self.axis_name = axis_name
        self.mesh = mesh


class ShardingStage1(_ShardingStage):
    pass


class ShardingStage2(_ShardingStage):
    pass


class ShardingStage3(_ShardingStage):
    pass


def shard_optimizer(optimizer, shard_fn: Optional[_ShardingStage] = None):
    """Make optimizer state follow parameter shardings (and, with a
    ShardingStage marker, additionally shard state over the given axis —
    ZeRO-style; see distributed.sharding for the dygraph-API analog).

    TPU-native: state arrays are device_put with the param's sharding
    (stage0) or with the fsdp axis sharded in (stage1/2/3) — XLA handles
    gather/scatter at use sites.
    """
    orig_acc = optimizer._acc

    def _sharded_acc(p, name, init=None):
        arr = orig_acc(p, name, init)
        target = _state_sharding(p, name, shard_fn)
        if target is not None and getattr(arr, "sharding", None) != target \
                and not isinstance(arr, jax.core.Tracer):
            arr = jax.device_put(arr, target)
            optimizer._accumulators[p.name][name] = arr
        return arr

    optimizer._acc = _sharded_acc
    optimizer._sharding_stage = shard_fn
    return optimizer


def _state_sharding(p, state_name, stage):
    sharding = getattr(p._data, "sharding", None)
    if not isinstance(sharding, NamedSharding):
        return None
    if stage is None or state_name == "master_weight":
        return sharding
    mesh = sharding.mesh
    spec = list(tuple(sharding.spec)) + [None] * (
        p._data.ndim - len(tuple(sharding.spec)))
    axis = stage.axis_name
    if axis in mesh.axis_names and axis not in [
            s for e in spec if e for s in
            (e if isinstance(e, tuple) else (e,))]:
        # shard state dim 0 over the fsdp/dp axis when divisible
        if p._data.ndim and p._data.shape[0] % mesh.shape[axis] == 0:
            first = spec[0]
            if first is None:
                spec[0] = axis
            elif isinstance(first, tuple):
                spec[0] = first + (axis,)
            else:
                spec[0] = (first, axis)
    return NamedSharding(mesh, PartitionSpec(*spec))


def dtensor_from_fn(fn: Callable, mesh: ProcessMesh,
                    placements: Sequence[Placement], *args,
                    **kwargs) -> Tensor:
    return shard_tensor(fn(*args, **kwargs), mesh, placements)


def unshard_dtensor(x: Tensor) -> Tensor:
    """Gather to a fully-replicated tensor (dist->dense)."""
    mesh = getattr(x, "_dist_mesh", None) or get_mesh()
    if mesh is None:
        return x
    return reshard(x, mesh, [Replicate() for _ in range(mesh.ndim)])


class DistModel:
    """Jitted distributed train/eval/predict wrapper
    (auto_parallel/api.py:2132 DistModel).

    The reference compiles the layer into a per-rank PIR program through
    the static Engine (engine.py _parallel_pir); here the layer's
    parameters already carry NamedShardings (shard_tensor/shard_layer),
    so one jitted step — forward + grad + optimizer update via
    jit.functional.TrainStep — IS the parallelized program: GSPMD
    partitions it and inserts the collectives the reference's partition/
    reshard passes emit. Batches are sharded over the mesh's first axis
    (the data axis by convention).
    """

    def __init__(self, layer, loader=None, loss=None, optimizer=None,
                 strategy=None, metrics=None):
        self.network = layer
        self._loss = loss
        self._opt = optimizer
        self._mode = "train" if (loss is not None and
                                 optimizer is not None) else (
            "eval" if loss is not None else "predict")
        self._step = None  # train mode: the jitted TrainStep
        # eval/predict run the eager forward: jit them per-user-need with
        # paddle.jit.to_static(layer); only the train step is fused here

    # -- mode switches (reference DistModel contract) ---------------------
    def train(self):
        if self._loss is None or self._opt is None:
            raise RuntimeError("DistModel needs loss and optimizer for "
                               "train mode (pass them to dist.to_static)")
        self._mode = "train"
        self.network.train()
        return self

    def eval(self):
        if self._loss is None:
            raise RuntimeError("DistModel needs a loss for eval mode")
        self._mode = "eval"
        self.network.eval()
        return self

    def predict(self):
        self._mode = "predict"
        self.network.eval()
        return self

    def _shard_batch(self, x):
        mesh = get_mesh()
        if mesh is None or not isinstance(x, Tensor):
            return x
        jm = mesh.jax_mesh()
        n = jm.shape[jm.axis_names[0]]
        if x._data.ndim and x._data.shape[0] % n == 0:
            return shard_tensor(
                x, mesh, [Shard(0)] + [Replicate()] * (mesh.ndim - 1))
        return x

    def __call__(self, *data):
        data = tuple(self._shard_batch(d) for d in data)
        if self._mode == "train":
            if self._step is None:
                from ..jit.functional import TrainStep
                self._step = TrainStep(self.network, self._opt,
                                       self._loss)
            return self._step(*data)
        if self._mode == "eval":
            with no_grad():
                out = self.network(*data[:-1])
                return self._loss(out, data[-1])
        with no_grad():
            return self.network(*data)

    def lower(self, *data):
        """Lower the train step with the batch sharded exactly as
        ``__call__`` would shard it — the compiled distributed program
        (``.compile().as_text()`` = optimized HLO with the GSPMD
        collectives) for traffic auditing
        (benchmarks/scaling_model.py)."""
        if self._mode != "train":
            raise RuntimeError("lower() audits the train step; call "
                               ".train() first")
        data = tuple(self._shard_batch(d) for d in data)
        if self._step is None:
            from ..jit.functional import TrainStep
            self._step = TrainStep(self.network, self._opt, self._loss)
        return self._step.lower(*data)

    def state_dict(self, *a, **k):
        return self.network.state_dict(*a, **k)

    def set_state_dict(self, sd, *a, **k):
        return self.network.set_state_dict(sd, *a, **k)

    def parameters(self):
        return self.network.parameters()

    def dist_main_program(self, mode=None):
        return None  # no per-rank program object: GSPMD owns partitioning


def to_static(layer, loader=None, loss=None, optimizer=None,
              strategy=None, metrics=None) -> DistModel:
    """dist.to_static (auto_parallel/api.py:2715): returns a DistModel
    whose __call__ runs one fully-jitted, GSPMD-sharded step."""
    return DistModel(layer, loader, loss, optimizer, strategy, metrics)
