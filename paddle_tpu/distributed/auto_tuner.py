"""Auto-tuner: parallel-config search.

Reference: python/paddle/distributed/auto_tuner/ (tuner.py, prune.py) —
grid search over dp/mp/pp/sharding degrees, micro-batch size, recompute;
prunes by divisibility/memory model, launches trial runs, records best.

TPU-native: candidates are mesh shapes; pruning uses an analytic memory
model (params + optimizer state + activations vs HBM) and the trial is a
user-supplied callable (typically: build GPTSpmdTrainer on the candidate
mesh, run a few steps, return tokens/sec). Compile caching makes trials
cheap relative to the reference's full relaunches.
"""
from __future__ import annotations

import dataclasses
import itertools
import json
import math
import os
import time
from typing import Callable, Dict, List, Optional

__all__ = ["TunerConfig", "Candidate", "AutoTuner", "default_candidates",
           "prune_by_memory", "tune_gpt"]


@dataclasses.dataclass
class Candidate:
    dp: int = 1
    mp: int = 1
    pp: int = 1
    sharding: int = 1
    sep: int = 1
    micro_batch_size: int = 1
    use_recompute: bool = False
    moe_experts: int = 0  # 0 = dense FFN

    @property
    def world(self):
        return self.dp * self.mp * self.pp * self.sharding * self.sep

    def as_dict(self):
        return dataclasses.asdict(self)

    def build_mesh(self):
        """The candidate AS a hybrid device mesh — the direct tie into
        GPTSpmdTrainer / shard_* mesh construction."""
        from ..models.gpt import build_mesh
        return build_mesh(n_devices=self.world, pipe=self.pp,
                          data=self.dp, fsdp=self.sharding,
                          sep=self.sep, model=self.mp)


@dataclasses.dataclass
class TunerConfig:
    n_devices: int = 8
    global_batch_size: int = 32
    max_mp: int = 8
    max_pp: int = 8
    hbm_bytes: float = 16e9  # v5e
    model_params: float = 1e9
    hidden_size: int = 2048
    seq_len: int = 2048
    layers: int = 24
    dtype_bytes: int = 2
    max_trials: int = 16
    num_heads: int = 16
    # schedule the trial trainer will run; MoE+pp candidates are only
    # emitted for the explicit-backward schedules (1f1b/vpp/zb)
    pipeline_schedule: str = "gpipe"
    # sequence-parallel degrees to sweep (Ulysses engages at sep>1);
    # only degrees compatible with heads/seq divisibility are emitted
    max_sep: int = 1
    # expert counts to sweep in addition to the dense FFN (0)
    moe_options: tuple = ()


def default_candidates(cfg: TunerConfig) -> List[Candidate]:
    out = []
    n = cfg.n_devices

    def powers(limit):
        p = 1
        while p <= limit:
            yield p
            p *= 2

    for mp in powers(min(cfg.max_mp, n)):
        for pp in powers(min(cfg.max_pp, n // mp)):
            rest = n // (mp * pp)
            for sep in powers(min(cfg.max_sep, rest)):
                if sep > 1 and (cfg.num_heads % (mp * sep)
                                or cfg.seq_len % sep
                                or pp > 1):
                    # Ulysses needs head/seq divisibility and no pipe
                    # (models/gpt.py flash/ulysses gating)
                    continue
                for sharding in powers(rest // sep):
                    dp = rest // (sep * sharding)
                    for mbs in (1, 2, 4, 8):
                        if cfg.global_batch_size % (dp * mbs):
                            continue
                        for rc in (False, True):
                            for moe in (0,) + tuple(cfg.moe_options):
                                if moe and moe % dp:
                                    # experts shard over 'data': each
                                    # data shard holds E/dp experts
                                    continue
                                if moe and pp > 1 and \
                                        cfg.pipeline_schedule == \
                                        "gpipe":
                                    # MoE composes with pipe only via
                                    # the explicit-backward schedules
                                    # (1f1b/vpp/zb); the autodiff'd
                                    # gpipe path rejects it
                                    continue
                                out.append(Candidate(
                                    dp, mp, pp, sharding, sep, mbs,
                                    rc, moe))
    return out


def prune_by_memory(cand: Candidate, cfg: TunerConfig) -> bool:
    """True = keep. Analytic per-chip memory (reference prune.py's memory
    model, re-derived for fp32 master + bf16 compute)."""
    if cand.world != cfg.n_devices:
        return False
    if cfg.layers % cand.pp:
        return False
    if cfg.hidden_size % cand.mp:
        return False
    shard_ways = cand.mp * cand.pp * cand.sharding
    params = cfg.model_params
    if cand.moe_experts:
        # ~2/3 of block params are FFN; each data shard holds E/dp
        # expert copies of that share
        ffn = params * 2 / 3
        params = (params - ffn) + ffn * cand.moe_experts / cand.dp
    # fp32 master + adam m/v (12B) sharded; bf16 working copy
    param_bytes = params * (12 / shard_ways + 2 / (cand.mp *
                                                   cand.pp))
    # activations shard over BOTH 'model' and 'sep' in the trainer
    # (specs ('data', 'sep', ...) — seq-sharded residual stream)
    act_per_layer = (cand.micro_batch_size * cfg.seq_len *
                     cfg.hidden_size * cfg.dtype_bytes *
                     (2 if cand.use_recompute else 14)
                     / (cand.mp * cand.sep))
    act_bytes = act_per_layer * cfg.layers / cand.pp
    return (param_bytes + act_bytes) < 0.9 * cfg.hbm_bytes


class AutoTuner:
    def __init__(self, cfg: TunerConfig,
                 trial_fn: Callable[[Candidate], float],
                 history_path: Optional[str] = None):
        self.cfg = cfg
        self.trial_fn = trial_fn
        self.history: List[Dict] = []
        self.history_path = history_path

    def tune(self) -> Optional[Candidate]:
        candidates = [c for c in default_candidates(self.cfg)
                      if prune_by_memory(c, self.cfg)]
        # prefer low-comm configs first (mp small, dp large)
        candidates.sort(key=lambda c: (c.mp * c.pp, -c.dp))
        best, best_score = None, -math.inf
        for cand in candidates[:self.cfg.max_trials]:
            t0 = time.time()
            try:
                score = self.trial_fn(cand)
                err = None
            except Exception as e:  # OOM / compile failure -> record, skip
                score, err = -math.inf, str(e)
            self.history.append({"candidate": cand.as_dict(),
                                 "score": score, "error": err,
                                 "elapsed_s": time.time() - t0})
            if score > best_score:
                best, best_score = cand, score
        if self.history_path:
            with open(self.history_path, "w") as f:
                json.dump(self.history, f, indent=2)
        return best


def tune_gpt(model_cfg, tuner_cfg: TunerConfig, steps: int = 3,
             trainer_kwargs: Optional[Dict] = None,
             history_path: Optional[str] = None):
    """End-to-end tuner over GPTSpmdTrainer (the reference's
    auto_tuner/tuner.py launches each candidate as a real training
    trial; here each trial is a jitted train_step on the candidate's
    mesh — same measurement, no process relaunch).

    Returns (best_candidate, history). Build the production trainer
    with ``GPTSpmdTrainer(model_cfg, best.build_mesh(), ...)``.
    """
    import numpy as np

    trainer_kwargs = dict(trainer_kwargs or {})

    def trial(cand: Candidate) -> float:
        from ..models.gpt import GPTSpmdTrainer
        import jax
        mesh = cand.build_mesh()
        m = max(2 * cand.pp, 1)
        trainer = GPTSpmdTrainer(
            model_cfg, mesh, microbatches=m,
            remat=cand.use_recompute,
            moe_experts=cand.moe_experts, **trainer_kwargs)
        # every candidate is measured at the SAME global batch the real
        # job will run (tokens/s comparable across candidates); configs
        # that cannot tile it raise and are recorded as failed trials
        batch = tuner_cfg.global_batch_size
        if batch % m:
            raise ValueError(
                f"global_batch_size {batch} not divisible by "
                f"{m} microbatches (pp={cand.pp})")
        seq = model_cfg.max_seq_len
        rng = np.random.RandomState(0)
        ids = rng.randint(0, model_cfg.vocab_size,
                          (batch, seq)).astype(np.int32)
        labels = np.roll(ids, -1, 1)
        # warmup/compile outside the timed region
        float(jax.device_get(trainer.train_step(ids, labels)))
        t0 = time.time()
        for _ in range(steps):
            loss = trainer.train_step(ids, labels)
        float(jax.device_get(loss))
        return batch * seq * steps / (time.time() - t0)

    tuner = AutoTuner(tuner_cfg, trial, history_path=history_path)
    best = tuner.tune()
    return best, tuner.history
