"""Table-driven SPMD pipeline engine: executes ANY validated job table
(1F1B / interleaved-VPP / ZeroBubble) ON DEVICE as one jitted program.

Reference behavior: the pipeline_scheduler_pass family reorders a static
program's microbatch jobs into per-rank instruction lists and executes
them over NCCL p2p (pipeline_vpp.py:42 interleaved, with the dygraph
runtime at fleet/meta_parallel/pipeline_parallel.py:1174;
pipeline_zero_bubble.py:62 ZB-H1). TPU-native design: the job table
(distributed.pipeline_schedules) is lowered to per-tick int32 arrays
that drive one ``lax.scan``; each tick every rank ``lax.switch``es into
its job (IDLE/F/B/B_INPUT/B_WEIGHT) and activations/cotangents hop the
ring via ``lax.ppermute`` as (payload, chunk, mb, valid) packets.
Per-(rank,chunk) packet inboxes and residual stores are ring buffers
whose depths are computed STATICALLY from the schedule timeline, so
memory stays at the schedule's true live-window size (the 1F1B/VPP
memory property) instead of O(M).

ZeroBubble's split backward maps to two vjps against the recomputed
stage forward: B_INPUT takes the cotangent w.r.t. the stage input (the
inter-stage critical path), pushing (saved_input, cotangent) onto a
FIFO; B_WEIGHT pops it and runs the params-only vjp in what was the
cooldown bubble. Activation-checkpointed style: each backward kind
recomputes the stage forward from the saved input, so ZB pays one extra
stage-forward per microbatch versus fused B — the schedule buys it back
by shortening the per-tick critical path and filling bubbles.

Interleaved VPP: stacked params carry a leading chunk dim [V, S, ...];
chunk ``c`` of rank ``r`` is global virtual stage ``c*S + r``. The ring
hop r=S-1 -> r=0 advances the chunk index, which is carried in the
packet tag.
"""
from __future__ import annotations

from typing import Any, Callable, Dict, List, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P
from jax import shard_map

from .pipeline_schedules import (PipelineSchedule, Job, F, B, BI, BW,
                                 IDLE)

__all__ = ["pipeline_train_scheduled", "schedule_arrays",
           "schedule_ring_sizes"]

_KIND = {IDLE: 0, F: 1, B: 2, BI: 3, BW: 4}


def schedule_arrays(sched: PipelineSchedule):
    """Lower a schedule's timeline to [S, T] int32 arrays
    (kind, mb, chunk)."""
    tl = sched.timeline()
    S = len(tl)
    T = len(tl[0])
    kind = np.zeros((S, T), np.int32)
    mb = np.zeros((S, T), np.int32)
    chunk = np.zeros((S, T), np.int32)
    for r, row in enumerate(tl):
        for t, j in enumerate(row):
            kind[r, t] = _KIND[j.kind]
            mb[r, t] = max(j.mb, 0)
            chunk[r, t] = j.chunk
    return kind, mb, chunk


def schedule_ring_sizes(sched: PipelineSchedule) -> Dict[str, int]:
    """Static ring-buffer depths implied by the timeline's live windows.

    resid:  stage inputs saved at F, freed at the LAST backward kind
            that recomputes from them (B, or B_WEIGHT when split).
    inbox_f: forward packets arrive one tick after the upstream F and
            wait until this rank's F consumes them.
    inbox_b: cotangent packets arrive one tick after the downstream
            B/B_INPUT and wait until this rank's backward.
    wqueue: (input, cotangent) pairs pushed at B_INPUT, popped at
            B_WEIGHT (FIFO per rank).
    """
    tl = sched.timeline()
    S = len(tl)
    V = sched.num_chunks
    T = len(tl[0])
    f_tick: Dict[Tuple[int, int], int] = {}
    b_tick: Dict[Tuple[int, int], int] = {}   # B or B_INPUT
    w_tick: Dict[Tuple[int, int], int] = {}
    for r, row in enumerate(tl):
        for t, j in enumerate(row):
            v = j.chunk * S + r
            if j.kind == F:
                f_tick[(j.mb, v)] = t
            elif j.kind in (B, BI):
                b_tick[(j.mb, v)] = t
            elif j.kind == BW:
                w_tick[(j.mb, v)] = t

    def max_live(windows: List[Tuple[int, int]]) -> int:
        events = []
        for a, b in windows:
            events.append((a, 1))
            events.append((b + 1, -1))
        live = peak = 0
        for _, d in sorted(events):
            live += d
            peak = max(peak, live)
        return max(peak, 1)

    resid_w, inf_w, inb_w, wq_w = [], [], [], []
    depth = S * V
    for v in range(depth):
        resid_w.append(max_live(
            [(f_tick[(m, v)], w_tick.get((m, v), b_tick[(m, v)]))
             for m in range(sched.M) if (m, v) in f_tick]))
        if v > 0:
            inf_w.append(max_live(
                [(f_tick[(m, v - 1)] + 1, f_tick[(m, v)])
                 for m in range(sched.M) if (m, v) in f_tick]))
        if v < depth - 1:
            inb_w.append(max_live(
                [(b_tick[(m, v + 1)] + 1, b_tick[(m, v)])
                 for m in range(sched.M) if (m, v) in b_tick]))
    for r, row in enumerate(tl):
        pend = peak = 0
        for j in row:
            if j.kind == BI:
                pend += 1
                peak = max(peak, pend)
            elif j.kind == BW:
                pend -= 1
        wq_w.append(max(peak, 1))
    return {"resid": max(resid_w), "inbox_f": max(inf_w or [1]),
            "inbox_b": max(inb_w or [1]), "wqueue": max(wq_w),
            "ticks": T}


def pipeline_train_scheduled(stage_fn: Callable, head_loss_fn: Callable,
                             stacked_params: Any, head_params: Any,
                             x_micro: jax.Array,
                             labels_micro: jax.Array,
                             mesh: Mesh, sched: PipelineSchedule,
                             axis: str = "pipe",
                             stage_aux_weight: float = 0.0,
                             stage_has_aux: bool = None):
    """Run a full train step (loss, param grads, head grads, input
    grads) for any job table from ``pipeline_schedules``.

    Args mirror ``pipeline_train_1f1b`` except:
      stacked_params: pytree with leaves [V, S, ...] — chunk-major
        virtual stages (V = sched.num_chunks; plain schedules use V=1).
    Returns (mean_loss, grads [V, S, ...], head_grads, dx_micro).
    """
    if stage_has_aux is None:
        stage_has_aux = bool(stage_aux_weight)
    sched.validate()
    S = mesh.shape[axis]
    if sched.S != S:
        raise ValueError(f"schedule built for {sched.S} stages, mesh "
                         f"axis {axis!r} has {S}")
    V = sched.num_chunks
    M = x_micro.shape[0]
    if sched.M != M:
        raise ValueError(f"schedule built for {sched.M} microbatches, "
                         f"got {M}")
    kind_tab, mb_tab, chunk_tab = schedule_arrays(sched)
    rings = schedule_ring_sizes(sched)
    T = rings["ticks"]
    R_RES, R_INF, R_INB, R_WQ = (rings["resid"], rings["inbox_f"],
                                 rings["inbox_b"], rings["wqueue"])
    mb_shape = x_micro.shape[1:]
    x_dtype = x_micro.dtype
    f32 = jnp.float32
    down = [(i, (i + 1) % S) for i in range(S)]
    up = [(i, (i - 1) % S) for i in range(S)]

    def per_rank(params, head_p, xs, labels, kind_row, mb_row,
                 chunk_row):
        # leaves arrive [V, 1, ...] (local stage shard) -> [V, ...]
        params = jax.tree.map(lambda a: a[:, 0], params)
        rank = jax.lax.axis_index(axis)

        def pick_chunk(c):
            return jax.tree.map(
                lambda a: jax.lax.dynamic_index_in_dim(
                    a, c, 0, keepdims=False), params)

        gacc0 = jax.tree.map(lambda a: jnp.zeros(a.shape, f32), params)
        zero_pkt = {"y": jnp.zeros(mb_shape, x_dtype),
                    "chunk": jnp.zeros((), jnp.int32),
                    "mb": jnp.zeros((), jnp.int32),
                    "valid": jnp.zeros((), jnp.bool_)}
        carry0 = {
            "fwd_pkt": zero_pkt,            # arrived last tick (down)
            "bwd_pkt": dict(zero_pkt),      # arrived last tick (up)
            "inbox_f": jnp.zeros((V, R_INF) + mb_shape, x_dtype),
            "inbox_b": jnp.zeros((V, R_INB) + mb_shape, x_dtype),
            "resid": jnp.zeros((V, R_RES) + mb_shape, x_dtype),
            "wq_x": jnp.zeros((R_WQ,) + mb_shape, x_dtype),
            "wq_g": jnp.zeros((R_WQ,) + mb_shape, x_dtype),
            "wq_chunk": jnp.zeros((R_WQ,), jnp.int32),
            "w_push": jnp.zeros((), jnp.int32),
            "w_pop": jnp.zeros((), jnp.int32),
            "gacc": gacc0,
            "ghead": jax.tree.map(lambda a: jnp.zeros(a.shape, f32),
                                  head_p),
            "loss": jnp.zeros((), f32),
            "dx_buf": jnp.zeros((M,) + mb_shape, x_dtype),
        }

        def store_pkt(buf, pkt, ring):
            slot = pkt["mb"] % ring
            cur = jax.lax.dynamic_slice(
                buf, (pkt["chunk"], slot) + (0,) * len(mb_shape),
                (1, 1) + mb_shape)
            new = jnp.where(pkt["valid"], pkt["y"][None, None], cur)
            return jax.lax.dynamic_update_slice(
                buf, new.astype(buf.dtype),
                (pkt["chunk"], slot) + (0,) * len(mb_shape))

        def load2(buf, c, slot):
            return jax.lax.dynamic_slice(
                buf, (c, slot) + (0,) * len(mb_shape),
                (1, 1) + mb_shape)[0, 0]

        def recompute(chunk_params, x_saved):
            if stage_has_aux:
                return stage_fn(chunk_params, x_saved)
            return stage_fn(chunk_params, x_saved), None

        def tick(carry, xs_t):
            kind_t, mb_t, chunk_t = xs_t
            c = dict(carry)
            # file arrivals from last tick's hops
            c["inbox_f"] = store_pkt(c["inbox_f"], c["fwd_pkt"], R_INF)
            c["inbox_b"] = store_pkt(c["inbox_b"], c["bwd_pkt"], R_INB)
            v_here = chunk_t * S + rank
            is_first = v_here == 0
            is_last = v_here == V * S - 1

            no_pkt = {"y": jnp.zeros(mb_shape, x_dtype),
                      "chunk": jnp.zeros((), jnp.int32),
                      "mb": mb_t, "valid": jnp.zeros((), jnp.bool_)}

            # ---- job branches: each returns (carry, fpkt, bpkt) ----
            def do_idle(c):
                return c, no_pkt, dict(no_pkt)

            def do_f(c):
                cp = pick_chunk(chunk_t)
                x_in = jnp.where(
                    is_first,
                    jax.lax.dynamic_index_in_dim(xs, mb_t, 0,
                                                 keepdims=False),
                    load2(c["inbox_f"], chunk_t, mb_t % R_INF))
                y, _ = recompute(cp, x_in)
                c = dict(c)
                c["resid"] = jax.lax.dynamic_update_slice(
                    c["resid"], x_in[None, None].astype(x_dtype),
                    (chunk_t, mb_t % R_RES) + (0,) * len(mb_shape))
                # receiver's chunk: +1 when the hop wraps S-1 -> 0
                fpkt = {"y": y.astype(x_dtype),
                        "chunk": jnp.where(rank == S - 1, chunk_t + 1,
                                           chunk_t),
                        "mb": mb_t,
                        "valid": jnp.logical_not(is_last)}
                return c, fpkt, dict(no_pkt)

            def seed_cotangent(c, y2):
                """Loss-head seed on the last virtual stage; inbox
                cotangent elsewhere. Returns (loss_j, g_out, dhp)."""
                lab = jax.lax.dynamic_index_in_dim(labels, mb_t, 0,
                                                   keepdims=False)

                def from_head(_):
                    loss_j, head_vjp = jax.vjp(
                        lambda hp, yy: head_loss_fn(hp, yy, lab),
                        head_p, y2)
                    dhp, dy = head_vjp(jnp.full((), 1.0 / M, f32))
                    return loss_j, dy.astype(x_dtype), dhp

                def from_inbox(_):
                    return (jnp.zeros((), f32),
                            load2(c["inbox_b"], chunk_t, mb_t % R_INB),
                            jax.tree.map(
                                lambda a: jnp.zeros(a.shape, f32),
                                head_p))

                return jax.lax.cond(is_last, from_head, from_inbox,
                                    operand=None)

            def bwd_common(c):
                """Recompute + full vjp; B uses both cotangents,
                B_INPUT discards dparams (W deferred to the queue)."""
                cp = pick_chunk(chunk_t)
                x_saved = load2(c["resid"], chunk_t, mb_t % R_RES)
                if stage_has_aux:
                    (y2, aux2), vjp_fn = jax.vjp(
                        lambda p, x: stage_fn(p, x), cp, x_saved)
                else:
                    y2, vjp_fn = jax.vjp(stage_fn, cp, x_saved)
                    aux2 = None
                loss_j, g_out, dhp = seed_cotangent(c, y2)
                if stage_has_aux:
                    seed = (g_out.astype(y2.dtype),
                            jnp.full((), stage_aux_weight / M, f32))
                else:
                    seed = g_out.astype(y2.dtype)
                dparams, dx = vjp_fn(seed)
                return (loss_j, g_out, dhp, dparams, dx, aux2, x_saved)

            def accum(c, chunk_idx, dparams, dhp, loss_j, aux2):
                c = dict(c)
                if dparams is not None:
                    c["gacc"] = jax.tree.map(
                        lambda g, d: jax.lax.dynamic_update_index_in_dim(
                            g,
                            jax.lax.dynamic_index_in_dim(
                                g, chunk_idx, 0, keepdims=False)
                            + d.astype(f32),
                            chunk_idx, 0),
                        c["gacc"], dparams)
                c["ghead"] = jax.tree.map(
                    lambda g, d: g + d.astype(f32), c["ghead"], dhp)
                loss_j = loss_j + (0.0 if aux2 is None
                                   else aux2 * stage_aux_weight)
                c["loss"] = c["loss"] + loss_j
                return c

            def emit_dx(c, dx):
                dxc = dx.astype(x_dtype)
                c = dict(c)
                c["dx_buf"] = jax.lax.cond(
                    is_first,
                    lambda b: jax.lax.dynamic_update_index_in_dim(
                        b, dxc, mb_t, 0),
                    lambda b: b, c["dx_buf"])
                bpkt = {"y": dxc,
                        "chunk": jnp.where(rank == 0, chunk_t - 1,
                                           chunk_t),
                        "mb": mb_t,
                        "valid": jnp.logical_not(is_first)}
                return c, bpkt

            def do_b(c):
                (loss_j, _g, dhp, dparams, dx, aux2, _x) = bwd_common(c)
                c = accum(c, chunk_t, dparams, dhp, loss_j, aux2)
                c, bpkt = emit_dx(c, dx)
                return c, dict(no_pkt), bpkt

            def do_bi(c):
                (loss_j, g_out, dhp, _dp, dx, aux2, x_saved) = \
                    bwd_common(c)
                c = accum(c, chunk_t, None, dhp, loss_j, aux2)
                # push (input, cotangent) for the deferred W job
                slot = c["w_push"] % R_WQ
                c["wq_x"] = jax.lax.dynamic_update_index_in_dim(
                    c["wq_x"], x_saved, slot, 0)
                c["wq_g"] = jax.lax.dynamic_update_index_in_dim(
                    c["wq_g"], g_out.astype(x_dtype), slot, 0)
                c["wq_chunk"] = jax.lax.dynamic_update_index_in_dim(
                    c["wq_chunk"], chunk_t, slot, 0)
                c["w_push"] = c["w_push"] + 1
                c, bpkt = emit_dx(c, dx)
                return c, dict(no_pkt), bpkt

            def do_bw(c):
                c = dict(c)
                slot = c["w_pop"] % R_WQ
                x_saved = jax.lax.dynamic_index_in_dim(
                    c["wq_x"], slot, 0, keepdims=False)
                g_out = jax.lax.dynamic_index_in_dim(
                    c["wq_g"], slot, 0, keepdims=False)
                wchunk = jax.lax.dynamic_index_in_dim(
                    c["wq_chunk"], slot, 0, keepdims=False)
                c["w_pop"] = c["w_pop"] + 1
                cp = pick_chunk(wchunk)
                if stage_has_aux:
                    (y2, aux2), vjp_fn = jax.vjp(
                        lambda p: stage_fn(p, x_saved), cp)
                    seed = (g_out.astype(y2.dtype),
                            jnp.full((), stage_aux_weight / M, f32))
                else:
                    y2, vjp_fn = jax.vjp(
                        lambda p: stage_fn(p, x_saved), cp)
                    seed = g_out.astype(y2.dtype)
                (dparams,) = vjp_fn(seed)
                c["gacc"] = jax.tree.map(
                    lambda g, d: jax.lax.dynamic_update_index_in_dim(
                        g,
                        jax.lax.dynamic_index_in_dim(
                            g, wchunk, 0, keepdims=False)
                        + d.astype(f32),
                        wchunk, 0),
                    c["gacc"], dparams)
                return c, dict(no_pkt), dict(no_pkt)

            c, fpkt, bpkt = jax.lax.switch(
                kind_t, [do_idle, do_f, do_b, do_bi, do_bw], c)

            # ---- ring hops (every rank, every tick) ---------------
            c["fwd_pkt"] = jax.tree.map(
                lambda a: jax.lax.ppermute(a, axis, down), fpkt)
            c["bwd_pkt"] = jax.tree.map(
                lambda a: jax.lax.ppermute(a, axis, up), bpkt)
            return c, None

        xs_scan = (kind_row, mb_row, chunk_row)
        carry, _ = jax.lax.scan(tick, carry0, xs_scan)

        loss = jax.lax.psum(carry["loss"], axis) / M
        ghead = jax.tree.map(lambda g: jax.lax.psum(g, axis),
                             carry["ghead"])
        dx = jax.lax.psum(
            jnp.where(rank == 0, carry["dx_buf"],
                      jnp.zeros_like(carry["dx_buf"])), axis)
        gstacked = jax.tree.map(lambda g: g[:, None], carry["gacc"])
        return loss, gstacked, ghead, dx

    # per-rank job rows ride the shard_map as 'pipe'-sharded operands
    kind_rows = jnp.asarray(kind_tab)
    mb_rows = jnp.asarray(mb_tab)
    chunk_rows = jnp.asarray(chunk_tab)

    def per_rank_rows(params, head_p, xs, labels, kr, mr, cr):
        return per_rank(params, head_p, xs, labels, kr[0], mr[0], cr[0])

    in_specs = (
        jax.tree.map(lambda _: P(None, axis), stacked_params),
        jax.tree.map(lambda _: P(), head_params),
        P(*([None] * x_micro.ndim)),
        P(*([None] * labels_micro.ndim)),
        P(axis), P(axis), P(axis),
    )
    out_specs = (
        P(),
        jax.tree.map(lambda _: P(None, axis), stacked_params),
        jax.tree.map(lambda _: P(), head_params),
        P(*([None] * x_micro.ndim)),
    )
    fn = shard_map(per_rank_rows, mesh=mesh, in_specs=in_specs,
                   out_specs=out_specs, axis_names={axis},
                   check_vma=False)
    return fn(stacked_params, head_params, x_micro, labels_micro,
              kind_rows, mb_rows, chunk_rows)
