"""ProcessMesh: the device-mesh abstraction.

Reference: python/paddle/distributed/auto_parallel/process_mesh.py +
C++ ProcessMesh (/root/reference/paddle/phi/core/distributed/auto_parallel/
process_mesh.h:34). TPU-native: a thin façade over jax.sharding.Mesh —
mesh axes map onto the ICI torus, and every collective is an XLA op over a
named axis instead of an NCCL communicator per group
(SURVEY.md §5 "Distributed communication backend" TPU mapping).
"""
from __future__ import annotations

from typing import List, Optional, Sequence

import numpy as np

import jax
from jax.sharding import Mesh


class ProcessMesh:
    def __init__(self, mesh: Sequence, dim_names: Optional[List[str]] = None,
                 process_ids=None):
        arr = np.asarray(mesh)
        if dim_names is None:
            dim_names = [f"d{i}" for i in range(arr.ndim)]
        if len(dim_names) != arr.ndim:
            raise ValueError("dim_names length must equal mesh ndim")
        self._shape = list(arr.shape)
        self._dim_names = list(dim_names)
        self._process_ids = arr.reshape(-1).tolist()
        self._jax_mesh: Optional[Mesh] = None

    # -- paddle surface ----------------------------------------------------
    @property
    def shape(self) -> List[int]:
        return list(self._shape)

    @property
    def ndim(self) -> int:
        return len(self._shape)

    @property
    def dim_names(self) -> List[str]:
        return list(self._dim_names)

    @property
    def process_ids(self) -> List[int]:
        return list(self._process_ids)

    @property
    def size(self) -> int:
        return int(np.prod(self._shape))

    def get_dim_size(self, dim_name: str) -> int:
        return self._shape[self._dim_names.index(dim_name)]

    def get_rank_by_dim_and_process_id(self, dim_name, process_id):
        coord = np.argwhere(np.asarray(self._process_ids).reshape(
            self._shape) == process_id)
        if coord.size == 0:
            return -1
        return int(coord[0][self._dim_names.index(dim_name)])

    def __eq__(self, other):
        return (isinstance(other, ProcessMesh)
                and self._shape == other._shape
                and self._process_ids == other._process_ids
                and self._dim_names == other._dim_names)

    def __hash__(self):
        return hash((tuple(self._shape), tuple(self._process_ids),
                     tuple(self._dim_names)))

    def __repr__(self):
        return (f"ProcessMesh(shape={self._shape}, "
                f"dim_names={self._dim_names})")

    # -- jax bridge --------------------------------------------------------
    def jax_mesh(self) -> Mesh:
        """Materialize the jax Mesh over this process's visible devices."""
        if self._jax_mesh is None:
            devices = jax.devices()
            if self.size > len(devices):
                raise RuntimeError(
                    f"ProcessMesh needs {self.size} devices, only "
                    f"{len(devices)} visible")
            dev_arr = np.asarray(
                [devices[i] for i in self._process_ids]).reshape(self._shape)
            self._jax_mesh = Mesh(dev_arr, axis_names=tuple(self._dim_names))
        return self._jax_mesh


_global_mesh: Optional[ProcessMesh] = None


def set_mesh(mesh: ProcessMesh):
    global _global_mesh
    _global_mesh = mesh


def get_mesh() -> Optional[ProcessMesh]:
    return _global_mesh


def auto_mesh(dim_names: Sequence[str], shape: Optional[Sequence[int]] = None
              ) -> ProcessMesh:
    """Build a mesh over all visible devices. With no shape, the first axis
    absorbs all devices."""
    n = jax.device_count()
    if shape is None:
        shape = [n] + [1] * (len(dim_names) - 1)
    return ProcessMesh(np.arange(int(np.prod(shape))).reshape(shape),
                       list(dim_names))
