"""Collective hang/failure detection.

Reference: paddle/phi/core/distributed/comm_task_manager.h:37
(CommTaskManager — an async watchdog thread that times out NCCL
collectives, NCCLCommTask::IsTimeout nccl_comm_task.h:53) plus
store-based exception propagation between ranks.

TPU-native: XLA collectives cannot be interrupted mid-kernel, so the
watchdog works at the step boundary — each rank heartbeats into the
rendezvous TCPStore; a background thread flags peers whose heartbeat
goes stale and surfaces exceptions other ranks published, so a hung or
crashed worker is detected in O(timeout) instead of blocking the job
forever (the contract of the reference's watchdog + async error
handling).
"""
from __future__ import annotations

import threading
import time
import weakref
from typing import Callable, List, Optional

from ..resilience.faults import maybe_fail

__all__ = ["CommWatchdog", "monitored_barrier",
           "StoreUnreachableError"]

_HB_PREFIX = "__watchdog__/hb"
_ERR_PREFIX = "__watchdog__/err"

# "no value for this key" answers from the supported store flavors
# (TCPStore raises TimeoutError, dict-backed test stores KeyError);
# anything else from a store read means the store itself is failing
_KEY_MISSING = (TimeoutError, KeyError)


class StoreUnreachableError(ConnectionError):
    """A store READ failed (transport error) — not the same thing as a
    peer that merely hasn't heartbeat yet."""


class CommWatchdog:
    """Store-backed heartbeat watchdog (CommTaskManager analog)."""

    def __init__(self, store, rank: int, world_size: int,
                 timeout: float = 60.0, interval: float = 2.0,
                 on_failure: Optional[Callable] = None,
                 auto_beat: bool = False,
                 flight_recorder=None, registry=None):
        """``auto_beat``: heartbeat from the background thread (process
        liveness only — a rank hung inside a collective still beats).
        Default False: the training loop must call beat() at step
        boundaries, so a hang IS detected once timeout < hang duration;
        size timeout above the longest legitimate step.

        Observability: each sweep publishes per-peer heartbeat age to
        the ``ptpu_dist_heartbeat_age_seconds`` gauge; newly-detected
        failures bump ``ptpu_dist_watchdog_failures_total`` and dump
        the flight recorder (once) so the last N step records survive
        the peer's death."""
        self.store = store
        self.rank = rank
        self.world_size = world_size
        self.timeout = timeout
        self.interval = interval
        self.on_failure = on_failure
        self.auto_beat = auto_beat
        self._stop = threading.Event()
        self._failed: List[str] = []
        self._exceptions: List[str] = []
        self._start_time = time.time()
        self._thread: Optional[threading.Thread] = None
        from ..observability import default_recorder, default_registry
        # `is None`, not truthiness: an empty FlightRecorder is falsy
        self.flight_recorder = flight_recorder \
            if flight_recorder is not None else default_recorder()
        reg = registry if registry is not None else default_registry()
        self._registry = reg
        self._m_age = reg.gauge(
            "ptpu_dist_heartbeat_age_seconds",
            "seconds since each peer's last heartbeat",
            labels=("rank",))
        self._m_failures = reg.counter(
            "ptpu_dist_watchdog_failures_total",
            "peer failures detected (stale heartbeat or reported "
            "exception)")
        self._counted_failures: set = set()
        self._dumped = False

    # -- lifecycle ---------------------------------------------------------
    def start(self):
        if self._thread is not None:
            return self
        self.beat()
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=self.interval * 2)
            self._thread = None

    # -- heartbeat ---------------------------------------------------------
    def beat(self):
        """Publish liveness; call at step boundaries."""
        maybe_fail("watchdog.beat", rank=self.rank)
        self.store.set(f"{_HB_PREFIX}/{self.rank}",
                       repr(time.time()).encode())

    def peer_ages(self, on_unreachable: str = "raise") -> dict:
        """Seconds since each peer's last heartbeat. A peer that never
        heartbeat ages from THIS watchdog's start (startup grace: a
        late-initializing rank is not instantly stale).

        Grace applies ONLY to a missing key; a store read that fails at
        the transport level raises :class:`StoreUnreachableError` (set
        ``on_unreachable="grace"`` for the old swallow-everything
        behavior) — a dead store must not masquerade as N healthy
        just-started peers."""
        now = time.time()
        ages = {}
        for r in range(self.world_size):
            if r == self.rank:
                continue
            try:
                raw = self.store.get(f"{_HB_PREFIX}/{r}", timeout=1.0)
                ages[r] = now - float(raw.decode())
            except _KEY_MISSING:
                ages[r] = now - self._start_time
            except Exception as e:
                if on_unreachable == "raise":
                    raise StoreUnreachableError(
                        f"heartbeat read for rank {r} failed: "
                        f"{type(e).__name__}: {e}") from e
                ages[r] = now - self._start_time
        return ages

    # -- exception propagation (store-based, as the reference) -------------
    def report_exception(self, message: str):
        self.store.set(f"{_ERR_PREFIX}/{self.rank}",
                       message.encode())

    def peer_exceptions(self) -> dict:
        out = {}
        for r in range(self.world_size):
            if r == self.rank:
                continue
            try:
                out[r] = self.store.get(f"{_ERR_PREFIX}/{r}",
                                        timeout=0.05).decode()
            except Exception:
                pass
        return out

    @property
    def failures(self) -> List[str]:
        return list(self._failed)

    def check(self):
        """Raise if any peer died or reported an exception (call at step
        boundaries for fail-fast training loops)."""
        if self._failed:
            raise RuntimeError(
                "distributed watchdog: " + "; ".join(self._failed))

    # -- internals ---------------------------------------------------------
    def _sweep(self) -> bool:
        """One watchdog pass (the loop body, callable directly from
        tests): refresh peer exception/staleness state, publish
        heartbeat-age gauges, count new failures, and dump the flight
        recorder the first time anything fails. Returns True when
        failures exist."""
        for r, msg in self.peer_exceptions().items():
            note = f"rank {r} reported: {msg}"
            if note not in self._exceptions:
                self._exceptions.append(note)
        # staleness recomputed each sweep: a rank that recovers
        # (heartbeat resumes) drops off; exceptions stay sticky.
        # An unreachable STORE is its own failure mode (rendezvous
        # gone), not N peers in startup grace.
        store_notes = []
        try:
            ages = self.peer_ages()
        except StoreUnreachableError as e:
            ages = {}
            store_notes = [f"store unreachable: {e}"]
        for r, age in ages.items():
            try:
                self._m_age.labels(rank=r).set(age)
            except Exception:
                # telemetry must never kill the watchdog: past the
                # registry's label-cardinality guard (world_size >
                # max_label_sets) extra ranks just go unpublished
                pass
        stale_ranks = [(r, age) for r, age in ages.items()
                       if age > self.timeout]
        stale = [f"rank {r} heartbeat stale "
                 f"({age:.1f}s > {self.timeout}s)"
                 for r, age in stale_ranks]
        self._failed = self._exceptions + stale + store_notes
        if not store_notes:
            # outage episodes count individually: once the store is
            # reachable again, a FUTURE outage must bump the failures
            # counter anew (unlike sticky peer exceptions)
            self._counted_failures.discard(("store", "unreachable"))
        # dedup on STABLE keys (the stale note embeds a changing age,
        # so the note string itself would re-count every sweep)
        for key in ([("exc", n) for n in self._exceptions]
                    + [("stale", r) for r, _ in stale_ranks]
                    + [("store", "unreachable")
                       for _ in store_notes]):
            if key not in self._counted_failures:
                self._counted_failures.add(key)
                self._m_failures.inc()
        if self._failed and not self._dumped:
            self._dumped = True
            try:
                self.flight_recorder.record(
                    "watchdog.failure", rank=self.rank,
                    failures=list(self._failed))
                self.flight_recorder.dump(
                    reason=f"watchdog rank {self.rank}: "
                           + "; ".join(self._failed),
                    registry=self._registry)
            except Exception:
                pass       # telemetry must never kill the watchdog
        return bool(self._failed)

    def _loop(self):
        while not self._stop.wait(self.interval):
            if self.auto_beat:
                try:
                    self.beat()
                except Exception:
                    # a transient store write failure must not kill the
                    # watchdog thread; the NEXT interval beats again
                    # (peers see at most one widened heartbeat gap)
                    pass
            if self._sweep() and self.on_failure is not None:
                try:
                    self.on_failure(list(self._failed))
                finally:
                    self._stop.set()


# rounds key on the store OBJECT, not id(store): after a store is
# garbage-collected, CPython reuses its address, and an id-keyed dict
# would hand a brand-new store the dead one's round numbers (skewed
# barrier keys between ranks). WeakKeyDictionary also frees the
# bookkeeping with the store instead of leaking one entry per store.
_barrier_rounds: "weakref.WeakKeyDictionary" = \
    weakref.WeakKeyDictionary()
_barrier_rounds_fallback: dict = {}      # stores that refuse weakrefs


def _rounds_for(store) -> dict:
    try:
        d = _barrier_rounds.get(store)
        if d is None:
            d = {}
            _barrier_rounds[store] = d
        return d
    except TypeError:
        # non-weakref-able store (e.g. __slots__ without __weakref__):
        # best-effort id keying, the pre-fix behavior
        return _barrier_rounds_fallback.setdefault(id(store), {})


def monitored_barrier(store, rank: int, world_size: int,
                      timeout: float = 60.0, tag: str = "mb"):
    """Barrier that names the missing ranks on timeout (the reference's
    monitored barrier / flight-recorder behavior): every rank registers,
    rank 0 waits for all and publishes the release key. Each use of a
    tag is round-numbered per process, so reuse works as long as all
    ranks call the same barriers in order (collective contract)."""
    rounds = _rounds_for(store)
    rnd = rounds.get(tag, 0)
    rounds[tag] = rnd + 1
    key = f"__watchdog__/barrier/{tag}/{rnd}"
    store.set(f"{key}/arrived/{rank}", b"1")
    deadline = time.time() + timeout
    if rank == 0:
        missing = list(range(1, world_size))
        while missing and time.time() < deadline:
            missing = [r for r in missing
                       if not _has_key(store, f"{key}/arrived/{r}")]
            if missing:
                time.sleep(0.05)
        if missing:
            raise TimeoutError(
                f"monitored_barrier('{tag}'): ranks {missing} missing "
                f"after {timeout}s")
        store.set(f"{key}/release", b"1")
    else:
        store.wait(f"{key}/release", timeout=timeout)


def _has_key(store, key) -> bool:
    try:
        store.get(key, timeout=0.02)
        return True
    except Exception:
        return False
