"""Named pipeline schedules: FThenB, 1F1B, interleaved (VPP), ZeroBubble.

Reference: python/paddle/distributed/passes/pipeline_scheduler_pass/
(pipeline_fthenb.py:35, pipeline_1f1b.py:39, pipeline_vpp.py:42,
pipeline_zero_bubble.py:62) — each pass reorders a static program's
micro-batch jobs into a per-rank instruction list. TPU-native framing:
a schedule IS that deterministic job table. The table drives
(a) the eager PipelineParallel runtime (real reordering of forward/
backward micro-steps), and (b) analysis/tests (bubble accounting,
dependency validation). The SPMD scan+ppermute engine
(distributed.pipeline) realizes FThenB semantics inside one XLA program,
where reverse-mode AD supplies the backward pipeline.

Job kinds:
  F(mb, chunk) — forward of microbatch `mb` through this rank's `chunk`
  B(mb, chunk) — backward (input+weight grads; ZeroBubble splits it)
  B_INPUT / B_WEIGHT — ZeroBubble's split backward (zero_bubble W jobs
  are freely movable; scheduling them into the cooldown bubble is what
  removes it — pipeline_zero_bubble.py:62 ZB-H1)
"""
from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional

__all__ = ["Job", "PipelineSchedule", "FThenBSchedule", "OneFOneBSchedule",
           "InterleavedSchedule", "ZeroBubbleSchedule", "get_schedule"]

F = "F"
B = "B"
BI = "B_INPUT"
BW = "B_WEIGHT"
IDLE = "IDLE"


@dataclasses.dataclass(frozen=True)
class Job:
    kind: str                 # F, B, B_INPUT, B_WEIGHT, IDLE
    mb: int = -1              # microbatch index
    chunk: int = 0            # virtual-stage chunk on this rank (VPP)

    def __repr__(self):
        c = f"c{self.chunk}" if self.chunk else ""
        return f"{self.kind}{self.mb}{c}"


class PipelineSchedule:
    """Per-rank job tables for an S-stage, M-microbatch pipeline."""

    name = "base"
    num_chunks = 1

    def __init__(self, num_stages: int, num_micro: int):
        if num_micro < 1 or num_stages < 1:
            raise ValueError("need >=1 stage and >=1 microbatch")
        self.S = num_stages
        self.M = num_micro

    def jobs(self, rank: int) -> List[Job]:
        raise NotImplementedError

    # -- analysis ----------------------------------------------------------
    def timeline(self) -> List[List[Job]]:
        """jobs() per rank, padded to equal length with IDLE."""
        per_rank = [self.jobs(r) for r in range(self.S)]
        T = max(len(j) for j in per_rank)
        return [j + [Job(IDLE)] * (T - len(j)) for j in per_rank]

    def bubble_fraction(self) -> float:
        tl = self.timeline()
        total = sum(len(row) for row in tl)
        idle = sum(1 for row in tl for j in row if j.kind == IDLE)
        return idle / total if total else 0.0

    def validate(self):
        """Check cross-rank dataflow: F(mb) at virtual stage v needs
        F(mb) at v-1 scheduled strictly earlier; B at v needs B at v+1
        earlier plus this rank's own F(mb, v). One job per rank per tick
        (the job list position IS the tick)."""
        S, V = self.S, self.num_chunks
        tl = self.timeline()
        # tick of each (kind, mb, virtual_stage)
        tick: Dict = {}
        w_tick: Dict = {}
        for r, row in enumerate(tl):
            for t, j in enumerate(row):
                if j.kind == IDLE:
                    continue
                v = j.chunk * S + r
                if j.kind == BW:
                    w_tick[(j.mb, v)] = t
                    continue
                kind = F if j.kind == F else B  # BI counts as B
                key = (kind, j.mb, v)
                if key in tick:
                    raise AssertionError(f"duplicate job {key}")
                tick[key] = t
        for (mb, v), t in w_tick.items():
            bt = tick.get((B, mb, v))
            if bt is None or bt >= t:
                raise AssertionError(
                    f"{self.name}: W(mb={mb}) at stage {v} before its "
                    f"B_INPUT")
        depth = S * V
        for (kind, mb, v), t in tick.items():
            if kind == F and v > 0:
                prev = tick.get((F, mb, v - 1))
                if prev is None or prev >= t:
                    raise AssertionError(
                        f"{self.name}: F(mb={mb}) at stage {v} scheduled "
                        f"tick {t} but stage {v-1} at {prev}")
            if kind == B:
                if v < depth - 1:
                    nxt = tick.get((B, mb, v + 1))
                    if nxt is None or nxt >= t:
                        raise AssertionError(
                            f"{self.name}: B(mb={mb}) at stage {v} tick "
                            f"{t} but stage {v+1} at {nxt}")
                fwd = tick.get((F, mb, v))
                if fwd is None or fwd >= t:
                    raise AssertionError(
                        f"{self.name}: B(mb={mb}) stage {v} before its F")
        return True


class FThenBSchedule(PipelineSchedule):
    """All forwards, then all backwards (pipeline_fthenb.py:35; GPipe).
    Peak activation memory: M in-flight microbatches."""

    name = "FThenB"

    def jobs(self, rank: int) -> List[Job]:
        out = [Job(IDLE)] * rank                      # fill
        out += [Job(F, m) for m in range(self.M)]
        # wait for the last stage's forwards + backward wave to arrive
        out += [Job(IDLE)] * (2 * (self.S - 1 - rank))
        out += [Job(B, m) for m in range(self.M)]
        return out


class OneFOneBSchedule(PipelineSchedule):
    """1F1B (pipeline_1f1b.py:39): warmup forwards up to the in-flight
    cap min(S-rank, M), then alternate 1F/1B, then cooldown backwards.
    Peak activation memory: min(M, S-rank) microbatches — the reason it
    replaces FThenB. Built by tick simulation so every cross-rank
    dependency (activations down, cotangents up, one-tick transfer) holds
    by construction."""

    name = "1F1B"

    def _cap(self, rank: int) -> int:
        return min(self.S - rank, self.M)

    def _build(self) -> List[List[Job]]:
        if getattr(self, "_rows", None) is not None:
            return self._rows
        S, M = self.S, self.M
        f_done: Dict = {}  # (mb, rank) -> completion tick
        b_done: Dict = {}
        rows: List[List[Job]] = [[] for _ in range(S)]
        next_f = [0] * S
        next_b = [0] * S
        t = 0
        while any(next_b[r] < M for r in range(S)):
            if t > 6 * (M + S) + 8:
                raise RuntimeError("1F1B scheduler did not converge")
            new_jobs = []
            for r in range(S):
                job = None
                m = next_b[r]
                b_ready = (m < M and f_done.get((m, r), t) < t and
                           (r == S - 1 or b_done.get((m, r + 1), t) < t))
                in_flight = next_f[r] - next_b[r]
                mf = next_f[r]
                f_ready = (mf < M and in_flight < self._cap(r) and
                           (r == 0 or f_done.get((mf, r - 1), t) < t))
                if b_ready:
                    job = Job(B, m)
                    next_b[r] += 1
                elif f_ready:
                    job = Job(F, mf)
                    next_f[r] += 1
                new_jobs.append(job or Job(IDLE))
                rows[r].append(new_jobs[-1])
            for r, j in enumerate(new_jobs):
                if j.kind == F:
                    f_done[(j.mb, r)] = t
                elif j.kind == B:
                    b_done[(j.mb, r)] = t
            t += 1
        self._rows = rows
        return rows

    def jobs(self, rank: int) -> List[Job]:
        return self._build()[rank]

    def peak_live_microbatches(self, rank: int) -> int:
        live = peak = 0
        for j in self.jobs(rank):
            if j.kind == F:
                live += 1
                peak = max(peak, live)
            elif j.kind in (B, BI):
                live -= 1
        return peak


class InterleavedSchedule(PipelineSchedule):
    """Interleaved 1F1B / VPP (pipeline_vpp.py:42; Megatron interleaving):
    each rank hosts `num_chunks` virtual stages (chunk c of rank r is
    global stage c*S + r); microbatches are fed in groups of S so every
    rank starts useful work after only `rank` ticks — the fill bubble
    shrinks by ~1/num_chunks in time units since each tick is 1/V of a
    full stage."""

    name = "VPP"

    def __init__(self, num_stages: int, num_micro: int,
                 num_chunks: int = 2):
        super().__init__(num_stages, num_micro)
        if num_micro % num_stages:
            raise ValueError("interleaved schedule needs M % S == 0 "
                             "(Megatron constraint)")
        self.num_chunks = num_chunks

    def _forward_order(self) -> List[Job]:
        """Chunk-major in groups of S microbatches: mbs 0..S-1 through
        chunk 0, then 0..S-1 through chunk 1, ..., then next group."""
        order = []
        for g in range(0, self.M, self.S):
            for c in range(self.num_chunks):
                for m in range(g, min(g + self.S, self.M)):
                    order.append((m, c))
        return order

    def _build(self) -> List[List[Job]]:
        """Greedy simulation against cross-rank readiness, chunk-major
        feed policy (the reference pass emits a precomputed ordering;
        this derives a dependency-correct one from the same policy)."""
        if getattr(self, "_rows", None) is not None:
            return self._rows
        S, V, M = self.S, self.num_chunks, self.M
        depth = S * V
        f_order = {r: list(self._forward_order()) for r in range(S)}
        f_done: Dict = {}   # (mb, v) -> tick completed
        b_done: Dict = {}
        b_count = {r: 0 for r in range(S)}
        rows: List[List[Job]] = [[] for _ in range(S)]
        t = 0
        max_ticks = 4 * (depth + V * M) + 8
        while (any(f_order[r] for r in range(S)) or
               any(b_count[r] < V * M for r in range(S))):
            if t > max_ticks:
                raise RuntimeError(
                    "interleaved scheduler did not converge")
            new_jobs = []
            for r in range(S):
                job = None
                # prefer a ready backward (bounds live activations),
                # deepest chunk first
                for c in reversed(range(V)):
                    v = c * S + r
                    for m in range(M):
                        if (m, v) in b_done:
                            continue
                        if f_done.get((m, v), t) >= t:
                            continue
                        if v == depth - 1 or \
                                b_done.get((m, v + 1), t) < t:
                            job = Job(B, m, c)
                            break
                    if job:
                        break
                if job is None and f_order[r]:
                    m, c = f_order[r][0]
                    v = c * S + r
                    if v == 0 or f_done.get((m, v - 1), t) < t:
                        f_order[r].pop(0)
                        job = Job(F, m, c)
                new_jobs.append(job or Job(IDLE))
                rows[r].append(new_jobs[-1])
            # commit completions at end of tick (same-tick sends land
            # next tick, matching the ppermute/isend semantics)
            for r, j in enumerate(new_jobs):
                if j.kind == F:
                    f_done[(j.mb, j.chunk * S + r)] = t
                elif j.kind == B:
                    b_done[(j.mb, j.chunk * S + r)] = t
                    b_count[r] += 1
            t += 1
        self._rows = rows
        return rows

    def jobs(self, rank: int) -> List[Job]:
        return self._build()[rank]


class ZeroBubbleSchedule(OneFOneBSchedule):
    """ZB-H1 (pipeline_zero_bubble.py:62,:151): split each backward into
    B_INPUT (activation grads — on the critical path to the previous
    stage) and B_WEIGHT (weight grads — free to move). B_INPUT keeps the
    1F1B position; B_WEIGHT jobs drop into what was the cooldown bubble,
    so the tail bubble disappears."""

    name = "ZeroBubble"

    def jobs(self, rank: int) -> List[Job]:
        base = super().jobs(rank)
        out: List[Job] = []
        pending_w: List[Job] = []
        for j in base:
            if j.kind == B:
                out.append(Job(BI, j.mb, j.chunk))
                pending_w.append(Job(BW, j.mb, j.chunk))
            elif j.kind == IDLE and pending_w:
                out.append(pending_w.pop(0))  # fill bubbles with W work
            else:
                out.append(j)
        out.extend(pending_w)
        return out


_SCHEDULES = {
    "FThenB": FThenBSchedule,
    "F-then-B": FThenBSchedule,
    "1F1B": OneFOneBSchedule,
    "VPP": InterleavedSchedule,
    "ZBH1": ZeroBubbleSchedule,
    "ZeroBubble": ZeroBubbleSchedule,
}


def get_schedule(name: str, num_stages: int, num_micro: int,
                 num_chunks: int = 2) -> PipelineSchedule:
    """Factory matching the reference's strategy switch
    (pipeline_scheduler_pass/__init__.py apply_pass pipeline_mode)."""
    cls = _SCHEDULES.get(name)
    if cls is None:
        raise ValueError(f"unknown pipeline schedule {name!r}; "
                         f"choose from {sorted(set(_SCHEDULES))}")
    if cls is InterleavedSchedule:
        return cls(num_stages, num_micro, num_chunks)
    return cls(num_stages, num_micro)
