"""Device-resident sparse-embedding training (the GPU-PS analog).

Reference: paddle/fluid/framework/ps_gpu_trainer.cc +
fleet/ps_gpu_wrapper.cc — embedding rows are cached in accelerator
memory for the duration of a pass, the optimizer runs ON the
accelerator, and the parameter server is the capacity/persistence tier
(pull on miss, write back on eviction/flush) instead of a per-step
round-trip.

TPU-native version: the cache is a dense ``[slots, dim]`` device
Parameter — lookups are device gathers through the tape, so any eager
optimizer trains the resident rows at HBM speed. Keys touched since
the last ``release_pins()`` are PINNED: they can neither be evicted
nor have their slot reassigned, so a gradient still in flight can
never be scattered into a row that now belongs to a different key —
call ``release_pins()`` after ``optimizer.step()``. The host keeps the
key->slot map (LRU) plus each row's PULL-TIME baseline; eviction and
``flush()`` write rows back EXACTLY by pushing ``baseline - current``
into a server-side ``sgd, lr=1.0`` table (new = old - 1.0*(old - new)),
so no raw-assign RPC is needed and the C++ server (csrc/ps_table.cc)
stays unchanged. Only MISSING rows ever cross the host<->device
boundary; hot ids never leave HBM — the property ps_gpu_trainer exists
for.
"""
from __future__ import annotations

from collections import OrderedDict
from typing import Dict, List, Optional

import numpy as np

import jax.numpy as jnp

from ..framework.tensor import Parameter, Tensor, no_grad
from .ps import PsClient, SparseTable

__all__ = ["DeviceCachedEmbedding"]


class DeviceCachedEmbedding:
    """A trainable embedding whose working set lives on device and
    whose full key space lives on the parameter server."""

    def __init__(self, client: PsClient, dim: int, cache_slots: int,
                 init_scale: float = 0.05,
                 table_id: Optional[int] = None):
        # lr=1.0 sgd makes push(delta) an exact raw write-back
        self.table = SparseTable(client, dim, optimizer="sgd", lr=1.0,
                                 init_scale=init_scale,
                                 table_id=table_id)
        self.dim = int(dim)
        self.slots = int(cache_slots)
        self.weight = Parameter(
            jnp.zeros((self.slots, self.dim), jnp.float32),
            name=f"device_cached_emb_{self.table.table_id}")
        self._key_slot: "OrderedDict[int, int]" = OrderedDict()  # LRU
        self._free: List[int] = list(range(self.slots))
        self._baseline = np.zeros((self.slots, self.dim), np.float32)
        self._pinned: set = set()   # keys with gradients in flight
        self._slot_reset_hooks: List = []
        self.stats = {"pulls": 0, "hits": 0, "evictions": 0}

    # -- optimizer-state hygiene ------------------------------------------
    def register_slot_reset_hook(self, fn):
        """``fn(slot_indices: np.ndarray)`` runs whenever those cache
        slots are (re)assigned to NEW keys. Stateful optimizers (Adam
        moments, momentum velocity) index their accumulators by cache
        slot of the dense weight Parameter — without a reset, a slot
        reassigned after eviction would INHERIT the evicted key's
        moment state. Use :meth:`attach_optimizer` for the common
        case."""
        self._slot_reset_hooks.append(fn)
        return fn

    def attach_optimizer(self, opt):
        """Zero ``opt``'s accumulator rows for the cache weight whenever
        a slot changes owner, making any stateful eager optimizer
        correct under slot reuse. (Resident rows with zero gradient
        still receive the optimizer's dense update, matching the
        reference's non-lazy ``adam(lazy_mode=False)`` semantics;
        non-resident rows receive none.)"""
        name = self.weight.name

        def _reset(slots: np.ndarray):
            accs = getattr(opt, "_accumulators", {}).get(name)
            if not accs:
                return
            idx = jnp.asarray(slots)
            for sname, arr in accs.items():
                if getattr(arr, "shape", ())[:1] != (self.slots,):
                    continue
                if sname == "master_weight":
                    # masters mirror the weight, not a decayed moment:
                    # re-seed from the freshly pulled rows, never zero
                    accs[sname] = arr.at[idx].set(
                        self.weight._data[idx].astype(arr.dtype))
                else:
                    accs[sname] = arr.at[idx].set(0)

        return self.register_slot_reset_hook(_reset)

    # -- host-side cache management ---------------------------------------
    def _ensure_resident(self, keys: np.ndarray) -> Dict[int, int]:
        uniq = np.unique(keys)
        if len(uniq) > self.slots:
            raise ValueError(
                f"batch touches {len(uniq)} unique keys > "
                f"{self.slots} cache slots")
        missing = [int(k) for k in uniq if int(k) not in self._key_slot]
        self.stats["hits"] += len(uniq) - len(missing)
        for k in uniq:
            k = int(k)
            if k in self._key_slot:
                self._key_slot.move_to_end(k)   # refresh LRU
            self._pinned.add(k)
        if missing:
            slots = self._take_slots(len(missing))
            rows = self.table.pull(np.asarray(missing, np.int64))
            self.stats["pulls"] += len(missing)
            with no_grad():
                self.weight._data = self.weight._data.at[
                    np.asarray(slots)].set(jnp.asarray(rows))
            self._baseline[slots] = rows
            for k, s in zip(missing, slots):
                self._key_slot[k] = s
            for hook in self._slot_reset_hooks:
                hook(np.asarray(slots, np.int64))
        return {int(k): self._key_slot[int(k)] for k in uniq}

    def _take_slots(self, n: int) -> List[int]:
        out = []
        while self._free and len(out) < n:
            out.append(self._free.pop())
        if len(out) < n:
            # evict the LRU tail — but never a PINNED key (its slot may
            # still receive a gradient from an earlier lookup)
            need = n - len(out)
            victims = [(k, s) for k, s in self._key_slot.items()
                       if k not in self._pinned][:need]
            if len(victims) < need:
                self._free.extend(out)   # undo: a refused lookup must
                out.clear()              # not leak the slots it took
                raise ValueError(
                    f"need {need} slots but only {len(victims)} "
                    f"unpinned evictable rows — call release_pins() "
                    f"after optimizer.step(), or grow cache_slots")
            self._writeback([s for _, s in victims],
                            [k for k, _ in victims])
            for k, s in victims:
                del self._key_slot[k]
                out.append(s)
            self.stats["evictions"] += need
        return out

    def _writeback(self, slots: List[int], keys: List[int]):
        if not slots:
            return
        cur = np.asarray(self.weight._data[np.asarray(slots)],
                         np.float32)
        delta = self._baseline[slots] - cur     # sgd lr=1.0 => assign
        self.table.push(np.asarray(keys, np.int64), delta)
        self._baseline[slots] = cur

    # -- public API --------------------------------------------------------
    def lookup(self, ids) -> Tensor:
        """Embedding rows for ``ids`` (any int array-like); gradients
        flow to the resident device table."""
        ids_np = np.asarray(getattr(ids, "_data", ids)).astype(np.int64)
        mapping = self._ensure_resident(ids_np.reshape(-1))
        if ids_np.size:
            uniq = np.asarray(sorted(mapping), np.int64)
            slots_for_uniq = np.fromiter(
                (mapping[int(k)] for k in uniq), np.int64,
                count=len(uniq))
            slot_ids = slots_for_uniq[np.searchsorted(uniq, ids_np)]
        else:
            slot_ids = ids_np
        return self.weight[Tensor(jnp.asarray(slot_ids))]

    def release_pins(self):
        """Declare in-flight gradients applied (call after
        ``optimizer.step()``): previously-looked-up rows become
        evictable again."""
        self._pinned.clear()

    def flush(self):
        """Write every resident row's trained value back to the PS
        (pass end / checkpoint)."""
        items = list(self._key_slot.items())
        self._writeback([s for _, s in items], [k for k, _ in items])

    def parameters(self):
        return [self.weight]
