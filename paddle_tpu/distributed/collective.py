"""Communication API (reference: python/paddle/distributed/communication/ —
all_reduce/all_gather/all_to_all/broadcast/reduce/reduce_scatter/scatter/
send/recv/barrier + Group, group.py:29).

TPU-native dual dispatch replacing the ProcessGroupNCCL object graph
(/root/reference/paddle/fluid/distributed/collective/process_group_nccl.h:37):

- under a ``shard_map`` trace (tensor is a jax Tracer and the group's mesh
  axis is live) the call lowers to the XLA collective (lax.psum /
  all_gather / all_to_all / ppermute) riding ICI;
- in eager single-controller mode a Group denotes a mesh axis, and the
  "collective" is a resharding of the global array (GSPMD view) — e.g.
  eager all_reduce of a Partial array = all-replica sum.

There are no streams, no ncclUniqueId bootstrap, no comm-task watchdog:
XLA orders collectives with compute, and jax.distributed (see env.py)
replaces the TCPStore rendezvous (store/tcp_store.h:121).
"""
from __future__ import annotations

from typing import List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..framework.tensor import Tensor, apply_op
from .process_mesh import ProcessMesh, get_mesh

__all__ = ["Group", "new_group", "get_group", "all_reduce", "all_gather",
           "P2POp", "batch_isend_irecv",
           "all_gather_object", "all_to_all", "all_to_all_single",
           "broadcast", "reduce", "reduce_scatter", "scatter", "send",
           "recv", "isend", "irecv", "barrier", "wait", "ReduceOp",
           "stream"]


class ReduceOp:
    SUM = "sum"
    MAX = "max"
    MIN = "min"
    PROD = "prod"
    AVG = "avg"


class Group:
    """A communicator = a named mesh axis (not an NCCL comm).

    ``axis_name`` selects which mesh dimension the collective spans; None
    means "all devices" (flattened mesh).
    """

    _counter = [0]

    def __init__(self, ranks: Optional[List[int]] = None,
                 axis_name: Optional[str] = None,
                 mesh: Optional[ProcessMesh] = None, gid: Optional[int] = None):
        self.ranks = ranks or []
        self.axis_name = axis_name
        self.mesh = mesh
        if gid is None:
            Group._counter[0] += 1
            gid = Group._counter[0]
        self.id = gid

    @property
    def nranks(self) -> int:
        if self.mesh is not None and self.axis_name is not None:
            return self.mesh.get_dim_size(self.axis_name)
        if self.ranks:
            return len(self.ranks)
        return jax.device_count()

    @property
    def world_size(self) -> int:
        return self.nranks

    def get_group_rank(self, rank):
        return self.ranks.index(rank) if self.ranks else rank

    @property
    def rank(self) -> int:
        from .env import get_rank
        return self.get_group_rank(get_rank()) if self.ranks else get_rank()

    def __repr__(self):
        return f"Group(id={self.id}, axis={self.axis_name}, " \
               f"nranks={self.nranks})"


_default_group: Optional[Group] = None
_groups = {}


def _get_default_group() -> Group:
    global _default_group
    if _default_group is None:
        _default_group = Group(ranks=list(range(jax.device_count())), gid=0)
        _groups[0] = _default_group
    return _default_group


def new_group(ranks: Optional[List[int]] = None, backend: Optional[str] = None,
              timeout=None, axis_name: Optional[str] = None) -> Group:
    g = Group(ranks=ranks, axis_name=axis_name, mesh=get_mesh())
    _groups[g.id] = g
    return g


def get_group(gid: int = 0) -> Group:
    if gid == 0:
        return _get_default_group()
    return _groups[gid]


def _axis(group: Optional[Group]):
    if group is not None and group.axis_name is not None:
        return group.axis_name
    return None


def _in_spmd_trace(x) -> bool:
    return isinstance(x._data if isinstance(x, Tensor) else x,
                      jax.core.Tracer)


def _reduce_fn(op):
    return {ReduceOp.SUM: jax.lax.psum, ReduceOp.MAX: jax.lax.pmax,
            ReduceOp.MIN: jax.lax.pmin,
            ReduceOp.AVG: jax.lax.pmean}[op]


def all_reduce(tensor: Tensor, op=ReduceOp.SUM, group: Optional[Group] = None,
               sync_op: bool = True):
    """In-place all-reduce (paddle contract: mutates ``tensor``)."""
    axis = _axis(group)
    if _in_spmd_trace(tensor) and axis is not None:
        fn = _reduce_fn(op)
        out = apply_op(lambda a: fn(a, axis), tensor._snapshot(),
                       _op_name="all_reduce")
        tensor._inplace(out)
        return tensor
    # eager single-controller: every "rank" already sees the global value
    return tensor


def all_gather(tensor_list: List[Tensor], tensor: Tensor,
               group: Optional[Group] = None, sync_op: bool = True):
    axis = _axis(group)
    if _in_spmd_trace(tensor) and axis is not None:
        n = (group.nranks if group else jax.device_count())
        out = apply_op(
            lambda a: jax.lax.all_gather(a, axis, axis=0, tiled=False),
            tensor, _op_name="all_gather")
        for i in range(n):
            tensor_list.append(out[i])
        return tensor_list
    n = group.nranks if group is not None else 1
    for _ in range(max(n, 1)):
        tensor_list.append(Tensor(tensor._data,
                                  stop_gradient=tensor.stop_gradient))
    return tensor_list


def all_gather_object(object_list: list, obj, group: Optional[Group] = None):
    n = group.nranks if group is not None else 1
    object_list.extend(obj for _ in range(max(n, 1)))
    return object_list


def all_to_all(out_tensor_list: List[Tensor], in_tensor_list: List[Tensor],
               group: Optional[Group] = None, sync_op: bool = True):
    axis = _axis(group)
    if in_tensor_list and _in_spmd_trace(in_tensor_list[0]) and axis:
        stacked = apply_op(lambda *xs: jnp.stack(xs), *in_tensor_list,
                           _op_name="a2a_stack")
        out = apply_op(
            lambda a: jax.lax.all_to_all(a, axis, split_axis=0,
                                         concat_axis=0, tiled=True),
            stacked, _op_name="all_to_all")
        n = len(in_tensor_list)
        for i in range(n):
            out_tensor_list.append(out[i])
        return out_tensor_list
    out_tensor_list.extend(in_tensor_list)
    return out_tensor_list


def all_to_all_single(out_tensor, in_tensor, out_split_sizes=None,
                      in_split_sizes=None, group: Optional[Group] = None,
                      sync_op: bool = True):
    axis = _axis(group)
    if _in_spmd_trace(in_tensor) and axis:
        out = apply_op(
            lambda a: jax.lax.all_to_all(a, axis, split_axis=0,
                                         concat_axis=0, tiled=True),
            in_tensor, _op_name="all_to_all_single")
        # out's node references in_tensor (a different handle), so the
        # rebind of out_tensor cannot self-cycle
        out_tensor._inplace(out)
        return out_tensor
    out_tensor.set_value(in_tensor._data)
    return out_tensor


def broadcast(tensor: Tensor, src: int = 0, group: Optional[Group] = None,
              sync_op: bool = True):
    return tensor  # single-controller: value already global


def reduce(tensor: Tensor, dst: int = 0, op=ReduceOp.SUM,
           group: Optional[Group] = None, sync_op: bool = True):
    return all_reduce(tensor, op, group, sync_op)


def reduce_scatter(tensor: Tensor, tensor_list: List[Tensor],
                   op=ReduceOp.SUM, group: Optional[Group] = None,
                   sync_op: bool = True):
    axis = _axis(group)
    if tensor_list and _in_spmd_trace(tensor_list[0]) and axis:
        stacked = apply_op(lambda *xs: jnp.stack(xs), *tensor_list,
                           _op_name="rs_stack")
        out = apply_op(
            lambda a: jax.lax.psum_scatter(a, axis, scatter_dimension=0,
                                           tiled=False),
            stacked, _op_name="reduce_scatter")
        tensor._inplace(out)
        return tensor
    tensor.set_value(tensor_list[0]._data if tensor_list else tensor._data)
    return tensor


def scatter(tensor: Tensor, tensor_list: Optional[List[Tensor]] = None,
            src: int = 0, group: Optional[Group] = None, sync_op: bool = True):
    if tensor_list:
        tensor.set_value(tensor_list[0]._data)
    return tensor


def send(tensor: Tensor, dst: int = 0, group: Optional[Group] = None,
         sync_op: bool = True):
    """Point-to-point send. Inside shard_map: ppermute to dst along the
    group axis (used by the pipeline runtime — see fleet.pipeline)."""
    axis = _axis(group)
    if _in_spmd_trace(tensor) and axis:
        n = group.nranks

        def f(a):
            perm = [(i, (i + (dst or 1)) % n) for i in range(n)]
            return jax.lax.ppermute(a, axis, perm)
        return apply_op(f, tensor, _op_name="send")
    return tensor


def recv(tensor: Tensor, src: int = 0, group: Optional[Group] = None,
         sync_op: bool = True):
    return tensor


def isend(tensor, dst=0, group=None):
    return _Work(send(tensor, dst, group))


def irecv(tensor, src=0, group=None):
    return _Work(recv(tensor, src, group))


class _Work:
    def __init__(self, result=None):
        self.result = result

    def wait(self, timeout=None):
        return True

    def is_completed(self):
        return True


class P2POp:
    """One batched p2p descriptor (communication/batch_isend_irecv.py
    P2POp): op is distributed.isend or distributed.irecv."""

    def __init__(self, op, tensor, peer, group=None):
        if op not in (isend, irecv):
            raise ValueError("P2POp op must be paddle.distributed.isend "
                             "or irecv")
        self.op = op
        self.tensor = tensor
        self.peer = peer
        self.group = group


def batch_isend_irecv(p2p_op_list):
    """Issue a batch of isend/irecv (pp_utils/p2p_communication.py:330
    batched NCCL group calls); returns the list of work handles. Under
    shard_map the sends are ppermutes XLA schedules together; eager
    single-process semantics match isend/irecv."""
    if not p2p_op_list:
        return []
    if not all(isinstance(p, P2POp) for p in p2p_op_list):
        raise TypeError("batch_isend_irecv expects a list of P2POp")
    return [p.op(p.tensor, p.peer, p.group) for p in p2p_op_list]


def barrier(group: Optional[Group] = None):
    """Device sync (the reference issues a 1-element allreduce)."""
    (jnp.zeros(()) + 0).block_until_ready()


def wait(tensor, group=None, use_calc_stream=True):
    if isinstance(tensor, Tensor) and not _in_spmd_trace(tensor):
        tensor._data.block_until_ready()


class _StreamNS:
    """paddle.distributed.communication.stream compat: the stream variants
    are the same ops (XLA has no user streams)."""
    all_reduce = staticmethod(all_reduce)
    all_gather = staticmethod(all_gather)
    all_to_all = staticmethod(all_to_all)
    all_to_all_single = staticmethod(all_to_all_single)
    broadcast = staticmethod(broadcast)
    reduce = staticmethod(reduce)
    reduce_scatter = staticmethod(reduce_scatter)
    scatter = staticmethod(scatter)
    send = staticmethod(send)
    recv = staticmethod(recv)


stream = _StreamNS()
