"""Long-tail distributed API parity (python/paddle/distributed/
__init__.py remainder): collective aliases/object collectives, PS-era
dataset classes, auto-parallel Strategy/DistAttr, TP split op."""
from __future__ import annotations

from typing import Any, List, Optional, Sequence

import numpy as np

from ..framework.tensor import Tensor
from .collective import all_to_all, all_to_all_single

__all__ = ["alltoall", "alltoall_single", "gather",
           "broadcast_object_list", "scatter_object_list",
           "destroy_process_group", "get_backend", "is_available",
           "gloo_init_parallel_env", "gloo_barrier", "gloo_release",
           "ParallelMode", "ReduceType", "DistAttr", "Strategy",
           "shard_dataloader", "shard_scaler", "split",
           "QueueDataset", "InMemoryDataset", "CountFilterEntry",
           "ProbabilityEntry", "ShowClickEntry"]

# collective aliases (communication/all_to_all.py exports both names)
alltoall = all_to_all
alltoall_single = all_to_all_single


def gather(tensor, gather_list=None, dst=0, group=None, sync_op=True):
    """Single-controller semantics: every rank's value is the global
    value, so gather materializes nranks copies at dst."""
    if gather_list is None:
        gather_list = []
    if group is None:
        from .collective import _get_default_group
        group = _get_default_group()
    n = group.nranks
    for _ in range(max(n, 1)):
        gather_list.append(Tensor(tensor._data,
                                  stop_gradient=tensor.stop_gradient))
    return gather_list


def broadcast_object_list(object_list, src=0, group=None):
    return object_list  # value already global in single-controller view


def scatter_object_list(out_object_list, in_object_list=None, src=0,
                        group=None):
    from .env import get_rank
    if in_object_list:
        out_object_list.append(
            in_object_list[get_rank() % len(in_object_list)])
    return out_object_list


def destroy_process_group(group=None):
    from . import collective
    if group is None:
        collective._groups.clear()
        collective._default_group = None
    else:
        collective._groups.pop(group.id, None)


def get_backend(group=None) -> str:
    return "xla"  # collectives are XLA HLO over ICI/DCN


def is_available() -> bool:
    return True


def gloo_init_parallel_env(rank_id, rank_num, server_endpoint):
    """CPU-barrier env (reference uses gloo): the TCPStore covers the
    same rendezvous contract."""
    from .store import TCPStore
    host, port = server_endpoint.rsplit(":", 1)
    global _gloo_store
    _gloo_store = TCPStore(host, int(port), is_master=(rank_id == 0),
                           world_size=rank_num)
    return _gloo_store


_gloo_store = None


def gloo_barrier():
    if _gloo_store is None:
        raise RuntimeError("call gloo_init_parallel_env first")
    _gloo_store.barrier()


def gloo_release():
    global _gloo_store
    if _gloo_store is not None:
        _gloo_store.close()
        _gloo_store = None


from .fleet.topology import ParallelMode  # noqa: E402,F401


class ReduceType:
    """auto_parallel reduce types (dist_attribute.h ReduceType)."""
    kRedSum = 0
    kRedMax = 1
    kRedMin = 2
    kRedProd = 3
    kRedAvg = 4
    kRedAny = 5
    kRedAll = 6


class DistAttr:
    """TensorDistAttr surface (phi/core/distributed/auto_parallel/
    dist_attr.h:81): process mesh + per-dim sharding names."""

    def __init__(self, mesh=None, sharding_specs=None):
        self.process_mesh = mesh
        self.sharding_specs = list(sharding_specs or [])
        self.dims_mapping = []
        if mesh is not None and sharding_specs is not None:
            names = list(mesh.dim_names)
            self.dims_mapping = [
                names.index(s) if s in names else -1
                for s in self.sharding_specs]

    def __repr__(self):
        return (f"DistAttr(mesh={self.process_mesh}, "
                f"specs={self.sharding_specs})")


class Strategy:
    """auto_parallel.Strategy (auto_parallel/strategy.py): nested config
    switches consumed by dist.to_static/Engine."""

    class _Cfg:
        def __init__(self, **kw):
            self.enable = False
            self.__dict__.update(kw)

    def __init__(self, config=None):
        self.amp = Strategy._Cfg(dtype="float16", level="o1")
        self.sharding = Strategy._Cfg(stage=1, degree=8)
        self.recompute = Strategy._Cfg()
        self.pipeline = Strategy._Cfg(schedule_mode="1F1B",
                                      micro_batch_size=1,
                                      accumulate_steps=1)
        self.gradient_merge = Strategy._Cfg(k_steps=1, avg=True)
        self.fused_passes = Strategy._Cfg(fused_passes_list=[])
        if config:
            for k, v in config.items():
                if hasattr(self, k) and isinstance(v, dict):
                    getattr(self, k).__dict__.update(v)


def shard_dataloader(dataloader, meshes, shard_dims=None,
                     input_keys=None, is_dataset_splitted=False):
    """Wrap a DataLoader so each batch lands data-sharded on the mesh
    (auto_parallel/api.py shard_dataloader): with a single global mesh
    the batch is device_put with the dp axis sharded."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec
    mesh = meshes[0] if isinstance(meshes, (list, tuple)) else meshes
    jm = mesh.jax_mesh() if hasattr(mesh, "jax_mesh") else mesh
    axis = jm.axis_names[0]

    class _Sharded:
        def __init__(self, dl):
            self._dl = dl

        def __iter__(self):
            for batch in self._dl:
                yield jax.tree.map(self._place, batch)

        def _place(self, x):
            if isinstance(x, Tensor) and x._data.ndim and \
                    x._data.shape[0] % jm.shape[axis] == 0:
                spec = [None] * x._data.ndim
                spec[0] = axis
                return Tensor(jax.device_put(
                    x._data, NamedSharding(jm, PartitionSpec(*spec))),
                    stop_gradient=x.stop_gradient)
            return x

        def __len__(self):
            return len(self._dl)
    return _Sharded(dataloader)


def shard_scaler(scaler):
    """GradScaler under sharding (auto_parallel/api.py shard_scaler):
    scale/unscale are elementwise and found/inf reduction is a global
    jnp.isfinite-all, which already sees the global array — identity."""
    return scaler


def split(x, size, operation="linear", axis=0, num_partitions=1,
          gather_out=True, weight_attr=None, bias_attr=None, name=None):
    """Megatron-style distributed fc/embedding op
    (python/paddle/distributed/collective.py split): axis=0 row-parallel,
    axis=1 column-parallel; backed by the fleet TP layer library."""
    from .fleet.mp_layers import (ColumnParallelLinear, RowParallelLinear,
                                  VocabParallelEmbedding)
    if operation == "embedding":
        layer = VocabParallelEmbedding(size[0], size[1])
        return layer(x)
    if axis == 1:
        layer = ColumnParallelLinear(size[0], size[1],
                                     gather_output=gather_out,
                                     bias_attr=bias_attr)
    else:
        layer = RowParallelLinear(size[0], size[1],
                                  input_is_parallel=False,
                                  bias_attr=bias_attr)
    return layer(x)


# ---------------------------------------------------------------------------
# PS-era dataset classes (fluid DataFeed/Dataset zoo; file-list driven)
# ---------------------------------------------------------------------------

class _EntryBase:
    def __init__(self, *a):
        self._args = a


class CountFilterEntry(_EntryBase):
    """Sparse-table admission rule: keep keys seen >= threshold
    (table/ctr_accessor.cc entry configs)."""

    def __init__(self, threshold: int):
        super().__init__(threshold)
        self.threshold = threshold


class ProbabilityEntry(_EntryBase):
    def __init__(self, probability: float):
        super().__init__(probability)
        self.probability = probability


class ShowClickEntry(_EntryBase):
    def __init__(self, show_name: str, click_name: str):
        super().__init__(show_name, click_name)
        self.show_name = show_name
        self.click_name = click_name


class QueueDataset:
    """Streaming file-list dataset (fluid data_feed.cc QueueDataset):
    iterates example lines from a file list through the native blocking
    queue when available."""

    def __init__(self):
        self._files: List[str] = []
        self._parse = None
        self.batch_size = 1

    def init(self, batch_size=1, use_var=None, pipe_command=None,
             thread_num=1, **kwargs):
        self.batch_size = batch_size

    def set_filelist(self, filelist: Sequence[str]):
        self._files = list(filelist)

    def set_parse_fn(self, fn):
        self._parse = fn

    def __iter__(self):
        batch = []
        for path in self._files:
            with open(path, encoding="utf-8", errors="ignore") as f:
                for line in f:
                    item = self._parse(line) if self._parse else line
                    batch.append(item)
                    if len(batch) == self.batch_size:
                        yield batch
                        batch = []
        if batch:
            yield batch


class InMemoryDataset(QueueDataset):
    """Loaded-then-shuffled variant (data_set.cc InMemoryDataset)."""

    def __init__(self):
        super().__init__()
        self._samples: List[Any] = []

    def load_into_memory(self):
        self._samples = []
        for path in self._files:
            with open(path, encoding="utf-8", errors="ignore") as f:
                for line in f:
                    self._samples.append(
                        self._parse(line) if self._parse else line)

    def local_shuffle(self):
        np.random.shuffle(self._samples)

    def global_shuffle(self, fleet=None, thread_num=12):
        self.local_shuffle()

    def release_memory(self):
        self._samples = []

    def get_memory_data_size(self, fleet=None):
        return len(self._samples)

    def __iter__(self):
        for i in range(0, len(self._samples), self.batch_size):
            yield self._samples[i:i + self.batch_size]
