"""Tensor-parallel (Megatron-style) layer library.

Reference: python/paddle/distributed/fleet/layers/mpu/mp_layers.py —
:49 VocabParallelEmbedding, :336 ColumnParallelLinear, :543
RowParallelLinear, :744 ParallelCrossEntropy, with identity/allreduce
PyLayers in mp_ops.py backed by collective CUDA ops.

TPU-native difference (deliberate): weights keep their GLOBAL logical shape
and carry a NamedSharding over the ``model`` mesh axis; forward annotates
activation shardings and GSPMD inserts the identity/allreduce/allgather
pattern the reference hand-writes (column: no comm fwd, allreduce bwd;
row: allreduce fwd). One code path serves 1..N-way TP, and the same layer
composes with dp/fsdp/sep axes for free.
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from ...framework.tensor import Tensor, apply_op
from ...nn import functional as F
from ...nn import initializer as I
from ...nn.layer_base import Layer
from ..api import reshard, shard_tensor
from ..placements import Partial, Replicate, Shard
from ..process_mesh import ProcessMesh, get_mesh

__all__ = ["VocabParallelEmbedding", "ColumnParallelLinear",
           "RowParallelLinear", "ParallelCrossEntropy"]


def _mesh_axis(mp_group=None, axis_name="model"):
    mesh = get_mesh()
    if mesh is None or axis_name not in mesh.dim_names:
        return None, None, 1
    return mesh, axis_name, mesh.get_dim_size(axis_name)


def _shard_param(p, mesh, axis_name, dim):
    placements = [Replicate() for _ in range(mesh.ndim)]
    placements[mesh.dim_names.index(axis_name)] = Shard(dim)
    return shard_tensor(p, mesh, placements)


def _replicated(t, mesh):
    return reshard(t, mesh, [Replicate() for _ in range(mesh.ndim)])


class ColumnParallelLinear(Layer):
    """W [in, out] sharded on out-columns over the model axis."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 has_bias=None, gather_output=True, fuse_matmul_bias=False,
                 mp_group=None, name=None):
        super().__init__()
        self._in_features = in_features
        self._out_features = out_features
        self.gather_output = gather_output
        self.mesh, self.axis, self.world_size = _mesh_axis(mp_group)
        if out_features % max(self.world_size, 1) != 0:
            raise ValueError(
                f"out_features {out_features} not divisible by mp degree "
                f"{self.world_size}")
        w = self.create_parameter([in_features, out_features], weight_attr,
                                  default_initializer=I.XavierNormal())
        if self.mesh is not None:
            w = _shard_param(w, self.mesh, self.axis, dim=1)
        self.weight = w
        self.weight.is_distributed = self.mesh is not None
        if has_bias is None or has_bias:
            b = self.create_parameter([out_features], is_bias=True)
            if self.mesh is not None:
                b = _shard_param(b, self.mesh, self.axis, dim=0)
            self.bias = b
        else:
            self.bias = None

    def forward(self, x):
        y = F.linear(x, self.weight, self.bias)
        if self.mesh is not None and self.gather_output:
            y = _replicated(y, self.mesh)
        elif self.mesh is not None:
            placements = [Replicate() for _ in range(self.mesh.ndim)]
            placements[self.mesh.dim_names.index(self.axis)] = \
                Shard(y.ndim - 1)
            y = reshard(y, self.mesh, placements)
        return y


class RowParallelLinear(Layer):
    """W [in, out] sharded on in-rows; forward ends with the GSPMD-inserted
    allreduce (reference: explicit mp_allreduce_sum)."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 has_bias=True, input_is_parallel=False, fuse_matmul_bias=False,
                 mp_group=None, name=None):
        super().__init__()
        self.input_is_parallel = input_is_parallel
        self.mesh, self.axis, self.world_size = _mesh_axis(mp_group)
        if in_features % max(self.world_size, 1) != 0:
            raise ValueError(
                f"in_features {in_features} not divisible by mp degree "
                f"{self.world_size}")
        w = self.create_parameter([in_features, out_features], weight_attr,
                                  default_initializer=I.XavierNormal())
        if self.mesh is not None:
            w = _shard_param(w, self.mesh, self.axis, dim=0)
        self.weight = w
        self.weight.is_distributed = self.mesh is not None
        if has_bias:
            # bias is replicated: applied after the reduction
            b = self.create_parameter([out_features], is_bias=True)
            if self.mesh is not None:
                b = shard_tensor(b, self.mesh,
                                 [Replicate()] * self.mesh.ndim)
            self.bias = b
        else:
            self.bias = None

    def forward(self, x):
        if self.mesh is not None and not self.input_is_parallel:
            placements = [Replicate() for _ in range(self.mesh.ndim)]
            placements[self.mesh.dim_names.index(self.axis)] = \
                Shard(x.ndim - 1)
            x = reshard(x, self.mesh, placements)
        y = F.linear(x, self.weight, None)
        if self.mesh is not None:
            y = _replicated(y, self.mesh)  # contracting-dim partial -> sum
        if self.bias is not None:
            y = y + self.bias
        return y


class VocabParallelEmbedding(Layer):
    """Embedding table sharded on the vocab dim over the model axis."""

    def __init__(self, num_embeddings, embedding_dim, weight_attr=None,
                 mp_group=None, name=None):
        super().__init__()
        self._num_embeddings = num_embeddings
        self._embedding_dim = embedding_dim
        self.mesh, self.axis, self.world_size = _mesh_axis(mp_group)
        if num_embeddings % max(self.world_size, 1) != 0:
            raise ValueError("vocab not divisible by mp degree")
        w = self.create_parameter([num_embeddings, embedding_dim],
                                  weight_attr,
                                  default_initializer=I.XavierNormal())
        if self.mesh is not None:
            w = _shard_param(w, self.mesh, self.axis, dim=0)
        self.weight = w
        self.weight.is_distributed = self.mesh is not None

    def forward(self, x):
        out = F.embedding(x, self.weight)
        if self.mesh is not None:
            out = _replicated(out, self.mesh)
        return out


class ParallelCrossEntropy(Layer):
    """Softmax CE over class-dim-sharded logits (reference:
    c_softmax_with_cross_entropy kernel + mp_layers.py:744). GSPMD emits
    the two-pass max/sum-exp reduction over the model axis."""

    def __init__(self, mp_group=None, name=None, ignore_index=-100):
        super().__init__()
        self.mesh, self.axis, self.world_size = _mesh_axis(mp_group)
        self.ignore_index = ignore_index

    def forward(self, input, label):
        loss = F.cross_entropy(input, label, reduction="none",
                               ignore_index=self.ignore_index)
        if self.mesh is not None:
            loss = _replicated(loss, self.mesh)
        return loss.unsqueeze(-1)
