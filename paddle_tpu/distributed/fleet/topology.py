"""Hybrid-parallel topology (reference: python/paddle/distributed/fleet/base/
topology.py:70 CommunicateTopology with axes [data, pipe, sharding, sep,
model], :189 HybridCommunicateGroup — mixed-radix rank decode + per-axis
groups).

TPU-native: the topology IS a jax Mesh; per-axis "groups" are axis names,
not NCCL communicators. Rank coordinates come from the same mixed-radix
decode for API parity.
"""
from __future__ import annotations

from enum import Enum
from typing import Dict, List, Optional

import numpy as np

from ..collective import Group, new_group
from ..process_mesh import ProcessMesh, set_mesh

__all__ = ["ParallelMode", "CommunicateTopology", "HybridCommunicateGroup"]


class ParallelMode(Enum):
    DATA_PARALLEL = 0
    TENSOR_PARALLEL = 1
    PIPELINE_PARALLEL = 2
    SHARDING_PARALLEL = 3
    SEGMENT_PARALLEL = 4


class CommunicateTopology:
    def __init__(self,
                 hybrid_group_names=("data", "pipe", "sharding", "sep",
                                     "model"),
                 dims=(1, 1, 1, 1, 1)):
        self._parallel_names = list(hybrid_group_names)
        self._dims = list(dims)
        self.coordinate = None
        self._world = int(np.prod(self._dims))

    def get_hybrid_group_names(self):
        return self._parallel_names

    def get_dim(self, axis_name):
        return self._dims[self._parallel_names.index(axis_name)]

    get_dim_size = get_dim

    def world_size(self):
        return self._world

    def get_rank(self, **kwargs) -> int:
        coord = [kwargs[n] for n in self._parallel_names]
        rank = 0
        for c, d in zip(coord, self._dims):
            rank = rank * d + c
        return rank

    def get_coord(self, rank: int):
        coords = []
        for d in reversed(self._dims):
            coords.append(rank % d)
            rank //= d
        return list(reversed(coords))

    def get_axis_list(self, axis_name: str, index: int) -> List[int]:
        ax = self._parallel_names.index(axis_name)
        return [r for r in range(self._world)
                if self.get_coord(r)[ax] == index]

    def get_comm_list(self, axis_name: str) -> List[List[int]]:
        ax = self._parallel_names.index(axis_name)
        groups: Dict[tuple, List[int]] = {}
        for r in range(self._world):
            coord = self.get_coord(r)
            key = tuple(c for i, c in enumerate(coord) if i != ax)
            groups.setdefault(key, []).append(r)
        return list(groups.values())


class HybridCommunicateGroup:
    """Per-axis rank bookkeeping + the jax Mesh for the whole job.

    Mesh axis order is (pipe, data, sharding, sep, model) — pipe outermost
    (stages should span slow links), model innermost (TP collectives are
    the most latency-sensitive and must ride adjacent-chip ICI). This is
    the layout decision the reference leaves to env flags; here it is the
    default because it is what the ICI torus wants.
    """

    def __init__(self, topology: CommunicateTopology, rank: int = 0):
        self._topo = topology
        self.global_rank = rank
        names = topology.get_hybrid_group_names()
        coord = topology.get_coord(rank)
        self._coord = dict(zip(names, coord))
        self._dp_degree = topology.get_dim("data")
        self._mp_degree = topology.get_dim("model")
        self._pp_degree = topology.get_dim("pipe")
        self._sharding_degree = topology.get_dim("sharding")
        self._sep_degree = topology.get_dim("sep") \
            if "sep" in names else 1
        # mesh axes named to match fleet user expectations
        shape = [self._pp_degree, self._dp_degree, self._sharding_degree,
                 self._sep_degree, self._mp_degree]
        self.mesh = ProcessMesh(
            np.arange(int(np.prod(shape))).reshape(shape),
            ["pipe", "data", "sharding", "sep", "model"])
        set_mesh(self.mesh)
        self._groups = {
            name: new_group(axis_name=axis)
            for name, axis in [("data", "data"), ("model", "model"),
                               ("pipe", "pipe"), ("sharding", "sharding"),
                               ("sep", "sep")]
        }

    # -- parallel mode -----------------------------------------------------
    def get_parallel_mode(self) -> ParallelMode:
        if self._mp_degree > 1:
            return ParallelMode.TENSOR_PARALLEL
        if self._pp_degree > 1:
            return ParallelMode.PIPELINE_PARALLEL
        if self._sharding_degree > 1:
            return ParallelMode.SHARDING_PARALLEL
        if self._sep_degree > 1:
            return ParallelMode.SEGMENT_PARALLEL
        return ParallelMode.DATA_PARALLEL

    def topology(self):
        return self._topo

    def get_global_rank(self):
        return self.global_rank

    # -- per-axis accessors (reference surface) ---------------------------
    def _axis_rank(self, name):
        return self._coord.get(name, 0)

    def get_data_parallel_rank(self):
        return self._axis_rank("data")

    def get_data_parallel_world_size(self):
        return self._dp_degree

    def get_data_parallel_group(self) -> Group:
        return self._groups["data"]

    def get_data_parallel_group_src_rank(self):
        return 0

    def get_model_parallel_rank(self):
        return self._axis_rank("model")

    def get_model_parallel_world_size(self):
        return self._mp_degree

    def get_model_parallel_group(self) -> Group:
        return self._groups["model"]

    def get_model_parallel_group_src_rank(self):
        return 0

    def get_stage_id(self):
        return self._axis_rank("pipe")

    def get_pipe_parallel_rank(self):
        return self._axis_rank("pipe")

    def get_pipe_parallel_world_size(self):
        return self._pp_degree

    def get_pipe_parallel_group(self) -> Group:
        return self._groups["pipe"]

    def get_p2p_groups(self):
        return None

    def get_sharding_parallel_rank(self):
        return self._axis_rank("sharding")

    def get_sharding_parallel_world_size(self):
        return self._sharding_degree

    def get_sharding_parallel_group(self) -> Group:
        return self._groups["sharding"]

    def get_sharding_parallel_group_src_rank(self):
        return 0

    def get_sep_parallel_rank(self):
        return self._axis_rank("sep")

    def get_sep_parallel_world_size(self):
        return self._sep_degree

    def get_sep_parallel_group(self) -> Group:
        return self._groups["sep"]

    def is_first_stage(self):
        return self.get_stage_id() == 0

    def is_last_stage(self):
        return self.get_stage_id() == self._pp_degree - 1

    def get_rank_from_stage(self, stage_id, **kwargs):
        coord = dict(self._coord)
        coord["pipe"] = stage_id
        return self._topo.get_rank(**coord)
