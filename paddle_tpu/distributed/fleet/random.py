"""TP-aware RNG state tracking (reference:
python/paddle/distributed/fleet/layers/mpu/random.py:34 RNGStatesTracker —
separate cuda RNG streams so dropout inside TP regions differs per rank
while replicated regions stay identical).

TPU-native: stateless PRNG — a tracker state is a (seed, offset) pair, and
"per-mp-rank" streams fold the mesh-axis index into the key, which is both
deterministic and correct under pjit (the same op in a sharded program
draws per-shard keys via fold_in)."""
from __future__ import annotations

import contextlib
from typing import Dict

import jax

from ...framework import random as rnd

__all__ = ["RNGStatesTracker", "get_rng_state_tracker",
           "model_parallel_random_seed", "determinate_seed"]

MODEL_PARALLEL_RNG = "model_parallel_rng"


class RNGStatesTracker:
    def __init__(self):
        self.states_: Dict[str, rnd.Generator] = {}
        self.seeds_ = set()

    def reset(self):
        self.states_ = {}
        self.seeds_ = set()

    def add(self, name: str, seed: int):
        if seed in self.seeds_:
            raise ValueError(f"seed {seed} already exists")
        if name in self.states_:
            raise ValueError(f"state {name} already exists")
        self.seeds_.add(seed)
        self.states_[name] = rnd.Generator(seed)

    def get_states_tracker(self):
        return {n: g.get_state() for n, g in self.states_.items()}

    def set_states_tracker(self, states):
        for n, s in states.items():
            self.states_.setdefault(n, rnd.Generator(0)).set_state(s)

    @contextlib.contextmanager
    def rng_state(self, name: str = MODEL_PARALLEL_RNG):
        if name not in self.states_:
            raise ValueError(f"state {name} does not exist")
        saved = rnd._default_generator
        rnd._default_generator = self.states_[name]
        try:
            yield
        finally:
            rnd._default_generator = saved


_tracker = RNGStatesTracker()


def get_rng_state_tracker() -> RNGStatesTracker:
    return _tracker


def model_parallel_random_seed(seed: int = 2048):
    """Seed local + model-parallel streams (reference: random.py
    model_parallel_random_seed — mp stream seed offset by mp rank; here the
    offset is a deterministic fold-in of the mesh model-axis size)."""
    from ..process_mesh import get_mesh
    mesh = get_mesh()
    mp_index = 0
    if mesh is not None and "model" in mesh.dim_names:
        mp_index = mesh.dim_names.index("model")
    _tracker.reset()
    rnd.seed(seed)
    _tracker.add(MODEL_PARALLEL_RNG, seed + 1024 + mp_index)


def determinate_seed(name: str) -> int:
    gen = _tracker.states_.get(name)
    return gen.initial_seed() if gen else rnd.default_generator().initial_seed()
