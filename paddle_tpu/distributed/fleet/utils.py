"""fleet.utils — recompute (activation checkpointing) and helpers.

Reference: python/paddle/distributed/fleet/utils/__init__.py recompute /
recompute_sequential (backed by PyLayer saving RNG state and re-running
forward in backward). TPU-native: ``jax.checkpoint`` on the
functionalized layer call — the recorded grad node's vjp recomputes the
forward, so only the inputs are saved as residuals (SURVEY.md §7:
rematerialisation trades FLOPs for HBM).
"""
from __future__ import annotations

from typing import Any

import jax

from ...framework.tensor import Tensor, apply_op
from ...nn.layer_base import Layer

__all__ = ["recompute", "recompute_sequential"]


def recompute(function, *args, **kwargs):
    """Run ``function(*args)`` saving only inputs; forward re-runs inside
    backward. ``function`` must be a Layer (its parameters are routed
    through the recompute boundary so their gradients flow); for a plain
    callable the call executes normally — correctness over memory, since
    gradients to parameters closed over by an opaque callable cannot pass
    a functional checkpoint boundary."""
    kwargs.pop("use_reentrant", None)
    kwargs.pop("preserve_rng_state", None)
    if not isinstance(function, Layer):
        return function(*args, **kwargs)
    for k, v in kwargs.items():
        if isinstance(v, Tensor) and not v.stop_gradient:
            raise ValueError(
                f"recompute: pass gradient-requiring tensor '{k}' "
                f"positionally — keyword tensors bypass the checkpoint "
                f"boundary and would silently get no gradient")

    layer = function
    params, buffers = layer.raw_state()
    pnames = list(params)
    bnames = list(buffers)
    n_p, n_b = len(pnames), len(bnames)
    from ...jit.functional import functional_call
    meta = {}

    def pure(*arrs):
        p = dict(zip(pnames, arrs[:n_p]))
        b = dict(zip(bnames, arrs[n_p:n_p + n_b]))
        out, new_b = functional_call(layer, p, b, *arrs[n_p + n_b:],
                                     **kwargs)
        outs = out if isinstance(out, tuple) else (out,)
        meta["n_out"] = len(outs)
        # buffer mutations (BN running stats) ride along as extra outputs
        return (*outs, *[new_b[n] for n in bnames])

    named = dict(layer.named_parameters())
    named_bufs = dict(layer.named_buffers())
    param_tensors = [named[n] for n in pnames]
    buffer_tensors = [named_bufs[n] for n in bnames]
    res = apply_op(jax.checkpoint(pure), *param_tensors,
                   *buffer_tensors, *args, _op_name="recompute")
    n_out = meta["n_out"]
    from ...framework.tensor import no_grad
    with no_grad():
        for bt, new in zip(buffer_tensors, res[n_out:]):
            bt._data = new._data
    outs = res[:n_out]
    return outs[0] if n_out == 1 else outs


def recompute_sequential(ctx: dict, functions, *args, **kwargs):
    """Reference recompute_sequential: checkpoint a Sequential in
    ``ctx['segments']`` chunks."""
    segments = int(ctx.get("segments", 1)) if ctx else 1
    sublayers = list(functions) if not isinstance(functions, Layer) \
        else list(functions.children())
    if not sublayers:
        return functions(*args, **kwargs)
    n = len(sublayers)
    bounds = [round(i * n / segments) for i in range(segments + 1)]
    from ...nn.layer.container import Sequential
    out = None
    first = True
    for lo, hi in zip(bounds[:-1], bounds[1:]):
        if lo == hi:
            continue
        seg = Sequential(*sublayers[lo:hi])
        if first:
            out = recompute(seg, *args, **kwargs)
            first = False
        else:
            out = recompute(seg, out, **kwargs)
    return out
