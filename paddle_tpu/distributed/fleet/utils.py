"""fleet.utils — recompute (activation checkpointing) and helpers.

Reference: python/paddle/distributed/fleet/utils/__init__.py recompute /
recompute_sequential (backed by PyLayer saving RNG state and re-running
forward in backward). TPU-native: ``jax.checkpoint`` on the
functionalized layer call — the recorded grad node's vjp recomputes the
forward, so only the inputs are saved as residuals (SURVEY.md §7:
rematerialisation trades FLOPs for HBM).
"""
from __future__ import annotations

from typing import Any

import jax

from ...framework.tensor import Tensor, apply_op
from ...nn.layer_base import Layer

__all__ = ["recompute", "recompute_sequential"]


def recompute(function, *args, **kwargs):
    """Run ``function(*args)`` saving only inputs; forward re-runs inside
    backward. ``function`` must be a Layer (its parameters are routed
    through the recompute boundary so their gradients flow); for a plain
    callable the call executes normally — correctness over memory, since
    gradients to parameters closed over by an opaque callable cannot pass
    a functional checkpoint boundary."""
    kwargs.pop("use_reentrant", None)
    kwargs.pop("preserve_rng_state", None)
    if not isinstance(function, Layer):
        return function(*args, **kwargs)

    layer = function
    params, buffers = layer.raw_state()
    pnames = list(params)
    bnames = list(buffers)
    n_p, n_b = len(pnames), len(bnames)
    from ...jit.functional import functional_call

    def pure(*arrs):
        p = dict(zip(pnames, arrs[:n_p]))
        b = dict(zip(bnames, arrs[n_p:n_p + n_b]))
        out, _ = functional_call(layer, p, b, *arrs[n_p + n_b:],
                                 **kwargs)
        return out

    named = dict(layer.named_parameters())
    param_tensors = [named[n] for n in pnames]
    buffer_tensors = [dict(layer.named_buffers())[n] for n in bnames]
    return apply_op(jax.checkpoint(pure), *param_tensors,
                    *buffer_tensors, *args, _op_name="recompute")


def recompute_sequential(ctx: dict, functions, *args, **kwargs):
    """Reference recompute_sequential: checkpoint a Sequential in
    ``ctx['segments']`` chunks."""
    segments = int(ctx.get("segments", 1)) if ctx else 1
    sublayers = list(functions) if not isinstance(functions, Layer) \
        else list(functions.children())
    if not sublayers:
        return functions(*args, **kwargs)
    n = len(sublayers)
    bounds = [round(i * n / segments) for i in range(segments + 1)]
    from ...nn.layer.container import Sequential
    out = args[0] if len(args) == 1 else args
    for lo, hi in zip(bounds[:-1], bounds[1:]):
        if lo == hi:
            continue
        seg = Sequential(*sublayers[lo:hi])
        out = recompute(seg, out, **kwargs)
    return out
