"""Fleet: hybrid-parallel trainer facade.

Reference: python/paddle/distributed/fleet/ — fleet.init (fleet.py:218),
DistributedStrategy (base/distributed_strategy.py, proto-backed),
distributed_model (model.py:32), distributed_optimizer
(fleet/optimizer.py -> HybridParallelOptimizer).
"""
from __future__ import annotations

from typing import Optional

from ...nn.layer_base import Layer
from ..env import get_rank, get_world_size, init_parallel_env
from .topology import (CommunicateTopology, HybridCommunicateGroup,
                       ParallelMode)
from . import mp_layers  # noqa: F401
from . import sequence_parallel  # noqa: F401
from .mp_layers import (ColumnParallelLinear, ParallelCrossEntropy,
                        RowParallelLinear, VocabParallelEmbedding)
from .random import get_rng_state_tracker, model_parallel_random_seed
from . import utils  # noqa: F401
from .utils import recompute  # noqa: F401

__all__ = ["init", "DistributedStrategy", "distributed_model",
           "distributed_optimizer", "get_hybrid_communicate_group",
           "HybridCommunicateGroup", "CommunicateTopology", "ParallelMode",
           "ColumnParallelLinear", "RowParallelLinear",
           "VocabParallelEmbedding", "ParallelCrossEntropy",
           "get_rng_state_tracker", "worker_num", "worker_index",
           "meta_parallel", "layers", "utils"]


class DistributedStrategy:
    """Switch container (reference: distributed_strategy.proto — amp,
    recompute, sharding, pipeline, hybrid degrees)."""

    def __init__(self):
        self.amp = False
        self.amp_configs = {}
        self.recompute = False
        self.recompute_configs = {}
        self.sharding = False
        self.sharding_configs = {}
        self.pipeline = False
        self.pipeline_configs = {"micro_batch_size": 1,
                                 "accumulate_steps": 1}
        self.hybrid_configs = {"dp_degree": 1, "mp_degree": 1,
                               "pp_degree": 1, "sharding_degree": 1,
                               "sep_degree": 1}
        self.gradient_merge = False
        self.gradient_merge_configs = {}
        self.find_unused_parameters = False
        self.tensor_parallel = False
        self.tensor_parallel_configs = {}

    def __repr__(self):
        return f"DistributedStrategy(hybrid={self.hybrid_configs})"


_hcg: Optional[HybridCommunicateGroup] = None
_strategy: Optional[DistributedStrategy] = None


def init(role_maker=None, is_collective: bool = True,
         strategy: Optional[DistributedStrategy] = None):
    """fleet.init analog: builds the hybrid topology mesh from strategy
    degrees over the visible devices."""
    global _hcg, _strategy
    init_parallel_env()
    strategy = strategy or DistributedStrategy()
    _strategy = strategy
    hc = strategy.hybrid_configs
    topo = CommunicateTopology(
        ["data", "pipe", "sharding", "sep", "model"],
        [hc.get("dp_degree", 1), hc.get("pp_degree", 1),
         hc.get("sharding_degree", 1), hc.get("sep_degree", 1),
         hc.get("mp_degree", 1)])
    _hcg = HybridCommunicateGroup(topo, rank=get_rank())
    return _hcg


def get_hybrid_communicate_group() -> Optional[HybridCommunicateGroup]:
    return _hcg


def fleet_strategy() -> Optional[DistributedStrategy]:
    return _strategy


def distributed_model(model: Layer):
    """Wrap per parallel mode (reference model.py:143-170 dispatch).
    TPU-native: TP/SP layers already carry shardings; DP wrap shards the
    batch; PP uses fleet.meta_parallel.PipelineLayer's own runtime."""
    from .meta_parallel import PipelineLayer, PipelineParallel
    from ..parallel import DataParallel
    if _hcg is None:
        return DataParallel(model)
    mode = _hcg.get_parallel_mode()
    if isinstance(model, PipelineLayer):
        return PipelineParallel(model, _hcg, _strategy)
    if mode == ParallelMode.DATA_PARALLEL and \
            _hcg.get_data_parallel_world_size() > 1:
        return DataParallel(model)
    return model


def distributed_optimizer(optimizer, strategy=None):
    """HybridParallelOptimizer analog: with a sharding axis active, shard
    optimizer state (stage1); grad clipping stays correct because global
    norms are computed on global-view arrays (the reference needs the
    cross-group partial-norm dance, hybrid_parallel_optimizer.py:103)."""
    from ..api import ShardingStage1, shard_optimizer
    if _hcg is not None and _hcg.get_sharding_parallel_world_size() > 1:
        return shard_optimizer(optimizer,
                               ShardingStage1("sharding", _hcg.mesh))
    return optimizer


def worker_num():
    return get_world_size()


def worker_index():
    return get_rank()


from . import meta_parallel  # noqa: E402,F401
from . import elastic  # noqa: E402,F401
from .meta_parallel import PipelineLayer, LayerDesc, SharedLayerDesc  # noqa: E402,F401


class _LayersNS:
    mpu = mp_layers


layers = _LayersNS()


class _UtilsNS:
    sequence_parallel_utils = sequence_parallel


utils = _UtilsNS()
