"""Sequence parallelism utilities.

Reference: python/paddle/distributed/fleet/utils/sequence_parallel_utils.py
— ScatterOp/GatherOp/AllGatherOp/ReduceScatterOp PyLayers (:85-137),
ColumnSequenceParallelLinear (:429), RowSequenceParallelLinear.

TPU-native: sequence sharding is an activation PartitionSpec — the seq dim
carries the model axis between TP regions; entering a TP matmul the
constraint flips to hidden-dim sharding and GSPMD emits exactly the
all-gather (fwd) / reduce-scatter (bwd) pair the reference hand-codes.
"""
from __future__ import annotations

from ...nn import functional as F
from ...nn import initializer as I
from ...nn.layer_base import Layer
from ..api import reshard
from ..placements import Replicate, Shard
from ..process_mesh import get_mesh
from .mp_layers import ColumnParallelLinear, RowParallelLinear, _mesh_axis

__all__ = ["ScatterOp", "GatherOp", "AllGatherOp", "ReduceScatterOp",
           "ColumnSequenceParallelLinear", "RowSequenceParallelLinear",
           "mark_as_sequence_parallel_parameter",
           "register_sequence_parallel_allreduce_hooks"]


def _seq_placements(mesh, axis, seq_dim):
    placements = [Replicate() for _ in range(mesh.ndim)]
    placements[mesh.dim_names.index(axis)] = Shard(seq_dim)
    return placements


class ScatterOp:
    """Split activations along seq dim across the model axis (fwd);
    backward = gather — expressed as one resharding."""

    @staticmethod
    def apply(x, axis=0):
        mesh, ax, world = _mesh_axis()
        if mesh is None:
            return x
        return reshard(x, mesh, _seq_placements(mesh, ax, axis))


class GatherOp:
    @staticmethod
    def apply(x, axis=0):
        mesh, ax, world = _mesh_axis()
        if mesh is None:
            return x
        return reshard(x, mesh, [Replicate()] * mesh.ndim)


AllGatherOp = GatherOp


class ReduceScatterOp:
    @staticmethod
    def apply(x, axis=0):
        mesh, ax, world = _mesh_axis()
        if mesh is None:
            return x
        return reshard(x, mesh, _seq_placements(mesh, ax, axis))


class ColumnSequenceParallelLinear(ColumnParallelLinear):
    """Input arrives seq-sharded; GSPMD all-gathers it into the column
    matmul (reference :429 does the explicit AllGatherOp)."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 has_bias=None, gather_output=False, fuse_matmul_bias=False,
                 mp_group=None, name=None):
        super().__init__(in_features, out_features, weight_attr, has_bias,
                         gather_output, fuse_matmul_bias, mp_group, name)

    def forward(self, x):
        if self.mesh is not None:
            x = reshard(x, self.mesh, [Replicate()] * self.mesh.ndim)
        return super().forward(x)


class RowSequenceParallelLinear(RowParallelLinear):
    """Output leaves seq-sharded (reference pairs the row matmul with
    ReduceScatterOp instead of allreduce)."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 has_bias=True, input_is_parallel=True, fuse_matmul_bias=False,
                 mp_group=None, name=None):
        super().__init__(in_features, out_features, weight_attr, has_bias,
                         input_is_parallel, fuse_matmul_bias, mp_group, name)

    def forward(self, x):
        y = super().forward(x)
        if self.mesh is not None:
            seq_dim = 0 if y.ndim == 2 else 1
            y = reshard(y, self.mesh,
                        _seq_placements(self.mesh, self.axis, seq_dim))
        return y


def mark_as_sequence_parallel_parameter(param):
    param.sequence_parallel = True


def register_sequence_parallel_allreduce_hooks(model, accumulation_steps=1,
                                               fuse_allreduce=False):
    """No-op TPU-natively: seq-parallel params (LayerNorm etc.) are
    replicated arrays; their grads are reduced by GSPMD because the loss is
    a global value (the reference needs explicit hooks —
    sequence_parallel_utils.py:192 — because each rank owns only a slice)."""
    return model
