"""Elastic training manager.

Reference: python/paddle/distributed/fleet/elastic/manager.py:125
ElasticManager — etcd-backed node registry, TTL heartbeats, fault-tolerance
levels, scale-up/down watch, relaunch via ELASTIC_EXIT_CODE=101.

TPU-native (SURVEY.md §5 failure-detection mapping): slice membership is
static per job, so "elastic" = detect peer failure (coordination-service
barrier timeout / heartbeat), save/restore a resharded checkpoint
(distributed.checkpoint works across changed meshes by construction), and
exit with the relaunch code for the launcher's watch loop.
"""
from __future__ import annotations

import os
import threading
import time
from enum import Enum
from typing import Callable, Optional

from ..checkpoint import load_state_dict, save_state_dict
from ..launch import ELASTIC_EXIT_CODE

__all__ = ["ElasticLevel", "ElasticStatus", "ElasticManager",
           "ELASTIC_EXIT_CODE"]


class ElasticLevel(Enum):
    NONE = 0
    FAULT_TOLERANCE = 1  # fixed size, restart on failure
    ELASTIC = 2          # size may change between restarts


class ElasticStatus(Enum):
    COMPLETED = 0
    ERROR = 1
    HOLD = 2
    RESTART = 3
    EXIT = 4


class ElasticManager:
    def __init__(self, checkpoint_dir: Optional[str] = None,
                 heartbeat_interval: float = 10.0,
                 heartbeat_timeout: float = 120.0,
                 elastic_level: ElasticLevel = ElasticLevel.FAULT_TOLERANCE,
                 on_failure: Optional[Callable] = None,
                 store=None):
        self.checkpoint_dir = checkpoint_dir or os.environ.get(
            "PADDLE_ELASTIC_CKPT_DIR", "/tmp/paddle_tpu_elastic")
        self.heartbeat_interval = heartbeat_interval
        self.heartbeat_timeout = heartbeat_timeout
        self.elastic_level = elastic_level
        self.on_failure = on_failure
        # etcd-registry analog (reference manager.py:125): a shared
        # TCPStore holds one `elastic/node/{rank}` counter per worker,
        # bumped by heartbeats. Liveness is judged by READER-side
        # change detection: a peer is alive while its counter keeps
        # changing within heartbeat_timeout on the reader's MONOTONIC
        # clock — no cross-host wall-clock comparison (unsynchronized
        # clocks must not shrink the TTL). Without a store, falls back
        # to the in-process table (single-process tests).
        self.store = store
        self._last_beats = {}
        self._seen = {}          # rank -> (last value, reader-mono time)
        self._register_mono = None
        self._rank = None
        self._world = None
        self._stop = threading.Event()
        self._watcher: Optional[threading.Thread] = None
        self._failed = False
        self._scale_up: list = []
        self._announcers: dict = {}  # rank -> (stop Event, thread)

    # -- membership (coordination-service analog of etcd registry) --------
    def register(self, rank: Optional[int] = None,
                 world: Optional[int] = None):
        if rank is None or world is None:
            import jax
            rank = jax.process_index() if rank is None else rank
            world = jax.process_count() if world is None else world
        self._rank = rank
        self._world = world
        self._register_mono = time.monotonic()
        if self.store is not None:
            self.store.add(f"elastic/node/{rank}", 1)
            # Registry keys are never deleted, so a key beyond the
            # current world may be a STALE leftover from a larger past
            # incarnation. Snapshot such keys pre-expired: only a
            # counter that MOVES after this point (a live joiner
            # heartbeating) can report as a scale-up — a frozen relic
            # cannot flap the job into a relaunch loop.
            expired = self._register_mono - self.heartbeat_timeout - 1.0
            for r in range(world, world + 8):
                try:
                    v = self.store.get(f"elastic/node/{r}", timeout=0.05)
                except Exception:
                    continue
                self._seen[r] = (v, expired)
        self._last_beats = {r: time.monotonic() for r in range(world)}
        return self

    def heartbeat(self, rank: Optional[int] = None):
        if rank is None:
            if self._rank is None:
                import jax
                self._rank = jax.process_index()
            rank = self._rank
        self._last_beats[rank] = time.monotonic()
        if self.store is not None:
            self.store.add(f"elastic/node/{rank}", 1)

    def _store_fresh(self, r, now):
        try:
            # non-blocking read: a missing key raises immediately
            v = self.store.get(f"elastic/node/{r}", timeout=0.05)
        except Exception:
            v = None
        if v is not None:
            prev = self._seen.get(r)
            if prev is None or prev[0] != v:
                self._seen[r] = (v, now)   # counter moved: alive now
                return True
            return now - prev[1] <= self.heartbeat_timeout
        # never-registered peers get the same grace a fresh heartbeat
        # would: a slow-starting rank is not a failure yet
        base = self._seen.get(r, (None, self._register_mono or now))[1]
        return now - base <= self.heartbeat_timeout

    def alive_nodes(self):
        """Ranks whose registry entry is fresh (TTL not expired)."""
        now = time.monotonic()
        if self.store is None:
            return [r for r, t in self._last_beats.items()
                    if now - t <= self.heartbeat_timeout]
        return [r for r in range(self._world)
                if self._store_fresh(r, now)]

    def dead_peers(self):
        if self.store is not None:
            alive = set(self.alive_nodes())
            return [r for r in range(self._world) if r not in alive]
        now = time.monotonic()
        return [r for r, t in self._last_beats.items()
                if now - t > self.heartbeat_timeout]

    # -- scale-up (reference manager.py watches BOTH directions) ----------
    def announce_join(self, rank: int, keepalive: bool = True):
        """Called by a NEW worker (rank >= current world) asking the
        job to grow; existing workers see it via ``joined_peers`` and
        exit for an upsized relaunch (reference: the etcd watch on the
        node prefix firing for added members, manager.py:125).

        ``joined_peers`` only reports a key whose counter is OBSERVED
        MOVING (stale-key immunity), so a single add would never be
        detected. By default this therefore starts a daemon keep-alive
        thread re-adding the key every ``heartbeat_timeout / 3`` s until
        ``stop_announce()`` (or process exit). Pass ``keepalive=False``
        to manage refreshing yourself — then you MUST keep calling
        ``announce_join`` at < heartbeat_timeout intervals."""
        if self.store is None:
            raise RuntimeError("announce_join requires a shared store")
        self.store.add(f"elastic/node/{rank}", 1)
        if keepalive and rank not in self._announcers:
            stop = threading.Event()

            def _refresh():
                # transient store errors (relaunch churn, timeouts)
                # must not kill the refresher: keep trying until
                # stop_announce() — a joiner whose counter goes quiet
                # silently vanishes from joined_peers()
                try:
                    while not stop.wait(self.heartbeat_timeout / 3.0):
                        try:
                            self.store.add(f"elastic/node/{rank}", 1)
                        except Exception:
                            continue
                finally:
                    # a dead thread must not block a re-announce — but
                    # only remove OUR entry: a successor registered
                    # after stop_announce() must stay stoppable
                    if self._announcers.get(rank, (None,))[0] is stop:
                        self._announcers.pop(rank, None)
            t = threading.Thread(target=_refresh, daemon=True,
                                 name=f"elastic-join-{rank}")
            t.start()
            self._announcers[rank] = (stop, t)

    def stop_announce(self, rank: Optional[int] = None):
        """Stop the keep-alive refresher(s) started by announce_join
        (call once the joiner has been folded into the new world)."""
        ranks = list(self._announcers) if rank is None else [rank]
        for r in ranks:
            ent = self._announcers.pop(r, None)
            if ent is not None:
                ent[0].set()

    def joined_peers(self, probe: int = 8):
        """Fresh registry entries BEYOND the current world size — i.e.
        new workers waiting to be folded in at the next relaunch.

        A key only counts once its counter is OBSERVED MOVING: a
        first-seen key is recorded and reported on a later poll when it
        has advanced. Registry keys are never deleted, so a frozen
        relic from a larger past incarnation (any rank, inside or
        outside register()'s snapshot window) can never flap the job
        into a relaunch loop; a real joiner heartbeats and is seen one
        poll later."""
        if self.store is None or self._world is None:
            return []
        now = time.monotonic()
        out = []
        for r in range(self._world, self._world + probe):
            try:
                v = self.store.get(f"elastic/node/{r}", timeout=0.05)
            except Exception:
                continue
            prev = self._seen.get(r)
            if prev is None:
                self._seen[r] = (v, now - self.heartbeat_timeout - 1.0)
            elif prev[0] != v:
                self._seen[r] = (v, now)
                out.append(r)
            elif now - prev[1] <= self.heartbeat_timeout:
                out.append(r)
        return out

    def watch(self, on_scale_up: Optional[Callable] = None):
        """Background failure watch (launcher controller.py poll analog).
        With ``elastic_level=ELASTIC`` (or an ``on_scale_up`` callback)
        the loop also fires when new peers announce themselves."""
        def loop():
            while not self._stop.is_set():
                dead = self.dead_peers()
                if dead:
                    self._failed = True
                    if self.on_failure is not None:
                        self.on_failure(dead)
                    break
                if on_scale_up is not None or \
                        self.elastic_level == ElasticLevel.ELASTIC:
                    joined = self.joined_peers()
                    if joined:
                        # always observable: the host polls .scale_up
                        # (or .failed) after the watcher ends
                        self._scale_up = joined
                        if on_scale_up is not None:
                            on_scale_up(joined)
                        break
                self._stop.wait(self.heartbeat_interval)

        self._watcher = threading.Thread(target=loop, daemon=True)
        self._watcher.start()
        return self

    def stop(self):
        self._stop.set()

    @property
    def failed(self) -> bool:
        return self._failed

    @property
    def scale_up(self) -> list:
        """New peer ranks the watch loop detected (empty if none).
        The watcher thread ends on either event — poll ``failed`` and
        ``scale_up`` to tell which fired."""
        return self._scale_up

    # -- checkpoint-restart protocol --------------------------------------
    def save(self, state_dict, step: int):
        save_state_dict(state_dict,
                        os.path.join(self.checkpoint_dir, f"step_{step}"),
                        async_save=True)
        with open(os.path.join(self.checkpoint_dir, "LATEST"), "w") as f:
            f.write(str(step))

    def latest_step(self) -> Optional[int]:
        p = os.path.join(self.checkpoint_dir, "LATEST")
        if not os.path.exists(p):
            return None
        with open(p) as f:
            return int(f.read().strip())

    def restore(self, state_dict) -> Optional[int]:
        step = self.latest_step()
        if step is None:
            return None
        load_state_dict(state_dict,
                        os.path.join(self.checkpoint_dir, f"step_{step}"))
        return step

    def request_relaunch(self):
        """Exit with the relaunch code; the launcher restarts us
        (reference manager.py:33 ELASTIC_EXIT_CODE protocol)."""
        os._exit(ELASTIC_EXIT_CODE)
