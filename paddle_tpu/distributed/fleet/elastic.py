"""Elastic training manager.

Reference: python/paddle/distributed/fleet/elastic/manager.py:125
ElasticManager — etcd-backed node registry, TTL heartbeats, fault-tolerance
levels, scale-up/down watch, relaunch via ELASTIC_EXIT_CODE=101.

TPU-native (SURVEY.md §5 failure-detection mapping): slice membership is
static per job, so "elastic" = detect peer failure (coordination-service
barrier timeout / heartbeat), save/restore a resharded checkpoint
(distributed.checkpoint works across changed meshes by construction), and
exit with the relaunch code for the launcher's watch loop.
"""
from __future__ import annotations

import os
import threading
import time
from enum import Enum
from typing import Callable, Optional

from ..checkpoint import load_state_dict, save_state_dict
from ..launch import ELASTIC_EXIT_CODE

__all__ = ["ElasticLevel", "ElasticStatus", "ElasticManager",
           "ELASTIC_EXIT_CODE"]


class ElasticLevel(Enum):
    NONE = 0
    FAULT_TOLERANCE = 1  # fixed size, restart on failure
    ELASTIC = 2          # size may change between restarts


class ElasticStatus(Enum):
    COMPLETED = 0
    ERROR = 1
    HOLD = 2
    RESTART = 3
    EXIT = 4


class ElasticManager:
    def __init__(self, checkpoint_dir: Optional[str] = None,
                 heartbeat_interval: float = 10.0,
                 heartbeat_timeout: float = 120.0,
                 elastic_level: ElasticLevel = ElasticLevel.FAULT_TOLERANCE,
                 on_failure: Optional[Callable] = None):
        self.checkpoint_dir = checkpoint_dir or os.environ.get(
            "PADDLE_ELASTIC_CKPT_DIR", "/tmp/paddle_tpu_elastic")
        self.heartbeat_interval = heartbeat_interval
        self.heartbeat_timeout = heartbeat_timeout
        self.elastic_level = elastic_level
        self.on_failure = on_failure
        self._last_beats = {}
        self._stop = threading.Event()
        self._watcher: Optional[threading.Thread] = None
        self._failed = False

    # -- membership (coordination-service analog of etcd registry) --------
    def register(self):
        import jax
        self._rank = jax.process_index()
        self._world = jax.process_count()
        self._last_beats = {r: time.monotonic()
                            for r in range(self._world)}
        return self

    def heartbeat(self, rank: Optional[int] = None):
        import jax
        r = rank if rank is not None else jax.process_index()
        self._last_beats[r] = time.monotonic()

    def dead_peers(self):
        now = time.monotonic()
        return [r for r, t in self._last_beats.items()
                if now - t > self.heartbeat_timeout]

    def watch(self):
        """Background failure watch (launcher controller.py poll analog)."""
        def loop():
            while not self._stop.is_set():
                dead = self.dead_peers()
                if dead:
                    self._failed = True
                    if self.on_failure is not None:
                        self.on_failure(dead)
                    break
                self._stop.wait(self.heartbeat_interval)

        self._watcher = threading.Thread(target=loop, daemon=True)
        self._watcher.start()
        return self

    def stop(self):
        self._stop.set()

    @property
    def failed(self) -> bool:
        return self._failed

    # -- checkpoint-restart protocol --------------------------------------
    def save(self, state_dict, step: int):
        save_state_dict(state_dict,
                        os.path.join(self.checkpoint_dir, f"step_{step}"),
                        async_save=True)
        with open(os.path.join(self.checkpoint_dir, "LATEST"), "w") as f:
            f.write(str(step))

    def latest_step(self) -> Optional[int]:
        p = os.path.join(self.checkpoint_dir, "LATEST")
        if not os.path.exists(p):
            return None
        with open(p) as f:
            return int(f.read().strip())

    def restore(self, state_dict) -> Optional[int]:
        step = self.latest_step()
        if step is None:
            return None
        load_state_dict(state_dict,
                        os.path.join(self.checkpoint_dir, f"step_{step}"))
        return step

    def request_relaunch(self):
        """Exit with the relaunch code; the launcher restarts us
        (reference manager.py:33 ELASTIC_EXIT_CODE protocol)."""
        os._exit(ELASTIC_EXIT_CODE)
