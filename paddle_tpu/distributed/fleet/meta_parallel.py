"""Pipeline-parallel model container + runtime.

Reference: fleet/meta_parallel/parallel_layers/pp_layers.py:257
PipelineLayer (+:56 LayerDesc, :92 SegmentLayers), runtime
fleet/meta_parallel/pipeline_parallel.py:255 (1F1B :575, interleave :1174),
P2P via NCCL send/recv (pp_utils/p2p_communication.py:576).

TPU-native: there is no NCCL p2p — the performant pipeline is a single
jitted program that scans microbatches over a 'pipe' mesh axis with
lax.ppermute moving activations between stage-ranks (see
distributed.pipeline.pipeline_step for the scan/shard_map engine used by
the GPT flagship). This module provides the user-facing container
(LayerDesc segmentation, shared embeddings) and an eager microbatch
runtime with gradient accumulation whose numerics match 1F1B (same
micro-loss mean), used when stages are heterogeneous.
"""
from __future__ import annotations

from typing import Callable, List, Optional

import numpy as np

from ...framework.tensor import Tensor
from ...nn.layer_base import Layer
from ...ops.manipulation import split as split_op

__all__ = ["LayerDesc", "SharedLayerDesc", "PipelineLayer",
           "PipelineParallel"]


class LayerDesc:
    def __init__(self, layer_cls, *inputs, **kwargs):
        self.layer_cls = layer_cls
        self.inputs = inputs
        self.kwargs = kwargs
        if not issubclass(layer_cls, Layer):
            raise TypeError("LayerDesc expects a Layer subclass")

    def build_layer(self) -> Layer:
        return self.layer_cls(*self.inputs, **self.kwargs)


class SharedLayerDesc(LayerDesc):
    """Weight-tied layer across stages (reference pp_layers.py
    SharedLayerDesc — embedding tying between first/last stage; here the
    shared module object is literally reused, and GSPMD keeps one global
    array, so no broadcast group is needed)."""

    def __init__(self, key, layer_cls, forward_func=None,
                 shared_weight_attr="weight", *inputs, **kwargs):
        super().__init__(layer_cls, *inputs, **kwargs)
        self.layer_name = key
        self.forward_func = forward_func
        self.shared_weight_attr = shared_weight_attr


class SegmentLayers:
    """Reference pp_layers.py:92 — split N layer descs into S stages by
    layer count ('uniform') or parameter-count cost."""

    def __init__(self, layers_desc, num_parts, method="uniform"):
        self.descs = layers_desc
        self.num_parts = num_parts
        self.method = method

    def do_segment(self) -> List[int]:
        n = len(self.descs)
        if self.num_parts <= 1:
            return [0, n]
        base = n // self.num_parts
        extra = n % self.num_parts
        bounds = [0]
        for i in range(self.num_parts):
            bounds.append(bounds[-1] + base + (1 if i < extra else 0))
        return bounds


class PipelineLayer(Layer):
    def __init__(self, layers, num_stages: Optional[int] = None,
                 topology=None, loss_fn: Optional[Callable] = None,
                 seg_method: str = "uniform", recompute_interval: int = 0,
                 **kwargs):
        super().__init__()
        self._loss_fn = loss_fn
        self.descs = list(layers)
        self.num_stages = num_stages or (
            topology.get_dim("pipe") if topology is not None else 1)
        self._shared = {}
        built = []
        for i, item in enumerate(self.descs):
            if isinstance(item, SharedLayerDesc):
                if item.layer_name in self._shared:
                    layer = self._shared[item.layer_name]
                else:
                    layer = item.build_layer()
                    self._shared[item.layer_name] = layer
                built.append((layer, item.forward_func))
            elif isinstance(item, LayerDesc):
                built.append((item.build_layer(), None))
            elif isinstance(item, Layer):
                built.append((item, None))
            elif callable(item):
                built.append((item, "func"))
            else:
                raise TypeError(f"invalid pipeline item: {item!r}")
        from ...nn.layer.container import LayerList
        self.run_function = built
        self._layers_list = LayerList(
            [l for l, tag in built if isinstance(l, Layer)])
        self.segment_bounds = SegmentLayers(
            self.descs, self.num_stages, seg_method).do_segment()

    def get_stage_from_index(self, idx: int) -> int:
        for s in range(self.num_stages):
            if self.segment_bounds[s] <= idx < self.segment_bounds[s + 1]:
                return s
        return self.num_stages - 1

    def forward(self, x):
        for layer, tag in self.run_function:
            if tag == "func":
                x = layer(x)
            elif tag is not None and callable(tag):
                x = tag(layer, x)
            else:
                x = layer(x)
        return x


class PipelineParallel(Layer):
    """Eager microbatch runtime (numerics of 1F1B: mean of micro losses,
    grads accumulated before one optimizer step)."""

    def __init__(self, layers: PipelineLayer, hcg, strategy=None):
        super().__init__()
        self._layers = layers
        self._hcg = hcg
        cfg = (strategy.pipeline_configs if strategy is not None else
               {"accumulate_steps": 1})
        self.accumulate_steps = cfg.get("accumulate_steps", 1)
        # F-vs-B interleave per the named schedule (reference
        # pipeline_scheduler_pass schedule_mode); 1F1B/ZeroBubble bound
        # live microbatch graphs, FThenB retains all M before backward.
        # Built once here: config errors surface at construction and the
        # tick simulation stays off the per-step hot path.
        from ..pipeline_schedules import get_schedule
        self.schedule_mode = cfg.get("schedule_mode", "1F1B")
        self._schedule = get_schedule(
            self.schedule_mode, max(layers.num_stages, 1),
            self.accumulate_steps)

    def forward(self, x):
        return self._layers(x)

    def train_batch(self, data, optimizer, lr_scheduler=None, scaler=None):
        inputs, labels = data
        m = self.accumulate_steps
        micro_x = split_op(inputs, m, axis=0) if m > 1 else [inputs]
        micro_y = split_op(labels, m, axis=0) if m > 1 else [labels]
        stages = max(self._layers.num_stages, 1)
        # drive F/B in the LAST stage's order (the rank that owns the
        # loss): FThenB -> all F then all B; 1F1B/ZB -> F0 B0 F1 B1 ...
        pending = {}
        total = 0.0
        for job in self._schedule.jobs(stages - 1):
            if job.kind == "F" and job.chunk == 0:
                out = self._layers(micro_x[job.mb])
                loss = self._layers._loss_fn(out, micro_y[job.mb])
                pending[job.mb] = loss
                total += float(loss)
            elif job.kind in ("B", "B_INPUT") and job.chunk == 0:
                micro_loss = pending.pop(job.mb) / m
                if scaler is not None:
                    micro_loss = scaler.scale(micro_loss)
                micro_loss.backward()
        if scaler is not None:
            scaler.step(optimizer)
            scaler.update()
        else:
            optimizer.step()
        optimizer.clear_grad()
        if lr_scheduler is not None:
            lr_scheduler.step()
        return Tensor(np.asarray(total / m, np.float32))

    def eval_batch(self, data, compute_loss=True):
        inputs, labels = data
        out = self._layers(inputs)
        if compute_loss:
            return self._layers._loss_fn(out, labels)
        return out

    def state_dict(self, *a, **k):
        return self._layers.state_dict(*a, **k)

    def set_state_dict(self, sd, *a, **k):
        return self._layers.set_state_dict(sd, *a, **k)
