"""Placement types: Shard / Replicate / Partial.

Reference: /root/reference/paddle/phi/core/distributed/auto_parallel/
placement_types.h + python surface dist.Shard/Replicate/Partial.
TPU-native: placements compile down to a jax PartitionSpec; Partial is
carried as metadata (GSPMD materializes partial sums itself — the
reference needs 13 explicit reshard functions, here reshard =
device_put / with_sharding_constraint with a new spec).
"""
from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

from jax.sharding import NamedSharding, PartitionSpec

from .process_mesh import ProcessMesh


class Placement:
    def is_shard(self, dim=None):
        return False

    def is_replicated(self):
        return False

    def is_partial(self):
        return False


class Shard(Placement):
    def __init__(self, dim: int):
        self.dim = int(dim)

    def is_shard(self, dim=None):
        return True if dim is None else dim == self.dim

    def get_dim(self):
        return self.dim

    def __repr__(self):
        return f"Shard(dim={self.dim})"

    def __eq__(self, o):
        return isinstance(o, Shard) and o.dim == self.dim

    def __hash__(self):
        return hash(("shard", self.dim))


class Replicate(Placement):
    def is_replicated(self):
        return True

    def __repr__(self):
        return "Replicate()"

    def __eq__(self, o):
        return isinstance(o, Replicate)

    def __hash__(self):
        return hash("replicate")


class Partial(Placement):
    def __init__(self, reduce_type: str = "sum"):
        self.reduce_type = reduce_type

    def is_partial(self):
        return True

    def __repr__(self):
        return f"Partial({self.reduce_type})"

    def __eq__(self, o):
        return isinstance(o, Partial) and o.reduce_type == self.reduce_type

    def __hash__(self):
        return hash(("partial", self.reduce_type))


def placements_to_spec(mesh: ProcessMesh,
                       placements: Sequence[Placement]) -> PartitionSpec:
    """placements (one per MESH dim, paddle convention) -> PartitionSpec
    (one entry per TENSOR dim, jax convention)."""
    if len(placements) != mesh.ndim:
        raise ValueError(
            f"expected {mesh.ndim} placements (one per mesh dim), got "
            f"{len(placements)}")
    dim_to_axes = {}
    for mesh_dim, p in enumerate(placements):
        if isinstance(p, Shard):
            dim_to_axes.setdefault(p.dim, []).append(
                mesh.dim_names[mesh_dim])
    if not dim_to_axes:
        return PartitionSpec()
    max_dim = max(dim_to_axes)
    entries = []
    for d in range(max_dim + 1):
        axes = dim_to_axes.get(d)
        if not axes:
            entries.append(None)
        elif len(axes) == 1:
            entries.append(axes[0])
        else:
            entries.append(tuple(axes))
    return PartitionSpec(*entries)


def spec_to_placements(mesh: ProcessMesh, spec: PartitionSpec,
                       ndim: int) -> List[Placement]:
    placements: List[Placement] = [Replicate() for _ in range(mesh.ndim)]
    for tensor_dim, entry in enumerate(tuple(spec)):
        if entry is None:
            continue
        axes = entry if isinstance(entry, tuple) else (entry,)
        for ax in axes:
            placements[mesh.dim_names.index(ax)] = Shard(tensor_dim)
    return placements


def named_sharding(mesh: ProcessMesh,
                   placements: Sequence[Placement]) -> NamedSharding:
    return NamedSharding(mesh.jax_mesh(), placements_to_spec(mesh,
                                                             placements))
