"""TCPStore: native TCP key-value rendezvous store.

Reference: /root/reference/paddle/phi/core/distributed/store/tcp_store.h:121
(MasterDaemon + TCPStore client with set/get/add/wait/barrier) — the KV
every Paddle job bootstraps through. Here the daemon is C++
(csrc/tcp_store.cc, ctypes C ABI), and it backs the launcher master,
``paddle_tpu.distributed.rpc`` rendezvous, and anything that needs a tiny
coordination KV next to jax.distributed's coordination service.
"""
from __future__ import annotations

import ctypes
import threading
from typing import Optional

from ..resilience.faults import maybe_fail

__all__ = ["TCPStore"]

_lock = threading.Lock()
_lib = None
_build_failed = False


def get_lib():
    global _lib, _build_failed
    if _lib is not None or _build_failed:
        return _lib
    with _lock:
        if _lib is not None or _build_failed:
            return _lib
        from ..utils.native_build import build_native_so
        so = build_native_so("tcp_store.cc", "libptstore.so", opt="-O2")
        if so is None:
            _build_failed = True
            return None
        lib = ctypes.CDLL(so)
        lib.pts_server_start.restype = ctypes.c_void_p
        lib.pts_server_start.argtypes = [ctypes.c_int]
        lib.pts_server_port.restype = ctypes.c_int
        lib.pts_server_port.argtypes = [ctypes.c_void_p]
        lib.pts_server_stop.argtypes = [ctypes.c_void_p]
        lib.pts_client_connect.restype = ctypes.c_void_p
        lib.pts_client_connect.argtypes = [ctypes.c_char_p, ctypes.c_int,
                                           ctypes.c_int]
        lib.pts_client_close.argtypes = [ctypes.c_void_p]
        lib.pts_set.restype = ctypes.c_int
        lib.pts_set.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                ctypes.c_char_p, ctypes.c_uint64]
        lib.pts_get.restype = ctypes.c_int
        lib.pts_get.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                ctypes.c_int64,
                                ctypes.POINTER(ctypes.POINTER(ctypes.c_uint8)),
                                ctypes.POINTER(ctypes.c_uint64)]
        lib.pts_add.restype = ctypes.c_int
        lib.pts_add.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                ctypes.c_int64,
                                ctypes.POINTER(ctypes.c_int64)]
        lib.pts_wait.restype = ctypes.c_int
        lib.pts_wait.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                 ctypes.c_int64]
        lib.pts_delete.restype = ctypes.c_int
        lib.pts_delete.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        lib.pts_num_keys.restype = ctypes.c_int64
        lib.pts_num_keys.argtypes = [ctypes.c_void_p]
        lib.pts_free.argtypes = [ctypes.c_void_p]
        _lib = lib
        return _lib


class TCPStore:
    """KV store client; rank 0 (is_master=True) also hosts the daemon.

    API contract mirrors the reference TCPStore: set/get/add/wait plus a
    counter-based barrier. One socket per instance; guarded by a lock, so
    an instance is safe to share between threads (blocking gets serialize).
    """

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 is_master: bool = False, world_size: int = 1,
                 timeout: float = 30.0):
        lib = get_lib()
        if lib is None:
            raise RuntimeError("native TCPStore library failed to build")
        self._lib = lib
        self._mu = threading.Lock()
        self._server = None
        self.world_size = world_size
        self.timeout_ms = int(timeout * 1000)
        if is_master:
            self._server = lib.pts_server_start(port)
            if not self._server:
                raise RuntimeError(f"TCPStore: cannot bind port {port}")
            port = lib.pts_server_port(self._server)
        self.host, self.port = host, port
        self._client = lib.pts_client_connect(host.encode(), port,
                                              self.timeout_ms)
        if not self._client:
            if self._server:
                lib.pts_server_stop(self._server)
            raise RuntimeError(f"TCPStore: cannot connect {host}:{port}")

    # -- KV ----------------------------------------------------------------
    def _h(self):
        """Live client handle; raises instead of passing NULL into C
        (use-after-close would otherwise segfault the interpreter)."""
        if self._client is None:
            raise RuntimeError("TCPStore is closed")
        return self._client

    def set(self, key: str, value) -> None:
        maybe_fail("store.set", key=key)
        data = value if isinstance(value, bytes) else str(value).encode()
        with self._mu:
            rc = self._lib.pts_set(self._h(), key.encode(), data,
                                   len(data))
        if rc != 0:
            # transport failure, typed like get/add so RetryPolicy's
            # default classification covers all client ops uniformly
            raise ConnectionError(f"TCPStore.set({key!r}): io error "
                                  f"(store unreachable)")

    def get(self, key: str, timeout: Optional[float] = None) -> bytes:
        maybe_fail("store.get", key=key)
        out = ctypes.POINTER(ctypes.c_uint8)()
        out_len = ctypes.c_uint64()
        tmo = self.timeout_ms if timeout is None else int(timeout * 1000)
        with self._mu:
            rc = self._lib.pts_get(self._h(), key.encode(), tmo,
                                   ctypes.byref(out), ctypes.byref(out_len))
        if rc == 1:
            raise TimeoutError(
                f"TCPStore.get({key!r}): no value within {tmo}ms")
        if rc != 0:
            raise ConnectionError(f"TCPStore.get({key!r}): io error "
                                  f"(store unreachable)")
        try:
            return ctypes.string_at(out, out_len.value)
        finally:
            if out:
                self._lib.pts_free(out)

    def add(self, key: str, delta: int = 1) -> int:
        maybe_fail("store.add", key=key)
        out = ctypes.c_int64()
        with self._mu:
            rc = self._lib.pts_add(self._h(), key.encode(), delta,
                                   ctypes.byref(out))
        if rc == 1:
            raise ValueError(
                f"TCPStore.add({key!r}): existing value is not an integer")
        if rc != 0:
            raise ConnectionError(f"TCPStore.add({key!r}): io error")
        return int(out.value)

    def wait(self, key: str, timeout: Optional[float] = None) -> None:
        maybe_fail("store.wait", key=key)
        tmo = self.timeout_ms if timeout is None else int(timeout * 1000)
        with self._mu:
            rc = self._lib.pts_wait(self._h(), key.encode(), tmo)
        if rc != 0:
            raise TimeoutError(f"TCPStore.wait({key!r}): not set within "
                               f"{tmo}ms")

    def delete_key(self, key: str) -> None:
        with self._mu:
            self._lib.pts_delete(self._h(), key.encode())

    def num_keys(self) -> int:
        with self._mu:
            return int(self._lib.pts_num_keys(self._h()))

    # -- barrier -----------------------------------------------------------
    def barrier(self, tag: str = "", timeout: Optional[float] = None):
        """Counter barrier across world_size participants. Every use of a
        tag is round-numbered per instance, so reusing a tag (or calling
        anonymous barriers in a loop) stays correct as long as all ranks
        call the same barriers in the same order — the usual collective
        contract."""
        if not hasattr(self, "_barrier_rounds"):
            self._barrier_rounds = {}
        rnd = self._barrier_rounds.get(tag, 0)
        self._barrier_rounds[tag] = rnd + 1
        key = f"__barrier__/{tag}/{rnd}"
        arrived = self.add(key + "/count", 1)
        if arrived == self.world_size:
            self.set(key + "/done", b"1")
        self.wait(key + "/done", timeout)

    def close(self):
        with self._mu:
            if self._client:
                self._lib.pts_client_close(self._client)
                self._client = None
            if self._server:
                self._lib.pts_server_stop(self._server)
                self._server = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass
