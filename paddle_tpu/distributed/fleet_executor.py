"""Actor-style micro-batch pipeline runtime — the FleetExecutor analog.

Reference: ``paddle/fluid/distributed/fleet_executor/`` — ``carrier.cc``,
``interceptor.cc``, ``compute_interceptor.cc``, ``source_interceptor.cc``,
``sink_interceptor.cc``, ``amplifier_interceptor.cc``,
``cond_interceptor.cc``, ``message_bus.cc``, ``runtime_graph.cc``,
``task_node.cc``, ``dist_model.cc``.

TPU-native rethink: on TPU the *performance* pipeline path is the jitted
SPMD schedule (``distributed.pipeline`` — scan + collective_permute inside
one XLA program), so this module does NOT drive training micro-batches the
way the reference's brpc actor mesh does. What it preserves is the
reference's *orchestration* capability: an actor graph whose interceptors
pass micro-batch-ready messages with credit-based flow control. That is
the right tool for host-side pipelines — multi-stage inference across
processes (``DistModel``), streaming pre/post-processing around a jitted
core, and cross-process serving — where each stage is a Python callable
(often itself a jitted function) rather than a fused XLA stage.

Messages are delivered in-process over thread queues; cross-rank delivery
goes through ``paddle_tpu.distributed.rpc`` (socket agent bootstrapped by
the native TCPStore) instead of brpc.
"""
from __future__ import annotations

import queue
import threading
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

__all__ = [
    "InterceptorMessage", "TaskNode", "MessageBus", "Interceptor",
    "ComputeInterceptor", "SourceInterceptor", "SinkInterceptor",
    "AmplifierInterceptor", "CondInterceptor", "Carrier", "RuntimeGraph",
    "FleetExecutor", "SOURCE_ID", "SINK_ID",
]

SOURCE_ID = -1
SINK_ID = -2

# message_type values (interceptor_message.proto: DATA_IS_READY etc.)
DATA_IS_READY = "DATA_IS_READY"
DATA_IS_USELESS = "DATA_IS_USELESS"
START = "START"
STOP = "STOP"


@dataclass
class InterceptorMessage:
    src_id: int
    dst_id: int
    message_type: str
    scope_idx: int = 0          # micro-batch index
    payload: Any = None


@dataclass
class TaskNode:
    """One node of the runtime graph (reference task_node.h).

    ``fn`` consumes a dict {upstream_id: payload} (micro-batch inputs) and
    returns the payload sent downstream. ``max_run_times`` = number of
    micro-batches this node processes per ``run``.
    """
    task_id: int
    fn: Optional[Callable[..., Any]] = None
    rank: int = 0
    max_run_times: int = 1
    type: str = "Compute"      # Source/Sink/Compute/Amplifier/Cond
    # downstream/upstream: task_id -> buffer size (flow-control credits)
    downstream: Dict[int, int] = field(default_factory=dict)
    upstream: Dict[int, int] = field(default_factory=dict)
    # Amplifier semantics (amplifier_interceptor.h): forward downstream /
    # reply upstream only every k-th run (gradient-accumulation-style
    # rate conversion)
    send_down_per_steps: int = 1
    reply_up_per_steps: int = 1
    # Cond semantics: predicate on the incoming payload; chooses branch
    cond: Optional[Callable[[Any], bool]] = None
    true_branch: Optional[int] = None
    false_branch: Optional[int] = None

    def add_downstream_task(self, task_id: int, buffer_size: int = 2):
        self.downstream[task_id] = buffer_size

    def add_upstream_task(self, task_id: int, buffer_size: int = 2):
        self.upstream[task_id] = buffer_size


class MessageBus:
    """Routes InterceptorMessages to interceptor inboxes.

    In-process: direct queue put. Cross-rank (interceptor registered on a
    different rank): forwarded through distributed.rpc (reference uses
    brpc message_service.cc).
    """

    def __init__(self, rank: int = 0):
        self.rank = rank
        self._local: Dict[int, "Interceptor"] = {}
        self._rank_of: Dict[int, int] = {}
        self._lock = threading.Lock()

    def register(self, interceptor: "Interceptor", rank: Optional[int] = None):
        with self._lock:
            self._local[interceptor.interceptor_id] = interceptor
            self._rank_of[interceptor.interceptor_id] = (
                self.rank if rank is None else rank)

    def register_remote(self, interceptor_id: int, rank: int):
        with self._lock:
            self._rank_of[interceptor_id] = rank

    def send(self, msg: InterceptorMessage) -> bool:
        target = self._local.get(msg.dst_id)
        if target is not None:
            target.enqueue(msg)
            return True
        dst_rank = self._rank_of.get(msg.dst_id)
        if dst_rank is None:
            raise KeyError(f"unknown interceptor {msg.dst_id}")
        from . import rpc as _rpc
        _rpc.rpc_sync(f"worker{dst_rank}", _deliver_remote,
                      args=(msg.src_id, msg.dst_id, msg.message_type,
                            msg.scope_idx, msg.payload))
        return True


_GLOBAL_BUS: Dict[int, MessageBus] = {}


def _deliver_remote(src_id, dst_id, message_type, scope_idx, payload):
    """rpc endpoint: re-inject a remote message into the local bus."""
    for bus in _GLOBAL_BUS.values():
        if dst_id in bus._local:
            bus.send(InterceptorMessage(src_id, dst_id, message_type,
                                        scope_idx, payload))
            return True
    raise KeyError(f"no local interceptor {dst_id}")


class Interceptor:
    """Base actor: a thread draining an inbox into a message handler."""

    def __init__(self, interceptor_id: int, node: TaskNode,
                 carrier: "Carrier"):
        self.interceptor_id = interceptor_id
        self.node = node
        self.carrier = carrier
        self._inbox: "queue.Queue[InterceptorMessage]" = queue.Queue()
        self._thread: Optional[threading.Thread] = None
        self._stopped = threading.Event()
        self.error: Optional[BaseException] = None

    # -- actor plumbing ---------------------------------------------------
    def start(self):
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name=f"interceptor{self.interceptor_id}")
        self._thread.start()

    def enqueue(self, msg: InterceptorMessage):
        self._inbox.put(msg)

    def join(self, timeout=None):
        if self._thread:
            self._thread.join(timeout)

    def send(self, dst_id: int, message_type: str, scope_idx: int = 0,
             payload: Any = None):
        self.carrier.bus.send(InterceptorMessage(
            self.interceptor_id, dst_id, message_type, scope_idx, payload))

    def _loop(self):
        try:
            while not self._stopped.is_set():
                msg = self._inbox.get()
                if msg.message_type == STOP:
                    self._stopped.set()
                    break
                self.handle(msg)
        except BaseException as e:  # surfaced by Carrier.wait
            self.error = e
            self.carrier.notify_error(e)

    def handle(self, msg: InterceptorMessage):
        raise NotImplementedError


class ComputeInterceptor(Interceptor):
    """Credit-based compute actor (compute_interceptor.cc).

    Runs when every upstream has a ready micro-batch and every downstream
    has a free buffer slot; replies DATA_IS_USELESS upstream (returning
    the credit) and sends DATA_IS_READY downstream.
    """

    def __init__(self, interceptor_id, node, carrier):
        super().__init__(interceptor_id, node, carrier)
        self._ready: Dict[int, deque] = {u: deque() for u in node.upstream}
        self._credits: Dict[int, int] = dict(node.downstream)
        self._run_count = 0

    def handle(self, msg: InterceptorMessage):
        if msg.message_type == DATA_IS_READY:
            self._ready[msg.src_id].append((msg.scope_idx, msg.payload))
        elif msg.message_type == DATA_IS_USELESS:
            self._credits[msg.src_id] += 1
        self._try_run()

    def _can_run(self) -> bool:
        if self._run_count >= self.node.max_run_times:
            return False
        if any(not d for d in self._ready.values()):
            return False
        if any(c <= 0 for c in self._credits.values()):
            return False
        return True

    def _compute(self, inputs: Dict[int, Any]) -> Any:
        fn = self.node.fn
        return fn(inputs) if fn is not None else inputs

    def _try_run(self):
        while self._can_run():
            inputs, scope_idx = {}, 0
            for up, dq in self._ready.items():
                scope_idx, payload = dq.popleft()
                inputs[up] = payload
            out = self._compute(inputs)
            self._run_count += 1
            for up in self._ready:
                self.send(up, DATA_IS_USELESS, scope_idx)
            for down in self._credits:
                self._credits[down] -= 1
                self.send(down, DATA_IS_READY, scope_idx, out)
            if self._run_count >= self.node.max_run_times:
                self.carrier.notify_done(self.interceptor_id)


class SourceInterceptor(Interceptor):
    """Feeds max_run_times micro-batches downstream as credits allow
    (source_interceptor.cc). Payloads come from carrier.feed(scope_idx)."""

    def __init__(self, interceptor_id, node, carrier):
        super().__init__(interceptor_id, node, carrier)
        self._credits: Dict[int, int] = dict(node.downstream)
        self._sent = 0

    def handle(self, msg: InterceptorMessage):
        if msg.message_type == DATA_IS_USELESS:
            self._credits[msg.src_id] += 1
        elif msg.message_type == START:
            pass
        self._try_send()

    def _try_send(self):
        while (self._sent < self.node.max_run_times
               and all(c > 0 for c in self._credits.values())):
            payload = self.carrier.feed(self._sent)
            for down in self._credits:
                self._credits[down] -= 1
                self.send(down, DATA_IS_READY, self._sent, payload)
            self._sent += 1
        if self._sent >= self.node.max_run_times:
            self.carrier.notify_done(self.interceptor_id)


class SinkInterceptor(Interceptor):
    """Collects final micro-batch outputs (sink_interceptor.cc)."""

    def __init__(self, interceptor_id, node, carrier):
        super().__init__(interceptor_id, node, carrier)
        self._received = 0

    def handle(self, msg: InterceptorMessage):
        if msg.message_type == DATA_IS_READY:
            self.carrier.collect(msg.scope_idx, msg.payload)
            self.send(msg.src_id, DATA_IS_USELESS, msg.scope_idx)
            self._received += 1
            if self._received >= self.node.max_run_times:
                self.carrier.notify_done(self.interceptor_id)


class AmplifierInterceptor(ComputeInterceptor):
    """Rate-changing compute node (amplifier_interceptor.cc): runs every
    micro-batch but only sends downstream / replies upstream every
    ``send_down_per_steps`` / ``reply_up_per_steps`` runs."""

    def _try_run(self):
        while self._can_run():
            inputs, scope_idx = {}, 0
            for up, dq in self._ready.items():
                scope_idx, payload = dq.popleft()
                inputs[up] = payload
            out = self._compute(inputs)
            step = self._run_count
            self._run_count += 1
            if (step + 1) % self.node.reply_up_per_steps == 0:
                for up in self._ready:
                    self.send(up, DATA_IS_USELESS, scope_idx)
            if (step + 1) % self.node.send_down_per_steps == 0:
                for down in self._credits:
                    self._credits[down] -= 1
                    self.send(down, DATA_IS_READY, scope_idx, out)
            if self._run_count >= self.node.max_run_times:
                self.carrier.notify_done(self.interceptor_id)


class CondInterceptor(ComputeInterceptor):
    """Routes each micro-batch to true_branch/false_branch by a predicate
    on the payload (cond_interceptor.cc drives while-loops; here the
    branch selection is explicit and data-driven)."""

    def _try_run(self):
        while self._can_run():
            inputs, scope_idx = {}, 0
            for up, dq in self._ready.items():
                scope_idx, payload = dq.popleft()
                inputs[up] = payload
            out = self._compute(inputs)
            self._run_count += 1
            for up in self._ready:
                self.send(up, DATA_IS_USELESS, scope_idx)
            value = next(iter(inputs.values())) if inputs else out
            branch = (self.node.true_branch if self.node.cond(value)
                      else self.node.false_branch)
            if branch in self._credits:
                self._credits[branch] -= 1
            self.send(branch, DATA_IS_READY, scope_idx, out)
            if self._run_count >= self.node.max_run_times:
                self.carrier.notify_done(self.interceptor_id)


_INTERCEPTOR_TYPES = {
    "Compute": ComputeInterceptor,
    "Source": SourceInterceptor,
    "Sink": SinkInterceptor,
    "Amplifier": AmplifierInterceptor,
    "Cond": CondInterceptor,
}


class Carrier:
    """Owns this rank's interceptors; wires the bus; runs one pass
    (carrier.cc)."""

    def __init__(self, rank: int = 0,
                 feed_fn: Optional[Callable[[int], Any]] = None):
        self.rank = rank
        self.bus = MessageBus(rank)
        _GLOBAL_BUS[id(self)] = self.bus
        self.interceptors: Dict[int, Interceptor] = {}
        self._feed_fn = feed_fn
        self._outputs: Dict[int, Any] = {}
        self._done: set = set()
        self._done_cv = threading.Condition()
        self._error: Optional[BaseException] = None

    def create_interceptor(self, node: TaskNode) -> Interceptor:
        cls = _INTERCEPTOR_TYPES[node.type]
        it = cls(node.task_id, node, self)
        self.interceptors[node.task_id] = it
        self.bus.register(it)
        return it

    # -- callbacks from interceptors --------------------------------------
    def feed(self, scope_idx: int) -> Any:
        return self._feed_fn(scope_idx) if self._feed_fn else scope_idx

    def collect(self, scope_idx: int, payload: Any):
        self._outputs[scope_idx] = payload

    def notify_done(self, interceptor_id: int):
        with self._done_cv:
            self._done.add(interceptor_id)
            self._done_cv.notify_all()

    def notify_error(self, err: BaseException):
        with self._done_cv:
            self._error = err
            self._done_cv.notify_all()

    # -- lifecycle --------------------------------------------------------
    def start(self):
        for it in self.interceptors.values():
            it.start()
        for it in self.interceptors.values():
            if isinstance(it, SourceInterceptor):
                self.bus.send(InterceptorMessage(
                    SOURCE_ID, it.interceptor_id, START))

    def wait(self, timeout: float = 120.0) -> Dict[int, Any]:
        deadline = threading.TIMEOUT_MAX if timeout is None else timeout
        with self._done_cv:
            ok = self._done_cv.wait_for(
                lambda: self._error is not None
                or self._done >= set(self.interceptors),
                timeout=deadline)
        if self._error is not None:
            raise self._error
        if not ok:
            raise TimeoutError("fleet_executor carrier timed out")
        return dict(self._outputs)

    def stop(self):
        for it in self.interceptors.values():
            it.enqueue(InterceptorMessage(SOURCE_ID, it.interceptor_id,
                                          STOP))
        for it in self.interceptors.values():
            it.join(timeout=5)
        _GLOBAL_BUS.pop(id(self), None)


class RuntimeGraph:
    """Builds the task-node graph for a linear pipeline of stages
    (runtime_graph.cc origin_program → per-rank task nodes)."""

    def __init__(self, stage_fns: List[Callable], num_micro_batches: int,
                 buffer_size: int = 2):
        self.nodes: Dict[int, TaskNode] = {}
        src = TaskNode(task_id=0, type="Source",
                       max_run_times=num_micro_batches)
        self.nodes[0] = src
        prev = src
        for i, fn in enumerate(stage_fns):
            node = TaskNode(task_id=i + 1, fn=fn,
                            max_run_times=num_micro_batches)
            prev.add_downstream_task(node.task_id, buffer_size)
            node.add_upstream_task(prev.task_id, buffer_size)
            self.nodes[node.task_id] = node
            prev = node
        sink = TaskNode(task_id=len(stage_fns) + 1, type="Sink",
                        max_run_times=num_micro_batches)
        prev.add_downstream_task(sink.task_id, buffer_size)
        sink.add_upstream_task(prev.task_id, buffer_size)
        self.nodes[sink.task_id] = sink


class FleetExecutor:
    """Top-level runner (fleet_executor.cc): build carrier from a runtime
    graph, feed micro-batches, return ordered outputs.

    ``stage_fns`` take and return a single payload (the micro-batch); the
    dict-of-upstreams plumbing is collapsed for the common linear case.
    """

    def __init__(self, stage_fns: List[Callable],
                 num_micro_batches: int = 1, buffer_size: int = 2,
                 rank: int = 0):
        def lift(fn):
            def wrapped(inputs: Dict[int, Any]):
                (payload,) = inputs.values()
                return fn(payload)
            return wrapped

        self.num_micro_batches = num_micro_batches
        self.graph = RuntimeGraph([lift(f) for f in stage_fns],
                                  num_micro_batches, buffer_size)
        self.rank = rank

    def run(self, feed: Callable[[int], Any] | List[Any],
            timeout: float = 120.0) -> List[Any]:
        if isinstance(feed, (list, tuple)):
            batches = list(feed)
            if len(batches) != self.num_micro_batches:
                raise ValueError(
                    f"feed has {len(batches)} micro-batches, expected "
                    f"{self.num_micro_batches}")
            feed_fn = lambda i: batches[i]  # noqa: E731
        else:
            feed_fn = feed
        carrier = Carrier(self.rank, feed_fn)
        for node in self.graph.nodes.values():
            carrier.create_interceptor(node)
        carrier.start()
        try:
            outputs = carrier.wait(timeout)
        finally:
            carrier.stop()
        return [outputs[i] for i in sorted(outputs)]
