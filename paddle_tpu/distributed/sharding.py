"""ZeRO-style group sharding (stage 1/2/3).

Reference: python/paddle/distributed/sharding/group_sharded.py:50
group_sharded_parallel dispatching to GroupShardedOptimizerStage2
(fleet/meta_parallel/sharding/group_sharded_optimizer_stage2.py:53),
GroupShardedStage2 (:46), GroupShardedStage3 (:85 — param sharding with
on-demand gather PyLayers + reduce_scatter hooks).

TPU-native: ZeRO == sharding annotations (SURVEY.md §7 hard part #3 —
"express as fsdp-axis sharding rather than hooks"):
- stage1/2: optimizer state (and grads, which under jit are transient XLA
  values anyway) sharded over the axis — shard_optimizer does this;
- stage3: parameters themselves sharded dim-0 over the axis; XLA
  all-gathers at use and reduce-scatters grads, overlapping with compute
  (the reference's forward-prefetch PyLayer :901 is XLA's latency-hiding
  scheduler here).
"""
from __future__ import annotations

from typing import Optional

import jax
from jax.sharding import NamedSharding, PartitionSpec

from ..framework.tensor import Parameter, no_grad
from ..nn.layer_base import Layer
from .api import ShardingStage1, ShardingStage2, ShardingStage3, \
    shard_optimizer
from .process_mesh import ProcessMesh, get_mesh

__all__ = ["group_sharded_parallel", "save_group_sharded_model",
           "shard_model_stage3"]


def _axis_of(mesh: ProcessMesh, preferred=("sharding", "fsdp", "data", "dp")):
    for name in preferred:
        if name in mesh.dim_names and mesh.get_dim_size(name) > 1:
            return name
    return mesh.dim_names[0]


def shard_model_stage3(model: Layer, mesh: Optional[ProcessMesh] = None,
                       axis_name: Optional[str] = None) -> Layer:
    """Shard every parameter dim-0 over the sharding axis (ZeRO-3)."""
    mesh = mesh or get_mesh()
    if mesh is None:
        return model
    axis = axis_name or _axis_of(mesh)
    n = mesh.get_dim_size(axis)
    jmesh = mesh.jax_mesh()
    with no_grad():
        for _, p in model.named_parameters():
            if p.ndim == 0 or p.shape[0] % n != 0:
                sharding = NamedSharding(jmesh, PartitionSpec())
            else:
                sharding = NamedSharding(
                    jmesh, PartitionSpec(axis, *([None] * (p.ndim - 1))))
            p._data = jax.device_put(p._data, sharding)
    return model


def group_sharded_parallel(model: Layer, optimizer, level: str,
                           scaler=None, group=None, offload=False,
                           sync_buffers=False, buffer_max_size=2 ** 23,
                           segment_size=2 ** 20, sync_comm=False,
                           dp_group=None, exclude_layer=None):
    """paddle.distributed.sharding.group_sharded_parallel analog.

    level: 'os' (stage1) | 'os_g' (stage2) | 'p_g_os' (stage3).
    """
    mesh = get_mesh()
    axis = _axis_of(mesh) if mesh is not None else "data"
    if level == "os":
        stage = ShardingStage1(axis, mesh)
    elif level == "os_g":
        stage = ShardingStage2(axis, mesh)
    elif level == "p_g_os":
        stage = ShardingStage3(axis, mesh)
        shard_model_stage3(model, mesh, axis)
    else:
        raise ValueError(f"level must be os/os_g/p_g_os, got {level}")
    optimizer = shard_optimizer(optimizer, stage)
    return model, optimizer, scaler


def save_group_sharded_model(model: Layer, output: str, optimizer=None):
    """Gather-free save: state_dict arrays may be sharded; framework.io
    converts via np.asarray which gathers replicas transparently."""
    from ..framework.io import save
    save(model.state_dict(), output + ".pdmodel")
    if optimizer is not None:
        save(optimizer.state_dict(), output + ".pdopt")
