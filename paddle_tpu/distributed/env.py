"""Distributed environment bootstrap.

Reference: paddle.distributed.init_parallel_env
(python/paddle/distributed/parallel.py) + TCPStore rendezvous
(/root/reference/paddle/phi/core/distributed/store/tcp_store.h:121) +
launcher env (PADDLE_TRAINER_ID/PADDLE_TRAINER_ENDPOINTS).

TPU-native: jax.distributed.initialize (coordination service) replaces the
TCPStore; each *process* is a host driving its local TPU chips, so rank =
jax.process_index() and the per-chip fan-out is the mesh, not extra ranks.
"""
from __future__ import annotations

import os
from typing import Optional

import jax

_initialized = False


def init_parallel_env(coordinator_address: Optional[str] = None,
                      num_processes: Optional[int] = None,
                      process_id: Optional[int] = None):
    """Bring up multi-host coordination when env describes a multi-host job
    (PADDLE_* envs accepted for compat, JAX_COORDINATOR_ADDRESS native)."""
    global _initialized
    if _initialized:
        return get_group()
    coord = coordinator_address or os.environ.get("JAX_COORDINATOR_ADDRESS")
    nproc = num_processes or _int_env("PADDLE_TRAINERS_NUM",
                                      "JAX_NUM_PROCESSES")
    pid = process_id if process_id is not None else _int_env(
        "PADDLE_TRAINER_ID", "JAX_PROCESS_ID")
    if coord is None and "PADDLE_TRAINER_ENDPOINTS" in os.environ:
        coord = os.environ["PADDLE_TRAINER_ENDPOINTS"].split(",")[0]
    if coord and nproc and nproc > 1:
        jax.distributed.initialize(coordinator_address=coord,
                                   num_processes=nproc, process_id=pid or 0)
    _initialized = True
    return get_group()


def _int_env(*names):
    for n in names:
        v = os.environ.get(n)
        if v is not None:
            return int(v)
    return None


def get_rank(group=None) -> int:
    if group is not None and getattr(group, "ranks", None):
        try:
            return group.ranks.index(jax.process_index())
        except ValueError:
            return -1
    return jax.process_index()


def get_world_size(group=None) -> int:
    if group is not None and getattr(group, "ranks", None):
        return len(group.ranks)
    return jax.process_count()


def is_initialized() -> bool:
    return _initialized


def parallel_device_count() -> int:
    return jax.device_count()


def get_group():
    from .collective import _get_default_group
    return _get_default_group()


class ParallelEnv:
    """Legacy paddle.distributed.ParallelEnv surface."""

    @property
    def rank(self):
        return get_rank()

    @property
    def world_size(self):
        return get_world_size()

    @property
    def local_rank(self):
        return int(os.environ.get("PADDLE_LOCAL_RANK", 0))

    @property
    def device_id(self):
        return 0

    @property
    def current_endpoint(self):
        eps = os.environ.get("PADDLE_TRAINER_ENDPOINTS", "").split(",")
        r = get_rank()
        return eps[r] if r < len(eps) else ""

    @property
    def trainer_endpoints(self):
        return os.environ.get("PADDLE_TRAINER_ENDPOINTS", "").split(",")
