"""Shared length-prefixed socket framing ('<Q' header + body).

One protocol, three transports: the rpc agent (distributed/rpc.py),
the cross-process DistModel pipeline (inference/dist_model_mp.py) and
the serving cluster RPC (serving/cluster.py / serving/worker.py) —
kept here so a framing change (checksums, size guards) cannot silently
diverge between them. csrc/tcp_store.cc uses the same shape natively.

Fault points ``cluster.rpc.send`` / ``cluster.rpc.recv`` fire here, so
network faults are injectable everywhere the framing layer is used.
Whatever exception is armed, callers observe a typed
:class:`ConnectionError` — a network fault IS a broken connection, and
after one the socket's stream position is undefined (``recv_msg`` may
have consumed a header whose body is still in flight), so the only
legal reaction is to close the socket. Never a partial-frame hang.
"""
from __future__ import annotations

import socket
import struct
from typing import Optional

from ..resilience.faults import maybe_fail  # stdlib-only at import

__all__ = ["send_msg", "recv_msg", "recv_exact", "nodelay",
           "MAX_FRAME_BYTES"]

# Upper bound on a single frame: a corrupt or hostile header must not
# drive recv_exact into a near-2^64 allocation loop. 4 GiB covers the
# largest activation tensors the serving pipeline ships; override via
# paddle_tpu.distributed._framing.MAX_FRAME_BYTES for larger payloads.
MAX_FRAME_BYTES = 4 << 30


def nodelay(sock: socket.socket) -> socket.socket:
    """Small frames + request/response chaining: Nagle batching would
    park them on delayed-ACK ticks (measured +548% on the 2-stage
    serving pipeline before this)."""
    sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
    return sock


def _fault(point: str, **ctx) -> None:
    """Injection hook: re-type any armed fault as ConnectionError so
    the caller's socket-error handling (close + reconnect/retry) runs
    for injected faults exactly as for real ones."""
    try:
        maybe_fail(point, **ctx)
    except ConnectionError:
        raise
    except Exception as e:
        raise ConnectionError(f"injected at {point}: {e}") from e


def send_msg(sock: socket.socket, data: bytes) -> None:
    _fault("cluster.rpc.send", nbytes=len(data))
    sock.sendall(struct.pack("<Q", len(data)) + data)


def recv_msg(sock: socket.socket,
             eof_ok: bool = False) -> Optional[bytes]:
    """One frame; on clean EOF returns None (eof_ok) or raises
    ConnectionError. EOF mid-frame always raises."""
    hdr = recv_exact(sock, 8, eof_ok=eof_ok)
    if hdr is None:
        return None
    (n,) = struct.unpack("<Q", hdr)
    # fires AFTER the header: the worst spot — the body is (or will
    # be) in the socket buffer, so a caller that kept reading would
    # desync on a stale frame. Raising ConnectionError forces a close.
    _fault("cluster.rpc.recv", nbytes=n)
    if n > MAX_FRAME_BYTES:
        raise ConnectionError(
            f"frame length {n} exceeds MAX_FRAME_BYTES "
            f"({MAX_FRAME_BYTES}): corrupt or hostile header")
    return recv_exact(sock, n)


def recv_exact(sock: socket.socket, n: int,
               eof_ok: bool = False) -> Optional[bytes]:
    chunks = []
    got = 0
    while got < n:
        b = sock.recv(min(n - got, 1 << 20))
        if not b:
            if eof_ok and got == 0:
                return None
            raise ConnectionError("peer closed mid-frame")
        chunks.append(b)
        got += len(b)
    return b"".join(chunks)
