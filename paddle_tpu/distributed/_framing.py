"""Shared length-prefixed socket framing ('<Q' header + body).

One protocol, two transports: the rpc agent (distributed/rpc.py) and
the cross-process DistModel pipeline (inference/dist_model_mp.py) —
kept here so a framing change (checksums, size guards) cannot silently
diverge between them. csrc/tcp_store.cc uses the same shape natively.
"""
from __future__ import annotations

import socket
import struct
from typing import Optional

__all__ = ["send_msg", "recv_msg", "recv_exact", "nodelay",
           "MAX_FRAME_BYTES"]

# Upper bound on a single frame: a corrupt or hostile header must not
# drive recv_exact into a near-2^64 allocation loop. 4 GiB covers the
# largest activation tensors the serving pipeline ships; override via
# paddle_tpu.distributed._framing.MAX_FRAME_BYTES for larger payloads.
MAX_FRAME_BYTES = 4 << 30


def nodelay(sock: socket.socket) -> socket.socket:
    """Small frames + request/response chaining: Nagle batching would
    park them on delayed-ACK ticks (measured +548% on the 2-stage
    serving pipeline before this)."""
    sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
    return sock


def send_msg(sock: socket.socket, data: bytes) -> None:
    sock.sendall(struct.pack("<Q", len(data)) + data)


def recv_msg(sock: socket.socket,
             eof_ok: bool = False) -> Optional[bytes]:
    """One frame; on clean EOF returns None (eof_ok) or raises
    ConnectionError. EOF mid-frame always raises."""
    hdr = recv_exact(sock, 8, eof_ok=eof_ok)
    if hdr is None:
        return None
    (n,) = struct.unpack("<Q", hdr)
    if n > MAX_FRAME_BYTES:
        raise ConnectionError(
            f"frame length {n} exceeds MAX_FRAME_BYTES "
            f"({MAX_FRAME_BYTES}): corrupt or hostile header")
    return recv_exact(sock, n)


def recv_exact(sock: socket.socket, n: int,
               eof_ok: bool = False) -> Optional[bytes]:
    chunks = []
    got = 0
    while got < n:
        b = sock.recv(min(n - got, 1 << 20))
        if not b:
            if eof_ok and got == 0:
                return None
            raise ConnectionError("peer closed mid-frame")
        chunks.append(b)
        got += len(b)
    return b"".join(chunks)
