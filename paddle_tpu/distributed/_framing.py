"""Shared length-prefixed socket framing ('<Q' header + body).

One protocol, three transports: the rpc agent (distributed/rpc.py),
the cross-process DistModel pipeline (inference/dist_model_mp.py) and
the serving cluster RPC (serving/cluster.py / serving/worker.py) —
kept here so a framing change (checksums, size guards) cannot silently
diverge between them. csrc/tcp_store.cc uses the same shape natively.

Fault points ``cluster.rpc.send`` / ``cluster.rpc.recv`` fire here, so
network faults are injectable everywhere the framing layer is used.
Whatever exception is armed, callers observe a typed
:class:`ConnectionError` — a network fault IS a broken connection, and
after one the socket's stream position is undefined (``recv_msg`` may
have consumed a header whose body is still in flight), so the only
legal reaction is to close the socket. Never a partial-frame hang.

Authentication (the cross-host trust boundary): :class:`FrameAuth`
adds a shared-secret HMAC handshake per connection and a per-frame
HMAC-SHA256 with strictly-sequential per-direction counters, so a
tampered, replayed, dropped-and-reordered, or unauthenticated frame is
rejected with a typed :class:`AuthError` (a ConnectionError subclass —
every existing close-socket/retry path already does the right thing)
and counted (:func:`auth_failures`). ``seal``/``open_sealed`` apply
the same secret to TCPStore rendezvous values (the store daemon treats
values as opaque bytes), and :func:`restricted_loads` unpickles the
worker spec under a data-only allowlist so a tampered spec cannot
execute code. The ``cluster.rpc.auth`` fault point fires inside the
verification paths.
"""
from __future__ import annotations

import hashlib
import hmac as _hmac_mod
import io
import os
import pickle
import socket
import struct
from typing import Callable, List, Optional

from ..resilience.faults import maybe_fail  # stdlib-only at import

__all__ = ["send_msg", "recv_msg", "recv_exact", "nodelay",
           "MAX_FRAME_BYTES", "AuthError", "FrameAuth",
           "client_handshake", "server_handshake", "seal",
           "open_sealed", "restricted_loads", "auth_failures",
           "register_auth_failure_hook"]

# Upper bound on a single frame: a corrupt or hostile header must not
# drive recv_exact into a near-2^64 allocation loop. 4 GiB covers the
# largest activation tensors the serving pipeline ships; override via
# paddle_tpu.distributed._framing.MAX_FRAME_BYTES for larger payloads.
MAX_FRAME_BYTES = 4 << 30


def nodelay(sock: socket.socket) -> socket.socket:
    """Small frames + request/response chaining: Nagle batching would
    park them on delayed-ACK ticks (measured +548% on the 2-stage
    serving pipeline before this)."""
    sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
    return sock


def _fault(point: str, **ctx) -> None:
    """Injection hook: re-type any armed fault as ConnectionError so
    the caller's socket-error handling (close + reconnect/retry) runs
    for injected faults exactly as for real ones."""
    try:
        maybe_fail(point, **ctx)
    except ConnectionError:
        raise
    except Exception as e:
        raise ConnectionError(f"injected at {point}: {e}") from e


# ---------------------------------------------------------------------------
# Authenticated framing
# ---------------------------------------------------------------------------

class AuthError(ConnectionError):
    """Typed auth rejection: failed handshake, missing/garbage frame
    MAC, replayed or reordered frame, tampered rendezvous value, or a
    worker spec that tries to smuggle code. Subclasses ConnectionError
    on purpose — after a rejection the stream position is as undefined
    as after any wire fault, so the close-socket/retry machinery must
    treat it identically (blips below the retry budget are absorbed by
    a reconnect + fresh handshake; a persistent mismatch exhausts the
    budget into the ordinary typed failover)."""


_MAGIC = b"ptpu-auth1"          # hello prefix: absence = unauth peer
_NONCE_LEN = 16
_MAC_LEN = 32                   # HMAC-SHA256

_auth_failures = 0
_auth_failure_hooks: List[Callable[[str], None]] = []


def auth_failures() -> int:
    """Process-wide count of typed auth rejections (mirrored into the
    ``ptpu_cluster_auth_failures_total`` registry counter by the
    cluster layer)."""
    return _auth_failures


def register_auth_failure_hook(cb: Callable[[str], None]) -> None:
    """Call ``cb(reason)`` on every auth rejection — the bridge the
    supervisor/worker use to publish the registry counter without this
    stdlib-only module importing observability."""
    if cb not in _auth_failure_hooks:
        _auth_failure_hooks.append(cb)


def _reject(reason: str, cause: Optional[BaseException] = None):
    global _auth_failures
    _auth_failures += 1
    for cb in list(_auth_failure_hooks):
        try:
            cb(reason)
        except Exception:
            pass                # a metrics hook must never mask the rejection
    raise AuthError(reason) from cause


def _auth_fault(**ctx) -> None:
    """``cluster.rpc.auth`` injection hook: any armed fault becomes a
    counted, typed AuthError — injected auth failures exercise exactly
    the rejection path real ones take."""
    try:
        maybe_fail("cluster.rpc.auth", **ctx)
    except Exception as e:
        _reject(f"injected at cluster.rpc.auth: {e}", cause=e)


def _mac(key: bytes, *parts: bytes) -> bytes:
    h = _hmac_mod.new(key, digestmod=hashlib.sha256)
    for p in parts:
        h.update(p)
    return h.digest()


class FrameAuth:
    """Per-connection frame authenticator produced by the handshake:
    direction-separated session keys plus strictly-sequential send and
    receive counters. The counter is mixed into every MAC, so a frame
    that is replayed, dropped, or reordered fails verification even
    though its MAC was once valid — exactly-once framing below the
    RPC layer's (token, seq) dedup."""

    def __init__(self, send_key: bytes, recv_key: bytes):
        self._send_key = send_key
        self._recv_key = recv_key
        self._send_seq = 0
        self._recv_seq = 0

    def seal_frame(self, payload: bytes) -> bytes:
        mac = _mac(self._send_key, struct.pack("<Q", self._send_seq),
                   payload)
        self._send_seq += 1
        return mac + payload

    def open_frame(self, body: bytes) -> bytes:
        _auth_fault(nbytes=len(body), seq=self._recv_seq)
        if len(body) < _MAC_LEN:
            _reject("frame shorter than its MAC: unauthenticated or "
                    "tampered peer")
        mac, payload = body[:_MAC_LEN], body[_MAC_LEN:]
        want = _mac(self._recv_key, struct.pack("<Q", self._recv_seq),
                    payload)
        if not _hmac_mod.compare_digest(mac, want):
            _reject(f"bad frame MAC at recv seq {self._recv_seq}: "
                    f"tampered, replayed or reordered frame")
        self._recv_seq += 1
        return payload


def client_handshake(sock: socket.socket, secret: bytes) -> FrameAuth:
    """One round trip at connect: prove knowledge of the shared secret
    in both directions and derive direction-separated session keys.
    Raises a counted :class:`AuthError` if the server cannot answer
    the challenge (wrong or missing secret)."""
    nonce_c = os.urandom(_NONCE_LEN)
    send_msg(sock, _MAGIC + nonce_c + _mac(secret, b"cli", nonce_c))
    reply = recv_msg(sock)
    if len(reply) != _NONCE_LEN + _MAC_LEN:
        _reject("malformed auth handshake reply")
    nonce_s, mac = reply[:_NONCE_LEN], reply[_NONCE_LEN:]
    _auth_fault(stage="client_handshake")
    if not _hmac_mod.compare_digest(
            mac, _mac(secret, b"srv", nonce_c, nonce_s)):
        _reject("server failed the shared-secret handshake (wrong or "
                "missing cluster secret)")
    return FrameAuth(_mac(secret, b"c2s", nonce_c, nonce_s),
                     _mac(secret, b"s2c", nonce_c, nonce_s))


def server_handshake(sock: socket.socket, secret: bytes) -> FrameAuth:
    """Server half of :func:`client_handshake`. A peer that closes
    without speaking raises plain ConnectionError (port scan, not an
    auth event); a peer that speaks anything but a valid hello — e.g.
    an unauthenticated client sending a pickled RPC — is a counted,
    typed rejection."""
    hello = recv_msg(sock, eof_ok=True)
    if hello is None:
        raise ConnectionError("peer closed before auth hello")
    if len(hello) != len(_MAGIC) + _NONCE_LEN + _MAC_LEN \
            or not hello.startswith(_MAGIC):
        _reject("peer did not speak the auth handshake "
                "(unauthenticated client rejected)")
    nonce_c = hello[len(_MAGIC):len(_MAGIC) + _NONCE_LEN]
    mac = hello[len(_MAGIC) + _NONCE_LEN:]
    _auth_fault(stage="server_handshake")
    if not _hmac_mod.compare_digest(mac, _mac(secret, b"cli", nonce_c)):
        _reject("client failed the shared-secret handshake")
    nonce_s = os.urandom(_NONCE_LEN)
    send_msg(sock, nonce_s + _mac(secret, b"srv", nonce_c, nonce_s))
    return FrameAuth(_mac(secret, b"s2c", nonce_c, nonce_s),
                     _mac(secret, b"c2s", nonce_c, nonce_s))


def seal(secret: bytes, key: str, value: bytes) -> bytes:
    """HMAC envelope for a TCPStore rendezvous value: the store daemon
    treats values as opaque bytes, so authn rides inside the value.
    The MAC covers the store KEY too — a valid value cannot be replayed
    under a different key (e.g. one worker's port as another's)."""
    return _mac(secret, b"store", key.encode("utf-8"), b"\x00",
                value) + value


def open_sealed(secret: bytes, key: str, blob: bytes) -> bytes:
    """Verify + strip a :func:`seal` envelope; counted typed
    :class:`AuthError` on any mismatch — a tampered rendezvous must
    never yield bytes."""
    if len(blob) < _MAC_LEN:
        _reject(f"sealed store value {key!r} shorter than its MAC")
    mac, value = blob[:_MAC_LEN], blob[_MAC_LEN:]
    if not _hmac_mod.compare_digest(
            mac, _mac(secret, b"store", key.encode("utf-8"), b"\x00",
                      value)):
        _reject(f"sealed store value {key!r} failed its MAC: "
                f"tampered rendezvous")
    return value


# The worker SPEC is plain configuration data: dicts/lists/strings/
# numbers plus (at most) small numpy scalars/arrays. Everything else —
# most importantly anything with a __reduce__ that calls code — is
# rejected. (numpy moved multiarray under numpy._core in 2.x; both
# spellings stay listed so the allowlist survives the rename.)
_SPEC_SAFE_GLOBALS = {
    ("collections", "OrderedDict"),
    ("numpy", "ndarray"),
    ("numpy", "dtype"),
    ("numpy.core.multiarray", "_reconstruct"),
    ("numpy.core.multiarray", "scalar"),
    ("numpy._core.multiarray", "_reconstruct"),
    ("numpy._core.multiarray", "scalar"),
}


class _SpecUnpickler(pickle.Unpickler):
    def find_class(self, module, name):
        if (module, name) in _SPEC_SAFE_GLOBALS:
            return super().find_class(module, name)
        _reject(f"worker spec pickle requested disallowed global "
                f"{module}.{name} — tampered spec rejected")


def restricted_loads(blob: bytes):
    """Unpickle the worker spec under the data-only allowlist. Any
    disallowed global or malformed stream is a counted, typed
    :class:`AuthError` — never arbitrary code execution. RPC payloads
    (requests, typed errors) stay ordinary pickle; they only flow over
    connections that already passed the handshake."""
    try:
        return _SpecUnpickler(io.BytesIO(blob)).load()
    except AuthError:
        raise
    except Exception as e:
        _reject(f"malformed worker spec pickle: {e!r}", cause=e)


def send_msg(sock: socket.socket, data: bytes,
             auth: Optional[FrameAuth] = None) -> None:
    if auth is not None:
        data = auth.seal_frame(data)
    _fault("cluster.rpc.send", nbytes=len(data))
    sock.sendall(struct.pack("<Q", len(data)) + data)


def recv_msg(sock: socket.socket, eof_ok: bool = False,
             auth: Optional[FrameAuth] = None) -> Optional[bytes]:
    """One frame; on clean EOF returns None (eof_ok) or raises
    ConnectionError. EOF mid-frame always raises. With ``auth`` the
    frame's MAC is verified (and stripped) before the payload is
    returned — a frame that fails is a counted typed AuthError and
    the socket must be closed like any other wire error."""
    hdr = recv_exact(sock, 8, eof_ok=eof_ok)
    if hdr is None:
        return None
    (n,) = struct.unpack("<Q", hdr)
    # fires AFTER the header: the worst spot — the body is (or will
    # be) in the socket buffer, so a caller that kept reading would
    # desync on a stale frame. Raising ConnectionError forces a close.
    _fault("cluster.rpc.recv", nbytes=n)
    if n > MAX_FRAME_BYTES:
        raise ConnectionError(
            f"frame length {n} exceeds MAX_FRAME_BYTES "
            f"({MAX_FRAME_BYTES}): corrupt or hostile header")
    body = recv_exact(sock, n)
    if auth is not None:
        body = auth.open_frame(body)
    return body


def recv_exact(sock: socket.socket, n: int,
               eof_ok: bool = False) -> Optional[bytes]:
    chunks = []
    got = 0
    while got < n:
        b = sock.recv(min(n - got, 1 << 20))
        if not b:
            if eof_ok and got == 0:
                return None
            raise ConnectionError("peer closed mid-frame")
        chunks.append(b)
        got += len(b)
    return b"".join(chunks)
