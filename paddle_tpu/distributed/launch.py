"""Launcher (reference: python/paddle/distributed/launch/ — main.py:23
``python -m paddle.distributed.launch``, collective controller spawning
per-device workers with PADDLE_* env, HTTP/ETCD master rendezvous).

TPU-native: one process per HOST (chips are driven through the mesh, not
extra processes), so the launcher's job shrinks to: set coordination env,
spawn/exec the training script per host, watch and propagate exit codes.
``spawn`` keeps the paddle.distributed.spawn API for CPU/test multi-proc.
"""
from __future__ import annotations

import os
import runpy
import subprocess
import sys
from argparse import ArgumentParser
from typing import Callable, Optional

__all__ = ["spawn", "launch_main", "ELASTIC_EXIT_CODE"]

ELASTIC_EXIT_CODE = 101  # relaunch-me protocol (fleet/elastic/manager.py:33)


def spawn(func: Callable, args=(), nprocs: int = 1, join: bool = True,
          daemon: bool = False, **options):
    """paddle.distributed.spawn analog (multiprocessing workers; used for
    CPU-backend multi-process tests — on TPU the mesh replaces this)."""
    import multiprocessing as mp
    ctx = mp.get_context("spawn")
    procs = []
    for rank in range(nprocs):
        env = {"PADDLE_TRAINER_ID": str(rank),
               "PADDLE_TRAINERS_NUM": str(nprocs)}
        p = ctx.Process(target=_worker, args=(func, args, env),
                        daemon=daemon)
        p.start()
        procs.append(p)
    if join:
        for p in procs:
            p.join()
        for p in procs:
            if p.exitcode:
                raise RuntimeError(
                    f"spawned worker failed with exit code {p.exitcode}")
    return procs


def _worker(func, args, env):
    os.environ.update(env)
    func(*args)


def launch_main(argv=None):
    """``python -m paddle_tpu.distributed.launch [--nnodes N] script.py``"""
    parser = ArgumentParser("paddle_tpu.distributed.launch")
    parser.add_argument("--nnodes", type=int, default=1)
    parser.add_argument("--node_rank", type=int, default=0)
    parser.add_argument("--master", type=str, default="127.0.0.1:49174")
    parser.add_argument("--devices", type=str, default=None,
                        help="accepted for compat; TPU chips come from "
                             "the runtime, not this flag")
    parser.add_argument("--log_dir", type=str, default=None)
    parser.add_argument("--max_restarts", type=int, default=0)
    parser.add_argument("script", type=str)
    parser.add_argument("script_args", nargs="...")
    args = parser.parse_args(argv)

    env = dict(os.environ)
    env["PADDLE_TRAINER_ID"] = str(args.node_rank)
    env["PADDLE_TRAINERS_NUM"] = str(args.nnodes)
    env["JAX_COORDINATOR_ADDRESS"] = args.master
    env["JAX_NUM_PROCESSES"] = str(args.nnodes)
    env["JAX_PROCESS_ID"] = str(args.node_rank)

    restarts = 0
    while True:
        proc = subprocess.run([sys.executable, args.script] +
                              list(args.script_args), env=env)
        if proc.returncode == ELASTIC_EXIT_CODE and \
                restarts < args.max_restarts:
            restarts += 1  # elastic relaunch protocol
            continue
        return proc.returncode


if __name__ == "__main__":
    sys.exit(launch_main())
