"""Cost model: op/program/communication time estimation.

Reference: python/paddle/cost_model/cost_model.py (CostModel with static
op-cost tables + profile_measure) and the auto-parallel comm/op cost
library (python/paddle/distributed/auto_parallel/static/cost/) used by
the planner and auto-tuner pruning. TPU-native: analytic roofline costs
(FLOPs / peak, bytes / HBM bandwidth, collective bytes / ICI bandwidth)
plus measured costs by timing the jitted program — XLA's compiled
executable replaces the reference's per-op benchmark tables.
"""
from __future__ import annotations

import time
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

__all__ = ["CostModel", "CommCostModel", "measure_program"]

# v5e-class defaults; overridable per instance
DEFAULT_PEAK_FLOPS = 197e12       # bf16 FLOP/s
DEFAULT_HBM_BW = 819e9            # bytes/s
DEFAULT_ICI_BW = 4.5e10           # bytes/s per link (one direction)
DEFAULT_DCN_BW = 1.25e10          # bytes/s


class CostModel:
    """Analytic + measured op/program costs (cost_model.py analog)."""

    def __init__(self, peak_flops: float = DEFAULT_PEAK_FLOPS,
                 hbm_bandwidth: float = DEFAULT_HBM_BW):
        self.peak_flops = peak_flops
        self.hbm_bw = hbm_bandwidth

    # -- analytic ----------------------------------------------------------
    def matmul_flops(self, m: int, k: int, n: int,
                     batch: int = 1) -> float:
        return 2.0 * batch * m * k * n

    def conv2d_flops(self, n, cin, h, w, cout, kh, kw,
                     stride=1, groups=1) -> float:
        oh, ow = h // stride, w // stride
        return 2.0 * n * oh * ow * cout * (cin // groups) * kh * kw

    def op_time(self, flops: float = 0.0, bytes_moved: float = 0.0,
                flops_util: float = 0.5) -> float:
        """Roofline: max of compute time and memory time, seconds."""
        t_c = flops / (self.peak_flops * flops_util) if flops else 0.0
        t_m = bytes_moved / self.hbm_bw if bytes_moved else 0.0
        return max(t_c, t_m)

    def static_op_time(self, op_name: str, inputs_numel: int = 0,
                       dtype_bytes: int = 4,
                       flops: Optional[float] = None) -> float:
        """Coarse per-op table for planner pruning: elementwise ops are
        bandwidth-bound (one read+write pass); compute-bound ops require
        their FLOP count (via matmul_flops/conv2d_flops) — returning 0
        would make planners prefer matmul-heavy plans as free."""
        if op_name in ("matmul", "conv2d", "conv3d", "einsum"):
            if flops is None:
                raise ValueError(
                    f"'{op_name}' is compute-bound; pass flops= (see "
                    f"matmul_flops/conv2d_flops)")
            return self.op_time(
                flops=flops,
                bytes_moved=inputs_numel * dtype_bytes)
        return self.op_time(bytes_moved=2 * inputs_numel * dtype_bytes)

    # -- measured ----------------------------------------------------------
    def profile_measure(self, run_fn, warmup: int = 2,
                        iters: int = 5) -> float:
        """Median wall time of a callable (the jitted program is the
        cost model on real hardware); returns seconds."""
        import jax

        def sync(o):
            jax.tree.map(
                lambda a: np.asarray(jax.device_get(a))
                if hasattr(a, "dtype") else a, o)

        for _ in range(warmup):
            sync(run_fn())  # drain async dispatch before timing starts
        ts = []
        for _ in range(iters):
            t0 = time.perf_counter()
            sync(run_fn())
            ts.append(time.perf_counter() - t0)
        return float(np.median(ts))


class CommCostModel:
    """Collective time estimates over the mesh fabric
    (auto_parallel/static/cost/comm_op_cost.py analog, ring algorithm)."""

    def __init__(self, bandwidth: float = DEFAULT_ICI_BW,
                 latency_s: float = 1e-6):
        self.bw = bandwidth
        self.latency = latency_s

    def all_reduce(self, nbytes: float, n: int) -> float:
        if n <= 1:
            return 0.0
        return 2.0 * (n - 1) / n * nbytes / self.bw + \
            2 * (n - 1) * self.latency

    def all_gather(self, nbytes_per_rank: float, n: int) -> float:
        if n <= 1:
            return 0.0
        return (n - 1) * nbytes_per_rank / self.bw + \
            (n - 1) * self.latency

    def reduce_scatter(self, nbytes_total: float, n: int) -> float:
        if n <= 1:
            return 0.0
        return (n - 1) / n * nbytes_total / self.bw + \
            (n - 1) * self.latency

    def all_to_all(self, nbytes_per_rank: float, n: int) -> float:
        if n <= 1:
            return 0.0
        return (n - 1) / n * nbytes_per_rank / self.bw + \
            (n - 1) * self.latency

    def p2p(self, nbytes: float) -> float:
        return nbytes / self.bw + self.latency


def measure_program(program, feed: Dict[str, Any], fetch_list,
                    executor=None, warmup: int = 1,
                    iters: int = 3) -> float:
    """Median run time of a static Program (profile_measure over the
    Executor; the reference profiles per-op via its cost model ops)."""
    from .static import Executor
    exe = executor or Executor()
    cm = CostModel()
    return cm.profile_measure(
        lambda: exe.run(program, feed=feed, fetch_list=fetch_list),
        warmup=warmup, iters=iters)
