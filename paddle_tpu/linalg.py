"""paddle.linalg namespace (reference: python/paddle/linalg.py re-exports
from tensor/linalg.py). The ops live in ops/linalg.py; this module is the
public namespace mirror."""
from .ops.linalg import *  # noqa: F401,F403
from .ops.linalg import __all__ as _ops_all

__all__ = list(_ops_all)
