"""Build/system config introspection (reference:
python/paddle/sysconfig.py: get_include/get_lib)."""
from __future__ import annotations

import os

__all__ = ["get_include", "get_lib"]

_ROOT = os.path.dirname(os.path.abspath(__file__))


def get_include():
    """Directory of C headers shipped with the package (csrc/)."""
    return os.path.join(os.path.dirname(_ROOT), "csrc")


def get_lib():
    """Directory of compiled native libraries."""
    return os.path.join(os.path.dirname(_ROOT), "csrc", "build")
