"""RMSProp / Adagrad / Adadelta / Rprop (reference:
python/paddle/optimizer/{rmsprop,adagrad,adadelta,rprop}.py)."""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from .optimizer import Optimizer

__all__ = ["RMSProp", "Adagrad", "Adadelta"]


@functools.partial(jax.jit, donate_argnums=(0, 2, 3),
                   static_argnames=("centered",))
def _rmsprop_update(p, g, mean_sq, mom, lr, rho, eps, momentum, centered,
                    mean_g):
    g = g.astype(jnp.float32)
    pf = p.astype(jnp.float32)
    ms_new = rho * mean_sq + (1 - rho) * jnp.square(g)
    if centered:
        mg_new = rho * mean_g + (1 - rho) * g
        denom = jnp.sqrt(ms_new - jnp.square(mg_new) + eps)
    else:
        mg_new = mean_g
        denom = jnp.sqrt(ms_new + eps)
    mom_new = momentum * mom + lr * g / denom
    return pf - mom_new, ms_new, mom_new, mg_new


class RMSProp(Optimizer):
    def __init__(self, learning_rate, rho=0.95, epsilon=1e-6, momentum=0.0,
                 centered=False, parameters=None, weight_decay=None,
                 grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name)
        self._rho, self._epsilon = rho, epsilon
        self._momentum, self._centered = momentum, centered

    def _update_param(self, p, g):
        ms = self._acc(p, "mean_square",
                       init=jnp.zeros(p._data.shape, jnp.float32))
        mom = self._acc(p, "momentum",
                        init=jnp.zeros(p._data.shape, jnp.float32))
        mg = self._acc(p, "mean_grad",
                       init=jnp.zeros(p._data.shape, jnp.float32))
        new_p, ms2, mom2, mg2 = _rmsprop_update(
            p._data, g, ms, mom, self._param_lr(p), self._rho,
            self._epsilon, self._momentum, self._centered, mg)
        self._set_acc(p, "mean_square", ms2)
        self._set_acc(p, "momentum", mom2)
        self._set_acc(p, "mean_grad", mg2)
        return new_p


@functools.partial(jax.jit, donate_argnums=(0, 2))
def _adagrad_update(p, g, acc, lr, eps):
    g = g.astype(jnp.float32)
    pf = p.astype(jnp.float32)
    acc_new = acc + jnp.square(g)
    return pf - lr * g / (jnp.sqrt(acc_new) + eps), acc_new


class Adagrad(Optimizer):
    def __init__(self, learning_rate, epsilon=1e-6, parameters=None,
                 weight_decay=None, grad_clip=None,
                 initial_accumulator_value=0.0, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name)
        self._epsilon = epsilon
        self._init_acc = initial_accumulator_value

    def _update_param(self, p, g):
        acc = self._acc(p, "moment",
                        init=jnp.full(p._data.shape, self._init_acc,
                                      jnp.float32))
        new_p, acc2 = _adagrad_update(p._data, g, acc, self._param_lr(p),
                                      self._epsilon)
        self._set_acc(p, "moment", acc2)
        return new_p


@functools.partial(jax.jit, donate_argnums=(0, 2, 3))
def _adadelta_update(p, g, avg_sq_g, avg_sq_dx, lr, rho, eps):
    g = g.astype(jnp.float32)
    pf = p.astype(jnp.float32)
    asg_new = rho * avg_sq_g + (1 - rho) * jnp.square(g)
    dx = -jnp.sqrt(avg_sq_dx + eps) / jnp.sqrt(asg_new + eps) * g
    asdx_new = rho * avg_sq_dx + (1 - rho) * jnp.square(dx)
    return pf + lr * dx, asg_new, asdx_new


class Adadelta(Optimizer):
    def __init__(self, learning_rate=0.001, epsilon=1e-6, rho=0.95,
                 parameters=None, weight_decay=None, grad_clip=None,
                 name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name)
        self._epsilon, self._rho = epsilon, rho

    def _update_param(self, p, g):
        asg = self._acc(p, "avg_squared_grad",
                        init=jnp.zeros(p._data.shape, jnp.float32))
        asdx = self._acc(p, "avg_squared_update",
                         init=jnp.zeros(p._data.shape, jnp.float32))
        new_p, asg2, asdx2 = _adadelta_update(
            p._data, g, asg, asdx, self._param_lr(p), self._rho,
            self._epsilon)
        self._set_acc(p, "avg_squared_grad", asg2)
        self._set_acc(p, "avg_squared_update", asdx2)
        return new_p
