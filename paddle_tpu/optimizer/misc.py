"""RMSProp / Adagrad / Adadelta / Rprop (reference:
python/paddle/optimizer/{rmsprop,adagrad,adadelta,rprop}.py)."""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from .optimizer import Optimizer

__all__ = ["RMSProp", "Adagrad", "Adadelta"]


@functools.partial(jax.jit, donate_argnums=(0, 2, 3),
                   static_argnames=("centered",))
def _rmsprop_update(p, g, mean_sq, mom, lr, rho, eps, momentum, centered,
                    mean_g):
    g = g.astype(jnp.float32)
    pf = p.astype(jnp.float32)
    ms_new = rho * mean_sq + (1 - rho) * jnp.square(g)
    if centered:
        mg_new = rho * mean_g + (1 - rho) * g
        denom = jnp.sqrt(ms_new - jnp.square(mg_new) + eps)
    else:
        mg_new = mean_g
        denom = jnp.sqrt(ms_new + eps)
    mom_new = momentum * mom + lr * g / denom
    return pf - mom_new, ms_new, mom_new, mg_new


class RMSProp(Optimizer):
    def __init__(self, learning_rate, rho=0.95, epsilon=1e-6, momentum=0.0,
                 centered=False, parameters=None, weight_decay=None,
                 grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name)
        self._rho, self._epsilon = rho, epsilon
        self._momentum, self._centered = momentum, centered

    def _update_param(self, p, g):
        ms = self._acc(p, "mean_square",
                       init=jnp.zeros(p._data.shape, jnp.float32))
        mom = self._acc(p, "momentum",
                        init=jnp.zeros(p._data.shape, jnp.float32))
        mg = self._acc(p, "mean_grad",
                       init=jnp.zeros(p._data.shape, jnp.float32))
        new_p, ms2, mom2, mg2 = _rmsprop_update(
            p._data, g, ms, mom, self._param_lr(p), self._rho,
            self._epsilon, self._momentum, self._centered, mg)
        self._set_acc(p, "mean_square", ms2)
        self._set_acc(p, "momentum", mom2)
        self._set_acc(p, "mean_grad", mg2)
        return new_p


@functools.partial(jax.jit, donate_argnums=(0, 2))
def _adagrad_update(p, g, acc, lr, eps):
    g = g.astype(jnp.float32)
    pf = p.astype(jnp.float32)
    acc_new = acc + jnp.square(g)
    return pf - lr * g / (jnp.sqrt(acc_new) + eps), acc_new


class Adagrad(Optimizer):
    def __init__(self, learning_rate, epsilon=1e-6, parameters=None,
                 weight_decay=None, grad_clip=None,
                 initial_accumulator_value=0.0, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name)
        self._epsilon = epsilon
        self._init_acc = initial_accumulator_value

    def _update_param(self, p, g):
        acc = self._acc(p, "moment",
                        init=jnp.full(p._data.shape, self._init_acc,
                                      jnp.float32))
        new_p, acc2 = _adagrad_update(p._data, g, acc, self._param_lr(p),
                                      self._epsilon)
        self._set_acc(p, "moment", acc2)
        return new_p


@functools.partial(jax.jit, donate_argnums=(0, 2, 3))
def _adadelta_update(p, g, avg_sq_g, avg_sq_dx, lr, rho, eps):
    g = g.astype(jnp.float32)
    pf = p.astype(jnp.float32)
    asg_new = rho * avg_sq_g + (1 - rho) * jnp.square(g)
    dx = -jnp.sqrt(avg_sq_dx + eps) / jnp.sqrt(asg_new + eps) * g
    asdx_new = rho * avg_sq_dx + (1 - rho) * jnp.square(dx)
    return pf + lr * dx, asg_new, asdx_new


class Adadelta(Optimizer):
    def __init__(self, learning_rate=0.001, epsilon=1e-6, rho=0.95,
                 parameters=None, weight_decay=None, grad_clip=None,
                 name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name)
        self._epsilon, self._rho = epsilon, rho

    def _update_param(self, p, g):
        asg = self._acc(p, "avg_squared_grad",
                        init=jnp.zeros(p._data.shape, jnp.float32))
        asdx = self._acc(p, "avg_squared_update",
                         init=jnp.zeros(p._data.shape, jnp.float32))
        new_p, asg2, asdx2 = _adadelta_update(
            p._data, g, asg, asdx, self._param_lr(p), self._rho,
            self._epsilon)
        self._set_acc(p, "avg_squared_grad", asg2)
        self._set_acc(p, "avg_squared_update", asdx2)
        return new_p


class ASGD(Optimizer):
    """Averaged SGD (optimizer/asgd.py): steps with the mean of the last
    ``batch_num`` gradients. A circular buffer of the window's gradients
    keeps the running sum exact (d = d - oldest + newest, the reference's
    ys buffer)."""

    def __init__(self, learning_rate=0.001, batch_num=1, parameters=None,
                 weight_decay=None, grad_clip=None, multi_precision=False,
                 name=None):
        super().__init__(learning_rate, parameters, weight_decay,
                         grad_clip, name)
        self._n = max(int(batch_num), 1)

    def _update_param(self, p, g):
        g = g.astype(jnp.float32)
        d = self._acc(p, "d", init=jnp.zeros(p._data.shape, jnp.float32))
        ys = self._acc(p, "ys", init=jnp.zeros((self._n,) + p._data.shape,
                                               jnp.float32))
        slot = (self._step_count - 1) % self._n
        oldest = ys[slot]
        d2 = d - oldest + g
        ys2 = ys.at[slot].set(g)
        # before the window fills, average over the steps seen so far
        seen = jnp.minimum(jnp.asarray(self._step_count, jnp.float32),
                           float(self._n))
        new_p = p._data.astype(jnp.float32) - \
            self._param_lr(p) * d2 / seen
        self._set_acc(p, "d", d2)
        self._set_acc(p, "ys", ys2)
        return new_p


class Rprop(Optimizer):
    """Resilient backprop (optimizer/rprop.py): per-weight step sizes
    grown/shrunk by the sign agreement of successive gradients."""

    def __init__(self, learning_rate=0.001, learning_rate_range=(1e-5, 50),
                 parameters=None, etas=(0.5, 1.2), grad_clip=None,
                 multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, None, grad_clip, name)
        self._lr_min, self._lr_max = learning_rate_range
        self._eta_neg, self._eta_pos = etas

    def _update_param(self, p, g):
        g = g.astype(jnp.float32)
        prev = self._acc(p, "prev_grad",
                         init=jnp.zeros(p._data.shape, jnp.float32))
        # init must stay traceable: inside a jitted TrainStep the lr is
        # a tracer and float() would concretize (the expression evaluates
        # even when the slot already exists)
        step = self._acc(p, "step_size",
                         init=jnp.full(p._data.shape,
                                       jnp.asarray(self.get_lr(),
                                                   jnp.float32)))
        sign = jnp.sign(g * prev)
        step2 = jnp.clip(
            jnp.where(sign > 0, step * self._eta_pos,
                      jnp.where(sign < 0, step * self._eta_neg, step)),
            self._lr_min, self._lr_max)
        g_eff = jnp.where(sign < 0, 0.0, g)  # no step on sign flip
        new_p = p._data.astype(jnp.float32) - jnp.sign(g_eff) * step2
        self._set_acc(p, "prev_grad", g_eff)
        self._set_acc(p, "step_size", step2)
        return new_p


class NAdam(Optimizer):
    """Nesterov Adam (optimizer/nadam.py)."""

    def __init__(self, learning_rate=0.002, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, momentum_decay=0.004, parameters=None,
                 weight_decay=None, grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay,
                         grad_clip, name)
        self._b1, self._b2 = beta1, beta2
        self._eps = epsilon
        self._psi = momentum_decay

    def _update_param(self, p, g):
        g = g.astype(jnp.float32)
        m = self._acc(p, "m", init=jnp.zeros(p._data.shape, jnp.float32))
        v = self._acc(p, "v", init=jnp.zeros(p._data.shape, jnp.float32))
        mu_prod = self._acc(p, "mu_prod",
                            init=jnp.ones((), jnp.float32))
        t = jnp.asarray(self._step_count, jnp.float32)
        mu_t = self._b1 * (1 - 0.5 * 0.96 ** (t * self._psi))
        mu_t1 = self._b1 * (1 - 0.5 * 0.96 ** ((t + 1) * self._psi))
        mu_prod2 = mu_prod * mu_t
        m2 = self._b1 * m + (1 - self._b1) * g
        v2 = self._b2 * v + (1 - self._b2) * g * g
        m_hat = mu_t1 * m2 / (1 - mu_prod2 * mu_t1) + \
            (1 - mu_t) * g / (1 - mu_prod2)
        v_hat = v2 / (1 - self._b2 ** t)
        new_p = p._data.astype(jnp.float32) - self._param_lr(p) * \
            m_hat / (jnp.sqrt(v_hat) + self._eps)
        self._set_acc(p, "m", m2)
        self._set_acc(p, "v", v2)
        self._set_acc(p, "mu_prod", mu_prod2)
        return new_p


class RAdam(Optimizer):
    """Rectified Adam (optimizer/radam.py): variance-rectification term
    switches between SGD-with-momentum and Adam."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, weight_decay=None,
                 grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay,
                         grad_clip, name)
        self._b1, self._b2 = beta1, beta2
        self._eps = epsilon

    def _update_param(self, p, g):
        g = g.astype(jnp.float32)
        m = self._acc(p, "m", init=jnp.zeros(p._data.shape, jnp.float32))
        v = self._acc(p, "v", init=jnp.zeros(p._data.shape, jnp.float32))
        t = jnp.asarray(self._step_count, jnp.float32)
        m2 = self._b1 * m + (1 - self._b1) * g
        v2 = self._b2 * v + (1 - self._b2) * g * g
        m_hat = m2 / (1 - self._b1 ** t)
        rho_inf = 2.0 / (1 - self._b2) - 1
        rho_t = rho_inf - 2 * t * self._b2 ** t / (1 - self._b2 ** t)
        r = jnp.sqrt(((rho_t - 4) * (rho_t - 2) * rho_inf) /
                     jnp.maximum((rho_inf - 4) * (rho_inf - 2) * rho_t,
                                 1e-12))
        v_hat = jnp.sqrt(v2 / (1 - self._b2 ** t))
        adam_step = r * m_hat / (v_hat + self._eps)
        sgd_step = m_hat
        step = jnp.where(rho_t > 4.0, adam_step, sgd_step)
        new_p = p._data.astype(jnp.float32) - self._param_lr(p) * step
        self._set_acc(p, "m", m2)
        self._set_acc(p, "v", v2)
        return new_p


__all__ += ["ASGD", "Rprop", "NAdam", "RAdam"]
