"""Adam-family optimizers (reference: python/paddle/optimizer/adam.py,
adamw.py, adamax.py, lamb.py; fused GPU kernels
phi/kernels/gpu/adam_kernel.cu, fused_adam_kernel — here one jitted XLA
update each, with optional float32 master weights for bf16 params
(AMP O2 "master grad/weight" semantics, python/paddle/amp/auto_cast.py)."""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from ..framework.tensor import Parameter, Tensor
from .optimizer import Optimizer, _DecoupledWD

__all__ = ["Adam", "AdamW", "Adamax", "Lamb"]


@functools.partial(jax.jit, donate_argnums=(0, 2, 3),
                   static_argnames=("wd_coupled",))
def _adam_update(p, g, m, v, lr, beta1, beta2, eps, t, wd_coupled):
    g = g.astype(jnp.float32)
    pf = p.astype(jnp.float32)
    if wd_coupled != 0.0:
        g = g + wd_coupled * pf
    m_new = beta1 * m + (1 - beta1) * g
    v_new = beta2 * v + (1 - beta2) * jnp.square(g)
    mhat = m_new / (1 - beta1 ** t)
    vhat = v_new / (1 - beta2 ** t)
    p_new = pf - lr * mhat / (jnp.sqrt(vhat) + eps)
    return p_new, m_new, v_new


@functools.partial(jax.jit, donate_argnums=(0, 2, 3))
def _adamw_update(p, g, m, v, lr, beta1, beta2, eps, t, wd):
    g = g.astype(jnp.float32)
    pf = p.astype(jnp.float32)
    pf = pf * (1 - lr * wd)  # decoupled decay (AdamW)
    m_new = beta1 * m + (1 - beta1) * g
    v_new = beta2 * v + (1 - beta2) * jnp.square(g)
    mhat = m_new / (1 - beta1 ** t)
    vhat = v_new / (1 - beta2 ** t)
    p_new = pf - lr * mhat / (jnp.sqrt(vhat) + eps)
    return p_new, m_new, v_new


class Adam(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, weight_decay=None,
                 grad_clip=None, lazy_mode=False, multi_precision=True,
                 use_multi_tensor=False, name=None):
        super().__init__(learning_rate, parameters, None, grad_clip, name)
        self._beta1 = beta1
        self._beta2 = beta2
        self._epsilon = epsilon
        if hasattr(weight_decay, "apply"):
            # L1Decay/L2Decay regularizer: applied as a grad term by the
            # base step() (the reference's append_regularization_ops path)
            self._weight_decay = weight_decay
            self._coupled_wd = 0.0
        else:
            self._coupled_wd = float(weight_decay) if weight_decay else 0.0
        self._multi_precision = multi_precision

    def _master(self, p: Parameter) -> jax.Array:
        """float32 master weight for low-precision params (AMP O2)."""
        if p._data.dtype == jnp.float32 or not self._multi_precision:
            return p._data
        return self._acc(p, "master_weight",
                         init=p._data.astype(jnp.float32))

    def _store_master(self, p: Parameter, new_p: jax.Array) -> jax.Array:
        if p._data.dtype != jnp.float32 and self._multi_precision:
            self._set_acc(p, "master_weight", new_p)
        return new_p

    def _update_param(self, p, g):
        m = self._acc(p, "moment1", init=jnp.zeros(p._data.shape,
                                                   jnp.float32))
        v = self._acc(p, "moment2", init=jnp.zeros(p._data.shape,
                                                   jnp.float32))
        new_p, m2, v2 = _adam_update(
            self._master(p), g, m, v, self._param_lr(p), self._beta1,
            self._beta2, self._epsilon, self._step_count, self._coupled_wd)
        self._set_acc(p, "moment1", m2)
        self._set_acc(p, "moment2", v2)
        return self._store_master(p, new_p)


class AdamW(Adam, _DecoupledWD):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, weight_decay=0.01,
                 lr_ratio=None, apply_decay_param_fun=None, grad_clip=None,
                 lazy_mode=False, multi_precision=True, name=None):
        super().__init__(learning_rate, beta1, beta2, epsilon, parameters,
                         None, grad_clip, lazy_mode, multi_precision)
        # decoupled decay takes a coefficient; accept L2Decay for API compat
        self._wd = weight_decay.coeff if hasattr(weight_decay, "coeff") \
            else float(weight_decay)
        self._apply_decay_param_fun = apply_decay_param_fun
        self._lr_ratio = lr_ratio

    def _update_param(self, p, g):
        m = self._acc(p, "moment1", init=jnp.zeros(p._data.shape,
                                                   jnp.float32))
        v = self._acc(p, "moment2", init=jnp.zeros(p._data.shape,
                                                   jnp.float32))
        wd = self._wd
        if self._apply_decay_param_fun is not None and \
                not self._apply_decay_param_fun(p.name):
            wd = 0.0
        lr = self._param_lr(p)
        if self._lr_ratio is not None:
            lr = lr * self._lr_ratio(p)
        new_p, m2, v2 = _adamw_update(
            self._master(p), g, m, v, lr, self._beta1, self._beta2,
            self._epsilon, self._step_count, wd)
        self._set_acc(p, "moment1", m2)
        self._set_acc(p, "moment2", v2)
        return self._store_master(p, new_p)


@functools.partial(jax.jit, donate_argnums=(0, 2, 3))
def _adamax_update(p, g, m, u, lr, beta1, beta2, eps, t):
    g = g.astype(jnp.float32)
    pf = p.astype(jnp.float32)
    m_new = beta1 * m + (1 - beta1) * g
    u_new = jnp.maximum(beta2 * u, jnp.abs(g))
    p_new = pf - (lr / (1 - beta1 ** t)) * m_new / (u_new + eps)
    return p_new, m_new, u_new


class Adamax(Adam):
    def _update_param(self, p, g):
        m = self._acc(p, "moment", init=jnp.zeros(p._data.shape,
                                                  jnp.float32))
        u = self._acc(p, "inf_norm", init=jnp.zeros(p._data.shape,
                                                    jnp.float32))
        new_p, m2, u2 = _adamax_update(
            self._master(p), g, m, u, self._param_lr(p), self._beta1,
            self._beta2, self._epsilon, self._step_count)
        self._set_acc(p, "moment", m2)
        self._set_acc(p, "inf_norm", u2)
        return self._store_master(p, new_p)


@functools.partial(jax.jit, donate_argnums=(0, 2, 3))
def _lamb_update(p, g, m, v, lr, beta1, beta2, eps, t, wd):
    g = g.astype(jnp.float32)
    pf = p.astype(jnp.float32)
    m_new = beta1 * m + (1 - beta1) * g
    v_new = beta2 * v + (1 - beta2) * jnp.square(g)
    mhat = m_new / (1 - beta1 ** t)
    vhat = v_new / (1 - beta2 ** t)
    r = mhat / (jnp.sqrt(vhat) + eps) + wd * pf
    w_norm = jnp.linalg.norm(pf)
    r_norm = jnp.linalg.norm(r)
    trust = jnp.where((w_norm > 0) & (r_norm > 0), w_norm / r_norm, 1.0)
    return pf - lr * trust * r, m_new, v_new


class Lamb(Optimizer):
    def __init__(self, learning_rate=0.001, lamb_weight_decay=0.01,
                 beta1=0.9, beta2=0.999, epsilon=1e-6, parameters=None,
                 grad_clip=None, exclude_from_weight_decay_fn=None,
                 multi_precision=True, name=None):
        super().__init__(learning_rate, parameters, None, grad_clip, name)
        self._beta1, self._beta2, self._epsilon = beta1, beta2, epsilon
        self._wd = lamb_weight_decay.coeff \
            if hasattr(lamb_weight_decay, "coeff") else float(lamb_weight_decay)
        self._exclude_fn = exclude_from_weight_decay_fn
        self._multi_precision = multi_precision
        self._master = Adam._master.__get__(self)
        self._store_master = Adam._store_master.__get__(self)

    def _update_param(self, p, g):
        m = self._acc(p, "moment1", init=jnp.zeros(p._data.shape,
                                                   jnp.float32))
        v = self._acc(p, "moment2", init=jnp.zeros(p._data.shape,
                                                   jnp.float32))
        wd = 0.0 if (self._exclude_fn is not None and self._exclude_fn(p)) \
            else self._wd
        new_p, m2, v2 = _lamb_update(
            self._master(p), g, m, v, self._param_lr(p), self._beta1,
            self._beta2, self._epsilon, self._step_count, wd)
        self._set_acc(p, "moment1", m2)
        self._set_acc(p, "moment2", v2)
        return self._store_master(p, new_p)
