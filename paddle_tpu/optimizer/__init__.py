"""Optimizers (reference: python/paddle/optimizer/, 11.5k LoC)."""
from .optimizer import Optimizer, SGD, Momentum  # noqa: F401
from .adam import Adam, AdamW, Adamax, Lamb  # noqa: F401
from .misc import (RMSProp, Adagrad, Adadelta, ASGD, Rprop,  # noqa: F401
                   NAdam, RAdam)
from .lbfgs import LBFGS  # noqa: F401
from .lars_dgc import (LarsMomentumOptimizer,  # noqa: F401
                       DGCMomentumOptimizer)
from . import lr  # noqa: F401
