"""L-BFGS optimizer (reference: python/paddle/optimizer/lbfgs.py — full-batch
quasi-Newton with strong-Wolfe line search over a closure).

TPU-native: parameters are flattened into ONE vector so the two-loop
recursion is a handful of dot products/axpys XLA fuses; history lives as
device arrays. The closure re-evaluates loss+grads (each evaluation is a
normal traced forward/backward)."""
from __future__ import annotations

from typing import Callable, List, Optional

import jax.numpy as jnp
import numpy as np

from ..framework.tensor import Parameter, Tensor, no_grad
from .optimizer import Optimizer

__all__ = ["LBFGS"]


def _flat(arrs):
    return jnp.concatenate([jnp.ravel(a.astype(jnp.float32)) for a in arrs])


class LBFGS(Optimizer):
    def __init__(self, learning_rate=1.0, max_iter=20, max_eval=None,
                 tolerance_grad=1e-7, tolerance_change=1e-9, history_size=100,
                 line_search_fn=None, parameters=None, weight_decay=None,
                 grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name)
        self.max_iter = max_iter
        self.max_eval = max_eval if max_eval is not None else max_iter * 5 // 4
        self.tolerance_grad = tolerance_grad
        self.tolerance_change = tolerance_change
        self.history_size = history_size
        if line_search_fn not in (None, "strong_wolfe"):
            raise ValueError("line_search_fn must be None or 'strong_wolfe'")
        self.line_search_fn = line_search_fn
        self._s: List = []
        self._y: List = []

    # -- param vector plumbing --------------------------------------------
    def _gather(self):
        return _flat([p._data for p in self._parameter_list])

    def _gather_grad(self):
        params_grads = [(p, p.grad) for p in self._parameter_list
                        if p.grad is not None]
        if self._grad_clip is not None and params_grads:
            params_grads = self._grad_clip(params_grads)
        clipped = {id(p): g for p, g in params_grads}
        gs = []
        for p in self._parameter_list:
            g = clipped.get(id(p))
            garr = jnp.zeros_like(p._data) if g is None else \
                (g._data if isinstance(g, Tensor) else g)
            if self._weight_decay:
                wd = self._weight_decay
                garr = wd.apply(p._data.astype(garr.dtype), garr) \
                    if hasattr(wd, "apply") else garr + float(wd) * \
                    p._data.astype(garr.dtype)
            gs.append(garr)
        return _flat(gs)

    def _scatter(self, vec):
        off = 0
        for p in self._parameter_list:
            n = int(np.prod(p._data.shape)) if p._data.shape else 1
            chunk = vec[off:off + n].reshape(p._data.shape)
            p._data = chunk.astype(p._data.dtype)
            off += n

    # -- two-loop recursion ------------------------------------------------
    def _direction(self, g):
        q = g
        alphas = []
        for s, y in zip(reversed(self._s), reversed(self._y)):
            rho = 1.0 / jnp.vdot(y, s)
            a = rho * jnp.vdot(s, q)
            q = q - a * y
            alphas.append((a, rho))
        if self._s:
            s, y = self._s[-1], self._y[-1]
            gamma = jnp.vdot(s, y) / jnp.vdot(y, y)
            q = gamma * q
        for (a, rho), (s, y) in zip(reversed(alphas),
                                    zip(self._s, self._y)):
            b = rho * jnp.vdot(y, q)
            q = q + (a - b) * s
        return -q

    @no_grad()
    def step(self, closure: Optional[Callable] = None):
        if closure is None:
            raise ValueError("LBFGS.step requires a closure that recomputes "
                             "the loss (call loss.backward() inside)")
        lr = self.get_lr()
        x = self._gather()

        def call_closure():
            # closure runs forward+backward with grads enabled
            from ..framework.tensor import enable_grad
            with enable_grad():
                return closure()

        loss = call_closure()
        f = float(loss._data if isinstance(loss, Tensor) else loss)
        g = self._gather_grad()
        n_eval = 1
        x_prev, g_prev = x, g

        for _ in range(self.max_iter):
            if float(jnp.max(jnp.abs(g))) <= self.tolerance_grad:
                break
            d = self._direction(g)
            gtd = float(jnp.vdot(g, d))
            if gtd > -1e-20:  # not a descent direction; reset history
                self._s.clear(); self._y.clear()
                d = -g
                gtd = float(jnp.vdot(g, d))
            t = lr
            if self.line_search_fn == "strong_wolfe":
                t, f, g, n_ev = self._strong_wolfe(call_closure, x, d, f, g,
                                                   gtd, t)
                n_eval += n_ev
                x = x + t * d
                self._scatter(x)
            else:
                x = x + t * d
                self._scatter(x)
                loss = call_closure()
                f = float(loss._data if isinstance(loss, Tensor) else loss)
                g = self._gather_grad()
                n_eval += 1
            s = x - x_prev
            y = g - g_prev
            if float(jnp.vdot(s, y)) > 1e-10:
                self._s.append(s); self._y.append(y)
                if len(self._s) > self.history_size:
                    self._s.pop(0); self._y.pop(0)
            if float(jnp.max(jnp.abs(s))) <= self.tolerance_change:
                break
            x_prev, g_prev = x, g
            if n_eval >= self.max_eval:
                break
        self._step_count += 1
        for p in self._parameter_list:
            p.grad_node = None
        return Tensor(jnp.asarray(f))

    def _strong_wolfe(self, closure, x, d, f0, g0, gtd0, t,
                      c1=1e-4, c2=0.9, max_ls=25):
        """Bisection-based strong-Wolfe line search (contract of the
        reference's _strong_wolfe, lbfgs.py)."""
        lo, hi = 0.0, None
        f_prev, n_ev = f0, 0
        for _ in range(max_ls):
            self._scatter(x + t * d)
            loss = closure()
            f = float(loss._data if isinstance(loss, Tensor) else loss)
            g = self._gather_grad()
            n_ev += 1
            t_eval = t  # the step size f/g above belong to
            if f > f0 + c1 * t * gtd0 or f >= f_prev:
                hi = t
            else:
                gtd = float(jnp.vdot(g, d))
                if abs(gtd) <= -c2 * gtd0:
                    return t, f, g, n_ev
                if gtd >= 0:
                    hi = t
                else:
                    lo = t
            t = (lo + hi) / 2.0 if hi is not None else t * 2.0
            f_prev = f
        # exhausted: return the last *evaluated* point so f/g match t
        return t_eval, f, g, n_ev
