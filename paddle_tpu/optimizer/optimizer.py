"""Optimizer base + SGD/Momentum.

Reference: python/paddle/optimizer/optimizer.py (base: regularization, grad
clip, LR scheduler plumbing) and momentum.py. TPU-native: each update rule
is one jitted pure function over (param, grad, state) arrays — XLA fuses the
whole update; there are no per-op fused CUDA kernels to maintain
(reference fused: phi/kernels/gpu/momentum_kernel.cu etc.).
"""
from __future__ import annotations

import functools
from collections import OrderedDict
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp

from ..framework.tensor import Parameter, Tensor, no_grad
from .lr import LRScheduler

__all__ = ["Optimizer", "SGD", "Momentum"]


class Optimizer:
    def __init__(self, learning_rate=0.001, parameters=None,
                 weight_decay=None, grad_clip=None, name=None):
        if parameters is None:
            from ..static.graph import in_static_mode
            if not in_static_mode():
                raise ValueError(
                    "parameters is required in eager mode (pass "
                    "model.parameters()); in static mode minimize() "
                    "collects the program's parameters")
            parameters = []
        self._parameter_list = list(parameters)
        self._learning_rate = learning_rate
        self._grad_clip = grad_clip
        self._weight_decay = weight_decay
        # state: param-name-keyed dict of jax arrays
        self._accumulators: Dict[str, Dict[str, jax.Array]] = {}
        self._step_count = 0

    # -- lr ---------------------------------------------------------------
    def get_lr(self) -> float:
        if isinstance(self._learning_rate, LRScheduler):
            return float(self._learning_rate())
        return float(self._learning_rate)

    def set_lr(self, value: float):
        if isinstance(self._learning_rate, LRScheduler):
            raise RuntimeError(
                "cannot set_lr when the learning rate is a scheduler")
        self._learning_rate = float(value)

    def set_lr_scheduler(self, scheduler: LRScheduler):
        self._learning_rate = scheduler

    def _param_lr(self, p: Parameter) -> float:
        base = self.get_lr()
        attr = getattr(p, "optimize_attr", None)
        if attr:
            return base * attr.get("learning_rate", 1.0)
        return base

    # -- state ------------------------------------------------------------
    def _acc(self, p: Parameter, name: str, init=None) -> jax.Array:
        slot = self._accumulators.setdefault(p.name, {})
        if name not in slot:
            slot[name] = init if init is not None else \
                jnp.zeros_like(p._data)
        return slot[name]

    def _set_acc(self, p: Parameter, name: str, value):
        self._accumulators[p.name][name] = value

    def state_dict(self) -> Dict:
        state = {"_step_count": self._step_count}
        for pname, slots in self._accumulators.items():
            for sname, arr in slots.items():
                state[f"{pname}.{sname}"] = Tensor(arr)
        if isinstance(self._learning_rate, LRScheduler):
            state["LR_Scheduler"] = self._learning_rate.state_dict()
        return state

    def set_state_dict(self, state_dict: Dict):
        self._step_count = int(state_dict.get("_step_count", 0))
        if "LR_Scheduler" in state_dict and isinstance(
                self._learning_rate, LRScheduler):
            self._learning_rate.set_state_dict(state_dict["LR_Scheduler"])
        for key, val in state_dict.items():
            if key in ("_step_count", "LR_Scheduler"):
                continue
            pname, _, sname = key.rpartition(".")
            arr = val._data if isinstance(val, Tensor) else jnp.asarray(val)
            self._accumulators.setdefault(pname, {})[sname] = arr
        return self

    # -- step -------------------------------------------------------------
    def _collect_params_grads(self) -> List[Tuple[Parameter, Tensor]]:
        pg = []
        for p in self._parameter_list:
            if p.stop_gradient:
                continue
            pg.append((p, p.grad))
        return pg

    @no_grad()
    def step(self):
        params_grads = [(p, g) for p, g in self._collect_params_grads()
                        if g is not None]
        if self._grad_clip is not None:
            params_grads = self._grad_clip(params_grads)
        self._step_count += 1
        for p, g in params_grads:
            garr = g._data if isinstance(g, Tensor) else g
            if self._weight_decay and not isinstance(self, _DecoupledWD):
                wd = self._weight_decay
                if hasattr(wd, "apply"):  # L1Decay/L2Decay regularizer
                    garr = wd.apply(p._data.astype(garr.dtype), garr)
                else:
                    garr = garr + float(wd) * p._data.astype(garr.dtype)
            new_data = self._update_param(p, garr)
            p._data = new_data.astype(p._data.dtype)
            p.grad_node = None

    def _update_param(self, p: Parameter, g: jax.Array) -> jax.Array:
        raise NotImplementedError

    def clear_grad(self, set_to_zero: bool = False):
        for p in self._parameter_list:
            p.clear_gradient(set_to_zero)

    clear_gradients = clear_grad

    def minimize(self, loss, startup_program=None, parameters=None,
                 no_grad_set=None):
        if getattr(loss, "_is_lazy", False):  # static-graph Variable
            from ..static.graph import append_optimize
            if parameters is not None:
                self._parameter_list = list(parameters)
            elif not self._parameter_list:
                self._parameter_list = [
                    p for p in loss.program._parameters
                    if not p.stop_gradient]
            append_optimize(self, loss)
            return None, None
        loss.backward()
        self.step()
        self.clear_grad()
        return None, None


class _DecoupledWD:
    """Marker: weight decay applied inside the rule (AdamW-style)."""


@functools.partial(jax.jit, donate_argnums=(0,))
def _sgd_update(p, g, lr):
    return p - lr * g.astype(p.dtype)


class SGD(Optimizer):
    def __init__(self, learning_rate=0.001, parameters=None,
                 weight_decay=None, grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name)

    def _update_param(self, p, g):
        return _sgd_update(p._data, g, self._param_lr(p))


@functools.partial(jax.jit, donate_argnums=(0, 2),
                   static_argnames=("use_nesterov",))
def _momentum_update(p, g, vel, lr, mu, use_nesterov):
    g = g.astype(p.dtype)
    vel_new = mu * vel + g
    if use_nesterov:
        update = g + mu * vel_new
    else:
        update = vel_new
    return p - lr * update, vel_new


class Momentum(Optimizer):
    def __init__(self, learning_rate=0.001, momentum=0.9, parameters=None,
                 use_nesterov=False, weight_decay=None, grad_clip=None,
                 name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name)
        self._momentum = momentum
        self._use_nesterov = use_nesterov

    def _update_param(self, p, g):
        vel = self._acc(p, "velocity")
        new_p, new_vel = _momentum_update(p._data, g, vel,
                                          self._param_lr(p), self._momentum,
                                          self._use_nesterov)
        self._set_acc(p, "velocity", new_vel)
        return new_p
