"""LARS momentum and DGC (deep gradient compression) momentum.

Reference behavior:
- python/paddle/incubate/optimizer/lars_momentum.py — layer-wise trust
  ratio: local_lr = lr * lars_coeff * ||p|| / (||g|| + wd*||p|| + eps);
  v = mu*v + local_lr*(g + wd*p); p -= v. The reference lowers to the
  lars_momentum CUDA kernel; here the whole rule is one jitted XLA
  fusion per parameter.
- python/paddle/distributed/fleet/meta_optimizers/dgc_optimizer.py —
  momentum correction + top-k gradient sparsification with residual
  accumulation (Lin et al., Deep Gradient Compression). The reference
  is CUDA-only static graph; the TPU-native version keeps the DGC
  state recurrence exactly (u = m*u + g; v = v + u; send top-k of v,
  keep the rest as residual) but communicates the sparsified gradient
  as a dense masked array: on ICI there is no sparse all-reduce — the
  bandwidth win on TPU comes from an optional int8/mask encoding, while
  the OPTIMIZATION-dynamics part of DGC (what affects convergence and
  what the tests pin) is identical.

TPU-native notes: top-k thresholds come from a quantile over |v| — on
big tensors a uniform sample bounds the sort cost, matching the
reference's sampled threshold estimation
(paddle/fluid/operators/dgc_op.h uses sampling too).
"""
from __future__ import annotations

import functools
from typing import List, Optional, Sequence

import jax
import jax.numpy as jnp

from ..framework.tensor import Parameter
from .optimizer import Optimizer
from .adam import Adam

__all__ = ["LarsMomentumOptimizer", "DGCMomentumOptimizer"]


@functools.partial(jax.jit, donate_argnums=(0, 2),
                   static_argnames=("wd", "coeff", "eps", "mu",
                                    "rescale"))
def _lars_update(p, g, vel, lr, *, mu, coeff, wd, eps, rescale):
    g = g.astype(jnp.float32) * rescale
    pf = p.astype(jnp.float32)
    p_norm = jnp.linalg.norm(pf)
    g_norm = jnp.linalg.norm(g)
    local_lr = jnp.where(
        (p_norm > 0.0) & (g_norm > 0.0),
        lr * coeff * p_norm / (g_norm + wd * p_norm + eps),
        jnp.asarray(lr, jnp.float32))
    v_new = mu * vel + local_lr * (g + wd * pf)
    return pf - v_new, v_new


class LarsMomentumOptimizer(Optimizer):
    """Momentum with layer-wise adaptive rate scaling (LARS).

    API parity: paddle.incubate.optimizer.LarsMomentumOptimizer
    (lars_momentum.py:25). ``exclude_from_weight_decay`` holds name
    substrings whose parameters skip BOTH the lars weight decay and the
    trust-ratio scaling (reference kernel behavior: they fall back to
    plain momentum at the base lr).
    """

    def __init__(self, learning_rate=0.001, momentum=0.9,
                 lars_coeff=0.001, lars_weight_decay=0.0005,
                 parameter_list=None, parameters=None,
                 regularization=None, grad_clip=None, name=None,
                 exclude_from_weight_decay=None, epsilon=0.0,
                 multi_precision=False, rescale_grad=1.0):
        super().__init__(learning_rate, parameters or parameter_list,
                         regularization, grad_clip, name)
        self._momentum = momentum
        self._lars_coeff = float(lars_coeff)
        self._lars_wd = float(lars_weight_decay)
        self._eps = float(epsilon)
        self._exclude = list(exclude_from_weight_decay or [])
        self._multi_precision = multi_precision
        self._rescale = float(rescale_grad)
        self._master = Adam._master.__get__(self)
        self._store_master = Adam._store_master.__get__(self)

    def _excluded(self, p: Parameter) -> bool:
        name = getattr(p, "name", "") or ""
        return any(s in name for s in self._exclude)

    def _update_param(self, p, g):
        vel = self._acc(p, "velocity",
                        init=jnp.zeros(p._data.shape, jnp.float32))
        if self._excluded(p):
            wd, coeff = 0.0, 0.0
        else:
            wd, coeff = self._lars_wd, self._lars_coeff
        if coeff == 0.0:
            # plain momentum at base lr (reference lars kernel with
            # lars_weight_decay excluded params)
            g32 = g.astype(jnp.float32) * self._rescale
            v_new = self._momentum * vel + g32 + wd * \
                self._master(p).astype(jnp.float32)
            new_p = self._master(p).astype(jnp.float32) - \
                self._param_lr(p) * v_new
            self._set_acc(p, "velocity", v_new)
            return self._store_master(p, new_p)
        new_p, v_new = _lars_update(
            self._master(p), g, vel,
            jnp.float32(self._param_lr(p)), mu=self._momentum,
            coeff=coeff, wd=wd, eps=self._eps, rescale=self._rescale)
        self._set_acc(p, "velocity", v_new)
        return self._store_master(p, new_p)


def _dgc_threshold(absv, keep_ratio, sample_cap=1 << 18):
    """|v| magnitude threshold keeping ~keep_ratio of entries. Sampled
    quantile on big tensors (bounds the sort at sample_cap elements)."""
    flat = absv.reshape(-1)
    n = flat.shape[0]
    if n > sample_cap:
        stride = n // sample_cap
        flat = flat[:: stride]
    return jnp.quantile(flat, 1.0 - keep_ratio)


@functools.partial(jax.jit, donate_argnums=(1, 2),
                   static_argnames=("mu", "keep_ratio", "use_nesterov"))
def _dgc_step(g, u, v, *, mu, keep_ratio, use_nesterov):
    """One DGC accumulate/select: returns (sparse_grad, u', v').

    u — momentum-corrected accumulator; v — residual accumulator.
    sparse_grad is dense-masked: entries below the top-k threshold are
    zero and stay in v for later steps.
    """
    g = g.astype(jnp.float32)
    u_new = mu * u + g
    if use_nesterov:
        acc = v + g + mu * u_new
    else:
        acc = v + u_new
    thr = _dgc_threshold(jnp.abs(acc), keep_ratio)
    mask = jnp.abs(acc) >= thr
    sparse = jnp.where(mask, acc, 0.0)
    v_new = jnp.where(mask, 0.0, acc)
    u_masked = jnp.where(mask, 0.0, u_new)
    return sparse, u_masked, v_new


class DGCMomentumOptimizer(Optimizer):
    """Momentum with deep gradient compression.

    API parity: fleet/meta_optimizers/dgc_optimizer.py:32 (which the
    reference restricts to CUDA static graph; this one runs eager and
    under jit on TPU). ``sparsity`` ramps from its first entry to its
    last across ``rampup_step`` steps starting at
    ``rampup_begin_step``; before rampup begins the update is plain
    (dense) momentum, as in the reference.

    In data-parallel runs pass ``allreduce=fn`` (e.g. a psum over the
    'data' axis or distributed.all_reduce) — it is applied to the
    SPARSIFIED gradient, which is the point of DGC: the dense momentum
    phase syncs full gradients, the compressed phase syncs ~0.1%.
    """

    def __init__(self, learning_rate, momentum, rampup_begin_step,
                 rampup_step=1, sparsity: Sequence[float] = (0.999,),
                 parameter_list=None, parameters=None,
                 use_nesterov=False, num_trainers=None,
                 regularization=None, grad_clip=None, name=None,
                 allreduce=None):
        super().__init__(learning_rate, parameters or parameter_list,
                         regularization, grad_clip, name)
        assert rampup_begin_step >= 0
        self._momentum = momentum
        self._use_nesterov = bool(use_nesterov)
        self._rampup_begin = int(rampup_begin_step)
        self._rampup_step = max(1, int(rampup_step))
        self._sparsity = list(sparsity)
        self._allreduce = allreduce
        self._num_trainers = num_trainers

    def current_sparsity(self) -> float:
        """Sparsity in effect this step (0 before rampup begins)."""
        s = self._step_count
        if s < self._rampup_begin:
            return 0.0
        i = (s - self._rampup_begin) * len(self._sparsity) \
            // self._rampup_step
        return self._sparsity[min(i, len(self._sparsity) - 1)]

    def _update_param(self, p, g):
        sp = self.current_sparsity()
        lr = self._param_lr(p)
        if sp <= 0.0 or p._data.size < 2:
            vel = self._acc(p, "velocity",
                            init=jnp.zeros(p._data.shape, jnp.float32))
            g32 = g.astype(jnp.float32)
            if self._allreduce is not None:
                g32 = self._allreduce(g32)
            v_new = self._momentum * vel + g32
            upd = g32 + self._momentum * v_new if self._use_nesterov \
                else v_new
            self._set_acc(p, "velocity", v_new)
            return (p._data.astype(jnp.float32) - lr * upd) \
                .astype(p._data.dtype)
        u = self._acc(p, "_dgc_u_",
                      init=jnp.zeros(p._data.shape, jnp.float32))
        v = self._acc(p, "_dgc_v_",
                      init=jnp.zeros(p._data.shape, jnp.float32))
        sparse, u2, v2 = _dgc_step(
            g, u, v, mu=self._momentum, keep_ratio=max(1.0 - sp, 1e-4),
            use_nesterov=self._use_nesterov)
        if self._allreduce is not None:
            sparse = self._allreduce(sparse)
        self._set_acc(p, "_dgc_u_", u2)
        self._set_acc(p, "_dgc_v_", v2)
        # DGC applies the sparse momentum-corrected gradient directly;
        # its momentum lives in _dgc_u_, not the dense-phase velocity
        return (p._data.astype(jnp.float32) - lr * sparse) \
            .astype(p._data.dtype)
