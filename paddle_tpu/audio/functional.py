"""paddle_tpu.audio.functional (reference:
/root/reference/python/paddle/audio/functional/functional.py — hz_to_mel:29,
mel_to_hz:83, mel_frequencies:126, fft_frequencies:166,
compute_fbank_matrix:189, power_to_db:262, create_dct:306; window.py:396
get_window)."""
from __future__ import annotations

import math

import jax.numpy as jnp
import numpy as np

from ..framework.tensor import Tensor


def _arr(x):
    return x._data if isinstance(x, Tensor) else x


def hz_to_mel(freq, htk: bool = False):
    f = _arr(freq)
    if htk:
        out = 2595.0 * jnp.log10(1.0 + jnp.asarray(f) / 700.0) \
            if isinstance(f, (jnp.ndarray, np.ndarray)) \
            else 2595.0 * math.log10(1.0 + f / 700.0)
        return Tensor(out) if isinstance(freq, Tensor) else out
    # Slaney scale
    f_min, f_sp = 0.0, 200.0 / 3
    min_log_hz = 1000.0
    min_log_mel = (min_log_hz - f_min) / f_sp
    logstep = math.log(6.4) / 27.0
    if isinstance(freq, (int, float)):
        if freq >= min_log_hz:
            return min_log_mel + math.log(freq / min_log_hz) / logstep
        return (freq - f_min) / f_sp
    f = jnp.asarray(f)
    mels = (f - f_min) / f_sp
    mels = jnp.where(f >= min_log_hz,
                     min_log_mel + jnp.log(jnp.maximum(f, 1e-10)
                                           / min_log_hz) / logstep, mels)
    return Tensor(mels) if isinstance(freq, Tensor) else mels


def mel_to_hz(mel, htk: bool = False):
    m = _arr(mel)
    if htk:
        if isinstance(mel, (int, float)):
            return 700.0 * (10.0 ** (m / 2595.0) - 1.0)
        out = 700.0 * (10.0 ** (jnp.asarray(m) / 2595.0) - 1.0)
        return Tensor(out) if isinstance(mel, Tensor) else out
    f_min, f_sp = 0.0, 200.0 / 3
    min_log_hz = 1000.0
    min_log_mel = (min_log_hz - f_min) / f_sp
    logstep = math.log(6.4) / 27.0
    if isinstance(mel, (int, float)):
        if m >= min_log_mel:
            return min_log_hz * math.exp(logstep * (m - min_log_mel))
        return f_min + f_sp * m
    m = jnp.asarray(m)
    freqs = f_min + f_sp * m
    freqs = jnp.where(m >= min_log_mel,
                      min_log_hz * jnp.exp(logstep * (m - min_log_mel)),
                      freqs)
    return Tensor(freqs) if isinstance(mel, Tensor) else freqs


def mel_frequencies(n_mels: int = 64, f_min: float = 0.0,
                    f_max: float = 11025.0, htk: bool = False,
                    dtype="float32"):
    min_mel = hz_to_mel(float(f_min), htk=htk)
    max_mel = hz_to_mel(float(f_max), htk=htk)
    mels = jnp.linspace(min_mel, max_mel, n_mels)
    return Tensor(mel_to_hz(mels, htk=htk).astype(str(dtype)))


def fft_frequencies(sr: int, n_fft: int, dtype="float32"):
    return Tensor(jnp.linspace(0, float(sr) / 2, 1 + n_fft // 2)
                  .astype(str(dtype)))


def compute_fbank_matrix(sr: int, n_fft: int, n_mels: int = 64,
                         f_min: float = 0.0, f_max=None, htk: bool = False,
                         norm="slaney", dtype="float32"):
    """Triangular mel filterbank [n_mels, 1 + n_fft//2]."""
    if f_max is None:
        f_max = float(sr) / 2
    fftfreqs = fft_frequencies(sr, n_fft)._data
    mel_f = mel_frequencies(n_mels + 2, f_min, f_max, htk)._data
    fdiff = jnp.diff(mel_f)
    ramps = mel_f[:, None] - fftfreqs[None, :]
    lower = -ramps[:-2] / fdiff[:-1, None]
    upper = ramps[2:] / fdiff[1:, None]
    weights = jnp.maximum(0, jnp.minimum(lower, upper))
    if norm == "slaney":
        enorm = 2.0 / (mel_f[2:n_mels + 2] - mel_f[:n_mels])
        weights = weights * enorm[:, None]
    elif isinstance(norm, (int, float)):
        pn = jnp.maximum(
            jnp.sum(jnp.abs(weights) ** norm, axis=-1,
                    keepdims=True) ** (1.0 / norm), 1e-10)
        weights = weights / pn
    elif norm is not None:
        raise ValueError(f"unsupported norm {norm!r}")
    return Tensor(weights.astype(str(dtype)))


def power_to_db(spect, ref_value: float = 1.0, amin: float = 1e-10,
                top_db: float = 80.0):
    """Power spectrogram → dB (functional.py:262)."""
    if top_db is not None and top_db < 0:
        raise ValueError("top_db must be non-negative")
    if amin <= 0:
        raise ValueError("amin must be strictly positive")
    if ref_value <= 0:
        raise ValueError("ref_value must be strictly positive")

    def f(x):
        log_spec = 10.0 * jnp.log10(jnp.maximum(amin, x))
        log_spec = log_spec - 10.0 * math.log10(max(amin, ref_value))
        if top_db is not None:
            log_spec = jnp.maximum(log_spec, log_spec.max() - top_db)
        return log_spec

    if isinstance(spect, Tensor):
        from ..framework.tensor import apply_op
        return apply_op(f, spect, _op_name="power_to_db")
    return f(jnp.asarray(spect))


def create_dct(n_mfcc: int, n_mels: int, norm="ortho", dtype="float32"):
    """DCT-II matrix [n_mels, n_mfcc] (functional.py:306)."""
    n = jnp.arange(float(n_mels))
    k = jnp.arange(float(n_mfcc))[:, None]
    dct = jnp.cos(math.pi / float(n_mels) * (n + 0.5) * k)
    if norm is None:
        dct = dct * 2.0
    else:
        if norm != "ortho":
            raise ValueError("norm must be 'ortho' or None")
        ortho = jnp.full((n_mfcc,), math.sqrt(2.0 / n_mels))
        ortho = ortho.at[0].set(math.sqrt(1.0 / n_mels))
        dct = dct * ortho[:, None]
    return Tensor(dct.T.astype(str(dtype)))


# -- windows (window.py) ---------------------------------------------------

def _general_cosine(M, a, sym):
    if M <= 1:
        return jnp.ones(max(M, 0))
    if not sym:
        M = M + 1
    fac = jnp.linspace(-math.pi, math.pi, M)
    w = jnp.zeros(M)
    for k, ak in enumerate(a):
        w = w + ak * jnp.cos(k * fac)
    return w if sym or M == 1 else w[:-1]


def _window_impl(name, M, sym, **kwargs):
    name = name.lower()
    if name in ("hamming",):
        return _general_cosine(M, [0.54, 0.46], sym)
    if name in ("hann", "hanning"):
        return _general_cosine(M, [0.5, 0.5], sym)
    if name == "blackman":
        return _general_cosine(M, [0.42, 0.5, 0.08], sym)
    if name == "nuttall":
        return _general_cosine(M, [0.3635819, 0.4891775, 0.1365995,
                                   0.0106411], sym)
    if name in ("bartlett", "triang"):
        if not sym:
            M = M + 1
        n = jnp.arange(M)
        if name == "bartlett":
            w = 1.0 - jnp.abs(2.0 * n / (M - 1) - 1.0)
        else:
            # triang has no zero endpoints
            w = 1.0 - jnp.abs(2.0 * (n + 1) / (M + 1) - 1.0) \
                if M % 2 else 1.0 - jnp.abs((2 * n + 1 - M) / M)
        return w if sym else w[:-1]
    if name == "cosine":
        if not sym:
            M = M + 1
        w = jnp.sin(math.pi / M * (jnp.arange(M) + 0.5))
        return w if sym else w[:-1]
    if name == "gaussian":
        std = kwargs.get("std", 7.0)
        if not sym:
            M = M + 1
        n = jnp.arange(M) - (M - 1) / 2.0
        w = jnp.exp(-(n ** 2) / (2 * std * std))
        return w if sym else w[:-1]
    if name == "exponential":
        tau = kwargs.get("tau", 1.0)
        if not sym:
            M = M + 1
        n = jnp.abs(jnp.arange(M) - (M - 1) / 2.0)
        w = jnp.exp(-n / tau)
        return w if sym else w[:-1]
    if name == "kaiser":
        beta = kwargs.get("beta", 12.0)
        w = jnp.kaiser(M if sym else M + 1, beta)
        return w if sym else w[:-1]
    if name == "bohman":
        if not sym:
            M = M + 1
        fac = jnp.abs(jnp.linspace(-1, 1, M))
        w = (1 - fac) * jnp.cos(math.pi * fac) + \
            1.0 / math.pi * jnp.sin(math.pi * fac)
        return w if sym else w[:-1]
    raise ValueError(f"unknown window {name!r}")


def get_window(window, win_length: int, fftbins: bool = True,
               dtype="float32"):
    """Window by name, periodic by default (window.py:396)."""
    if isinstance(window, (list, tuple)):
        name, args = window[0], window[1:]
        kw = {}
        if name == "gaussian" and args:
            kw["std"] = args[0]
        elif name == "exponential" and args:
            kw["tau"] = args[-1]
        elif name == "kaiser" and args:
            kw["beta"] = args[0]
        w = _window_impl(name, win_length, not fftbins, **kw)
    else:
        w = _window_impl(window, win_length, not fftbins)
    return Tensor(w.astype(str(dtype)))
