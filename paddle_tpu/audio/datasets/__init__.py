"""paddle.audio.datasets: ESC50 / TESS audio-classification datasets.

Reference: python/paddle/audio/datasets/{dataset.py,esc50.py,tess.py} —
AudioClassificationDataset loads each wav through paddle.audio.load and
optionally extracts a feature (melspectrogram/mfcc/...), ESC50 splits
by the meta csv's fold column, TESS round-robins files into n_folds.
Same archives, URLs, md5s, label lists and split semantics here; the
download rides utils/download.get_path_from_url (file:// URLs work for
air-gapped clusters, ``archive=`` overrides the source).
"""
from __future__ import annotations

import os
from collections import namedtuple
from typing import Dict, List, Optional, Tuple

from ...io.dataset import Dataset
from ...utils.download import DATA_HOME, get_path_from_url
from ..features import MFCC, LogMelSpectrogram, MelSpectrogram, \
    Spectrogram

__all__ = ["AudioClassificationDataset", "ESC50", "TESS"]

feat_funcs = {
    "raw": None,
    "melspectrogram": MelSpectrogram,
    "mfcc": MFCC,
    "logmelspectrogram": LogMelSpectrogram,
    "spectrogram": Spectrogram,
}


class AudioClassificationDataset(Dataset):
    """Base class: (waveform-or-feature, label) records over wav files
    (reference dataset.py AudioClassificationDataset)."""

    def __init__(self, files: List[str], labels: List[int],
                 feat_type: str = "raw",
                 sample_rate: Optional[int] = None, **kwargs):
        super().__init__()
        if feat_type not in feat_funcs:
            raise RuntimeError(
                f"Unknown feat_type: {feat_type}, it must be one in "
                f"{list(feat_funcs.keys())}")
        self.files = files
        self.labels = labels
        self.feat_type = feat_type
        self.sample_rate = sample_rate
        self.feat_config = kwargs
        self._feat = None        # built once: depends only on sr+config

    def _convert_to_record(self, idx: int):
        import paddle_tpu.audio as audio

        file, label = self.files[idx], self.labels[idx]
        waveform, sample_rate = audio.load(file)
        self.sample_rate = sample_rate
        feat_cls = feat_funcs[self.feat_type]
        if waveform.ndim == 2:
            waveform = waveform.squeeze(0)  # mono: [T]
        if feat_cls is not None:
            if self._feat is None:
                # mel filterbank/window construction amortizes across
                # the epoch (same sr for a whole corpus)
                self._feat = feat_cls(sr=sample_rate,
                                      **self.feat_config)
            # [1, T] -> [1, n_feat, frames] -> [n_feat, frames]
            waveform = self._feat(waveform.unsqueeze(0)).squeeze(0)
        return waveform, label

    def __getitem__(self, idx: int):
        return self._convert_to_record(idx)

    def __len__(self) -> int:
        return len(self.files)


class ESC50(AudioClassificationDataset):
    """ESC-50: 2000 environmental recordings, 50 classes, 5 folds
    (reference esc50.py; split semantics: ``mode='train'`` takes folds
    != split, ``'dev'`` takes fold == split)."""

    archive: Dict[str, str] = {
        "url": "https://paddleaudio.bj.bcebos.com/datasets/"
               "ESC-50-master.zip",
        "md5": "7771e4b9d86d0945acce719c7a59305a",
    }
    label_list: List[str] = [
        # Animals
        "Dog", "Rooster", "Pig", "Cow", "Frog", "Cat", "Hen",
        "Insects (flying)", "Sheep", "Crow",
        # Natural soundscapes & water sounds
        "Rain", "Sea waves", "Crackling fire", "Crickets",
        "Chirping birds", "Water drops", "Wind", "Pouring water",
        "Toilet flush", "Thunderstorm",
        # Human, non-speech sounds
        "Crying baby", "Sneezing", "Clapping", "Breathing", "Coughing",
        "Footsteps", "Laughing", "Brushing teeth", "Snoring",
        "Drinking - sipping",
        # Interior/domestic sounds
        "Door knock", "Mouse click", "Keyboard typing",
        "Door - wood creaks", "Can opening", "Washing machine",
        "Vacuum cleaner", "Clock alarm", "Clock tick", "Glass breaking",
        # Exterior/urban noises
        "Helicopter", "Chainsaw", "Siren", "Car horn", "Engine",
        "Train", "Church bells", "Airplane", "Fireworks", "Hand saw",
    ]
    meta: str = os.path.join("ESC-50-master", "meta", "esc50.csv")
    audio_path: str = os.path.join("ESC-50-master", "audio")
    meta_info = namedtuple(
        "meta_info",
        ("filename", "fold", "target", "category", "esc10", "src_file",
         "take"))

    def __init__(self, mode: str = "train", split: int = 1,
                 feat_type: str = "raw",
                 archive: Optional[Dict[str, str]] = None, **kwargs):
        assert split in range(1, 6), (
            f"The selected split should be integer, and 1 <= split <= "
            f"5, but got {split}")
        if archive is not None:
            self.archive = archive
        files, labels = self._get_data(mode, split)
        super().__init__(files=files, labels=labels, feat_type=feat_type,
                         **kwargs)

    def _get_meta_info(self):
        ret = []
        with open(os.path.join(DATA_HOME, self.meta)) as rf:
            for line in rf.readlines()[1:]:
                ret.append(self.meta_info(*line.strip().split(",")))
        return ret

    def _get_data(self, mode: str,
                  split: int) -> Tuple[List[str], List[int]]:
        if not os.path.isdir(os.path.join(DATA_HOME, self.audio_path)) \
                or not os.path.isfile(os.path.join(DATA_HOME, self.meta)):
            get_path_from_url(self.archive["url"], DATA_HOME,
                              self.archive["md5"], decompress=True)
        meta_info = self._get_meta_info()
        files, labels = [], []
        for sample in meta_info:
            filename, fold, target = sample[0], sample[1], sample[2]
            if (mode == "train" and int(fold) != split) or \
                    (mode != "train" and int(fold) == split):
                files.append(os.path.join(DATA_HOME, self.audio_path,
                                          filename))
                labels.append(int(target))
        return files, labels


class TESS(AudioClassificationDataset):
    """TESS: 2800 emotional speech recordings, 7 classes (reference
    tess.py; files round-robin into ``n_folds``, ``'train'`` takes
    folds != split, ``'dev'`` takes fold == split)."""

    archive: Dict[str, str] = {
        "url": "https://bj.bcebos.com/paddleaudio/datasets/"
               "TESS_Toronto_emotional_speech_set.zip",
        "md5": "1465311b24d1de704c4c63e4ccc470c7",
    }
    label_list: List[str] = [
        "angry", "disgust", "fear", "happy", "neutral",
        "ps",  # pleasant surprise
        "sad",
    ]
    audio_path: str = "TESS_Toronto_emotional_speech_set"
    meta_info = namedtuple("meta_info", ("speaker", "word", "emotion"))

    def __init__(self, mode: str = "train", n_folds: int = 5,
                 split: int = 1, feat_type: str = "raw",
                 archive: Optional[Dict[str, str]] = None, **kwargs):
        assert isinstance(n_folds, int) and n_folds >= 1, (
            f"the n_folds should be integer and n_folds >= 1, but got "
            f"{n_folds}")
        assert split in range(1, n_folds + 1), (
            f"The selected split should be integer and should be "
            f"1 <= split <= {n_folds}, but got {split}")
        if archive is not None:
            self.archive = archive
        files, labels = self._get_data(mode, n_folds, split)
        super().__init__(files=files, labels=labels, feat_type=feat_type,
                         **kwargs)

    def _get_meta_info(self, files):
        ret = []
        for file in files:
            base = os.path.basename(file)[:-4]
            ret.append(self.meta_info(*base.split("_")))
        return ret

    def _get_data(self, mode: str, n_folds: int,
                  split: int) -> Tuple[List[str], List[int]]:
        if not os.path.isdir(os.path.join(DATA_HOME, self.audio_path)):
            get_path_from_url(self.archive["url"], DATA_HOME,
                              self.archive["md5"], decompress=True)
        wav_files = []
        for root, _, fnames in os.walk(
                os.path.join(DATA_HOME, self.audio_path)):
            for fname in sorted(fnames):
                if fname.endswith(".wav"):
                    wav_files.append(os.path.join(root, fname))
        files, labels = [], []
        for idx, sample in enumerate(self._get_meta_info(wav_files)):
            target = self.label_list.index(sample.emotion)
            fold = idx % n_folds + 1
            if (mode == "train" and fold != split) or \
                    (mode != "train" and fold == split):
                files.append(wav_files[idx])
                labels.append(target)
        return files, labels
