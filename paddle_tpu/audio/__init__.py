"""paddle_tpu.audio (reference: /root/reference/python/paddle/audio/
__init__.py — functional, features, backends (PCM16 wave I/O with
swappable backends), datasets (ESC50/TESS))."""
from . import functional  # noqa: F401
from . import features  # noqa: F401
from . import backends  # noqa: F401
from . import datasets  # noqa: F401
from .backends.backend import info, load, save  # noqa: F401
from .features import (  # noqa: F401
    MFCC, LogMelSpectrogram, MelSpectrogram, Spectrogram)

__all__ = ["functional", "features", "backends", "datasets",
           "info", "load", "save",
           "Spectrogram", "MelSpectrogram", "LogMelSpectrogram", "MFCC"]
