"""paddle_tpu.audio (reference: /root/reference/python/paddle/audio/
__init__.py — features, functional; backends/datasets are IO-bound and
delegated to paddle_tpu.io datasets)."""
from . import functional  # noqa: F401
from . import features  # noqa: F401
from .features import (  # noqa: F401
    MFCC, LogMelSpectrogram, MelSpectrogram, Spectrogram)

__all__ = ["functional", "features", "Spectrogram", "MelSpectrogram",
           "LogMelSpectrogram", "MFCC"]
