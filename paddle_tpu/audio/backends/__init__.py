"""paddle.audio.backends (reference: python/paddle/audio/backends/
__init__.py)."""
from . import backend, wave_backend  # noqa: F401
from .backend import AudioInfo  # noqa: F401
from .init_backend import (  # noqa: F401
    _init_set_audio_backend, get_current_backend,
    list_available_backends, register_backend, set_backend)

_init_set_audio_backend()

__all__ = ["AudioInfo", "get_current_backend", "list_available_backends",
           "register_backend", "set_backend", "wave_backend"]
