"""Dispatch surface for the active audio backend.

Reference: python/paddle/audio/backends/backend.py — AudioInfo plus
module-level info/load/save that init_backend.py rebinds when the
backend changes. Same shape here: ``set_backend`` swaps these three
attributes (and paddle.audio's copies) in place.
"""
from __future__ import annotations


class AudioInfo:
    """Audio info, return type of the backend ``info`` function."""

    def __init__(self, sample_rate: int, num_samples: int,
                 num_channels: int, bits_per_sample: int,
                 encoding: str) -> None:
        self.sample_rate = sample_rate
        self.num_samples = num_samples
        self.num_channels = num_channels
        self.bits_per_sample = bits_per_sample
        self.encoding = encoding

    def __repr__(self):
        return (f"AudioInfo(sample_rate={self.sample_rate}, "
                f"num_samples={self.num_samples}, "
                f"num_channels={self.num_channels}, "
                f"bits_per_sample={self.bits_per_sample}, "
                f"encoding={self.encoding!r})")


# rebound by init_backend._init_set_audio_backend / set_backend
info = None
load = None
save = None
