"""Backend selection: list/get/set, with a registration hook.

Reference: python/paddle/audio/backends/init_backend.py — the reference
discovers extra backends by importing the ``paddleaudio`` wheel; here
third-party backends register explicitly via ``register_backend`` (a
module or object exposing info/load/save), which is the same
set_backend-swaps-the-functions mechanism without the import-time
probing.
"""
from __future__ import annotations

from typing import List

from . import backend, wave_backend

_BACKENDS = {"wave_backend": wave_backend}
_current = "wave_backend"


def register_backend(name: str, module) -> None:
    """Make ``module`` (exposing info/load/save) selectable via
    :func:`set_backend`."""
    for func in ("info", "load", "save"):
        if not callable(getattr(module, func, None)):
            raise TypeError(f"backend {name!r} lacks callable {func}()")
    _BACKENDS[name] = module


def list_available_backends() -> List[str]:
    """Names accepted by :func:`set_backend` (always includes the
    built-in ``wave_backend``)."""
    return sorted(_BACKENDS)


def get_current_backend() -> str:
    """Name of the backend currently serving paddle.audio.load/save/
    info."""
    return _current


def set_backend(backend_name: str) -> None:
    """Route paddle.audio.{info,load,save} through the named backend."""
    global _current
    if backend_name not in _BACKENDS:
        raise NotImplementedError(
            f"unknown audio backend {backend_name!r}; available: "
            f"{list_available_backends()} (register_backend to add)")
    module = _BACKENDS[backend_name]
    import paddle_tpu.audio as _audio
    for func in ("save", "load", "info"):
        setattr(backend, func, getattr(module, func))
        setattr(_audio, func, getattr(module, func))
    _current = backend_name


def _init_set_audio_backend() -> None:
    for func in ("save", "load", "info"):
        setattr(backend, func, getattr(wave_backend, func))
