"""PCM16 WAV I/O via the stdlib ``wave`` module.

Reference: python/paddle/audio/backends/wave_backend.py — info/load/save
restricted to PCM16 WAV, with the same shapes, dtypes, normalize and
channels_first semantics. TPU-native note: audio files decode on the
HOST (numpy); tensors land on device only when the caller moves them —
the dataloader's device path stays the single host→HBM hop.
"""
from __future__ import annotations

import wave
from typing import BinaryIO, Optional, Tuple, Union

import numpy as np

from .backend import AudioInfo


def _error_message() -> str:
    return ("only PCM16 WAV supported by the built-in wave_backend; "
            "register a richer backend via "
            "paddle.audio.backends.register_backend(name, module) and "
            "select it with set_backend(name)")


def _open(filepath):
    """(wave.Wave_read, owned_file_obj_or_None) for a path or file."""
    file_obj = filepath if hasattr(filepath, "read") else \
        open(filepath, "rb")
    try:
        return wave.open(file_obj), file_obj
    except (wave.Error, EOFError):
        try:
            file_obj.seek(0)
        finally:
            file_obj.close()
        raise NotImplementedError(_error_message()) from None


def info(filepath: Union[str, BinaryIO]) -> AudioInfo:
    """Signal information of an audio file (PCM16 WAV)."""
    f, file_obj = _open(filepath)
    try:
        return AudioInfo(sample_rate=f.getframerate(),
                         num_samples=f.getnframes(),
                         num_channels=f.getnchannels(),
                         bits_per_sample=f.getsampwidth() * 8,
                         encoding="PCM_S")
    finally:
        file_obj.close()


def load(filepath, frame_offset: int = 0, num_frames: int = -1,
         normalize: bool = True,
         channels_first: bool = True) -> Tuple["object", int]:
    """Load audio as (Tensor, sample_rate).

    normalize=True → float32 in (-1, 1); False → raw int16 values (as
    float32, matching the reference). channels_first=True → [C, T].
    """
    from ...framework.tensor import Tensor
    import jax.numpy as jnp

    f, file_obj = _open(filepath)
    try:
        channels = f.getnchannels()
        sample_rate = f.getframerate()
        frames = f.getnframes()
        if f.getsampwidth() != 2:
            raise NotImplementedError(_error_message())
        raw = f.readframes(frames)
    finally:
        file_obj.close()
    audio = np.frombuffer(raw, dtype=np.int16).astype(np.float32)
    if normalize:
        audio = audio / float(2 ** 15)
    waveform = audio.reshape(frames, channels)
    if num_frames != -1:
        waveform = waveform[frame_offset:frame_offset + num_frames, :]
    elif frame_offset:
        waveform = waveform[frame_offset:, :]
    if channels_first:
        waveform = waveform.T
    return Tensor(jnp.asarray(waveform)), sample_rate


def save(filepath: str, src, sample_rate: int,
         channels_first: bool = True,
         encoding: Optional[str] = None,
         bits_per_sample: Optional[int] = 16) -> None:
    """Save a 2D audio tensor as PCM16 WAV."""
    arr = np.asarray(getattr(src, "_data", src))
    if arr.ndim != 2:
        raise AssertionError("Expected 2D tensor")
    if bits_per_sample not in (None, 16):
        raise ValueError("Invalid bits_per_sample, only support 16 bit")
    if channels_first:
        arr = arr.T          # -> (time, channels)
    if arr.dtype != np.int16:
        arr = (arr.astype(np.float32) * (2 ** 15)).astype("<h")
    with wave.open(filepath, "w") as f:
        f.setnchannels(arr.shape[1])
        f.setsampwidth(2)
        f.setframerate(sample_rate)
        f.writeframes(np.ascontiguousarray(arr).tobytes())
