"""paddle_tpu.audio.features (reference:
/root/reference/python/paddle/audio/features/layers.py — Spectrogram:47,
MelSpectrogram:132, LogMelSpectrogram:239, MFCC:346).

TPU-first: STFT = static frame-gather + window multiply + rfft, one XLA
graph (the reference routes through a frame op + paddle.signal.stft)."""
from __future__ import annotations

from typing import Optional

import jax.numpy as jnp

from ..framework.tensor import Tensor, apply_op
from ..nn.layer_base import Layer
from . import functional as F


def _stft_power(x, n_fft, hop_length, win, center, pad_mode, power):
    """[..., T] → [..., n_fft//2+1, n_frames] power spectrogram."""
    def f(a, w):
        if center:
            pad = [(0, 0)] * (a.ndim - 1) + [(n_fft // 2, n_fft // 2)]
            a = jnp.pad(a, pad, mode=pad_mode)
        t = a.shape[-1]
        n_frames = 1 + (t - n_fft) // hop_length
        idx = (jnp.arange(n_frames)[:, None] * hop_length
               + jnp.arange(n_fft)[None, :])
        frames = a[..., idx] * w  # [..., n_frames, n_fft]
        spec = jnp.fft.rfft(frames, axis=-1)
        mag = jnp.abs(spec)
        out = mag ** power if power != 1.0 else mag
        return jnp.swapaxes(out, -1, -2)  # [..., freq, frames]

    return apply_op(f, x, win, _op_name="stft_power")


class Spectrogram(Layer):
    """STFT power spectrogram (layers.py:47)."""

    def __init__(self, n_fft: int = 512, hop_length: Optional[int] = 512,
                 win_length: Optional[int] = None, window: str = "hann",
                 power: float = 1.0, center: bool = True,
                 pad_mode: str = "reflect", dtype: str = "float32"):
        super().__init__()
        if power <= 0:
            raise ValueError("Power of spectrogram must be > 0.")
        self.n_fft = n_fft
        self.hop_length = hop_length or n_fft // 4
        self.win_length = win_length or n_fft
        self.power = power
        self.center = center
        self.pad_mode = pad_mode
        w = F.get_window(window, self.win_length, fftbins=True,
                         dtype=dtype)
        if self.win_length < n_fft:  # center-pad window to n_fft
            lpad = (n_fft - self.win_length) // 2
            w = Tensor(jnp.pad(w._data,
                               (lpad, n_fft - self.win_length - lpad)))
        self.register_buffer("window", w)

    def forward(self, x):
        return _stft_power(x, self.n_fft, self.hop_length,
                           self._buffers["window"], self.center,
                           self.pad_mode, self.power)


class MelSpectrogram(Layer):
    """Spectrogram → mel filterbank (layers.py:132)."""

    def __init__(self, sr: int = 22050, n_fft: int = 2048,
                 hop_length: Optional[int] = 512,
                 win_length: Optional[int] = None, window: str = "hann",
                 power: float = 2.0, center: bool = True,
                 pad_mode: str = "reflect", n_mels: int = 64,
                 f_min: float = 50.0, f_max: Optional[float] = None,
                 htk: bool = False, norm="slaney", dtype: str = "float32"):
        super().__init__()
        self._spectrogram = Spectrogram(n_fft, hop_length, win_length,
                                        window, power, center, pad_mode,
                                        dtype)
        self.register_buffer(
            "fbank_matrix",
            F.compute_fbank_matrix(sr, n_fft, n_mels, f_min, f_max, htk,
                                   norm, dtype))

    def forward(self, x):
        spec = self._spectrogram(x)
        return apply_op(lambda fb, s: jnp.matmul(fb, s),
                        self._buffers["fbank_matrix"], spec,
                        _op_name="mel_fbank")


class LogMelSpectrogram(Layer):
    """Mel spectrogram in dB (layers.py:239)."""

    def __init__(self, sr: int = 22050, n_fft: int = 512,
                 hop_length: Optional[int] = None,
                 win_length: Optional[int] = None, window: str = "hann",
                 power: float = 2.0, center: bool = True,
                 pad_mode: str = "reflect", n_mels: int = 64,
                 f_min: float = 50.0, f_max: Optional[float] = None,
                 htk: bool = False, norm="slaney", ref_value: float = 1.0,
                 amin: float = 1e-10, top_db: Optional[float] = None,
                 dtype: str = "float32"):
        super().__init__()
        self._melspectrogram = MelSpectrogram(
            sr, n_fft, hop_length, win_length, window, power, center,
            pad_mode, n_mels, f_min, f_max, htk, norm, dtype)
        self.ref_value = ref_value
        self.amin = amin
        self.top_db = top_db

    def forward(self, x):
        mel = self._melspectrogram(x)
        return F.power_to_db(mel, self.ref_value, self.amin, self.top_db)


class MFCC(Layer):
    """Mel-frequency cepstral coefficients (layers.py:346)."""

    def __init__(self, sr: int = 22050, n_mfcc: int = 40, n_fft: int = 512,
                 hop_length: Optional[int] = None,
                 win_length: Optional[int] = None, window: str = "hann",
                 power: float = 2.0, center: bool = True,
                 pad_mode: str = "reflect", n_mels: int = 64,
                 f_min: float = 50.0, f_max: Optional[float] = None,
                 htk: bool = False, norm="slaney", ref_value: float = 1.0,
                 amin: float = 1e-10, top_db: Optional[float] = None,
                 dtype: str = "float32"):
        super().__init__()
        self._log_melspectrogram = LogMelSpectrogram(
            sr, n_fft, hop_length, win_length, window, power, center,
            pad_mode, n_mels, f_min, f_max, htk, norm, ref_value, amin,
            top_db, dtype)
        self.register_buffer("dct_matrix",
                             F.create_dct(n_mfcc, n_mels, dtype=dtype))

    def forward(self, x):
        logmel = self._log_melspectrogram(x)
        return apply_op(
            lambda d, m: jnp.swapaxes(
                jnp.matmul(jnp.swapaxes(m, -1, -2), d), -1, -2),
            self._buffers["dct_matrix"], logmel, _op_name="mfcc_dct")
