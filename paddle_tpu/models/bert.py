"""BERT / ERNIE model family.

Reference shape: the reference trains BERT-base DP and ERNIE-3.0
finetune as flagship configs (BASELINE.md configs[1]/[3]); model code in
its ecosystem lives in PaddleNLP, but the framework-side contract is the
transformer layer stack (python/paddle/nn/layer/transformer.py) these
models compose. Built entirely from this framework's nn layers so the
whole family runs eagerly, under jit.to_static, and under
dist.to_static/DistModel with GSPMD shardings.

ERNIE (1.0/2.0-style) shares the BERT architecture with different
pretraining objectives; ``ErnieModel`` reuses the encoder with the
task-type embedding ERNIE adds.
"""
from __future__ import annotations

import dataclasses
from typing import Optional

import numpy as np

from ..framework.tensor import Tensor
from ..nn import functional as F
from ..nn.layer_base import Layer
from ..nn.layer.common import Dropout, Embedding, Linear
from ..nn.layer.norm import LayerNorm
from ..nn.layer.transformer import (TransformerEncoder,
                                    TransformerEncoderLayer)

__all__ = ["BertConfig", "BertModel", "BertPooler",
           "BertForPretraining", "BertForSequenceClassification",
           "bert_base", "bert_large", "ErnieModel",
           "ErnieForSequenceClassification"]


@dataclasses.dataclass
class BertConfig:
    vocab_size: int = 30522
    hidden_size: int = 768
    num_hidden_layers: int = 12
    num_attention_heads: int = 12
    intermediate_size: int = 3072
    hidden_act: str = "gelu"
    hidden_dropout_prob: float = 0.1
    attention_probs_dropout_prob: float = 0.1
    max_position_embeddings: int = 512
    type_vocab_size: int = 2
    layer_norm_eps: float = 1e-12
    use_task_id: bool = False  # ERNIE task-type embedding
    task_type_vocab_size: int = 3


class BertEmbeddings(Layer):
    def __init__(self, cfg: BertConfig):
        super().__init__()
        self.word_embeddings = Embedding(cfg.vocab_size, cfg.hidden_size)
        self.position_embeddings = Embedding(cfg.max_position_embeddings,
                                             cfg.hidden_size)
        self.token_type_embeddings = Embedding(cfg.type_vocab_size,
                                               cfg.hidden_size)
        if cfg.use_task_id:
            self.task_type_embeddings = Embedding(
                cfg.task_type_vocab_size, cfg.hidden_size)
        self.layer_norm = LayerNorm(cfg.hidden_size,
                                    epsilon=cfg.layer_norm_eps)
        self.dropout = Dropout(cfg.hidden_dropout_prob)
        self._use_task_id = cfg.use_task_id

    def forward(self, input_ids, token_type_ids=None, position_ids=None,
                task_type_ids=None):
        from ..ops.creation import arange, zeros_like
        b, t = input_ids.shape
        if position_ids is None:
            position_ids = arange(t, dtype="int64").unsqueeze(0)
        if token_type_ids is None:
            token_type_ids = zeros_like(input_ids)
        x = (self.word_embeddings(input_ids)
             + self.position_embeddings(position_ids)
             + self.token_type_embeddings(token_type_ids))
        if self._use_task_id:
            if task_type_ids is None:
                task_type_ids = zeros_like(input_ids)
            x = x + self.task_type_embeddings(task_type_ids)
        return self.dropout(self.layer_norm(x))


class BertPooler(Layer):
    def __init__(self, hidden_size: int):
        super().__init__()
        self.dense = Linear(hidden_size, hidden_size)

    def forward(self, hidden_states):
        return F.tanh(self.dense(hidden_states[:, 0]))


class BertModel(Layer):
    """Encoder: embeddings -> TransformerEncoder -> (sequence, pooled)."""

    def __init__(self, cfg: BertConfig):
        super().__init__()
        self.config = cfg
        self.embeddings = BertEmbeddings(cfg)
        enc_layer = TransformerEncoderLayer(
            cfg.hidden_size, cfg.num_attention_heads,
            cfg.intermediate_size, dropout=cfg.hidden_dropout_prob,
            activation=cfg.hidden_act,
            attn_dropout=cfg.attention_probs_dropout_prob,
            layer_norm_eps=cfg.layer_norm_eps)
        self.encoder = TransformerEncoder(enc_layer,
                                          cfg.num_hidden_layers)
        self.pooler = BertPooler(cfg.hidden_size)

    def forward(self, input_ids, token_type_ids=None, position_ids=None,
                attention_mask=None, task_type_ids=None):
        if attention_mask is not None and attention_mask.ndim == 2:
            # [B, T] 1/0 mask -> additive [B, 1, 1, T]
            neg = (1.0 - attention_mask.astype("float32")) * -1e4
            attention_mask = neg.unsqueeze(1).unsqueeze(1)
        x = self.embeddings(input_ids, token_type_ids, position_ids,
                            task_type_ids)
        seq = self.encoder(x, attention_mask)
        return seq, self.pooler(seq)


class BertForPretraining(Layer):
    """MLM + NSP heads (the BERT-base pretraining config)."""

    def __init__(self, cfg: BertConfig):
        super().__init__()
        self.bert = BertModel(cfg)
        self.transform = Linear(cfg.hidden_size, cfg.hidden_size)
        self.transform_norm = LayerNorm(cfg.hidden_size,
                                        epsilon=cfg.layer_norm_eps)
        self.nsp_head = Linear(cfg.hidden_size, 2)
        self.config = cfg

    def forward(self, input_ids, token_type_ids=None, position_ids=None,
                attention_mask=None):
        seq, pooled = self.bert(input_ids, token_type_ids, position_ids,
                                attention_mask)
        h = self.transform_norm(F.gelu(self.transform(seq)))
        # decoder tied to word embeddings (BERT weight tying)
        from ..ops.linalg import matmul
        mlm_logits = matmul(
            h, self.bert.embeddings.word_embeddings.weight,
            transpose_y=True)
        nsp_logits = self.nsp_head(pooled)
        return mlm_logits, nsp_logits

    def loss(self, input_ids, mlm_labels, nsp_labels=None,
             token_type_ids=None, attention_mask=None,
             ignore_index: int = -100):
        mlm_logits, nsp_logits = self(input_ids, token_type_ids,
                                      attention_mask=attention_mask)
        V = self.config.vocab_size
        mlm = F.cross_entropy(mlm_logits.reshape([-1, V]),
                              mlm_labels.reshape([-1]),
                              ignore_index=ignore_index)
        if nsp_labels is None:
            return mlm
        nsp = F.cross_entropy(nsp_logits, nsp_labels.reshape([-1]))
        return mlm + nsp


class BertForSequenceClassification(Layer):
    def __init__(self, cfg: BertConfig, num_classes: int = 2,
                 dropout: Optional[float] = None):
        super().__init__()
        self.bert = BertModel(cfg)
        self.dropout = Dropout(cfg.hidden_dropout_prob
                               if dropout is None else dropout)
        self.classifier = Linear(cfg.hidden_size, num_classes)

    def forward(self, input_ids, token_type_ids=None, position_ids=None,
                attention_mask=None):
        _, pooled = self.bert(input_ids, token_type_ids, position_ids,
                              attention_mask)
        return self.classifier(self.dropout(pooled))


def bert_base(**kwargs) -> BertConfig:
    return BertConfig(**kwargs)


def bert_large(**kwargs) -> BertConfig:
    kwargs.setdefault("hidden_size", 1024)
    kwargs.setdefault("num_hidden_layers", 24)
    kwargs.setdefault("num_attention_heads", 16)
    kwargs.setdefault("intermediate_size", 4096)
    return BertConfig(**kwargs)


class ErnieModel(BertModel):
    """ERNIE encoder = BERT encoder + task-type embedding."""

    def __init__(self, cfg: Optional[BertConfig] = None, **kwargs):
        if cfg is None:
            kwargs.setdefault("use_task_id", True)
            cfg = BertConfig(**kwargs)
        super().__init__(cfg)


class ErnieForSequenceClassification(BertForSequenceClassification):
    def __init__(self, cfg: Optional[BertConfig] = None,
                 num_classes: int = 2,
                 dropout: Optional[float] = None, **kwargs):
        if cfg is None:
            kwargs.setdefault("use_task_id", True)
            cfg = BertConfig(**kwargs)
        # ErnieModel(cfg) == BertModel(cfg) once use_task_id is in the
        # config, so the parent-built encoder is already the ERNIE one
        super().__init__(cfg, num_classes, dropout)
