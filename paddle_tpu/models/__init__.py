"""Flagship model families (GPT for causal LM, BERT/ERNIE encoders)."""
from . import bert  # noqa: F401
from . import llama  # noqa: F401
from . import gpt  # noqa: F401
from .bert import (BertConfig, BertForPretraining,  # noqa: F401
                   BertForSequenceClassification, BertModel, ErnieModel,
                   ErnieForSequenceClassification, bert_base, bert_large)
from .gpt import (GPTConfig, GPTForCausalLM, GPTModel,  # noqa: F401
                  GPTSpmdTrainer, build_mesh)
from .llama import (LlamaConfig, LlamaForCausalLM,  # noqa: F401
                    LlamaModel, llama_tiny_config)
