"""GPT model family — the flagship LLM stack.

Two implementations, by design:

1. ``GPTModel``/``GPTForCausalLM`` — imperative ``nn.Layer`` model built
   from the fleet TP layer library (VocabParallelEmbedding /
   Column/RowParallelLinear), the analog of the reference's fleet GPT
   (test/auto_parallel/hybrid_strategy/semi_auto_llama.py is the shape of
   this). Runs eagerly, under to_static, and under GSPMD meshes.

2. ``GPTSpmdTrainer`` — the performance path: a single jitted training
   step over a ('pipe','data','fsdp','sep','model') mesh composing
   - tp:   head/ffn dims sharded over 'model' (Megatron partitioning),
   - sp:   activation seq dim sharded over 'sep' (q local, k/v gathered),
   - dp:   batch over 'data',
   - fsdp: weight hidden-dim sharded over 'fsdp' (ZeRO-3; XLA gathers at
           use and reduce-scatters grads),
   - pp:   stage-stacked blocks pipelined via
           distributed.pipeline.pipeline_forward (scan + ppermute),
   with bf16 compute, fp32 master params/optimizer state, remat per block.
   This is what the reference needs its entire fleet/meta_parallel +
   pipeline-pass + sharding-pass machinery for (SURVEY.md §2.2 P2-P10);
   here it is ~300 lines because the mesh does the orchestration.
"""
from __future__ import annotations

import dataclasses
import math
from functools import partial
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.ad_checkpoint import checkpoint_name
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..nn import functional as F
from ..nn.layer_base import Layer
from ..nn.layer.common import Dropout, Embedding, Linear
from ..nn.layer.norm import LayerNorm
from ..nn.layer.container import LayerList
from ..framework.tensor import Tensor, apply_op
from ._decode_cache import (cache_attend, check_cache_pos,
                            paged_cache_attend)

__all__ = ["GPTConfig", "GPTModel", "GPTForCausalLM", "GPTSpmdTrainer",
           "build_mesh", "tp_param_spec"]


# Tensor-parallel SERVING shard rules for the imperative GPT family
# (GPTForCausalLM.raw_state() names). Output-dim-only, same contract
# as models/llama.tp_param_spec: shards only non-contracted dims so
# sharded decode stays bitwise token-identical to single-chip (fc1
# stays replicated — sharding it would turn fc2's contraction into a
# float-reassociating psum). The fused qkv output and its bias shard
# along 3*H*D; the tied wte shards over vocab (it is both the
# embedding table and the logits head's rhs, contracted over hidden).
_TP_OUT_DIM = ("qkv.weight", "proj.weight", "fc2.weight")
_TP_OUT_BIAS = ("qkv.bias", "proj.bias", "fc2.bias")


def tp_param_spec(name: str, shape, tp: int, axis: str = "model"):
    """PartitionSpec for one ``raw_state()`` param under the serving
    engine's tensor-parallel mesh, or None for replicated (see
    models/llama.tp_param_spec — same contract)."""
    if tp <= 1:
        return None
    if name.endswith(_TP_OUT_DIM) and len(shape) == 2 \
            and shape[-1] % tp == 0:
        return P(None, axis)
    if name.endswith(_TP_OUT_BIAS) and len(shape) == 1 \
            and shape[0] % tp == 0:
        return P(axis)
    if name.endswith("wte.weight") and len(shape) == 2 \
            and shape[0] % tp == 0:
        return P(axis, None)
    return None


@dataclasses.dataclass
class GPTConfig:
    vocab_size: int = 50304
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    max_seq_len: int = 1024
    ffn_mult: int = 4
    dropout: float = 0.0
    tie_embeddings: bool = True
    dtype: Any = jnp.bfloat16

    @property
    def head_dim(self):
        return self.hidden_size // self.num_heads

    @property
    def ffn_size(self):
        return self.hidden_size * self.ffn_mult


# ---------------------------------------------------------------------------
# 1) imperative model (TP-aware via fleet layers when a mesh is set)
# ---------------------------------------------------------------------------

class GPTBlock(Layer):
    def __init__(self, cfg: GPTConfig, use_tp: bool = False):
        super().__init__()
        self.cfg = cfg
        self.ln1 = LayerNorm(cfg.hidden_size)
        self.ln2 = LayerNorm(cfg.hidden_size)
        if use_tp:
            from ..distributed.fleet.mp_layers import (ColumnParallelLinear,
                                                       RowParallelLinear)
            self.qkv = ColumnParallelLinear(cfg.hidden_size,
                                            3 * cfg.hidden_size,
                                            gather_output=False)
            self.proj = RowParallelLinear(cfg.hidden_size, cfg.hidden_size,
                                          input_is_parallel=True)
            self.fc1 = ColumnParallelLinear(cfg.hidden_size, cfg.ffn_size,
                                            gather_output=False)
            self.fc2 = RowParallelLinear(cfg.ffn_size, cfg.hidden_size,
                                         input_is_parallel=True)
        else:
            self.qkv = Linear(cfg.hidden_size, 3 * cfg.hidden_size)
            self.proj = Linear(cfg.hidden_size, cfg.hidden_size)
            self.fc1 = Linear(cfg.hidden_size, cfg.ffn_size)
            self.fc2 = Linear(cfg.ffn_size, cfg.hidden_size)
        self.drop = Dropout(cfg.dropout)

    def forward(self, x, cache=None):
        """cache: optional (k_cache [b, Tmax, H, D], v_cache, pos) — the
        fixed-buffer serving decode path (mirrors llama's static cache;
        pos is a scalar or a per-row [b] vector of write positions).
        Returns (out, cache') when given."""
        b, t, d = x.shape
        h = self.ln1(x)
        qkv = self.qkv(h)
        n_local = qkv.shape[-1] // (3 * self.cfg.head_dim)
        qkv = qkv.reshape([b, t, 3, n_local, self.cfg.head_dim])
        q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
        new_cache = None
        if cache is not None and len(cache) in (6, 7):
            # paged pool flavor (see llama._forward_static_cache):
            # (k_pool, v_pool, k_scale, v_scale, page_table, pos);
            # the 7-tuple appends a per-row write length `wlen` — the
            # speculative VERIFY flavor (masked writes -> trash page)
            if len(cache) == 7:
                kp, vp, ksc, vsc, table, pos, wlen = cache
            else:
                kp, vp, ksc, vsc, table, pos = cache
                wlen = None
            # t=1: bucket-padded extend writes past the table are
            # legal (trash-redirected); only the start pos is checked
            check_cache_pos(pos, 1, table.shape[1] * kp.shape[1])
            out_dtype = getattr(x, "_data", x).dtype
            has_wl = wlen is not None

            def fp(q, k, v, kp, vp, table, p, *rest):
                if has_wl:
                    wl, rest = jnp.asarray(rest[0], jnp.int32), rest[1:]
                else:
                    wl = None
                ks, vs = rest if rest else (None, None)
                out, kp2, vp2, ks2, vs2 = paged_cache_attend(
                    q, k, v, kp, vp, ks, vs, table,
                    jnp.asarray(p, jnp.int32), jnp.dtype(out_dtype),
                    wlen=wl)
                return (out, kp2, vp2, ks2, vs2) if rest \
                    else (out, kp2, vp2)

            args = (q, k, v, kp, vp, table, pos) \
                + ((wlen,) if has_wl else ()) \
                + ((ksc, vsc) if ksc is not None else ())
            res = apply_op(fp, *args,
                           _op_name="gpt_paged_cache_attn")
            if ksc is not None:
                attn, kp2, vp2, ks2, vs2 = res
            else:
                (attn, kp2, vp2), ks2, vs2 = res, None, None
            new_cache = (kp2, vp2, ks2, vs2, table, pos + t)
        elif cache is not None:
            if len(cache) == 4:     # speculative VERIFY flavor
                k_cache, v_cache, pos, wlen = cache
            else:
                k_cache, v_cache, pos = cache
                wlen = None
            # verify writes past the buffer are index-dropped, so only
            # the start position is checked on that flavor
            per_row = check_cache_pos(
                pos, 1 if wlen is not None else t, k_cache.shape[1])

            def f(q, k, v, kc, vc, p, *rest):
                wl = jnp.asarray(rest[0], jnp.int32) if rest else None
                return cache_attend(q, k, v, kc, vc,
                                    jnp.asarray(p, jnp.int32), per_row,
                                    wlen=wl)

            args = (q, k, v, k_cache, v_cache, pos) \
                + ((wlen,) if wlen is not None else ())
            attn, kc2, vc2 = apply_op(f, *args,
                                      _op_name="gpt_static_cache_attn")
            new_cache = (kc2, vc2, pos + t)
        else:
            attn = F.scaled_dot_product_attention(
                q, k, v, is_causal=True, training=self.training)
            attn = attn.reshape([b, t, n_local * self.cfg.head_dim])
        # ONE tail for both paths: the engine's token-parity guarantee
        # rides on cached and uncached decode sharing these exact ops
        x = x + self.drop(self.proj(attn))
        h = self.ln2(x)
        x = x + self.drop(self.fc2(F.gelu(self.fc1(h), approximate=True)))
        return x if new_cache is None else (x, new_cache)


class GPTModel(Layer):
    def __init__(self, cfg: GPTConfig, use_tp: bool = False):
        super().__init__()
        self.cfg = cfg
        if use_tp:
            from ..distributed.fleet.mp_layers import VocabParallelEmbedding
            self.wte = VocabParallelEmbedding(cfg.vocab_size,
                                              cfg.hidden_size)
        else:
            self.wte = Embedding(cfg.vocab_size, cfg.hidden_size)
        self.wpe = Embedding(cfg.max_seq_len, cfg.hidden_size)
        self.blocks = LayerList([GPTBlock(cfg, use_tp)
                                 for _ in range(cfg.num_layers)])
        self.ln_f = LayerNorm(cfg.hidden_size)

    def forward(self, input_ids, caches=None):
        b, t = input_ids.shape
        from ..ops.creation import arange
        if caches is not None:
            # serving decode: learned positions come from the cache's
            # write position (scalar, or per-row for the slot pool);
            # pos is the LAST element of the contiguous 3-tuple and
            # paged 6-tuple flavors, second-to-last in the speculative
            # VERIFY flavors (4/7-tuples, which append `wlen`)
            verify = len(caches[0]) in (4, 7)
            base = caches[0][-2] if verify else caches[0][-1]

            def mk_pos(p):
                p = jnp.asarray(p, jnp.int32)
                ar = jnp.arange(t, dtype=jnp.int32)
                out = p[:, None] + ar[None, :] if p.ndim >= 1 \
                    else (p + ar)[None, :]
                if verify:
                    # rows near their cap may run p + t past the wpe
                    # table; those positions are write-masked anyway —
                    # clip so the embedding gather stays in range
                    out = jnp.minimum(out, self.cfg.max_seq_len - 1)
                return out

            positions = apply_op(mk_pos, base, _op_name="gpt_cache_pos")
            x = self.wte(input_ids) + self.wpe(positions)
            new_caches = []
            for blk, c in zip(self.blocks, caches):
                x, nc = blk(x, c)
                new_caches.append(nc)
            return self.ln_f(x), new_caches
        pos = arange(t, dtype="int64").unsqueeze(0)
        x = self.wte(input_ids) + self.wpe(pos)
        for blk in self.blocks:
            x = blk(x)
        return self.ln_f(x)


class GPTForCausalLM(Layer):
    def __init__(self, cfg: GPTConfig, use_tp: bool = False):
        super().__init__()
        self.cfg = cfg
        self.gpt = GPTModel(cfg, use_tp)
        if not cfg.tie_embeddings:
            self.lm_head = Linear(cfg.hidden_size, cfg.vocab_size,
                                  bias_attr=False)

    def forward(self, input_ids, caches=None):
        if caches is not None:
            h, new_caches = self.gpt(input_ids, caches=caches)
            return self._head(h), new_caches
        return self._head(self.gpt(input_ids))

    def _head(self, h):
        if self.cfg.tie_embeddings:
            from ..ops.linalg import matmul
            return matmul(h, self.gpt.wte.weight, transpose_y=True)
        return self.lm_head(h)

    def loss(self, input_ids, labels):
        logits = self(input_ids)
        return F.cross_entropy(
            logits.reshape([-1, self.cfg.vocab_size]),
            labels.reshape([-1]))


# ---------------------------------------------------------------------------
# 2) SPMD trainer: one jitted step over the full hybrid mesh
# ---------------------------------------------------------------------------

AXES = ("pipe", "data", "fsdp", "sep", "model")

# Sharding specs of the stacked [S, L, ...] blocks leaves (mirrors
# _init_params); the per-layer pytree layout (layer_unroll="full")
# re-places each unstacked leaf with the tail of the same spec.
_BLOCK_SPECS = {
    "ln1_g": ("pipe", None, None), "ln1_b": ("pipe", None, None),
    "ln2_g": ("pipe", None, None), "ln2_b": ("pipe", None, None),
    "wqkv": ("pipe", None, "fsdp", "model"),
    "bqkv": ("pipe", None, "model"),
    "wproj": ("pipe", None, "model", "fsdp"),
    "bproj": ("pipe", None, None),
    "win": ("pipe", None, "fsdp", "model"),
    "bin": ("pipe", None, "model"),
    "wout": ("pipe", None, "model", "fsdp"),
    "bout": ("pipe", None, None),
    "wg": ("pipe", None, None, None),
    "w_in": ("pipe", None, "data", "fsdp", "model"),
    "b_in": ("pipe", None, "data", "model"),
    "w_out": ("pipe", None, "data", "model", "fsdp"),
    "b_out": ("pipe", None, "data", None),
}


def build_mesh(n_devices: Optional[int] = None,
               pipe: int = 1, data: Optional[int] = None, fsdp: int = 1,
               sep: int = 1, model: int = 1) -> Mesh:
    """Mesh over the hybrid axes; 'data' absorbs the remainder."""
    devices = jax.devices()
    n = n_devices or len(devices)
    fixed = pipe * fsdp * sep * model
    if data is None:
        if n % fixed != 0:
            raise ValueError(f"{n} devices not divisible by {fixed}")
        data = n // fixed
    shape = (pipe, data, fsdp, sep, model)
    return Mesh(np.asarray(devices[:int(np.prod(shape))]).reshape(shape),
                AXES)


def _spec(mesh: Mesh, *entries) -> NamedSharding:
    return NamedSharding(mesh, P(*entries))


class GPTSpmdTrainer:
    # class-level defaults so __new__-built instances (AOT tests) and
    # hot paths see consistent attributes without per-site guards
    lr_schedule = None
    ce_int8 = False
    int8_guard_period = 0
    int8_guard_threshold = 0.10
    _unroll_full = False
    fuse_bwd_colq = False
    _host_step = 0
    _guard_fn = None
    _guard_events = ()   # __init__ replaces with a per-instance list

    """Functional GPT pretraining step, fully sharded.

    Parameter shardings (fp32 masters; bf16 cast inside the step):
      wte [V, D]          ('model', 'fsdp')  — vocab-parallel embedding
      wpe [T, D]          (None, 'fsdp')
      blocks (stacked [S, Lps, ...], S over 'pipe'):
        wqkv [S,Lps,D,3D]  ('pipe', None, 'fsdp', 'model')
        wproj [S,Lps,D,D]  ('pipe', None, 'model', 'fsdp')
        win  [S,Lps,D,F]   ('pipe', None, 'fsdp', 'model')
        wout [S,Lps,F,D]   ('pipe', None, 'model', 'fsdp')
        ln scales/biases   ('pipe', None, None)
      with moe_experts=E, win/bin/wout/bout are replaced by:
        wg    [S,Lps,D,E]    ('pipe', None, None, None)  — gate
        w_in  [S,Lps,E,D,F]  ('pipe', None, 'data', 'fsdp', 'model')
        b_in  [S,Lps,E,F]    ('pipe', None, 'data', 'model')
        w_out [S,Lps,E,F,D]  ('pipe', None, 'data', 'model', 'fsdp')
        b_out [S,Lps,E,D]    ('pipe', None, 'data', None)
        (experts sharded over 'data' = expert parallelism)
      ln_f [D]            (None,)
    Activations: (batch='data', seq='sep') with q-local/kv-gathered
    attention (Megatron-SP over 'sep').
    """

    def __init__(self, cfg: GPTConfig, mesh: Mesh,
                 microbatches: Optional[int] = None,
                 learning_rate: float = 3e-4, weight_decay: float = 0.1,
                 beta1: float = 0.9, beta2: float = 0.95,
                 grad_clip: float = 1.0, seed: int = 0,
                 use_flash: Optional[bool] = None,
                 remat: bool = True,
                 mixed_precision: bool = True,
                 moment_dtype: Any = jnp.float32,
                 master_dtype: Any = jnp.float32,
                 quant8: bool = False,
                 pipeline_schedule: str = "gpipe",
                 vpp_chunks: int = 2,
                 moe_experts: int = 0,
                 moe_capacity_factor: float = 1.25,
                 moe_aux_weight: float = 1e-2,
                 fused_optimizer: Optional[bool] = None,
                 moment8: bool = False,
                 layer_unroll: int = 1,
                 ce_chunks: int = 16,
                 ce_int8: bool = False,
                 fuse_gelu_quant: Optional[bool] = None,
                 fuse_ln_quant: Optional[bool] = None,
                 fuse_bwd_colq: Optional[bool] = None,
                 lr_schedule=None,
                 int8_guard_period: int = 0,
                 int8_guard_threshold: float = 0.10):
        self.cfg = cfg
        self.mesh = mesh
        self.remat = remat  # per-block activation checkpointing
        # AMP-O2 contract (reference python/paddle/amp/auto_cast.py O2
        # `decorate`): compute/grads in cfg.dtype, fp32 master params in
        # the optimizer. Grads materialize at cfg.dtype (half the HBM of
        # fp32 grads), masters+update stay fp32.
        self.mixed_precision = mixed_precision
        # AdamW moment storage dtype; bf16 moments let ~1.3B params fit
        # a single 16G chip (update math still fp32)
        self.moment_dtype = moment_dtype
        # Master-weight storage dtype. fp32 = classic AMP-O2 masters.
        # bf16 = store masters AT compute precision and apply the AdamW
        # update with stochastic rounding (update math in fp32, the
        # rounding noise is unbiased so tiny updates accumulate in
        # expectation — the bf16+SR training recipe). Halves master HBM
        # and removes the per-step master->compute cast entirely, which
        # is what frees enough HBM for save_dots remat at 1.3B/16G.
        self.master_dtype = master_dtype
        self._stoch_round = (jnp.dtype(master_dtype) == jnp.bfloat16)
        # int8 MXU forward for the wide block matmuls (qkv/ffn), exact
        # bf16 backward — ~2x MXU rate on v5e (ops/quant_matmul.py).
        # quant8="dgrad" additionally runs the activation gradient on
        # the int8 MXU (wgrad stays exact bf16). quant8="wgrad" runs
        # ALL THREE matmuls int8 — the weight gradient quantizes with
        # stochastic rounding along the token axis, which keeps it
        # unbiased so Adam's moments integrate the noise to zero
        # (ops/quant_matmul.int8_linear_all8); SR streams are seeded
        # per (step, layer, site) from the optimizer step counter.
        self.quant8 = quant8
        # lr_schedule: traced fn step_f32 -> multiplier on the base lr
        # (cosine decay etc.); costs nothing — the multiplier rides the
        # fused kernel's scalar vector.
        self.lr_schedule = lr_schedule
        # int8 drift guard: every `period` steps measure the relative
        # dgrad error of the int8 path on ONE layer-0 matmul (~1% of a
        # step); if it exceeds the threshold, fall back one quant tier
        # (wgrad -> dgrad -> exact) and recompile the step. Exists
        # because the 500-step parity runs end with wqkv SNR ~1 — the
        # default is earned, but nothing should drift unwatched.
        self.int8_guard_period = int(int8_guard_period)
        self.int8_guard_threshold = float(int8_guard_threshold)
        if self.int8_guard_period and mesh.shape.get("pipe", 1) > 1:
            # the probe indexes blocks leaves as [S, L, ...][0, 0];
            # pipelined/VPP layouts need their own probe — refuse
            # loudly rather than crash inside the jitted probe
            raise ValueError(
                "int8_guard_period requires a single-stage mesh "
                "(pipe=1)")
        self._guard_fn = None
        self._guard_events = []
        self._host_step = 0
        if quant8 == "wgrad" and mesh.shape.get("pipe", 1) > 1:
            # the pipeline paths do not thread the per-step SR seed;
            # running them would silently reuse one stream every step —
            # exactly the data-correlated bias SR exists to remove
            raise ValueError(
                "quant8='wgrad' supports single-stage meshes (pipe=1); "
                "pipeline schedules keep wgrad exact (use 'dgrad')")
        # pp schedule: "gpipe" = autodiff'd scan+ppermute forward
        # (F-then-B); "1f1b" = explicit on-device 1F1B train schedule
        # (distributed/pipeline.pipeline_train_1f1b) with O(S) instead
        # of O(M) in-flight activations per stage; "vpp" = interleaved
        # virtual-pipeline (each rank holds vpp_chunks model chunks —
        # fill bubble shrinks by 1/V) and "zb" = ZeroBubble ZB-H1
        # (backward split into input-grad and weight-grad jobs, W fills
        # the cooldown bubble) — both execute their job tables on
        # device via distributed/pipeline_scheduled.py
        aliases = {"fthenb": "gpipe", "zero_bubble": "zb",
                   "interleaved": "vpp"}
        pipeline_schedule = aliases.get(pipeline_schedule,
                                        pipeline_schedule)
        if pipeline_schedule not in ("gpipe", "1f1b", "vpp", "zb"):
            raise ValueError(f"unknown pipeline_schedule "
                             f"{pipeline_schedule!r}")
        self.pipeline_schedule = pipeline_schedule
        # chunked params only make sense with a pipe axis: with pipe=1
        # every schedule degenerates to the plain forward, which
        # consumes unchunked [S=1, L, ...] stage params
        self.V = int(vpp_chunks) if (pipeline_schedule == "vpp"
                                     and mesh.shape["pipe"] > 1) else 1
        # MoE-FFN variant: E experts per block, GShard top-2 dispatch,
        # experts sharded over the 'data' mesh axis (expert parallelism
        # — the dispatch/combine einsums lower to the all-to-all pair
        # the reference's global_scatter/global_gather implement by
        # hand, moe_layer.py:263); the load-balance aux loss is
        # accumulated through the layer scan and added to the CE loss.
        self.moe_experts = int(moe_experts)
        self.moe_capacity_factor = moe_capacity_factor
        self.moe_aux_weight = moe_aux_weight
        # single-pass Pallas AdamW (ops/fused_adamw.py): one kernel per
        # leaf reads p/g/m/v and writes p/m/v with in-kernel SR random
        # bits — 14 bytes/param of HBM traffic vs ~26 for the XLA
        # multi-pass schedule. Only meaningful on a real TPU; the
        # unsharded leaves the kernel needs exist when no mesh axis
        # shards params in ways the 2-D collapse can't see, so gate to
        # single-device meshes (GSPMD partitions pallas_call manually
        # sharded kernels poorly).
        if fused_optimizer is None:
            fused_optimizer = (jax.default_backend() in ("tpu", "axon")
                               and mesh.size == 1)
        self.fused_optimizer = fused_optimizer
        # int8 moment storage for fused-eligible leaves (round-5 lever
        # b): m int8-SR, v as sqrt(v) int8-SR, per-row f32
        # scales — 14 -> ~10 B/param of optimizer HBM traffic
        # (ops/fused_adamw.fused_adamw_update8). Parity-gated like every
        # quantization default: benchmarks/parity_int8.py --moment8.
        self.moment8 = bool(moment8)
        if self.moment8 and not (self.fused_optimizer
                                 and mesh.size == 1):
            # mesh.size must be checked here too: fused_optimizer=True
            # passed explicitly on a multi-device mesh would otherwise
            # let the opaque fused_adamw_update8 pallas_call reach the
            # partitioner, which replicates custom calls (same gate as
            # quantize_rowwise_fast's device_count()==1)
            raise ValueError(
                "moment8 rides the fused AdamW kernel, which requires "
                "a SINGLE-device TPU mesh (got fused_optimizer="
                f"{self.fused_optimizer}, mesh.size={mesh.size}); it "
                "has no XLA fallback path")
        # unroll policy for the per-stage layer loop. An int is the
        # classic lax.scan body-unroll factor: the body is replicated
        # but params/carries stay STACKED [L, ...], so every
        # remat-saved residual still round-trips HBM through a
        # dynamic-update-slice into the stacked buffer (plus a matching
        # dynamic-slice in the backward) — measured ~49 ms of pure
        # stacking traffic on the 1.3B step, and scan-unroll alone
        # measured a LOSS (round 3/5). "full" is the structural fix
        # (round 6): blocks params live as a PER-LAYER pytree (a dict
        # of "layer_NNN" subtrees, no [L, ...] leading dim anywhere —
        # dict-shaped so checkpointing flattens it like any state), the
        # stage runs as a Python loop, and remat saves/gradients/
        # optimizer state are per-layer leaves — XLA writes each
        # layer's residuals and weight-grad dequants straight from the
        # producing fusion instead of DUS-stacking them. Costs compile
        # time roughly linearly in num_layers; requires pipe=1 (the
        # pipeline shard_map consumes stacked stage params).
        self._unroll_full = (layer_unroll == "full")
        if self._unroll_full:
            if mesh.shape["pipe"] > 1 or self.V > 1:
                raise ValueError(
                    "layer_unroll='full' requires a single-stage mesh "
                    "(pipe=1, vpp_chunks=1): pipeline schedules consume "
                    "stacked [S, L, ...] stage params")
            self.layer_unroll = cfg.num_layers
        else:
            self.layer_unroll = int(layer_unroll)
        # vocab-chunk count for the fused CE: fewer chunks = bigger
        # (faster) head matmuls but a larger live logits buffer
        self.ce_chunks = int(ce_chunks)
        # int8-MXU CE head matmuls (fwd + recompute + dx; dhead exact —
        # it feeds the tied embedding's Adam state). ~31 ms of head
        # matmuls at the flagship shape; earn/reject via parity_int8.
        self.ce_int8 = bool(ce_int8)
        # producer-fused gelu->quantize for the ffn2 site (round-5
        # lever d); auto-on for the all-int8 recipe. Note: removes the
        # standalone "ffn_act" residual, so policies that SAVE ffn_act
        # (save_attn_ffn) force it off.
        if fuse_gelu_quant and quant8 != "wgrad":
            raise ValueError(
                "fuse_gelu_quant rides the all-int8 recipe: it needs "
                "quant8='wgrad' (the fused op quantizes both the fwd "
                "row and the wgrad SR column streams)")
        if fuse_gelu_quant is None:
            fuse_gelu_quant = quant8 == "wgrad"
        self.fuse_gelu_quant = bool(fuse_gelu_quant) and \
            remat != "save_attn_ffn"
        # producer-fused LayerNorm->quantize for the qkv/ffn1 sites
        # (round-5 lever a): same mechanism as fuse_gelu_quant — the
        # rowq kernel computes LN stats + normalize + quantize in one
        # read of the pre-LN residual; the wgrad colq kernel reuses the
        # emitted [M,1] stats. Default OFF: measured a structural LOSS
        # on the flagship step (337.4 -> 344-356 ms across full/qkv/
        # ffn1/fwd-only variants) — the custom-call boundary breaks
        # XLA's residual-add/bias/save fusions around each site, which
        # costs more than the saved LN-output round-trip (trace diff in
        # benchmarks/RESULTS.md; contrast fuse_gelu_quant, whose site
        # feeds another custom call, not an XLA fusion).
        if fuse_ln_quant and quant8 != "wgrad":
            raise ValueError(
                "fuse_ln_quant rides the all-int8 recipe: it needs "
                "quant8='wgrad' (the fused op quantizes both the fwd "
                "row and the wgrad SR column streams)")
        if fuse_ln_quant is None:
            fuse_ln_quant = False
        # True = both sites; "qkv"/"ffn1" = that site only (A/B probes)
        if fuse_ln_quant not in (True, False, "qkv", "ffn1"):
            raise ValueError(
                f"fuse_ln_quant must be True/False/'qkv'/'ffn1', got "
                f"{fuse_ln_quant!r}")
        self.fuse_ln_quant = fuse_ln_quant
        # fuse_ln_quant's wgrad sub-knob (ADVICE r5): True computes the
        # LN inside the backward column-quantize path from the saved
        # [M,1] stats (two reads of the pre-LN x, no h buffer); False
        # re-materializes LN(x) once and runs the plain one-pass colq
        # kernel. None defers to env PTPU_FUSE_BWD_COLQ (default off —
        # the A/B that earned the default is in benchmarks/RESULTS.md).
        # The [M,1] mean/rstd residuals are only SAVED when the branch
        # is on (ops/quant_matmul.int8_ln_linear_all8).
        if fuse_bwd_colq is None:
            from ..ops.quant_matmul import _env_fuse_bwd_colq
            fuse_bwd_colq = _env_fuse_bwd_colq()
        self.fuse_bwd_colq = bool(fuse_bwd_colq)
        if self.moe_experts and mesh.shape["pipe"] > 1 \
                and self.pipeline_schedule == "gpipe":
            raise NotImplementedError(
                "MoE + pipeline parallelism requires an explicit "
                "schedule engine ('1f1b', 'vpp' or 'zb'): the "
                "autodiff'd GPipe scan has no aux-loss side channel")
        # Pallas flash attention on real TPU; XLA einsum attention
        # elsewhere (interpret-mode pallas is orders slower on CPU, and
        # the Mosaic kernel does not lower on GPU backends)
        if use_flash is None:
            use_flash = jax.default_backend() in ("tpu", "axon")
        self.use_flash = use_flash
        self.S = mesh.shape["pipe"]
        if cfg.num_layers % (self.S * self.V):
            raise ValueError("num_layers must divide pp degree "
                             "(x vpp_chunks for 'vpp')")
        self.Lps = cfg.num_layers // (self.S * self.V)
        self.M = microbatches or max(2 * self.S, 1)
        if self.pipeline_schedule == "vpp" and self.S > 1 \
                and self.M % self.S:
            raise ValueError("interleaved schedule needs "
                             "microbatches % pp degree == 0")
        self._sched_cache = None
        self.lr = learning_rate
        self.wd = weight_decay
        self.betas = (beta1, beta2)
        self.grad_clip = grad_clip
        self.params = self._init_params(jax.random.key(seed))
        zeros_moment = lambda p: jnp.zeros(  # noqa: E731
            p.shape, self.moment_dtype, device=p.sharding)
        if self.moment8:
            from ..ops.fused_adamw import (moment8_eligible,
                                           moment8_init)

            def m_leaf(p):
                if moment8_eligible(p):
                    mq, msc, _, _ = moment8_init(p)
                    return (mq, msc)
                return zeros_moment(p)

            def v_leaf(p):
                if moment8_eligible(p):
                    _, _, vq, vsc = moment8_init(p)
                    return (vq, vsc)
                return zeros_moment(p)

            self.opt_state = {
                "step": jnp.zeros((), jnp.int32),
                "m": jax.tree.map(m_leaf, self.params),
                "v": jax.tree.map(v_leaf, self.params),
            }
        else:
            self.opt_state = {
                "step": jnp.zeros((), jnp.int32),
                "m": jax.tree.map(zeros_moment, self.params),
                "v": jax.tree.map(zeros_moment, self.params),
            }
        self._step_fn = None

    # -- init --------------------------------------------------------------
    def _init_params(self, key) -> Dict[str, Any]:
        cfg = self.cfg
        D, V, T, Ff = (cfg.hidden_size, cfg.vocab_size, cfg.max_seq_len,
                       cfg.ffn_size)
        S, L = self.S, self.Lps
        k = jax.random.split(key, 8)
        std = 0.02
        resid_std = std / math.sqrt(2 * cfg.num_layers)

        mdt = self.master_dtype
        n_chunks = self.V

        def vshape(shape, spec):
            # interleaved VPP: blocks leaves grow a leading chunk dim
            # [V, S, ...] — chunk c of pipe-rank r is virtual stage
            # c*S + r (pipeline_scheduled.py)
            if n_chunks > 1 and spec and spec[0] == "pipe":
                return (n_chunks,) + shape, (None,) + spec
            return shape, spec

        def init(key, shape, scale, spec):
            shape, spec = vshape(shape, spec)
            arr = (scale * jax.random.normal(key, shape,
                                             jnp.float32)).astype(mdt)
            return jax.device_put(arr, _spec(self.mesh, *spec))

        def zeros(shape, spec):
            shape, spec = vshape(shape, spec)
            return jax.device_put(jnp.zeros(shape, mdt),
                                  _spec(self.mesh, *spec))

        def ones(shape, spec):
            shape, spec = vshape(shape, spec)
            return jax.device_put(jnp.ones(shape, mdt),
                                  _spec(self.mesh, *spec))

        params = {
            "wte": init(k[0], (V, D), std, ("model", "fsdp")),
            "wpe": init(k[1], (T, D), std, (None, "fsdp")),
            "ln_f_g": ones((D,), (None,)),
            "ln_f_b": zeros((D,), (None,)),
            "blocks": {
                "ln1_g": ones((S, L, D), ("pipe", None, None)),
                "ln1_b": zeros((S, L, D), ("pipe", None, None)),
                "ln2_g": ones((S, L, D), ("pipe", None, None)),
                "ln2_b": zeros((S, L, D), ("pipe", None, None)),
                "wqkv": init(k[2], (S, L, D, 3 * D), std,
                             ("pipe", None, "fsdp", "model")),
                "bqkv": zeros((S, L, 3 * D), ("pipe", None, "model")),
                "wproj": init(k[3], (S, L, D, D), resid_std,
                              ("pipe", None, "model", "fsdp")),
                "bproj": zeros((S, L, D), ("pipe", None, None)),
            },
        }
        if not self.moe_experts:
            params["blocks"].update({
                "win": init(k[4], (S, L, D, Ff), std,
                            ("pipe", None, "fsdp", "model")),
                "bin": zeros((S, L, Ff), ("pipe", None, "model")),
                "wout": init(k[5], (S, L, Ff, D), resid_std,
                             ("pipe", None, "model", "fsdp")),
                "bout": zeros((S, L, D), ("pipe", None, None)),
            })
        else:
            E = self.moe_experts
            b = params["blocks"]
            km = jax.random.split(k[7], 3)
            # experts over 'data' (expert parallelism), fsdp/tp inside
            # each expert; the gate is tiny and replicated
            b["wg"] = init(km[0], (S, L, D, E), std,
                           ("pipe", None, None, None))
            b["w_in"] = init(km[1], (S, L, E, D, Ff), std,
                             ("pipe", None, "data", "fsdp", "model"))
            b["b_in"] = zeros((S, L, E, Ff), ("pipe", None, "data",
                                              "model"))
            b["w_out"] = init(km[2], (S, L, E, Ff, D), resid_std,
                              ("pipe", None, "data", "model", "fsdp"))
            b["b_out"] = zeros((S, L, E, D), ("pipe", None, "data",
                                              None))
        if not self.cfg.tie_embeddings:
            params["head"] = init(k[6], (D, V), std, ("fsdp", "model"))
        if self._unroll_full:
            # per-layer pytree layout (layer_unroll="full"): blocks is
            # a dict of per-layer subtrees keyed "layer_000".. — no
            # [S, L, ...] leading dims, so remat saves, gradients, and
            # optimizer state are per-layer leaves that never
            # round-trip HBM through dynamic-update-slice stacking.
            # Zero-padded string keys keep sorted() == layer order AND
            # keep the tree dict-shaped, which is what
            # distributed/checkpoint.save_state_dict flattens. Values
            # come from the SAME stacked init (identical RNG draws),
            # so rolled/unrolled trainers with equal seeds start
            # bit-identical.
            blocks = params["blocks"]
            params["blocks"] = {
                f"layer_{li:03d}": {
                    k2: jax.device_put(
                        v[0, li],
                        _spec(self.mesh, *_BLOCK_SPECS[k2][2:]))
                    for k2, v in blocks.items()}
                for li in range(L)}
        return params

    # -- model -------------------------------------------------------------
    def _mm(self, seed=None):
        # bf16 in/out einsums: the TPU MXU accumulates bf16 products in
        # fp32 internally, so a bf16 output dtype only rounds the final
        # result while halving the HBM write (measured ~7% step win vs
        # preferred_element_type=f32 + cast). ``site`` decorrelates the
        # SR streams of the three matmul sites in a block (wgrad mode).
        if self.quant8 == "wgrad":
            from ..ops.quant_matmul import int8_linear_all8, site_seed
            return lambda a, w, site=0: int8_linear_all8(
                a, w, site_seed(seed, site))
        if self.quant8 == "dgrad":
            from ..ops.quant_matmul import int8_linear_dgrad8
            return lambda a, w, site=0: int8_linear_dgrad8(a, w)
        if self.quant8:
            from ..ops.quant_matmul import int8_linear
            return lambda a, w, site=0: int8_linear(a, w)
        return lambda a, w, site=0: jnp.einsum("btd,df->btf", a, w)

    def _attn_sublayer(self, x, bp, mm, act, seed=None):
        """ln1 + qkv + attention + proj + residual on [mb, T, D]."""
        cfg = self.cfg
        mb, T, D = x.shape
        H, dh = cfg.num_heads, cfg.head_dim
        if self.quant8 == "wgrad" and self.fuse_ln_quant in (True, "qkv"):
            from ..ops.quant_matmul import int8_ln_linear_all8, site_seed
            qkv = int8_ln_linear_all8(
                x, bp["ln1_g"], bp["ln1_b"],
                bp["wqkv"].astype(x.dtype), site_seed(seed, 1),
                fuse_bwd_colq=self.fuse_bwd_colq)
        else:
            h = _layer_norm(x, bp["ln1_g"], bp["ln1_b"])
            qkv = mm(h, bp["wqkv"].astype(x.dtype), 1)
        qkv = qkv + bp["bqkv"].astype(x.dtype)
        qkv = checkpoint_name(qkv, "qkv_out")
        shape = self.mesh.shape
        # zero-relayout path: the hsplit flash kernel consumes the qkv
        # matmul's native [mb, T, H*dh] layout (column slices per head
        # inside the kernel's BlockSpecs) — no (T,H) transposes at all.
        # Gated to model==1: with TP the packed 3HD columns are sharded
        # over 'model', and a plain column slice would cross shards.
        # dh must be lane-aligned (128): the kernel's column blocks are
        # dh wide, and Mosaic requires the last block dim % 128 == 0
        # when it is not the whole array dim (interpret mode does NOT
        # check this — dh=64 passes CPU tests but fails on hardware)
        hsplit_ok = (self.use_flash and shape["sep"] == 1
                     and shape["pipe"] == 1 and shape["model"] == 1
                     and T % 128 == 0 and dh % 128 == 0
                     and mb % shape["data"] == 0)
        if hsplit_ok:
            from ..ops.pallas_ops import flash_attention_qkv_fused
            spec = P("data", None, None)
            f = jax.shard_map(
                partial(flash_attention_qkv_fused, num_heads=H,
                        causal=True),
                in_specs=(spec,), out_specs=spec,
                axis_names=set(self.mesh.axis_names),
                check_vma=False)
            attn = f(qkv)
        else:
            qkv4 = qkv.reshape(mb, T, 3, H, dh)
            q, k, v = qkv4[:, :, 0], qkv4[:, :, 1], qkv4[:, :, 2]
            attn = self._attention(q, k, v, act).reshape(mb, T, H * dh)
        attn = checkpoint_name(attn, "attn_out")
        proj = jnp.einsum("btf,fd->btd", attn, bp["wproj"].astype(x.dtype))
        x = x + proj + bp["bproj"].astype(x.dtype)
        return act(x, _spec(self.mesh, "data", "sep", None))

    def _block(self, x, bp, seed=None):
        """One transformer block on [mb, T, D] activations (GSPMD view)."""
        act = partial(jax.lax.with_sharding_constraint)
        mm = self._mm(seed)
        x = self._attn_sublayer(x, bp, mm, act, seed)

        if self.quant8 == "wgrad" and self.fuse_ln_quant in (True, "ffn1"):
            from ..ops.quant_matmul import int8_ln_linear_all8, site_seed
            a = int8_ln_linear_all8(
                x, bp["ln2_g"], bp["ln2_b"],
                bp["win"].astype(x.dtype), site_seed(seed, 2),
                fuse_bwd_colq=self.fuse_bwd_colq)
        else:
            h = _layer_norm(x, bp["ln2_g"], bp["ln2_b"])
            a = mm(h, bp["win"].astype(x.dtype), 2)
        a = a + bp["bin"].astype(x.dtype)
        a = checkpoint_name(a, "ffn1_out")  # pre-gelu: gelu vjp needs it
        if self.quant8 == "wgrad" and self.fuse_gelu_quant:
            # round-5 lever d: gelu computed INSIDE the ffn2 quantize
            # kernels (fwd rowq + wgrad SR colq) — the bf16 gelu output
            # never lands in HBM and the quantizers stop re-reading it
            from ..ops.quant_matmul import (int8_gelu_linear_all8,
                                            site_seed)
            o = int8_gelu_linear_all8(a, bp["wout"].astype(x.dtype),
                                      site_seed(seed, 3))
        else:
            a = jax.nn.gelu(a, approximate=True)
            a = checkpoint_name(a, "ffn_act")
            o = mm(a, bp["wout"].astype(x.dtype), 3)
        o = checkpoint_name(o, "ffn2_out")
        x = x + o + bp["bout"].astype(x.dtype)
        return act(x, _spec(self.mesh, "data", "sep", None))

    def _block_moe(self, x, bp, seed=None):
        """Transformer block with a GShard top-2 MoE FFN; returns
        (x, load_balance_aux). Experts live on the 'data' mesh axis —
        the dispatch/combine einsums below ARE the all-to-all pair."""
        from ..incubate.moe import moe_dispatch_combine
        act = partial(jax.lax.with_sharding_constraint)
        mm = self._mm(seed)
        x = self._attn_sublayer(x, bp, mm, act, seed)
        mb, T, D = x.shape
        E = self.moe_experts

        h = _layer_norm(x, bp["ln2_g"], bp["ln2_b"])
        hf = h.reshape(mb * T, D)
        logits = jnp.einsum("td,de->te", hf.astype(jnp.float32),
                            bp["wg"].astype(jnp.float32))
        capacity = max(1, int(self.moe_capacity_factor * mb * T * 2 / E))
        expert_in, combine, aux = moe_dispatch_combine(hf, logits,
                                                       capacity)
        expert_in = act(expert_in,
                        _spec(self.mesh, "data", None, "fsdp"))
        a = jnp.einsum("ecd,edf->ecf", expert_in,
                       bp["w_in"].astype(h.dtype))
        a = jax.nn.gelu(a + bp["b_in"][:, None, :].astype(h.dtype),
                        approximate=True)
        o = jnp.einsum("ecf,efd->ecd", a, bp["w_out"].astype(h.dtype))
        o = o + bp["b_out"][:, None, :].astype(h.dtype)
        y = jnp.einsum("tec,ecd->td", combine.astype(h.dtype), o)
        x = x + y.reshape(mb, T, D)
        return act(x, _spec(self.mesh, "data", "sep", None)), aux

    def _attention(self, q, k, v, act):
        """Causal self-attention on [mb, T, H, dh]; Pallas flash kernel on
        TPU (batch over 'data', heads over 'model' via shard_map), XLA
        einsum with Megatron-SP (q seq-sharded, k/v gathered) otherwise."""
        mb, T, H, dh = q.shape
        shape = self.mesh.shape
        # pipe must be 1: the Mosaic lowering requires manual_axes to
        # cover EVERY mesh axis, and nested shard_map manual-axes do not
        # union with the pipeline's, so flash attention cannot run inside
        # the pipe shard_map (pipe>1 configs use the XLA einsum path)
        flash_ok = (self.use_flash and shape["sep"] == 1
                    and shape["pipe"] == 1
                    and T % 128 == 0 and dh in (64, 128, 256)
                    and H % shape["model"] == 0
                    and mb % shape["data"] == 0)
        if flash_ok:
            from ..ops.pallas_ops import flash_attention_fwd
            spec = P("data", None, "model", None)
            f = jax.shard_map(
                partial(flash_attention_fwd, causal=True),
                in_specs=(spec, spec, spec),
                out_specs=spec,
                axis_names=set(self.mesh.axis_names),  # fully manual
                check_vma=False)
            return f(q, k, v)
        # long-context path: Ulysses all-to-all attention — seq-sharded
        # activations become head-sharded full-sequence blocks, so per-chip
        # kv memory is S*(H/n)*D instead of the gathered S*H*D
        ulysses_ok = (self.use_flash and shape["pipe"] == 1
                      and shape["sep"] > 1
                      and T % 128 == 0 and dh in (64, 128, 256)
                      and H % (shape["model"] * shape["sep"]) == 0
                      and mb % shape["data"] == 0)
        if ulysses_ok:
            from ..ops.pallas_ops import ulysses_attention
            return ulysses_attention(
                q, k, v, self.mesh, axis="sep", causal=True,
                manual_axes=set(self.mesh.axis_names),
                use_flash=jax.default_backend() in ("tpu", "axon"),
                in_spec=P("data", "sep", "model", None))
        # SP: q stays seq-sharded; k/v gathered over 'sep'
        q = act(q, _spec(self.mesh, "data", "sep", "model", None))
        k = act(k, _spec(self.mesh, "data", None, "model", None))
        v = act(v, _spec(self.mesh, "data", None, "model", None))
        logits = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                            preferred_element_type=jnp.float32)
        logits = logits / math.sqrt(dh)
        causal = jnp.tril(jnp.ones((T, T), bool))
        logits = jnp.where(causal, logits, -1e30)
        probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
        return jnp.einsum("bhqk,bkhd->bqhd", probs, v)

    def _stage_fn(self, stage_params, x, seed=None):
        """One pipeline stage = Lps blocks, scanned.

        remat: False = save everything; True = full per-block remat;
        "save_attn" / "save_attn_ffn" = selective policies that keep the
        expensive flash-attention output (and optionally the ffn
        activation) while recomputing the cheap elementwise tail;
        "save_dots" = save every matmul output (recompute only norms /
        elementwise) — remat's 2N extra FLOPs shrink to ~0 at the cost
        of ~9 activation buffers per layer."""
        blk = self._remat_wrap(self._block)
        if self._unroll_full:
            # per-layer pytree path: stage_params maps "layer_NNN" ->
            # per-layer dict; residual saves and weight grads are
            # per-layer leaves (no stacked carries, no DUS)
            for li, key in enumerate(sorted(stage_params)):
                bp = stage_params[key]
                if self.quant8 == "wgrad":
                    x = blk(x, bp, self._layer_seed(seed, li))
                else:
                    x = blk(x, bp)
            return x
        if self.quant8 == "wgrad":
            xs = (stage_params, self._layer_seeds(seed))
            body = lambda carry, t: (blk(carry, t[0], t[1]), None)
        else:
            xs = stage_params
            body = lambda carry, bp: (blk(carry, bp), None)
        x, _ = jax.lax.scan(body, x, xs,
                            unroll=min(self.layer_unroll, self.Lps))
        return x

    def _layer_seeds(self, seed):
        """Per-layer SR seed array for the wgrad scan: layers sit 16
        apart so _mm's ``s*8 + site`` keeps (layer, site) streams
        distinct — ONE definition for the dense and MoE stages."""
        base = jnp.int32(1) if seed is None else seed
        return base + jnp.arange(self.Lps, dtype=jnp.int32) * 16

    def _layer_seed(self, seed, li):
        """Scalar layer seed for the unrolled path — same derivation
        as _layer_seeds, so rolled and unrolled draw IDENTICAL SR
        streams (the bit-parity test relies on it)."""
        base = jnp.int32(1) if seed is None else seed
        return base + jnp.int32(li * 16)

    def _remat_wrap(self, block_fn):
        """Apply the configured remat policy to a block fn (shared by
        the dense and MoE stages)."""
        if not self.remat:
            return block_fn
        if self.remat == "save_attn":
            pol = jax.checkpoint_policies.save_only_these_names("attn_out")
        elif self.remat == "save_attn_ffn":
            pol = jax.checkpoint_policies.save_only_these_names(
                "attn_out", "ffn_act")
        elif self.remat == "save_dots":
            # matmul outputs + the flash kernel's own residuals (out,
            # lse): backward recomputes only layernorms/elementwise —
            # remat overhead drops from ~33% of step FLOPs to ~0
            pol = jax.checkpoint_policies.save_from_both_policies(
                jax.checkpoint_policies.dots_with_no_batch_dims_saveable,
                jax.checkpoint_policies.save_only_these_names(
                    "flash_out", "flash_lse"))
        elif self.remat == "save_main":
            # like save_dots but drops the attention-proj output buffer
            # (cheapest matmul, 2/24 of block FLOPs to recompute) —
            # ~0.6G less HBM at bs6/1.3B, which is what lets this fit
            # alongside bf16 masters on a 16G chip. ffn2_out is NOT
            # saved: the residual-add backward is identity in it, so
            # saving it only costs a stacked buffer + copy traffic
            pol = jax.checkpoint_policies.save_only_these_names(
                "qkv_out", "ffn1_out", "flash_out", "flash_lse")
        elif self.remat == "save_qkv":
            # S=2048 memory recipe: drops the stacked ffn1_out residual
            # too (~3.2 GB at bs4/seq2048) — backward re-runs the ffn1
            # matmul and gelu from the recomputed ln2 output in exchange
            # for the batch size the freed HBM buys
            pol = jax.checkpoint_policies.save_only_these_names(
                "qkv_out")
        elif self.remat == "save_qkv_ffn":
            # drops the flash out/lse residuals too: backward re-runs
            # the flash FORWARD kernel from the saved qkv projection
            # (~13 ms/step at 1.3B) in exchange for ~1.2 GB of stacked
            # residual HBM — the trade that buys layer_unroll room
            pol = jax.checkpoint_policies.save_only_these_names(
                "qkv_out", "ffn1_out")
        else:
            return jax.checkpoint(block_fn)
        return jax.checkpoint(block_fn, policy=pol)

    def _stage_fn_moe(self, stage_params, x, seed=None):
        """MoE stage: like _stage_fn but threads the summed
        load-balance aux loss through the layer scan."""
        blk = self._remat_wrap(self._block_moe)
        if self._unroll_full:
            aux = jnp.zeros((), jnp.float32)
            for li, key in enumerate(sorted(stage_params)):
                bp = stage_params[key]
                if self.quant8 == "wgrad":
                    x, a = blk(x, bp, self._layer_seed(seed, li))
                else:
                    x, a = blk(x, bp)
                aux = aux + a
            return x, aux
        if self.quant8 == "wgrad":
            xs = (stage_params, self._layer_seeds(seed))

            def body(carry, t):
                x, aux = carry
                x, a = blk(x, t[0], t[1])
                return (x, aux + a), None
        else:
            xs = stage_params

            def body(carry, bp):
                x, aux = carry
                x, a = blk(x, bp)
                return (x, aux + a), None

        (x, aux), _ = jax.lax.scan(body, (x, jnp.zeros((), jnp.float32)),
                                   xs,
                                   unroll=min(self.layer_unroll, self.Lps))
        return x, aux

    def _embed(self, wte, wpe, input_ids):
        """Token + position embedding, activation-sharded (shared by the
        autodiff'd path and the explicit 1F1B path)."""
        T = input_ids.shape[1]
        dtype = self.cfg.dtype
        x = wte.astype(dtype)[input_ids] + \
            wpe.astype(dtype)[jnp.arange(T)][None]
        return jax.lax.with_sharding_constraint(
            x, _spec(self.mesh, "data", "sep", None))

    def _forward_loss(self, params, input_ids, labels, seed=None):
        cfg = self.cfg
        B, T = input_ids.shape
        dtype = cfg.dtype
        if self.quant8 == "wgrad" and seed is None:
            seed = jnp.int32(1)
        x = self._embed(params["wte"], params["wpe"], input_ids)

        moe_aux = None
        if self.S == 1:
            # no pipeline: run the (single) stage outside the pipe
            # shard_map (lets Pallas flash run); microbatches still scan
            # so per-step working shapes match the pipelined path
            stage = params["blocks"] if self._unroll_full \
                else jax.tree.map(lambda a: a[0], params["blocks"])
            stage_fn = self._stage_fn_moe if self.moe_experts \
                else self._stage_fn
            if self.M > 1:
                if B % self.M:
                    raise ValueError(
                        f"batch {B} not divisible by microbatches {self.M}")
                xm = x.reshape(self.M, B // self.M, T, cfg.hidden_size)
                if self.quant8 == "wgrad":
                    # fold the microbatch index into the SR seed so the
                    # M summed wgrads draw independent streams
                    mb_seeds = seed + (jnp.arange(self.M, dtype=jnp.int32)
                                       + 1) * jnp.int32(-1640531527)
                    out = jax.lax.map(
                        lambda t: stage_fn(stage, t[0], t[1]),
                        (xm, mb_seeds))
                else:
                    out = jax.lax.map(partial(stage_fn, stage), xm)
                if self.moe_experts:
                    x, aux_m = out
                    moe_aux = jnp.mean(aux_m)
                else:
                    x = out
                x = x.reshape(B, T, cfg.hidden_size)
            else:
                if self.quant8 == "wgrad":
                    out = stage_fn(stage, x, seed)
                    x, moe_aux = out if self.moe_experts else (out, None)
                elif self.moe_experts:
                    x, moe_aux = stage_fn(stage, x)
                else:
                    x = stage_fn(stage, x)
        else:
            M = self.M
            mb = B // M
            x_micro = x.reshape(M, mb, T, cfg.hidden_size)
            from ..distributed.pipeline import pipeline_forward
            out = pipeline_forward(self._stage_fn, params["blocks"],
                                   x_micro, self.mesh, axis="pipe",
                                   remat=False)
            x = out.reshape(B, T, cfg.hidden_size)
        x = _layer_norm(x, params["ln_f_g"], params["ln_f_b"])
        shape = self.mesh.shape
        # fused vocab-chunked CE when no axis shards the vocab/seq dims:
        # never materializes [B,T,V] logits (ops/fused_ce.py)
        if (shape["model"] == 1 and shape["sep"] == 1
                and cfg.vocab_size % self.ce_chunks == 0):
            from ..ops.fused_ce import fused_softmax_cross_entropy
            # tied head passes wte's native [V, D] layout straight
            # through (vocab_major): the .T would cost a materialized
            # 200MB transpose for dhead in the backward (~7 ms/step,
            # r5 chrome trace bitcast_convert_fusion); untied heads
            # are stored [D, V] and keep the head-major path
            vm = bool(cfg.tie_embeddings)
            head = params["wte"] if vm else params["head"]
            loss = fused_softmax_cross_entropy(x, head.astype(dtype),
                                               labels,
                                               n_chunks=self.ce_chunks,
                                               int8=self.ce_int8,
                                               vocab_major=vm)
        else:
            head = params["wte"].T if cfg.tie_embeddings \
                else params["head"]
            logits = jnp.einsum("btd,dv->btv", x, head.astype(dtype),
                                preferred_element_type=jnp.float32)
            logits = jax.lax.with_sharding_constraint(
                logits, _spec(self.mesh, "data", "sep", "model"))
            lp = jax.nn.log_softmax(logits, axis=-1)
            ll = jnp.take_along_axis(lp, labels[..., None],
                                     axis=-1)[..., 0]
            loss = -jnp.mean(ll)
        if moe_aux is not None:
            # mean over layers, weighted (GShard's l_aux term)
            loss = loss + self.moe_aux_weight * moe_aux / self.Lps
        return loss

    def _loss_and_grads_1f1b(self, params, input_ids, labels):
        """Full loss+grads via the explicit on-device 1F1B schedule:
        embedding fwd/bwd outside the pipe, blocks + loss head inside
        (distributed/pipeline.pipeline_train_1f1b)."""
        from ..distributed.pipeline import pipeline_train_1f1b
        cfg = self.cfg
        B, T = input_ids.shape
        dtype = cfg.dtype
        M = self.M
        mb = B // M

        def embed(ep):
            return self._embed(ep["wte"], ep["wpe"], input_ids)

        emb_p = {"wte": params["wte"], "wpe": params["wpe"]}
        x, embed_vjp = jax.vjp(embed, emb_p)
        x_micro = x.reshape(M, mb, T, cfg.hidden_size)
        labels_micro = labels.reshape(M, mb, T)

        head_p = {"ln_f_g": params["ln_f_g"], "ln_f_b": params["ln_f_b"]}
        if cfg.tie_embeddings:
            head_p["wte"] = params["wte"]
        else:
            head_p["head"] = params["head"]

        def head_loss(hp, y, lab):
            h = _layer_norm(y, hp["ln_f_g"], hp["ln_f_b"])
            hw = hp["wte"].T if cfg.tie_embeddings else hp["head"]
            logits = jnp.einsum("btd,dv->btv", h, hw.astype(h.dtype),
                                preferred_element_type=jnp.float32)
            # same sharding as _forward_loss's head: vocab over 'model'
            logits = jax.lax.with_sharding_constraint(
                logits, _spec(self.mesh, "data", "sep", "model"))
            lp = jax.nn.log_softmax(logits, axis=-1)
            ll = jnp.take_along_axis(lp, lab[..., None], axis=-1)[..., 0]
            return -jnp.mean(ll)

        if self.moe_experts:
            # MoE+PP composition: the explicit schedule carries the
            # balance-loss side channel (normalized per layer to match
            # the non-pipelined objective)
            stage_fn = self._stage_fn_moe
            aux_w = self.moe_aux_weight / cfg.num_layers
        else:
            stage_fn = self._stage_fn
            aux_w = 0.0
        if self.pipeline_schedule == "1f1b":
            loss, gblocks, ghead, dx_micro = pipeline_train_1f1b(
                stage_fn, head_loss, params["blocks"], head_p,
                x_micro, labels_micro, self.mesh, axis="pipe",
                stage_aux_weight=aux_w,
                stage_has_aux=bool(self.moe_experts))
        else:  # "vpp" / "zb": table-driven on-device engine
            from ..distributed.pipeline_scheduled import \
                pipeline_train_scheduled
            sched = self._get_schedule()
            blocks = params["blocks"]
            if self.V == 1:  # engine expects a leading chunk dim
                blocks = jax.tree.map(lambda a: a[None], blocks)
            loss, gblocks, ghead, dx_micro = pipeline_train_scheduled(
                stage_fn, head_loss, blocks, head_p,
                x_micro, labels_micro, self.mesh, sched, axis="pipe",
                stage_aux_weight=aux_w,
                stage_has_aux=bool(self.moe_experts))
            if self.V == 1:
                gblocks = jax.tree.map(lambda a: a[0], gblocks)

        (demb,) = embed_vjp(dx_micro.reshape(B, T, cfg.hidden_size))
        gwte = demb["wte"].astype(jnp.float32)
        if cfg.tie_embeddings:
            gwte = gwte + ghead["wte"]
        grads = {
            "wte": gwte,
            "wpe": demb["wpe"].astype(jnp.float32),
            "ln_f_g": ghead["ln_f_g"],
            "ln_f_b": ghead["ln_f_b"],
            "blocks": gblocks,
        }
        if not cfg.tie_embeddings:
            grads["head"] = ghead["head"]
        return loss, grads

    def _get_schedule(self):
        """Job table for the 'vpp'/'zb' engines (cached; host-side)."""
        if self._sched_cache is None:
            from ..distributed.pipeline_schedules import (
                InterleavedSchedule, ZeroBubbleSchedule)
            if self.pipeline_schedule == "vpp":
                self._sched_cache = InterleavedSchedule(
                    self.S, self.M, num_chunks=self.V)
            else:
                self._sched_cache = ZeroBubbleSchedule(self.S, self.M)
        return self._sched_cache

    # -- optimizer (fused AdamW, sharded like params) ----------------------
    def _adamw(self, params, grads, opt_state):
        b1, b2 = self.betas
        step = opt_state["step"] + 1
        tf = step.astype(jnp.float32)
        # global-norm clip
        gnorm = jnp.sqrt(sum(jnp.sum(jnp.square(g.astype(jnp.float32)))
                             for g in jax.tree.leaves(grads)))
        scale = jnp.minimum(1.0, self.grad_clip / (gnorm + 1e-6))
        step_u32 = step.astype(jnp.uint32)

        lr_mult = jnp.float32(1.0) if self.lr_schedule is None \
            else jnp.asarray(self.lr_schedule(tf), jnp.float32)

        def upd(p, g, m, v, key):
            g = g.astype(jnp.float32) * scale
            m2 = b1 * m.astype(jnp.float32) + (1 - b1) * g
            v2 = b2 * v.astype(jnp.float32) + (1 - b2) * g * g
            mhat = m2 / (1 - b1 ** tf)
            vhat = v2 / (1 - b2 ** tf)
            lr_t = self.lr * lr_mult
            p2 = p.astype(jnp.float32) * (1 - lr_t * self.wd) - \
                lr_t * mhat / (jnp.sqrt(vhat) + 1e-8)
            if self._stoch_round:
                p2 = _stochastic_round_bf16(p2, key)
            return (p2, m2.astype(self.moment_dtype),
                    v2.astype(self.moment_dtype))

        use_fused = self.fused_optimizer
        if use_fused:
            from ..ops.fused_adamw import (fused_adamw_update,
                                           fused_adamw_update8,
                                           fused_adamw_eligible)
            b1f, b2f = float(b1), float(b2)
            inv_bc1 = 1.0 / (1.0 - b1f ** tf)
            inv_bc2 = 1.0 / (1.0 - b2f ** tf)

        _is8 = lambda x: isinstance(x, tuple)  # noqa: E731
        flat_p, tdef = jax.tree.flatten(params)
        flat_g = jax.tree.leaves(grads)
        flat_m = jax.tree.flatten(opt_state["m"], is_leaf=_is8)[0]
        flat_v = jax.tree.flatten(opt_state["v"], is_leaf=_is8)[0]
        new_p, new_m, new_v = [], [], []
        for i, (p, g, m, v) in enumerate(zip(flat_p, flat_g, flat_m,
                                             flat_v)):
            if _is8(m):
                # int8 moment storage: (q, scale) pairs ride the fused
                # kernel's int8 variant (moment8 implies fused+eligible)
                if not use_fused:
                    # e.g. a moment8 checkpoint resumed on a trainer
                    # built without the fused optimizer (CPU debug):
                    # fail with the diagnosis, not an UnboundLocalError
                    raise RuntimeError(
                        "opt_state carries int8 (q, scale) moment "
                        "pairs but this trainer runs without the "
                        "fused optimizer; rebuild with moment8=True "
                        "on a single-device TPU mesh, or dequantize "
                        "the state via ops.fused_adamw.moment8_unpack")
                p2, mq, msc, vq, vsc = fused_adamw_update8(
                    p, g, m[0], m[1], v[0], v[1], scale, inv_bc1,
                    inv_bc2, step.astype(jnp.int32),
                    lr=float(self.lr), wd=float(self.wd),
                    b1=b1f, b2=b2f, eps=1e-8,
                    stoch_round=self._stoch_round, leaf_id=i,
                    lr_scale=lr_mult)
                new_p.append(p2)
                new_m.append((mq, msc))
                new_v.append((vq, vsc))
                continue
            if use_fused and fused_adamw_eligible(p):
                p2, m2, v2 = fused_adamw_update(
                    p, g, m, v, scale, inv_bc1, inv_bc2,
                    step.astype(jnp.int32),
                    lr=float(self.lr), wd=float(self.wd),
                    b1=b1f, b2=b2f, eps=1e-8,
                    stoch_round=self._stoch_round, leaf_id=i,
                    lr_scale=lr_mult)
                new_p.append(p2)
                new_m.append(m2.astype(self.moment_dtype))
                new_v.append(v2.astype(self.moment_dtype))
                continue
            # rbg keys are cheap to build and the generator is ~10x
            # faster than threefry on TPU (SR needs 16 bits/param/step)
            key = jnp.array([0x5eed, 0xbeef, i, 0], jnp.uint32) \
                .at[3].set(step_u32) if self._stoch_round else None
            p2, m2, v2 = upd(p, g, m, v, key)
            new_p.append(p2)
            new_m.append(m2)
            new_v.append(v2)
        return (jax.tree.unflatten(tdef, new_p),
                {"step": step, "m": jax.tree.unflatten(tdef, new_m),
                 "v": jax.tree.unflatten(tdef, new_v)})

    # -- public step -------------------------------------------------------
    def build_step(self):
        if self._step_fn is not None:
            return self._step_fn

        def step(params, opt_state, input_ids, labels):
            # per-step SR seed for wgrad quantization; int32 multiply
            # wraps, which only mixes the stream (never collapses it
            # the way f32 rounding of big bases would)
            sr_seed = (opt_state["step"].astype(jnp.int32) + 1) \
                * jnp.int32(40503) if self.quant8 == "wgrad" else None
            if self.S > 1 and self.pipeline_schedule in ("1f1b", "vpp",
                                                         "zb"):
                cparams = params if self._stoch_round else jax.tree.map(
                    lambda p: p.astype(self.cfg.dtype), params) \
                    if self.mixed_precision else params
                loss, grads = self._loss_and_grads_1f1b(
                    cparams, input_ids, labels)
            elif self._stoch_round:
                # bf16 masters ARE the compute params — no cast, no
                # second weight copy in HBM
                loss, grads = jax.value_and_grad(self._forward_loss)(
                    params, input_ids, labels, sr_seed)
            elif self.mixed_precision:
                # cast masters -> compute dtype OUTSIDE the diff'd fn so
                # grads materialize at cfg.dtype (AMP-O2 master-weight
                # semantics; halves grad HBM)
                cparams = jax.tree.map(
                    lambda p: p.astype(self.cfg.dtype), params)
                loss, grads = jax.value_and_grad(self._forward_loss)(
                    cparams, input_ids, labels, sr_seed)
            else:
                loss, grads = jax.value_and_grad(self._forward_loss)(
                    params, input_ids, labels, sr_seed)
            params, opt_state = self._adamw(params, grads, opt_state)
            return params, opt_state, loss

        data_spec = _spec(self.mesh, ("data",), None)
        self._step_fn = jax.jit(
            step, donate_argnums=(0, 1),
            in_shardings=(None, None, data_spec, data_spec))
        return self._step_fn

    def _build_guard(self):
        """Jitted drift probe: relative error of the int8 dgrad (and,
        in wgrad mode, the SR int8 wgrad) on layer 0's qkv matmul with
        the CURRENT weights — ~1% of a step. The 500-step parity runs
        end with wqkv SNR ~1, so the int8 default is watched, not
        assumed (benchmarks/RESULTS.md)."""
        from ..ops.quant_matmul import (quantize_rowwise_fast,
                                        sr_quantize_colwise)
        wgrad_mode = self.quant8 == "wgrad"

        def probe(params, input_ids, seed):
            x = self._embed(params["wte"], params["wpe"], input_ids)
            bp = params["blocks"]["layer_000"] if self._unroll_full \
                else jax.tree.map(lambda a: a[0, 0], params["blocks"])
            h = _layer_norm(x, bp["ln1_g"], bp["ln1_b"])
            w = bp["wqkv"].astype(h.dtype)
            key = jax.random.PRNGKey(seed.astype(jnp.uint32))
            g = jax.random.normal(
                key, h.shape[:-1] + (w.shape[1],)).astype(h.dtype)
            dx_e = jax.lax.dot_general(
                g, w, (((g.ndim - 1,), (1,)), ((), ()))) \
                .astype(jnp.float32)
            gq, gs = quantize_rowwise_fast(g, axis=-1)
            wq, ws = quantize_rowwise_fast(w, axis=1)
            y = jax.lax.dot_general(
                gq, wq, (((g.ndim - 1,), (1,)), ((), ())),
                preferred_element_type=jnp.int32)
            dx_i = (y.astype(jnp.float32) * gs *
                    jnp.reshape(ws, (1,) * (g.ndim - 1) + (-1,)))
            rel = jnp.linalg.norm(dx_i - dx_e) / \
                (jnp.linalg.norm(dx_e) + 1e-30)
            if wgrad_mode:
                D = h.shape[-1]
                N = w.shape[1]
                h2 = h.reshape(-1, D)
                g2 = g.reshape(-1, N)
                dw_e = jax.lax.dot_general(
                    h2, g2, (((0,), (0,)), ((), ()))) \
                    .astype(jnp.float32)
                si = seed.astype(jnp.int32)
                xq, xs = sr_quantize_colwise(h2, si)
                gq2, gs2 = sr_quantize_colwise(g2, si + 1)
                dwi = jax.lax.dot_general(
                    xq, gq2, (((0,), (0,)), ((), ())),
                    preferred_element_type=jnp.int32)
                dw_i = dwi.astype(jnp.float32) * \
                    xs.reshape(D, 1) * gs2
                relw = jnp.linalg.norm(dw_i - dw_e) / \
                    (jnp.linalg.norm(dw_e) + 1e-30)
                rel = jnp.maximum(rel, relw)
            return rel

        return jax.jit(probe)

    def _run_guard(self, input_ids):
        """Measure drift; fall back one int8 tier if it exceeds the
        threshold (wgrad -> dgrad -> exact bf16). Returns the measured
        relative error."""
        if self._guard_fn is None:
            self._guard_fn = self._build_guard()
        seed = self.opt_state["step"].astype(jnp.float32)
        r = float(jax.device_get(
            self._guard_fn(self.params, input_ids, seed)))
        if r > self.int8_guard_threshold:
            ladder = {"wgrad": "dgrad", "dgrad": False, True: False}
            nxt = ladder.get(self.quant8, False)
            self._guard_events.append(
                {"step": int(jax.device_get(self.opt_state["step"])),
                 "rel_err": r, "from": self.quant8, "to": nxt})
            self.quant8 = nxt
            self._step_fn = None   # recompile without the drifted tier
            self._guard_fn = None
        return r

    def guard_events(self):
        """Drift-guard fallback log: [{step, rel_err, from, to}]."""
        return list(self._guard_events)

    def train_step(self, input_ids, labels) -> float:
        fn = self.build_step()
        if isinstance(input_ids, Tensor):
            input_ids = input_ids._data
        if isinstance(labels, Tensor):
            labels = labels._data
        with jax.set_mesh(self.mesh):
            if self.quant8 and self.int8_guard_period and \
                    self._host_step % self.int8_guard_period == 0:
                self._run_guard(jnp.asarray(input_ids))
                fn = self.build_step()  # guard may have recompiled
            self.params, self.opt_state, loss = fn(
                self.params, self.opt_state, input_ids, labels)
        self._host_step += 1
        return loss

    def n_params(self) -> int:
        return sum(int(np.prod(l.shape))
                   for l in jax.tree.leaves(self.params))


def _stochastic_round_bf16(x_f32, key):
    """Unbiased fp32 -> bf16 rounding: bf16 is the top 16 bits of f32,
    so adding uniform-[0, 2^16) bits to the f32 representation and
    truncating rounds up with probability exactly equal to the dropped
    fraction (exact stochastic rounding, no special-casing of ulp).

    ``key``: uint32[4] rbg key (hardware bit generator; threefry costs
    ~2x the whole AdamW update at 1.3B params)."""
    bits = jax.lax.bitcast_convert_type(x_f32, jnp.uint32)
    _, r32 = jax.lax.rng_bit_generator(
        key, x_f32.shape, jnp.uint32,
        algorithm=jax.lax.RandomAlgorithm.RNG_DEFAULT)
    y = bits + (r32 & jnp.uint32(0xFFFF))
    # inf/nan inputs: the add could wrap the exponent; keep them verbatim
    y = jnp.where(jnp.isfinite(x_f32), y, bits)
    return jax.lax.bitcast_convert_type(
        (y >> 16).astype(jnp.uint16), jnp.bfloat16)


def _layer_norm(x, g, b, eps=1e-5):
    xf = x.astype(jnp.float32)
    m = jnp.mean(xf, axis=-1, keepdims=True)
    v = jnp.var(xf, axis=-1, keepdims=True)
    out = (xf - m) * jax.lax.rsqrt(v + eps)
    return (out * g + b).astype(x.dtype)
