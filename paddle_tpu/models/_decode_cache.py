"""Shared fixed-buffer KV-cache attention for serving decode.

One pure-jax routine used by every causal LM's static-cache path
(llama RoPE attention, gpt learned-position attention): write the new
k/v block into the fixed ``[B, Tmax, KV, D]`` buffers at the write
position (``dynamic_update_slice``) and attend over the causally
masked full buffer.

The write position ``p`` is either a SCALAR (the whole batch is at one
position — the synchronized ``generate()`` decode) or a PER-ROW
``[B]`` vector (every row at its own position — the continuous-batching
slot-pool decode, ``paddle_tpu/serving``). Both lower to the same
einsum contraction so per-row results are bitwise identical to the
scalar path's, which is what makes the serving engine's greedy outputs
token-identical to ``generate()``'s.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["cache_attend", "check_cache_pos"]


def check_cache_pos(pos, t: int, Tmax: int) -> bool:
    """Validate a static-cache write position against the buffer and
    classify it: returns per_row (True when ``pos`` is a [B] vector).

    When the position is concrete (not under a jax trace), a write past
    the buffer fails HERE with a diagnosis — dynamic_update_slice would
    otherwise silently clamp and corrupt the cache tail."""
    pos_data = getattr(pos, "_data", pos)
    per_row = getattr(pos_data, "ndim", 0) >= 1
    concrete = pos if isinstance(pos, int) else (
        None if isinstance(pos_data, jax.core.Tracer)
        else int(np.asarray(pos_data).max()))
    if concrete is not None and concrete + t > Tmax:
        raise ValueError(
            f"static cache overflow: pos {concrete} + {t} new "
            f"tokens exceeds cache length {Tmax}")
    return per_row


def cache_attend(qr, kr, v, kc, vc, p, per_row: bool):
    """Masked fixed-buffer cache attention.

    qr: [B, t, H, D] position-encoded queries; kr/v: [B, t, KV, D] new
    keys (position-encoded) / values; kc/vc: [B, Tmax, KV, D] cache
    buffers; p: int32 write position — scalar, or [B] when ``per_row``.
    GQA folds the query-group dim into the einsum against kv-head
    caches instead of materializing a head-repeated cache copy.

    Returns (out [B, t, H*D], kc', vc').
    """
    b, t, h, D = qr.shape
    kv = kr.shape[2]
    rep = h // kv
    Tmax = kc.shape[1]
    if per_row:
        upd = lambda c, u, pi: jax.lax.dynamic_update_slice(
            c, u.astype(c.dtype), (pi, 0, 0))
        kc = jax.vmap(upd)(kc, kr, p)
        vc = jax.vmap(upd)(vc, v, p)
        qpos = p[:, None] + jnp.arange(t)[None, :]            # [B, t]
        mask = jnp.arange(Tmax)[None, None, :] <= qpos[:, :, None]
        maskx = mask[:, None, None]                    # [B,1,1,t,Tmax]
    else:
        kc = jax.lax.dynamic_update_slice(
            kc, kr.astype(kc.dtype), (0, p, 0, 0))
        vc = jax.lax.dynamic_update_slice(
            vc, v.astype(vc.dtype), (0, p, 0, 0))
        qpos = p + jnp.arange(t)[:, None]                     # [t, 1]
        kpos = jnp.arange(Tmax)[None, :]                      # [1, Tmax]
        mask = kpos <= qpos                          # causal over buffer
        maskx = mask[None, None, None]                 # [1,1,1,t,Tmax]
    qg = qr.reshape(b, t, kv, rep, D)
    scores = jnp.einsum("bqgrd,bkgd->bgrqk",
                        qg.astype(jnp.float32),
                        kc.astype(jnp.float32)) / (D ** 0.5)
    scores = jnp.where(maskx, scores, -1e30)
    # cast back to the CACHE dtype (the model dtype), not qr.dtype:
    # RoPE's float32 cos/sin tables promote a bf16 q to f32, and
    # keying on qr.dtype would upcast the whole value cache + output
    # to f32 on the bf16 decode path
    probs = jax.nn.softmax(scores, axis=-1).astype(vc.dtype)
    out = jnp.einsum("bgrqk,bkgd->bqgrd", probs, vc)
    return out.reshape(b, t, h * D), kc, vc
