"""Shared fixed-buffer KV-cache attention for serving decode.

One pure-jax routine used by every causal LM's static-cache path
(llama RoPE attention, gpt learned-position attention): write the new
k/v block into the fixed ``[B, Tmax, KV, D]`` buffers at the write
position (``dynamic_update_slice``) and attend over the causally
masked full buffer.

The write position ``p`` is either a SCALAR (the whole batch is at one
position — the synchronized ``generate()`` decode) or a PER-ROW
``[B]`` vector (every row at its own position — the continuous-batching
slot-pool decode, ``paddle_tpu/serving``). Both lower to the same
einsum contraction so per-row results are bitwise identical to the
scalar path's, which is what makes the serving engine's greedy outputs
token-identical to ``generate()``'s.

Both flavors also take an optional per-row write length ``wlen``
(``[B]`` int32) — the SPECULATIVE-VERIFY contract: row ``b`` carries
``wlen[b]`` real tokens (the last emitted token + its draft window)
followed by ``t - wlen[b]`` padding, and only the real tokens write
their k/v (token ``j``'s write is DROPPED when ``j >= wlen[b]`` —
out-of-range scatter index on the contiguous path, trash-page redirect
on the paged path), so padded lanes can never clobber live positions
or run past a row's budget. Reads are untouched: position ``j`` still
attends causally over everything ``<= pos + j``, so the per-position
outputs for ``j < wlen[b]`` are bitwise what a sequential
one-token-at-a-time decode would have computed — the greedy-identity
proof obligation of speculative decoding (paddle_tpu/serving engine,
``speculative=True``). NOTE: draft tokens the verifier then REJECTS
are within ``wlen`` and DO write — their k/v is garbage sitting at
positions >= the new write position. That is safe for the same reason
stale tails have always been safe here (the causal mask hides
positions beyond the current length, and each later step overwrites a
position right before first attending it), but it means decode-written
pages/rows must never be shared or indexed, and the serving engine's
page rollback only returns OVER-ALLOCATED pages, it does not (and need
not) scrub accepted-range pages.

TENSOR-PARALLEL serving note (serving/mesh.py): both attends are
mesh-safe by construction when the cache buffers/pools shard on their
``kv_heads`` axis — every einsum batches over that axis (GQA groups
fold into the per-kv-head contraction instead of crossing it), the
softmax reduces over positions, and the write scatter indexes only
batch/position dims, so no arithmetic ever crosses kv-heads and GSPMD
partitioning preserves BITWISE identity with the single-chip program.
The serving engine relies on this for its sharded token-identity law.

``paged_cache_attend`` is the PAGE-TABLE flavor of the same attention:
instead of one contiguous ``[B, Tmax, KV, D]`` row per sequence, k/v
live in a shared pool of fixed-size pages ``[num_pages, page, KV, D]``
and each row carries a static ``[B, pages_per_seq]`` int32 page table.
Writes scatter the new tokens through the table (flat position ``f``
lands in page ``table[b, f // page]`` at offset ``f % page``); reads
gather the row's pages back into a ``[B, pages_per_seq * page, KV, D]``
view and run the IDENTICAL masked einsum as ``cache_attend`` — when
``pages_per_seq * page == Tmax`` the contraction shapes match the
contiguous path exactly, which is what keeps paged greedy decode
token-identical to the slot-pool path. Optional int8 storage keeps the
pools in int8 with per-page f32 scales (one scale per page slot ×
position × kv-head, absmax over head_dim) and dequantizes inside the
attend.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["cache_attend", "check_cache_pos", "paged_cache_attend",
           "quantize_kv_page"]


def check_cache_pos(pos, t: int, Tmax: int) -> bool:
    """Validate a static-cache write position against the buffer and
    classify it: returns per_row (True when ``pos`` is a [B] vector).

    When the position is concrete (not under a jax trace), a write past
    the buffer fails HERE with a diagnosis — dynamic_update_slice would
    otherwise silently clamp and corrupt the cache tail."""
    pos_data = getattr(pos, "_data", pos)
    per_row = getattr(pos_data, "ndim", 0) >= 1
    concrete = pos if isinstance(pos, int) else (
        None if isinstance(pos_data, jax.core.Tracer)
        else int(np.asarray(pos_data).max()))
    if concrete is not None and concrete + t > Tmax:
        raise ValueError(
            f"static cache overflow: pos {concrete} + {t} new "
            f"tokens exceeds cache length {Tmax}")
    return per_row


def cache_attend(qr, kr, v, kc, vc, p, per_row: bool, wlen=None):
    """Masked fixed-buffer cache attention.

    qr: [B, t, H, D] position-encoded queries; kr/v: [B, t, KV, D] new
    keys (position-encoded) / values; kc/vc: [B, Tmax, KV, D] cache
    buffers; p: int32 write position — scalar, or [B] when ``per_row``.
    ``wlen`` ([B] int32, per_row only): only the first ``wlen[b]``
    incoming tokens of row ``b`` write their k/v (speculative verify —
    see module docstring); None = every token writes. GQA folds the
    query-group dim into the einsum against kv-head caches instead of
    materializing a head-repeated cache copy.

    Returns (out [B, t, H*D], kc', vc').
    """
    b, t, h, D = qr.shape
    kv = kr.shape[2]
    rep = h // kv
    Tmax = kc.shape[1]
    if wlen is not None and not per_row:
        # scalar-pos + wlen is the CHUNKED-PREFILL flavor (one row at
        # one position, a real-token count gating the padded tail):
        # broadcast the position and take the per-row masked-scatter
        # path, which is bitwise-identical for the same positions
        p = jnp.broadcast_to(jnp.asarray(p, jnp.int32), (b,))
        per_row = True
    if per_row:
        if wlen is None:
            upd = lambda c, u, pi: jax.lax.dynamic_update_slice(
                c, u.astype(c.dtype), (pi, 0, 0))
            kc = jax.vmap(upd)(kc, kr, p)
            vc = jax.vmap(upd)(vc, v, p)
        else:
            # write-masked scatter: token j of row b lands at p[b]+j
            # only when j < wlen[b] AND in range; everything else gets
            # index Tmax and mode="drop" discards it (a clamped
            # dynamic_update_slice would smear masked/overflowing
            # writes over the live tail instead)
            idx = p[:, None] + jnp.arange(t, dtype=jnp.int32)[None, :]
            ok = (jnp.arange(t, dtype=jnp.int32)[None, :]
                  < wlen[:, None]) & (idx < Tmax)
            widx = jnp.where(ok, idx, Tmax)
            bidx = jnp.arange(b)[:, None]
            kc = kc.at[bidx, widx].set(kr.astype(kc.dtype),
                                       mode="drop")
            vc = vc.at[bidx, widx].set(v.astype(vc.dtype),
                                       mode="drop")
        qpos = p[:, None] + jnp.arange(t)[None, :]            # [B, t]
        mask = jnp.arange(Tmax)[None, None, :] <= qpos[:, :, None]
        maskx = mask[:, None, None]                    # [B,1,1,t,Tmax]
    else:
        kc = jax.lax.dynamic_update_slice(
            kc, kr.astype(kc.dtype), (0, p, 0, 0))
        vc = jax.lax.dynamic_update_slice(
            vc, v.astype(vc.dtype), (0, p, 0, 0))
        qpos = p + jnp.arange(t)[:, None]                     # [t, 1]
        kpos = jnp.arange(Tmax)[None, :]                      # [1, Tmax]
        mask = kpos <= qpos                          # causal over buffer
        maskx = mask[None, None, None]                 # [1,1,1,t,Tmax]
    qg = qr.reshape(b, t, kv, rep, D)
    scores = jnp.einsum("bqgrd,bkgd->bgrqk",
                        qg.astype(jnp.float32),
                        kc.astype(jnp.float32)) / (D ** 0.5)
    scores = jnp.where(maskx, scores, -1e30)
    # cast back to the CACHE dtype (the model dtype), not qr.dtype:
    # RoPE's float32 cos/sin tables promote a bf16 q to f32, and
    # keying on qr.dtype would upcast the whole value cache + output
    # to f32 on the bf16 decode path
    probs = jax.nn.softmax(scores, axis=-1).astype(vc.dtype)
    out = jnp.einsum("bgrqk,bkgd->bqgrd", probs, vc)
    return out.reshape(b, t, h * D), kc, vc


def quantize_kv_page(x):
    """Symmetric int8 quantization of a k/v block ``[..., KV, D]``:
    per-(position, kv-head) absmax over head_dim. Returns (int8 values,
    f32 scales ``[..., KV]``). The scale floor keeps all-zero rows
    (never-written page tails) from dividing by zero."""
    amax = jnp.max(jnp.abs(x.astype(jnp.float32)), axis=-1)
    scale = jnp.maximum(amax / 127.0, 1e-8)
    q = jnp.clip(jnp.round(x.astype(jnp.float32) / scale[..., None]),
                 -127, 127).astype(jnp.int8)
    return q, scale


def _dequant(pool_rows, scale_rows):
    return pool_rows.astype(jnp.float32) * scale_rows[..., None]


def paged_cache_attend(qr, kr, v, kp, vp, ks, vs, table, p,
                       out_dtype, wlen=None):
    """Masked paged-pool cache attention (see module docstring).

    qr: [B, t, H, D] position-encoded queries; kr/v: [B, t, KV, D] new
    keys/values; kp/vp: [num_pages, page, KV, D] pools (int8 when
    ks/vs scales are given, else the model dtype); ks/vs: per-page f32
    scales [num_pages, page, KV] or None; table: [B, pages_per_seq]
    int32 page table (rows of inactive lanes must point at the
    reserved trash page 0); p: int32 write position, scalar or [B];
    ``wlen`` ([B] int32): only the first ``wlen[b]`` incoming tokens
    of row ``b`` write (speculative verify — masked writes land in the
    trash page); None = every token writes.

    Returns (out [B, t, H*D], kp', vp', ks', vs').
    """
    b, t, h, D = qr.shape
    kv = kr.shape[2]
    rep = h // kv
    page = kp.shape[1]
    Tmax = table.shape[1] * page
    pv = jnp.asarray(p, jnp.int32)
    if pv.ndim == 0:
        pv = jnp.broadcast_to(pv, (b,))
    qpos = pv[:, None] + jnp.arange(t, dtype=jnp.int32)[None, :]
    # bucket-padded writes (the shared-prefix extend prefill pads its
    # token block) can run past the table: redirect them into the
    # reserved trash page 0 — the gather clamp would otherwise smear
    # them over a REAL page at a wrong offset
    w_ok = qpos < Tmax
    if wlen is not None:
        w_ok = w_ok & (jnp.arange(t, dtype=jnp.int32)[None, :]
                       < wlen[:, None])
    pidx = jnp.minimum(qpos // page, table.shape[1] - 1)
    pid = jnp.where(w_ok,
                    jnp.take_along_axis(table, pidx, axis=1),
                    0)                                       # [B, t]
    off = jnp.where(w_ok, qpos % page, 0)
    quant = ks is not None
    if quant:
        kq, ksc = quantize_kv_page(kr)
        vq, vsc = quantize_kv_page(v)
        kp = kp.at[pid, off].set(kq)
        vp = vp.at[pid, off].set(vq)
        ks = ks.at[pid, off].set(ksc)
        vs = vs.at[pid, off].set(vsc)
    else:
        kp = kp.at[pid, off].set(kr.astype(kp.dtype))
        vp = vp.at[pid, off].set(v.astype(vp.dtype))
    # gather the row's pages into the contiguous attend view; with
    # pages_per_seq * page == Tmax this is value-identical to the
    # contiguous buffer, so the einsum below matches cache_attend's
    gather = lambda pool: pool[table].reshape(
        b, Tmax, *pool.shape[2:])
    kc = _dequant(gather(kp), gather(ks)) if quant else gather(kp)
    mask = jnp.arange(Tmax)[None, None, :] <= qpos[:, :, None]
    maskx = mask[:, None, None]                    # [B,1,1,t,Tmax]
    qg = qr.reshape(b, t, kv, rep, D)
    scores = jnp.einsum("bqgrd,bkgd->bgrqk",
                        qg.astype(jnp.float32),
                        kc.astype(jnp.float32)) / (D ** 0.5)
    scores = jnp.where(maskx, scores, -1e30)
    if quant:
        vc = _dequant(gather(vp), gather(vs)).astype(out_dtype)
        probs = jax.nn.softmax(scores, axis=-1).astype(out_dtype)
    else:
        # bf16 non-shared token-identity contract: same probs dtype
        # and same value einsum as the contiguous cache_attend
        vc = gather(vp)
        probs = jax.nn.softmax(scores, axis=-1).astype(vc.dtype)
    out = jnp.einsum("bgrqk,bkgd->bqgrd", probs, vc)
    return (out.reshape(b, t, h * D).astype(out_dtype),
            kp, vp, ks, vs)
