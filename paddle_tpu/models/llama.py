"""Llama model family (RMSNorm + RoPE + SwiGLU decoder).

Reference shape: the reference's end-to-end auto-parallel parity test is
a Llama (test/auto_parallel/hybrid_strategy/semi_auto_llama.py:98 —
full model under DPxMPxPP configs with acc-align and save/load). Built
from this framework's layers so it runs eagerly, under jit.to_static,
under dist.to_static/DistModel, and with the fleet TP layer library when
``use_tp`` — mirroring the GPT family's two-path design.
"""
from __future__ import annotations

import dataclasses
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..framework.tensor import apply_op
from ._decode_cache import (cache_attend, check_cache_pos,
                            paged_cache_attend)
from ..nn import functional as F
from ..nn.layer_base import Layer
from ..nn.layer.common import Embedding, Linear
from ..nn.layer.container import LayerList
from ..nn.layer.norm import RMSNorm

__all__ = ["LlamaConfig", "LlamaModel", "LlamaForCausalLM",
           "llama_tiny_config", "tp_param_spec"]


# raw_state() param names shardable along their OUTPUT (non-contracted)
# dim under tensor-parallel serving. Output-dim-only sharding is the
# deliberate TP slice that keeps sharded decode provably BITWISE
# token-identical to the single-chip engine: each shard computes full
# contractions over identical operands, collectives are pure data
# movement (all-gather), and no psum ever re-associates a float sum.
# gate/up_proj stay replicated — splitting them would shard
# down_proj's contraction dim and turn it into a partial-sum psum
# (serving/mesh.py, docs/SERVING.md "Multi-chip serving").
_TP_OUT_DIM_PARAMS = ("q_proj.weight", "k_proj.weight", "v_proj.weight",
                      "o_proj.weight", "down_proj.weight",
                      "lm_head.weight")


def tp_param_spec(name: str, shape, tp: int, axis: str = "model"):
    """PartitionSpec for one ``raw_state()`` param under the serving
    engine's tensor-parallel mesh, or None for replicated. Params a
    rule does not cover (norms, embeddings, gate/up_proj, quantized
    weights with their own names) replicate — always correct, just
    unsharded."""
    from jax.sharding import PartitionSpec
    if tp > 1 and name.endswith(_TP_OUT_DIM_PARAMS) \
            and len(shape) == 2 and shape[-1] % tp == 0:
        return PartitionSpec(None, axis)
    return None


@dataclasses.dataclass
class LlamaConfig:
    vocab_size: int = 32000
    hidden_size: int = 4096
    intermediate_size: int = 11008
    num_hidden_layers: int = 32
    num_attention_heads: int = 32
    num_key_value_heads: Optional[int] = None  # GQA; None = MHA
    max_position_embeddings: int = 4096
    rms_norm_eps: float = 1e-6
    rope_theta: float = 10000.0
    tie_word_embeddings: bool = False

    @property
    def head_dim(self):
        return self.hidden_size // self.num_attention_heads

    @property
    def kv_heads(self):
        return self.num_key_value_heads or self.num_attention_heads


def llama_tiny_config(**kw) -> LlamaConfig:
    kw.setdefault("vocab_size", 128)
    kw.setdefault("hidden_size", 64)
    kw.setdefault("intermediate_size", 128)
    kw.setdefault("num_hidden_layers", 2)
    kw.setdefault("num_attention_heads", 4)
    kw.setdefault("max_position_embeddings", 64)
    return LlamaConfig(**kw)


def _rope_cache(head_dim: int, max_len: int, theta: float):
    inv = 1.0 / (theta ** (np.arange(0, head_dim, 2, dtype=np.float32)
                           / head_dim))
    t = np.arange(max_len, dtype=np.float32)
    freqs = np.outer(t, inv)                      # [T, D/2]
    return np.cos(freqs), np.sin(freqs)


def _apply_rope(x, cos, sin):
    """x [B, T, H, D]; rotate pairs (x0,x1) per RoPE.

    cos/sin are [T, D/2] (shared positions) or [B, T, D/2] (per-row
    positions — the serving slot-pool decode)."""
    d2 = x.shape[-1] // 2
    x1 = x[..., :d2]
    x2 = x[..., d2:]
    if cos.ndim == 3:
        c = cos[:, :, None, :]
        s = sin[:, :, None, :]
    else:
        c = cos[None, :, None, :]
        s = sin[None, :, None, :]
    return jnp.concatenate([x1 * c - x2 * s, x2 * c + x1 * s], axis=-1)


class LlamaAttention(Layer):
    def __init__(self, cfg: LlamaConfig, use_tp: bool = False,
                 rope_cache=None):
        super().__init__()
        self.cfg = cfg
        H, KV, D = cfg.num_attention_heads, cfg.kv_heads, cfg.head_dim
        if use_tp:
            from ..distributed.fleet.mp_layers import (
                ColumnParallelLinear, RowParallelLinear)
            self.q_proj = ColumnParallelLinear(cfg.hidden_size, H * D,
                                               gather_output=False,
                                               has_bias=False)
            self.k_proj = ColumnParallelLinear(cfg.hidden_size, KV * D,
                                               gather_output=False,
                                               has_bias=False)
            self.v_proj = ColumnParallelLinear(cfg.hidden_size, KV * D,
                                               gather_output=False,
                                               has_bias=False)
            self.o_proj = RowParallelLinear(H * D, cfg.hidden_size,
                                            input_is_parallel=True,
                                            has_bias=False)
        else:
            self.q_proj = Linear(cfg.hidden_size, H * D, bias_attr=False)
            self.k_proj = Linear(cfg.hidden_size, KV * D,
                                 bias_attr=False)
            self.v_proj = Linear(cfg.hidden_size, KV * D,
                                 bias_attr=False)
            self.o_proj = Linear(H * D, cfg.hidden_size, bias_attr=False)
        if rope_cache is None:  # standalone use; model shares one cache
            cos, sin = _rope_cache(D, cfg.max_position_embeddings,
                                   cfg.rope_theta)
            rope_cache = (jnp.asarray(cos), jnp.asarray(sin))
        self._cos, self._sin = rope_cache

    def forward(self, x, attn_mask=None, cache=None):
        """cache: optional (k_cache, v_cache) Tensors [B, T_past, KV, D];
        when given, ``x`` holds only the NEW tokens and the return is
        (out, (k_cache', v_cache')) — the serving decode path."""
        cfg = self.cfg
        b, t, _ = x.shape
        # cache flavors: len 3 = contiguous static buffers (k, v, pos);
        # len 6 = paged pool (k_pool, v_pool, k_scale, v_scale,
        # page_table, pos) — paddle_tpu/serving's paged KV cache;
        # len 4 / len 7 append a per-row write-length `wlen` — the
        # speculative k-token VERIFY flavor (only the first wlen[b]
        # incoming tokens of row b write their k/v)
        static_cache = cache is not None and len(cache) in (3, 4, 6, 7)
        past = cache[0].shape[1] if cache is not None \
            and not static_cache and cache[0] is not None else 0
        if past + t > cfg.max_position_embeddings:
            raise ValueError(
                f"sequence length {past + t} exceeds "
                f"max_position_embeddings={cfg.max_position_embeddings}")
        D = cfg.head_dim
        q = self.q_proj(x)
        k = self.k_proj(x)
        v = self.v_proj(x)
        h_local = q.shape[-1] // D
        kv_local = k.shape[-1] // D
        q = q.reshape([b, t, h_local, D])
        k = k.reshape([b, t, kv_local, D])
        v = v.reshape([b, t, kv_local, D])
        if static_cache:
            # STATIC cache: (k_cache, v_cache, pos) with fixed [B, Tmax]
            # buffers and a (possibly traced) write position — the
            # compile-once serving decode path (one program per step
            # instead of a shape-changing concat per token).
            if attn_mask is not None:
                raise NotImplementedError(
                    "attn_mask with KV cache is not supported; pad-free "
                    "batches only in cached decoding")
            return self._forward_static_cache(x, q, k, v, cache)
        cos, sin = self._cos[past:past + t], self._sin[past:past + t]
        q = apply_op(lambda a: _apply_rope(a, cos, sin), q,
                     _op_name="rope_q")
        k = apply_op(lambda a: _apply_rope(a, cos, sin), k,
                     _op_name="rope_k")
        if cache is not None:
            if cache[0] is not None:  # (None, None) = empty prefill cache
                from ..ops.manipulation import concat
                k = concat([cache[0], k], axis=1)
                v = concat([cache[1], v], axis=1)
            new_cache = (k, v)
        if kv_local != h_local:  # GQA: repeat kv heads
            rep = h_local // kv_local
            k = apply_op(lambda a: jnp.repeat(a, rep, axis=2), k,
                         _op_name="gqa_repeat_k")
            v = apply_op(lambda a: jnp.repeat(a, rep, axis=2), v,
                         _op_name="gqa_repeat_v")
        if cache is not None:
            if attn_mask is not None:
                raise NotImplementedError(
                    "attn_mask with KV cache is not supported; pad-free "
                    "batches only in cached decoding")
            # decoding: new queries attend all cached positions plus the
            # causal prefix of the new block (the XLA sdpa bottom-right-
            # aligns the triangle when Sq < Skv)
            attn = F.scaled_dot_product_attention(
                q, k, v, is_causal=True, training=self.training)
            attn = attn.reshape([b, t, h_local * D])
            return self.o_proj(attn), new_cache
        if attn_mask is not None:
            # combine with causality: a decoder NEVER attends forward,
            # mask or not (a padding mask must not disable the triangle)
            causal = apply_op(
                lambda m: jnp.logical_and(
                    m.astype(bool),
                    jnp.tril(jnp.ones((t, t), bool))[None, None]),
                attn_mask, _op_name="causal_and_mask")
            attn = F.scaled_dot_product_attention(
                q, k, v, attn_mask=causal, training=self.training)
        else:
            attn = F.scaled_dot_product_attention(
                q, k, v, is_causal=True, training=self.training)
        attn = attn.reshape([b, t, h_local * D])
        return self.o_proj(attn)


    def _forward_static_cache(self, x, q, k, v, cache):
        """Fixed-size cache attention: write the new k/v block at ``pos``
        (dynamic_update_slice), attend over the masked full buffer.
        q/k/v arrive reshaped [b, t, heads_local, D]; cache =
        (k_cache [b, Tmax, KV, D], v_cache, pos). ``pos`` is a scalar
        (whole batch at one position — generate()) or a [b] vector of
        per-row positions (every row independent — the continuous-
        batching slot pool, paddle_tpu/serving).

        The 6-tuple flavor routes through paged_cache_attend instead:
        (k_pool, v_pool, k_scale, v_scale, page_table, pos) with
        [num_pages, page, KV, D] pools and a [b, pages_per_seq] int32
        table per row (scales None = model-dtype pages, set = int8
        pages with per-page f32 scales).

        The 4-tuple (k, v, pos, wlen) and 7-tuple (... pos, wlen)
        flavors are the speculative VERIFY forms: per-row [b] write
        lengths gate which of the t incoming tokens write their k/v
        (rejected-draft positions never touch the pools)."""
        t = q.shape[1]
        paged = len(cache) in (6, 7)
        wlen = None
        if paged:
            if len(cache) == 7:
                kp, vp, ksc, vsc, table, pos, wlen = cache
            else:
                kp, vp, ksc, vsc, table, pos = cache
            # t=1: only the START position must be in range — the
            # extend prefill's bucket padding may overshoot the table
            # and is redirected into the trash page by the attend
            per_row = check_cache_pos(
                pos, 1, table.shape[1] * kp.shape[1])
        else:
            if len(cache) == 4:
                k_cache, v_cache, pos, wlen = cache
            else:
                k_cache, v_cache, pos = cache
            # verify flavor: writes past the buffer are index-dropped
            # (cache_attend wlen scatter), so only the START position
            # must be in range, like the paged flavor
            per_row = check_cache_pos(
                pos, 1 if wlen is not None else t, k_cache.shape[1])
        cos_full, sin_full = self._cos, self._sin
        out_dtype = getattr(x, "_data", x).dtype   # the MODEL dtype

        def _rope(q, k, p):
            if wlen is not None:
                # verify / chunked prefill: p + t may run past the rope
                # table for rows near their length cap — a clamped
                # SLICE start would mis-rotate the real leading tokens,
                # so gather per POSITION with a clip that only touches
                # the masked tail (same fix as the paged extend path
                # below). p is [b] (verify) or a scalar (chunk flavor).
                pb = p[:, None] if getattr(p, "ndim", 0) >= 1 else p
                idx = jnp.clip(
                    pb + jnp.arange(t, dtype=jnp.int32)[None],
                    0, cos_full.shape[0] - 1)
                cos, sin = cos_full[idx], sin_full[idx]    # [b, t, D/2]
            elif per_row:
                sl = lambda tbl, pi: jax.lax.dynamic_slice_in_dim(
                    tbl, pi, t)
                cos = jax.vmap(partial(sl, cos_full))(p)   # [b, t, D/2]
                sin = jax.vmap(partial(sl, sin_full))(p)
            elif paged:
                # per-POSITION gather, not dynamic_slice: the paged
                # extend prefill's bucket padding may run p + t past
                # the rope table, and a clamped SLICE start would
                # silently shift the rotation of the real tail tokens.
                # Gathering clamps only the padding rows (whose writes
                # are trash-redirected / overwritten before any read).
                idx = jnp.clip(p + jnp.arange(t, dtype=jnp.int32),
                               0, cos_full.shape[0] - 1)
                cos, sin = cos_full[idx], sin_full[idx]
            else:
                cos = jax.lax.dynamic_slice_in_dim(cos_full, p, t)
                sin = jax.lax.dynamic_slice_in_dim(sin_full, p, t)
            return _apply_rope(q, cos, sin), _apply_rope(k, cos, sin)

        has_wl = wlen is not None
        if paged:
            def f(q, k, v, kp, vp, table, p, *rest):
                p = jnp.asarray(p, jnp.int32)
                if has_wl:
                    wl, rest = jnp.asarray(rest[0], jnp.int32), rest[1:]
                else:
                    wl = None
                qr, kr = _rope(q, k, p)
                ks, vs = rest if rest else (None, None)
                out, kp2, vp2, ks2, vs2 = paged_cache_attend(
                    qr, kr, v, kp, vp, ks, vs, table, p,
                    jnp.dtype(out_dtype), wlen=wl)
                return (out, kp2, vp2, ks2, vs2) if rest \
                    else (out, kp2, vp2)

            args = (q, k, v, kp, vp, table, pos) \
                + ((wlen,) if has_wl else ()) \
                + ((ksc, vsc) if ksc is not None else ())
            res = apply_op(f, *args, _op_name="paged_cache_attn")
            if ksc is not None:
                out, kp2, vp2, ks2, vs2 = res
            else:
                out, kp2, vp2 = res
                ks2, vs2 = None, None
            return self.o_proj(out), (kp2, vp2, ks2, vs2, table,
                                      pos + t)

        def f(q, k, v, kc, vc, p, *rest):
            p = jnp.asarray(p, jnp.int32)
            wl = jnp.asarray(rest[0], jnp.int32) if rest else None
            qr, kr = _rope(q, k, p)
            return cache_attend(qr, kr, v, kc, vc, p, per_row, wlen=wl)

        args = (q, k, v, k_cache, v_cache, pos) \
            + ((wlen,) if has_wl else ())
        out, kc2, vc2 = apply_op(f, *args,
                                 _op_name="static_cache_attn")
        return self.o_proj(out), (kc2, vc2, pos + t)


class LlamaMLP(Layer):
    def __init__(self, cfg: LlamaConfig, use_tp: bool = False):
        super().__init__()
        if use_tp:
            from ..distributed.fleet.mp_layers import (
                ColumnParallelLinear, RowParallelLinear)
            self.gate_proj = ColumnParallelLinear(
                cfg.hidden_size, cfg.intermediate_size,
                gather_output=False, has_bias=False)
            self.up_proj = ColumnParallelLinear(
                cfg.hidden_size, cfg.intermediate_size,
                gather_output=False, has_bias=False)
            self.down_proj = RowParallelLinear(
                cfg.intermediate_size, cfg.hidden_size,
                input_is_parallel=True, has_bias=False)
        else:
            self.gate_proj = Linear(cfg.hidden_size,
                                    cfg.intermediate_size,
                                    bias_attr=False)
            self.up_proj = Linear(cfg.hidden_size, cfg.intermediate_size,
                                  bias_attr=False)
            self.down_proj = Linear(cfg.intermediate_size,
                                    cfg.hidden_size, bias_attr=False)

    def forward(self, x):
        return self.down_proj(F.silu(self.gate_proj(x)) *
                              self.up_proj(x))


class LlamaDecoderLayer(Layer):
    def __init__(self, cfg: LlamaConfig, use_tp: bool = False,
                 rope_cache=None):
        super().__init__()
        self.input_layernorm = RMSNorm(cfg.hidden_size,
                                       epsilon=cfg.rms_norm_eps)
        self.self_attn = LlamaAttention(cfg, use_tp, rope_cache)
        self.post_attention_layernorm = RMSNorm(
            cfg.hidden_size, epsilon=cfg.rms_norm_eps)
        self.mlp = LlamaMLP(cfg, use_tp)

    def forward(self, x, attn_mask=None, cache=None):
        if cache is not None:
            a, new_cache = self.self_attn(self.input_layernorm(x),
                                          attn_mask, cache)
            x = x + a
            return x + self.mlp(self.post_attention_layernorm(x)), \
                new_cache
        x = x + self.self_attn(self.input_layernorm(x), attn_mask)
        return x + self.mlp(self.post_attention_layernorm(x))


class LlamaModel(Layer):
    def __init__(self, cfg: LlamaConfig, use_tp: bool = False):
        super().__init__()
        self.config = cfg
        if use_tp:
            from ..distributed.fleet.mp_layers import (
                VocabParallelEmbedding)
            self.embed_tokens = VocabParallelEmbedding(cfg.vocab_size,
                                                       cfg.hidden_size)
        else:
            self.embed_tokens = Embedding(cfg.vocab_size,
                                          cfg.hidden_size)
        cos, sin = _rope_cache(cfg.head_dim,
                               cfg.max_position_embeddings,
                               cfg.rope_theta)
        rope_cache = (jnp.asarray(cos), jnp.asarray(sin))
        self.layers = LayerList(
            [LlamaDecoderLayer(cfg, use_tp, rope_cache)
             for _ in range(cfg.num_hidden_layers)])
        self.norm = RMSNorm(cfg.hidden_size, epsilon=cfg.rms_norm_eps)

    def forward(self, input_ids, attn_mask=None, caches=None):
        x = self.embed_tokens(input_ids)
        if caches is not None:
            new_caches = []
            for layer, c in zip(self.layers, caches):
                x, nc = layer(x, attn_mask, c)
                new_caches.append(nc)
            return self.norm(x), new_caches
        for layer in self.layers:
            x = layer(x, attn_mask)
        return self.norm(x)


class LlamaForCausalLM(Layer):
    def __init__(self, cfg: LlamaConfig, use_tp: bool = False):
        super().__init__()
        self.config = cfg
        self.llama = LlamaModel(cfg, use_tp)
        if not cfg.tie_word_embeddings:
            self.lm_head = Linear(cfg.hidden_size, cfg.vocab_size,
                                  bias_attr=False)

    def forward(self, input_ids, attn_mask=None):
        return self._head(self.llama(input_ids, attn_mask))

    def loss(self, input_ids, labels):
        logits = self(input_ids)
        return F.cross_entropy(
            logits.reshape([-1, self.config.vocab_size]),
            labels.reshape([-1]))

    def _head(self, h):
        if self.config.tie_word_embeddings:
            from ..ops.linalg import matmul
            return matmul(h, self.llama.embed_tokens.weight,
                          transpose_y=True)
        return self.lm_head(h)

    def generate(self, input_ids, max_new_tokens: int = 16,
                 temperature: float = 0.0, top_p: float = 1.0,
                 use_cache="static"):
        """Greedy / nucleus decoding.

        use_cache:
          - True / "static" (default): compile-once serving path — one
            jitted prefill program + one jitted decode-step program over
            fixed-size KV buffers written at the current position.
          - "dynamic": concat-grown KV cache, one trace per length
            (numerics reference; also used automatically under tracing).
          - False: no cache, full-context recompute per token.
        """
        import paddle_tpu as paddle
        from ..ops.manipulation import concat
        ids = input_ids

        def pick(last):
            if temperature <= 0:
                return apply_op(
                    lambda a: jnp.argmax(a, axis=-1).astype(jnp.int64)[
                        :, None], last, _op_name="greedy")
            probs = F.softmax(last / temperature, axis=-1)
            ps = paddle.full([ids.shape[0]], top_p, dtype="float32")
            return paddle.top_p_sampling(probs, ps)[1]

        if max_new_tokens <= 0:
            return ids
        if not use_cache:
            for _ in range(max_new_tokens):
                nxt = pick(self(ids)[:, -1])
                ids = concat([ids, nxt], axis=1)
            return ids

        if use_cache != "dynamic" and not isinstance(
                ids._data, jax.core.Tracer):
            return self._generate_static(ids, max_new_tokens, pick,
                                         greedy=temperature <= 0)

        # dynamic-cache path (shape grows per step; kept for tracing and
        # as the numerics reference): (None, None) makes each layer seed
        # its cache with ITS local k/v (correct head count and dtype
        # under tensor parallelism too)
        h, caches = self.llama(
            ids, caches=[(None, None)] * len(self.llama.layers))
        nxt = pick(self._head(h[:, -1:])[:, -1])
        ids = concat([ids, nxt], axis=1)
        for _ in range(max_new_tokens - 1):
            h, caches = self.llama(nxt, caches=caches)
            nxt = pick(self._head(h[:, -1:])[:, -1])
            ids = concat([ids, nxt], axis=1)
        return ids

    # -- compile-once serving decode --------------------------------------
    def _cached_step(self, params, buffers, tok_arr, ks, vs, pos):
        """One static-cache model step (shared by the per-step and the
        fused decode programs): tokens in, last-token logits + updated
        fixed-size caches out."""
        from ..framework.tensor import Tensor as _T
        caches = [(_T(k), _T(v), _T(pos)) for k, v in zip(ks, vs)]
        with self.bind_state(params, buffers):
            h, new_caches = self.llama(_T(tok_arr), None, caches)
            logits = self._head(h[:, -1:])
        return (logits._data[:, -1],
                [c[0]._data for c in new_caches],
                [c[1]._data for c in new_caches])

    def _decode_pure(self):
        """One jitted program covering prefill (t=prompt) and decode
        (t=1): runs the static-cache path and returns last-token logits
        plus the updated fixed-size caches (donated)."""
        if getattr(self, "_decode_jit", None) is not None:
            return self._decode_jit

        def pure(params, buffers, ids_arr, ks, vs, pos):
            return self._cached_step(params, buffers, ids_arr, ks, vs,
                                     jnp.asarray(pos))

        self._decode_jit = jax.jit(pure, donate_argnums=(3, 4))
        return self._decode_jit

    def _decode_fused_greedy(self):
        """Prefill + the ENTIRE greedy decode loop as ONE jitted program
        (lax.scan over decode steps). The per-step host loop costs ~5 ms
        of dispatch per program through a tunneled/remote chip — 3
        programs/token made bs=1 decode dispatch-bound; fused, a whole
        generate() is a single dispatch. ``steps`` is a static arg, so
        jax's own compile cache keys on it."""
        fn = getattr(self, "_decode_fused_jit", None)
        if fn is not None:
            return fn

        def greedy(logits, dtype):
            return jnp.argmax(logits, axis=-1).astype(dtype)[:, None]

        def pure(params, buffers, ids_arr, ks, vs, steps):
            T0 = ids_arr.shape[1]
            last, ks, vs = self._cached_step(params, buffers, ids_arr,
                                             ks, vs, jnp.asarray(0))
            first = greedy(last, ids_arr.dtype)

            def body(carry, _):
                tok, ks, vs, pos = carry
                last, ks, vs = self._cached_step(params, buffers, tok,
                                                 ks, vs, pos)
                nxt = greedy(last, ids_arr.dtype)
                return (nxt, ks, vs, pos + 1), nxt[:, 0]

            _, toks = jax.lax.scan(
                body, (first, ks, vs, jnp.asarray(T0)), None,
                length=steps - 1)
            # [prompt | first generated token | scan-emitted tokens]
            return jnp.concatenate([ids_arr, first, toks.T], axis=1)

        fn = jax.jit(pure, donate_argnums=(3, 4), static_argnums=(5,))
        self._decode_fused_jit = fn
        return fn

    def _generate_static(self, ids, max_new_tokens, pick, greedy=False):
        from ..ops.manipulation import concat
        import paddle_tpu as paddle
        cfg = self.config
        B, T0 = ids.shape
        L = len(self.llama.layers)
        D = cfg.head_dim
        attn0 = self.llama.layers[0].self_attn
        # k_proj may be a Linear (weight [in, out]) or a weight-only
        # Int8Linear (wq [in, out] int8) after quantization
        kp = attn0.k_proj
        kw = kp.weight if hasattr(kp, "weight") else kp.wq
        kv_local = kw.shape[-1] // D
        dtype = self.llama.embed_tokens.weight._data.dtype
        # round the buffer up so nearby generation lengths share programs
        want = T0 + max_new_tokens
        max_len = min(cfg.max_position_embeddings,
                      ((want + 63) // 64) * 64)
        if want > cfg.max_position_embeddings:
            raise ValueError(
                f"sequence length {want} exceeds "
                f"max_position_embeddings={cfg.max_position_embeddings}")
        params, buffers = self.raw_state()
        ks = [jnp.zeros((B, max_len, kv_local, D), dtype)
              for _ in range(L)]
        vs = [jnp.zeros((B, max_len, kv_local, D), dtype)
              for _ in range(L)]
        from ..framework.tensor import Tensor as _T
        if greedy:
            fused = self._decode_fused_greedy()
            return _T(fused(params, buffers, ids._data, ks, vs,
                            max_new_tokens))
        fn = self._decode_pure()
        last, ks, vs = fn(params, buffers, ids._data, ks, vs, 0)
        nxt = pick(_T(last))
        ids = concat([ids, nxt], axis=1)
        pos = T0
        for _ in range(max_new_tokens - 1):
            last, ks, vs = fn(params, buffers, nxt._data, ks, vs, pos)
            nxt = pick(_T(last))
            ids = concat([ids, nxt], axis=1)
            pos += 1
        return ids
