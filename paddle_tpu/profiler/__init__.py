"""Profiler (reference: python/paddle/profiler/profiler.py:358 Profiler with
state-machine scheduler, make_scheduler:129, export_chrome_tracing:227,
summary tables; C++ host/CUPTI tracers under
/root/reference/paddle/fluid/platform/profiler/).

TPU-native: the device timeline comes from the JAX/XLA profiler (XPlane →
TensorBoard/perfetto); this module keeps the reference's python surface —
RecordEvent host annotations, the CLOSED/READY/RECORD scheduler states,
chrome-trace export of host events, and a summary table — and starts/stops
jax.profiler traces for device capture (SURVEY.md §5 tracing mapping).
"""
from __future__ import annotations

import contextlib
import json
import os
import threading
import time
from enum import Enum
from typing import Callable, Iterable, List, Optional

__all__ = ["Profiler", "ProfilerState", "ProfilerTarget", "RecordEvent",
           "make_scheduler", "export_chrome_tracing", "export_metrics",
           "load_profiler_result", "SortedKeys", "benchmark"]


class ProfilerState(Enum):
    CLOSED = 0
    READY = 1
    RECORD = 2
    RECORD_AND_RETURN = 3


class ProfilerTarget(Enum):
    CPU = 0
    GPU = 1
    TPU = 2
    CUSTOM_DEVICE = 3


class SortedKeys(Enum):
    CPUTotal = 0
    CPUAvg = 1
    CPUMax = 2
    GPUTotal = 3


_events_lock = threading.Lock()
_events: List[dict] = []
# PROCESS-WIDE recording flag (was threading.local(): Profiler.start()
# only flipped the flag in the calling thread, so RecordEvents from
# dataloader/watchdog worker threads were silently dropped — the whole
# point of host tracing is seeing those threads). One-element list so
# _transition mutates in place; _events_lock still guards the list.
_recording = [False]


def _is_recording() -> bool:
    return _recording[0]


class RecordEvent:
    """Host-side annotation (reference: platform/profiler/event_tracing.h:43
    RecordEvent — emitted inside every generated ad_func). Also forwards to
    jax.profiler.TraceAnnotation so events appear in XPlane traces.

    ``args`` (a dict) lands in the chrome trace event's ``args`` field —
    observability spans use it to carry request ids; it is read at
    ``end()`` time, so attributes added mid-span are captured."""

    def __init__(self, name: str, event_type=None, args=None):
        self.name = name
        self.args = args
        self._t0 = None
        self._jax_ann = None

    def begin(self):
        self._t0 = time.perf_counter_ns()
        if _is_recording():
            try:
                import jax.profiler
                self._jax_ann = jax.profiler.TraceAnnotation(self.name)
                self._jax_ann.__enter__()
            except Exception:
                self._jax_ann = None

    def end(self):
        if self._t0 is None:
            return
        t1 = time.perf_counter_ns()
        if self._jax_ann is not None:
            self._jax_ann.__exit__(None, None, None)
            self._jax_ann = None
        if _is_recording():
            ev = {
                "name": self.name, "ph": "X", "pid": os.getpid(),
                "tid": threading.get_ident(),
                "ts": self._t0 / 1000.0,
                "dur": (t1 - self._t0) / 1000.0,
                "cat": "host",
            }
            if self.args:
                ev["args"] = {k: (v if isinstance(
                    v, (int, float, str, bool, type(None))) else repr(v))
                    for k, v in self.args.items()}
            with _events_lock:
                _events.append(ev)
        self._t0 = None

    def __enter__(self):
        self.begin()
        return self

    def __exit__(self, *exc):
        self.end()
        return False


def make_scheduler(*, closed: int, ready: int, record: int, repeat: int = 0,
                   skip_first: int = 0) -> Callable[[int], ProfilerState]:
    """Reference profiler.py:129 — step-indexed state machine."""
    period = closed + ready + record

    def scheduler(step: int) -> ProfilerState:
        if step < skip_first:
            return ProfilerState.CLOSED
        s = step - skip_first
        if repeat and s >= repeat * period:
            return ProfilerState.CLOSED
        pos = s % period
        if pos < closed:
            return ProfilerState.CLOSED
        if pos < closed + ready:
            return ProfilerState.READY
        if pos == period - 1:
            return ProfilerState.RECORD_AND_RETURN
        return ProfilerState.RECORD

    return scheduler


def export_chrome_tracing(dir_name: str, worker_name: Optional[str] = None):
    def handler(prof: "Profiler"):
        os.makedirs(dir_name, exist_ok=True)
        fname = worker_name or f"worker_{os.getpid()}"
        path = os.path.join(dir_name, f"{fname}.json")
        prof._export_chrome(path)
        print(f"[profiler] chrome trace written to {path}")

    return handler


def export_metrics(dir_name: str, worker_name: Optional[str] = None,
                   fmt: str = "prometheus"):
    """on_trace_ready-style handler writing the observability metrics
    registry snapshot next to the trace, so one run yields BOTH a
    chrome trace and a metrics snapshot::

        prof = Profiler(on_trace_ready=lambda p: (
            export_chrome_tracing("./out")(p),
            export_metrics("./out")(p)))
    """
    def handler(prof: "Profiler"):
        os.makedirs(dir_name, exist_ok=True)
        fname = worker_name or f"worker_{os.getpid()}"
        ext = "prom" if fmt == "prometheus" else "json"
        path = os.path.join(dir_name, f"{fname}.{ext}")
        prof.export_metrics(path, fmt=fmt)
        print(f"[profiler] metrics snapshot written to {path}")

    return handler


def load_profiler_result(path: str):
    with open(path) as f:
        return json.load(f)


class Profiler:
    def __init__(self, *, targets: Optional[Iterable] = None,
                 scheduler=None, on_trace_ready=None, timer_only=False,
                 record_shapes=False, profile_memory=False,
                 with_flops=False):
        if scheduler is None:
            self._scheduler = lambda step: ProfilerState.RECORD
        elif isinstance(scheduler, (tuple, list)):
            start, end = scheduler
            self._scheduler = make_scheduler(closed=start, ready=0,
                                             record=end - start, repeat=1)
        else:
            self._scheduler = scheduler
        self._on_trace_ready = on_trace_ready
        self._timer_only = timer_only
        self.step_num = 0
        self._state = ProfilerState.CLOSED
        self._jax_dir = None
        self._step_times: List[float] = []
        self._last_step_t = None

    # -- lifecycle ---------------------------------------------------------
    def start(self):
        self._transition(self._scheduler(self.step_num))

    def stop(self):
        self._transition(ProfilerState.CLOSED, final=True)

    def step(self, num_samples: Optional[int] = None):
        now = time.perf_counter()
        if self._last_step_t is not None:
            self._step_times.append((now - self._last_step_t,
                                     num_samples))
        self._last_step_t = now
        self.step_num += 1
        self._transition(self._scheduler(self.step_num))

    def _transition(self, new_state: ProfilerState, final=False):
        recording = self._state in (ProfilerState.RECORD,
                                    ProfilerState.RECORD_AND_RETURN)
        will_record = new_state in (ProfilerState.RECORD,
                                    ProfilerState.RECORD_AND_RETURN)
        if will_record and not recording:
            _recording[0] = True
            if not self._timer_only:
                try:
                    import jax.profiler
                    self._jax_dir = "/tmp/paddle_tpu_xplane"
                    jax.profiler.start_trace(self._jax_dir)
                except Exception:
                    self._jax_dir = None
        if (recording and not will_record) or \
                (final and recording):
            _recording[0] = False
            if self._jax_dir is not None:
                try:
                    import jax.profiler
                    jax.profiler.stop_trace()
                except Exception:
                    pass
                self._jax_dir = None
            if self._on_trace_ready is not None:
                self._on_trace_ready(self)
        self._state = new_state

    def __enter__(self):
        self.start()
        return self

    def __exit__(self, *exc):
        self.stop()
        return False

    # -- output ------------------------------------------------------------
    def _export_chrome(self, path: str):
        with _events_lock:
            events = list(_events)
        with open(path, "w") as f:
            json.dump({"traceEvents": events,
                       "displayTimeUnit": "ms"}, f)

    def export_chrome_tracing(self, path: str):
        self._export_chrome(path)

    def export_metrics(self, path: str, fmt: str = "prometheus") -> str:
        """Write the observability default-registry snapshot to
        ``path`` (``fmt``: "prometheus" text exposition or "json") and
        return the serialized text — the metrics half of a run whose
        chrome/XPlane traces come from this same profiler."""
        from ..observability import default_registry
        reg = default_registry()
        text = reg.to_prometheus() if fmt == "prometheus" \
            else reg.to_json_str(indent=1)
        with open(path, "w") as f:
            f.write(text)
        return text

    def summary(self, sorted_by=SortedKeys.CPUTotal, op_detail=True,
                thread_sep=False, time_unit="ms"):
        with _events_lock:
            events = list(_events)
        agg = {}
        for e in events:
            st = agg.setdefault(e["name"], [0.0, 0, 0.0])
            st[0] += e["dur"] / 1000.0
            st[1] += 1
            st[2] = max(st[2], e["dur"] / 1000.0)
        rows = sorted(agg.items(), key=lambda kv: -kv[1][0])
        lines = [f"{'Name':<40}{'Calls':>8}{'Total(ms)':>12}{'Max(ms)':>12}"]
        for name, (total, calls, mx) in rows[:50]:
            lines.append(f"{name[:40]:<40}{calls:>8}{total:>12.3f}"
                         f"{mx:>12.3f}")
        table = "\n".join(lines)
        print(table)
        return table


class benchmark:
    """Throughput timer (reference: profiler/timer.py:351 Benchmark —
    step_info ips)."""

    def __init__(self):
        self._times = []
        self._t = None

    def begin(self):
        self._t = time.perf_counter()

    def step(self, num_samples=1):
        now = time.perf_counter()
        if self._t is not None:
            self._times.append((now - self._t, num_samples))
        self._t = now

    def step_info(self, unit="samples"):
        if not self._times:
            return "no steps recorded"
        dts = [t for t, _ in self._times]
        ns = [n for _, n in self._times]
        ips = sum(ns) / sum(dts)
        return (f"avg step {1000 * sum(dts) / len(dts):.2f} ms, "
                f"ips {ips:.1f} {unit}/s")

    def end(self):
        pass


class SummaryView:
    """profiler.SummaryView enum (profiler/profiler.py)."""
    DeviceView = 0
    OverView = 1
    ModelView = 2
    DistributedView = 3
    KernelView = 4
    OperatorView = 5
    MemoryView = 6
    MemoryManipulationView = 7
    UDFView = 8


def export_protobuf(path: str):
    """Serialized-dump export hook (the reference dumps protobuf event
    trees; here the chrome-trace JSON is the canonical dump and this
    writes it at ``path``)."""
    def handler(prof):
        prof.export_chrome_tracing(path)
    return handler


__all__ += ["SummaryView", "export_protobuf"]
