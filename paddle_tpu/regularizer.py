"""Weight-decay regularizers (reference: python/paddle/regularizer.py —
L1Decay/L2Decay appended as grad terms by the optimizer).

TPU-native: a regularizer is a pure function grad' = grad + d/dp penalty(p);
the optimizer applies it inside its jitted update, so XLA fuses it with the
main update kernel (the reference has dedicated CUDA append-regularization
ops)."""
from __future__ import annotations

import jax.numpy as jnp

__all__ = ["L1Decay", "L2Decay"]


class WeightDecayRegularizer:
    def apply(self, param, grad):
        raise NotImplementedError


class L1Decay(WeightDecayRegularizer):
    def __init__(self, coeff=0.0):
        self.coeff = float(coeff)

    def apply(self, param, grad):
        return grad + self.coeff * jnp.sign(param)

    def __repr__(self):
        return f"L1Decay(coeff={self.coeff})"


class L2Decay(WeightDecayRegularizer):
    def __init__(self, coeff=0.0):
        self.coeff = float(coeff)

    def apply(self, param, grad):
        return grad + self.coeff * param

    def __repr__(self):
        return f"L2Decay(coeff={self.coeff})"
