"""paddle.callbacks namespace (reference: python/paddle/callbacks.py
re-exporting hapi.callbacks)."""
from .hapi.callbacks import (Callback, EarlyStopping,  # noqa: F401
                             LRScheduler, ModelCheckpoint, ProgBarLogger,
                             ReduceLROnPlateau, VisualDL, WandbCallback)

__all__ = ["Callback", "EarlyStopping", "LRScheduler", "ModelCheckpoint",
           "ProgBarLogger", "ReduceLROnPlateau", "VisualDL",
           "WandbCallback"]
