"""paddle_tpu.text (reference: /root/reference/python/paddle/text/
__init__.py — viterbi_decode:31 / ViterbiDecoder:110; dataset loaders are
IO-bound and live in paddle_tpu.io).

TPU-first: the Viterbi DP is a ``lax.scan`` over time with a vectorized
[B, C_prev, C] max-plus inner step (the reference is a hand CUDA kernel,
paddle/phi/kernels/gpu/viterbi_decode_kernel.cu); variable lengths are
handled by identity backpointers past each sequence's end, so the whole
batch decodes in one compiled graph.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..framework.tensor import Tensor
from ..nn.layer_base import Layer

__all__ = ["viterbi_decode", "ViterbiDecoder"]


def _arr(t):
    return t._data if isinstance(t, Tensor) else jnp.asarray(t)


def viterbi_decode(potentials, transition_params, lengths,
                   include_bos_eos_tag: bool = True, name=None):
    """Batched Viterbi decode → (scores [B], paths [B, max_len])."""
    pot = _arr(potentials)
    trans = _arr(transition_params)
    lens = _arr(lengths).astype(jnp.int32)
    b, seq_len, c = pot.shape
    max_len = int(jnp.max(lens)) if lens.size else 0
    if max_len == 0:
        return (Tensor(jnp.zeros((b,), pot.dtype)),
                Tensor(jnp.zeros((b, 0), jnp.int32)))
    start_tag, stop_tag = c - 1, c - 2

    alpha = pot[:, 0]
    if include_bos_eos_tag:
        alpha = alpha + trans[start_tag][None]

    identity_bp = jnp.broadcast_to(jnp.arange(c, dtype=jnp.int32), (b, c))

    def step(alpha, t):
        scores = alpha[:, :, None] + trans[None]          # [B, Cprev, C]
        best_prev = jnp.argmax(scores, axis=1).astype(jnp.int32)
        alpha_new = jnp.max(scores, axis=1) + pot[:, t]
        live = (t < lens)[:, None]
        return (jnp.where(live, alpha_new, alpha),
                jnp.where(live, best_prev, identity_bp))

    alpha, hists = jax.lax.scan(step, alpha, jnp.arange(1, max_len))
    if include_bos_eos_tag:
        alpha = alpha + trans[:, stop_tag][None]

    scores = jnp.max(alpha, axis=1)
    last_tag = jnp.argmax(alpha, axis=1).astype(jnp.int32)

    def back(tag, hist_t):
        prev = jnp.take_along_axis(hist_t, tag[:, None], 1)[:, 0]
        return prev, prev

    _, prev_tags = jax.lax.scan(back, last_tag, hists, reverse=True)
    # prev_tags[k] = tag at position k (k = 0..max_len-2)
    path = jnp.concatenate(
        [jnp.swapaxes(prev_tags, 0, 1), last_tag[:, None]], axis=1) \
        if max_len > 1 else last_tag[:, None]
    # zero-pad positions beyond each sequence's length
    path = jnp.where(jnp.arange(max_len)[None] < lens[:, None], path, 0)
    return Tensor(scores), Tensor(path)


class ViterbiDecoder(Layer):
    """Layer wrapper holding the transition matrix
    (text/viterbi_decode.py:110)."""

    def __init__(self, transitions, include_bos_eos_tag: bool = True,
                 name=None):
        super().__init__()
        self.transitions = transitions
        self.include_bos_eos_tag = include_bos_eos_tag

    def forward(self, potentials, lengths):
        return viterbi_decode(potentials, self.transitions, lengths,
                              self.include_bos_eos_tag)


# ---------------------------------------------------------------------------
# classic text datasets (reference: python/paddle/text/datasets/*) —
# file-backed; the reference auto-downloads, this build has no network
# egress so ``data_file`` must point at a local copy.
# ---------------------------------------------------------------------------

from ..io.dataset import Dataset as _Dataset


class _FileDataset(_Dataset):
    """Shared shape for the classic datasets: a local archive/file path
    plus a parse step; raises with download instructions if absent."""

    URL = ""
    NAME = "dataset"

    def __init__(self, data_file=None, mode="train", **kwargs):
        import os
        self.mode = mode
        if data_file is None or not os.path.exists(data_file):
            raise FileNotFoundError(
                f"{self.NAME}: pass data_file= pointing at a local copy "
                f"(this environment has no network egress; reference "
                f"source: {self.URL})")
        self.data_file = data_file
        self._samples = self._load()

    def _load(self):
        raise NotImplementedError

    def __getitem__(self, idx):
        return self._samples[idx]

    def __len__(self):
        return len(self._samples)


class UCIHousing(_FileDataset):
    """UCI Boston housing (text/datasets/uci_housing.py): 13 features +
    price per line, whitespace-separated."""

    URL = "http://paddlemodels.bj.bcebos.com/uci_housing/housing.data"
    NAME = "UCIHousing"

    def _load(self):
        import numpy as _np
        rows = []
        with open(self.data_file) as f:
            for line in f:
                vals = [float(v) for v in line.split()]
                if len(vals) == 14:
                    rows.append(vals)
        arr = _np.asarray(rows, _np.float32)
        # normalize with FULL-dataset statistics, then split (the
        # reference preprocesses before splitting, so train/test share
        # one feature scale)
        mean, std = arr[:, :13].mean(0), arr[:, :13].std(0) + 1e-8
        n = len(arr)
        split = int(n * 0.8)
        arr = arr[:split] if self.mode == "train" else arr[split:]
        return [((r[:13] - mean) / std, r[13:]) for r in arr]


class Imdb(_FileDataset):
    """IMDB sentiment (text/datasets/imdb.py): expects the aclImdb tgz
    or an extracted dir with pos/ and neg/ subdirs per split."""

    URL = "https://dataset.bj.bcebos.com/imdb%2FaclImdb_v1.tar.gz"
    NAME = "Imdb"

    def __init__(self, data_file=None, mode="train", cutoff=150, **kw):
        self.cutoff = cutoff
        super().__init__(data_file, mode, **kw)

    def _iter_texts(self, split):
        import os
        import re as _re
        base = os.path.join(self.data_file, split)
        for label, sub in ((1, "pos"), (0, "neg")):
            d = os.path.join(base, sub)
            if not os.path.isdir(d):
                continue
            for fn in sorted(os.listdir(d))[:5000]:
                text = open(os.path.join(d, fn),
                            encoding="utf-8", errors="ignore").read()
                yield _re.findall(r"[a-z\']+", text.lower()), label

    def _load(self):
        # vocab over BOTH splits with frequency cutoff, deterministic
        # (freq desc, then token) — train/test must share word ids
        from collections import Counter
        freq = Counter()
        for split in ("train", "test"):
            for toks, _ in self._iter_texts(split):
                freq.update(toks)
        kept = sorted((t for t, c in freq.items() if c >= min(
            self.cutoff, max(freq.values()) if freq else 1)),
            key=lambda t: (-freq[t], t))
        vocab = {t: i for i, t in enumerate(kept)}
        unk = len(vocab)
        samples = []
        for toks, label in self._iter_texts(self.mode):
            samples.append(([vocab.get(t, unk) for t in toks], label))
        self.word_idx = vocab
        return samples


class Imikolov(_FileDataset):
    """PTB-style n-gram dataset (text/datasets/imikolov.py)."""

    URL = "https://dataset.bj.bcebos.com/imikolov%2Fsimple-examples.tgz"
    NAME = "Imikolov"

    def __init__(self, data_file=None, data_type="NGRAM", window_size=5,
                 mode="train", min_word_freq=50, **kw):
        self.window_size = window_size
        self.data_type = data_type
        super().__init__(data_file, mode, **kw)

    def _load(self):
        lines = open(self.data_file, encoding="utf-8",
                     errors="ignore").read().splitlines()
        vocab = {"<unk>": 0}
        grams = []
        for ln in lines:
            toks = ln.split()
            ids = []
            for t in toks:
                if t not in vocab:
                    vocab[t] = len(vocab)
                ids.append(vocab[t])
            for i in range(len(ids) - self.window_size + 1):
                grams.append(tuple(ids[i:i + self.window_size]))
        self.word_idx = vocab
        return grams


class Movielens(_FileDataset):
    """MovieLens ratings (text/datasets/movielens.py): expects the
    ml-1m ratings.dat ('uid::mid::rating::ts')."""

    URL = "https://dataset.bj.bcebos.com/movielens%2Fml-1m.zip"
    NAME = "Movielens"

    def _load(self):
        rows = []
        for ln in open(self.data_file, encoding="utf-8",
                       errors="ignore"):
            parts = ln.strip().split("::")
            if len(parts) >= 3:
                rows.append((int(parts[0]), int(parts[1]),
                             float(parts[2])))
        n = len(rows)
        split = int(n * 0.9)
        return rows[:split] if self.mode == "train" else rows[split:]


class Conll05st(_FileDataset):
    """CoNLL-2005 SRL (text/datasets/conll05.py): expects the
    preprocessed word/label file pairs joined by tab."""

    URL = "http://paddlemodels.bj.bcebos.com/conll05st/conll05st-tests.tar.gz"
    NAME = "Conll05st"

    def _load(self):
        samples = []
        for ln in open(self.data_file, encoding="utf-8",
                       errors="ignore"):
            parts = ln.rstrip("\n").split("\t")
            if len(parts) >= 2:
                samples.append((parts[0].split(), parts[1].split()))
        return samples


class _WMTBase(_FileDataset):
    def _load(self):
        samples = []
        for ln in open(self.data_file, encoding="utf-8",
                       errors="ignore"):
            parts = ln.rstrip("\n").split("\t")
            if len(parts) >= 2:
                samples.append((parts[0].split(), parts[1].split()))
        return samples


class WMT14(_WMTBase):
    """WMT'14 en-fr (text/datasets/wmt14.py): tab-separated parallel
    sentences per line."""
    URL = "http://paddlemodels.bj.bcebos.com/wmt/wmt14.tgz"
    NAME = "WMT14"


class WMT16(_WMTBase):
    """WMT'16 en-de (text/datasets/wmt16.py)."""
    URL = "http://paddlemodels.bj.bcebos.com/wmt/wmt16.tar.gz"
    NAME = "WMT16"


__all__ += ["UCIHousing", "Imdb", "Imikolov", "Movielens", "Conll05st",
            "WMT14", "WMT16"]
