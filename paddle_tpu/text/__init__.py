"""paddle_tpu.text (reference: /root/reference/python/paddle/text/
__init__.py — viterbi_decode:31 / ViterbiDecoder:110; dataset loaders are
IO-bound and live in paddle_tpu.io).

TPU-first: the Viterbi DP is a ``lax.scan`` over time with a vectorized
[B, C_prev, C] max-plus inner step (the reference is a hand CUDA kernel,
paddle/phi/kernels/gpu/viterbi_decode_kernel.cu); variable lengths are
handled by identity backpointers past each sequence's end, so the whole
batch decodes in one compiled graph.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..framework.tensor import Tensor
from ..nn.layer_base import Layer

__all__ = ["viterbi_decode", "ViterbiDecoder"]


def _arr(t):
    return t._data if isinstance(t, Tensor) else jnp.asarray(t)


def viterbi_decode(potentials, transition_params, lengths,
                   include_bos_eos_tag: bool = True, name=None):
    """Batched Viterbi decode → (scores [B], paths [B, max_len])."""
    pot = _arr(potentials)
    trans = _arr(transition_params)
    lens = _arr(lengths).astype(jnp.int32)
    b, seq_len, c = pot.shape
    max_len = int(jnp.max(lens)) if lens.size else 0
    if max_len == 0:
        return (Tensor(jnp.zeros((b,), pot.dtype)),
                Tensor(jnp.zeros((b, 0), jnp.int32)))
    start_tag, stop_tag = c - 1, c - 2

    alpha = pot[:, 0]
    if include_bos_eos_tag:
        alpha = alpha + trans[start_tag][None]

    identity_bp = jnp.broadcast_to(jnp.arange(c, dtype=jnp.int32), (b, c))

    def step(alpha, t):
        scores = alpha[:, :, None] + trans[None]          # [B, Cprev, C]
        best_prev = jnp.argmax(scores, axis=1).astype(jnp.int32)
        alpha_new = jnp.max(scores, axis=1) + pot[:, t]
        live = (t < lens)[:, None]
        return (jnp.where(live, alpha_new, alpha),
                jnp.where(live, best_prev, identity_bp))

    alpha, hists = jax.lax.scan(step, alpha, jnp.arange(1, max_len))
    if include_bos_eos_tag:
        alpha = alpha + trans[:, stop_tag][None]

    scores = jnp.max(alpha, axis=1)
    last_tag = jnp.argmax(alpha, axis=1).astype(jnp.int32)

    def back(tag, hist_t):
        prev = jnp.take_along_axis(hist_t, tag[:, None], 1)[:, 0]
        return prev, prev

    _, prev_tags = jax.lax.scan(back, last_tag, hists, reverse=True)
    # prev_tags[k] = tag at position k (k = 0..max_len-2)
    path = jnp.concatenate(
        [jnp.swapaxes(prev_tags, 0, 1), last_tag[:, None]], axis=1) \
        if max_len > 1 else last_tag[:, None]
    # zero-pad positions beyond each sequence's length
    path = jnp.where(jnp.arange(max_len)[None] < lens[:, None], path, 0)
    return Tensor(scores), Tensor(path)


class ViterbiDecoder(Layer):
    """Layer wrapper holding the transition matrix
    (text/viterbi_decode.py:110)."""

    def __init__(self, transitions, include_bos_eos_tag: bool = True,
                 name=None):
        super().__init__()
        self.transitions = transitions
        self.include_bos_eos_tag = include_bos_eos_tag

    def forward(self, potentials, lengths):
        return viterbi_decode(potentials, self.transitions, lengths,
                              self.include_bos_eos_tag)
