"""Pallas TPU kernels: flash attention (fwd + bwd) with custom VJP.

Replaces the reference's FlashAttention-2 CUDA integration
(/root/reference/paddle/phi/kernels/gpu/flash_attn_kernel.cu via dynload of
the external flashattn repo; cutlass memory_efficient_attention under
kernels/fusion/cutlass/). TPU-native: blockwise online-softmax attention
written in Pallas — q blocks stream against k/v blocks in VMEM with fp32
accumulators on the MXU; backward follows the standard dq/dk/dv two-pass
recomputation using saved logsumexp. Layout is paddle's
[batch, seq, heads, head_dim] at the API boundary, [B*H, S, D] inside.

On non-TPU backends the kernels run under ``interpret=True`` (tests), and
nn.functional falls back to fused-XLA attention anyway.
"""
from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.ad_checkpoint import checkpoint_name
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30
_LANES = 128  # VPU lane width: scalar-per-row carries live as [bq, 128]


def _choose_block(seq_len: int, target: int = 0,
                  which: str = "") -> int:
    """Block size for one kernel axis. Env overrides, most specific
    wins: PTPU_FLASH_BWD_BQ/_BWD_BK beat PTPU_FLASH_BQ/_BK beat the
    all-four fallback PTPU_FLASH_BLOCK — the fwd and bwd kernels have
    different reuse patterns, so their optima differ (the step-level
    sweep lives in benchmarks/).

    Default (round-5 step-level sweeps, RESULTS.md): 1024 blocks
    everywhere — at S=1024 fwd+bwd all-1024 measures 348 ms/step vs
    373 at the old 512 default (fewer grid steps, no online-softmax
    carry rescaling, the PV matmul's contraction grows with the
    block), and the S=2048 re-sweep with SEPARATE fwd/bwd knobs also
    prefers 1024 (407 vs 419 ms/step; the r4 '512 wins at 2048'
    result was an artifact of the single shared knob)."""
    import os
    if target <= 0:
        target = min(seq_len, 1024)
    names = {"fwd_q": ("PTPU_FLASH_BQ",),
             "fwd_k": ("PTPU_FLASH_BK",),
             "bwd_q": ("PTPU_FLASH_BWD_BQ", "PTPU_FLASH_BQ"),
             "bwd_k": ("PTPU_FLASH_BWD_BK", "PTPU_FLASH_BK")}
    for name in names.get(which, ()) + ("PTPU_FLASH_BLOCK",):
        raw = os.environ.get(name, "")
        if raw:
            try:
                override = int(raw)
            except ValueError:
                override = 0
            if override >= 1:  # invalid/sentinel values keep default
                target = override
                break
    b = min(target, seq_len)
    while seq_len % b:
        b //= 2
    return max(b, 1)


def _interpret() -> bool:
    return jax.default_backend() == "cpu"


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------
# Mosaic-native structure: the k/v block index is a GRID axis (innermost,
# 'arbitrary'), so block DMAs double-buffer automatically while the MXU
# works; the online-softmax carry (acc, m, l) persists in VMEM scratch
# across the innermost axis. Causal masking touches only diagonal blocks
# and strictly-upper blocks are skipped entirely.

def _fa_fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref,
                   acc_ref, m_ref, l_ref, *, bq, bk, nk, causal, scale,
                   id_axes=(1, 2)):
    qi = pl.program_id(id_axes[0])
    j = pl.program_id(id_axes[1])
    j_last = jnp.minimum(((qi + 1) * bq - 1) // bk, nk - 1) if causal \
        else nk - 1
    run = j <= j_last if causal else True

    @pl.when(j == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)

    @pl.when(run)
    def _body():
        q = q_ref[0]  # [bq, d] bf16: MXU takes bf16 in, accumulates fp32
        k = k_ref[0]
        v = v_ref[0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale  # [bq, bk]
        if causal:
            # mask only when this block straddles the diagonal
            diag = (j + 1) * bk - 1 > qi * bq

            @pl.when(diag)
            def _():
                iq = qi * bq + jax.lax.broadcasted_iota(
                    jnp.int32, (bq, bk), 0)
                ik = j * bk + jax.lax.broadcasted_iota(
                    jnp.int32, (bq, bk), 1)
                s_ref_val = jnp.where(iq >= ik, s, NEG_INF)
                _online_update(s_ref_val, v, acc_ref, m_ref, l_ref)

            @pl.when(jnp.logical_not(diag))
            def _():
                _online_update(s, v, acc_ref, m_ref, l_ref)
        else:
            _online_update(s, v, acc_ref, m_ref, l_ref)

    @pl.when(j == j_last)
    def _finish():
        l = l_ref[:, 0]
        l_safe = jnp.where(l == 0.0, 1.0, l)
        o_ref[0] = (acc_ref[...] / l_safe[:, None]).astype(o_ref.dtype)
        lse_ref[0] = (m_ref[:, :1] + jnp.log(l_safe)[:, None]) \
            .astype(jnp.float32)


def _online_update(s, v, acc_ref, m_ref, l_ref):
    m_prev = m_ref[:, 0]
    l_prev = l_ref[:, 0]
    m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1))
    p = jnp.exp(s - m_new[:, None])
    alpha = jnp.exp(m_prev - m_new)
    l_new = l_prev * alpha + jnp.sum(p, axis=-1)
    acc_ref[...] = acc_ref[...] * alpha[:, None] + jax.lax.dot(
        p.astype(v.dtype), v, preferred_element_type=jnp.float32)
    m_ref[...] = jnp.broadcast_to(m_new[:, None], m_ref.shape)
    l_ref[...] = jnp.broadcast_to(l_new[:, None], l_ref.shape)


def _fa_forward(q, k, v, causal, scale, bq, bk):
    BH, S, D = q.shape
    nk = S // bk
    grid = (BH, S // bq, nk)
    kernel = functools.partial(_fa_fwd_kernel, bq=bq, bk=bk, nk=nk,
                               causal=causal, scale=scale)
    out, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, bq, D), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, bk, D), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, bk, D), lambda b, i, j: (b, j, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, bq, D), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, bq, 1), lambda b, i, j: (b, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((BH, S, D), q.dtype),
            jax.ShapeDtypeStruct((BH, S, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((bq, D), jnp.float32),
            pltpu.VMEM((bq, _LANES), jnp.float32),
            pltpu.VMEM((bq, _LANES), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=_interpret(),
    )(q, k, v)
    return out, lse


# ---------------------------------------------------------------------------
# backward
# ---------------------------------------------------------------------------

def _fa_bwd_dkdv_kernel(k_ref, v_ref, q_ref, do_ref, lse_ref, delta_ref,
                        dk_ref, dv_ref, dk_acc, dv_acc,
                        *, bq, bk, nq, causal, scale, id_axes=(1, 2)):
    ki = pl.program_id(id_axes[0])
    i = pl.program_id(id_axes[1])
    i_start = (ki * bk) // bq if causal else 0
    run = i >= i_start if causal else True

    @pl.when(i == 0)
    def _init():
        dk_acc[...] = jnp.zeros_like(dk_acc)
        dv_acc[...] = jnp.zeros_like(dv_acc)

    @pl.when(run)
    def _body():
        k = k_ref[0]  # [bk, d]
        v = v_ref[0]
        q = q_ref[0]  # [bq, d]
        do = do_ref[0]
        lse = lse_ref[0][:, 0]
        delta = delta_ref[0][:, 0]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        if causal:
            iq = i * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
            ik = ki * bk + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
            s = jnp.where(iq >= ik, s, NEG_INF)
        p = jnp.exp(s - lse[:, None])  # [bq, bk]
        pb = p.astype(do.dtype)
        dv_acc[...] += jax.lax.dot_general(
            pb, do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = (p * (dp - delta[:, None]) * scale).astype(q.dtype)
        dk_acc[...] += jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(i == nq - 1)
    def _finish():
        dk_ref[0] = dk_acc[...].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[...].astype(dv_ref.dtype)


def _fa_bwd_dq_kernel(q_ref, do_ref, lse_ref, delta_ref, k_ref, v_ref,
                      dq_ref, dq_acc, *, bq, bk, nk, causal, scale,
                      id_axes=(1, 2)):
    qi = pl.program_id(id_axes[0])
    j = pl.program_id(id_axes[1])
    j_last = jnp.minimum(((qi + 1) * bq - 1) // bk, nk - 1) if causal \
        else nk - 1
    run = j <= j_last if causal else True

    @pl.when(j == 0)
    def _init():
        dq_acc[...] = jnp.zeros_like(dq_acc)

    @pl.when(run)
    def _body():
        q = q_ref[0]
        do = do_ref[0]
        lse = lse_ref[0][:, 0]
        delta = delta_ref[0][:, 0]
        k = k_ref[0]
        v = v_ref[0]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        if causal:
            iq = qi * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
            ik = j * bk + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
            s = jnp.where(iq >= ik, s, NEG_INF)
        p = jnp.exp(s - lse[:, None])
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = (p * (dp - delta[:, None]) * scale).astype(k.dtype)
        dq_acc[...] += jax.lax.dot(ds, k,
                                   preferred_element_type=jnp.float32)

    @pl.when(j == j_last)
    def _finish():
        dq_ref[0] = dq_acc[...].astype(dq_ref.dtype)


def _fa_backward(res, g, causal, scale, bq, bk):
    q, k, v, out, lse = res
    BH, S, D = q.shape
    delta = jnp.sum(out.astype(jnp.float32) * g.astype(jnp.float32),
                    axis=-1)[..., None]  # [BH, S, 1] (lane-dim, see fwd)
    interp = _interpret()
    nq, nk = S // bq, S // bk
    seq_params = pltpu.CompilerParams(
        dimension_semantics=("parallel", "parallel", "arbitrary"))
    dk, dv = pl.pallas_call(
        functools.partial(_fa_bwd_dkdv_kernel, bq=bq, bk=bk, nq=nq,
                          causal=causal, scale=scale),
        grid=(BH, nk, nq),
        in_specs=[
            pl.BlockSpec((1, bk, D), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, bk, D), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, bq, D), lambda b, j, i: (b, i, 0)),
            pl.BlockSpec((1, bq, D), lambda b, j, i: (b, i, 0)),
            pl.BlockSpec((1, bq, 1), lambda b, j, i: (b, i, 0)),
            pl.BlockSpec((1, bq, 1), lambda b, j, i: (b, i, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, bk, D), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, bk, D), lambda b, j, i: (b, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((BH, S, D), q.dtype),
            jax.ShapeDtypeStruct((BH, S, D), q.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((bk, D), jnp.float32),
            pltpu.VMEM((bk, D), jnp.float32),
        ],
        compiler_params=seq_params,
        interpret=interp,
    )(k, v, q, g, lse, delta)
    dq = pl.pallas_call(
        functools.partial(_fa_bwd_dq_kernel, bq=bq, bk=bk, nk=nk,
                          causal=causal, scale=scale),
        grid=(BH, nq, nk),
        in_specs=[
            pl.BlockSpec((1, bq, D), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, bq, D), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, bq, 1), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, bq, 1), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, bk, D), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, bk, D), lambda b, i, j: (b, j, 0)),
        ],
        out_specs=[pl.BlockSpec((1, bq, D), lambda b, i, j: (b, i, 0))],
        out_shape=[jax.ShapeDtypeStruct((BH, S, D), q.dtype)],
        scratch_shapes=[pltpu.VMEM((bq, D), jnp.float32)],
        compiler_params=seq_params,
        interpret=interp,
    )(q, g, lse, delta, k, v)[0]
    return dq, dk, dv


# ---------------------------------------------------------------------------
# public API: [B, S, H, D] layout with custom VJP
# ---------------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _flash_bshd(q, k, v, causal, scale):
    return _flash_fwd_rule(q, k, v, causal, scale)[0]


def _pack(x):
    B, S, H, D = x.shape
    return jnp.swapaxes(x, 1, 2).reshape(B * H, S, D)


def _unpack(x, B, H):
    BH, S, D = x.shape
    return jnp.swapaxes(x.reshape(B, H, S, D), 1, 2)


def _flash_fwd_rule(q, k, v, causal, scale):
    B, S, H, D = q.shape
    bq = _choose_block(S, which="fwd_q")
    bk = _choose_block(S, which="fwd_k")
    qp, kp, vp = _pack(q), _pack(k), _pack(v)
    out, lse = _fa_forward(qp, kp, vp, causal, scale, bq, bk)
    # named so remat policies can keep the flash residuals and skip the
    # whole forward-kernel recompute in the backward pass
    # (models/gpt.py "save_dots" saves these alongside matmul outputs)
    out = checkpoint_name(out, "flash_out")
    lse = checkpoint_name(lse, "flash_lse")
    return _unpack(out, B, H), (qp, kp, vp, out, lse, B, H, bq, bk)


def _flash_bwd_rule(causal, scale, res, g):
    qp, kp, vp, out, lse, B, H, _, _ = res  # fwd blocks: not reused
    S = qp.shape[1]
    bq, bk = (_choose_block(S, which="bwd_q"),
              _choose_block(S, which="bwd_k"))
    gp = _pack(g)
    dq, dk, dv = _fa_backward((qp, kp, vp, out, lse), gp, causal, scale,
                              bq, bk)
    return (_unpack(dq, B, H), _unpack(dk, B, H), _unpack(dv, B, H))


_flash_bshd.defvjp(_flash_fwd_rule, _flash_bwd_rule)


def flash_attention_fwd(q, k, v, causal=False, scale=None):
    """Fused attention on [batch, seq, heads, head_dim] arrays."""
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    return _flash_bshd(q, k, v, causal, scale)


# ---------------------------------------------------------------------------
# ring attention (context parallelism over a mesh axis)
# ---------------------------------------------------------------------------

def ulysses_attention(q, k, v, mesh, axis: str = "sep",
                      causal: bool = False, scale=None,
                      manual_axes=None, use_flash: Optional[bool] = None,
                      in_spec=None):
    """DeepSpeed-Ulysses attention: sequence-sharded activations are
    all-to-all'd into head-sharded full-sequence blocks, attended
    locally, and all-to-all'd back.

    The reference has NO long-context mechanism (SURVEY.md P8 — absent);
    with ring_attention below this is the TPU-native superset. vs ring:
    per-chip kv memory drops to S*(H/n)*D (heads split) instead of the
    gathered S*H*D, comm is two all-to-alls riding ICI, and causal
    masking is the plain triangle since every rank sees the full
    sequence for its head subset. Layout [B, S, H, D], S sharded over
    ``axis``; requires num_heads % n == 0.

    ``manual_axes``: mesh axes to go manual in the shard_map (defaults
    to {axis}); pass ALL mesh axis names to run the Pallas flash kernel
    inside (Mosaic requires a fully-manual region). ``in_spec``:
    override the activation PartitionSpec when batch/head dims are also
    sharded (e.g. P('data','sep','model',None) in the hybrid trainer)."""
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    from jax.sharding import PartitionSpec as P
    axes = set(manual_axes) if manual_axes is not None else {axis}
    if use_flash is None:
        use_flash = (jax.default_backend() in ("tpu", "axon") and
                     axes == set(mesh.axis_names))

    def per_rank(ql, kl, vl):
        # [B, S/n, H_loc, D] -> [B, S, H_loc/n, D]
        def fwd(x):
            return jax.lax.all_to_all(x, axis, split_axis=2,
                                      concat_axis=1, tiled=True)

        qg, kg, vg = fwd(ql), fwd(kl), fwd(vl)
        if use_flash:
            out = _flash_bshd(qg, kg, vg, causal, scale)
        else:
            out = _dense_bshd(qg, kg, vg, causal, scale)
        return jax.lax.all_to_all(out, axis, split_axis=1,
                                  concat_axis=2, tiled=True)

    spec = in_spec if in_spec is not None else P(None, axis, None, None)
    fn = jax.shard_map(per_rank, mesh=mesh, in_specs=(spec, spec, spec),
                       out_specs=spec, axis_names=axes, check_vma=False)
    return fn(q, k, v)


def _dense_bshd(q, k, v, causal, scale):
    """Plain fused-XLA attention on [B, S, H, D] (fp32 softmax accum)."""
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                        preferred_element_type=jnp.float32) * scale
    if causal:
        S_q, S_k = logits.shape[-2], logits.shape[-1]
        mask = jnp.tril(jnp.ones((S_q, S_k), bool), k=S_k - S_q)
        logits = jnp.where(mask, logits, NEG_INF)
    probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


def ring_attention(q, k, v, mesh, axis: str = "sep", causal: bool = False,
                   scale=None):
    """Exact attention with the sequence sharded over ``axis``.

    The reference has NO long-context mechanism (SURVEY.md P8 — absent);
    this is the TPU-native superset: k/v blocks rotate around the ring via
    ``ppermute`` while each rank accumulates its queries' online softmax —
    peak memory per chip is O(S/N), comm is overlapped block-by-block over
    ICI. Layout [B, S, H, D] global view; S sharded over ``axis``.

    Differentiable with O(S/N) residual memory: a custom VJP saves only
    the local q/k/v blocks, output, and logsumexp; the backward pass
    re-rotates k/v (flash-attention-style recomputation) while dk/dv
    partial sums travel the ring with their blocks back to the owner —
    jax's default scan autodiff would instead save every rotated block
    (the full sequence per chip), defeating ring attention's point.
    """
    from jax.sharding import PartitionSpec as P

    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    N = mesh.shape[axis]
    perm = [(i, (i + 1) % N) for i in range(N)]

    @jax.custom_vjp
    def per_rank(ql, kl, vl):
        return _ring_fwd(ql, kl, vl)[0]

    def _block_scores(qf, kb, rank, src_rank, Sl):
        s = jnp.einsum("bqhd,bkhd->bqhk", qf,
                       kb.astype(jnp.float32)) * scale
        if causal:
            iq = rank * Sl + jax.lax.broadcasted_iota(
                jnp.int32, (Sl, Sl), 0)
            ik = src_rank * Sl + jax.lax.broadcasted_iota(
                jnp.int32, (Sl, Sl), 1)
            s = jnp.where((iq >= ik)[None, :, None, :], s, NEG_INF)
        return s

    def _ring_fwd(ql, kl, vl):
        rank = jax.lax.axis_index(axis)
        B, Sl, H, D = ql.shape
        qf = ql.astype(jnp.float32)
        acc = jnp.zeros((B, Sl, H, D), jnp.float32)
        m = jnp.full((B, Sl, H), NEG_INF, jnp.float32)
        l = jnp.zeros((B, Sl, H), jnp.float32)

        def step(carry, t):
            acc, m, l, kb, vb = carry
            src_rank = (rank - t) % N  # whose k/v block we hold now
            s = _block_scores(qf, kb, rank, src_rank, Sl)
            m_new = jnp.maximum(m, jnp.max(s, axis=-1))
            p = jnp.exp(s - m_new[..., None])
            alpha = jnp.exp(m - m_new)
            l_new = l * alpha + jnp.sum(p, axis=-1)
            acc_new = acc * alpha[..., None] + jnp.einsum(
                "bqhk,bkhd->bqhd", p, vb.astype(jnp.float32))
            kb2 = jax.lax.ppermute(kb, axis, perm)
            vb2 = jax.lax.ppermute(vb, axis, perm)
            return (acc_new, m_new, l_new, kb2, vb2), None

        (acc, m, l, _, _), _ = jax.lax.scan(
            step, (acc, m, l, kl, vl), jnp.arange(N))
        l_safe = jnp.where(l == 0.0, 1.0, l)
        out = (acc / l_safe[..., None]).astype(ql.dtype)
        lse = m + jnp.log(l_safe)
        return out, lse

    def fwd_rule(ql, kl, vl):
        out, lse = _ring_fwd(ql, kl, vl)
        return out, (ql, kl, vl, out, lse)

    def bwd_rule(res, g):
        ql, kl, vl, out, lse = res
        rank = jax.lax.axis_index(axis)
        B, Sl, H, D = ql.shape
        qf = ql.astype(jnp.float32)
        gf = g.astype(jnp.float32)
        delta = jnp.sum(out.astype(jnp.float32) * gf, axis=-1)  # [B,S,H]
        dq = jnp.zeros((B, Sl, H, D), jnp.float32)

        def step(carry, t):
            dq, kb, vb, dkb, dvb = carry
            src_rank = (rank - t) % N
            s = _block_scores(qf, kb, rank, src_rank, Sl)
            p = jnp.exp(s - lse[..., None])           # [B,Sq,H,Sk]
            dp = jnp.einsum("bqhd,bkhd->bqhk", gf,
                            vb.astype(jnp.float32))
            ds = p * (dp - delta[..., None]) * scale
            dq = dq + jnp.einsum("bqhk,bkhd->bqhd", ds,
                                 kb.astype(jnp.float32))
            dkb = dkb + jnp.einsum("bqhk,bqhd->bkhd", ds, qf)
            dvb = dvb + jnp.einsum("bqhk,bqhd->bkhd", p, gf)
            # k/v grads travel WITH their blocks; after N hops both are
            # back at the owner rank with every rank's contribution
            kb = jax.lax.ppermute(kb, axis, perm)
            vb = jax.lax.ppermute(vb, axis, perm)
            dkb = jax.lax.ppermute(dkb, axis, perm)
            dvb = jax.lax.ppermute(dvb, axis, perm)
            return (dq, kb, vb, dkb, dvb), None

        zeros = jnp.zeros((B, Sl, H, D), jnp.float32)
        (dq, _, _, dk, dv), _ = jax.lax.scan(
            step, (dq, kl, vl, zeros, zeros), jnp.arange(N))
        return (dq.astype(ql.dtype), dk.astype(kl.dtype),
                dv.astype(vl.dtype))

    per_rank.defvjp(fwd_rule, bwd_rule)

    spec = P(None, axis, None, None)
    fn = jax.shard_map(per_rank, mesh=mesh, in_specs=(spec, spec, spec),
                       out_specs=spec, axis_names={axis}, check_vma=False)
    return fn(q, k, v)


# ---------------------------------------------------------------------------
# fused-layout flash attention: [B, S, H*D] activations, zero relayouts
# ---------------------------------------------------------------------------
# The packed [B*H, S, D] API above needs a (B,S,H,D)->(B,H,S,D)
# transpose on every input/output — ~34 ms/step of pure relayout in the
# GPT-1.3B profile. These wrappers read each head's slice DIRECTLY from
# the qkv matmul's natural [B, S, H*D] layout via BlockSpec index maps
# (head = a grid axis selecting a column block), so q/k/v/out never
# change layout between the projection matmuls and the kernel. lse
# keeps the [B*H, S, 1] shape via a computed (b*H + h) index map.

def _fa_backward_hsplit(res, g, H, causal, scale, bq, bk):
    q, k, v, out, lse = res
    B, S, HD = q.shape
    D = HD // H
    delta_full = out.astype(jnp.float32) * g.astype(jnp.float32)
    # per-head delta: sum each head's D-column block -> [B*H, S, 1]
    delta = jnp.sum(delta_full.reshape(B, S, H, D), axis=-1)
    delta = jnp.moveaxis(delta, -1, 1).reshape(B * H, S, 1)
    interp = _interpret()
    nq, nk = S // bq, S // bk
    seq4 = pltpu.CompilerParams(
        dimension_semantics=("parallel", "parallel", "parallel",
                             "arbitrary"))
    lse_spec_q = pl.BlockSpec((1, bq, 1),
                              lambda b, h, j, i: (b * H + h, i, 0))
    dk, dv = pl.pallas_call(
        functools.partial(_fa_bwd_dkdv_kernel, bq=bq, bk=bk, nq=nq,
                          causal=causal, scale=scale, id_axes=(2, 3)),
        grid=(B, H, nk, nq),
        in_specs=[
            pl.BlockSpec((1, bk, D), lambda b, h, j, i: (b, j, h)),
            pl.BlockSpec((1, bk, D), lambda b, h, j, i: (b, j, h)),
            pl.BlockSpec((1, bq, D), lambda b, h, j, i: (b, i, h)),
            pl.BlockSpec((1, bq, D), lambda b, h, j, i: (b, i, h)),
            lse_spec_q,
            lse_spec_q,
        ],
        out_specs=[
            pl.BlockSpec((1, bk, D), lambda b, h, j, i: (b, j, h)),
            pl.BlockSpec((1, bk, D), lambda b, h, j, i: (b, j, h)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B, S, HD), q.dtype),
            jax.ShapeDtypeStruct((B, S, HD), q.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((bk, D), jnp.float32),
            pltpu.VMEM((bk, D), jnp.float32),
        ],
        compiler_params=seq4,
        interpret=interp,
    )(k, v, q, g, lse, delta)
    dq = pl.pallas_call(
        functools.partial(_fa_bwd_dq_kernel, bq=bq, bk=bk, nk=nk,
                          causal=causal, scale=scale, id_axes=(2, 3)),
        grid=(B, H, nq, nk),
        in_specs=[
            pl.BlockSpec((1, bq, D), lambda b, h, i, j: (b, i, h)),
            pl.BlockSpec((1, bq, D), lambda b, h, i, j: (b, i, h)),
            pl.BlockSpec((1, bq, 1),
                         lambda b, h, i, j: (b * H + h, i, 0)),
            pl.BlockSpec((1, bq, 1),
                         lambda b, h, i, j: (b * H + h, i, 0)),
            pl.BlockSpec((1, bk, D), lambda b, h, i, j: (b, j, h)),
            pl.BlockSpec((1, bk, D), lambda b, h, i, j: (b, j, h)),
        ],
        out_specs=[pl.BlockSpec((1, bq, D),
                                lambda b, h, i, j: (b, i, h))],
        out_shape=[jax.ShapeDtypeStruct((B, S, HD), q.dtype)],
        scratch_shapes=[pltpu.VMEM((bq, D), jnp.float32)],
        compiler_params=seq4,
        interpret=interp,
    )(q, g, lse, delta, k, v)[0]
    return dq, dk, dv


def _fa_forward_qkvpacked(qkv, H, causal, scale, bq, bk):
    """Forward directly from the projection output [B, S, 3*H*D]:
    q/k/v are the same array with BlockSpec column offsets 0/H/2H."""
    B, S, HD3 = qkv.shape
    D = HD3 // (3 * H)
    nq, nk = S // bq, S // bk
    kernel = functools.partial(_fa_fwd_kernel, bq=bq, bk=bk, nk=nk,
                               causal=causal, scale=scale,
                               id_axes=(2, 3))
    out, lse = pl.pallas_call(
        kernel,
        grid=(B, H, nq, nk),
        in_specs=[
            pl.BlockSpec((1, bq, D), lambda b, h, i, j: (b, i, h)),
            pl.BlockSpec((1, bk, D),
                         lambda b, h, i, j: (b, j, H + h)),
            pl.BlockSpec((1, bk, D),
                         lambda b, h, i, j: (b, j, 2 * H + h)),
        ],
        out_specs=[
            pl.BlockSpec((1, bq, D), lambda b, h, i, j: (b, i, h)),
            pl.BlockSpec((1, bq, 1),
                         lambda b, h, i, j: (b * H + h, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B, S, H * D), qkv.dtype),
            jax.ShapeDtypeStruct((B * H, S, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((bq, D), jnp.float32),
            pltpu.VMEM((bq, _LANES), jnp.float32),
            pltpu.VMEM((bq, _LANES), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel",
                                 "arbitrary")),
        interpret=_interpret(),
    )(qkv, qkv, qkv)
    return out, lse


@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2, 3))
def _flash_qkvpacked(qkv, H, causal, scale):
    return _flash_qkvpacked_fwd(qkv, H, causal, scale)[0]


def _flash_qkvpacked_fwd(qkv, H, causal, scale):
    S = qkv.shape[1]
    bq = _choose_block(S, which="fwd_q")
    bk = _choose_block(S, which="fwd_k")
    out, lse = _fa_forward_qkvpacked(qkv, H, causal, scale, bq, bk)
    out = checkpoint_name(out, "flash_out")
    lse = checkpoint_name(lse, "flash_lse")
    return out, (qkv, out, lse, bq, bk)


def _flash_qkvpacked_bwd(H, causal, scale, res, g):
    qkv, out, lse, _, _ = res  # fwd blocks: not reused by the bwd
    S = qkv.shape[1]
    bq, bk = (_choose_block(S, which="bwd_q"),
              _choose_block(S, which="bwd_k"))
    HD = out.shape[-1]
    q = qkv[..., :HD]
    k = qkv[..., HD:2 * HD]
    v = qkv[..., 2 * HD:]
    dq, dk, dv = _fa_backward_hsplit((q, k, v, out, lse), g, H, causal,
                                     scale, bq, bk)
    return (jnp.concatenate([dq, dk, dv], axis=-1),)


_flash_qkvpacked.defvjp(_flash_qkvpacked_fwd, _flash_qkvpacked_bwd)


def flash_attention_qkv_fused(qkv, num_heads, causal=False, scale=None):
    """Fused attention straight off the qkv projection output
    [batch, seq, 3*heads*head_dim]; returns [batch, seq, heads*head_dim]
    with no relayout or slicing on the forward path.

    head_dim must be a multiple of 128 (Mosaic lane constraint on the
    column blocks — checked here because interpret mode does not)."""
    if qkv.shape[-1] % (3 * num_heads):
        raise ValueError(
            f"last dim {qkv.shape[-1]} is not 3*num_heads*head_dim "
            f"(num_heads={num_heads})")
    head_dim = qkv.shape[-1] // (3 * num_heads)
    if head_dim % 128:
        raise ValueError(
            f"head_dim {head_dim} must be a multiple of 128 for the "
            f"fused-layout kernel; use flash_attention_fwd instead")
    if scale is None:
        scale = 1.0 / math.sqrt(head_dim)
    return _flash_qkvpacked(qkv, num_heads, causal, scale)
