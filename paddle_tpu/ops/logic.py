"""Logical / comparison / bitwise ops
(reference: python/paddle/tensor/logic.py)."""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..framework.tensor import Tensor, apply_op, _unwrap

__all__ = [
    "logical_and", "logical_or", "logical_xor", "logical_not", "equal",
    "not_equal", "greater_than", "greater_equal", "less_than", "less_equal",
    "equal_all", "allclose", "isclose", "bitwise_and", "bitwise_or",
    "bitwise_xor", "bitwise_not", "bitwise_left_shift", "bitwise_right_shift",
    "is_empty", "is_tensor",
]


def _bin(jfn, name):
    def op(x, y, out=None, name=None):
        if not isinstance(y, Tensor):
            y = Tensor(jnp.asarray(y))
        return apply_op(jfn, x, y, _op_name=name_)
    name_ = name
    op.__name__ = name
    return op


logical_and = _bin(jnp.logical_and, "logical_and")
logical_or = _bin(jnp.logical_or, "logical_or")
logical_xor = _bin(jnp.logical_xor, "logical_xor")
equal = _bin(jnp.equal, "equal")
not_equal = _bin(jnp.not_equal, "not_equal")
greater_than = _bin(jnp.greater, "greater_than")
greater_equal = _bin(jnp.greater_equal, "greater_equal")
less_than = _bin(jnp.less, "less_than")
less_equal = _bin(jnp.less_equal, "less_equal")
bitwise_and = _bin(jnp.bitwise_and, "bitwise_and")
bitwise_or = _bin(jnp.bitwise_or, "bitwise_or")
bitwise_xor = _bin(jnp.bitwise_xor, "bitwise_xor")
bitwise_left_shift = _bin(jnp.left_shift, "bitwise_left_shift")
bitwise_right_shift = _bin(jnp.right_shift, "bitwise_right_shift")


def logical_not(x, out=None, name=None):
    return apply_op(jnp.logical_not, x, _op_name="logical_not")


def bitwise_not(x, out=None, name=None):
    return apply_op(jnp.bitwise_not, x, _op_name="bitwise_not")


def equal_all(x, y, name=None):
    return apply_op(lambda a, b: jnp.array_equal(a, b), x, y,
                    _op_name="equal_all")


def allclose(x, y, rtol=1e-05, atol=1e-08, equal_nan=False, name=None):
    return apply_op(
        lambda a, b: jnp.allclose(a, b, rtol=rtol, atol=atol,
                                  equal_nan=equal_nan), x, y,
        _op_name="allclose")


def isclose(x, y, rtol=1e-05, atol=1e-08, equal_nan=False, name=None):
    return apply_op(
        lambda a, b: jnp.isclose(a, b, rtol=rtol, atol=atol,
                                 equal_nan=equal_nan), x, y,
        _op_name="isclose")


def is_empty(x, name=None):
    return Tensor(jnp.asarray(x.size == 0))


def is_tensor(x):
    return isinstance(x, Tensor)


import sys

_this = sys.modules[__name__]
for _name in __all__:
    _fn = getattr(_this, _name, None)
    if callable(_fn) and not hasattr(Tensor, _name):
        Tensor._bind(_name, _fn)
del _this, _name, _fn
