"""Shape/layout/index manipulation ops
(reference: python/paddle/tensor/manipulation.py, search.py, indexing)."""
from __future__ import annotations

import builtins
from typing import List, Sequence, Union

import jax
import jax.numpy as jnp
import numpy as np

from ..framework.dtype import to_dtype
from ..framework.tensor import Tensor, apply_op, _unwrap

__all__ = [
    "reshape", "reshape_", "flatten", "squeeze", "squeeze_", "unsqueeze",
    "unsqueeze_", "transpose", "moveaxis", "swapaxes", "concat", "stack",
    "hstack", "vstack", "split", "chunk", "unbind", "tile", "expand",
    "expand_as", "broadcast_to", "broadcast_tensors", "flip", "rot90", "roll",
    "gather", "gather_nd", "scatter", "scatter_nd_add", "index_select",
    "index_add", "index_put", "masked_select", "masked_fill", "where",
    "nonzero", "sort", "argsort", "topk", "unique", "unique_consecutive",
    "searchsorted", "bucketize", "repeat_interleave", "take_along_axis",
    "put_along_axis", "strided_slice", "slice", "crop", "pad", "shard_index",
    "tensordot", "as_complex", "as_real", "view", "view_as", "atleast_1d",
    "atleast_2d", "atleast_3d", "select_scatter", "diagonal", "t",
    "cast", "flatten_", "tensor_split", "dsplit", "hsplit", "vsplit",
]


def _static_shape(shape):
    if isinstance(shape, Tensor):
        return tuple(int(s) for s in np.asarray(shape._data))
    out = []
    for s in shape:
        out.append(int(s._data) if isinstance(s, Tensor) else int(s))
    return tuple(out)


def reshape(x, shape, name=None):
    shp = _static_shape(shape)
    return apply_op(lambda a: jnp.reshape(a, shp), x, _op_name="reshape")


def reshape_(x, shape, name=None):
    return x._inplace(reshape(x._snapshot(), shape))


def view(x, shape_or_dtype, name=None):
    if isinstance(shape_or_dtype, (list, tuple)):
        return reshape(x, shape_or_dtype)
    dt = to_dtype(shape_or_dtype).np_dtype
    return apply_op(lambda a: a.view(dt), x, _op_name="view_dtype")


def view_as(x, other, name=None):
    return reshape(x, other.shape)


def flatten(x, start_axis=0, stop_axis=-1, name=None):
    def f(a):
        nd = a.ndim
        s = start_axis % nd if nd else 0
        e = stop_axis % nd if nd else 0
        new_shape = a.shape[:s] + (-1,) + a.shape[e + 1:]
        return jnp.reshape(a, new_shape)
    return apply_op(f, x, _op_name="flatten")


def flatten_(x, start_axis=0, stop_axis=-1, name=None):
    return x._inplace(flatten(x._snapshot(), start_axis, stop_axis))


def _axes(axis):
    if axis is None:
        return None
    if isinstance(axis, Tensor):
        a = np.asarray(axis._data).reshape(-1)
        return tuple(int(i) for i in a)
    if isinstance(axis, (list, tuple)):
        return tuple(int(_unwrap_i(a)) for a in axis)
    return int(_unwrap_i(axis))


def _unwrap_i(a):
    return int(a._data) if isinstance(a, Tensor) else int(a)


def squeeze(x, axis=None, name=None):
    ax = _axes(axis)

    def f(a):
        if ax is None:
            return jnp.squeeze(a)
        axs = ax if isinstance(ax, tuple) else (ax,)
        axs = tuple(i % a.ndim for i in axs)
        axs = tuple(i for i in axs if a.shape[i] == 1)
        return jnp.squeeze(a, axis=axs) if axs else a
    return apply_op(f, x, _op_name="squeeze")


def squeeze_(x, axis=None, name=None):
    return x._inplace(squeeze(x._snapshot(), axis))


def unsqueeze(x, axis, name=None):
    ax = _axes(axis)
    axs = ax if isinstance(ax, tuple) else (ax,)
    return apply_op(lambda a: jnp.expand_dims(a, axs), x,
                    _op_name="unsqueeze")


def unsqueeze_(x, axis, name=None):
    return x._inplace(unsqueeze(x._snapshot(), axis))


def transpose(x, perm, name=None):
    p = _axes(perm)
    return apply_op(lambda a: jnp.transpose(a, p), x, _op_name="transpose")


def t(x, name=None):
    return apply_op(lambda a: a.T, x, _op_name="t")


def moveaxis(x, source, destination, name=None):
    return apply_op(lambda a: jnp.moveaxis(a, source, destination), x,
                    _op_name="moveaxis")


def swapaxes(x, axis0, axis1, name=None):
    return apply_op(lambda a: jnp.swapaxes(a, axis0, axis1), x,
                    _op_name="swapaxes")


def cast(x, dtype):
    return x.astype(dtype)


def concat(x: Sequence[Tensor], axis=0, name=None):
    ax = _unwrap_i(axis)
    tensors = list(x)
    return apply_op(lambda *arrs: jnp.concatenate(arrs, axis=ax), *tensors,
                    _op_name="concat")


def stack(x: Sequence[Tensor], axis=0, name=None):
    tensors = list(x)
    return apply_op(lambda *arrs: jnp.stack(arrs, axis=axis), *tensors,
                    _op_name="stack")


def hstack(x, name=None):
    return apply_op(lambda *arrs: jnp.hstack(arrs), *list(x),
                    _op_name="hstack")


def vstack(x, name=None):
    return apply_op(lambda *arrs: jnp.vstack(arrs), *list(x),
                    _op_name="vstack")


def split(x, num_or_sections, axis=0, name=None):
    ax = _unwrap_i(axis)
    if isinstance(num_or_sections, int):
        outs = apply_op(
            lambda a: tuple(jnp.split(a, num_or_sections, axis=ax)), x,
            _op_name="split")
    else:
        secs = [int(_unwrap_i(s)) for s in num_or_sections]
        total = x.shape[ax]
        if -1 in secs:
            known = sum(s for s in secs if s != -1)
            secs = [total - known if s == -1 else s for s in secs]
        idx = np.cumsum(secs)[:-1].tolist()
        outs = apply_op(lambda a: tuple(jnp.split(a, idx, axis=ax)), x,
                        _op_name="split")
    return list(outs)


def tensor_split(x, num_or_indices, axis=0, name=None):
    ax = _unwrap_i(axis)
    if isinstance(num_or_indices, int):
        n = num_or_indices
        outs = apply_op(lambda a: tuple(jnp.array_split(a, n, axis=ax)), x,
                        _op_name="tensor_split")
    else:
        idx = [int(_unwrap_i(i)) for i in num_or_indices]
        outs = apply_op(lambda a: tuple(jnp.split(a, idx, axis=ax)), x,
                        _op_name="tensor_split")
    return list(outs)


def dsplit(x, num_or_indices, name=None):
    return tensor_split(x, num_or_indices, axis=2)


def hsplit(x, num_or_indices, name=None):
    return tensor_split(x, num_or_indices, axis=1 if x.ndim > 1 else 0)


def vsplit(x, num_or_indices, name=None):
    return tensor_split(x, num_or_indices, axis=0)


def chunk(x, chunks, axis=0, name=None):
    return split(x, chunks, axis)


def unbind(x, axis=0, name=None):
    n = x.shape[axis]
    outs = apply_op(
        lambda a: tuple(jnp.squeeze(s, axis) for s in
                        jnp.split(a, n, axis=axis)),
        x, _op_name="unbind")
    return list(outs)


def tile(x, repeat_times, name=None):
    reps = _axes(repeat_times)
    reps = reps if isinstance(reps, tuple) else (reps,)
    return apply_op(lambda a: jnp.tile(a, reps), x, _op_name="tile")


def expand(x, shape, name=None):
    shp = _static_shape(shape)

    def f(a):
        tgt = list(shp)
        off = len(tgt) - a.ndim
        for i in range(a.ndim):
            if tgt[off + i] == -1:
                tgt[off + i] = a.shape[i]
        return jnp.broadcast_to(a, tuple(tgt))
    return apply_op(f, x, _op_name="expand")


def expand_as(x, y, name=None):
    return expand(x, y.shape)


def broadcast_to(x, shape, name=None):
    return expand(x, shape)


def broadcast_tensors(inputs, name=None):
    tensors = list(inputs)
    outs = apply_op(lambda *arrs: tuple(jnp.broadcast_arrays(*arrs)),
                    *tensors, _op_name="broadcast_tensors")
    return list(outs)


def flip(x, axis, name=None):
    ax = _axes(axis)
    return apply_op(lambda a: jnp.flip(a, axis=ax), x, _op_name="flip")


def rot90(x, k=1, axes=(0, 1), name=None):
    return apply_op(lambda a: jnp.rot90(a, k=k, axes=tuple(axes)), x,
                    _op_name="rot90")


def roll(x, shifts, axis=None, name=None):
    sh = _axes(shifts)
    ax = _axes(axis)
    return apply_op(lambda a: jnp.roll(a, sh, axis=ax), x, _op_name="roll")


def gather(x, index, axis=0, name=None):
    ax = _unwrap_i(axis) if axis is not None else 0
    return apply_op(lambda a, i: jnp.take(a, i.reshape(-1), axis=ax), x,
                    index, _op_name="gather")


def gather_nd(x, index, name=None):
    def f(a, idx):
        comps = tuple(idx[..., i] for i in range(idx.shape[-1]))
        return a[comps]
    return apply_op(f, x, index, _op_name="gather_nd")


def scatter(x, index, updates, overwrite=True, name=None):
    def f(a, i, u):
        i = i.reshape(-1)
        if overwrite:
            return a.at[i].set(u)
        zeroed = a.at[i].set(jnp.zeros_like(u))
        return zeroed.at[i].add(u)
    return apply_op(f, x, index, updates, _op_name="scatter")


def scatter_nd_add(x, index, updates, name=None):
    def f(a, idx, u):
        comps = tuple(idx[..., i] for i in range(idx.shape[-1]))
        return a.at[comps].add(u)
    return apply_op(f, x, index, updates, _op_name="scatter_nd_add")


def index_select(x, index, axis=0, name=None):
    return apply_op(lambda a, i: jnp.take(a, i.reshape(-1), axis=axis), x,
                    index, _op_name="index_select")


def index_add(x, index, axis, value, name=None):
    def f(a, i, v):
        a_m = jnp.moveaxis(a, axis, 0)
        v_m = jnp.moveaxis(v, axis, 0)
        out = a_m.at[i.reshape(-1)].add(v_m)
        return jnp.moveaxis(out, 0, axis)
    return apply_op(f, x, index, value, _op_name="index_add")


def index_put(x, indices, value, accumulate=False, name=None):
    idx_arrs = tuple(_unwrap(i) for i in indices)

    def f(a, v):
        if accumulate:
            return a.at[idx_arrs].add(v)
        return a.at[idx_arrs].set(v)
    return apply_op(f, x, value, _op_name="index_put")


def masked_select(x, mask, name=None):
    """Data-dependent output shape: eager-only (not jit-traceable), like
    reference masked_select (ops.yaml)."""
    a = np.asarray(_unwrap(x))
    m = np.asarray(_unwrap(mask))
    return Tensor(jnp.asarray(a[np.broadcast_to(m, a.shape)]))


def masked_fill(x, mask, value, name=None):
    v = _unwrap(value)
    return apply_op(lambda a, m: jnp.where(m, v, a), x, mask,
                    _op_name="masked_fill")


def where(condition, x=None, y=None, name=None):
    if x is None and y is None:
        return nonzero(condition, as_tuple=True)
    return apply_op(lambda c, a, b: jnp.where(c, a, b), condition,
                    x if isinstance(x, Tensor) else Tensor(jnp.asarray(x)),
                    y if isinstance(y, Tensor) else Tensor(jnp.asarray(y)),
                    _op_name="where")


def nonzero(x, as_tuple=False, name=None):
    a = np.asarray(_unwrap(x))
    nz = np.nonzero(a)
    if as_tuple:
        return tuple(Tensor(jnp.asarray(i.reshape(-1, 1))) for i in nz)
    return Tensor(jnp.asarray(np.stack(nz, axis=1).astype(np.int64)))


def sort(x, axis=-1, descending=False, stable=False, name=None):
    def f(a):
        s = jnp.sort(a, axis=axis, stable=True)
        return jnp.flip(s, axis=axis) if descending else s
    return apply_op(f, x, _op_name="sort")


def argsort(x, axis=-1, descending=False, stable=False, name=None):
    def f(a):
        i = jnp.argsort(a, axis=axis, stable=True)
        return jnp.flip(i, axis=axis) if descending else i
    return apply_op(f, x, _op_name="argsort").astype("int64")


def topk(x, k, axis=-1, largest=True, sorted=True, name=None):
    kk = _unwrap_i(k)

    def f(a):
        ax = axis % a.ndim
        src = a if largest else -a
        moved = jnp.moveaxis(src, ax, -1)
        vals, idx = jax.lax.top_k(moved, kk)
        if not largest:
            vals = -vals
        return (jnp.moveaxis(vals, -1, ax),
                jnp.moveaxis(idx.astype(jnp.int64), -1, ax))
    return apply_op(f, x, _op_name="topk")


def unique(x, return_index=False, return_inverse=False, return_counts=False,
           axis=None, dtype="int64", name=None):
    a = np.asarray(_unwrap(x))
    res = np.unique(a, return_index=return_index,
                    return_inverse=return_inverse,
                    return_counts=return_counts, axis=axis)
    if not isinstance(res, tuple):
        return Tensor(jnp.asarray(res))
    return tuple(Tensor(jnp.asarray(r)) for r in res)


def unique_consecutive(x, return_inverse=False, return_counts=False,
                       axis=None, dtype="int64", name=None):
    a = np.asarray(_unwrap(x)).reshape(-1) if axis is None else \
        np.asarray(_unwrap(x))
    if a.size == 0:
        return Tensor(jnp.asarray(a))
    keep = np.concatenate([[True], a[1:] != a[:-1]]) if axis is None else None
    out = a[keep]
    results = [Tensor(jnp.asarray(out))]
    if return_inverse:
        inv = np.cumsum(keep) - 1
        results.append(Tensor(jnp.asarray(inv.astype(np.int64))))
    if return_counts:
        idx = np.flatnonzero(keep)
        counts = np.diff(np.concatenate([idx, [a.size]]))
        results.append(Tensor(jnp.asarray(counts.astype(np.int64))))
    return results[0] if len(results) == 1 else tuple(results)


def searchsorted(sorted_sequence, values, out_int32=False, right=False,
                 name=None):
    side = "right" if right else "left"
    dt = jnp.int32 if out_int32 else jnp.int64
    return apply_op(
        lambda s, v: jnp.searchsorted(s, v, side=side).astype(dt),
        sorted_sequence, values, _op_name="searchsorted")


def bucketize(x, sorted_sequence, out_int32=False, right=False, name=None):
    return searchsorted(sorted_sequence, x, out_int32, right)


def repeat_interleave(x, repeats, axis=None, name=None):
    if isinstance(repeats, Tensor):
        reps = np.asarray(repeats._data)
        a = np.asarray(_unwrap(x))
        return Tensor(jnp.asarray(np.repeat(a, reps, axis=axis)))
    return apply_op(lambda a: jnp.repeat(a, repeats, axis=axis), x,
                    _op_name="repeat_interleave")


def take_along_axis(arr, indices, axis, broadcast=True, name=None):
    return apply_op(lambda a, i: jnp.take_along_axis(a, i, axis=axis), arr,
                    indices, _op_name="take_along_axis")


def put_along_axis(arr, indices, values, axis, reduce="assign",
                   include_self=True, broadcast=True, name=None):
    def f(a, i, v):
        v = jnp.broadcast_to(v, i.shape) if v.ndim else \
            jnp.full(i.shape, v, a.dtype)
        dim_idx = [jnp.arange(s).reshape(
            tuple(s if d == k else 1 for k, _ in enumerate(i.shape)))
            for d, s in enumerate(i.shape)]
        full_idx = tuple(i if d == axis % a.ndim else
                         jnp.broadcast_to(dim_idx[d], i.shape)
                         for d in range(a.ndim))
        if reduce == "add":
            return a.at[full_idx].add(v)
        if reduce == "multiply" or reduce == "mul":
            return a.at[full_idx].multiply(v)
        return a.at[full_idx].set(v)
    if not isinstance(values, Tensor):
        values = Tensor(jnp.asarray(values))
    return apply_op(f, arr, indices, values, _op_name="put_along_axis")


def strided_slice(x, axes, starts, ends, strides, name=None):
    def f(a):
        idx = [builtins.slice(None)] * a.ndim
        for ax, s, e, st in zip(axes, starts, ends, strides):
            idx[ax] = builtins.slice(_unwrap_i(s), _unwrap_i(e), _unwrap_i(st))
        return a[tuple(idx)]
    return apply_op(f, x, _op_name="strided_slice")


def slice(input, axes, starts, ends, name=None):
    return strided_slice(input, axes, starts, ends, [1] * len(list(axes)))


def crop(x, shape=None, offsets=None, name=None):
    shp = _static_shape(shape)
    offs = [0] * len(shp) if offsets is None else \
        [_unwrap_i(o) for o in offsets]

    def f(a):
        idx = tuple(builtins.slice(o, o + (s if s != -1 else a.shape[d] - o))
                    for d, (o, s) in enumerate(zip(offs, shp)))
        return a[idx]
    return apply_op(f, x, _op_name="crop")


def pad(x, pad, mode="constant", value=0.0, data_format="NCHW", name=None):
    """paddle.nn.functional.pad semantics (list len == 2*ndim or per-format)."""
    p = [_unwrap_i(i) for i in pad] if not isinstance(pad, int) else None

    def f(a):
        if isinstance(pad, int):
            widths = [(pad, pad)] * a.ndim
        elif len(p) == 2 * a.ndim:
            widths = [(p[2 * i], p[2 * i + 1]) for i in range(a.ndim)]
        else:
            # NCHW-style: pad applies to trailing spatial dims, reversed pairs
            n_spatial = len(p) // 2
            widths = [(0, 0)] * (a.ndim - n_spatial) + \
                [(p[2 * i], p[2 * i + 1]) for i in range(n_spatial)]
            if data_format in ("NCHW", "NCL", "NCDHW"):
                pass
            else:  # NHWC: spatial dims sit before channel
                widths = [(0, 0)] + widths[2:] + [(0, 0)]
        if mode == "constant":
            return jnp.pad(a, widths, constant_values=value)
        jmode = {"reflect": "reflect", "replicate": "edge",
                 "circular": "wrap"}[mode]
        return jnp.pad(a, widths, mode=jmode)
    return apply_op(f, x, _op_name="pad")


def shard_index(input, index_num, nshards, shard_id, ignore_value=-1):
    """reference: python/paddle/tensor/manipulation.py shard_index — used by
    parallel cross entropy."""
    size = (index_num + nshards - 1) // nshards

    def f(i):
        shard = i // size
        local = i % size
        return jnp.where(shard == shard_id, local, ignore_value)
    return apply_op(f, input, _op_name="shard_index")


def tensordot(x, y, axes=2, name=None):
    ax = axes
    if isinstance(ax, Tensor):
        ax = np.asarray(ax._data).tolist()
    if isinstance(ax, (list, tuple)):
        ax = tuple(tuple(_unwrap_i(i) for i in a) if isinstance(a, (list, tuple))
                   else _unwrap_i(a) for a in ax)
    return apply_op(lambda a, b: jnp.tensordot(a, b, axes=ax), x, y,
                    _op_name="tensordot")


def as_complex(x, name=None):
    return apply_op(lambda a: jax.lax.complex(a[..., 0], a[..., 1]), x,
                    _op_name="as_complex")


def as_real(x, name=None):
    return apply_op(lambda a: jnp.stack([jnp.real(a), jnp.imag(a)], axis=-1),
                    x, _op_name="as_real")


def atleast_1d(*inputs, name=None):
    outs = [apply_op(jnp.atleast_1d, x, _op_name="atleast_1d") for x in inputs]
    return outs[0] if len(outs) == 1 else outs


def atleast_2d(*inputs, name=None):
    outs = [apply_op(jnp.atleast_2d, x, _op_name="atleast_2d") for x in inputs]
    return outs[0] if len(outs) == 1 else outs


def atleast_3d(*inputs, name=None):
    outs = [apply_op(jnp.atleast_3d, x, _op_name="atleast_3d") for x in inputs]
    return outs[0] if len(outs) == 1 else outs


def select_scatter(x, values, axis, index, name=None):
    def f(a, v):
        idx = [builtins.slice(None)] * a.ndim
        idx[axis] = index
        return a.at[tuple(idx)].set(v)
    return apply_op(f, x, values, _op_name="select_scatter")


def diagonal(x, offset=0, axis1=0, axis2=1, name=None):
    return apply_op(lambda a: jnp.diagonal(a, offset=offset, axis1=axis1,
                                           axis2=axis2), x,
                    _op_name="diagonal")


# bind methods
import sys

_this = sys.modules[__name__]
for _name in __all__:
    _fn = getattr(_this, _name, None)
    if callable(_fn) and not hasattr(Tensor, _name):
        Tensor._bind(_name, _fn)
del _this, _name, _fn
