"""Linear algebra ops (reference: python/paddle/tensor/linalg.py; matmul at
:191 -> phi MatmulKernel). On TPU these lower straight onto the MXU."""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..framework.tensor import Tensor, apply_op, _unwrap

__all__ = [
    "matmul", "mm", "bmm", "dot", "dist", "norm", "cond", "cross",
    "cholesky", "matrix_rank", "mv", "det", "slogdet", "inv", "pinv",
    "solve", "triangular_solve", "cholesky_solve", "eig", "eigvals", "eigh",
    "eigvalsh", "svd", "qr", "lu", "matrix_power", "multi_dot", "einsum",
    "histogram", "bincount", "lstsq", "corrcoef", "cov", "householder_product",
]


def matmul(x, y, transpose_x=False, transpose_y=False, name=None):
    def f(a, b):
        if transpose_x:
            a = jnp.swapaxes(a, -1, -2) if a.ndim > 1 else a
        if transpose_y:
            b = jnp.swapaxes(b, -1, -2) if b.ndim > 1 else b
        return jnp.matmul(a, b)
    return apply_op(f, x, y, _op_name="matmul")


def mm(input, mat2, name=None):
    return matmul(input, mat2)


def bmm(x, y, name=None):
    return apply_op(jnp.matmul, x, y, _op_name="bmm")


def dot(x, y, name=None):
    return apply_op(lambda a, b: jnp.sum(a * b, axis=-1), x, y,
                    _op_name="dot")


def mv(x, vec, name=None):
    return apply_op(jnp.matmul, x, vec, _op_name="mv")


def dist(x, y, p=2, name=None):
    return apply_op(
        lambda a, b: _p_norm(a - b, p, None, False), x, y, _op_name="dist")


def _p_norm(a, p, axis, keepdim):
    if p == np.inf or p == float("inf"):
        return jnp.max(jnp.abs(a), axis=axis, keepdims=keepdim)
    if p == -np.inf or p == float("-inf"):
        return jnp.min(jnp.abs(a), axis=axis, keepdims=keepdim)
    if p == 0:
        return jnp.sum((a != 0).astype(a.dtype), axis=axis, keepdims=keepdim)
    return jnp.power(
        jnp.sum(jnp.power(jnp.abs(a), p), axis=axis, keepdims=keepdim),
        1.0 / p)


def norm(x, p=None, axis=None, keepdim=False, name=None):
    def f(a):
        if axis is None and (p is None or p == "fro" or p == 2):
            return jnp.sqrt(jnp.sum(jnp.square(a)))
        ax = tuple(axis) if isinstance(axis, (list, tuple)) else axis
        if p is None or p == "fro":
            return jnp.sqrt(jnp.sum(jnp.square(a), axis=ax, keepdims=keepdim))
        if p == "nuc":
            s = jnp.linalg.svd(a, compute_uv=False)
            return jnp.sum(s, axis=-1, keepdims=keepdim)
        return _p_norm(a, p, ax, keepdim)
    return apply_op(f, x, _op_name="p_norm")


def cond(x, p=None, name=None):
    return apply_op(lambda a: jnp.linalg.cond(a, p=p), x, _op_name="cond")


def cross(x, y, axis=9, name=None):
    def f(a, b):
        ax = axis
        if ax == 9:  # paddle default: first axis with dim 3
            ax = next(i for i, s in enumerate(a.shape) if s == 3)
        return jnp.cross(a, b, axis=ax)
    return apply_op(f, x, y, _op_name="cross")


def cholesky(x, upper=False, name=None):
    def f(a):
        L = jnp.linalg.cholesky(a)
        return jnp.swapaxes(L, -1, -2) if upper else L
    return apply_op(f, x, _op_name="cholesky")


def matrix_rank(x, tol=None, hermitian=False, name=None):
    a = _unwrap(x)
    return Tensor(jnp.linalg.matrix_rank(a, tol=_unwrap(tol)
                                         if tol is not None else None))


def det(x, name=None):
    return apply_op(jnp.linalg.det, x, _op_name="det")


def slogdet(x, name=None):
    outs = apply_op(lambda a: tuple(jnp.linalg.slogdet(a)), x,
                    _op_name="slogdet")
    return outs


def inv(x, name=None):
    return apply_op(jnp.linalg.inv, x, _op_name="inverse")


inverse = inv


def pinv(x, rcond=1e-15, hermitian=False, name=None):
    return apply_op(lambda a: jnp.linalg.pinv(a, rtol=rcond,
                                              hermitian=hermitian), x,
                    _op_name="pinv")


def solve(x, y, name=None):
    return apply_op(jnp.linalg.solve, x, y, _op_name="solve")


def triangular_solve(x, y, upper=True, transpose=False, unitriangular=False,
                     name=None):
    def f(a, b):
        a_ = jnp.swapaxes(a, -1, -2) if transpose else a
        up = not upper if transpose else upper
        return jax.scipy.linalg.solve_triangular(
            a_, b, lower=not up, unit_diagonal=unitriangular)
    return apply_op(f, x, y, _op_name="triangular_solve")


def cholesky_solve(x, y, upper=False, name=None):
    def f(b, L):
        Lm = jnp.swapaxes(L, -1, -2) if upper else L
        z = jax.scipy.linalg.solve_triangular(Lm, b, lower=True)
        return jax.scipy.linalg.solve_triangular(
            jnp.swapaxes(Lm, -1, -2), z, lower=False)
    return apply_op(f, x, y, _op_name="cholesky_solve")


def eig(x, name=None):
    a = np.asarray(_unwrap(x))
    w, v = np.linalg.eig(a)
    return Tensor(jnp.asarray(w)), Tensor(jnp.asarray(v))


def eigvals(x, name=None):
    a = np.asarray(_unwrap(x))
    return Tensor(jnp.asarray(np.linalg.eigvals(a)))


def eigh(x, UPLO="L", name=None):
    outs = apply_op(
        lambda a: tuple(jnp.linalg.eigh(a, symmetrize_input=True)), x,
        _op_name="eigh")
    return outs


def eigvalsh(x, UPLO="L", name=None):
    return apply_op(jnp.linalg.eigvalsh, x, _op_name="eigvalsh")


def svd(x, full_matrices=False, name=None):
    outs = apply_op(
        lambda a: tuple(jnp.linalg.svd(a, full_matrices=full_matrices)), x,
        _op_name="svd")
    return outs


def qr(x, mode="reduced", name=None):
    outs = apply_op(lambda a: tuple(jnp.linalg.qr(a, mode=mode)), x,
                    _op_name="qr")
    return outs


def lu(x, pivot=True, get_infos=False, name=None):
    def f(a):
        lu_, piv = jax.scipy.linalg.lu_factor(a)
        return lu_, piv.astype(jnp.int32) + 1  # paddle pivots are 1-based
    lu_t, piv_t = apply_op(f, x, _op_name="lu")
    if get_infos:
        info = Tensor(jnp.zeros(x.shape[:-2] or (1,), jnp.int32))
        return lu_t, piv_t, info
    return lu_t, piv_t


def matrix_power(x, n, name=None):
    return apply_op(lambda a: jnp.linalg.matrix_power(a, n), x,
                    _op_name="matrix_power")


def multi_dot(x, name=None):
    tensors = list(x)
    return apply_op(lambda *arrs: jnp.linalg.multi_dot(arrs), *tensors,
                    _op_name="multi_dot")


def einsum(equation, *operands):
    tensors = list(operands)
    return apply_op(lambda *arrs: jnp.einsum(equation, *arrs), *tensors,
                    _op_name="einsum")


def histogram(input, bins=100, min=0, max=0, weight=None, density=False,
              name=None):
    a = np.asarray(_unwrap(input))
    lo, hi = (min, max) if (min != 0 or max != 0) else (a.min(), a.max())
    w = np.asarray(_unwrap(weight)) if weight is not None else None
    h, _ = np.histogram(a, bins=bins, range=(lo, hi), weights=w,
                        density=density)
    return Tensor(jnp.asarray(h if density or w is not None
                              else h.astype(np.int64)))


def bincount(x, weights=None, minlength=0, name=None):
    a = np.asarray(_unwrap(x))
    w = np.asarray(_unwrap(weights)) if weights is not None else None
    return Tensor(jnp.asarray(np.bincount(a, weights=w,
                                          minlength=minlength)))


def lstsq(x, y, rcond=None, driver=None, name=None):
    def f(a, b):
        sol, res, rank, sv = jnp.linalg.lstsq(a, b, rcond=rcond)
        return sol, res, rank, sv
    return apply_op(f, x, y, _op_name="lstsq")


def corrcoef(x, rowvar=True, name=None):
    return apply_op(lambda a: jnp.corrcoef(a, rowvar=rowvar), x,
                    _op_name="corrcoef")


def cov(x, rowvar=True, ddof=True, fweights=None, aweights=None, name=None):
    fw = _unwrap(fweights) if fweights is not None else None
    aw = _unwrap(aweights) if aweights is not None else None
    return apply_op(
        lambda a: jnp.cov(a, rowvar=rowvar, ddof=1 if ddof else 0,
                          fweights=fw, aweights=aw), x, _op_name="cov")


def householder_product(x, tau, name=None):
    def f(a, t):
        m, n = a.shape[-2], a.shape[-1]
        eye = jnp.eye(m, dtype=a.dtype)

        def body(i, Q):
            v = jnp.where(jnp.arange(m) < i, 0.0,
                          jnp.where(jnp.arange(m) == i, 1.0, a[:, i]))
            H = eye - t[i] * jnp.outer(v, v)
            return Q @ H
        Q = jax.lax.fori_loop(0, t.shape[0], body, eye)
        return Q[:, :n]
    return apply_op(f, x, tau, _op_name="householder_product")


# bind methods
import sys

_this = sys.modules[__name__]
for _name in __all__:
    _fn = getattr(_this, _name, None)
    if callable(_fn) and not hasattr(Tensor, _name):
        Tensor._bind(_name, _fn)
del _this, _name, _fn


# ---------------------------------------------------------------------------
# long-tail linalg parity (reference tensor/linalg.py remainder)
# ---------------------------------------------------------------------------

def vector_norm(x, p=2.0, axis=None, keepdim=False, name=None):
    def f(a):
        if p == float("inf"):
            return jnp.max(jnp.abs(a), axis=axis, keepdims=keepdim)
        if p == float("-inf"):
            return jnp.min(jnp.abs(a), axis=axis, keepdims=keepdim)
        if p == 0:
            return jnp.sum((a != 0).astype(a.dtype), axis=axis,
                           keepdims=keepdim)
        return jnp.sum(jnp.abs(a) ** p, axis=axis,
                       keepdims=keepdim) ** (1.0 / p)
    return apply_op(f, x, _op_name="vector_norm")


def matrix_norm(x, p="fro", axis=(-2, -1), keepdim=False, name=None):
    def f(a):
        if p == "fro":
            return jnp.sqrt(jnp.sum(a * a, axis=axis, keepdims=keepdim))
        if p == "nuc":
            s = jnp.linalg.svd(a, compute_uv=False)
            out = jnp.sum(s, axis=-1)
            return out[..., None, None] if keepdim else out
        if p in (1, -1, jnp.inf, -jnp.inf, 2, -2):
            return jnp.linalg.norm(a, ord=p, axis=axis, keepdims=keepdim)
        raise ValueError(f"unsupported matrix norm order {p!r}")
    return apply_op(f, x, _op_name="matrix_norm")


def matrix_exp(x, name=None):
    import jax.scipy.linalg as jsl
    return apply_op(lambda a: jsl.expm(a), x, _op_name="matrix_exp")


def cholesky_inverse(x, upper=False, name=None):
    """Inverse of A given its Cholesky factor (tensor/linalg.py)."""
    def f(L):
        n = L.shape[-1]
        eye = jnp.broadcast_to(jnp.eye(n, dtype=L.dtype),
                               L.shape[:-2] + (n, n))
        import jax.scipy.linalg as jsl
        inv_f = jsl.solve_triangular(L, eye, lower=not upper)
        inv_t = inv_f.swapaxes(-1, -2)
        return inv_t @ inv_f if not upper else inv_f @ inv_t
    return apply_op(f, x, _op_name="cholesky_inverse")


def lu_unpack(lu_data, lu_pivots, unpack_ludata=True, unpack_pivots=True,
              name=None):
    """Unpack combined LU factors + pivots into (P, L, U)."""
    def f(lu, piv):
        n = lu.shape[-2]
        m = lu.shape[-1]
        k = min(n, m)
        L = jnp.tril(lu[..., :, :k], -1) + jnp.eye(n, k, dtype=lu.dtype)
        U = jnp.triu(lu[..., :k, :])
        # pivots (1-based sequential swaps) -> permutation matrix
        def perm_from_pivots(pv):
            perm = jnp.arange(n)
            def body(i, pm):
                j = pv[i] - 1
                a, b = pm[i], pm[j]
                pm = pm.at[i].set(b).at[j].set(a)
                return pm
            perm = jax.lax.fori_loop(0, pv.shape[0], body, perm)
            # rows of M @ A = L @ U are permuted by `perm`; the contract
            # A = P @ L @ U needs P = M.T, i.e. eye indexed by columns
            return jnp.eye(n, dtype=lu.dtype)[:, perm]
        fn_p = perm_from_pivots
        pv = piv.astype(jnp.int32)
        for _ in range(pv.ndim - 1):  # vmap over leading batch dims
            fn_p = jax.vmap(fn_p)
        P = fn_p(pv)
        return P, L, U
    return apply_op(f, lu_data, lu_pivots, _op_name="lu_unpack")


def ormqr(x, tau, other, left=True, transpose=False, name=None):
    """Multiply `other` by Q from a geqrf factorization (householder)."""
    def f(a, t, c):
        import jax.lax.linalg as lxl
        q = lxl.householder_product(a, t)
        qm = q.swapaxes(-1, -2) if transpose else q
        return qm @ c if left else c @ qm
    return apply_op(f, x, tau, other, _op_name="ormqr")


def svd_lowrank(x, q=6, niter=2, M=None, name=None):
    """Randomized low-rank SVD (tensor/linalg.py svd_lowrank)."""
    from ..framework import random as rnd
    key = rnd.op_key(x)

    def f(a, k):
        m, n = a.shape[-2:]
        r = min(q, m, n)
        omega = jax.random.normal(k, a.shape[:-2] + (n, r), a.dtype)
        y = a @ omega
        for _ in range(niter):
            y = a @ (a.swapaxes(-1, -2) @ y)
        Q, _ = jnp.linalg.qr(y)
        B = Q.swapaxes(-1, -2) @ a
        u, s, vh = jnp.linalg.svd(B, full_matrices=False)
        return Q @ u, s, vh.swapaxes(-1, -2)
    return apply_op(f, x, key, _op_name="svd_lowrank")


def pca_lowrank(x, q=None, center=True, niter=2, name=None):
    def f(a):
        return a - jnp.mean(a, axis=-2, keepdims=True) if center else a
    xc = apply_op(f, x, _op_name="pca_center")
    k = q if q is not None else min(6, *x.shape[-2:])
    u, s, v = svd_lowrank(xc, q=k, niter=niter)
    return u, s, v


def fp8_fp8_half_gemm_fused(x, y, transpose_x=False, transpose_y=False,
                            bias=None, scale=1.0, output_dtype="float16",
                            activation_type="identity", name=None):
    """fp8 x fp8 -> half GEMM (reference: cutlass fp8 kernel,
    phi/kernels/fusion/cutlass/fp8_gemm). TPU-native: cast to
    float8_e4m3fn and let the MXU (v5p+/Trillium fp8 paths, emulated
    elsewhere) accumulate; output in half precision."""
    from ..framework.dtype import to_dtype
    out_np = to_dtype(output_dtype).np_dtype

    def f(a, b, *bias_arr):
        a8 = a.astype(jnp.float8_e4m3fn)
        b8 = b.astype(jnp.float8_e4m3fn)
        if transpose_x:
            a8 = a8.swapaxes(-1, -2)
        if transpose_y:
            b8 = b8.swapaxes(-1, -2)
        out = jnp.matmul(a8, b8,
                         preferred_element_type=jnp.float32) * scale
        if bias_arr:
            out = out + bias_arr[0].astype(jnp.float32)
        if activation_type in ("gelu", "relu"):
            out = jax.nn.gelu(out) if activation_type == "gelu" \
                else jax.nn.relu(out)
        return out.astype(out_np)
    args = (x, y) + ((bias,) if bias is not None else ())
    return apply_op(f, *args, _op_name="fp8_fp8_half_gemm_fused")


_EXTRA_LINALG = ["vector_norm", "matrix_norm", "matrix_exp",
                 "cholesky_inverse", "lu_unpack", "ormqr", "svd_lowrank",
                 "pca_lowrank", "fp8_fp8_half_gemm_fused"]
__all__ += _EXTRA_LINALG
# the module's method-bind loop above ran before these were defined
for _name in _EXTRA_LINALG:
    Tensor._bind(_name, globals()[_name])
