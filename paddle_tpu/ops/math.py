"""Elementwise / reduction math ops (reference: python/paddle/tensor/math.py,
stat.py; kernels /root/reference/paddle/phi/kernels/*_kernel.h)."""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..framework.dtype import to_dtype
from ..framework.tensor import Tensor, apply_op, _unwrap

__all__ = [
    "add", "subtract", "multiply", "divide", "floor_divide", "remainder",
    "mod", "pow", "matmul_alias_guard", "maximum", "minimum", "fmax", "fmin",
    "abs", "sign", "neg", "reciprocal", "square", "sqrt", "rsqrt", "exp",
    "expm1", "log", "log2", "log10", "log1p", "floor", "ceil", "round",
    "trunc", "frac", "sin", "cos", "tan", "asin", "acos", "atan", "atan2",
    "sinh", "cosh", "tanh", "asinh", "acosh", "atanh", "erf", "erfinv",
    "lgamma", "digamma", "clip", "lerp", "scale", "increment", "stanh",
    "sum", "mean", "max", "min", "prod", "amax", "amin", "std", "var",
    "median", "nanmedian", "nansum", "nanmean", "argmax", "argmin", "cumsum",
    "cumprod", "cummax", "cummin", "logsumexp", "logcumsumexp", "isnan",
    "isinf", "isfinite", "all", "any", "kron", "trace", "diff", "angle",
    "conj", "real", "imag", "count_nonzero", "heaviside", "rad2deg",
    "deg2rad", "gcd", "lcm", "take", "multiply_", "add_n", "addmm", "inner",
    "outer", "logit", "nan_to_num",
]


def _ew(fn, name, *xs, **kw):
    """Route an elementwise op; promote python scalars transparently."""
    tensors = [x if isinstance(x, Tensor) else Tensor(jnp.asarray(x))
               for x in xs]
    return apply_op(fn, *tensors, _op_name=name, **kw)


def add(x, y, name=None):
    return _ew(jnp.add, "add", x, y)


def subtract(x, y, name=None):
    return _ew(jnp.subtract, "subtract", x, y)


def multiply(x, y, name=None):
    return _ew(jnp.multiply, "multiply", x, y)


def divide(x, y, name=None):
    return _ew(jnp.divide, "divide", x, y)


def floor_divide(x, y, name=None):
    return _ew(jnp.floor_divide, "floor_divide", x, y)


def remainder(x, y, name=None):
    return _ew(jnp.remainder, "remainder", x, y)


mod = remainder


def pow(x, y, name=None):
    return _ew(jnp.power, "pow", x, y)


matmul_alias_guard = None  # placeholder so __all__ import stays clean


def maximum(x, y, name=None):
    return _ew(jnp.maximum, "maximum", x, y)


def minimum(x, y, name=None):
    return _ew(jnp.minimum, "minimum", x, y)


def fmax(x, y, name=None):
    return _ew(jnp.fmax, "fmax", x, y)


def fmin(x, y, name=None):
    return _ew(jnp.fmin, "fmin", x, y)


def _unary(jfn, name):
    def op(x, name=None):
        return _ew(jfn, name_, x)
    name_ = name
    op.__name__ = name
    return op


abs = _unary(jnp.abs, "abs")
sign = _unary(jnp.sign, "sign")
neg = _unary(jnp.negative, "neg")
reciprocal = _unary(jnp.reciprocal, "reciprocal")
square = _unary(jnp.square, "square")
sqrt = _unary(jnp.sqrt, "sqrt")
rsqrt = _unary(lambda a: jax.lax.rsqrt(a), "rsqrt")
exp = _unary(jnp.exp, "exp")
expm1 = _unary(jnp.expm1, "expm1")
log = _unary(jnp.log, "log")
log2 = _unary(jnp.log2, "log2")
log10 = _unary(jnp.log10, "log10")
log1p = _unary(jnp.log1p, "log1p")
floor = _unary(jnp.floor, "floor")
ceil = _unary(jnp.ceil, "ceil")
round = _unary(jnp.round, "round")
trunc = _unary(jnp.trunc, "trunc")
frac = _unary(lambda a: a - jnp.trunc(a), "frac")
sin = _unary(jnp.sin, "sin")
cos = _unary(jnp.cos, "cos")
tan = _unary(jnp.tan, "tan")
asin = _unary(jnp.arcsin, "asin")
acos = _unary(jnp.arccos, "acos")
atan = _unary(jnp.arctan, "atan")
sinh = _unary(jnp.sinh, "sinh")
cosh = _unary(jnp.cosh, "cosh")
tanh = _unary(jnp.tanh, "tanh")
asinh = _unary(jnp.arcsinh, "asinh")
acosh = _unary(jnp.arccosh, "acosh")
atanh = _unary(jnp.arctanh, "atanh")
erf = _unary(jax.scipy.special.erf, "erf")
erfinv = _unary(jax.scipy.special.erfinv, "erfinv")
lgamma = _unary(jax.scipy.special.gammaln, "lgamma")
digamma = _unary(jax.scipy.special.digamma, "digamma")
isnan = _unary(jnp.isnan, "isnan")
isinf = _unary(jnp.isinf, "isinf")
isfinite = _unary(jnp.isfinite, "isfinite")
conj = _unary(jnp.conj, "conj")
real = _unary(jnp.real, "real")
imag = _unary(jnp.imag, "imag")
angle = _unary(jnp.angle, "angle")
rad2deg = _unary(jnp.rad2deg, "rad2deg")
deg2rad = _unary(jnp.deg2rad, "deg2rad")
logit = _unary(jax.scipy.special.logit, "logit")


def atan2(x, y, name=None):
    return _ew(jnp.arctan2, "atan2", x, y)


def heaviside(x, y, name=None):
    return _ew(jnp.heaviside, "heaviside", x, y)


def gcd(x, y, name=None):
    return _ew(jnp.gcd, "gcd", x, y)


def lcm(x, y, name=None):
    return _ew(jnp.lcm, "lcm", x, y)


def stanh(x, scale_a=0.67, scale_b=1.7159, name=None):
    return _ew(lambda a: scale_b * jnp.tanh(scale_a * a), "stanh", x)


def clip(x, min=None, max=None, name=None):
    lo = _unwrap(min) if min is not None else None
    hi = _unwrap(max) if max is not None else None
    return _ew(lambda a: jnp.clip(a, lo, hi), "clip", x)


def lerp(x, y, weight, name=None):
    if isinstance(weight, Tensor):
        return _ew(lambda a, b, w: a + w * (b - a), "lerp", x, y, weight)
    return _ew(lambda a, b: a + weight * (b - a), "lerp", x, y)


def scale(x, scale=1.0, bias=0.0, bias_after_scale=True, act=None, name=None):
    s, b = _unwrap(scale), _unwrap(bias)

    def f(a):
        return a * s + b if bias_after_scale else (a + b) * s

    return _ew(f, "scale", x)


def increment(x, value=1.0, name=None):
    return x._inplace(_ew(lambda a: a + value, "increment", x._snapshot()))


def nan_to_num(x, nan=0.0, posinf=None, neginf=None, name=None):
    return _ew(lambda a: jnp.nan_to_num(a, nan=nan, posinf=posinf,
                                        neginf=neginf), "nan_to_num", x)


# -- reductions -------------------------------------------------------------

def _axis(axis):
    if axis is None:
        return None
    if isinstance(axis, Tensor):
        ax = np.asarray(axis._data)
        return tuple(int(a) for a in ax.reshape(-1))
    if isinstance(axis, (list, tuple)):
        return tuple(int(a) for a in axis)
    return int(axis)


def _reduce(jfn, name):
    def op(x, axis=None, keepdim=False, name=None, dtype=None):
        ax = _axis(axis)
        kw = {}
        if dtype is not None:
            kw["dtype"] = to_dtype(dtype).np_dtype
        return _ew(lambda a: jfn(a, axis=ax, keepdims=keepdim, **kw),
                   name_, x)
    name_ = name
    op.__name__ = name
    return op


sum = _reduce(jnp.sum, "sum")
nansum = _reduce(jnp.nansum, "nansum")
prod = _reduce(jnp.prod, "prod")
amax = _reduce(jnp.max, "amax")
amin = _reduce(jnp.min, "amin")


def mean(x, axis=None, keepdim=False, name=None):
    def f(a):
        if jnp.issubdtype(a.dtype, jnp.integer) or a.dtype == jnp.bool_:
            a = a.astype(jnp.float32)
        return jnp.mean(a, axis=_axis(axis), keepdims=keepdim)
    return _ew(f, "mean", x)


nanmean = _reduce(jnp.nanmean, "nanmean")
max = _reduce(jnp.max, "max")
min = _reduce(jnp.min, "min")


def std(x, axis=None, unbiased=True, keepdim=False, name=None):
    return _ew(lambda a: jnp.std(a, axis=_axis(axis), ddof=1 if unbiased else 0,
                                 keepdims=keepdim), "std", x)


def var(x, axis=None, unbiased=True, keepdim=False, name=None):
    return _ew(lambda a: jnp.var(a, axis=_axis(axis), ddof=1 if unbiased else 0,
                                 keepdims=keepdim), "var", x)


def median(x, axis=None, keepdim=False, mode="avg", name=None):
    return _ew(lambda a: jnp.median(a, axis=_axis(axis), keepdims=keepdim),
               "median", x)


def nanmedian(x, axis=None, keepdim=False, name=None):
    return _ew(lambda a: jnp.nanmedian(a, axis=_axis(axis), keepdims=keepdim),
               "nanmedian", x)


def argmax(x, axis=None, keepdim=False, dtype="int64", name=None):
    dt = to_dtype(dtype).np_dtype
    return _ew(lambda a: jnp.argmax(a, axis=_axis(axis),
                                    keepdims=keepdim).astype(dt), "argmax", x)


def argmin(x, axis=None, keepdim=False, dtype="int64", name=None):
    dt = to_dtype(dtype).np_dtype
    return _ew(lambda a: jnp.argmin(a, axis=_axis(axis),
                                    keepdims=keepdim).astype(dt), "argmin", x)


def cumsum(x, axis=None, dtype=None, name=None):
    dt = to_dtype(dtype).np_dtype if dtype is not None else None
    return _ew(lambda a: jnp.cumsum(a if axis is not None else a.reshape(-1),
                                    axis=axis if axis is not None else 0,
                                    dtype=dt), "cumsum", x)


def cumprod(x, dim=None, dtype=None, name=None):
    dt = to_dtype(dtype).np_dtype if dtype is not None else None
    return _ew(lambda a: jnp.cumprod(a, axis=dim, dtype=dt), "cumprod", x)


def cummax(x, axis=None, dtype="int64", name=None):
    def f(a):
        flat = axis is None
        ax = 0 if flat else axis
        src = a.reshape(-1) if flat else a
        vals = jax.lax.associative_scan(jnp.maximum, src, axis=ax)
        idx = jnp.argmax(
            jnp.cumsum(jnp.ones_like(src, dtype=jnp.int32), axis=ax) *
            (src == vals), axis=ax)
        return vals
    return _ew(f, "cummax", x)


def cummin(x, axis=None, dtype="int64", name=None):
    def f(a):
        flat = axis is None
        ax = 0 if flat else axis
        src = a.reshape(-1) if flat else a
        return jax.lax.associative_scan(jnp.minimum, src, axis=ax)
    return _ew(f, "cummin", x)


def logsumexp(x, axis=None, keepdim=False, name=None):
    return _ew(lambda a: jax.scipy.special.logsumexp(
        a, axis=_axis(axis), keepdims=keepdim), "logsumexp", x)


def logcumsumexp(x, axis=None, name=None):
    def f(a):
        src = a.reshape(-1) if axis is None else a
        ax = 0 if axis is None else axis
        return jax.lax.associative_scan(jnp.logaddexp, src, axis=ax)
    return _ew(f, "logcumsumexp", x)


def all(x, axis=None, keepdim=False, name=None):
    return _ew(lambda a: jnp.all(a, axis=_axis(axis), keepdims=keepdim),
               "all", x)


def any(x, axis=None, keepdim=False, name=None):
    return _ew(lambda a: jnp.any(a, axis=_axis(axis), keepdims=keepdim),
               "any", x)


def count_nonzero(x, axis=None, keepdim=False, name=None):
    return _ew(lambda a: jnp.count_nonzero(a, axis=_axis(axis),
                                           keepdims=keepdim).astype(jnp.int64),
               "count_nonzero", x)


def trace(x, offset=0, axis1=0, axis2=1, name=None):
    return _ew(lambda a: jnp.trace(a, offset=offset, axis1=axis1, axis2=axis2),
               "trace", x)


def kron(x, y, name=None):
    return _ew(jnp.kron, "kron", x, y)


def diff(x, n=1, axis=-1, prepend=None, append=None, name=None):
    pre = _unwrap(prepend) if prepend is not None else None
    app = _unwrap(append) if append is not None else None
    return _ew(lambda a: jnp.diff(a, n=n, axis=axis, prepend=pre, append=app),
               "diff", x)


def take(x, index, mode="raise", name=None):
    return _ew(lambda a, i: jnp.take(a.reshape(-1), i.reshape(-1), mode="clip"
                                     if mode == "clip" else "wrap"
                                     if mode == "wrap" else None),
               "take", x, index)


def add_n(inputs, name=None):
    if isinstance(inputs, Tensor):
        return inputs
    tensors = list(inputs)

    def f(*arrs):
        out = arrs[0]
        for a in arrs[1:]:
            out = out + a
        return out

    return apply_op(f, *tensors, _op_name="add_n")


def addmm(input, x, y, beta=1.0, alpha=1.0, name=None):
    return _ew(lambda i, a, b: beta * i + alpha * (a @ b), "addmm",
               input, x, y)


def inner(x, y, name=None):
    return _ew(jnp.inner, "inner", x, y)


def outer(x, y, name=None):
    return _ew(lambda a, b: jnp.outer(a, b), "outer", x, y)


def multiply_(x, y):
    return x.multiply_(y)


# -- bind tensor methods ----------------------------------------------------
import sys

_this = sys.modules[__name__]
for _name in __all__:
    _fn = getattr(_this, _name, None)
    if callable(_fn) and not hasattr(Tensor, _name):
        Tensor._bind(_name, _fn)
del _this, _name, _fn
