"""Dynamic-quantized int8 matmul for TPU training forward passes.

The v5e MXU runs int8 x int8 -> int32 at ~2x the bf16 rate (measured
294.8 vs 167.6 TOPS on [6144,2048]x[2048,8192]; benchmarks/RESULTS.md).
``int8_linear`` exploits that for the *forward* matmul only:

  forward:  per-row activation scales + per-column weight scales
            (symmetric, dynamic — no calibration), int8 MXU matmul,
            fused dequant epilogue back to the activation dtype;
  backward: exact bf16 dgrad/wgrad via custom_vjp (a straight-through
            estimator w.r.t. the quantization rounding), so optimizer
            updates see full-precision gradients.

Reference behavior analog: the reference's QAT fake-quant linear
(python/paddle/nn/quant/qat/linear.py) simulates int8 in fp32; this is
the TPU-native real-int8 version that actually engages the int8 MXU
path. W8A8 with per-row/per-channel scales keeps per-matmul relative
error at the same order as bf16 rounding; bench_gpt_hybrid measures
end-to-end loss parity (see benchmarks/RESULTS.md).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

__all__ = ["int8_linear", "int8_linear_dgrad8", "int8_linear_all8",
           "int8_gelu_linear_all8", "int8_ln_linear_all8",
           "int8_dot_dequant",
           "quantize_rowwise", "quantize_rowwise_fast",
           "ln_quantize_rowwise", "sr_quantize_colwise",
           "sr_quantize_colwise_ln", "site_seed"]


def site_seed(seed, site: int):
    """The (layer, site) SR-stream derivation used by EVERY int8 block
    matmul: layer seeds arrive 16 apart (_layer_seeds), so seed*8+site
    keeps streams distinct; int32 wrap just mixes. One definition —
    _mm's closure and the fused gelu site both call this."""
    import jax.numpy as _jnp
    s = _jnp.int32(1) if seed is None else seed
    return s * _jnp.int32(8) + _jnp.int32(site)


def quantize_rowwise(x, axis):
    """Symmetric int8 quantization along ``axis``: returns (q, scale)
    with x ~= q * scale, scale shaped like x with ``axis`` size 1."""
    # one hoisted upcast: the amax pass and the cast pass share the f32
    # view instead of each materializing their own convert (dtype-
    # discipline pass, round 6 — XLA usually CSEs this, but the jaxpr
    # should not rely on it)
    xf = x.astype(jnp.float32)
    amax = jnp.max(jnp.abs(xf), axis=axis, keepdims=True)
    scale = jnp.where(amax == 0.0, 1.0, amax) / 127.0
    q = jnp.clip(jnp.round(xf / scale), -127, 127).astype(jnp.int8)
    return q, scale


# ---------------------------------------------------------------------------
# single-pass Pallas quantize
# ---------------------------------------------------------------------------
# XLA lowers quantize_rowwise to two passes over x in HBM: a reduce
# fusion for amax, then an elementwise fusion that re-reads x to scale
# and cast. The row fits in VMEM, so a Pallas kernel does amax + scale
# in ONE read of x — quantize passes were ~12 ms of the 411 ms flagship
# step (benchmarks/RESULTS.md round-3 decomposition), roughly half of
# which is the second read this kernel removes.

def _apply_act(x, act):
    """Producer-fused activation inside the quantize kernels: the
    activation's own HBM write + the quantizer's re-read disappear
    (round-5 lever d: ~27 ms of gelu+rowq+colq passes on the GPT step
    touch the same [6144, 8192] tensor three times without this)."""
    if act is None:
        return x
    if act == "gelu":
        # tanh-approximate gelu, matching jax.nn.gelu(approximate=True)
        c = jnp.float32(0.7978845608028654)      # sqrt(2/pi)
        return 0.5 * x * (1.0 + jnp.tanh(c * (x + 0.044715 * x * x * x)))
    raise ValueError(f"unsupported fused act {act!r}")


def _rowq_kernel(x_ref, q_ref, s_ref, *, act=None):
    x = _apply_act(x_ref[...].astype(jnp.float32), act)    # [bm, K]
    amax = jnp.max(jnp.abs(x), axis=1, keepdims=True)
    scale = jnp.where(amax == 0.0, 1.0, amax) / 127.0
    q_ref[...] = jnp.clip(jnp.round(x / scale), -127, 127) \
        .astype(jnp.int8)
    s_ref[...] = scale


def _colq_kernel(x_ref, q_ref, s_ref):
    x = x_ref[...].astype(jnp.float32)                     # [K, bn]
    amax = jnp.max(jnp.abs(x), axis=0, keepdims=True)
    scale = jnp.where(amax == 0.0, 1.0, amax) / 127.0
    q_ref[...] = jnp.clip(jnp.round(x / scale), -127, 127) \
        .astype(jnp.int8)
    s_ref[...] = scale


def _pick_block(rows: int, row_bytes: int, budget: int = 2 << 20) -> int:
    for b in (512, 256, 128, 64, 32, 16, 8):
        if rows % b == 0 and b * row_bytes <= budget:
            return b
    return 0


@functools.partial(jax.jit, static_argnums=(1, 2))
def _rowq_call(x2, interpret, act=None):
    M, K = x2.shape
    bm = _pick_block(M, K * x2.dtype.itemsize)
    kernel = pl.pallas_call(
        functools.partial(_rowq_kernel, act=act), grid=(M // bm,),
        in_specs=[pl.BlockSpec((bm, K), lambda i: (i, 0))],
        out_specs=[pl.BlockSpec((bm, K), lambda i: (i, 0)),
                   pl.BlockSpec((bm, 1), lambda i: (i, 0))],
        out_shape=[jax.ShapeDtypeStruct((M, K), jnp.int8),
                   jax.ShapeDtypeStruct((M, 1), jnp.float32)],
        interpret=interpret)
    return kernel(x2)


@functools.partial(jax.jit, static_argnums=(1,))
def _colq_call(x2, interpret):
    K, N = x2.shape
    bn = _pick_block(N, K * x2.dtype.itemsize)
    kernel = pl.pallas_call(
        _colq_kernel, grid=(N // bn,),
        in_specs=[pl.BlockSpec((K, bn), lambda j: (0, j))],
        out_specs=[pl.BlockSpec((K, bn), lambda j: (0, j)),
                   pl.BlockSpec((1, bn), lambda j: (0, j))],
        out_shape=[jax.ShapeDtypeStruct((K, N), jnp.int8),
                   jax.ShapeDtypeStruct((1, N), jnp.float32)],
        interpret=interpret)
    return kernel(x2)


def quantize_rowwise_fast(x, axis, interpret=None, act=None):
    """quantize_rowwise with a single-pass Pallas kernel where the
    layout permits (TPU backend, lane-aligned reduced dim, divisible
    row count); falls back to the XLA version otherwise. ``act``
    applies a producer-fused activation (see _apply_act) before
    quantizing — one read of x instead of act-write + quantize-read."""
    def _fallback(x, axis):
        if act is not None:
            # f32 like the Pallas kernel, so the two paths quantize
            # the same values (bit-identical across eligibility)
            x = _apply_act(x.astype(jnp.float32), act).astype(x.dtype)
        return quantize_rowwise(x, axis)
    if interpret is None:
        # single-device TPU only: under GSPMD the pallas_call is an
        # opaque custom call the partitioner would replicate, so
        # multi-device meshes keep the (partitionable) XLA fusion path
        if jax.default_backend() not in ("tpu", "axon") \
                or jax.device_count() != 1:
            return _fallback(x, axis)
        interpret = False
    axis = axis % x.ndim
    if axis == x.ndim - 1:
        lead = x.shape[:-1]
        K = x.shape[-1]
        M = 1
        for s in lead:
            M *= s
        if K % 128 == 0 and _pick_block(M, K * x.dtype.itemsize):
            q, s = _rowq_call(x.reshape(M, K), interpret, act)
            return q.reshape(x.shape), s.reshape(lead + (1,))
    elif axis == 0 and x.ndim == 2 and act is None:
        K, N = x.shape
        if N % 128 == 0 and K % 8 == 0 \
                and _pick_block(N, K * x.dtype.itemsize):
            return _colq_call(x, interpret)
    return _fallback(x, axis)


# ---------------------------------------------------------------------------
# producer-fused LayerNorm -> quantize (round-5 lever a)
# ---------------------------------------------------------------------------
# The qkv and ffn1 matmuls consume LayerNorm outputs. Unfused, each site
# pays: LN reads x + writes h, then the rowq kernel re-reads h — three
# HBM passes over a [6144, 2048] activation, twice per layer per
# execution (forward + remat recompute). LN is row-wise and the rowq
# kernel already holds full rows in VMEM, so stats + normalize + scale
# + amax + cast collapse into ONE read of the pre-LN activation. The
# wgrad SR column kernel cannot compute row stats from its column
# blocks, so the row kernel also emits mean/rstd ([M,1] f32 — 24 KB at
# the flagship shape) for the backward to reuse.

_LN_EPS = 1e-5


def _rowq_ln_kernel(x_ref, g_ref, b_ref, q_ref, s_ref, m_ref, r_ref):
    x = x_ref[...].astype(jnp.float32)                     # [bm, K]
    m = jnp.mean(x, axis=1, keepdims=True)
    xc = x - m
    v = jnp.mean(xc * xc, axis=1, keepdims=True)
    r = jax.lax.rsqrt(v + _LN_EPS)
    h = xc * r * g_ref[...].astype(jnp.float32) \
        + b_ref[...].astype(jnp.float32)
    amax = jnp.max(jnp.abs(h), axis=1, keepdims=True)
    scale = jnp.where(amax == 0.0, 1.0, amax) / 127.0
    q_ref[...] = jnp.clip(jnp.round(h / scale), -127, 127) \
        .astype(jnp.int8)
    s_ref[...] = scale
    m_ref[...] = m
    r_ref[...] = r


@functools.partial(jax.jit, static_argnums=(3,))
def _rowq_ln_call(x2, g, b, interpret):
    M, K = x2.shape
    bm = _pick_block(M, K * x2.dtype.itemsize)
    kernel = pl.pallas_call(
        _rowq_ln_kernel, grid=(M // bm,),
        in_specs=[pl.BlockSpec((bm, K), lambda i: (i, 0)),
                  pl.BlockSpec((1, K), lambda i: (0, 0)),
                  pl.BlockSpec((1, K), lambda i: (0, 0))],
        out_specs=[pl.BlockSpec((bm, K), lambda i: (i, 0)),
                   pl.BlockSpec((bm, 1), lambda i: (i, 0)),
                   pl.BlockSpec((bm, 1), lambda i: (i, 0)),
                   pl.BlockSpec((bm, 1), lambda i: (i, 0))],
        out_shape=[jax.ShapeDtypeStruct((M, K), jnp.int8),
                   jax.ShapeDtypeStruct((M, 1), jnp.float32),
                   jax.ShapeDtypeStruct((M, 1), jnp.float32),
                   jax.ShapeDtypeStruct((M, 1), jnp.float32)],
        interpret=interpret)
    return kernel(x2, g.reshape(1, K), b.reshape(1, K))


def _ln_stats(x2):
    xf = x2.astype(jnp.float32)
    m = jnp.mean(xf, axis=-1, keepdims=True)
    v = jnp.var(xf, axis=-1, keepdims=True)
    return m, jax.lax.rsqrt(v + _LN_EPS)


def ln_quantize_rowwise(x2, g, b, interpret=None):
    """LayerNorm + symmetric per-row int8 quantize of [M, K] in one
    pass: returns (q, scale, mean, rstd). The stats make the backward's
    column-quantize of LN(x) possible without re-deriving them from
    full rows (see sr_quantize_colwise_ln)."""
    M, K = x2.shape
    if interpret is None:
        if jax.default_backend() not in ("tpu", "axon") \
                or jax.device_count() != 1:
            interpret = None          # fall through to XLA
        else:
            interpret = False
    if interpret is not None and K % 128 == 0 \
            and _pick_block(M, K * x2.dtype.itemsize):
        return _rowq_ln_call(x2, g, b, interpret)
    m, r = _ln_stats(x2)
    h = (x2.astype(jnp.float32) - m) * r \
        * g.astype(jnp.float32) + b.astype(jnp.float32)
    q, s = quantize_rowwise(h, axis=-1)
    return q, s, m, r


def _sr_cast_ln_kernel(seed_ref, x_ref, m_ref, r_ref, g_ref, b_ref,
                       sc_ref, q_ref):
    # Tiled SR cast with the column scale precomputed: a whole-column
    # one-pass variant (amax in-kernel) needs the full [M, bn] block
    # plus an f32 LN temp resident, which blows the 16M scoped-vmem
    # budget at the flagship [6144, 2048] (the non-LN colq kernel fit
    # with 343K to spare; +h does not). Splitting amax out to one XLA
    # reduce fusion costs a second bf16 read of x but keeps the
    # in-kernel hardware PRNG (the XLA SR path would write+read a full
    # uint32 rng buffer per operand — the bigger tax).
    from jax.experimental.pallas import tpu as pltpu
    x = x_ref[...].astype(jnp.float32)
    h = (x - m_ref[...]) * r_ref[...] \
        * g_ref[...].astype(jnp.float32) \
        + b_ref[...].astype(jnp.float32)
    # Mosaic caps prng_seed at 2 values: fold the 2-D grid id into one
    pltpu.prng_seed(seed_ref[0], pl.program_id(0) * pl.num_programs(1)
                    + pl.program_id(1))
    bits = pltpu.prng_random_bits(h.shape).astype(jnp.uint32)
    f = jax.lax.bitcast_convert_type(
        jnp.uint32(0x3F800000) | (bits >> 9), jnp.float32)
    q_ref[...] = jnp.clip(jnp.floor(h / sc_ref[...] + (f - 1.0)),
                          -127, 127).astype(jnp.int8)


@functools.partial(jax.jit, static_argnums=(6,))
def _sr_colq_ln_pallas(x2, m, r, g, b, seed_i, interpret):
    M, C = x2.shape
    gf = g.astype(jnp.float32).reshape(1, C)
    bf = b.astype(jnp.float32).reshape(1, C)
    h_for_amax = (x2.astype(jnp.float32) - m) * r * gf + bf
    amax = jnp.max(jnp.abs(h_for_amax), axis=0, keepdims=True)
    scale = jnp.where(amax == 0.0, 1.0, amax) / 127.0
    bm = _pick_block(M, 256 * 4)
    bn = 256 if C % 256 == 0 else 128
    kernel = pl.pallas_call(
        _sr_cast_ln_kernel, grid=(M // bm, C // bn),
        in_specs=[pl.BlockSpec(memory_space=pltpu_smem()),
                  pl.BlockSpec((bm, bn), lambda i, j: (i, j)),
                  pl.BlockSpec((bm, 1), lambda i, j: (i, 0)),
                  pl.BlockSpec((bm, 1), lambda i, j: (i, 0)),
                  pl.BlockSpec((1, bn), lambda i, j: (0, j)),
                  pl.BlockSpec((1, bn), lambda i, j: (0, j)),
                  pl.BlockSpec((1, bn), lambda i, j: (0, j))],
        out_specs=[pl.BlockSpec((bm, bn), lambda i, j: (i, j))],
        out_shape=[jax.ShapeDtypeStruct((M, C), jnp.int8)],
        interpret=interpret)
    (q,) = kernel(seed_i.reshape(1), x2, m, r,
                  g.reshape(1, C), b.reshape(1, C), scale)
    return q, scale


def sr_quantize_colwise_ln(x2, m, r, g, b, seed_i):
    """Unbiased int8 column quantize of LN(x2) given precomputed row
    stats; one read of the PRE-LN activation instead of an LN pass plus
    a re-read of its output."""
    M, C = x2.shape
    if jax.default_backend() in ("tpu", "axon") \
            and jax.device_count() == 1 \
            and C % 128 == 0 and _pick_block(M, 256 * 4):
        return _sr_colq_ln_pallas(x2, m, r, g, b, seed_i, False)
    h = ((x2.astype(jnp.float32) - m) * r
         * g.astype(jnp.float32) + b.astype(jnp.float32))
    return _sr_colq_xla(h, seed_i)


def int8_dot_dequant(aq, a_scale, bq, b_scale, dims, out_dtype=None):
    """int8 dot_general + f32 dequant. ``dims`` = (a_axes, b_axes)
    contraction dims; scales must already broadcast against the
    result. The ONE quantized-matmul core shared by the block matmuls
    and the CE head (three call paths, one arithmetic). ``out_dtype``
    folds the final downcast into the dequant epilogue so the fusion
    writes the consumer dtype directly instead of an f32 buffer plus a
    separate convert (dtype-discipline pass, round 6); scale math stays
    f32 either way."""
    y = jax.lax.dot_general(aq, bq, (dims, ((), ())),
                            preferred_element_type=jnp.int32)
    out = y.astype(jnp.float32) * a_scale * b_scale
    return out if out_dtype is None else out.astype(out_dtype)


def _int8_matmul(x, w):
    """x [..., K] @ w [K, N] with int8 MXU math, output in x.dtype."""
    xq, xs = quantize_rowwise_fast(x, axis=-1)     # [..., 1]
    wq, ws = quantize_rowwise_fast(w, axis=0)      # [1, N]
    return int8_dot_dequant(xq, xs, wq, ws, ((x.ndim - 1,), (0,)),
                            out_dtype=x.dtype)


@jax.custom_vjp
def int8_linear(x, w):
    """Forward int8 x int8 matmul; backward exact in the input dtype."""
    return _int8_matmul(x, w)


def _fwd(x, w):
    return _int8_matmul(x, w), (x, w)


def _bwd(res, g):
    x, w = res
    # dgrad/wgrad in bf16: gradients have too much dynamic range for
    # naive per-row int8, and the optimizer's moment estimates would
    # see the quantization noise twice
    dx = jax.lax.dot_general(g, w, (((g.ndim - 1,), (1,)), ((), ())))
    k = x.ndim - 1
    dw = jax.lax.dot_general(
        x, g, ((tuple(range(k)), tuple(range(k))), ((), ())))
    return dx.astype(x.dtype), dw.astype(w.dtype)


int8_linear.defvjp(_fwd, _bwd)


@jax.custom_vjp
def int8_linear_dgrad8(x, w):
    """Like int8_linear but the ACTIVATION gradient (dgrad) also runs on
    the int8 MXU: per-row scales on the incoming cotangent, per-row
    scales on w's contraction dim. The WEIGHT gradient stays exact bf16
    — it feeds the optimizer's moment estimates directly, where
    quantization noise integrates over steps."""
    return _int8_matmul(x, w)


def _fwd8(x, w):
    return _int8_matmul(x, w), (x, w)


def _bwd8(res, g):
    x, w = res
    # dx = g [..., N] @ w.T [N, K], both sides int8-quantized along N
    gq, gs = quantize_rowwise_fast(g, axis=-1)       # [..., 1]
    wq, ws = quantize_rowwise_fast(w, axis=1)        # [K, 1]
    y = jax.lax.dot_general(gq, wq, (((g.ndim - 1,), (1,)), ((), ())),
                            preferred_element_type=jnp.int32)
    dx = (y.astype(jnp.float32) * gs *
          jnp.reshape(ws, (1,) * (g.ndim - 1) + (-1,)))
    k = x.ndim - 1
    dw = jax.lax.dot_general(
        x, g, ((tuple(range(k)), tuple(range(k))), ((), ())))
    return dx.astype(x.dtype), dw.astype(w.dtype)


int8_linear_dgrad8.defvjp(_fwd8, _bwd8)


# ---------------------------------------------------------------------------
# int8 wgrad with stochastic rounding (round 4)
# ---------------------------------------------------------------------------
# The weight gradient dw[k,n] = sum_m x[m,k] g[m,n] contracts the token
# axis. Round-to-nearest int8 there would feed a persistent, data-
# correlated bias straight into Adam's moments; stochastic rounding
# makes each quantization UNBIASED (E[q*s] = value), so over steps the
# wgrad noise integrates to zero like SGD noise instead of drifting.
# Streams are decorrelated per (step, layer, site, operand) via the
# seed, drawn in-kernel from the TPU hardware PRNG (no HBM rng buffer —
# the XLA lowering would write+read a full uint32 buffer per operand).

def _colq_sr_kernel(seed_ref, x_ref, q_ref, s_ref, *, act=None):
    from jax.experimental.pallas import tpu as pltpu
    x = _apply_act(x_ref[...].astype(jnp.float32), act)    # [M, bn]
    amax = jnp.max(jnp.abs(x), axis=0, keepdims=True)
    scale = jnp.where(amax == 0.0, 1.0, amax) / 127.0
    pltpu.prng_seed(seed_ref[0], pl.program_id(0))
    bits = pltpu.prng_random_bits(x.shape).astype(jnp.uint32)
    f = jax.lax.bitcast_convert_type(
        jnp.uint32(0x3F800000) | (bits >> 9), jnp.float32)
    q_ref[...] = jnp.clip(jnp.floor(x / scale + (f - 1.0)),
                          -127, 127).astype(jnp.int8)
    s_ref[...] = scale


@functools.partial(jax.jit, static_argnums=(2, 3))
def _sr_colq_pallas(x2, seed_i, interpret, act=None):
    """Column-wise (per output channel) symmetric int8 SR quantize of
    [M, C] in ONE read of x: full-column blocks (M x 128 lanes) hold
    the whole reduction in VMEM, so amax, SR bits, and the cast happen
    in a single pass — the XLA lowering is a convert+abs+reduce pass
    PLUS a re-reading cast pass (~33 ms/step of abs_reduce fusions on
    the GPT-1.3B step before this kernel)."""
    M, C = x2.shape
    # f32 temps are M*bn*4 and several are live at once (x, bits, u,
    # q-pre-cast) plus double-buffered IO: ~4.5 copies must fit the
    # 16M scoped-vmem budget
    bn = 256 if (C % 256 == 0 and M * 256 * 4 * 9 // 2 <= (15 << 20)) \
        else 128
    kernel = pl.pallas_call(
        functools.partial(_colq_sr_kernel, act=act), grid=(C // bn,),
        in_specs=[pl.BlockSpec(memory_space=pltpu_smem()),
                  pl.BlockSpec((M, bn), lambda j: (0, j))],
        out_specs=[pl.BlockSpec((M, bn), lambda j: (0, j)),
                   pl.BlockSpec((1, bn), lambda j: (0, j))],
        out_shape=[jax.ShapeDtypeStruct((M, C), jnp.int8),
                   jax.ShapeDtypeStruct((1, C), jnp.float32)],
        interpret=interpret)
    return kernel(seed_i.reshape(1), x2)


def pltpu_smem():
    from jax.experimental.pallas import tpu as pltpu
    return pltpu.SMEM


def _sr_colq_xla(x2, seed_i, act=None):
    """Portable SR column quantize (CPU tests / ineligible layouts)."""
    if act is not None:
        x2 = _apply_act(x2.astype(jnp.float32), act)
    amax = jnp.max(jnp.abs(x2.astype(jnp.float32)), axis=0,
                   keepdims=True)
    scale = jnp.where(amax == 0.0, 1.0, amax) / 127.0
    key = jax.random.fold_in(jax.random.PRNGKey(0),
                             seed_i.astype(jnp.uint32))
    u = jax.random.uniform(key, x2.shape, jnp.float32)
    q = jnp.clip(jnp.floor(x2.astype(jnp.float32) / scale + u),
                 -127, 127).astype(jnp.int8)
    return q, scale


def sr_quantize_colwise(x2, seed_i, act=None):
    """Unbiased int8 quantize of [M, C] with per-column scales;
    ``act`` fuses an activation before quantization (one read)."""
    M, C = x2.shape
    if jax.default_backend() in ("tpu", "axon") \
            and jax.device_count() == 1 \
            and C % 128 == 0 and M % 8 == 0 \
            and M * 128 * 4 * 9 // 2 <= (15 << 20):
        return _sr_colq_pallas(x2, seed_i, False, act)
    return _sr_colq_xla(x2, seed_i, act)


@jax.custom_vjp
def int8_linear_all8(x, w, seed):
    """int8 MXU matmul on all three step matmuls: forward and dgrad as
    in ``int8_linear_dgrad8``; wgrad ALSO int8, with stochastic-rounding
    quantization along the token axis (unbiased — see module note).
    ``seed`` is a traced int32 scalar decorrelating SR streams per
    (step, microbatch, layer, site); int32 wrap-around only mixes the
    stream, it never collapses distinct seeds onto each other the way
    f32 rounding of large bases would. Its cotangent is float0."""
    del seed
    return _int8_matmul(x, w)


def _fwd_all8(x, w, seed):
    return _int8_matmul(x, w), (x, w, seed)


def _bwd_all8(res, g):
    x, w, seed = res
    # dgrad: int8 per-row, as int8_linear_dgrad8
    gq, gs = quantize_rowwise_fast(g, axis=-1)
    wq, ws = quantize_rowwise_fast(w, axis=1)
    y = jax.lax.dot_general(gq, wq, (((g.ndim - 1,), (1,)), ((), ())),
                            preferred_element_type=jnp.int32)
    dx = (y.astype(jnp.float32) * gs *
          jnp.reshape(ws, (1,) * (g.ndim - 1) + (-1,)))
    # wgrad: int8 with SR quantization along the contraction (tokens)
    K = x.shape[-1]
    N = g.shape[-1]
    x2 = x.reshape(-1, K)
    g2 = g.reshape(-1, N)
    base = jnp.asarray(seed, jnp.int32) * jnp.int32(1000003)
    xq, xs = sr_quantize_colwise(x2, base + jnp.int32(7919))
    gq2, gs2 = sr_quantize_colwise(g2, base + jnp.int32(104729))
    dwi = jax.lax.dot_general(xq, gq2, (((0,), (0,)), ((), ())),
                              preferred_element_type=jnp.int32)
    dw = dwi.astype(jnp.float32) * xs.reshape(K, 1) * gs2  # [K,N]
    import numpy as np
    return (dx.astype(x.dtype), dw.astype(w.dtype),
            np.zeros((), jax.dtypes.float0))


int8_linear_all8.defvjp(_fwd_all8, _bwd_all8)


@jax.custom_vjp
def int8_gelu_linear_all8(x, w, seed):
    """``int8_linear_all8(gelu(x), w, seed)`` with the gelu computed
    INSIDE the quantize kernels (round-5 lever d): x here is the
    PRE-activation (the saved ffn1 residual). Forward and wgrad each
    read x once and never materialize the bf16 gelu output; dgrad
    chains through gelu' outside (one fused elementwise)."""
    del seed
    return _int8_matmul_gelu(x, w)


def _int8_matmul_gelu(x, w):
    xq, xs = quantize_rowwise_fast(x, axis=-1, act="gelu")
    wq, ws = quantize_rowwise_fast(w, axis=0)
    return int8_dot_dequant(xq, xs, wq, ws, ((x.ndim - 1,), (0,)),
                            out_dtype=x.dtype)


def _fwd_gelu_all8(x, w, seed):
    return _int8_matmul_gelu(x, w), (x, w, seed)


def _bwd_gelu_all8(res, g):
    x, w, seed = res
    # dgrad w.r.t. a = gelu(x): int8 per-row, as int8_linear_all8
    gq, gs = quantize_rowwise_fast(g, axis=-1)
    wq, ws = quantize_rowwise_fast(w, axis=1)
    y = jax.lax.dot_general(gq, wq, (((g.ndim - 1,), (1,)), ((), ())),
                            preferred_element_type=jnp.int32)
    da = (y.astype(jnp.float32) * gs *
          jnp.reshape(ws, (1,) * (g.ndim - 1) + (-1,)))
    # chain through gelu' (tanh approximation, matching _apply_act)
    _, gelu_vjp = jax.vjp(
        lambda t: jax.nn.gelu(t.astype(jnp.float32), approximate=True),
        x)
    dx = gelu_vjp(da)[0]
    # wgrad: SR int8 of a = gelu(x), fused in the colq kernel
    K = x.shape[-1]
    N = g.shape[-1]
    x2 = x.reshape(-1, K)
    g2 = g.reshape(-1, N)
    base = jnp.asarray(seed, jnp.int32) * jnp.int32(1000003)
    aq, as_ = sr_quantize_colwise(x2, base + jnp.int32(7919),
                                  act="gelu")
    gq2, gs2 = sr_quantize_colwise(g2, base + jnp.int32(104729))
    dwi = jax.lax.dot_general(aq, gq2, (((0,), (0,)), ((), ())),
                              preferred_element_type=jnp.int32)
    dw = dwi.astype(jnp.float32) * as_.reshape(K, 1) * gs2
    import numpy as np
    return (dx.astype(x.dtype), dw.astype(w.dtype),
            np.zeros((), jax.dtypes.float0))


int8_gelu_linear_all8.defvjp(_fwd_gelu_all8, _bwd_gelu_all8)


def _int8_matmul_ln(x, g_ln, b_ln, w):
    lead, K = x.shape[:-1], x.shape[-1]
    x2 = x.reshape(-1, K)
    q, s, m, r = ln_quantize_rowwise(x2, g_ln, b_ln)
    wq, ws = quantize_rowwise_fast(w, axis=0)
    y = int8_dot_dequant(q, s, wq, ws, ((1,), (0,)),
                         out_dtype=x.dtype)
    return y.reshape(lead + (w.shape[1],)), m, r


def _env_fuse_bwd_colq() -> bool:
    import os
    return os.environ.get("PTPU_FUSE_BWD_COLQ", "0") \
        not in ("0", "", "false")


def int8_ln_linear_all8(x, g_ln, b_ln, w, seed, fuse_bwd_colq=None):
    """``int8_linear_all8(layer_norm(x, g_ln, b_ln), w, seed)`` with
    the LayerNorm computed INSIDE the quantize kernels (round-5 lever
    a): x is the PRE-LN residual stream. Forward and wgrad each read x
    once and never materialize the bf16 LN output; the backward chains
    the LN vjp outside (one fused elementwise + row reductions) and
    returns real gradients for g_ln/b_ln.

    ``fuse_bwd_colq`` (ADVICE r5 — was the dead module constant
    _FUSE_BWD_COLQ): True computes the wgrad column quantize of LN(x)
    from the forward's saved [M,1] mean/rstd stats
    (sr_quantize_colwise_ln — two reads of the pre-LN x, no h buffer);
    False re-materializes h once (shared with the LN vjp) and runs the
    plain one-pass colq kernel, and the [M,1] stats are NOT saved as
    residuals at all. None defers to env PTPU_FUSE_BWD_COLQ; the
    trainer threads its own knob (GPTSpmdTrainer(fuse_bwd_colq=...))."""
    if fuse_bwd_colq is None:
        fuse_bwd_colq = _env_fuse_bwd_colq()
    return _int8_ln_linear_all8(bool(fuse_bwd_colq), x, g_ln, b_ln, w,
                                seed)


@functools.partial(jax.custom_vjp, nondiff_argnums=(0,))
def _int8_ln_linear_all8(fuse_bwd_colq, x, g_ln, b_ln, w, seed):
    del seed
    return _int8_matmul_ln(x, g_ln, b_ln, w)[0]


def _fwd_ln_all8(fuse_bwd_colq, x, g_ln, b_ln, w, seed):
    y, m, r = _int8_matmul_ln(x, g_ln, b_ln, w)
    # the [M,1] stats are residuals ONLY for the fused-bwd-colq branch;
    # when it is off they would be dead saves (ADVICE r5)
    stats = (m, r) if fuse_bwd_colq else None
    return y, (x, g_ln, b_ln, w, seed, stats)


def _bwd_ln_all8(fuse_bwd_colq, res, gy):
    x, g_ln, b_ln, w, seed, stats = res
    K = x.shape[-1]
    N = gy.shape[-1]
    # dgrad w.r.t. h = LN(x): int8 per-row, as int8_linear_all8
    gq, gs = quantize_rowwise_fast(gy, axis=-1)
    wq, ws = quantize_rowwise_fast(w, axis=1)
    y = jax.lax.dot_general(gq, wq, (((gy.ndim - 1,), (1,)), ((), ())),
                            preferred_element_type=jnp.int32)
    da = (y.astype(jnp.float32) * gs *
          jnp.reshape(ws, (1,) * (gy.ndim - 1) + (-1,)))
    # LN vjp via jax.vjp on the bf16 cotangent — replays the exact
    # graph the unfused path's autodiff built. A hand-written f32 vjp
    # from the saved stats measured +23.6 ms/step: the f32 [M, K]
    # cotangent feeds three row reductions XLA cannot fuse into one
    # pass, while this form fuses like any other LN backward.
    def _ref_ln(xx, gg, bb):
        xf = xx.astype(jnp.float32)
        mu = jnp.mean(xf, axis=-1, keepdims=True)
        va = jnp.var(xf, axis=-1, keepdims=True)
        out = (xf - mu) * jax.lax.rsqrt(va + _LN_EPS)
        return (out * gg + bb).astype(xx.dtype)

    h, ln_vjp = jax.vjp(_ref_ln, x, g_ln, b_ln)
    dx, dg_ln, db_ln = ln_vjp(da.astype(x.dtype))
    # wgrad: SR int8 of h = LN(x). fuse_bwd_colq=True computes the LN
    # inside the colq path (amax pass + tiled SR cast, two reads of x,
    # no h buffer) from the saved stats; False materializes h once
    # (shared with the vjp above) and runs the plain one-pass colq
    # kernel — the bwd then matches the unfused path op-for-op (A/B
    # isolation knob).
    g2 = gy.reshape(-1, N)
    base = jnp.asarray(seed, jnp.int32) * jnp.int32(1000003)
    if fuse_bwd_colq:
        m, r = stats
        hq, hs = sr_quantize_colwise_ln(x.reshape(-1, K), m, r,
                                        g_ln, b_ln,
                                        base + jnp.int32(7919))
    else:
        hq, hs = sr_quantize_colwise(h.reshape(-1, K),
                                     base + jnp.int32(7919))
    gq2, gs2 = sr_quantize_colwise(g2, base + jnp.int32(104729))
    dwi = jax.lax.dot_general(hq, gq2, (((0,), (0,)), ((), ())),
                              preferred_element_type=jnp.int32)
    dw = dwi.astype(jnp.float32) * hs.reshape(K, 1) * gs2
    import numpy as np
    return (dx, dg_ln, db_ln, dw.astype(w.dtype),
            np.zeros((), jax.dtypes.float0))


_int8_ln_linear_all8.defvjp(_fwd_ln_all8, _bwd_ln_all8)
