"""Dynamic-quantized int8 matmul for TPU training forward passes.

The v5e MXU runs int8 x int8 -> int32 at ~2x the bf16 rate (measured
294.8 vs 167.6 TOPS on [6144,2048]x[2048,8192]; benchmarks/RESULTS.md).
``int8_linear`` exploits that for the *forward* matmul only:

  forward:  per-row activation scales + per-column weight scales
            (symmetric, dynamic — no calibration), int8 MXU matmul,
            fused dequant epilogue back to the activation dtype;
  backward: exact bf16 dgrad/wgrad via custom_vjp (a straight-through
            estimator w.r.t. the quantization rounding), so optimizer
            updates see full-precision gradients.

Reference behavior analog: the reference's QAT fake-quant linear
(python/paddle/nn/quant/qat/linear.py) simulates int8 in fp32; this is
the TPU-native real-int8 version that actually engages the int8 MXU
path. W8A8 with per-row/per-channel scales keeps per-matmul relative
error at the same order as bf16 rounding; bench_gpt_hybrid measures
end-to-end loss parity (see benchmarks/RESULTS.md).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

__all__ = ["int8_linear", "int8_linear_dgrad8", "quantize_rowwise"]


def quantize_rowwise(x, axis):
    """Symmetric int8 quantization along ``axis``: returns (q, scale)
    with x ~= q * scale, scale shaped like x with ``axis`` size 1."""
    amax = jnp.max(jnp.abs(x.astype(jnp.float32)), axis=axis,
                   keepdims=True)
    scale = jnp.where(amax == 0.0, 1.0, amax) / 127.0
    q = jnp.clip(jnp.round(x.astype(jnp.float32) / scale), -127, 127) \
        .astype(jnp.int8)
    return q, scale


def _int8_matmul(x, w):
    """x [..., K] @ w [K, N] with int8 MXU math, output in x.dtype."""
    xq, xs = quantize_rowwise(x, axis=-1)          # [..., 1]
    wq, ws = quantize_rowwise(w, axis=0)           # [1, N]
    y = jax.lax.dot_general(xq, wq, (((x.ndim - 1,), (0,)), ((), ())),
                            preferred_element_type=jnp.int32)
    return (y.astype(jnp.float32) * xs * ws).astype(x.dtype)


@jax.custom_vjp
def int8_linear(x, w):
    """Forward int8 x int8 matmul; backward exact in the input dtype."""
    return _int8_matmul(x, w)


def _fwd(x, w):
    return _int8_matmul(x, w), (x, w)


def _bwd(res, g):
    x, w = res
    # dgrad/wgrad in bf16: gradients have too much dynamic range for
    # naive per-row int8, and the optimizer's moment estimates would
    # see the quantization noise twice
    dx = jax.lax.dot_general(g, w, (((g.ndim - 1,), (1,)), ((), ())))
    k = x.ndim - 1
    dw = jax.lax.dot_general(
        x, g, ((tuple(range(k)), tuple(range(k))), ((), ())))
    return dx.astype(x.dtype), dw.astype(w.dtype)


int8_linear.defvjp(_fwd, _bwd)


@jax.custom_vjp
def int8_linear_dgrad8(x, w):
    """Like int8_linear but the ACTIVATION gradient (dgrad) also runs on
    the int8 MXU: per-row scales on the incoming cotangent, per-row
    scales on w's contraction dim. The WEIGHT gradient stays exact bf16
    — it feeds the optimizer's moment estimates directly, where
    quantization noise integrates over steps."""
    return _int8_matmul(x, w)


def _fwd8(x, w):
    return _int8_matmul(x, w), (x, w)


def _bwd8(res, g):
    x, w = res
    # dx = g [..., N] @ w.T [N, K], both sides int8-quantized along N
    gq, gs = quantize_rowwise(g, axis=-1)            # [..., 1]
    wq, ws = quantize_rowwise(w, axis=1)             # [K, 1]
    y = jax.lax.dot_general(gq, wq, (((g.ndim - 1,), (1,)), ((), ())),
                            preferred_element_type=jnp.int32)
    dx = (y.astype(jnp.float32) * gs *
          jnp.reshape(ws, (1,) * (g.ndim - 1) + (-1,)))
    k = x.ndim - 1
    dw = jax.lax.dot_general(
        x, g, ((tuple(range(k)), tuple(range(k))), ((), ())))
    return dx.astype(x.dtype), dw.astype(w.dtype)


int8_linear_dgrad8.defvjp(_fwd8, _bwd8)
