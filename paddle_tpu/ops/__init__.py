"""Op namespace: the TPU-native replacement for the phi kernel library +
YAML-generated API (/root/reference/paddle/phi/kernels, ~507k LoC;
/root/reference/paddle/phi/ops/yaml/ops.yaml 467 forward ops).

Every op is a thin jax.numpy/lax composition routed through
``framework.tensor.apply_op`` — XLA supplies the kernels, fusion, and (via
jax.vjp) every gradient, so there are no per-backend kernel files and no
separate backward.yaml: one definition serves CPU/TPU, eager/jit, fwd/bwd.
"""
from .creation import *  # noqa: F401,F403
from .math import *  # noqa: F401,F403
from .manipulation import *  # noqa: F401,F403
from .linalg import *  # noqa: F401,F403
from .logic import *  # noqa: F401,F403
from .random_ops import *  # noqa: F401,F403
from .extras import *  # noqa: F401,F403
