"""Fused AdamW update as a single Pallas TPU kernel per parameter leaf.

Why: XLA schedules the sharded AdamW update as separate HLO passes —
(m,v) moment fusion, then a full-size ``rng-bit-generator`` buffer
materialized to HBM, then the stochastic-rounding parameter fusion that
reads it back. Measured on the GPT-1.3B step that is ~26 bytes of HBM
traffic per parameter (~50 ms/step at 1.3B params). The information
floor is 14 bytes/param (read p,g,m,v; write p,m,v at bf16): this
kernel hits it by computing the whole update — including the
stochastic-rounding random bits, drawn from the core's hardware PRNG
via ``pltpu.prng_random_bits`` — inside one VMEM-resident pass.

Semantics match ``models/gpt.py:GPTSpmdTrainer._adamw`` exactly
(decoupled weight decay on every leaf, fp32 update math, bias
correction, optional exact stochastic rounding to bf16 masters). The
reference's analog is the fused multi-tensor Adam CUDA kernels
(paddle/phi/kernels/gpu/fused_adam_kernel.cu, multi_tensor_adam);
this is the TPU-native version.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

__all__ = ["fused_adamw_update", "fused_adamw_eligible"]


def _kernel(sc_ref, seed_ref, p_ref, g_ref, m_ref, v_ref,
            po_ref, mo_ref, vo_ref, *,
            lr, wd, b1, b2, eps, stoch_round, leaf_id):
    scale = sc_ref[0]
    inv_bc1 = sc_ref[1]
    inv_bc2 = sc_ref[2]
    # dynamic lr multiplier (schedules trace per step; the base lr
    # stays a compile-time constant so the schedule costs nothing)
    lr = lr * sc_ref[3]
    g = g_ref[...].astype(jnp.float32) * scale
    m2 = b1 * m_ref[...].astype(jnp.float32) + (1.0 - b1) * g
    v2 = b2 * v_ref[...].astype(jnp.float32) + (1.0 - b2) * g * g
    p2 = p_ref[...].astype(jnp.float32) * (1.0 - lr * wd) - \
        lr * (m2 * inv_bc1) / (jnp.sqrt(v2 * inv_bc2) + eps)
    if stoch_round:
        # exact stochastic rounding f32 -> bf16: add uniform 16-bit
        # noise below the kept mantissa, then truncate. Truncation is
        # done by zeroing the low 16 bits and converting — the convert
        # is exact because the dropped bits are already zero.
        # Mosaic's prng_seed takes at most two words: fold the leaf id
        # into the first and the flat tile index into the second
        tile = pl.program_id(0) * pl.num_programs(1) + pl.program_id(1)
        pltpu.prng_seed(seed_ref[0] + jnp.int32(leaf_id * 1000003), tile)
        bits = pltpu.prng_random_bits(p2.shape).astype(jnp.uint32)
        u = jax.lax.bitcast_convert_type(p2, jnp.uint32)
        y = u + (bits & jnp.uint32(0xFFFF))
        y = jnp.where(jnp.isfinite(p2), y, u)
        po_ref[...] = jax.lax.bitcast_convert_type(
            y & jnp.uint32(0xFFFF0000), jnp.float32).astype(jnp.bfloat16)
    else:
        po_ref[...] = p2.astype(po_ref.dtype)
    mo_ref[...] = m2.astype(mo_ref.dtype)
    vo_ref[...] = v2.astype(vo_ref.dtype)


def _tile(n, candidates):
    for c in candidates:
        if n % c == 0:
            return c
    return None


def fused_adamw_eligible(p) -> bool:
    """Leaves the kernel can take: collapsible to [R, C] with the lane
    dim a multiple of 128 and the sublane dim a multiple of 8 (so the
    2-D view is a layout bitcast of the (8,128)-tiled original), and
    big enough that a kernel launch beats the XLA fusion."""
    if p.ndim < 2 or p.size < (1 << 16):
        return False
    c = p.shape[-1]
    r = p.size // c
    return c % 128 == 0 and r % 8 == 0 and \
        _tile(c, (2048, 1024, 512, 384, 256, 128)) is not None and \
        _tile(r, (512, 256, 128, 64, 32, 16, 8)) is not None


@functools.partial(jax.jit, static_argnames=(
    "lr", "wd", "b1", "b2", "eps", "stoch_round", "leaf_id",
    "interpret"))
def fused_adamw_update(p, g, m, v, scale, inv_bc1, inv_bc2, seed, *,
                       lr, wd, b1, b2, eps=1e-8, stoch_round=False,
                       leaf_id=0, interpret=False, lr_scale=1.0):
    """One-pass AdamW: returns (p', m', v').

    ``scale``: global grad-clip multiplier (traced f32 scalar).
    ``inv_bc1``/``inv_bc2``: 1/(1-beta^t) bias corrections.
    ``seed``: int32 scalar; the PRNG stream is (seed, leaf_id, tile).
    """
    shape = p.shape
    C = shape[-1]
    R = p.size // C
    bc = _tile(C, (2048, 1024, 512, 384, 256, 128))
    br = _tile(R, (512, 256, 128, 64, 32, 16, 8))
    # cap the tile at 512KB bf16: 7 live buffers x double-buffering
    # x fp32 temps must fit the 16MB scoped-VMEM budget
    while br > 8 and br * bc * 2 > (1 << 19) and R % (br // 2) == 0:
        br //= 2
    p2 = p.reshape(R, C)
    g2 = g.reshape(R, C)
    m2 = m.reshape(R, C)
    v2 = v.reshape(R, C)
    sc = jnp.stack([jnp.asarray(scale, jnp.float32),
                    jnp.asarray(inv_bc1, jnp.float32),
                    jnp.asarray(inv_bc2, jnp.float32),
                    jnp.asarray(lr_scale, jnp.float32)])
    seed = jnp.asarray(seed, jnp.int32).reshape(1)
    grid = (R // br, C // bc)
    blk = pl.BlockSpec((br, bc), lambda i, j: (i, j))
    out_dtype = jnp.bfloat16 if stoch_round else p.dtype
    po, mo, vo = pl.pallas_call(
        functools.partial(_kernel, lr=lr, wd=wd, b1=b1, b2=b2, eps=eps,
                          stoch_round=stoch_round, leaf_id=leaf_id),
        grid=grid,
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
            blk, blk, blk, blk,
        ],
        out_specs=[blk, blk, blk],
        out_shape=[
            jax.ShapeDtypeStruct((R, C), out_dtype),
            jax.ShapeDtypeStruct((R, C), m.dtype),
            jax.ShapeDtypeStruct((R, C), v.dtype),
        ],
        # update in place: p/m/v buffers are donated by the train step
        input_output_aliases={2: 0, 4: 1, 5: 2},
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel")),
        interpret=interpret,
    )(sc, seed, p2, g2, m2, v2)
    return po.reshape(shape), mo.reshape(shape), vo.reshape(shape)
