"""Fused AdamW update as a single Pallas TPU kernel per parameter leaf.

Why: XLA schedules the sharded AdamW update as separate HLO passes —
(m,v) moment fusion, then a full-size ``rng-bit-generator`` buffer
materialized to HBM, then the stochastic-rounding parameter fusion that
reads it back. Measured on the GPT-1.3B step that is ~26 bytes of HBM
traffic per parameter (~50 ms/step at 1.3B params). The information
floor is 14 bytes/param (read p,g,m,v; write p,m,v at bf16): this
kernel hits it by computing the whole update — including the
stochastic-rounding random bits, drawn from the core's hardware PRNG
via ``pltpu.prng_random_bits`` — inside one VMEM-resident pass.

Semantics match ``models/gpt.py:GPTSpmdTrainer._adamw`` exactly
(decoupled weight decay on every leaf, fp32 update math, bias
correction, optional exact stochastic rounding to bf16 masters). The
reference's analog is the fused multi-tensor Adam CUDA kernels
(paddle/phi/kernels/gpu/fused_adam_kernel.cu, multi_tensor_adam);
this is the TPU-native version.

Dtype-discipline audit (round 6, part of the convert-tail sweep): all
bf16<->f32 conversion happens INSIDE the kernels on VMEM-resident
blocks — no dtype boundary here materializes an HBM convert. The
kernels sit at the 14 B/param (bf16 moments) / ~10 B/param (int8
moments) information floor; the remaining optimizer-adjacent HBM
passes live in the caller (grad-clip global norm reads every leaf
once) and are shared with the unfused path.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

__all__ = ["fused_adamw_update", "fused_adamw_eligible",
           "fused_adamw_update8", "moment8_init", "moment8_unpack",
           "moment8_eligible"]


def _kernel(sc_ref, seed_ref, p_ref, g_ref, m_ref, v_ref,
            po_ref, mo_ref, vo_ref, *,
            lr, wd, b1, b2, eps, stoch_round, leaf_id):
    scale = sc_ref[0]
    inv_bc1 = sc_ref[1]
    inv_bc2 = sc_ref[2]
    # dynamic lr multiplier (schedules trace per step; the base lr
    # stays a compile-time constant so the schedule costs nothing)
    lr = lr * sc_ref[3]
    g = g_ref[...].astype(jnp.float32) * scale
    m2 = b1 * m_ref[...].astype(jnp.float32) + (1.0 - b1) * g
    v2 = b2 * v_ref[...].astype(jnp.float32) + (1.0 - b2) * g * g
    p2 = p_ref[...].astype(jnp.float32) * (1.0 - lr * wd) - \
        lr * (m2 * inv_bc1) / (jnp.sqrt(v2 * inv_bc2) + eps)
    if stoch_round:
        # exact stochastic rounding f32 -> bf16: add uniform 16-bit
        # noise below the kept mantissa, then truncate. Truncation is
        # done by zeroing the low 16 bits and converting — the convert
        # is exact because the dropped bits are already zero.
        # Mosaic's prng_seed takes at most two words: fold the leaf id
        # into the first and the flat tile index into the second
        tile = pl.program_id(0) * pl.num_programs(1) + pl.program_id(1)
        pltpu.prng_seed(seed_ref[0] + jnp.int32(leaf_id * 1000003), tile)
        bits = pltpu.prng_random_bits(p2.shape).astype(jnp.uint32)
        u = jax.lax.bitcast_convert_type(p2, jnp.uint32)
        y = u + (bits & jnp.uint32(0xFFFF))
        y = jnp.where(jnp.isfinite(p2), y, u)
        po_ref[...] = jax.lax.bitcast_convert_type(
            y & jnp.uint32(0xFFFF0000), jnp.float32).astype(jnp.bfloat16)
    else:
        po_ref[...] = p2.astype(po_ref.dtype)
    mo_ref[...] = m2.astype(mo_ref.dtype)
    vo_ref[...] = v2.astype(vo_ref.dtype)


def _tile(n, candidates):
    for c in candidates:
        if n % c == 0:
            return c
    return None


def fused_adamw_eligible(p) -> bool:
    """Leaves the kernel can take: collapsible to [R, C] with the lane
    dim a multiple of 128 and the sublane dim a multiple of 8 (so the
    2-D view is a layout bitcast of the (8,128)-tiled original), and
    big enough that a kernel launch beats the XLA fusion."""
    if p.ndim < 2 or p.size < (1 << 16):
        return False
    c = p.shape[-1]
    r = p.size // c
    return c % 128 == 0 and r % 8 == 0 and \
        _tile(c, (2048, 1024, 512, 384, 256, 128)) is not None and \
        _tile(r, (512, 256, 128, 64, 32, 16, 8)) is not None


# ---------------------------------------------------------------------------
# int8 moment storage (round-5 lever b): 14 -> 10 bytes/param
# ---------------------------------------------------------------------------
# The bf16-moment kernel's HBM floor is 14 B/param (read p,g,m,v; write
# p,m,v). Storing both moments int8 with per-row f32 scales
# cuts that to ~10 B/param: m quantizes directly (zero-mean; stochastic
# rounding keeps the EMA recurrence unbiased), v stores sqrt(v) (halves
# the dynamic range an int8 grid must span; also the quantity the
# update actually divides by). A v entry whose sqrt SR-rounds to zero
# is refreshed by the (1-b2) g^2 term the same step, which bounds the
# worst-case update inflation at ~sqrt(1/(1-b2)) ~ 4.5x of a normal
# Adam step — a spike, not a blow-up; the 300-step parity harness is
# the accept/reject gate (benchmarks/parity_int8.py --moment8).
# Scales are per-ROW [R, 1] f32 (one per 2048-6144 values): the kernel
# takes full-row blocks on a 1-D grid, so the row amax is computable
# in-block and the scale block shape satisfies Mosaic's lane rules.

def _kernel8(sc_ref, seed_ref, p_ref, g_ref, m_ref, ms_ref, v_ref,
             vs_ref, po_ref, mo_ref, mso_ref, vo_ref, vso_ref, *,
             lr, wd, b1, b2, eps, stoch_round, leaf_id):
    scale = sc_ref[0]
    inv_bc1 = sc_ref[1]
    inv_bc2 = sc_ref[2]
    lr = lr * sc_ref[3]
    # 1-D grid of full-row blocks: per-ROW scales ([R,1] f32 — the
    # (br,1) scale block satisfies Mosaic's last-dim rule, which a
    # per-(row, col-tile) [R, C/bc] layout does not)
    pltpu.prng_seed(seed_ref[0] + jnp.int32(leaf_id * 1000003),
                    pl.program_id(0))

    def _unif(shape):
        bits = pltpu.prng_random_bits(shape).astype(jnp.uint32)
        return jax.lax.bitcast_convert_type(
            jnp.uint32(0x3F800000) | (bits >> 9), jnp.float32) - 1.0

    g = g_ref[...].astype(jnp.float32) * scale
    m = m_ref[...].astype(jnp.float32) * ms_ref[...]
    vsq = v_ref[...].astype(jnp.float32) * vs_ref[...]
    v = vsq * vsq
    m2 = b1 * m + (1.0 - b1) * g
    v2 = b2 * v + (1.0 - b2) * g * g
    p2 = p_ref[...].astype(jnp.float32) * (1.0 - lr * wd) - \
        lr * (m2 * inv_bc1) / (jnp.sqrt(v2 * inv_bc2) + eps)
    if stoch_round:
        bits = pltpu.prng_random_bits(p2.shape).astype(jnp.uint32)
        u = jax.lax.bitcast_convert_type(p2, jnp.uint32)
        y = u + (bits & jnp.uint32(0xFFFF))
        y = jnp.where(jnp.isfinite(p2), y, u)
        po_ref[...] = jax.lax.bitcast_convert_type(
            y & jnp.uint32(0xFFFF0000), jnp.float32).astype(jnp.bfloat16)
    else:
        po_ref[...] = p2.astype(po_ref.dtype)
    # requantize m with SR (unbiased: the EMA must not drift)
    ma = jnp.max(jnp.abs(m2), axis=1, keepdims=True)
    msc = jnp.where(ma == 0.0, 1.0, ma) / 127.0
    mo_ref[...] = jnp.clip(jnp.floor(m2 / msc + _unif(m2.shape)),
                           -127, 127).astype(jnp.int8)
    mso_ref[...] = msc
    # requantize sqrt(v) with SR (non-negative: codes 0..127)
    s2 = jnp.sqrt(v2)
    va = jnp.max(s2, axis=1, keepdims=True)
    vsc = jnp.where(va == 0.0, 1.0, va) / 127.0
    vo_ref[...] = jnp.clip(jnp.floor(s2 / vsc + _unif(s2.shape)),
                           0, 127).astype(jnp.int8)
    vso_ref[...] = vsc


def _row_block(R: int, C: int):
    # full-row blocks: ~10 live [br, C] f32 temps must fit scoped VMEM
    for br in (512, 256, 128, 64, 32, 16, 8):
        if R % br == 0 and br * C <= (1 << 18):
            return br
    return None


def moment8_eligible(p) -> bool:
    """fused_adamw_eligible AND rows narrow enough that a full row
    block fits VMEM (the vocab-head leaves stay bf16)."""
    if not fused_adamw_eligible(p):
        return False
    C = p.shape[-1]
    return _row_block(p.size // C, C) is not None


def moment8_init(p):
    """Zero int8-moment state for one eligible leaf: returns
    (m_q, m_scale, v_q, v_scale) — [R, C] int8 + per-row [R, 1] f32."""
    C = p.shape[-1]
    R = p.size // C
    z8 = jnp.zeros((R, C), jnp.int8)
    sc = jnp.full((R, 1), 1.0 / 127.0, jnp.float32)
    return z8, sc, z8, sc


def moment8_unpack(mq, msc, vq, vsc, shape):
    """Dequantize int8 moment state back to f32 (checkpoint export /
    debugging): inverse of the kernel's requantize."""
    m = (mq.astype(jnp.float32) * msc).reshape(shape)
    s = (vq.astype(jnp.float32) * vsc).reshape(shape)
    return m, (s * s).reshape(shape)


@functools.partial(jax.jit, static_argnames=(
    "lr", "wd", "b1", "b2", "eps", "stoch_round", "leaf_id",
    "interpret"))
def fused_adamw_update8(p, g, mq, msc, vq, vsc, scale, inv_bc1,
                        inv_bc2, seed, *, lr, wd, b1, b2, eps=1e-8,
                        stoch_round=False, leaf_id=0, interpret=False,
                        lr_scale=1.0):
    """One-pass AdamW with int8 moment storage: returns
    (p', m_q', m_scale', v_q', v_scale'). Same contract as
    fused_adamw_update otherwise."""
    shape = p.shape
    C = shape[-1]
    R = p.size // C
    br = _row_block(R, C)
    sc = jnp.stack([jnp.asarray(scale, jnp.float32),
                    jnp.asarray(inv_bc1, jnp.float32),
                    jnp.asarray(inv_bc2, jnp.float32),
                    jnp.asarray(lr_scale, jnp.float32)])
    seed = jnp.asarray(seed, jnp.int32).reshape(1)
    blk = pl.BlockSpec((br, C), lambda i: (i, 0))
    sblk = pl.BlockSpec((br, 1), lambda i: (i, 0))
    out_dtype = jnp.bfloat16 if stoch_round else p.dtype
    po, mo, mso, vo, vso = pl.pallas_call(
        functools.partial(_kernel8, lr=lr, wd=wd, b1=b1, b2=b2,
                          eps=eps, stoch_round=stoch_round,
                          leaf_id=leaf_id),
        grid=(R // br,),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
            blk, blk, blk, sblk, blk, sblk,
        ],
        out_specs=[blk, blk, sblk, blk, sblk],
        out_shape=[
            jax.ShapeDtypeStruct((R, C), out_dtype),
            jax.ShapeDtypeStruct((R, C), jnp.int8),
            jax.ShapeDtypeStruct((R, 1), jnp.float32),
            jax.ShapeDtypeStruct((R, C), jnp.int8),
            jax.ShapeDtypeStruct((R, 1), jnp.float32),
        ],
        input_output_aliases={2: 0, 4: 1, 5: 2, 6: 3, 7: 4},
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel",)),
        interpret=interpret,
    )(sc, seed, p.reshape(R, C), g.reshape(R, C), mq, msc, vq, vsc)
    return po.reshape(shape), mo, mso, vo, vso


@functools.partial(jax.jit, static_argnames=(
    "lr", "wd", "b1", "b2", "eps", "stoch_round", "leaf_id",
    "interpret"))
def fused_adamw_update(p, g, m, v, scale, inv_bc1, inv_bc2, seed, *,
                       lr, wd, b1, b2, eps=1e-8, stoch_round=False,
                       leaf_id=0, interpret=False, lr_scale=1.0):
    """One-pass AdamW: returns (p', m', v').

    ``scale``: global grad-clip multiplier (traced f32 scalar).
    ``inv_bc1``/``inv_bc2``: 1/(1-beta^t) bias corrections.
    ``seed``: int32 scalar; the PRNG stream is (seed, leaf_id, tile).
    """
    shape = p.shape
    C = shape[-1]
    R = p.size // C
    bc = _tile(C, (2048, 1024, 512, 384, 256, 128))
    br = _tile(R, (512, 256, 128, 64, 32, 16, 8))
    # cap the tile at 512KB bf16: 7 live buffers x double-buffering
    # x fp32 temps must fit the 16MB scoped-VMEM budget
    while br > 8 and br * bc * 2 > (1 << 19) and R % (br // 2) == 0:
        br //= 2
    p2 = p.reshape(R, C)
    g2 = g.reshape(R, C)
    m2 = m.reshape(R, C)
    v2 = v.reshape(R, C)
    sc = jnp.stack([jnp.asarray(scale, jnp.float32),
                    jnp.asarray(inv_bc1, jnp.float32),
                    jnp.asarray(inv_bc2, jnp.float32),
                    jnp.asarray(lr_scale, jnp.float32)])
    seed = jnp.asarray(seed, jnp.int32).reshape(1)
    grid = (R // br, C // bc)
    blk = pl.BlockSpec((br, bc), lambda i, j: (i, j))
    out_dtype = jnp.bfloat16 if stoch_round else p.dtype
    po, mo, vo = pl.pallas_call(
        functools.partial(_kernel, lr=lr, wd=wd, b1=b1, b2=b2, eps=eps,
                          stoch_round=stoch_round, leaf_id=leaf_id),
        grid=grid,
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
            blk, blk, blk, blk,
        ],
        out_specs=[blk, blk, blk],
        out_shape=[
            jax.ShapeDtypeStruct((R, C), out_dtype),
            jax.ShapeDtypeStruct((R, C), m.dtype),
            jax.ShapeDtypeStruct((R, C), v.dtype),
        ],
        # update in place: p/m/v buffers are donated by the train step
        input_output_aliases={2: 0, 4: 1, 5: 2},
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel")),
        interpret=interpret,
    )(sc, seed, p2, g2, m2, v2)
    return po.reshape(shape), mo.reshape(shape), vo.reshape(shape)
