"""Fused vocab-chunked softmax cross-entropy.

Reference: the reference fuses softmax+CE on GPU
(``paddle/phi/kernels/gpu/cross_entropy_kernel.cu``,
``c_softmax_with_cross_entropy`` for the tensor-parallel variant in
``paddle/fluid/operators/collective/``). TPU-native version: instead of a
hand-written kernel, stream the LM head matmul over vocab chunks with an
online-logsumexp (flash-attention-style rescaling) so the full
``[batch, seq, vocab]`` logits tensor is NEVER materialized in HBM —
the dominant memory cost of LLM training steps at large vocab. The
backward is a custom VJP that recomputes chunk logits and accumulates
``dx``/``dhead`` per chunk, so peak memory stays O(vocab_chunk).
"""
from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["fused_softmax_cross_entropy"]


def _chunk_heads(head, n_chunks, vocab_major):
    if vocab_major:                       # head: [V, D]
        V, D = head.shape
        return head.reshape(n_chunks, V // n_chunks, D)  # [C, Vc, D]
    D, V = head.shape
    Vc = V // n_chunks
    return head.reshape(D, n_chunks, Vc).transpose(1, 0, 2)  # [C, D, Vc]


def _chunk_logits(x, hc, vocab_major):
    """fp32-accumulated logits for one head chunk, either layout —
    vocab-major keeps the TIED embedding's native [V, D] layout end to
    end (no 200MB transpose materialized for dhead in the backward)."""
    eq = "btd,vd->btv" if vocab_major else "btd,dv->btv"
    return jnp.einsum(eq, x, hc, preferred_element_type=jnp.float32)


def _quantized_x(x, int8):
    """Quantize the activations ONCE, outside the vocab-chunk scan —
    the Pallas quantize is an opaque custom call XLA cannot hoist out
    of lax.scan itself. Scale structure = the block matmuls' proven
    per-row/per-col recipe (ops/quant_matmul.py)."""
    if not int8:
        return None
    from .quant_matmul import quantize_rowwise_fast
    return quantize_rowwise_fast(x, axis=-1)


def _head_logits_int8(xq_xs, hc, vocab_major=False):
    from .quant_matmul import quantize_rowwise_fast, int8_dot_dequant
    xq, xs = xq_xs
    hq, hs = quantize_rowwise_fast(hc, axis=1 if vocab_major else 0)
    if vocab_major:
        # hc [Vc, D] -> per-vocab-row scales [Vc, 1]: broadcast against
        # [..., Vc] logits needs the LAST axis
        hs = jnp.reshape(hs, (1,) * (xq.ndim - 1) + (-1,))
    cdim = ((xq.ndim - 1,), (1,) if vocab_major else (0,))
    return int8_dot_dequant(xq, xs, hq, hs, cdim)


def _forward(x, head, labels, n_chunks, int8=False,
             vocab_major=False):
    """Online logsumexp over vocab chunks; returns (loss, (max, sumexp))."""
    V = head.shape[0] if vocab_major else head.shape[1]
    Vc = V // n_chunks
    hb = _chunk_heads(head.astype(x.dtype), n_chunks, vocab_major)
    xq_xs = _quantized_x(x, int8)

    def body(carry, hc):
        m, s, lterm, off = carry
        lg = _head_logits_int8(xq_xs, hc, vocab_major) if int8 else \
            _chunk_logits(x, hc, vocab_major)
        m2 = jnp.maximum(m, lg.max(-1))
        s = s * jnp.exp(m - m2) + jnp.exp(lg - m2[..., None]).sum(-1)
        idx = labels - off
        inb = (idx >= 0) & (idx < Vc)
        pick = jnp.take_along_axis(
            lg, jnp.clip(idx, 0, Vc - 1)[..., None], -1)[..., 0]
        return (m2, s, lterm + jnp.where(inb, pick, 0.0), off + Vc), None

    m0 = jnp.full(x.shape[:-1], -jnp.inf, jnp.float32)
    s0 = jnp.zeros(x.shape[:-1], jnp.float32)
    (m, s, lterm, _), _ = jax.lax.scan(body, (m0, s0, s0, 0), hb)
    lse = m + jnp.log(s)
    return jnp.mean(lse - lterm), (m, s)


@partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def fused_softmax_cross_entropy(x, head, labels, n_chunks=8,
                                int8=False, vocab_major=False):
    """Mean token NLL of ``softmax(x @ head)`` against integer ``labels``.

    x: [..., D] activations (bf16/f32); head: [D, V] (or [V, D] with
    ``vocab_major=True`` — the tied-embedding layout, gradient returned
    in the same layout with no transpose); labels: [...] int. V must
    divide by n_chunks. Equivalent to
    ``-mean(log_softmax(x @ head)[labels])`` with fp32 accumulation, but
    O(V/n_chunks) peak memory.
    """
    return _forward(x, head, labels, n_chunks, int8, vocab_major)[0]


def _ce_fwd(x, head, labels, n_chunks, int8, vocab_major):
    loss, (m, s) = _forward(x, head, labels, n_chunks, int8,
                            vocab_major)
    return loss, (x, head, labels, m, s)


def _ce_bwd(n_chunks, int8, vocab_major, res, g):
    x, head, labels, m, s = res
    V = head.shape[0] if vocab_major else head.shape[1]
    D = head.shape[1] if vocab_major else head.shape[0]
    Vc = V // n_chunks
    hb = _chunk_heads(head.astype(x.dtype), n_chunks, vocab_major)
    n_tokens = np.float32(np.prod(x.shape[:-1]))

    xq_xs = _quantized_x(x, int8)

    def body(carry, hc):
        dx, off = carry
        # the recompute must match the forward's arithmetic exactly —
        # softmax normalizers (m, s) were computed on THOSE logits
        lg = _head_logits_int8(xq_xs, hc, vocab_major) if int8 else \
            _chunk_logits(x, hc, vocab_major)
        p = jnp.exp(lg - m[..., None]) / s[..., None]
        idx = labels - off
        inb = (idx >= 0) & (idx < Vc)
        onehot = jax.nn.one_hot(jnp.where(inb, idx, -1), Vc, dtype=p.dtype)
        dlg = (p - onehot) * (g / n_tokens)
        dlg = dlg.astype(x.dtype)
        if int8:
            from .quant_matmul import (quantize_rowwise_fast,
                                       int8_dot_dequant)
            gq, gs = quantize_rowwise_fast(dlg, axis=-1)
            hcq, hcs = quantize_rowwise_fast(hc,
                                             axis=0 if vocab_major
                                             else 1)
            dxc = int8_dot_dequant(
                gq, gs, hcq,
                jnp.reshape(hcs, (1,) * (dlg.ndim - 1) + (-1,)),
                ((dlg.ndim - 1,), (0,) if vocab_major else (1,)))
        else:
            eq = "btv,vd->btd" if vocab_major else "btv,dv->btd"
            dxc = jnp.einsum(eq, dlg, hc,
                             preferred_element_type=jnp.float32)
        eqh = "btv,btd->vd" if vocab_major else "btd,btv->dv"
        dhc = jnp.einsum(eqh, *((dlg, x) if vocab_major else (x, dlg)),
                         preferred_element_type=jnp.float32)
        return (dx + dxc, off + Vc), dhc

    dx0 = jnp.zeros(x.shape, jnp.float32)
    (dx, _), dh = jax.lax.scan(body, (dx0, 0), hb)
    if vocab_major:
        dh = dh.reshape(V, D)        # [C, Vc, D] stack: zero-copy
    else:
        dh = dh.transpose(1, 0, 2).reshape(D, V)
    return dx.astype(x.dtype), dh.astype(head.dtype), None


fused_softmax_cross_entropy.defvjp(_ce_fwd, _ce_bwd)
