"""Fused vocab-chunked softmax cross-entropy.

Reference: the reference fuses softmax+CE on GPU
(``paddle/phi/kernels/gpu/cross_entropy_kernel.cu``,
``c_softmax_with_cross_entropy`` for the tensor-parallel variant in
``paddle/fluid/operators/collective/``). TPU-native version: instead of a
hand-written kernel, stream the LM head matmul over vocab chunks with an
online-logsumexp (flash-attention-style rescaling) so the full
``[batch, seq, vocab]`` logits tensor is NEVER materialized in HBM —
the dominant memory cost of LLM training steps at large vocab. The
backward is a custom VJP that recomputes chunk logits and accumulates
``dx``/``dhead`` per chunk, so peak memory stays O(vocab_chunk).
"""
from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["fused_softmax_cross_entropy"]


def _chunk_heads(head, n_chunks):
    D, V = head.shape
    Vc = V // n_chunks
    return head.reshape(D, n_chunks, Vc).transpose(1, 0, 2)  # [C, D, Vc]


def _forward(x, head, labels, n_chunks):
    """Online logsumexp over vocab chunks; returns (loss, (max, sumexp))."""
    Vc = head.shape[1] // n_chunks
    hb = _chunk_heads(head.astype(x.dtype), n_chunks)

    def body(carry, hc):
        m, s, lterm, off = carry
        lg = jnp.einsum("btd,dv->btv", x, hc,
                        preferred_element_type=jnp.float32)
        m2 = jnp.maximum(m, lg.max(-1))
        s = s * jnp.exp(m - m2) + jnp.exp(lg - m2[..., None]).sum(-1)
        idx = labels - off
        inb = (idx >= 0) & (idx < Vc)
        pick = jnp.take_along_axis(
            lg, jnp.clip(idx, 0, Vc - 1)[..., None], -1)[..., 0]
        return (m2, s, lterm + jnp.where(inb, pick, 0.0), off + Vc), None

    m0 = jnp.full(x.shape[:-1], -jnp.inf, jnp.float32)
    s0 = jnp.zeros(x.shape[:-1], jnp.float32)
    (m, s, lterm, _), _ = jax.lax.scan(body, (m0, s0, s0, 0), hb)
    lse = m + jnp.log(s)
    return jnp.mean(lse - lterm), (m, s)


@partial(jax.custom_vjp, nondiff_argnums=(3,))
def fused_softmax_cross_entropy(x, head, labels, n_chunks=8):
    """Mean token NLL of ``softmax(x @ head)`` against integer ``labels``.

    x: [..., D] activations (bf16/f32); head: [D, V]; labels: [...] int.
    V must divide by n_chunks. Equivalent to
    ``-mean(log_softmax(x @ head)[labels])`` with fp32 accumulation, but
    O(V/n_chunks) peak memory.
    """
    return _forward(x, head, labels, n_chunks)[0]


def _ce_fwd(x, head, labels, n_chunks):
    loss, (m, s) = _forward(x, head, labels, n_chunks)
    return loss, (x, head, labels, m, s)


def _ce_bwd(n_chunks, res, g):
    x, head, labels, m, s = res
    D, V = head.shape
    Vc = V // n_chunks
    hb = _chunk_heads(head.astype(x.dtype), n_chunks)
    n_tokens = np.float32(np.prod(x.shape[:-1]))

    def body(carry, hc):
        dx, off = carry
        lg = jnp.einsum("btd,dv->btv", x, hc,
                        preferred_element_type=jnp.float32)
        p = jnp.exp(lg - m[..., None]) / s[..., None]
        idx = labels - off
        inb = (idx >= 0) & (idx < Vc)
        onehot = jax.nn.one_hot(jnp.where(inb, idx, -1), Vc, dtype=p.dtype)
        dlg = (p - onehot) * (g / n_tokens)
        dlg = dlg.astype(x.dtype)
        dxc = jnp.einsum("btv,dv->btd", dlg, hc,
                         preferred_element_type=jnp.float32)
        dhc = jnp.einsum("btd,btv->dv", x, dlg,
                         preferred_element_type=jnp.float32)
        return (dx + dxc, off + Vc), dhc

    dx0 = jnp.zeros(x.shape, jnp.float32)
    (dx, _), dh = jax.lax.scan(body, (dx0, 0), hb)
    dh = dh.transpose(1, 0, 2).reshape(D, V)
    return dx.astype(x.dtype), dh.astype(head.dtype), None


fused_softmax_cross_entropy.defvjp(_ce_fwd, _ce_bwd)
