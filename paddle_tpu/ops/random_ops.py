"""Random sampling ops (reference: python/paddle/tensor/random.py; phi
Generator /root/reference/paddle/phi/core/generator.h:32).

TPU-native: stateless JAX PRNG keys drawn from the global stateful
``framework.random`` counter generator, keeping paddle's stateful-RNG user
model while staying reproducible and shardable.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..framework import random as rnd
from ..framework import dtype as dtype_mod
from ..framework.dtype import to_dtype
from ..framework.tensor import Tensor, apply_op, _unwrap

__all__ = [
    "rand", "randn", "randint", "randint_like", "uniform", "normal",
    "standard_normal", "gaussian", "randperm", "multinomial", "bernoulli",
    "poisson", "exponential_", "uniform_", "normal_", "shuffle",
]


def _shape_list(shape):
    if isinstance(shape, Tensor):
        return tuple(int(s) for s in np.asarray(shape._data))
    if isinstance(shape, (int, np.integer)):
        return (int(shape),)
    return tuple(int(_unwrap(s)) if not isinstance(s, Tensor) else int(s._data)
                 for s in shape)


def _fdt(dtype):
    return to_dtype(dtype).np_dtype if dtype is not None \
        else dtype_mod.get_default_dtype().np_dtype


def rand(shape, dtype=None, name=None):
    return Tensor(jax.random.uniform(rnd.next_key(), _shape_list(shape),
                                     dtype=_fdt(dtype)))


def randn(shape, dtype=None, name=None):
    return Tensor(jax.random.normal(rnd.next_key(), _shape_list(shape),
                                    dtype=_fdt(dtype)))


standard_normal = randn


def gaussian(shape, mean=0.0, std=1.0, seed=0, dtype=None, name=None):
    key = rnd.next_key() if seed == 0 else jax.random.key(seed)
    return Tensor(mean + std * jax.random.normal(key, _shape_list(shape),
                                                 dtype=_fdt(dtype)))


def normal(mean=0.0, std=1.0, shape=None, name=None):
    if isinstance(mean, Tensor) or isinstance(std, Tensor):
        m = _unwrap(mean) if isinstance(mean, Tensor) else mean
        s = _unwrap(std) if isinstance(std, Tensor) else std
        shp = jnp.broadcast_shapes(
            np.shape(m) if not isinstance(m, jax.Array) else m.shape,
            np.shape(s) if not isinstance(s, jax.Array) else s.shape)
        return Tensor(m + s * jax.random.normal(rnd.next_key(), shp,
                                                dtype=jnp.float32))
    return Tensor(mean + std * jax.random.normal(
        rnd.next_key(), _shape_list(shape if shape is not None else []),
        dtype=dtype_mod.get_default_dtype().np_dtype))


def uniform(shape, dtype=None, min=-1.0, max=1.0, seed=0, name=None):
    key = rnd.next_key() if seed == 0 else jax.random.key(seed)
    return Tensor(jax.random.uniform(key, _shape_list(shape),
                                     dtype=_fdt(dtype), minval=min,
                                     maxval=max))


def randint(low=0, high=None, shape=(1,), dtype=None, name=None):
    if high is None:
        low, high = 0, low
    dt = to_dtype(dtype).np_dtype if dtype is not None else np.int64
    return Tensor(jax.random.randint(rnd.next_key(), _shape_list(shape),
                                     low, high, dtype=dt))


def randint_like(x, low=0, high=None, dtype=None, name=None):
    return randint(low, high, x.shape,
                   dtype or x.dtype)


def randperm(n, dtype="int64", name=None):
    return Tensor(jax.random.permutation(rnd.next_key(), int(n)).astype(
        to_dtype(dtype).np_dtype))


def multinomial(x, num_samples=1, replacement=False, name=None):
    key = rnd.next_key()

    def f(a):
        logits = jnp.log(jnp.maximum(a, 1e-30))
        if replacement:
            return jax.random.categorical(
                key, logits, shape=a.shape[:-1] + (num_samples,),
                axis=-1).astype(jnp.int64)
        # without replacement: Gumbel top-k trick
        g = jax.random.gumbel(key, a.shape, dtype=jnp.float32)
        _, idx = jax.lax.top_k(logits + g, num_samples)
        return idx.astype(jnp.int64)
    return Tensor(f(_unwrap(x)))


def bernoulli(x, name=None):
    key = rnd.next_key()
    return Tensor(jax.random.bernoulli(key, _unwrap(x)).astype(
        _unwrap(x).dtype))


def poisson(x, name=None):
    key = rnd.next_key()
    a = _unwrap(x)
    return Tensor(jax.random.poisson(key, a).astype(a.dtype))


def exponential_(x, lam=1.0, name=None):
    key = rnd.next_key()
    new = jax.random.exponential(key, tuple(x.shape),
                                 dtype=x._data.dtype) / lam
    x._data = new
    x.grad_node = None
    return x


def uniform_(x, min=-1.0, max=1.0, seed=0, name=None):
    key = rnd.next_key() if seed == 0 else jax.random.key(seed)
    x._data = jax.random.uniform(key, tuple(x.shape), dtype=x._data.dtype,
                                 minval=min, maxval=max)
    x.grad_node = None
    return x


def normal_(x, mean=0.0, std=1.0, name=None):
    x._data = mean + std * jax.random.normal(rnd.next_key(), tuple(x.shape),
                                             dtype=x._data.dtype)
    x.grad_node = None
    return x


def shuffle(x, name=None):
    key = rnd.next_key()
    return Tensor(jax.random.permutation(key, _unwrap(x), axis=0))


import sys

_this = sys.modules[__name__]
for _name in __all__:
    _fn = getattr(_this, _name, None)
    if callable(_fn) and not hasattr(Tensor, _name):
        Tensor._bind(_name, _fn)
del _this, _name, _fn
