"""Long-tail op-surface parity: the remaining paddle top-level APIs.

Reference: scattered across python/paddle/tensor/{math,manipulation,
stat,search,creation}.py — each here is a thin jax.numpy / jax.scipy
composition through apply_op (kernels, fusion, and gradients come from
XLA). The in-place ``op_`` variants are generated at the bottom from
their out-of-place bases (paddle's inplace ops rebind the tensor's
buffer; the façade's ``_inplace`` preserves handle identity).
"""
from __future__ import annotations

import math as _math
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import jax.scipy.special as jsp
import numpy as np

from ..framework.tensor import Tensor, apply_op

__all__ = [
    # math
    "logaddexp", "sinc", "signbit", "isneginf", "isposinf", "isreal",
    "copysign", "hypot", "nextafter", "ldexp", "frexp", "i0", "i0e",
    "i1", "i1e", "polygamma", "gammaln", "gammainc", "gammaincc",
    "multigammaln", "sgn", "floor_mod",
    # stats / reductions
    "quantile", "nanquantile", "mode", "kthvalue",
    "histogram_bin_edges", "histogramdd", "reduce_as", "trapezoid",
    "cumulative_trapezoid", "cdist", "pdist",
    # manipulation
    "block_diag", "diag_embed", "unstack", "cartesian_prod",
    "combinations", "slice_scatter", "diagonal_scatter",
    "masked_scatter", "index_fill", "index_sample", "scatter_nd",
    "dstack", "column_stack", "row_stack", "reverse", "unflatten",
    "as_strided", "unfold", "vander", "polar", "complex",
    "tril_indices", "triu_indices", "multiplex", "isin", "renorm",
    "broadcast_shape", "shape", "rank",
    # random
    "binomial", "standard_gamma", "log_normal",
    # dtype / predicates
    "iinfo", "finfo", "is_floating_point", "is_complex", "is_integer",
    # misc API
    "set_printoptions", "LazyGuard", "summary", "flops",
    "get_cuda_rng_state", "set_cuda_rng_state", "log_normal_",
    "cauchy_", "geometric_", "check_shape", "batch",
]


def _u(fn, name, *xs, **kw):
    return apply_op(fn, *xs, _op_name=name, **kw)


# ---------------------------------------------------------------------------
# math
# ---------------------------------------------------------------------------

def logaddexp(x, y, name=None):
    return _u(jnp.logaddexp, "logaddexp", x, y)


def sinc(x, name=None):
    return _u(jnp.sinc, "sinc", x)


def signbit(x, name=None):
    return _u(jnp.signbit, "signbit", x)


def isneginf(x, name=None):
    return _u(jnp.isneginf, "isneginf", x)


def isposinf(x, name=None):
    return _u(jnp.isposinf, "isposinf", x)


def isreal(x, name=None):
    return _u(jnp.isreal, "isreal", x)


def copysign(x, y, name=None):
    if not isinstance(y, Tensor):
        y = Tensor(jnp.asarray(y))
    return _u(jnp.copysign, "copysign", x, y)


def hypot(x, y, name=None):
    return _u(jnp.hypot, "hypot", x, y)


def nextafter(x, y, name=None):
    return _u(jnp.nextafter, "nextafter", x, y)


def ldexp(x, y, name=None):
    return _u(lambda a, b: jnp.ldexp(a, b.astype(jnp.int32)), "ldexp",
              x, y)


def frexp(x, name=None):
    return _u(lambda a: jnp.frexp(a), "frexp", x)


def i0(x, name=None):
    return _u(jsp.i0, "i0", x)


def i0e(x, name=None):
    return _u(jsp.i0e, "i0e", x)


def i1(x, name=None):
    return _u(jsp.i1, "i1", x)


def i1e(x, name=None):
    return _u(jsp.i1e, "i1e", x)


def polygamma(x, n, name=None):
    return _u(lambda a: jsp.polygamma(int(n), a), "polygamma", x)


def gammaln(x, name=None):
    return _u(jsp.gammaln, "gammaln", x)


def gammainc(x, y, name=None):
    return _u(jsp.gammainc, "gammainc", x, y)


def gammaincc(x, y, name=None):
    return _u(jsp.gammaincc, "gammaincc", x, y)


def multigammaln(x, p, name=None):
    return _u(lambda a: jsp.multigammaln(a, int(p)), "multigammaln", x)


def sgn(x, name=None):
    def f(a):
        if jnp.issubdtype(a.dtype, jnp.complexfloating):
            mag = jnp.abs(a)
            return jnp.where(mag == 0, 0, a / jnp.where(mag == 0, 1, mag))
        return jnp.sign(a)
    return _u(f, "sgn", x)


def floor_mod(x, y, name=None):
    from .math import mod
    return mod(x, y)


# ---------------------------------------------------------------------------
# stats / reductions
# ---------------------------------------------------------------------------

def quantile(x, q, axis=None, keepdim=False, interpolation="linear",
             name=None):
    return _u(lambda a: jnp.quantile(a, jnp.asarray(q), axis=axis,
                                     keepdims=keepdim,
                                     method=interpolation),
              "quantile", x)


def nanquantile(x, q, axis=None, keepdim=False, interpolation="linear",
                name=None):
    return _u(lambda a: jnp.nanquantile(a, jnp.asarray(q), axis=axis,
                                        keepdims=keepdim,
                                        method=interpolation),
              "nanquantile", x)


def mode(x, axis=-1, keepdim=False, name=None):
    """Most frequent value (ties -> largest, paddle contract). O(n log n):
    sort, then per-element run length from cummax/cummin of run
    boundaries — no n*n comparison matrix."""
    def f(a):
        s = jnp.sort(a, axis=axis)
        n = a.shape[axis]
        sm = jnp.moveaxis(s, axis, -1)
        p = jnp.broadcast_to(jnp.arange(n), sm.shape)
        neq = sm[..., 1:] != sm[..., :-1]
        run_start = jnp.concatenate(
            [jnp.ones_like(sm[..., :1], bool), neq], axis=-1)
        run_end = jnp.concatenate(
            [neq, jnp.ones_like(sm[..., :1], bool)], axis=-1)
        # start/end position of the run each element belongs to
        last = sm.ndim - 1  # lax.cummax/cummin reject negative axes
        s_pos = jax.lax.cummax(jnp.where(run_start, p, 0), axis=last)
        e_pos = jnp.flip(jax.lax.cummin(
            jnp.flip(jnp.where(run_end, p, n - 1), -1), axis=last), -1)
        length = e_pos - s_pos + 1
        # last max run = largest value on count ties (ascending sort)
        best = (n - 1) - jnp.argmax(jnp.flip(length, -1), axis=-1)
        vals = jnp.take_along_axis(sm, best[..., None], axis=-1)[..., 0]
        return vals if not keepdim else jnp.expand_dims(vals, axis)
    values = _u(f, "mode", x)
    # indices: first occurrence of the value in the ORIGINAL tensor
    def g(a, v):
        vv = jnp.expand_dims(v, axis) if not keepdim else v
        eq = a == vv
        am = jnp.moveaxis(eq, axis, -1)
        idx = jnp.argmax(am, axis=-1)
        return idx if not keepdim else jnp.expand_dims(idx, axis)
    indices = _u(g, "mode_idx", x, values)
    return values, indices


def kthvalue(x, k, axis=-1, keepdim=False, name=None):
    def f(a):
        s = jnp.sort(a, axis=axis)
        i = jnp.argsort(a, axis=axis)
        vals = jnp.take(s, k - 1, axis=axis)
        idx = jnp.take(i, k - 1, axis=axis)
        if keepdim:
            vals = jnp.expand_dims(vals, axis)
            idx = jnp.expand_dims(idx, axis)
        return vals, idx
    return _u(f, "kthvalue", x)


def histogram_bin_edges(x, bins=100, min=0, max=0, name=None):
    def f(a):
        lo, hi = (jnp.min(a), jnp.max(a)) if min == 0 and max == 0 \
            else (min, max)
        return jnp.linspace(lo, hi, bins + 1)
    return _u(f, "histogram_bin_edges", x)


def histogramdd(x, bins=10, ranges=None, density=False, weights=None,
                name=None):
    arrs = np.asarray(x.numpy() if isinstance(x, Tensor) else x)
    w = np.asarray(weights.numpy()) if isinstance(weights, Tensor) \
        else weights
    h, edges = np.histogramdd(arrs, bins=bins, range=ranges,
                              density=density, weights=w)
    return Tensor(h), [Tensor(e) for e in edges]


def reduce_as(x, target, name=None):
    """Sum-reduce x to target's shape (the broadcast inverse)."""
    def f(a, t):
        extra = a.ndim - t.ndim
        out = jnp.sum(a, axis=tuple(range(extra))) if extra else a
        axes = tuple(i for i, (s, d) in
                     enumerate(zip(t.shape, out.shape)) if s == 1 != d)
        if axes:
            out = jnp.sum(out, axis=axes, keepdims=True)
        return out.reshape(t.shape)
    return _u(f, "reduce_as", x, target)


def trapezoid(y, x=None, dx=None, axis=-1, name=None):
    if x is not None:
        return _u(lambda a, b: jnp.trapezoid(a, x=b, axis=axis),
                  "trapezoid", y, x)
    return _u(lambda a: jnp.trapezoid(a, dx=dx or 1.0, axis=axis),
              "trapezoid", y)


def cumulative_trapezoid(y, x=None, dx=None, axis=-1, name=None):
    import jax.scipy.integrate as jsi
    if hasattr(jsi, "cumulative_trapezoid"):
        base = jsi.cumulative_trapezoid
    else:
        def base(a, x=None, dx=1.0, axis=-1):
            am = jnp.moveaxis(a, axis, -1)
            if x is not None:
                xm = jnp.moveaxis(jnp.broadcast_to(x, a.shape), axis, -1)
                d = xm[..., 1:] - xm[..., :-1]
            else:
                d = dx
            avg = (am[..., 1:] + am[..., :-1]) / 2.0
            return jnp.moveaxis(jnp.cumsum(avg * d, axis=-1), -1, axis)
    if x is not None:
        return _u(lambda a, b: base(a, x=b, axis=axis),
                  "cumulative_trapezoid", y, x)
    return _u(lambda a: base(a, dx=dx or 1.0, axis=axis),
              "cumulative_trapezoid", y)


def cdist(x, y, p=2.0, compute_mode=None, name=None):
    def f(a, b):
        d = a[..., :, None, :] - b[..., None, :, :]
        if p == 2.0:
            return jnp.sqrt(jnp.sum(d * d, axis=-1) + 1e-30)
        return jnp.sum(jnp.abs(d) ** p, axis=-1) ** (1.0 / p)
    return _u(f, "cdist", x, y)


def pdist(x, p=2.0, name=None):
    def f(a):
        n = a.shape[0]
        d = a[:, None, :] - a[None, :, :]
        if p == 2.0:
            m = jnp.sqrt(jnp.sum(d * d, axis=-1) + 1e-30)
        else:
            m = jnp.sum(jnp.abs(d) ** p, axis=-1) ** (1.0 / p)
        iu = jnp.triu_indices(n, k=1)
        return m[iu]
    return _u(f, "pdist", x)


# ---------------------------------------------------------------------------
# manipulation
# ---------------------------------------------------------------------------

def block_diag(inputs, name=None):
    def f(*arrs):
        arrs = [jnp.atleast_2d(a) for a in arrs]
        rows = sum(a.shape[0] for a in arrs)
        cols = sum(a.shape[1] for a in arrs)
        out = jnp.zeros((rows, cols), arrs[0].dtype)
        r = c = 0
        for a in arrs:
            out = jax.lax.dynamic_update_slice(out, a.astype(out.dtype),
                                               (r, c))
            r += a.shape[0]
            c += a.shape[1]
        return out
    return _u(f, "block_diag", *inputs)


def diag_embed(x, offset=0, dim1=-2, dim2=-1, name=None):
    def f(a):
        n = a.shape[-1] + abs(offset)
        base = jnp.zeros(a.shape[:-1] + (n, n), a.dtype)
        idx = jnp.arange(a.shape[-1])
        r = idx + max(-offset, 0)
        c = idx + max(offset, 0)
        out = base.at[..., r, c].set(a)
        nd = out.ndim
        d1 = dim1 % nd
        d2 = dim2 % nd
        perm = [i for i in range(nd) if i not in (nd - 2, nd - 1)]
        # place the two new axes at dim1/dim2
        order = []
        src = iter(perm)
        for i in range(nd):
            if i == d1:
                order.append(nd - 2)
            elif i == d2:
                order.append(nd - 1)
            else:
                order.append(next(src))
        return jnp.transpose(out, order)
    return _u(f, "diag_embed", x)


def unstack(x, axis=0, num=None, name=None):
    n = num or x.shape[axis]
    return _u(lambda a: tuple(jnp.moveaxis(a, axis, 0)[i]
                              for i in range(n)), "unstack", x)


def cartesian_prod(inputs, name=None):
    def f(*arrs):
        grids = jnp.meshgrid(*arrs, indexing="ij")
        return jnp.stack([g.reshape(-1) for g in grids], axis=-1)
    out = _u(f, "cartesian_prod", *inputs)
    return out


def combinations(x, r=2, with_replacement=False, name=None):
    from itertools import combinations as comb, combinations_with_replacement
    n = x.shape[0]
    gen = combinations_with_replacement(range(n), r) if with_replacement \
        else comb(range(n), r)
    idx = np.asarray(list(gen), np.int32).reshape(-1, r)
    return _u(lambda a: a[jnp.asarray(idx)], "combinations", x)


def slice_scatter(x, value, axes, starts, ends, strides, name=None):
    def f(a, v):
        idx = [slice(None)] * a.ndim
        for ax, st, en, sd in zip(axes, starts, ends, strides):
            idx[ax] = slice(st, en, sd)
        return a.at[tuple(idx)].set(v.astype(a.dtype))
    return _u(f, "slice_scatter", x, value)


def diagonal_scatter(x, y, offset=0, axis1=0, axis2=1, name=None):
    def f(a, v):
        n = min(a.shape[axis1], a.shape[axis2]) - abs(offset)
        idx = jnp.arange(n)
        r = idx + max(-offset, 0)
        c = idx + max(offset, 0)
        am = jnp.moveaxis(a, (axis1, axis2), (0, 1))
        am = am.at[r, c].set(jnp.moveaxis(v.astype(a.dtype), -1, 0)
                             if v.ndim > 1 else v.astype(a.dtype))
        return jnp.moveaxis(am, (0, 1), (axis1, axis2))
    return _u(f, "diagonal_scatter", x, y)


def masked_scatter(x, mask, value, name=None):
    """Fill masked positions with consecutive values (paddle contract:
    value is consumed in row-major order)."""
    def f(a, m, v):
        vf = v.reshape(-1)
        pos = jnp.cumsum(m.reshape(-1)) - 1
        take = vf[jnp.clip(pos, 0, vf.shape[0] - 1)].reshape(a.shape)
        return jnp.where(m, take.astype(a.dtype), a)
    return _u(f, "masked_scatter", x, mask, value)


def index_fill(x, index, axis, value, name=None):
    def f(a, i):
        am = jnp.moveaxis(a, axis, 0)
        am = am.at[i].set(jnp.asarray(value, a.dtype))
        return jnp.moveaxis(am, 0, axis)
    return _u(f, "index_fill", x, index)


def index_sample(x, index, name=None):
    return _u(lambda a, i: jnp.take_along_axis(a, i, axis=1),
              "index_sample", x, index)


def scatter_nd(index, updates, shape, name=None):
    def f(i, u):
        out = jnp.zeros(tuple(int(s) for s in shape), u.dtype)
        return out.at[tuple(jnp.moveaxis(i, -1, 0))].add(u)
    return _u(f, "scatter_nd", index, updates)


def dstack(inputs, name=None):
    return _u(lambda *a: jnp.dstack(a), "dstack", *inputs)


def column_stack(inputs, name=None):
    return _u(lambda *a: jnp.column_stack(a), "column_stack", *inputs)


def row_stack(inputs, name=None):
    return _u(lambda *a: jnp.vstack(a), "row_stack", *inputs)


def reverse(x, axis, name=None):
    ax = tuple(axis) if isinstance(axis, (list, tuple)) else (axis,)
    return _u(lambda a: jnp.flip(a, axis=ax), "reverse", x)


def unflatten(x, axis, shape, name=None):
    def f(a):
        new = list(a.shape[:axis]) + list(shape) + \
            list(a.shape[axis + 1:] if axis != -1 else [])
        if axis == -1:
            new = list(a.shape[:-1]) + list(shape)
        return a.reshape(new)
    return _u(f, "unflatten", x)


def as_strided(x, shape, stride, offset=0, name=None):
    """View with explicit strides (element units), via flat gather."""
    def f(a):
        flat = a.reshape(-1)
        idx = jnp.full((), offset, jnp.int32)
        grid = jnp.zeros(tuple(shape), jnp.int32) + idx
        for d, (s, st) in enumerate(zip(shape, stride)):
            r = jnp.arange(s, dtype=jnp.int32) * st
            r = r.reshape((1,) * d + (s,) + (1,) * (len(shape) - d - 1))
            grid = grid + r
        return flat[grid]
    return _u(f, "as_strided", x)


def unfold(x, axis, size, step, name=None):
    """Sliding windows along ``axis`` (tensor.unfold contract)."""
    def f(a):
        n = (a.shape[axis] - size) // step + 1
        am = jnp.moveaxis(a, axis, -1)
        starts = jnp.arange(n) * step
        win = jnp.arange(size)
        idx = starts[:, None] + win[None, :]
        out = am[..., idx]  # [..., n, size]
        return jnp.moveaxis(out, -2, axis)
    return _u(f, "unfold", x)


def vander(x, n=None, increasing=False, name=None):
    return _u(lambda a: jnp.vander(a, N=n, increasing=increasing),
              "vander", x)


def polar(abs_t, angle, name=None):
    # lax.complex keeps f32->c64 / f64->c128 (no silent downcast)
    return _u(lambda r, t: jax.lax.complex(r * jnp.cos(t),
                                           r * jnp.sin(t)),
              "polar", abs_t, angle)


def complex(real, imag, name=None):
    return _u(lambda r, i: jax.lax.complex(r, i), "complex", real, imag)


def tril_indices(row, col=None, offset=0, dtype="int64"):
    r, c = np.tril_indices(row, k=offset, m=col or row)
    return Tensor(np.stack([r, c]).astype(np.int64))


def triu_indices(row, col=None, offset=0, dtype="int64"):
    r, c = np.triu_indices(row, k=offset, m=col or row)
    return Tensor(np.stack([r, c]).astype(np.int64))


def multiplex(inputs, index, name=None):
    def f(i, *arrs):
        stacked = jnp.stack(arrs)  # [K, B, ...]
        sel = i.reshape(-1).astype(jnp.int32)
        return stacked[sel, jnp.arange(stacked.shape[1])]
    return _u(f, "multiplex", index, *inputs)


def isin(x, test_x, assume_unique=False, invert=False, name=None):
    return _u(lambda a, t: jnp.isin(a, t, invert=invert), "isin", x,
              test_x)


def renorm(x, p, axis, max_norm, name=None):
    def f(a):
        am = jnp.moveaxis(a, axis, 0)
        flat = am.reshape(am.shape[0], -1)
        norms = jnp.sum(jnp.abs(flat) ** p, axis=1) ** (1.0 / p)
        scale = jnp.where(norms > max_norm,
                          max_norm / (norms + 1e-12), 1.0)
        out = flat * scale[:, None]
        return jnp.moveaxis(out.reshape(am.shape), 0, axis)
    return _u(f, "renorm", x)


def broadcast_shape(x_shape, y_shape):
    return list(np.broadcast_shapes(tuple(x_shape), tuple(y_shape)))


def shape(x):
    """Runtime shape as a 1-D int32 Tensor (paddle.shape contract)."""
    return _u(lambda a: jnp.asarray(a.shape, jnp.int32), "shape", x)


def rank(x):
    return Tensor(np.asarray(x.ndim, np.int32))


# ---------------------------------------------------------------------------
# random
# ---------------------------------------------------------------------------

def binomial(count, prob, name=None):
    from ..framework import random as rnd
    key = rnd.op_key(count, prob)
    return _u(lambda n, p, k: jax.random.binomial(
        k, n.astype(jnp.float32), p).astype(jnp.int64),
        "binomial", count, prob, key)


def standard_gamma(x, name=None):
    from ..framework import random as rnd
    key = rnd.op_key(x)
    return _u(lambda a, k: jax.random.gamma(k, a), "standard_gamma", x,
              key)


def log_normal(mean=1.0, std=2.0, shape=None, dtype="float32", name=None):
    from ..framework import random as rnd
    from ..framework.dtype import to_dtype
    key = rnd.next_key()
    arr = jnp.exp(mean + std * jax.random.normal(
        key, tuple(shape or []), jnp.float32))
    return Tensor(arr.astype(to_dtype(dtype).np_dtype))


def log_normal_(x, mean=1.0, std=2.0, name=None):
    """In-place fill with LogNormal(mean, std) samples."""
    from ..framework import random as rnd
    key = rnd.op_key(x)
    return x._inplace(_u(
        lambda a, k: jnp.exp(mean + std * jax.random.normal(
            k, a.shape, jnp.float32)).astype(a.dtype),
        "log_normal_", x._snapshot(), key))


def cauchy_(x, loc=0.0, scale=1.0, name=None):
    """In-place fill with Cauchy(loc, scale) samples."""
    from ..framework import random as rnd
    key = rnd.op_key(x)
    return x._inplace(_u(
        lambda a, k: (loc + scale * jax.random.cauchy(
            k, a.shape, jnp.float32)).astype(a.dtype),
        "cauchy_", x._snapshot(), key))


def geometric_(x, probs, name=None):
    """In-place fill with Geometric(probs) samples (number of trials)."""
    from ..framework import random as rnd
    key = rnd.op_key(x)
    return x._inplace(_u(
        lambda a, k: jax.random.geometric(
            k, a.shape, p=probs).astype(a.dtype),
        "geometric_", x._snapshot(), key))


def check_shape(x, expected_shape):
    """Assert a tensor's static shape (paddle.static check helper):
    -1/None entries match any size."""
    actual = list(x.shape)
    if len(actual) != len(expected_shape) or any(
            e not in (-1, None) and e != a
            for e, a in zip(expected_shape, actual)):
        raise ValueError(f"shape mismatch: expected {expected_shape}, "
                         f"got {actual}")
    return True


def batch(reader, batch_size, drop_last=False):
    """Deprecated reader decorator kept for API compat
    (python/paddle/reader) — batches an iterable-returning reader."""
    def batched():
        buf = []
        for item in reader():
            buf.append(item)
            if len(buf) == batch_size:
                yield buf
                buf = []
        if buf and not drop_last:
            yield buf
    return batched


# ---------------------------------------------------------------------------
# dtype / predicates / misc
# ---------------------------------------------------------------------------

class iinfo:
    def __init__(self, dtype):
        from ..framework.dtype import to_dtype
        info = np.iinfo(to_dtype(dtype).np_dtype)
        self.min = int(info.min)
        self.max = int(info.max)
        self.bits = info.bits
        self.dtype = str(info.dtype)


class finfo:
    def __init__(self, dtype):
        from ..framework.dtype import to_dtype
        import ml_dtypes
        info = ml_dtypes.finfo(to_dtype(dtype).np_dtype)
        self.min = float(info.min)
        self.max = float(info.max)
        self.eps = float(info.eps)
        self.tiny = float(info.tiny)
        self.smallest_normal = float(info.smallest_normal)
        self.resolution = float(info.resolution)
        self.bits = info.bits
        self.dtype = str(info.dtype)


def is_floating_point(x) -> bool:
    return jnp.issubdtype(np.dtype(x._data.dtype), jnp.floating)


def is_complex(x) -> bool:
    return jnp.issubdtype(np.dtype(x._data.dtype), jnp.complexfloating)


def is_integer(x) -> bool:
    return jnp.issubdtype(np.dtype(x._data.dtype), jnp.integer)


def set_printoptions(precision=None, threshold=None, edgeitems=None,
                     sci_mode=None, linewidth=None):
    kw = {}
    if precision is not None:
        kw["precision"] = precision
    if threshold is not None:
        kw["threshold"] = threshold
    if edgeitems is not None:
        kw["edgeitems"] = edgeitems
    if linewidth is not None:
        kw["linewidth"] = linewidth
    if sci_mode is not None:
        kw["suppress"] = not sci_mode
    np.set_printoptions(**kw)


class LazyGuard:
    """paddle.LazyGuard compat: the reference defers parameter
    materialization; here initialization is cheap (host numpy), so the
    guard is a documented no-op context."""

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


def summary(net, input_size=None, dtypes=None, input=None):
    """Model summary (hapi.summary): walks sublayers, counts params."""
    rows = []
    total = trainable = 0
    for name, sub in net.named_sublayers():
        n_params = sum(int(np.prod(p.shape))
                       for p in sub._parameters.values() if p is not None)
        if n_params or not list(sub.children()):
            rows.append((name or sub.__class__.__name__,
                         sub.__class__.__name__, n_params))
    for p in net.parameters():
        n = int(np.prod(p.shape))
        total += n
        if not p.stop_gradient:
            trainable += n
    lines = [f"{'Layer':40s} {'Type':24s} {'Params':>12s}"]
    lines += [f"{n[:40]:40s} {t[:24]:24s} {c:>12,d}" for n, t, c in rows]
    lines.append(f"Total params: {total:,d}")
    lines.append(f"Trainable params: {trainable:,d}")
    out = "\n".join(lines)
    print(out)
    return {"total_params": total, "trainable_params": trainable}


def flops(net, input_size, custom_ops=None, print_detail=False):
    """Rough FLOPs estimate: 2*numel per linear/conv weight application
    scaled by output spatial size (paddle.flops analog, coarse)."""
    from ..nn.layer.conv import _ConvNd
    from ..nn.layer.common import Linear
    batch = input_size[0] if input_size else 1
    total = 0
    spatial = int(np.prod(input_size[2:])) if input_size and \
        len(input_size) > 2 else 1
    for _, sub in net.named_sublayers(include_self=True):
        if isinstance(sub, Linear):
            total += 2 * int(np.prod(sub.weight.shape)) * batch
        elif isinstance(sub, _ConvNd):
            total += 2 * int(np.prod(sub.weight.shape)) * batch * spatial
    if print_detail:
        print(f"FLOPs (approx): {total:,d}")
    return total


def get_cuda_rng_state():
    from ..framework.random import get_rng_state
    return get_rng_state()


def set_cuda_rng_state(state):
    from ..framework.random import set_rng_state
    return set_rng_state(state)


# ---------------------------------------------------------------------------
# generated in-place variants (paddle `op_` contract: same computation,
# the input tensor's buffer is rebound; returns the input handle)
# ---------------------------------------------------------------------------

_INPLACE_BASES = [
    "abs", "acos", "acosh", "addmm", "asin", "asinh", "atan", "atanh",
    "bernoulli", "ceil", "clip", "cosh", "erfinv", "exp", "floor",
    "lerp", "log1p", "logical_xor", "not_equal", "put_along_axis",
    "reciprocal", "round", "rsqrt", "sigmoid", "sqrt",
    "bitwise_and",
    "bitwise_left_shift", "bitwise_not", "bitwise_or",
    "bitwise_right_shift", "bitwise_xor", "cast", "copysign", "cos",
    "cumprod", "cumsum", "digamma", "divide", "equal", "erf", "expm1",
    "floor_divide", "floor_mod", "frac", "gammainc", "gammaincc",
    "gammaln", "gcd", "greater_equal", "greater_than", "hypot", "i0",
    "index_add", "index_fill", "index_put", "lcm", "ldexp", "less_equal", "less_than",
    "lgamma", "log", "log10", "log2", "logical_and", "logical_not",
    "logical_or", "logit", "masked_fill", "masked_scatter", "mod",
    "multigammaln", "nan_to_num", "neg", "polygamma", "pow", "remainder",
    "renorm", "scatter", "sin", "sinc", "sinh", "square", "t", "tan",
    "tanh", "transpose", "tril", "triu", "trunc", "where",
]


def _make_inplace(base_name, base_fn):
    def fn(x, *args, **kwargs):
        # the op must reference a SNAPSHOT of x, not x itself: _inplace
        # rebinds x to the new grad node, and a node whose input is x
        # would self-cycle and silently drop upstream gradients
        return x._inplace(base_fn(x._snapshot(), *args, **kwargs))
    fn.__name__ = base_name + "_"
    fn.__doc__ = f"In-place variant of ``{base_name}`` (rebinds the " \
                 f"tensor's buffer; returns the same handle)."
    return fn


def _install_inplace():
    import sys
    from . import math as _m
    from . import manipulation as _mp
    from . import linalg as _lin
    from . import logic as _lg
    from . import creation as _cr
    from . import random_ops as _ro
    here = sys.modules[__name__]
    sources = [here, _m, _mp, _lin, _lg, _cr, _ro]
    for base in _INPLACE_BASES:
        fn = None
        for mod in sources:
            fn = getattr(mod, base, None)
            if fn is not None:
                break
        if fn is None:
            continue
        name = base + "_"
        wrapper = _make_inplace(base, fn)
        setattr(here, name, wrapper)
        __all__.append(name)
        Tensor._bind(name, wrapper)


_install_inplace()

# bind the out-of-place extras as Tensor methods where paddle has them
for _m_name in ["logaddexp", "sinc", "signbit", "isneginf", "isposinf",
                "isreal", "copysign", "hypot", "nextafter", "ldexp",
                "frexp", "i0", "i0e", "i1", "i1e", "polygamma",
                "gammaln", "gammainc", "gammaincc", "multigammaln",
                "sgn", "floor_mod", "quantile", "nanquantile", "mode",
                "kthvalue", "cdist", "diag_embed", "unstack",
                "slice_scatter", "diagonal_scatter", "masked_scatter",
                "index_fill", "index_sample", "reverse", "unflatten",
                "as_strided", "unfold", "vander", "isin", "renorm",
                "is_floating_point", "is_complex", "is_integer",
                "reduce_as", "trapezoid", "cumulative_trapezoid",
                "log_normal_", "cauchy_", "geometric_"]:
    Tensor._bind(_m_name, globals()[_m_name])


# ---------------------------------------------------------------------------
# remaining Tensor-method parity (tensor/__init__.py method list)
# ---------------------------------------------------------------------------

def inverse(x, name=None):
    return _u(jnp.linalg.inv, "inverse", x)


def create_tensor(dtype="float32", name=None, persistable=False):
    from ..framework.dtype import to_dtype
    t = Tensor(jnp.zeros((), to_dtype(dtype).np_dtype), name=name)
    t.persistable = persistable
    return t


def top_p_sampling(x, ps, threshold=None, topp_seed=None, seed=-1,
                   k=0, mode="truncated", return_top=False, name=None):
    """Nucleus sampling (tensor/search.py top_p_sampling): keep the
    smallest prefix of descending probs whose mass reaches ps, renorm,
    sample. Returns (sampled_probs, sampled_ids)."""
    from ..framework import random as rnd
    key = rnd.op_key(x, ps)

    def f(probs, p_thresh, kk):
        order = jnp.argsort(-probs, axis=-1)
        sorted_p = jnp.take_along_axis(probs, order, axis=-1)
        csum = jnp.cumsum(sorted_p, axis=-1)
        keep = csum - sorted_p < p_thresh[..., None]  # keep first >= ps
        keep = keep.at[..., 0].set(True)
        filt = jnp.where(keep, sorted_p, 0.0)
        filt = filt / jnp.sum(filt, axis=-1, keepdims=True)
        g = jax.random.gumbel(kk, filt.shape)
        choice = jnp.argmax(jnp.log(filt + 1e-30) + g, axis=-1)
        ids = jnp.take_along_axis(order, choice[..., None],
                                  axis=-1)
        pvals = jnp.take_along_axis(probs, ids, axis=-1)
        return pvals, ids.astype(jnp.int64)
    return _u(f, "top_p_sampling", x, ps, key)


def _bind_method_parity():
    """Bind remaining functions the reference exposes as Tensor methods
    (python/paddle/tensor/__init__.py tensor_method_func)."""
    import sys
    from . import creation as _cr
    from . import linalg as _lin
    from . import manipulation as _mp
    from . import math as _m
    here = sys.modules[__name__]

    def _stft(self, *a, **k):
        from .. import signal as _sig
        return _sig.stft(self, *a, **k)

    def _istft(self, *a, **k):
        from .. import signal as _sig
        return _sig.istft(self, *a, **k)

    Tensor._bind("stft", _stft)
    Tensor._bind("istft", _istft)
    for name in ["diag", "diagflat", "tril", "triu", "multiplex",
                 "scatter_nd", "histogram_bin_edges", "histogramdd",
                 "polar", "rank", "broadcast_shape", "block_diag",
                 "inverse", "top_p_sampling", "create_tensor",
                 "create_parameter"]:
        fn = None
        for mod in (here, _m, _mp, _lin, _cr):
            fn = getattr(mod, name, None)
            if fn is not None:
                break
        if fn is None and name == "create_parameter":
            from ..static.graph import create_parameter as fn  # noqa
        if fn is not None:
            Tensor._bind(name, fn)
    from ..nn.functional.activation import sigmoid as _sigmoid
    Tensor._bind("sigmoid", _sigmoid)
    Tensor._bind("sigmoid_", _make_inplace("sigmoid", _sigmoid))


_bind_method_parity()
__all__ += ["inverse", "create_tensor", "top_p_sampling"]
