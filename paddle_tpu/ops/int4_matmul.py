"""Pallas int4 weight-only matmul: unpack + dequant fused into the dot.

The XLA lowering of unpack->dequant->matmul materializes the bf16
weight copy in HBM every call, which DESTROYS the bandwidth win decode
exists for (measured 62 tok/s bs1 vs 329 bf16 — benchmarks/RESULTS.md
round-5 int4 ledger). This kernel reads the PACKED uint8 nibbles
[K/2, N] straight from HBM, unpacks and scales in VMEM registers, and
feeds the MXU — HBM cost stays 0.5 B/weight.

Packing layout (pack_rows_int4): nibble pair (hi, lo) holds original
rows (k, k + K/2), so the kernel needs NO interleave — it computes
``y = x[:, :K/2] @ W_hi + x[:, K/2:] @ W_lo`` (two dots, one
accumulator). Per-group scales (group size divides K/2) broadcast to
rows in-register.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

__all__ = ["pack_rows_int4", "quantize_int4_rows", "int4_matmul"]


def quantize_int4_rows(w: np.ndarray, group: int = 128):
    """[K, N] float -> (q int8-valued [-7,7] [K, N],
    scales f32 [K//group, N]), symmetric per (group, out-column)."""
    K, N = w.shape
    if K % group:
        raise ValueError(f"K {K} % group {group} != 0")
    g = K // group
    wg = w.reshape(g, group, N).astype(np.float32)
    scale = np.abs(wg).max(axis=1) / 7.0
    scale = np.where(scale == 0.0, 1.0, scale)
    q = np.clip(np.round(wg / scale[:, None, :]), -7, 7)
    return q.reshape(K, N).astype(np.int8), scale.astype(np.float32)


def pack_rows_int4(q: np.ndarray) -> np.ndarray:
    """[K, N] int4-valued -> uint8 [K/2, N]: row k in the HIGH nibble,
    row k + K/2 in the LOW nibble (halves layout — the kernel's two
    half-dots need no interleave)."""
    K = q.shape[0]
    if K % 2:
        raise ValueError("K must be even")
    u = (q.astype(np.int16) + 8).astype(np.uint8)
    return ((u[:K // 2] << 4) | u[K // 2:]).astype(np.uint8)


def _kernel(x_ref, p_ref, s_ref, o_ref, *, group, out_dtype, cdtype):
    # x [Bb, K]; p [K/2, Nb] packed; s [G, Nb]; o [Bb, Nb]
    Bb, K = x_ref.shape
    half = K // 2
    Nb = p_ref.shape[1]
    # Mosaic cannot legalize shifts on i8 vectors (arith.shrui) —
    # widen to i32 for the nibble arithmetic, it stays in registers
    p = p_ref[...].astype(jnp.int32)
    hi = ((p >> 4) - 8).astype(cdtype)           # rows 0..K/2
    lo = ((p & 0xF) - 8).astype(cdtype)          # rows K/2..K
    s = s_ref[...].astype(jnp.float32)           # [G, Nb]
    x = x_ref[...].astype(cdtype)
    gh = half // group                           # groups per half

    # y = sum_g (x_g @ q_g) * s_g: per-group dots with the scale
    # applied to the SMALL [Bb, Nb] partial output — scaling the
    # W-sized block per row measured ~2x slower (VPU-bound) than the
    # int8 path it was supposed to beat. The group loop is UNROLLED in
    # python (gh is static, <=22): Mosaic has no dynamic_slice on TC.
    acc = jnp.zeros((Bb, Nb), jnp.float32)
    for g in range(gh):
        r = slice(g * group, (g + 1) * group)
        acc = acc + jax.lax.dot(
            x[:, r], hi[r, :],
            preferred_element_type=jnp.float32) * s[g]
        acc = acc + jax.lax.dot(
            x[:, half + g * group:half + (g + 1) * group], lo[r, :],
            preferred_element_type=jnp.float32) * s[gh + g]
    o_ref[...] = acc.astype(out_dtype)


def int4_matmul(x, packed, scales, group: int = 128,
                block_n: int = 256, block_b: int = 256,
                interpret=None):
    """``x [B, K] @ dequant(packed [K/2, N], scales [K//group, N])``
    with the unpack fused in VMEM; rows and columns both blocked so
    decode (B<=32) AND prefill (B=bs*seq) shapes fit scoped VMEM."""
    B, K = x.shape
    N = packed.shape[1]
    if (K // 2) % group:
        # the kernel's halves layout assigns whole scale groups to each
        # nibble half; a group straddling the half boundary would be
        # silently dropped/mis-scaled
        raise ValueError(
            f"group {group} must divide K//2 = {K // 2} "
            f"(pick a group size with group | K/2)")
    if packed.shape[0] != K // 2:
        raise ValueError(
            f"packed rows {packed.shape[0]} != K//2 = {K // 2}")
    if scales.shape != (K // group, N):
        raise ValueError(
            f"scales shape {scales.shape} != {(K // group, N)}")
    if interpret is None:
        interpret = jax.default_backend() == "cpu"
    cdtype = jnp.float32 if interpret else jnp.bfloat16
    bn = min(block_n, N)
    while N % bn:
        bn //= 2
    bb = min(block_b, B)
    while B % bb:
        bb //= 2
    grid = (B // bb, N // bn)
    kernel = functools.partial(_kernel, group=group,
                               out_dtype=x.dtype, cdtype=cdtype)
    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((bb, K), lambda i, j: (i, 0)),
            pl.BlockSpec((K // 2, bn), lambda i, j: (0, j)),
            pl.BlockSpec((K // group, bn), lambda i, j: (0, j)),
        ],
        out_specs=pl.BlockSpec((bb, bn), lambda i, j: (i, j)),
        out_shape=jax.ShapeDtypeStruct((B, N), x.dtype),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "arbitrary")),
        interpret=interpret,
    )(x, packed, scales)
