"""Tensor creation ops (reference: python/paddle/tensor/creation.py)."""
from __future__ import annotations

from typing import Optional, Sequence, Union

import jax.numpy as jnp
import numpy as np

from ..framework import dtype as dtype_mod
from ..framework.dtype import to_dtype
from ..framework.tensor import Tensor, apply_op, _unwrap

__all__ = [
    "to_tensor", "zeros", "ones", "full", "empty", "zeros_like", "ones_like",
    "full_like", "empty_like", "arange", "linspace", "logspace", "eye",
    "tril", "triu", "diag", "diagflat", "meshgrid", "assign", "clone",
    "numel", "tolist", "as_tensor",
]


def _shape_list(shape):
    if isinstance(shape, Tensor):
        return [int(s) for s in np.asarray(shape._data)]
    if isinstance(shape, (int, np.integer)):
        return [int(shape)]
    return [int(_unwrap_s(s)) for s in shape]


def _unwrap_s(s):
    return int(s._data) if isinstance(s, Tensor) else int(s)


def to_tensor(data, dtype=None, place=None, stop_gradient: bool = True):
    """paddle.to_tensor analog."""
    if isinstance(data, Tensor):
        t = Tensor(data._data, dtype=dtype)
        t.stop_gradient = stop_gradient
        return t
    t = Tensor(data, dtype=dtype)
    t.stop_gradient = stop_gradient
    return t


as_tensor = to_tensor


def _float_dtype(dtype):
    return to_dtype(dtype).np_dtype if dtype is not None \
        else dtype_mod.get_default_dtype().np_dtype


def zeros(shape, dtype=None, name=None):
    return Tensor(jnp.zeros(_shape_list(shape), _float_dtype(dtype)))


def ones(shape, dtype=None, name=None):
    return Tensor(jnp.ones(_shape_list(shape), _float_dtype(dtype)))


def full(shape, fill_value, dtype=None, name=None):
    fill = _unwrap(fill_value)
    if dtype is None and isinstance(fill_value, (bool, int, float)):
        if isinstance(fill_value, bool):
            dt = np.bool_
        elif isinstance(fill_value, int):
            dt = np.int64
        else:
            dt = dtype_mod.get_default_dtype().np_dtype
    else:
        dt = _float_dtype(dtype)
    return Tensor(jnp.full(_shape_list(shape), fill, dt))


def empty(shape, dtype=None, name=None):
    return zeros(shape, dtype)


def zeros_like(x, dtype=None, name=None):
    dt = to_dtype(dtype).np_dtype if dtype is not None else None
    return Tensor(jnp.zeros_like(_unwrap(x), dtype=dt))


def ones_like(x, dtype=None, name=None):
    dt = to_dtype(dtype).np_dtype if dtype is not None else None
    return Tensor(jnp.ones_like(_unwrap(x), dtype=dt))


def full_like(x, fill_value, dtype=None, name=None):
    dt = to_dtype(dtype).np_dtype if dtype is not None else None
    return Tensor(jnp.full_like(_unwrap(x), _unwrap(fill_value), dtype=dt))


def empty_like(x, dtype=None, name=None):
    return zeros_like(x, dtype)


def arange(start=0, end=None, step=1, dtype=None, name=None):
    start, end, step = _unwrap(start), _unwrap(end), _unwrap(step)
    dt = to_dtype(dtype).np_dtype if dtype is not None else None
    if end is None:
        start, end = 0, start
    return Tensor(jnp.arange(start, end, step, dtype=dt))


def linspace(start, stop, num, dtype=None, name=None):
    return Tensor(jnp.linspace(_unwrap(start), _unwrap(stop), _unwrap_s(num),
                               dtype=_float_dtype(dtype)))


def logspace(start, stop, num, base=10.0, dtype=None, name=None):
    return Tensor(jnp.logspace(_unwrap(start), _unwrap(stop), _unwrap_s(num),
                               base=_unwrap(base), dtype=_float_dtype(dtype)))


def eye(num_rows, num_columns=None, dtype=None, name=None):
    return Tensor(jnp.eye(_unwrap_s(num_rows),
                          None if num_columns is None else _unwrap_s(num_columns),
                          dtype=_float_dtype(dtype)))


def tril(x, diagonal=0, name=None):
    return apply_op(lambda a: jnp.tril(a, k=diagonal), x, _op_name="tril")


def triu(x, diagonal=0, name=None):
    return apply_op(lambda a: jnp.triu(a, k=diagonal), x, _op_name="triu")


def diag(x, offset=0, padding_value=0, name=None):
    def f(a):
        if a.ndim == 1 and padding_value != 0:
            d = jnp.diag(a, k=offset)
            mask = jnp.eye(d.shape[0], dtype=bool) if offset == 0 else \
                jnp.diag(jnp.ones_like(a, dtype=bool), k=offset)
            return jnp.where(mask, d, padding_value)
        return jnp.diag(a, k=offset)
    return apply_op(f, x, _op_name="diag")


def diagflat(x, offset=0, name=None):
    return apply_op(lambda a: jnp.diagflat(a, k=offset), x,
                    _op_name="diagflat")


def meshgrid(*args, **kwargs):
    if len(args) == 1 and isinstance(args[0], (list, tuple)):
        args = tuple(args[0])
    outs = jnp.meshgrid(*[_unwrap(a) for a in args], indexing="ij")
    return [Tensor(o) for o in outs]


def assign(x, output: Optional[Tensor] = None):
    src = _unwrap(x) if isinstance(x, Tensor) else jnp.asarray(np.asarray(x))
    if output is None:
        return Tensor(src)
    output.set_value(src)
    return output


def clone(x, name=None):
    return x.clone()


def numel(x, name=None):
    return Tensor(jnp.asarray(x.size, dtype=np.int64))


def tolist(x):
    return x.tolist()


Tensor._bind("tolist", tolist)
Tensor._bind("numel", lambda self: self.size)
