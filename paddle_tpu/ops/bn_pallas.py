"""Pallas training BatchNorm for NCHW activations.

Why: on v5e, XLA's BN reduce/apply fusions sustain only ~150-250 GB/s
against the ~660 GB/s the in-house Pallas kernels reach (measured:
benchmarks/RESULTS.md round-5 ResNet ledger; the 98.8 ms ResNet-50 step
carries ~93 ms of such fusions). BatchNorm is pure streaming work, so
the fix is the same one fused_adamw applied to the optimizer: hand
Pallas the whole pass. Four kernels, each one read (+ at most one
write) of the activation:

  fwd:  K1 per-channel sum/sumsq (accumulated over the batch grid axis)
        -> tiny XLA math on [C] -> K2 scale/shift apply (+ optional
        fused relu)
  bwd:  K3 per-channel sum(dy), sum(dy*x) -> tiny XLA -> K4
        dx = A[c]*dy + B[c]*x + D[c] (the BN backward collapsed to a
        per-channel FMA over dy and x)

Layout contract: x is [N, C, spatial...] (NCHW/NCDHW); kernels view it
as [N, C, S] with S = prod(spatial) as the (whole-dim) lane axis, so S
needs no 128 alignment. Reference analog: the reference's cuDNN-backed
``batch_norm`` training kernels (paddle/phi/kernels/gpu/batch_norm_*).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

__all__ = ["bn_train", "bn_train_eligible"]


def _stats_kernel(x_ref, s1_ref, s2_ref):
    n = pl.program_id(1)
    xf = x_ref[...].astype(jnp.float32)              # [bn, bc, S]
    s1 = jnp.sum(xf, axis=(0, 2))[None, :, None]
    s2 = jnp.sum(xf * xf, axis=(0, 2))[None, :, None]

    @pl.when(n == 0)
    def _init():
        s1_ref[...] = s1
        s2_ref[...] = s2

    @pl.when(n > 0)
    def _acc():
        s1_ref[...] += s1
        s2_ref[...] += s2


def _apply_kernel(x_ref, sc_ref, sh_ref, y_ref, *, relu):
    xf = x_ref[...].astype(jnp.float32)              # [bn, bc, S]
    y = xf * sc_ref[...] + sh_ref[...]
    if relu:
        y = jnp.maximum(y, 0.0)
    y_ref[...] = y.astype(y_ref.dtype)


def _gsum_kernel(dy_ref, x_ref, sdy_ref, sdyx_ref):
    n = pl.program_id(1)
    dyf = dy_ref[...].astype(jnp.float32)
    xf = x_ref[...].astype(jnp.float32)
    a = jnp.sum(dyf, axis=(0, 2))[None, :, None]
    b = jnp.sum(dyf * xf, axis=(0, 2))[None, :, None]

    @pl.when(n == 0)
    def _init():
        sdy_ref[...] = a
        sdyx_ref[...] = b

    @pl.when(n > 0)
    def _acc():
        sdy_ref[...] += a
        sdyx_ref[...] += b


def _dx_kernel(dy_ref, x_ref, a_ref, b_ref, d_ref, dx_ref):
    dyf = dy_ref[...].astype(jnp.float32)
    xf = x_ref[...].astype(jnp.float32)
    dx = dyf * a_ref[...] + xf * b_ref[...] + d_ref[...]
    dx_ref[...] = dx.astype(dx_ref.dtype)


def _pick_bc(C: int, S: int) -> int:
    # largest channel tile whose (bc, S) f32 face stays ~1 MB: small-
    # spatial deep layers take the WHOLE channel dim (fewer grid steps
    # — a (1, bc, S) block design measured grid-overhead-bound there)
    for bc in (C, 512, 256, 128, 64, 32, 16, 8):
        if C % bc == 0 and bc * S * 4 <= (1 << 19):
            return bc
    return 0


def _pick_bn(N: int, bc: int, S: int) -> int:
    for bn in (32, 16, 8, 4, 2):
        if N % bn == 0 and bn * bc * S * 4 <= (1 << 20):
            return bn
    return 1


def _grids(x3):
    N, C, S = x3.shape
    bc = _pick_bc(C, S)
    bn = _pick_bn(N, bc, S)
    blk = pl.BlockSpec((bn, bc, S), lambda j, n: (n, j, 0))
    cblk = pl.BlockSpec((1, bc, 1), lambda j, n: (0, j, 0))
    # batch-blocks innermost: the [C]-sized accumulator blocks are
    # revisited on CONSECUTIVE grid steps, the pattern Pallas TPU
    # keeps in VMEM
    return (C // bc, N // bn), blk, cblk


@functools.partial(jax.jit, static_argnums=(1,))
def _stats_call(x3, interpret):
    N, C, S = x3.shape
    grid, blk, cblk = _grids(x3)
    s1, s2 = pl.pallas_call(
        _stats_kernel, grid=grid,
        in_specs=[blk], out_specs=[cblk, cblk],
        out_shape=[jax.ShapeDtypeStruct((1, C, 1), jnp.float32)] * 2,
        compiler_params=_params(),
        interpret=interpret)(x3)
    return s1.reshape(C), s2.reshape(C)


def _params():
    from jax.experimental.pallas import tpu as pltpu
    return pltpu.CompilerParams(
        dimension_semantics=("parallel", "arbitrary"))


@functools.partial(jax.jit, static_argnums=(3, 4))
def _apply_call(x3, scale, shift, relu, interpret):
    N, C, S = x3.shape
    grid, blk, cblk = _grids(x3)
    return pl.pallas_call(
        functools.partial(_apply_kernel, relu=relu), grid=grid,
        in_specs=[blk, cblk, cblk], out_specs=[blk],
        out_shape=[jax.ShapeDtypeStruct((N, C, S), x3.dtype)],
        compiler_params=_params(),
        interpret=interpret)(x3, scale.reshape(1, C, 1),
                             shift.reshape(1, C, 1))[0]


@functools.partial(jax.jit, static_argnums=(2,))
def _gsum_call(dy3, x3, interpret):
    N, C, S = x3.shape
    grid, blk, cblk = _grids(x3)
    sdy, sdyx = pl.pallas_call(
        _gsum_kernel, grid=grid,
        in_specs=[blk, blk], out_specs=[cblk, cblk],
        out_shape=[jax.ShapeDtypeStruct((1, C, 1), jnp.float32)] * 2,
        compiler_params=_params(),
        interpret=interpret)(dy3, x3)
    return sdy.reshape(C), sdyx.reshape(C)


@functools.partial(jax.jit, static_argnums=(5,))
def _dx_call(dy3, x3, a, b, d, interpret):
    N, C, S = x3.shape
    grid, blk, cblk = _grids(x3)
    return pl.pallas_call(
        _dx_kernel, grid=grid,
        in_specs=[blk, blk, cblk, cblk, cblk], out_specs=[blk],
        out_shape=[jax.ShapeDtypeStruct((N, C, S), dy3.dtype)],
        compiler_params=_params(),
        interpret=interpret)(dy3, x3, a.reshape(1, C, 1),
                             b.reshape(1, C, 1), d.reshape(1, C, 1))[0]


def bn_train_eligible(x) -> bool:
    """4-D+ [N, C, spatial...] with a Pallas-block-compatible C."""
    if x.ndim < 3:
        return False
    C = x.shape[1]
    S = 1
    for s in x.shape[2:]:
        S *= s
    # C % 8: stay on sublane-aligned channel tiles (hardware-verified
    # geometry); every shipped vision net satisfies it
    return C % 8 == 0 \
        and _pick_bc(C, S) != 0 \
        and x.shape[0] >= 1


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def bn_train(x, gamma, beta, eps=1e-5, relu=False, interpret=False):
    """Training BatchNorm over [N, C, spatial...]: returns
    (y, batch_mean, batch_var). mean/var are emitted for the caller's
    running-stats update and are NOT differentiated through (the
    standard BN-train contract). ``relu`` fuses max(y, 0) into the
    apply pass; its backward masks on y > 0."""
    y, mean, var, _ = _fwd_core(x, gamma, beta, eps, relu, interpret)
    return y, mean, var


def _fwd_core(x, gamma, beta, eps, relu, interpret):
    N, C = x.shape[0], x.shape[1]
    S = x.size // (N * C)
    x3 = x.reshape(N, C, S)
    s1, s2 = _stats_call(x3, interpret)
    n = N * S
    mean = s1 / n
    var = jnp.maximum(s2 / n - mean * mean, 0.0)
    rstd = jax.lax.rsqrt(var + eps)
    g = jnp.ones((C,), jnp.float32) if gamma is None \
        else gamma.astype(jnp.float32)
    b = jnp.zeros((C,), jnp.float32) if beta is None \
        else beta.astype(jnp.float32)
    scale = g * rstd
    shift = b - mean * scale
    y = _apply_call(x3, scale, shift, relu, interpret).reshape(x.shape)
    return y, mean, var, rstd


def _bn_fwd(x, gamma, beta, eps, relu, interpret):
    y, mean, var, rstd = _fwd_core(x, gamma, beta, eps, relu, interpret)
    res = (x, gamma, beta, mean, rstd, y if relu else None)
    return (y, mean, var), res


def _bn_bwd(eps, relu, interpret, res, cts):
    x, gamma, beta, mean, rstd, y = res
    dy = cts[0]   # mean/var cotangents are zero by contract
    N, C = x.shape[0], x.shape[1]
    S = x.size // (N * C)
    if relu:
        # mask through the fused relu: dY/dpre = [y > 0]
        dy = jnp.where(y > 0, dy, jnp.zeros((), dy.dtype))
    dy3 = dy.reshape(N, C, S)
    x3 = x.reshape(N, C, S)
    sdy, sdyx = _gsum_call(dy3, x3, interpret)
    n = N * S
    g = jnp.ones((C,), jnp.float32) if gamma is None \
        else gamma.astype(jnp.float32)
    # dgamma = sum(dy * xhat) = rstd * (sum(dy x) - mu sum(dy))
    dgamma = rstd * (sdyx - mean * sdy)
    dbeta = sdy
    # dx = g*rstd*(dy - mean_dy - xhat*mean(dy*xhat))
    #    = A*dy + B*x + D with per-channel A, B, D
    m1 = sdy / n
    m2 = dgamma / n          # mean(dy * xhat)
    A = g * rstd
    B = -g * rstd * rstd * m2
    D = -A * m1 - B * mean
    dx = _dx_call(dy3, x3, A, B, D, interpret).reshape(x.shape) \
        .astype(x.dtype)
    dg = None if gamma is None else dgamma.astype(gamma.dtype)
    db = None if beta is None else dbeta.astype(beta.dtype)
    return dx, dg, db


bn_train.defvjp(_bn_fwd, _bn_bwd)
