"""PyLayer: user-defined differentiable ops.

Reference: python/paddle/autograd/py_layer.py + C++ core
/root/reference/paddle/fluid/eager/pylayer/. TPU-native: the custom backward
is installed as a hand-built GradNode whose vjp closure calls the user's
``backward`` staticmethod; jax.custom_vjp is intentionally NOT required
because the tape engine already accepts arbitrary python vjp closures.
"""
from __future__ import annotations

from typing import Any, Tuple

import jax.numpy as jnp

from ..framework.tensor import (GradNode, Tensor, grad_enabled, no_grad)


class PyLayerContext:
    def __init__(self):
        self._saved: Tuple[Tensor, ...] = ()
        self.not_inplace = False
        self._materialize_grads = True

    def save_for_backward(self, *tensors):
        self._saved = tuple(tensors)

    def saved_tensor(self):
        return self._saved

    def mark_not_inplace(self, *args):
        self.not_inplace = True

    def mark_non_differentiable(self, *args):
        pass

    def set_materialize_grads(self, value: bool):
        self._materialize_grads = bool(value)


class PyLayerMeta(type):
    pass


class PyLayer(metaclass=PyLayerMeta):
    @staticmethod
    def forward(ctx: PyLayerContext, *args, **kwargs):
        raise NotImplementedError

    @staticmethod
    def backward(ctx: PyLayerContext, *grads):
        raise NotImplementedError

    @classmethod
    def apply(cls, *args, **kwargs):
        ctx = PyLayerContext()
        tensor_inputs = [a for a in args if isinstance(a, Tensor)] + \
            [v for v in kwargs.values() if isinstance(v, Tensor)]
        tracked = grad_enabled() and any(
            not t.stop_gradient for t in tensor_inputs)

        with no_grad():
            outputs = cls.forward(ctx, *args, **kwargs)

        multi = isinstance(outputs, (tuple, list))
        outs = list(outputs) if multi else [outputs]
        if not tracked:
            return outputs

        out_meta = [(tuple(o.shape), o._data.dtype) for o in outs]

        def vjp_fn(cots):
            cot_list = list(cots) if multi else [cots]
            grads_in = [Tensor(c, stop_gradient=True) for c in cot_list]
            with no_grad():
                res = cls.backward(ctx, *grads_in)
            res_list = list(res) if isinstance(res, (tuple, list)) else [res]
            if len(res_list) != len(tensor_inputs):
                raise RuntimeError(
                    f"{cls.__name__}.backward returned {len(res_list)} grads "
                    f"for {len(tensor_inputs)} tensor inputs")
            return tuple(
                g._data if isinstance(g, Tensor) else
                (jnp.zeros(tuple(t.shape), t._data.dtype) if g is None
                 else jnp.asarray(g))
                for g, t in zip(res_list, tensor_inputs))

        node = GradNode(vjp_fn, tuple(tensor_inputs), out_meta, multi,
                        cls.__name__)
        wrapped = [
            Tensor(o._data, stop_gradient=False, _node=node, _out_idx=i)
            for i, o in enumerate(outs)
        ]
        return tuple(wrapped) if multi else wrapped[0]
