"""Autograd public API (reference: python/paddle/autograd/ — backward,
paddle.grad via egr::Grad /root/reference/paddle/fluid/eager/general_grad.h,
PyLayer python/paddle/autograd/py_layer.py)."""
from .backward_api import backward, grad
from .py_layer import PyLayer, PyLayerContext
from ..framework.tensor import no_grad, enable_grad, set_grad_enabled

__all__ = ["backward", "grad", "PyLayer", "PyLayerContext", "no_grad",
           "enable_grad", "set_grad_enabled"]


def jacobian(ys, xs, batch_axis=None):
    """paddle.autograd.jacobian (functional): lazy Jacobian object
    (delegates to incubate.autograd.Jacobian over a function or a pair
    of computed tensors is not supported — pass a callable)."""
    from ..incubate.autograd import Jacobian
    if callable(ys):
        return Jacobian(ys, xs, is_batched=batch_axis is not None)
    raise TypeError(
        "paddle.autograd.jacobian expects (func, xs); tensor-pair form "
        "has no graph to re-trace in this framework — wrap the "
        "computation in a function")


def hessian(ys, xs, batch_axis=None):
    from ..incubate.autograd import Hessian
    if callable(ys):
        return Hessian(ys, xs, is_batched=batch_axis is not None)
    raise TypeError(
        "paddle.autograd.hessian expects (func, xs); wrap the "
        "computation in a function")


class saved_tensors_hooks:
    """Context registering pack/unpack hooks for saved activations
    (python/paddle/autograd/saved_tensors_hooks.py). The façade saves
    residuals inside jax vjp closures, which cannot be intercepted
    per-tensor; the context is accepted and the hooks validated, with
    recompute (fleet.utils.recompute) as the supported memory-saving
    path."""

    def __init__(self, pack_hook, unpack_hook):
        if not callable(pack_hook) or not callable(unpack_hook):
            raise TypeError("pack_hook and unpack_hook must be callable")
        self.pack_hook = pack_hook
        self.unpack_hook = unpack_hook

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


__all__ += ["jacobian", "hessian", "saved_tensors_hooks"]
