"""Autograd public API (reference: python/paddle/autograd/ — backward,
paddle.grad via egr::Grad /root/reference/paddle/fluid/eager/general_grad.h,
PyLayer python/paddle/autograd/py_layer.py)."""
from .backward_api import backward, grad
from .py_layer import PyLayer, PyLayerContext
from ..framework.tensor import no_grad, enable_grad, set_grad_enabled

__all__ = ["backward", "grad", "PyLayer", "PyLayerContext", "no_grad",
           "enable_grad", "set_grad_enabled"]
