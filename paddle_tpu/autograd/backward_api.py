"""paddle.autograd.backward / paddle.grad analogs.

Reference: egr::Backward (/root/reference/paddle/fluid/eager/backward.cc:439)
and egr::Grad (general_grad.h). ``grad`` runs the same engine but captures
grads for exactly the requested inputs without touching ``.grad``.
"""
from __future__ import annotations

from typing import List, Optional, Sequence, Union

from ..framework.tensor import Tensor, run_backward


def _as_list(x):
    if x is None:
        return None
    if isinstance(x, (list, tuple)):
        return list(x)
    return [x]


def backward(tensors, grad_tensors=None, retain_graph=False):
    tensors = _as_list(tensors)
    grad_tensors = _as_list(grad_tensors)
    run_backward(tensors, grad_tensors, retain_graph)


def grad(outputs, inputs, grad_outputs=None, retain_graph=None,
         create_graph=False, only_inputs=True, allow_unused=False,
         no_grad_vars=None) -> List[Optional[Tensor]]:
    """paddle.grad analog: returns grads of ``outputs`` w.r.t ``inputs``.

    Implementation: snapshot each input's ``.grad``, run the engine with
    ``_retain_grad`` forced on the inputs, return the delta, then restore.
    ``create_graph`` (higher-order) is not yet supported — the engine runs
    under no_grad; double-grad arrives with the functional jax.grad path
    (jit.functional), tracked as a gap.
    """
    outputs = _as_list(outputs)
    inputs = _as_list(inputs)
    grad_outputs = _as_list(grad_outputs)
    if create_graph:
        raise NotImplementedError(
            "create_graph=True: use paddle_tpu.jit.functional grad transforms "
            "for higher-order derivatives")
    retain_graph = bool(retain_graph) if retain_graph is not None else False

    saved = [(t.grad, t._retain_grad) for t in inputs]
    for t in inputs:
        t.grad = None
        t._retain_grad = True
    try:
        run_backward(outputs, grad_outputs, retain_graph)
        results: List[Optional[Tensor]] = []
        for t in inputs:
            if t.grad is None and not allow_unused:
                raise RuntimeError(
                    f"input tensor {t.name} is unused in the graph "
                    "(pass allow_unused=True to get None)")
            results.append(t.grad)
    finally:
        for t, (g, r) in zip(inputs, saved):
            t.grad = g
            t._retain_grad = r
    return results
