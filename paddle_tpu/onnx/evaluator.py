"""Reference evaluator for exported ONNX models (numpy).

This environment has no onnxruntime; this evaluator executes the op
subset `convert.py` emits so exports can be validated numerically
in-repo (tests compare against the eager paddle forward). It reads the
decoded proto from `proto.load`, so a test run exercises writer →
reader → semantics end to end.
"""
from __future__ import annotations

from typing import Dict

import numpy as np

from .proto import DecodedModel, ONNX2NP, BFLOAT16


def _cast(arr, onnx_type):
    if onnx_type == BFLOAT16:
        # numpy has no bfloat16: evaluate in float32 (values identical
        # up to bf16 rounding, which the tolerance owns)
        return arr.astype(np.float32)
    return arr.astype(ONNX2NP[onnx_type])


def _conv(x, w, strides, pads, dilations, group):
    n, c, *ispatial = x.shape
    o, cg, *kspatial = w.shape
    nd = len(ispatial)
    pad_width = [(0, 0), (0, 0)] + [
        (pads[i], pads[nd + i]) for i in range(nd)]
    x = np.pad(x, pad_width)
    out_sp = [
        (x.shape[2 + i] - (dilations[i] * (kspatial[i] - 1) + 1))
        // strides[i] + 1 for i in range(nd)]
    y = np.zeros([n, o] + out_sp, np.float32)
    og = o // group
    for g in range(group):
        xs = x[:, g * cg:(g + 1) * cg]
        for oi in range(og):
            ko = g * og + oi
            acc = np.zeros([n] + out_sp, np.float32)
            for idx in np.ndindex(*kspatial):
                sl = tuple(
                    slice(idx[i] * dilations[i],
                          idx[i] * dilations[i]
                          + out_sp[i] * strides[i],
                          strides[i]) for i in range(nd))
                patch = xs[(slice(None), slice(None)) + sl]
                acc += np.einsum("nc...,c->n...",
                                 patch.astype(np.float32),
                                 w[ko][(slice(None),) + idx]
                                 .astype(np.float32))
            y[:, ko] = acc
    return y.astype(x.dtype)


def _pool(x, kshape, strides, pads, mode):
    n, c, *ispatial = x.shape
    nd = len(kshape)
    fill = -np.inf if mode == "max" else 0.0
    pad_width = [(0, 0), (0, 0)] + [
        (pads[i], pads[nd + i]) for i in range(nd)]
    x = np.pad(x, pad_width, constant_values=fill)
    out_sp = [(x.shape[2 + i] - kshape[i]) // strides[i] + 1
              for i in range(nd)]
    from numpy.lib.stride_tricks import sliding_window_view
    win = sliding_window_view(x, kshape, axis=tuple(range(2, 2 + nd)))
    sl = tuple(slice(None, out_sp[i] * strides[i], strides[i])
               for i in range(nd))
    win = win[(slice(None), slice(None)) + sl]
    red = tuple(range(2 + nd, 2 + 2 * nd))
    return (win.max(axis=red) if mode == "max"
            else win.mean(axis=red, dtype=np.float32).astype(x.dtype))


def run(model: DecodedModel,
        feeds: Dict[str, np.ndarray]) -> Dict[str, np.ndarray]:
    g = model.graph
    env: Dict[str, np.ndarray] = dict(g.initializers)
    for vi in g.inputs:
        if vi.name not in feeds:
            raise ValueError(f"missing input {vi.name}")
        env[vi.name] = np.asarray(feeds[vi.name])

    for nd in g.nodes:
        i = [env[x] for x in nd.inputs if x]
        a = nd.attrs
        op = nd.op_type
        if op == "Identity":
            r = i[0]
        elif op in ("Add", "Sub", "Mul", "Div", "Pow"):
            f = {"Add": np.add, "Sub": np.subtract,
                 "Mul": np.multiply, "Div": np.divide,
                 "Pow": np.power}[op]
            r = f(i[0], i[1])
            if i[0].dtype.kind in "fiu":
                r = r.astype(np.result_type(i[0], i[1]))
        elif op == "MatMul":
            r = np.matmul(i[0].astype(np.float32),
                          i[1].astype(np.float32)).astype(i[0].dtype) \
                if i[0].dtype.kind == "f" else np.matmul(i[0], i[1])
        elif op == "Max":
            r = np.maximum(i[0], i[1])
        elif op == "Min":
            r = np.minimum(i[0], i[1])
        elif op == "Cast":
            r = _cast(i[0], a["to"])
        elif op == "Reshape":
            shape = [int(d) for d in i[1]]
            r = i[0].reshape(shape)
        elif op == "Transpose":
            r = np.transpose(i[0], a["perm"])
        elif op == "Expand":
            r = np.broadcast_to(i[0], [int(d) for d in i[1]]).copy()
        elif op == "Unsqueeze":
            r = i[0]
            for ax in sorted(int(d) for d in i[1]):
                r = np.expand_dims(r, ax)
        elif op == "Squeeze":
            r = np.squeeze(i[0], tuple(int(d) for d in i[1])) \
                if len(i) > 1 else np.squeeze(i[0])
        elif op == "Concat":
            r = np.concatenate(i, axis=a["axis"])
        elif op == "Slice":
            starts, ends = i[1], i[2]
            axes = i[3] if len(i) > 3 else np.arange(len(starts))
            steps = i[4] if len(i) > 4 else np.ones_like(starts)
            sl = [slice(None)] * i[0].ndim
            for s, e, ax, st in zip(starts, ends, axes, steps):
                s, e, st = int(s), int(e), int(st)
                lo = None if e <= -(1 << 62) and st < 0 else e
                sl[int(ax)] = slice(s, lo, st)
            r = i[0][tuple(sl)]
        elif op == "Pad":
            pads = [int(p) for p in i[1]]
            nd_ = i[0].ndim
            pw = [(pads[k], pads[nd_ + k]) for k in range(nd_)]
            cv = i[2].item() if len(i) > 2 else 0.0
            r = np.pad(i[0], pw, constant_values=cv)
        elif op == "Conv":
            r = _conv(i[0], i[1], a["strides"], a["pads"],
                      a["dilations"], a.get("group", 1))
        elif op == "MaxPool":
            r = _pool(i[0], a["kernel_shape"], a["strides"],
                      a["pads"], "max")
        elif op == "AveragePool":
            r = _pool(i[0], a["kernel_shape"], a["strides"],
                      a["pads"], "avg")
        elif op in ("ReduceSum", "ReduceMax", "ReduceMin",
                    "ReduceProd"):
            if op == "ReduceSum":
                axes = tuple(int(x) for x in i[1])
            else:
                axes = tuple(a["axes"])
            keep = bool(a.get("keepdims", 1))
            f = {"ReduceSum": np.sum, "ReduceMax": np.max,
                 "ReduceMin": np.min, "ReduceProd": np.prod}[op]
            r = f(i[0], axis=axes, keepdims=keep)
            if i[0].dtype.kind == "f":
                r = r.astype(i[0].dtype)
        elif op in ("ArgMax", "ArgMin"):
            f = np.argmax if op == "ArgMax" else np.argmin
            r = f(i[0], axis=a["axis"])
            if a.get("keepdims", 1):
                r = np.expand_dims(r, a["axis"])
            r = r.astype(np.int64)
        elif op == "CumSum":
            r = np.cumsum(i[0], axis=int(i[1]))
        elif op == "Gather":
            r = np.take(i[0], i[1], axis=a.get("axis", 0))
        elif op == "Where":
            r = np.where(i[0], i[1], i[2])
        elif op == "Clip":
            r = np.clip(i[0], i[1], i[2])
        elif op == "Mod":
            r = np.fmod(i[0], i[1]) if a.get("fmod") else \
                np.mod(i[0], i[1])
        elif op in ("Exp", "Log", "Tanh", "Abs", "Neg", "Sqrt",
                    "Sign", "Floor", "Ceil", "Round", "Sin", "Cos",
                    "Erf", "Sigmoid", "Reciprocal", "Not"):
            import scipy.special
            f = {"Exp": np.exp, "Log": np.log, "Tanh": np.tanh,
                 "Abs": np.abs, "Neg": np.negative, "Sqrt": np.sqrt,
                 "Sign": np.sign, "Floor": np.floor, "Ceil": np.ceil,
                 "Round": np.round, "Sin": np.sin, "Cos": np.cos,
                 "Erf": scipy.special.erf,
                 "Sigmoid": lambda x: 1 / (1 + np.exp(-x)),
                 "Reciprocal": np.reciprocal,
                 "Not": np.logical_not}[op]
            r = f(i[0])
            if i[0].dtype.kind == "f" and op != "Not":
                r = r.astype(i[0].dtype)
        elif op in ("Equal", "Less", "Greater", "LessOrEqual",
                    "GreaterOrEqual", "And", "Or", "Xor"):
            f = {"Equal": np.equal, "Less": np.less,
                 "Greater": np.greater, "LessOrEqual": np.less_equal,
                 "GreaterOrEqual": np.greater_equal,
                 "And": np.logical_and, "Or": np.logical_or,
                 "Xor": np.logical_xor}[op]
            r = f(i[0], i[1])
        else:
            raise NotImplementedError(f"evaluator: op {op}")
        env[nd.outputs[0]] = r

    return {vo.name: env[vo.name] for vo in g.outputs}
