"""ONNX export stub (reference: python/paddle/onnx/export.py — a thin
delegation to the external paddle2onnx package).

TPU-native: the first-class interchange format here is StableHLO
(paddle_tpu.jit.save / paddle_tpu.inference export that portable bytecode);
ONNX export delegates to an optional converter package if present."""
from __future__ import annotations

__all__ = ["export"]


def export(layer, path, input_spec=None, opset_version=9, **configs):
    try:
        import paddle2onnx  # noqa: F401
    except ImportError:
        raise NotImplementedError(
            "ONNX export requires the optional paddle2onnx converter, which "
            "is not installed. Use paddle_tpu.jit.save(...) for StableHLO "
            "export — the portable deployment format of this framework.")
