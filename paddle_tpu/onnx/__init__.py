"""paddle.onnx.export analog — a native jaxpr -> ONNX exporter.

Reference: python/paddle/onnx/export.py (a thin delegation to the
external paddle2onnx package, which translates the static Program
op-by-op). Here the model traces to a jaxpr and `convert.py` lowers
each primitive to ONNX ops; weights become initializers; the protobuf
is serialized by `proto.py` (no onnx/protobuf dependency — field
numbers cross-validated against the descriptor embedded in libtorch).

Covers inference graphs (conv/pool/matmul/normalization/activations/
reshape ops — the vision zoo exports end to end); training steps and
control-flow graphs should use paddle_tpu.jit.save (StableHLO), the
first-class interchange format of this framework.
"""
from __future__ import annotations

import numpy as np

__all__ = ["export"]


def export(layer, path, input_spec=None, opset_version=13, **configs):
    """Trace ``layer`` (nn.Layer or callable on Tensors) with
    ``input_spec`` and write ``<path>.onnx`` (the reference appends
    the suffix the same way). Returns the written path.

    ``input_spec``: list of InputSpec (None dims export as symbolic
    dim_params and trace at size 2) or example Tensors/ndarrays.
    """
    import jax

    from ..framework.tensor import Tensor
    from ..jit.static_function import InputSpec
    from .convert import jaxpr_to_model

    if input_spec is None:
        raise ValueError("onnx.export requires input_spec")
    if opset_version < 13:
        raise ValueError(
            f"opset_version={opset_version}: this exporter emits "
            f"opset-13 op forms (axes-as-input ReduceSum/Unsqueeze/"
            f"Squeeze, input-form Slice/Clip); pass >= 13")

    # each symbolic (None) dim traces at its OWN distinctive prime so
    # the converter can recognize the sizes inside static shape params
    # (by divisibility, for flatten-style products) and emit -1 /
    # dim_params instead of baking traced sizes. Distinct primes keep
    # independent dynamic dims independent.
    PRIMES = [1867, 2003, 2129, 2213, 2339, 2459, 2579, 2693]
    prime_iter = iter(PRIMES)
    used_primes = []
    example = []
    dims = []
    names = []
    for i, spec in enumerate(input_spec):
        if isinstance(spec, InputSpec):
            shape = []
            declared = []
            for d in spec.shape:
                if d is None:
                    try:
                        p = next(prime_iter)
                    except StopIteration:
                        raise ValueError("too many dynamic dims (>8)")
                    used_primes.append(p)
                    shape.append(p)
                    declared.append(f"dyn_{p}")
                else:
                    shape.append(int(d))
                    declared.append(int(d))
            example.append(np.zeros(shape, np.dtype(spec.dtype)))
            names.append(spec.name or f"input_{i}")
        else:
            arr = spec.numpy() if isinstance(spec, Tensor) \
                else np.asarray(spec)
            example.append(arr)
            declared = list(arr.shape)
            names.append(f"input_{i}")
        dims.append(declared)

    from ..nn.layer_base import Layer
    is_layer = isinstance(layer, Layer)
    was_training = is_layer and layer.training
    if is_layer:
        layer.eval()
    try:
        def fn(*xs):
            out = layer(*[Tensor(x) for x in xs])
            return _unwrap(out)

        closed = jax.make_jaxpr(fn)(*example)
    finally:
        if was_training:
            layer.train()

    data = jaxpr_to_model(
        closed, names, dims,
        graph_name=type(layer).__name__, opset=opset_version,
        dynamic_sizes=tuple(used_primes))
    out_path = str(path)
    if not out_path.endswith(".onnx"):
        out_path += ".onnx"
    with open(out_path, "wb") as f:
        f.write(data)
    return out_path


def _unwrap(out):
    from ..framework.tensor import Tensor
    if isinstance(out, Tensor):
        return out._data
    if isinstance(out, (list, tuple)):
        return tuple(_unwrap(o) for o in out)
    if isinstance(out, dict):
        return {k: _unwrap(v) for k, v in out.items()}
    return out
