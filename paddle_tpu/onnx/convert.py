"""jaxpr -> ONNX graph converter.

Reference analog: paddle2onnx's program translator (the reference's
python/paddle/onnx/export.py hands the static Program to the external
paddle2onnx package, ~50k LoC of per-op converters). TPU-native: the
model is traced to a jaxpr (the same IR everything else here uses) and
each primitive lowers to ONNX ops. Weights arrive as jaxpr constants
and become initializers. Higher-order primitives (pjit, custom_jvp,
remat) are inlined; control-flow primitives (scan/while/cond) are
rejected with a clear error — export inference graphs, not training
steps.

Op coverage targets the inference zoo: conv/pool/matmul/normalization/
activations/reshapes. Anything unmapped raises NotImplementedError
naming the primitive.
"""
from __future__ import annotations

from typing import Any, Dict, List, Sequence

import numpy as np
import jax
import jax.numpy as jnp
from jax.extend import core as jcore

from . import proto
from .proto import Msg, node as pnode

INT64_MIN = -(1 << 63) + 1


class _Ctx:
    def __init__(self, dynamic_sizes=()):
        self.nodes: List[Msg] = []
        self.initializers: List[Msg] = []
        self.names: Dict[Any, str] = {}
        self.n = 0
        self.const_cache: Dict[Any, str] = {}
        # trace-time sizes that stand in for symbolic dims (the export
        # entry traces None dims at a distinctive prime so they can be
        # recognized inside static shape parameters and emitted as -1)
        self.dynamic_sizes = set(dynamic_sizes)
        # names whose produced array is SMALLER than the aval claims:
        # broadcast_in_dim defers its stretch to consumers' numpy-style
        # broadcasting; non-broadcasting consumers call name_mat to
        # materialize with an explicit Expand
        self.deferred: Dict[str, tuple] = {}
        self._materialized: Dict[str, str] = {}

    def reshape_target(self, dims) -> List[int]:
        """Static reshape target with dynamic placeholder sizes mapped
        to -1. Placeholders are large primes, so a target dim that
        CONTAINS a dynamic dim (e.g. flatten's batch*features) is
        recognized by divisibility."""
        out = []
        subbed = 0
        for d in dims:
            d = int(d)
            hits = [p for p in self.dynamic_sizes if d % p == 0]
            if len(hits) > 1 or (hits and d // hits[0]
                                 in self.dynamic_sizes):
                raise NotImplementedError(
                    "a Reshape merges two independent dynamic dims — "
                    "fix one of them to a concrete size for export")
            if hits:
                if subbed:
                    raise NotImplementedError(
                        "Reshape with two dynamic target dims")
                out.append(-1)
                subbed += 1
            else:
                out.append(d)
        return out

    def fresh(self, hint: str) -> str:
        self.n += 1
        return f"{hint}_{self.n}"

    def name_of(self, v) -> str:
        if isinstance(v, jcore.Literal):
            arr = np.asarray(v.val)
            return self.const(arr, "lit")
        return self.names[v]

    def set_name(self, v, name: str):
        self.names[v] = name

    def const(self, arr, hint: str) -> str:
        arr = np.asarray(arr)
        # byte-exact dedup for small consts (shape vectors, scalars,
        # norm stats); big weights dedup by object identity so the
        # cache never holds a second copy of hundreds of MB
        if arr.nbytes <= (1 << 16):
            key = (arr.dtype.str, arr.shape, arr.tobytes())
        else:
            # the cache VALUE retains arr, so the id cannot be reused
            # by a new object while this entry lives
            key = (arr.dtype.str, arr.shape, id(arr))
        got = self.const_cache.get(key)
        if got is not None:
            return got[0]
        name = self.fresh(hint)
        self.initializers.append(proto.tensor_proto(name, arr))
        self.const_cache[key] = (name, arr)
        return name

    def i64(self, vals, hint="shape") -> str:
        return self.const(np.asarray(vals, np.int64), hint)

    def emit(self, op: str, ins: Sequence[str], outs: Sequence[str],
             **attrs):
        self.nodes.append(pnode(op, ins, outs,
                                name=self.fresh(op.lower()), **attrs))

    def emit1(self, op: str, ins: Sequence[str], hint=None, **attrs):
        out = self.fresh(hint or op.lower())
        self.emit(op, ins, [out], **attrs)
        return out

    def emit_identity(self, src: str, dst: str):
        self.emit("Identity", [src], [dst])
        if src in self.deferred:
            self.deferred[dst] = self.deferred[src]

    def name_mat(self, v) -> str:
        """Like name_of, but guarantees the array has its full aval
        shape (materializes a deferred broadcast with Expand)."""
        nm = self.name_of(v)
        shape = self.deferred.get(nm)
        if shape is None:
            return nm
        got = self._materialized.get(nm)
        if got is None:
            got = self.emit1("Expand", [nm, self.i64(shape, "bshape")])
            self._materialized[nm] = got
        return got


def _np_dtype(aval):
    return np.dtype(aval.dtype) if str(aval.dtype) != "bfloat16" \
        else aval.dtype


def _onnx_dtype_of(aval) -> int:
    return proto.onnx_dtype(aval.dtype)


# ---------------------------------------------------------------------------
# primitive handlers
# ---------------------------------------------------------------------------
PRIMS: Dict[str, Any] = {}


def _prim(*names):
    def deco(fn):
        for n in names:
            PRIMS[n] = fn
        return fn
    return deco


def _binop(op):
    def h(ctx, eqn):
        a, b = (ctx.name_of(v) for v in eqn.invars)
        ctx.emit(op, [a, b], [ctx.name_of(eqn.outvars[0])])
    return h


def _unop(op):
    def h(ctx, eqn):
        ctx.emit(op, [ctx.name_of(eqn.invars[0])],
                 [ctx.name_of(eqn.outvars[0])])
    return h


for prim, op in [("add", "Add"), ("sub", "Sub"), ("mul", "Mul"),
                 ("div", "Div"), ("max", "Max"), ("min", "Min"),
                 ("pow", "Pow"), ("add_any", "Add"),
                 ("and", "And"), ("or", "Or"), ("xor", "Xor"),
                 ("eq", "Equal"), ("lt", "Less"), ("gt", "Greater"),
                 ("le", "LessOrEqual"), ("ge", "GreaterOrEqual"),
                 ("atan2", "Atan2")]:
    PRIMS[prim] = _binop(op)

for prim, op in [("exp", "Exp"), ("log", "Log"), ("tanh", "Tanh"),
                 ("abs", "Abs"), ("neg", "Neg"), ("sqrt", "Sqrt"),
                 ("sign", "Sign"), ("floor", "Floor"),
                 ("ceil", "Ceil"), ("round_nearest_even", "Round"),
                 ("logistic", "Sigmoid"), ("erf", "Erf"),
                 ("sin", "Sin"), ("cos", "Cos"), ("not", "Not"),
                 ("copy", "Identity"), ("stop_gradient", "Identity")]:
    PRIMS[prim] = _unop(op)


@_prim("ne")
def _ne(ctx, eqn):
    a, b = (ctx.name_of(v) for v in eqn.invars)
    e = ctx.emit1("Equal", [a, b])
    ctx.emit("Not", [e], [ctx.name_of(eqn.outvars[0])])


@_prim("rsqrt")
def _rsqrt(ctx, eqn):
    s = ctx.emit1("Sqrt", [ctx.name_of(eqn.invars[0])])
    ctx.emit("Reciprocal", [s], [ctx.name_of(eqn.outvars[0])])


@_prim("square")
def _square(ctx, eqn):
    a = ctx.name_of(eqn.invars[0])
    ctx.emit("Mul", [a, a], [ctx.name_of(eqn.outvars[0])])


@_prim("log1p")
def _log1p(ctx, eqn):
    aval = eqn.invars[0].aval
    one = ctx.const(np.ones((), _np_dtype(aval)), "one")
    s = ctx.emit1("Add", [ctx.name_of(eqn.invars[0]), one])
    ctx.emit("Log", [s], [ctx.name_of(eqn.outvars[0])])


@_prim("expm1")
def _expm1(ctx, eqn):
    aval = eqn.invars[0].aval
    one = ctx.const(np.ones((), _np_dtype(aval)), "one")
    e = ctx.emit1("Exp", [ctx.name_of(eqn.invars[0])])
    ctx.emit("Sub", [e, one], [ctx.name_of(eqn.outvars[0])])


@_prim("erfc")
def _erfc(ctx, eqn):
    aval = eqn.invars[0].aval
    one = ctx.const(np.ones((), _np_dtype(aval)), "one")
    e = ctx.emit1("Erf", [ctx.name_of(eqn.invars[0])])
    ctx.emit("Sub", [one, e], [ctx.name_of(eqn.outvars[0])])


@_prim("integer_pow")
def _integer_pow(ctx, eqn):
    aval = eqn.invars[0].aval
    y = ctx.const(np.asarray(eqn.params["y"], _np_dtype(aval)), "exp")
    ctx.emit("Pow", [ctx.name_of(eqn.invars[0]), y],
             [ctx.name_of(eqn.outvars[0])])


@_prim("rem")
def _rem(ctx, eqn):
    a, b = (ctx.name_of(v) for v in eqn.invars)
    ctx.emit("Mod", [a, b], [ctx.name_of(eqn.outvars[0])], fmod=1)


@_prim("clamp")
def _clamp(ctx, eqn):
    lo, x, hi = (ctx.name_of(v) for v in eqn.invars)
    ctx.emit("Clip", [x, lo, hi], [ctx.name_of(eqn.outvars[0])])


@_prim("select_n")
def _select_n(ctx, eqn):
    if len(eqn.invars) != 3:
        raise NotImplementedError("select_n with >2 cases")
    which, f, t = (ctx.name_of(v) for v in eqn.invars)
    # select_n picks cases[which]: which=True -> second case
    ctx.emit("Where", [which, t, f], [ctx.name_of(eqn.outvars[0])])


@_prim("convert_element_type")
def _convert(ctx, eqn):
    to = proto.onnx_dtype(eqn.params["new_dtype"])
    ctx.emit("Cast", [ctx.name_of(eqn.invars[0])],
             [ctx.name_of(eqn.outvars[0])], to=to)


@_prim("reshape")
def _reshape(ctx, eqn):
    x = ctx.name_mat(eqn.invars[0])
    if eqn.params.get("dimensions") is not None:
        x = ctx.emit1("Transpose", [x],
                      perm=list(eqn.params["dimensions"]))
    shape = ctx.i64(ctx.reshape_target(eqn.params["new_sizes"]))
    ctx.emit("Reshape", [x, shape], [ctx.name_of(eqn.outvars[0])])


@_prim("transpose")
def _transpose(ctx, eqn):
    ctx.emit("Transpose", [ctx.name_mat(eqn.invars[0])],
             [ctx.name_of(eqn.outvars[0])],
             perm=list(eqn.params["permutation"]))


@_prim("broadcast_in_dim")
def _broadcast(ctx, eqn):
    # rank promotion as Unsqueeze (shape-agnostic: no baked batch
    # sizes); the size-1 stretch itself is DEFERRED to the consumer's
    # numpy-style ONNX broadcasting (Add/Mul/Where/MatMul... all
    # broadcast). A consumer that does not broadcast (Concat) would
    # need an explicit Expand — the evaluator-backed tests own that.
    x = ctx.name_of(eqn.invars[0])
    shape = eqn.params["shape"]
    bdims = eqn.params["broadcast_dimensions"]
    in_shape = eqn.invars[0].aval.shape
    insert = [d for d in range(len(shape)) if d not in bdims]
    # an input dim can also be MOVED (bdims not ascending is illegal in
    # lax, so positions are ascending — Unsqueeze composes correctly)
    if insert:
        x = ctx.emit1("Unsqueeze", [x, ctx.i64(insert, "axes")])
    out = ctx.name_of(eqn.outvars[0])
    ctx.emit("Identity", [x], [out])
    interim = [1] * len(shape)
    for i, d in enumerate(bdims):
        interim[d] = in_shape[i]
    if tuple(interim) != tuple(shape):
        # register the pending stretch so non-broadcasting consumers
        # (Reshape/Concat/reduce/MatMul/outputs) materialize it
        ctx.deferred[out] = tuple(shape)


@_prim("concatenate")
def _concat(ctx, eqn):
    ctx.emit("Concat", [ctx.name_mat(v) for v in eqn.invars],
             [ctx.name_of(eqn.outvars[0])],
             axis=int(eqn.params["dimension"]))


@_prim("slice")
def _slice(ctx, eqn):
    p = eqn.params
    nd = len(p["start_indices"])
    strides = p["strides"] or (1,) * nd
    ctx.emit("Slice",
             [ctx.name_mat(eqn.invars[0]),
              ctx.i64(p["start_indices"], "starts"),
              ctx.i64(p["limit_indices"], "ends"),
              ctx.i64(range(nd), "axes"),
              ctx.i64(strides, "steps")],
             [ctx.name_of(eqn.outvars[0])])


@_prim("rev")
def _rev(ctx, eqn):
    dims = list(eqn.params["dimensions"])
    ctx.emit("Slice",
             [ctx.name_mat(eqn.invars[0]),
              ctx.i64([-1] * len(dims), "starts"),
              ctx.i64([INT64_MIN] * len(dims), "ends"),
              ctx.i64(dims, "axes"),
              ctx.i64([-1] * len(dims), "steps")],
             [ctx.name_of(eqn.outvars[0])])


@_prim("pad")
def _pad(ctx, eqn):
    cfg = eqn.params["padding_config"]
    if any(i != 0 for _, _, i in cfg):
        raise NotImplementedError("interior (dilation) padding")
    if any(lo < 0 or hi < 0 for lo, hi, _ in cfg):
        raise NotImplementedError("negative padding")
    pads = [lo for lo, _, _ in cfg] + [hi for _, hi, _ in cfg]
    ctx.emit("Pad",
             [ctx.name_mat(eqn.invars[0]), ctx.i64(pads, "pads"),
              ctx.name_of(eqn.invars[1])],
             [ctx.name_of(eqn.outvars[0])])


@_prim("iota")
def _iota(ctx, eqn):
    p = eqn.params
    # the iota is baked as a constant at trace-time sizes, so a dim that
    # the caller declared dynamic would silently be pinned to its
    # placeholder prime — fail loudly instead (ADVICE r3)
    hits = [d for d in p["shape"]
            if any(d % q == 0 for q in ctx.dynamic_sizes)]
    if hits:
        raise NotImplementedError(
            f"iota over dynamic dims {hits}: the exported constant "
            "would pin the dynamic dim to its trace-time size")
    arr = np.asarray(
        jax.lax.iota(p["dtype"], int(np.prod(p["shape"])))
        if len(p["shape"]) == 1 else
        jax.lax.broadcasted_iota(p["dtype"], p["shape"], p["dimension"]))
    ctx.emit("Identity", [ctx.const(arr, "iota")],
             [ctx.name_of(eqn.outvars[0])])


def _reduce(op, axes_as_input):
    def h(ctx, eqn):
        axes = list(eqn.params["axes"])
        x = ctx.name_mat(eqn.invars[0])
        out = ctx.name_of(eqn.outvars[0])
        if axes_as_input:  # ReduceSum since opset 13
            ctx.emit(op, [x, ctx.i64(axes, "axes")], [out], keepdims=0)
        else:
            ctx.emit(op, [x], [out], axes=axes, keepdims=0)
    return h


PRIMS["reduce_sum"] = _reduce("ReduceSum", True)
PRIMS["reduce_max"] = _reduce("ReduceMax", False)
PRIMS["reduce_min"] = _reduce("ReduceMin", False)
PRIMS["reduce_prod"] = _reduce("ReduceProd", False)


@_prim("argmax", "argmin")
def _argmax(ctx, eqn):
    op = "ArgMax" if eqn.primitive.name == "argmax" else "ArgMin"
    axes = eqn.params["axes"]
    if len(axes) != 1:
        raise NotImplementedError(f"{op} over multiple axes")
    a = ctx.emit1(op, [ctx.name_mat(eqn.invars[0])],
                  axis=int(axes[0]), keepdims=0)
    ctx.emit("Cast", [a], [ctx.name_of(eqn.outvars[0])],
             to=_onnx_dtype_of(eqn.outvars[0].aval))


@_prim("cumsum")
def _cumsum(ctx, eqn):
    ax = ctx.const(np.asarray(eqn.params["axis"], np.int64), "axis")
    if eqn.params.get("reverse"):
        raise NotImplementedError("reverse cumsum")
    ctx.emit("CumSum", [ctx.name_mat(eqn.invars[0]), ax],
             [ctx.name_of(eqn.outvars[0])])


@_prim("dot_general")
def _dot_general(ctx, eqn):
    (lc, rc), (lb, rb) = eqn.params["dimension_numbers"]
    lhs, rhs = eqn.invars
    ls, rs = lhs.aval.shape, rhs.aval.shape
    nl, nr = len(ls), len(rs)
    lfree = [d for d in range(nl) if d not in lc and d not in lb]
    rfree = [d for d in range(nr) if d not in rc and d not in rb]
    nb = len(lb)

    # Fast path: ONNX MatMul has numpy @ semantics — [..., m, k] @
    # [k, n] and leading-batch [..B.., m, k] @ [..B.., k, n] both map
    # directly, with NO reshapes (keeps symbolic batch dims symbolic).
    std = (tuple(lb) == tuple(range(nb))
           and tuple(rb) == tuple(range(nb))
           and tuple(lc) == (nl - 1,)
           and tuple(rc) == (nb,)
           and lfree == list(range(nb, nl - 1))
           and rfree == list(range(nb + 1, nr))
           and (nb == 0 and nr == 2 or nb > 0))
    ln, rn = ctx.name_mat(lhs), ctx.name_mat(rhs)
    out_aval = eqn.outvars[0].aval
    if std and nl >= 2:
        final = ctx.emit1("MatMul", [ln, rn])
    else:
        def prep(name, shape, batch, free, contract, contract_first):
            order = list(batch) + (list(contract) + list(free)
                                   if contract_first
                                   else list(free) + list(contract))
            if order != list(range(len(shape))):
                name = ctx.emit1("Transpose", [name], perm=order)
            b = int(np.prod([shape[d] for d in batch])) if batch \
                else None
            f = int(np.prod([shape[d] for d in free])) if free else 1
            c = int(np.prod([shape[d] for d in contract]))
            tgt = ([b] if b is not None else []) + \
                ([c, f] if contract_first else [f, c])
            return ctx.emit1(
                "Reshape", [name, ctx.i64(ctx.reshape_target(tgt))])

        a = prep(ln, ls, lb, lfree, lc, False)
        b = prep(rn, rs, rb, rfree, rc, True)
        mm = ctx.emit1("MatMul", [a, b])
        final = ctx.emit1(
            "Reshape", [mm, ctx.i64(ctx.reshape_target(out_aval.shape))])
    if jnp.dtype(out_aval.dtype) != jnp.dtype(lhs.aval.dtype):
        final = ctx.emit1("Cast", [final],
                          to=_onnx_dtype_of(out_aval))
    ctx.emit("Identity", [final], [ctx.name_of(eqn.outvars[0])])


@_prim("conv_general_dilated")
def _conv(ctx, eqn):
    p = eqn.params
    dn = p["dimension_numbers"]
    lhs_spec, rhs_spec, out_spec = dn.lhs_spec, dn.rhs_spec, dn.out_spec
    if any(d != 1 for d in p["lhs_dilation"]):
        raise NotImplementedError("transposed conv (lhs_dilation)")
    if p.get("batch_group_count", 1) != 1:
        raise NotImplementedError("batch_group_count")
    x = ctx.name_mat(eqn.invars[0])
    w = ctx.name_mat(eqn.invars[1])
    nsp = len(lhs_spec) - 2
    # to NCHW / OIHW
    if list(lhs_spec) != list(range(nsp + 2)):
        x = ctx.emit1("Transpose", [x], perm=list(lhs_spec))
    if list(rhs_spec) != list(range(nsp + 2)):
        w = ctx.emit1("Transpose", [w], perm=list(rhs_spec))
    pads = [lo for lo, _ in p["padding"]] + \
        [hi for _, hi in p["padding"]]
    y = ctx.emit1("Conv", [x, w],
                  strides=list(p["window_strides"]),
                  pads=pads,
                  dilations=list(p["rhs_dilation"]),
                  group=int(p["feature_group_count"]))
    # from NCHW to out_spec
    inv = [0] * (nsp + 2)
    for logical, physical in enumerate(out_spec):
        inv[physical] = logical
    if inv != list(range(nsp + 2)):
        y = ctx.emit1("Transpose", [y], perm=inv)
    ctx.emit("Identity", [y], [ctx.name_of(eqn.outvars[0])])


def _pool(ctx, eqn, op, extra_attrs):
    p = eqn.params
    wd = list(p["window_dimensions"])
    ws = list(p["window_strides"])
    pad = list(p["padding"])
    if any(d != 1 for d in p.get("base_dilation", (1,) * len(wd))) or \
            any(d != 1 for d in p.get("window_dilation",
                                      (1,) * len(wd))):
        raise NotImplementedError("dilated pooling")
    spatial = [i for i, d in enumerate(wd) if d != 1 or ws[i] != 1
               or pad[i] != (0, 0)]
    if not spatial:
        # degenerate 1x1 window (e.g. adaptive pool when the input is
        # already the target size): the reduction is an identity
        return ctx.name_mat(eqn.invars[0]), [1]
    passive = [i for i in range(len(wd)) if i not in spatial]
    if len(passive) != 2:
        raise NotImplementedError(f"pool layout wd={wd}")
    x = ctx.name_mat(eqn.invars[0])
    order = passive + spatial  # -> NC + spatial
    if order != list(range(len(wd))):
        x = ctx.emit1("Transpose", [x], perm=order)
    pads = [pad[i][0] for i in spatial] + [pad[i][1] for i in spatial]
    y = ctx.emit1(op, [x],
                  kernel_shape=[wd[i] for i in spatial],
                  strides=[ws[i] for i in spatial],
                  pads=pads, **extra_attrs)
    inv = [0] * len(order)
    for a, b in enumerate(order):
        inv[b] = a
    if inv != list(range(len(wd))):
        y = ctx.emit1("Transpose", [y], perm=inv)
    return y, [wd[i] for i in spatial]


@_prim("reduce_window_max")
def _maxpool(ctx, eqn):
    y, _ = _pool(ctx, eqn, "MaxPool", {})
    ctx.emit("Identity", [y], [ctx.name_of(eqn.outvars[0])])


@_prim("reduce_window_sum")
def _sumpool(ctx, eqn):
    y, kshape = _pool(ctx, eqn, "AveragePool",
                      {"count_include_pad": 1})
    scale = ctx.const(
        np.asarray(np.prod(kshape),
                   _np_dtype(eqn.invars[0].aval)), "winsz")
    ctx.emit("Mul", [y, scale], [ctx.name_of(eqn.outvars[0])])


@_prim("gather")
def _gather(ctx, eqn):
    # embedding-style take along axis 0: operand [V, ...], int indices
    p = eqn.params
    dn = p["dimension_numbers"]
    operand, indices = eqn.invars
    oshape = operand.aval.shape
    ishape = indices.aval.shape
    ss = tuple(p["slice_sizes"])
    if (tuple(dn.start_index_map) == (0,)
            and tuple(dn.collapsed_slice_dims) == (0,)
            and ss == (1,) + tuple(oshape[1:])
            and ishape and ishape[-1] == 1):
        idx = ctx.emit1(
            "Squeeze",
            [ctx.name_mat(indices),
             ctx.i64([len(ishape) - 1], "axes")])
        idx64 = ctx.emit1("Cast", [idx], to=proto.INT64)
        ctx.emit("Gather", [ctx.name_mat(operand), idx64],
                 [ctx.name_of(eqn.outvars[0])], axis=0)
        return
    raise NotImplementedError(
        "general gather (only embedding-style take is exported)")


@_prim("dynamic_slice")
def _dynamic_slice(ctx, eqn):
    x = eqn.invars[0]
    starts = eqn.invars[1:]
    sizes = eqn.params["slice_sizes"]
    nd = len(sizes)
    parts = []
    for s in starts:
        c = ctx.emit1("Cast", [ctx.name_of(s)], to=proto.INT64)
        parts.append(ctx.emit1(
            "Reshape", [c, ctx.i64([1], "one")]))
    start_cat = ctx.emit1("Concat", parts, axis=0)
    # lax.dynamic_slice CLAMPS the start so the output keeps its full
    # size; ONNX Slice clamps the END and would SHRINK the output —
    # clamp starts to [0, dim - size] first (static dims from the aval)
    maxs = [int(d) - int(s) for d, s in zip(x.aval.shape, sizes)]
    start_cl = ctx.emit1(
        "Clip", [start_cat, ctx.i64([0] * nd, "zero"),
                 ctx.i64(maxs, "maxstart")])
    ends = ctx.emit1("Add", [start_cl, ctx.i64(sizes, "sizes")])
    ctx.emit("Slice",
             [ctx.name_mat(x), start_cl, ends,
              ctx.i64(range(nd), "axes")],
             [ctx.name_of(eqn.outvars[0])])


# higher-order primitives: inline the inner jaxpr
def _inline(ctx, inner_closed, invals, outvars):
    inner = inner_closed.jaxpr
    for cv, cval in zip(inner.constvars, inner_closed.consts):
        ctx.set_name(cv, ctx.const(np.asarray(cval), "const"))
    for iv, nm in zip(inner.invars, invals):
        ctx.set_name(iv, nm)
    _convert_eqns(ctx, inner)
    for ov, outer in zip(inner.outvars, outvars):
        ctx.emit_identity(ctx.name_of(ov), ctx.name_of(outer))


@_prim("pjit", "jit", "closed_call", "core_call", "xla_call")
def _pjit(ctx, eqn):
    _inline(ctx, eqn.params["jaxpr"],
            [ctx.name_of(v) for v in eqn.invars], eqn.outvars)


@_prim("custom_jvp_call", "custom_vjp_call", "custom_vjp_call_jaxpr",
       "custom_jvp_call_jaxpr")
def _custom_call(ctx, eqn):
    inner = eqn.params.get("call_jaxpr") or eqn.params.get("fun_jaxpr")
    if inner is None:
        raise NotImplementedError(
            f"{eqn.primitive.name} without call_jaxpr")
    _inline(ctx, inner, [ctx.name_of(v) for v in eqn.invars],
            eqn.outvars)


@_prim("remat", "checkpoint", "remat2")
def _remat(ctx, eqn):
    inner = eqn.params["jaxpr"]
    closed = jcore.ClosedJaxpr(inner, ())
    _inline(ctx, closed, [ctx.name_of(v) for v in eqn.invars],
            eqn.outvars)


def _convert_eqns(ctx: _Ctx, jaxpr):
    for eqn in jaxpr.eqns:
        for ov in eqn.outvars:
            if ov not in ctx.names:
                ctx.set_name(ov, ctx.fresh("v"))
        h = PRIMS.get(eqn.primitive.name)
        if h is None:
            raise NotImplementedError(
                f"no ONNX lowering for primitive "
                f"'{eqn.primitive.name}' — this exporter covers "
                f"inference graphs (conv/pool/matmul/elementwise); "
                f"use paddle_tpu.jit.save for StableHLO export of "
                f"anything else")
        h(ctx, eqn)


def jaxpr_to_model(closed_jaxpr, input_names: Sequence[str],
                   input_dims: Sequence[Sequence],
                   graph_name: str = "paddle_tpu",
                   opset: int = 13,
                   dynamic_sizes: Sequence[int] = ()) -> bytes:
    """Convert a ClosedJaxpr to serialized ONNX ModelProto bytes.

    input_dims entries may contain strings (symbolic dim_params) in
    place of ints — declared in the ValueInfo, and when the symbolic
    dim was traced at a size from ``dynamic_sizes``, occurrences of
    that size inside Reshape targets are emitted as -1 so the graph
    stays batch-size agnostic."""
    jaxpr = closed_jaxpr.jaxpr
    ctx = _Ctx(dynamic_sizes=dynamic_sizes)
    for cv, cval in zip(jaxpr.constvars, closed_jaxpr.consts):
        ctx.set_name(cv, ctx.const(np.asarray(cval), "w"))
    inputs = []
    for iv, nm, dims in zip(jaxpr.invars, input_names, input_dims):
        ctx.set_name(iv, nm)
        inputs.append(proto.value_info(
            nm, _onnx_dtype_of(iv.aval), dims))
    _convert_eqns(ctx, jaxpr)
    outputs = []
    dyn = {s: f"dyn_{s}" for s in ctx.dynamic_sizes}
    for i, ov in enumerate(jaxpr.outvars):
        nm = f"output_{i}"
        # outputs must carry their full aval shape (materialize any
        # deferred broadcast), declared with symbolic dims where the
        # traced placeholder size appears
        ctx.emit("Identity", [ctx.name_mat(ov)], [nm])
        outputs.append(proto.value_info(
            nm, _onnx_dtype_of(ov.aval),
            [dyn.get(int(d), int(d)) for d in ov.aval.shape]))
    g = proto.graph(ctx.nodes, graph_name, inputs, outputs,
                    ctx.initializers)
    return proto.model(g, opset=opset)
