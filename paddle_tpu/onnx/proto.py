"""Minimal ONNX protobuf wire format: writer + reader, no deps.

Reference analog: python/paddle/onnx/export.py delegates to the
external paddle2onnx package; this environment has neither that nor the
`onnx` python package, so the exporter serializes the ONNX protobuf
itself. Field numbers and enum values below were extracted from the
authoritative FileDescriptorProto embedded in libtorch_cpu.so's
compiled onnx_onnx_torch-ml.proto (see
tests/test_onnx_export.py::test_schema_matches_libtorch_descriptor,
which re-extracts and cross-checks them), not recalled from memory.

Only the subset of messages the exporter emits is implemented:
ModelProto, GraphProto, NodeProto, AttributeProto, TensorProto,
ValueInfoProto, TypeProto(.Tensor), TensorShapeProto(.Dimension),
OperatorSetIdProto, StringStringEntryProto.
"""
from __future__ import annotations

import struct
from typing import Any, Dict, List, Optional, Sequence, Tuple, Union

import numpy as np

# TensorProto.DataType
FLOAT, UINT8, INT8, UINT16, INT16, INT32, INT64 = 1, 2, 3, 4, 5, 6, 7
STRING, BOOL, FLOAT16, DOUBLE, UINT32, UINT64 = 8, 9, 10, 11, 12, 13
BFLOAT16 = 16

# AttributeProto.AttributeType
ATTR_FLOAT, ATTR_INT, ATTR_STRING, ATTR_TENSOR, ATTR_GRAPH = 1, 2, 3, 4, 5
ATTR_FLOATS, ATTR_INTS, ATTR_STRINGS = 6, 7, 8

NP2ONNX = {
    np.dtype(np.float32): FLOAT, np.dtype(np.uint8): UINT8,
    np.dtype(np.int8): INT8, np.dtype(np.uint16): UINT16,
    np.dtype(np.int16): INT16, np.dtype(np.int32): INT32,
    np.dtype(np.int64): INT64, np.dtype(np.bool_): BOOL,
    np.dtype(np.float16): FLOAT16, np.dtype(np.float64): DOUBLE,
    np.dtype(np.uint32): UINT32, np.dtype(np.uint64): UINT64,
}

ONNX2NP = {v: k for k, v in NP2ONNX.items()}


def onnx_dtype(np_dtype) -> int:
    if str(np_dtype) == "bfloat16":
        return BFLOAT16
    try:
        return NP2ONNX[np.dtype(np_dtype)]
    except KeyError:
        raise NotImplementedError(f"no ONNX dtype for {np_dtype}")


# ---------------------------------------------------------------------------
# writer
# ---------------------------------------------------------------------------

def _varint(v: int) -> bytes:
    if v < 0:  # proto int64: 10-byte two's complement
        v += 1 << 64
    out = bytearray()
    while True:
        b = v & 0x7F
        v >>= 7
        if v:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


class Msg:
    """Append-only protobuf message writer."""

    __slots__ = ("buf",)

    def __init__(self):
        self.buf = bytearray()

    def int(self, field: int, v: int) -> "Msg":
        self.buf += _varint(field << 3 | 0) + _varint(int(v))
        return self

    def float32(self, field: int, v: float) -> "Msg":
        self.buf += _varint(field << 3 | 5) + struct.pack("<f", v)
        return self

    def bytes_(self, field: int, b: bytes) -> "Msg":
        self.buf += _varint(field << 3 | 2) + _varint(len(b)) + b
        return self

    def str(self, field: int, s: str) -> "Msg":
        return self.bytes_(field, s.encode("utf-8"))

    def msg(self, field: int, m: "Msg") -> "Msg":
        return self.bytes_(field, bytes(m.buf))

    def __bytes__(self):
        return bytes(self.buf)


def tensor_proto(name: str, arr) -> Msg:
    """TensorProto from a numpy (or bfloat16 jax) array via raw_data."""
    t = Msg()
    shape = arr.shape
    if str(arr.dtype) == "bfloat16":
        dt = BFLOAT16
        raw = np.asarray(arr).view(np.uint16).tobytes()
    else:
        arr = np.ascontiguousarray(np.asarray(arr))
        dt = onnx_dtype(arr.dtype)
        raw = arr.tobytes()
    for d in shape:
        t.int(1, d)
    t.int(2, dt)
    t.str(8, name)
    t.bytes_(9, raw)
    return t


def value_info(name: str, elem_type: int,
               shape: Sequence[Union[int, str]]) -> Msg:
    tt = Msg().int(1, elem_type)
    sh = Msg()
    for d in shape:
        dim = Msg()
        if isinstance(d, str):
            dim.str(2, d)      # dim_param (symbolic)
        else:
            dim.int(1, int(d))  # dim_value
        sh.msg(1, dim)
    tt.msg(2, sh)
    tp = Msg().msg(1, tt)      # TypeProto.tensor_type
    vi = Msg().str(1, name).msg(2, tp)
    return vi


def attribute(name: str, v) -> Msg:
    a = Msg().str(1, name)
    if isinstance(v, float):
        a.float32(2, v).int(20, ATTR_FLOAT)
    elif isinstance(v, bool):
        a.int(3, int(v)).int(20, ATTR_INT)
    elif isinstance(v, int):
        a.int(3, v).int(20, ATTR_INT)
    elif isinstance(v, str):
        a.bytes_(4, v.encode()).int(20, ATTR_STRING)
    elif isinstance(v, bytes):
        a.bytes_(4, v).int(20, ATTR_STRING)
    elif isinstance(v, Msg):  # pre-built TensorProto
        a.msg(5, v).int(20, ATTR_TENSOR)
    elif isinstance(v, (list, tuple)) and v and isinstance(v[0], float):
        for x in v:
            a.float32(7, x)
        a.int(20, ATTR_FLOATS)
    elif isinstance(v, (list, tuple)):
        for x in v:
            a.int(8, int(x))
        a.int(20, ATTR_INTS)
    else:
        raise NotImplementedError(f"attribute {name}={v!r}")
    return a


def node(op_type: str, inputs: Sequence[str], outputs: Sequence[str],
         name: str = "", **attrs) -> Msg:
    n = Msg()
    for i in inputs:
        n.str(1, i)
    for o in outputs:
        n.str(2, o)
    if name:
        n.str(3, name)
    n.str(4, op_type)
    for k in sorted(attrs):
        n.msg(5, attribute(k, attrs[k]))
    return n


def graph(nodes: Sequence[Msg], name: str,
          inputs: Sequence[Msg], outputs: Sequence[Msg],
          initializers: Sequence[Msg] = ()) -> Msg:
    g = Msg()
    for n in nodes:
        g.msg(1, n)
    g.str(2, name)
    for t in initializers:
        g.msg(5, t)
    for vi in inputs:
        g.msg(11, vi)
    for vo in outputs:
        g.msg(12, vo)
    return g


def model(graph_msg: Msg, opset: int = 13,
          producer: str = "paddle_tpu") -> bytes:
    m = Msg()
    m.int(1, 8)  # ir_version 8 (onnx 1.13 era; pairs with opset 13)
    m.str(2, producer)
    m.str(3, "0.1")
    opset_id = Msg().str(1, "").int(2, opset)
    m.msg(7, graph_msg)
    m.msg(8, opset_id)
    return bytes(m)


# ---------------------------------------------------------------------------
# reader (for tests / the bundled evaluator)
# ---------------------------------------------------------------------------

def read_fields(b: bytes) -> List[Tuple[int, int, Any]]:
    """[(field_number, wire_type, raw_value)] — varints as int, length-
    delimited as bytes, fixed32/64 as raw bytes."""
    out = []
    i = 0
    n = len(b)
    while i < n:
        tag, i = _read_varint(b, i)
        num, wt = tag >> 3, tag & 7
        if wt == 0:
            v, i = _read_varint(b, i)
        elif wt == 2:
            ln, i = _read_varint(b, i)
            v = b[i:i + ln]
            i += ln
        elif wt == 5:
            v = b[i:i + 4]
            i += 4
        elif wt == 1:
            v = b[i:i + 8]
            i += 8
        else:
            raise ValueError(f"wire type {wt}")
        out.append((num, wt, v))
    return out


def _read_varint(b: bytes, i: int) -> Tuple[int, int]:
    v = 0
    s = 0
    while True:
        x = b[i]
        i += 1
        v |= (x & 0x7F) << s
        if not x & 0x80:
            if v >= 1 << 63:  # negative int64
                v -= 1 << 64
            return v, i
        s += 7


def _group(b: bytes) -> Dict[int, list]:
    d: Dict[int, list] = {}
    for num, wt, v in read_fields(b):
        d.setdefault(num, []).append((wt, v))
    return d


def _first(d, num, default=None):
    return d[num][0][1] if num in d else default


class DecodedTensor:
    def __init__(self, b: bytes):
        d = _group(b)
        self.dims = tuple(v for wt, v in d.get(1, ()))
        self.data_type = _first(d, 2, 0)
        self.name = _first(d, 8, b"").decode()
        raw = _first(d, 9)
        if raw is not None:
            if self.data_type == BFLOAT16:
                u16 = np.frombuffer(raw, np.uint16).reshape(self.dims)
                self.array = (u16.astype(np.uint32) << 16).view(
                    np.float32).astype(np.float32)
            else:
                self.array = np.frombuffer(
                    raw, ONNX2NP[self.data_type]).reshape(self.dims)
        else:  # int64_data/float_data fallbacks
            if self.data_type == INT64:
                vals = [v for wt, v in d.get(7, ())]
            elif self.data_type == FLOAT:
                vals = [struct.unpack("<f", v)[0]
                        for wt, v in d.get(4, ())]
            else:
                raise NotImplementedError(
                    f"tensor data fields for dtype {self.data_type}")
            self.array = np.asarray(vals, ONNX2NP[self.data_type]) \
                .reshape(self.dims)


class DecodedAttr:
    def __init__(self, b: bytes):
        d = _group(b)
        self.name = _first(d, 1, b"").decode()
        ty = _first(d, 20, 0)
        if ty == ATTR_FLOAT:
            self.value = struct.unpack("<f", _first(d, 2))[0]
        elif ty == ATTR_INT:
            self.value = _first(d, 3)
        elif ty == ATTR_STRING:
            self.value = _first(d, 4).decode()
        elif ty == ATTR_TENSOR:
            self.value = DecodedTensor(_first(d, 5))
        elif ty == ATTR_FLOATS:
            self.value = [struct.unpack("<f", v)[0]
                          for wt, v in d.get(7, ())]
        elif ty == ATTR_INTS:
            self.value = [v for wt, v in d.get(8, ())]
        else:
            raise NotImplementedError(f"attr type {ty}")


class DecodedNode:
    def __init__(self, b: bytes):
        d = _group(b)
        self.inputs = [v.decode() for wt, v in d.get(1, ())]
        self.outputs = [v.decode() for wt, v in d.get(2, ())]
        self.name = _first(d, 3, b"").decode()
        self.op_type = _first(d, 4, b"").decode()
        self.attrs = {a.name: a.value
                      for a in (DecodedAttr(v) for wt, v in d.get(5, ()))}


class DecodedValueInfo:
    def __init__(self, b: bytes):
        d = _group(b)
        self.name = _first(d, 1, b"").decode()
        tp = _group(_first(d, 2, b""))
        tt = _group(_first(tp, 1, b""))
        self.elem_type = _first(tt, 1, 0)
        self.shape = []
        sh = _first(tt, 2)
        if sh is not None:
            for wt, v in _group(sh).get(1, ()):
                dd = _group(v)
                if 1 in dd:
                    self.shape.append(_first(dd, 1))
                else:
                    self.shape.append(_first(dd, 2, b"?").decode())


class DecodedGraph:
    def __init__(self, b: bytes):
        d = _group(b)
        self.name = _first(d, 2, b"").decode()
        self.nodes = [DecodedNode(v) for wt, v in d.get(1, ())]
        self.initializers = {t.name: t.array for t in
                             (DecodedTensor(v) for wt, v in d.get(5, ()))}
        self.inputs = [DecodedValueInfo(v) for wt, v in d.get(11, ())]
        self.outputs = [DecodedValueInfo(v) for wt, v in d.get(12, ())]


class DecodedModel:
    def __init__(self, b: bytes):
        d = _group(b)
        self.ir_version = _first(d, 1, 0)
        self.producer = _first(d, 2, b"").decode()
        self.graph = DecodedGraph(_first(d, 7, b""))
        self.opsets = {}
        for wt, v in d.get(8, ()):
            od = _group(v)
            self.opsets[_first(od, 1, b"").decode()] = _first(od, 2, 0)


def load(path_or_bytes) -> DecodedModel:
    if isinstance(path_or_bytes, (bytes, bytearray)):
        return DecodedModel(bytes(path_or_bytes))
    with open(path_or_bytes, "rb") as f:
        return DecodedModel(f.read())
