"""Single-pass Pallas quantize kernel vs the two-pass XLA reference.

The kernels (`ops/quant_matmul.py::_rowq_kernel/_colq_kernel`) must be
bit-identical to `quantize_rowwise`: same amax, same round-half-even,
same clip. Run under interpret=True on the CPU mesh; the real-TPU
engagement is exercised by bench_gpt_hybrid (quant8 defaults).
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu.ops.quant_matmul import (quantize_rowwise,
                                         quantize_rowwise_fast)


def _check(x, axis):
    q0, s0 = quantize_rowwise(x, axis)
    q1, s1 = quantize_rowwise_fast(x, axis, interpret=True)
    # XLA may fold /127.0 to a reciprocal multiply on one path and not
    # the other — allow 1 ULP on the scale, which can shift a value
    # sitting exactly on a rounding boundary by one quantization step
    np.testing.assert_allclose(np.asarray(s0), np.asarray(s1),
                               rtol=1e-6)
    dq = np.abs(np.asarray(q0, np.int32) - np.asarray(q1, np.int32))
    assert dq.max() <= 1 and (dq != 0).mean() < 0.01
    assert q1.dtype == jnp.int8 and s1.shape == s0.shape


@pytest.mark.parametrize("dtype", [jnp.bfloat16, jnp.float32])
def test_row_quantize_2d(dtype):
    x = jax.random.normal(jax.random.key(0), (64, 256), dtype)
    _check(x, axis=-1)
    _check(x, axis=1)


def test_row_quantize_3d():
    x = jax.random.normal(jax.random.key(1), (4, 16, 384), jnp.bfloat16)
    _check(x, axis=-1)


def test_col_quantize_weight():
    w = jax.random.normal(jax.random.key(2), (256, 384), jnp.bfloat16)
    _check(w, axis=0)


def test_zero_row_scale_is_one():
    x = jnp.zeros((16, 128), jnp.float32).at[0, 0].set(3.0)
    q, s = quantize_rowwise_fast(x, axis=-1, interpret=True)
    np.testing.assert_allclose(np.asarray(s[1:]),
                               np.full((15, 1), 1.0 / 127.0, np.float32),
                               rtol=0, atol=0)
    assert int(q[0, 0]) == 127


def test_unaligned_shapes_fall_back():
    # lane-unaligned K and odd row counts must route to the XLA path
    x = jax.random.normal(jax.random.key(3), (7, 100), jnp.float32)
    q0, s0 = quantize_rowwise(x, -1)
    q1, s1 = quantize_rowwise_fast(x, -1, interpret=True)
    np.testing.assert_array_equal(np.asarray(q0), np.asarray(q1))
    np.testing.assert_array_equal(np.asarray(s0), np.asarray(s1))
