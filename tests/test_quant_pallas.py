"""Single-pass Pallas quantize kernel vs the two-pass XLA reference.

The kernels (`ops/quant_matmul.py::_rowq_kernel/_colq_kernel`) must be
bit-identical to `quantize_rowwise`: same amax, same round-half-even,
same clip. Run under interpret=True on the CPU mesh; the real-TPU
engagement is exercised by bench_gpt_hybrid (quant8 defaults).
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu.ops.quant_matmul import (quantize_rowwise,
                                         quantize_rowwise_fast)


def _check(x, axis):
    q0, s0 = quantize_rowwise(x, axis)
    q1, s1 = quantize_rowwise_fast(x, axis, interpret=True)
    # XLA may fold /127.0 to a reciprocal multiply on one path and not
    # the other — allow 1 ULP on the scale, which can shift a value
    # sitting exactly on a rounding boundary by one quantization step
    np.testing.assert_allclose(np.asarray(s0), np.asarray(s1),
                               rtol=1e-6)
    dq = np.abs(np.asarray(q0, np.int32) - np.asarray(q1, np.int32))
    assert dq.max() <= 1 and (dq != 0).mean() < 0.01
    assert q1.dtype == jnp.int8 and s1.shape == s0.shape


@pytest.mark.parametrize("dtype", [jnp.bfloat16, jnp.float32])
def test_row_quantize_2d(dtype):
    x = jax.random.normal(jax.random.key(0), (64, 256), dtype)
    _check(x, axis=-1)
    _check(x, axis=1)


def test_row_quantize_3d():
    x = jax.random.normal(jax.random.key(1), (4, 16, 384), jnp.bfloat16)
    _check(x, axis=-1)


def test_col_quantize_weight():
    w = jax.random.normal(jax.random.key(2), (256, 384), jnp.bfloat16)
    _check(w, axis=0)


def test_zero_row_scale_is_one():
    x = jnp.zeros((16, 128), jnp.float32).at[0, 0].set(3.0)
    q, s = quantize_rowwise_fast(x, axis=-1, interpret=True)
    np.testing.assert_allclose(np.asarray(s[1:]),
                               np.full((15, 1), 1.0 / 127.0, np.float32),
                               rtol=0, atol=0)
    assert int(q[0, 0]) == 127


def test_unaligned_shapes_fall_back():
    # lane-unaligned K and odd row counts must route to the XLA path
    x = jax.random.normal(jax.random.key(3), (7, 100), jnp.float32)
    q0, s0 = quantize_rowwise(x, -1)
    q1, s1 = quantize_rowwise_fast(x, -1, interpret=True)
    np.testing.assert_array_equal(np.asarray(q0), np.asarray(q1))
    np.testing.assert_array_equal(np.asarray(s0), np.asarray(s1))


# -- stochastic-rounding column quantize + int8 wgrad (round 4) ----------

def test_sr_colwise_unbiased_xla_path():
    from paddle_tpu.ops.quant_matmul import _sr_colq_xla
    x = jax.random.normal(jax.random.key(7), (64, 128), jnp.float32)
    acc = np.zeros(x.shape, np.float64)
    n = 96
    for s in range(n):
        q, sc = _sr_colq_xla(x, jnp.int32(s))
        assert q.dtype == jnp.int8 and sc.shape == (1, 128)
        acc += np.asarray(q.astype(jnp.float32) * sc, np.float64)
    acc /= n
    lsb = np.asarray(jnp.max(jnp.abs(x), axis=0) / 127.0).mean()
    bias = np.abs(acc - np.asarray(x)).mean()
    # SR noise is +-0.5 LSB uniform; averaging n draws leaves
    # ~LSB/sqrt(12 n) — assert within 4x of that
    assert bias < 4 * lsb / np.sqrt(12 * n)


def test_sr_colwise_zero_column_scale_is_one():
    from paddle_tpu.ops.quant_matmul import _sr_colq_xla
    x = jnp.zeros((16, 128), jnp.float32).at[3, 5].set(-2.0)
    q, s = _sr_colq_xla(x, jnp.int32(0))
    cols = np.asarray(s)[0]
    assert cols[5] == np.float32(2.0 / 127.0)
    others = np.delete(cols, 5)
    np.testing.assert_allclose(others, 1.0 / 127.0, rtol=1e-6)
    assert int(q[3, 5]) in (-127, -126)  # SR can round either way


def test_int8_linear_all8_grads_close_and_unbiased():
    from paddle_tpu.ops.quant_matmul import int8_linear_all8
    kx, kw, kg = jax.random.split(jax.random.key(3), 3)
    x = jax.random.normal(kx, (4, 32, 128), jnp.float32)
    w = jax.random.normal(kw, (128, 256), jnp.float32) * 0.1
    g = jax.random.normal(kg, (4, 32, 256), jnp.float32)

    def f8(x, w, s):
        return jnp.sum(int8_linear_all8(x, w, s) * g)

    def fe(x, w):
        return jnp.sum(jnp.einsum("btd,df->btf", x, w) * g)

    dx8, dw8, ds = jax.grad(f8, argnums=(0, 1, 2), allow_int=True)(
        x, w, jnp.int32(5))
    dxe, dwe = jax.grad(fe, argnums=(0, 1))(x, w)
    assert float(jnp.linalg.norm(dw8 - dwe) / jnp.linalg.norm(dwe)) < 0.06
    assert float(jnp.linalg.norm(dx8 - dxe) / jnp.linalg.norm(dxe)) < 0.06
    assert ds.dtype == jax.dtypes.float0  # seed carries no gradient

    # unbiasedness: averaging wgrad over seeds converges to exact
    acc = np.zeros(dwe.shape, np.float64)
    n = 48
    for s in range(n):
        _, dws, _ = jax.grad(f8, argnums=(0, 1, 2), allow_int=True)(
            x, w, jnp.int32(s))
        acc += np.asarray(dws, np.float64)
    acc /= n
    bias = float(np.linalg.norm(acc - np.asarray(dwe)) /
                 np.linalg.norm(dwe))
    per_draw = float(jnp.linalg.norm(dw8 - dwe) / jnp.linalg.norm(dwe))
    assert bias < 3 * per_draw / np.sqrt(n)


def test_wgrad_trainer_smoke_cpu():
    # quant8="wgrad" end-to-end on the CPU mesh: runs, loss finite,
    # close to the exact-bf16 step at tiny scale
    from paddle_tpu.models.gpt import GPTConfig, GPTSpmdTrainer, build_mesh
    cfg = GPTConfig(vocab_size=512, hidden_size=128, num_layers=2,
                    num_heads=2, max_seq_len=64, dtype=jnp.float32)
    mesh = build_mesh(n_devices=1, pipe=1, model=1, fsdp=1, sep=1)
    rng = np.random.RandomState(0)
    ids = rng.randint(0, 512, (2, 64)).astype(np.int32)
    labels = np.roll(ids, -1, 1)
    losses = {}
    for q8 in (False, "wgrad"):
        tr = GPTSpmdTrainer(cfg, mesh, microbatches=1, remat=False,
                            quant8=q8, seed=0, use_flash=False)
        for _ in range(3):
            loss = tr.train_step(ids, labels)
        losses[q8] = float(jax.device_get(loss))
    assert np.isfinite(losses["wgrad"])
    assert abs(losses["wgrad"] - losses[False]) < 0.05


def test_wgrad_trainer_no_tracer_leak():
    # Tracing the step must not leave traced state on the trainer: a
    # later direct _forward_loss trace (the parity harness pattern)
    # would hit UnexpectedTracerError if step() mutated self with a
    # tracer (round-4 review finding).
    from paddle_tpu.models.gpt import GPTConfig, GPTSpmdTrainer, build_mesh
    cfg = GPTConfig(vocab_size=256, hidden_size=128, num_layers=2,
                    num_heads=2, max_seq_len=32, dtype=jnp.float32)
    mesh = build_mesh(n_devices=1, pipe=1, model=1, fsdp=1, sep=1)
    tr = GPTSpmdTrainer(cfg, mesh, microbatches=1, remat=False,
                        quant8="wgrad", seed=0, use_flash=False)
    rng = np.random.RandomState(0)
    ids = rng.randint(0, 256, (2, 32)).astype(np.int32)
    labels = np.roll(ids, -1, 1)
    tr.train_step(ids, labels)
    with jax.set_mesh(mesh):
        loss, g = jax.jit(jax.value_and_grad(tr._forward_loss))(
            tr.params, jnp.asarray(ids), jnp.asarray(labels))
    assert np.isfinite(float(jax.device_get(loss)))


def test_wgrad_microbatches_fold_seed():
    # M>1 path: runs, and distinct microbatch streams change nothing
    # about correctness (loss finite, near exact)
    from paddle_tpu.models.gpt import GPTConfig, GPTSpmdTrainer, build_mesh
    cfg = GPTConfig(vocab_size=256, hidden_size=128, num_layers=2,
                    num_heads=2, max_seq_len=32, dtype=jnp.float32)
    mesh = build_mesh(n_devices=1, pipe=1, model=1, fsdp=1, sep=1)
    tr = GPTSpmdTrainer(cfg, mesh, microbatches=2, remat=False,
                        quant8="wgrad", seed=0, use_flash=False)
    rng = np.random.RandomState(0)
    ids = rng.randint(0, 256, (4, 32)).astype(np.int32)
    labels = np.roll(ids, -1, 1)
    for _ in range(2):
        loss = tr.train_step(ids, labels)
    assert np.isfinite(float(jax.device_get(loss)))


# -- round-5 producer-fused gelu->quantize (lever d) -------------------

def test_act_fused_rowq_matches_gelu_then_quant():
    import jax
    import jax.numpy as jnp
    from paddle_tpu.ops.quant_matmul import (quantize_rowwise,
                                             quantize_rowwise_fast)
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(16, 256).astype(np.float32))
    q1, s1 = quantize_rowwise_fast(x, axis=-1, act="gelu",
                                   interpret=True)
    q2, s2 = quantize_rowwise(jax.nn.gelu(x, approximate=True), -1)
    np.testing.assert_allclose(np.asarray(s1), np.asarray(s2),
                               rtol=1e-5)
    # rounding at +-0.5 boundaries may flip the odd value
    assert (np.asarray(q1) == np.asarray(q2)).mean() > 0.999


def test_int8_gelu_linear_all8_matches_unfused():
    """Fused gelu+int8 matmul == int8_linear_all8(gelu(x)) in fwd and
    grads (same seeds -> same SR streams on the wgrad side; dgrad adds
    the gelu' chain)."""
    import jax
    import jax.numpy as jnp
    from paddle_tpu.ops.quant_matmul import (int8_gelu_linear_all8,
                                             int8_linear_all8)
    rng = np.random.RandomState(1)
    x = jnp.asarray(rng.randn(32, 128).astype(np.float32))
    w = jnp.asarray(rng.randn(128, 192).astype(np.float32) * 0.1)
    seed = jnp.int32(17)

    def fused(x, w):
        return (int8_gelu_linear_all8(x, w, seed) ** 2).sum()

    def unfused(x, w):
        a = jax.nn.gelu(x, approximate=True)
        return (int8_linear_all8(a, w, seed) ** 2).sum()

    f1, (gx1, gw1) = jax.value_and_grad(fused, argnums=(0, 1))(x, w)
    f2, (gx2, gw2) = jax.value_and_grad(unfused, argnums=(0, 1))(x, w)
    np.testing.assert_allclose(float(f1), float(f2), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(gw1), np.asarray(gw2),
                               rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(np.asarray(gx1), np.asarray(gx2),
                               rtol=1e-3, atol=1e-4)


# -- round-5 producer-fused LayerNorm->quantize (lever a) ---------------

def _ref_ln(x, g, b, eps=1e-5):
    xf = np.asarray(x, np.float32)
    m = xf.mean(-1, keepdims=True)
    v = xf.var(-1, keepdims=True)
    return (xf - m) / np.sqrt(v + eps) * np.asarray(g, np.float32) \
        + np.asarray(b, np.float32)


def test_ln_fused_rowq_matches_ln_then_quant():
    from paddle_tpu.ops.quant_matmul import (ln_quantize_rowwise,
                                             quantize_rowwise)
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(16, 256).astype(np.float32) * 3 + 0.5)
    g = jnp.asarray(rng.rand(256).astype(np.float32) + 0.5)
    b = jnp.asarray(rng.randn(256).astype(np.float32) * 0.1)
    q1, s1, m1, r1 = ln_quantize_rowwise(x, g, b, interpret=True)
    href = _ref_ln(x, g, b)
    q2, s2 = quantize_rowwise(jnp.asarray(href), -1)
    np.testing.assert_allclose(np.asarray(s1), np.asarray(s2),
                               rtol=1e-5)
    assert (np.asarray(q1) == np.asarray(q2)).mean() > 0.999
    np.testing.assert_allclose(np.asarray(m1)[:, 0],
                               np.asarray(x, np.float32).mean(-1),
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(
        np.asarray(r1)[:, 0],
        1.0 / np.sqrt(np.asarray(x, np.float32).var(-1) + 1e-5),
        rtol=1e-4)


def test_int8_ln_linear_all8_matches_unfused():
    """Fused LN+int8 matmul == int8_linear_all8(layer_norm(x)) in fwd
    and all four grads (x, ln gamma/beta, w); same seeds -> same SR
    streams on the wgrad side."""
    from paddle_tpu.ops.quant_matmul import (int8_ln_linear_all8,
                                             int8_linear_all8)
    rng = np.random.RandomState(1)
    x = jnp.asarray(rng.randn(32, 128).astype(np.float32))
    g = jnp.asarray(rng.rand(128).astype(np.float32) + 0.5)
    b = jnp.asarray(rng.randn(128).astype(np.float32) * 0.1)
    w = jnp.asarray(rng.randn(128, 192).astype(np.float32) * 0.1)
    seed = jnp.int32(17)

    def _ln(x, g, b, eps=1e-5):
        m = x.mean(-1, keepdims=True)
        v = ((x - m) ** 2).mean(-1, keepdims=True)
        return (x - m) * jax.lax.rsqrt(v + eps) * g + b

    def fused(x, g, b, w):
        return (int8_ln_linear_all8(x, g, b, w, seed) ** 2).sum()

    def unfused(x, g, b, w):
        return (int8_linear_all8(_ln(x, g, b), w, seed) ** 2).sum()

    f1, g1 = jax.value_and_grad(fused, argnums=(0, 1, 2, 3))(x, g, b, w)
    f2, g2 = jax.value_and_grad(unfused, argnums=(0, 1, 2, 3))(x, g, b, w)
    np.testing.assert_allclose(float(f1), float(f2), rtol=1e-5)
    for a1, a2 in zip(g1, g2):
        np.testing.assert_allclose(np.asarray(a1), np.asarray(a2),
                                   rtol=1e-3, atol=1e-3)


def test_sr_colq_ln_matches_ln_then_colq():
    from paddle_tpu.ops.quant_matmul import (sr_quantize_colwise,
                                             sr_quantize_colwise_ln)
    if jax.default_backend() in ("tpu", "axon"):
        pytest.skip("the fused/unfused SR kernels derive per-tile PRNG "
                    "seeds differently on TPU; the identical-stream "
                    "premise only holds on the shared XLA fallback")
    rng = np.random.RandomState(2)
    x = rng.randn(24, 128).astype(np.float32)
    g = rng.rand(128).astype(np.float32) + 0.5
    b = rng.randn(128).astype(np.float32) * 0.1
    m = x.mean(-1, keepdims=True)
    r = 1.0 / np.sqrt(x.var(-1, keepdims=True) + 1e-5)
    h = (x - m) * r * g + b
    seed = jnp.int32(23)
    q1, s1 = sr_quantize_colwise_ln(jnp.asarray(x), jnp.asarray(m),
                                    jnp.asarray(r), jnp.asarray(g),
                                    jnp.asarray(b), seed)
    q2, s2 = sr_quantize_colwise(jnp.asarray(h), seed)
    np.testing.assert_allclose(np.asarray(s1), np.asarray(s2),
                               rtol=1e-5)
    # identical SR streams + near-identical inputs: stray one-step
    # differences only at float boundaries
    dq = np.abs(np.asarray(q1, np.int32) - np.asarray(q2, np.int32))
    assert dq.max() <= 1 and (dq != 0).mean() < 0.01
