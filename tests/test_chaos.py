"""Chaos-soak harness (resilience/chaos.py + invariants.py): the
deterministic seed matrix asserted in tier-1, the conservation-ledger
and invariant-checker units, and the pinned seeds that demonstrably
catch the PR-3 deferred failure-path bug classes — each pinned test
re-introduces the pre-fix code path via monkeypatch and asserts the
harness goes red on that exact seed, then green on the fixed code.
Everything runs on CPU with virtual clocks and seeded RNG: a red
episode is reproducible from its seed alone."""
import threading
import types

import numpy as np
import pytest

from paddle_tpu.resilience import chaos, faults, invariants
from paddle_tpu.resilience.invariants import (ConservationLedger,
                                              InvariantViolation)

pytestmark = pytest.mark.chaos


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.clear()
    faults.reset_counts()
    yield
    faults.clear()


# -- the sweep covers the whole fault-point catalogue ------------------

def test_sweep_covers_registered_fault_points():
    """Adding a fault point to faults.KNOWN_POINTS without enrolling
    it in an episode kind silently shrinks the soak — fail loudly."""
    sweeps = {"serving": set(chaos.SERVING_SWEEP),
              "training": set(chaos.TRAINING_SWEEP),
              "frontdoor": set(chaos.FRONTDOOR_SWEEP),
              "cluster": set(chaos.CLUSTER_SWEEP),
              "control": set(chaos.CONTROL_SWEEP)}
    swept = set().union(*sweeps.values())
    assert swept == set(faults.KNOWN_POINTS)
    # coverage ownership is a partition (front-door episodes also
    # SAMPLE the serving points — the full stack includes the
    # engines — but each point is owned by exactly one sweep)
    names = sorted(sweeps)
    for i, a in enumerate(names):
        for b in names[i + 1:]:
            assert not sweeps[a] & sweeps[b], (a, b)


# -- conservation ledger units (no engine, injected state) -------------

def _req(rid, finished=True, reason="length", toks=(), max_new=4):
    return types.SimpleNamespace(
        rid=rid, finished=finished, finish_reason=reason,
        out_tokens=list(toks), max_new_tokens=max_new)


def test_ledger_exactly_once_accounting():
    led = ConservationLedger()
    a, b, c = _req(0), _req(1), _req(2)
    for r in (a, b, c):
        led.on_submitted(r)
    led.on_delivered(a, via="step")
    led.on_delivered(b, via="recover")
    led.on_delivered(c, via="drain")
    assert led.violations() == []
    led.check()                                  # no raise


def test_ledger_catches_lost_duplicate_phantom_nonterminal():
    led = ConservationLedger()
    lost = _req(0)                               # never delivered
    dup = _req(1)
    nonterm = _req(2, finished=False, reason=None)
    noreason = _req(3, finished=True, reason=None)
    for r in (lost, dup, nonterm, noreason):
        led.on_submitted(r)
    led.on_delivered(dup, via="step")
    led.on_delivered(dup, via="recover")         # double delivery
    led.on_delivered(nonterm, via="step")        # not terminal
    led.on_delivered(noreason, via="step")       # no finish_reason
    phantom = _req(9)
    led.on_delivered(phantom, via="step")        # never submitted
    v = "\n".join(led.violations())
    assert "request 0 LOST" in v
    assert "request 1 DELIVERED 2 times" in v
    assert "not in a terminal state" in v
    assert "without a finish_reason" in v
    assert "phantom" in v
    with pytest.raises(InvariantViolation, match="LOST"):
        led.check()


def test_ledger_frontdoor_attempt_law():
    """Mounted at the front door, the ledger also audits admission:
    every attempt gets exactly one outcome (accept | typed reject) —
    an attempt that produced neither is a vanished request."""
    led = ConservationLedger()
    a, b = _req(0), _req(1)
    led.on_attempt()
    led.on_submitted(a)
    led.on_attempt()
    led.on_rejected(tenant="t", reason="rate_limited")
    led.on_delivered(a, via="stream")
    led.on_delivered(b, via="stream")   # phantom — never submitted
    v = "\n".join(led.violations())
    assert "phantom" in v
    led2 = ConservationLedger()
    led2.on_attempt()
    led2.on_attempt()                   # outcome never recorded
    led2.on_submitted(a)
    led2.on_delivered(a, via="stream")
    assert any("vanished at the boundary" in s
               for s in led2.violations())


def test_token_prefix_invariant():
    ref = [5, 6, 7, 8]
    ok_full = _req(0, reason="length", toks=[5, 6, 7, 8], max_new=4)
    ok_part = _req(1, reason="deadline", toks=[5, 6], max_new=4)
    bad_tok = _req(2, reason="length", toks=[5, 9], max_new=2)
    too_long = _req(3, reason="length", toks=[5, 6, 7, 8, 1],
                    max_new=5)
    short_len = _req(4, reason="length", toks=[5], max_new=3)
    v = invariants.token_prefix_violations(
        [(ok_full, ref), (ok_part, ref), (bad_tok, ref),
         (too_long, ref), (short_len, ref)])
    joined = "\n".join(v)
    assert "request 0" not in joined and "request 1" not in joined
    assert "request 2 tokens diverged" in joined
    assert "request 3" in joined            # longer than the replay
    assert "request 4 finished 'length' with 1/3" in joined


def test_loss_trajectory_invariant():
    base = [(0, 1.0), (1, 0.5), (2, 0.25)]
    ok = {"losses": [(0, 1.0), (1, 0.5), (2, 0.25)]}
    resumed = {"losses": [(2, 0.25)]}       # relaunch tail: still ok
    assert invariants.loss_trajectory_violations([ok, resumed],
                                                 base) == []
    diverged = {"losses": [(0, 1.0), (1, 0.75)]}
    dup_step = {"losses": [(0, 1.0), (0, 1.0)]}
    v = "\n".join(invariants.loss_trajectory_violations(
        [diverged, dup_step], base))
    assert "diverged from the uninjected baseline" in v
    assert "not strictly increasing" in v


def test_thread_leak_invariant():
    before = list(threading.enumerate())
    assert invariants.thread_leak_violations(before) == []
    stop = threading.Event()
    t = threading.Thread(target=stop.wait, name="chaos-leak",
                         daemon=False)
    t.start()
    try:
        v = invariants.thread_leak_violations(before)
        assert v and "chaos-leak" in v[0]
    finally:
        stop.set()
        t.join()


# -- the deterministic seed matrix (acceptance criterion) --------------
# >= 25 seeded episodes spanning serving and training, every invariant
# asserted per episode. A red seed reproduces standalone:
#   python -c "from paddle_tpu.resilience import chaos; \
#              print(chaos.run_serving_episode(SEED).violations)"

SERVING_SEEDS = list(range(0, 13))
TRAINING_SEEDS = list(range(100, 112))
# the replica-kill + front-door arm (ISSUE 7): FrontDoor over a 2-3
# replica router, whole-replica kills (flag + mid-step, i.e. mid-
# prefill/mid-stream), audited END-TO-END at the front door. Across
# this band: >= 12 episodes with at least one replica death and
# >= 10 with requests failed over to a peer (pinned below so the
# band cannot silently go quiet).
FRONTDOOR_SEEDS = list(range(300, 325))
# the tensor-parallel + disaggregated arm (ISSUE 9): mesh engines over
# the emulated 8-device CPU mesh — TP=2 on odd seeds, disaggregated
# 2-prefill + 2-decode on even seeds — with the sharded-decode and
# mid-KV-handoff kill arms sampled on top of the usual serving faults.
# Every episode is audited against the SAME single-chip reference
# outputs (cross-flavor token identity) plus the page/slot/staged-
# handoff no-leak laws across both chip groups.
TP_SERVING_SEEDS = list(range(400, 425))
# the cross-process arm (ISSUE 11): the same ReplicaRouter, but each
# replica is a RemoteReplica proxy over a REAL worker subprocess —
# killed three ways per the sampled schedule: cooperative flag,
# mid-step SIGKILL (immediate, or armed at a serving fault point
# INSIDE the worker so it dies mid-prefill/mid-decode), and network
# partition (cluster.rpc.* wire faults outlasting the retry budget).
# Audited end to end at the front door, plus per-worker page/slot
# audits fetched over RPC from the survivors. Needs the native
# TCPStore extension for rendezvous; skipped (not silently green)
# where it can't build.
CLUSTER_SEEDS = list(range(500, 525))


def _have_cluster():
    try:
        from paddle_tpu.distributed.store import get_lib
        return get_lib() is not None
    except Exception:
        return False


_serving_spec_tally = {"episodes": 0, "speculative": 0,
                       "accepted_drafts": 0, "verify_kills": 0,
                       "chunked": 0, "chunk_kills": 0,
                       "tiered": 0, "demotions": 0, "promotions": 0,
                       "tier_kills": 0, "draft_proposed": 0,
                       "spec_sampled": 0, "spec_tuned": 0,
                       "draft_kills": 0, "draft_faults": 0}

# chunk-budget controller coverage, fed by BOTH serving matrices
# (single-chip and TP — the controller rides on any chunked engine)
_chunk_ctl_tally = {"bands": set(), "controlled": 0, "adaptations": 0}


@pytest.mark.parametrize("seed", SERVING_SEEDS)
def test_serving_episode_matrix(seed):
    res = chaos.run_serving_episode(seed)
    assert res.ok, "\n".join(res.violations)
    assert res.stats["requests"] >= 1
    _serving_spec_tally["episodes"] += 1
    _serving_spec_tally["speculative"] += \
        1 if res.stats["speculative"] else 0
    _serving_spec_tally["accepted_drafts"] += \
        res.stats["spec_accepted_drafts"]
    _serving_spec_tally["verify_kills"] += \
        res.fired.get("serving.decode.verify", 0)
    _serving_spec_tally["chunked"] += \
        1 if res.stats["prefill_chunk"] else 0
    _serving_spec_tally["chunk_kills"] += \
        res.fired.get("serving.prefill.chunk", 0)
    _serving_spec_tally["tiered"] += 1 if res.stats["kv_tiered"] else 0
    _serving_spec_tally["demotions"] += res.stats["demotions"]
    _serving_spec_tally["promotions"] += res.stats["promotions"]
    _serving_spec_tally["tier_kills"] += \
        res.fired.get("serving.kv.demote", 0) \
        + res.fired.get("serving.kv.promote", 0)
    _serving_spec_tally["draft_proposed"] += \
        1 if res.stats["spec_proposer"] == "draft" else 0
    _serving_spec_tally["spec_sampled"] += \
        1 if res.stats["spec_sampled"] else 0
    _serving_spec_tally["spec_tuned"] += \
        1 if res.stats["spec_tuned"] else 0
    _serving_spec_tally["draft_kills"] += \
        res.fired.get("serving.spec.draft", 0)
    _serving_spec_tally["draft_faults"] += \
        res.stats["spec_draft_faults"]
    _chunk_ctl_tally["controlled"] += 1 if res.stats["chunk_ctl"] else 0
    _chunk_ctl_tally["adaptations"] += res.stats["chunk_adaptations"]
    if _serving_spec_tally["episodes"] == len(SERVING_SEEDS):
        _chunk_ctl_tally["bands"].add("serving")


def test_serving_matrix_actually_speculates():
    """The speculative arm must stay LOADED: episodes that really run
    the verify program, really accept drafted tokens, and really get
    killed mid-verify-step — otherwise the speculative-mode soak goes
    green by vacuity."""
    if _serving_spec_tally["episodes"] < len(SERVING_SEEDS):
        pytest.skip("full serving matrix did not run")
    assert _serving_spec_tally["speculative"] >= 4, _serving_spec_tally
    assert _serving_spec_tally["accepted_drafts"] >= 3, \
        _serving_spec_tally
    assert _serving_spec_tally["verify_kills"] >= 2, _serving_spec_tally


def test_serving_matrix_actually_chunks():
    """The chunked-prefill arm must stay LOADED: episodes that really
    run with a ``prefill_chunk`` budget (sampled on its own rng stream
    so pre-chunk seeds stay bit-identical) and really get killed
    MID-CHUNK (between chunks of a PREFILLING request) — otherwise
    the ``serving.prefill.chunk`` coverage goes green by vacuity."""
    if _serving_spec_tally["episodes"] < len(SERVING_SEEDS):
        pytest.skip("full serving matrix did not run")
    assert _serving_spec_tally["chunked"] >= 3, _serving_spec_tally
    assert _serving_spec_tally["chunk_kills"] >= 1, _serving_spec_tally


def test_serving_matrix_actually_tiers():
    """The KV-tier arm must stay LOADED: episodes that really run with
    a host tier attached (sampled on its own rng stream so pre-tier
    seeds stay bit-identical), episodes that really demote cold pages
    to host RAM under the clamped pool, and at least one promotion
    genuinely installing a host page back on-device — otherwise the
    tier regime soaks green by vacuity. Kills ON the tier fault
    points are pinned separately (the dropped-promotion seed below
    fires ``serving.kv.promote`` on every run). Floors re-baselined
    for ISSUE-19: draft-model speculation accepts multi-token runs on
    two of the band's tiered seeds, finishing them in fewer decode
    steps and below the demotion-pressure threshold — band demotions
    dropped from 4 to 2; the pinned dropped-promotion seed still
    proves real demotions AND promotions on every run."""
    if _serving_spec_tally["episodes"] < len(SERVING_SEEDS):
        pytest.skip("full serving matrix did not run")
    assert _serving_spec_tally["tiered"] >= 3, _serving_spec_tally
    assert _serving_spec_tally["demotions"] >= 2, _serving_spec_tally
    assert _serving_spec_tally["promotions"] >= 1, _serving_spec_tally


def test_serving_matrix_actually_drafts():
    """The draft-model arm must stay LOADED: speculative episodes that
    really run a ``DraftModelProposer`` (sampled on its own rng stream
    so pre-spec-v2 seeds stay bit-identical), episodes that really
    submit sampled (temperature > 0) requests through the sampled
    acceptance rule, episodes that really attach the accept-rate
    tuner, and at least one kill genuinely fired ON a draft proposal
    with the fault contained (the row fell back to k=1, the episode
    stayed green) — otherwise the ISSUE-19 regimes soak green by
    vacuity. The resample kill point needs a sampled + draft + armed
    draw and is pinned separately below."""
    if _serving_spec_tally["episodes"] < len(SERVING_SEEDS):
        pytest.skip("full serving matrix did not run")
    assert _serving_spec_tally["draft_proposed"] >= 4, _serving_spec_tally
    assert _serving_spec_tally["spec_sampled"] >= 1, _serving_spec_tally
    assert _serving_spec_tally["spec_tuned"] >= 2, _serving_spec_tally
    assert _serving_spec_tally["draft_kills"] >= 1, _serving_spec_tally
    assert _serving_spec_tally["draft_faults"] >= 1, _serving_spec_tally


# ISSUE-17 chaos certification, the false-positive half: the SAME 25
# seeded serving workloads (identical rng schedules — every draw still
# happens; only the fault arming is skipped) with a watchtower mounted
# must raise ZERO incidents. Any page here is a detector that would
# cry wolf on healthy production traffic.
WATCHTOWER_CLEAN_SEEDS = list(range(25))


@pytest.mark.parametrize("seed", WATCHTOWER_CLEAN_SEEDS)
def test_watchtower_clean_band_raises_zero_incidents(seed):
    res = chaos.run_serving_episode(seed, watchtower=True,
                                    arm_faults=False)
    assert res.ok, "\n".join(res.violations)
    assert res.fired == {}                   # genuinely clean
    assert res.stats["incidents"] == 0, res.stats["incident_kinds"]


@pytest.mark.parametrize("seed", TRAINING_SEEDS)
def test_training_episode_matrix(seed, tmp_path):
    res = chaos.run_training_episode(seed, str(tmp_path))
    assert res.ok, "\n".join(res.violations)


_tp_tally = {"episodes": 0, "disagg": 0, "handoff_kills": 0,
             "sharded_kills": 0, "recoveries": 0, "chunked": 0,
             "chunk_kills": 0, "wired": 0, "wire_handoffs": 0,
             "wire_kills": 0}


@pytest.mark.parametrize("seed", TP_SERVING_SEEDS)
def test_tp_serving_episode_matrix(seed):
    import jax
    if jax.device_count() < 4:
        pytest.skip("mesh episodes need the 8-device emulation")
    flavor = "disagg" if seed % 2 == 0 else "tp"
    res = chaos.run_serving_episode(seed, mesh_flavor=flavor)
    assert res.ok, "\n".join(res.violations)
    assert res.stats["mesh"] == flavor
    assert res.stats["tp"] == 2          # both flavors decode at TP=2
    _tp_tally["episodes"] += 1
    _tp_tally["disagg"] += 1 if res.stats["mesh"] == "disagg" else 0
    _tp_tally["handoff_kills"] += \
        res.fired.get("serving.kv.handoff", 0)
    _tp_tally["sharded_kills"] += \
        res.fired.get("serving.decode.sharded", 0)
    _tp_tally["recoveries"] += res.stats["recoveries"]
    _tp_tally["chunked"] += 1 if res.stats["prefill_chunk"] else 0
    _tp_tally["chunk_kills"] += \
        res.fired.get("serving.prefill.chunk", 0)
    _tp_tally["wired"] += 1 if res.stats["kv_wired"] else 0
    _tp_tally["wire_handoffs"] += res.stats["wire_handoffs"]
    _tp_tally["wire_kills"] += res.fired.get("cluster.kv.wire", 0)
    _chunk_ctl_tally["controlled"] += 1 if res.stats["chunk_ctl"] else 0
    _chunk_ctl_tally["adaptations"] += res.stats["chunk_adaptations"]
    if _tp_tally["episodes"] == len(TP_SERVING_SEEDS):
        _chunk_ctl_tally["bands"].add("tp")


def test_serving_matrices_actually_adapt_chunk_budget():
    """ISSUE-20 coverage floor: the chunk-budget controller must stay
    LOADED across the chunked serving episodes (both bands feed it) —
    episodes that really run under the controller and budgets that
    really move. Otherwise the adaptive-chunk soak is vacuous."""
    if _chunk_ctl_tally["bands"] != {"serving", "tp"}:
        pytest.skip("both serving matrices did not run in full")
    assert _chunk_ctl_tally["controlled"] >= 3, _chunk_ctl_tally
    assert _chunk_ctl_tally["adaptations"] >= 3, _chunk_ctl_tally


def test_tp_matrix_actually_kills_handoffs_and_sharded_decodes():
    """The mesh arm must stay LOADED: episodes that really run
    disaggregated, really get killed MID-KV-HANDOFF (span computed on
    the prefill group, not yet installed on the decode pool) and
    mid-sharded-decode, and really recover — otherwise the
    tensor-parallel soak goes green by vacuity."""
    if _tp_tally["episodes"] < len(TP_SERVING_SEEDS):
        pytest.skip("full TP serving matrix did not run")
    assert _tp_tally["disagg"] >= 10, _tp_tally
    assert _tp_tally["handoff_kills"] >= 5, _tp_tally
    assert _tp_tally["sharded_kills"] >= 8, _tp_tally
    assert _tp_tally["recoveries"] >= 5, _tp_tally
    # chunked prefill composes with the mesh: episodes really chunk
    # on the mesh engines and really get killed mid-chunk there too
    assert _tp_tally["chunked"] >= 6, _tp_tally
    assert _tp_tally["chunk_kills"] >= 2, _tp_tally


def test_tp_matrix_actually_ships_kv_over_the_wire():
    """The wire-handoff arm (ISSUE 18) must stay LOADED: disaggregated
    episodes that really route every KV handoff through the
    authenticated socket transport (sampled on its own rng stream so
    pre-fabric seeds stay bit-identical), handoffs that really
    round-trip the wire, and ``cluster.kv.wire`` faults that really
    fire mid-transfer — otherwise the cross-host handoff soak goes
    green by vacuity."""
    if _tp_tally["episodes"] < len(TP_SERVING_SEEDS):
        pytest.skip("full TP serving matrix did not run")
    assert _tp_tally["wired"] >= 8, _tp_tally
    assert _tp_tally["wire_handoffs"] >= 10, _tp_tally
    assert _tp_tally["wire_kills"] >= 4, _tp_tally


_frontdoor_death_tally = {"episodes": 0, "deaths": 0,
                          "failover_requests": 0,
                          "control": 0, "sheds": 0, "tier0_sheds": 0,
                          "affinity_hits": 0, "scale_actions": 0,
                          "control_arms": 0}


@pytest.mark.parametrize("seed", FRONTDOOR_SEEDS)
def test_frontdoor_episode_matrix(seed):
    res = chaos.run_frontdoor_episode(seed)
    assert res.ok, "\n".join(res.violations)
    assert res.stats["requests"] >= 1
    _frontdoor_death_tally["episodes"] += 1
    _frontdoor_death_tally["deaths"] += \
        1 if res.stats["replica_deaths"] else 0
    _frontdoor_death_tally["failover_requests"] += \
        res.stats["failover_requests"]
    _frontdoor_death_tally["control"] += \
        1 if res.stats["control_on"] else 0
    _frontdoor_death_tally["sheds"] += res.stats["sheds"]
    _frontdoor_death_tally["tier0_sheds"] += \
        res.stats["sheds_by_tier"].get(0, 0)
    _frontdoor_death_tally["affinity_hits"] += \
        res.stats["affinity_hits"]
    _frontdoor_death_tally["scale_actions"] += \
        res.stats["scale_actions"]
    _frontdoor_death_tally["control_arms"] += sum(
        res.fired.get(p, 0) for p in ("control.shed",
                                      "control.affinity",
                                      "control.scale"))


def test_frontdoor_matrix_actually_controls():
    """ISSUE-20 coverage floors: the control arms must stay LOADED —
    across the band the brownout must actually shed (never tier 0),
    prefix affinity must actually route warm, the autoscaler must
    actually act, and the control.* actuator faults must actually
    fire. Otherwise the self-driving soak goes green by vacuity (the
    per-episode graceful-degradation law lives inside the episode)."""
    if _frontdoor_death_tally["episodes"] < len(FRONTDOOR_SEEDS):
        pytest.skip("full front-door matrix did not run")
    assert _frontdoor_death_tally["control"] >= 8, \
        _frontdoor_death_tally
    assert _frontdoor_death_tally["sheds"] >= 3, \
        _frontdoor_death_tally
    assert _frontdoor_death_tally["tier0_sheds"] == 0, \
        _frontdoor_death_tally
    assert _frontdoor_death_tally["affinity_hits"] >= 3, \
        _frontdoor_death_tally
    assert _frontdoor_death_tally["scale_actions"] >= 2, \
        _frontdoor_death_tally
    assert _frontdoor_death_tally["control_arms"] >= 2, \
        _frontdoor_death_tally


def test_frontdoor_matrix_actually_kills_replicas():
    """The replica-kill arm must stay LOADED: if sampling drift ever
    stops killing replicas (or failing requests over), the matrix
    would go green by vacuity — pin the coverage floor."""
    if _frontdoor_death_tally["episodes"] < len(FRONTDOOR_SEEDS):
        pytest.skip("full front-door matrix did not run")
    assert _frontdoor_death_tally["deaths"] >= 12, \
        _frontdoor_death_tally
    assert _frontdoor_death_tally["failover_requests"] >= 10, \
        _frontdoor_death_tally


_cluster_tally = {"episodes": 0, "requests": 0, "coop": 0,
                  "sigkill": 0, "partition": 0, "authpart": 0,
                  "deaths": 0, "failover_requests": 0, "respawns": 0,
                  "partition_incidents": 0, "death_incidents": 0,
                  "auth_blips": 0, "weights_arms": 0}


@pytest.mark.parametrize("seed", CLUSTER_SEEDS)
def test_cluster_episode_matrix(seed):
    if not _have_cluster():
        pytest.skip("native TCPStore extension unavailable")
    res = chaos.run_cluster_episode(seed)
    assert res.ok, "\n".join(res.violations)
    # every episode offers load; whether any request COMPLETES is
    # chaos-dependent (a seed may legitimately refuse every submit
    # with a typed error while both workers are down — e.g. seed
    # 519).  Completed-request coverage is floored band-wide below.
    assert res.stats["attempts"] >= 1
    _cluster_tally["episodes"] += 1
    _cluster_tally["requests"] += res.stats["requests"]
    for kind in ("coop", "sigkill", "partition", "authpart"):
        _cluster_tally[kind] += res.stats["kills"].get(kind, 0)
    _cluster_tally["auth_blips"] += 1 if res.stats["auth_blip"] else 0
    _cluster_tally["weights_arms"] += \
        1 if res.stats["weights_arm"] else 0
    _cluster_tally["deaths"] += 1 if res.stats["replica_deaths"] else 0
    _cluster_tally["failover_requests"] += \
        res.stats["failover_requests"]
    _cluster_tally["respawns"] += res.stats["respawns"]
    # watchtower attribution law, per episode: an episode where no
    # worker died must raise NO death-class incidents (the false-
    # positive bar under full chaos load)
    kinds = {tuple(k) for k in res.stats["incident_kinds"]}
    death_kinds = {k for k in kinds
                   if k[0] in ("partition", "worker_death")}
    if not res.stats["replica_deaths"]:
        assert not death_kinds, res.stats
    _cluster_tally["partition_incidents"] += \
        1 if ("partition", "dispatch") in kinds else 0
    _cluster_tally["death_incidents"] += \
        1 if ("worker_death", "failover") in kinds else 0


def test_cluster_matrix_actually_kills_workers():
    """The cross-process arm must stay LOADED, per kill KIND: across
    the band, real cooperative kills, real SIGKILLs, and real
    partitions must each fire, workers must actually die, requests
    must actually fail over, and the supervisor must actually respawn
    — otherwise the cluster soak goes green by vacuity."""
    if _cluster_tally["episodes"] < len(CLUSTER_SEEDS):
        pytest.skip("full cluster matrix did not run")
    assert _cluster_tally["requests"] >= 25, _cluster_tally
    assert _cluster_tally["coop"] >= 4, _cluster_tally
    assert _cluster_tally["sigkill"] >= 4, _cluster_tally
    assert _cluster_tally["partition"] >= 4, _cluster_tally
    assert _cluster_tally["deaths"] >= 8, _cluster_tally
    assert _cluster_tally["failover_requests"] >= 6, _cluster_tally
    assert _cluster_tally["respawns"] >= 6, _cluster_tally


def test_cluster_matrix_actually_exercises_the_fabric():
    """The serving-fabric arms (ISSUE 18) must stay LOADED across the
    band: auth blips (``cluster.rpc.auth`` under the handshake/frame
    retry budget, healed invisibly), auth partitions (exhausted auth =
    a fenced worker: respawned like any partition), and weight-store
    fetch faults (``cluster.weights.fetch`` armed inside the worker
    against its manifest fetch, absorbed by the digest-verified
    retry). All sampled on the fabric rng stream so the pre-fabric
    kill schedules stay bit-identical."""
    if _cluster_tally["episodes"] < len(CLUSTER_SEEDS):
        pytest.skip("full cluster matrix did not run")
    assert _cluster_tally["authpart"] >= 3, _cluster_tally
    assert _cluster_tally["auth_blips"] >= 6, _cluster_tally
    assert _cluster_tally["weights_arms"] >= 6, _cluster_tally


def test_cluster_matrix_watchtower_attributes_kills():
    """ISSUE-17 chaos certification, band-wide: the watchtower mounted
    on every cluster episode must raise correctly-attributed incidents
    for the REAL kills — network partitions as ``(partition,
    dispatch)`` (the wire died past the retry budget; the worker may
    be fine) and coop/SIGKILL deaths as ``(worker_death, failover)``.
    The per-episode false-positive law (no deaths -> no death-class
    incidents) is asserted inside the matrix itself."""
    if _cluster_tally["episodes"] < len(CLUSTER_SEEDS):
        pytest.skip("full cluster matrix did not run")
    assert _cluster_tally["partition_incidents"] >= 3, _cluster_tally
    assert _cluster_tally["death_incidents"] >= 3, _cluster_tally


def test_matrix_spans_all_kinds_and_enough_episodes():
    assert len(SERVING_SEEDS) + len(TRAINING_SEEDS) >= 25
    assert len(FRONTDOOR_SEEDS) >= 25      # ISSUE-7 acceptance bar
    assert len(TP_SERVING_SEEDS) >= 25     # ISSUE-9 acceptance bar
    assert len(CLUSTER_SEEDS) >= 25        # ISSUE-11 acceptance bar


def test_episodes_are_deterministic():
    """Same seed, same schedule, same faults fired, same verdict —
    the property that makes a red episode a one-line reproducer."""
    a = chaos.run_serving_episode(3)
    b = chaos.run_serving_episode(3)
    assert [(x.point, x.times, x.after) for x in a.schedule] \
        == [(x.point, x.times, x.after) for x in b.schedule]
    assert a.fired == b.fired
    assert a.violations == b.violations
    assert a.stats == b.stats


def test_frontdoor_episodes_are_deterministic():
    """Replica kills, failover adoption order, stream faults — all a
    function of the seed alone (virtual clocks, seeded RNG)."""
    a = chaos.run_frontdoor_episode(306)
    b = chaos.run_frontdoor_episode(306)
    assert [(x.point, x.times, x.after) for x in a.schedule] \
        == [(x.point, x.times, x.after) for x in b.schedule]
    assert a.fired == b.fired
    assert a.violations == b.violations
    assert a.stats == b.stats
    assert a.stats["replica_deaths"] >= 1     # the arm is loaded


def test_cluster_episodes_are_deterministic():
    """The kill schedule, workload, and verdict are a function of the
    seed alone even across the process boundary (every RPC carries the
    virtual clock). `fired` is deliberately NOT compared: when a
    worker is SIGKILLed the client may notice via proc.poll() before
    the next send or via a wire error after it — same outcome, but a
    kernel-timing race over whether one more client-side wire fault
    gets consumed."""
    if not _have_cluster():
        pytest.skip("native TCPStore extension unavailable")
    a = chaos.run_cluster_episode(502)
    b = chaos.run_cluster_episode(502)
    assert [(x.point, x.times, x.after) for x in a.schedule] \
        == [(x.point, x.times, x.after) for x in b.schedule]
    assert a.violations == b.violations
    assert a.stats["kills"] == b.stats["kills"]
    assert a.stats["requests"] == b.stats["requests"]
    assert a.stats["replica_deaths"] >= 1     # the arm is loaded


# -- open-ended soak (slow tier: excluded from smoke via `full`) -------

@pytest.mark.full
def test_open_ended_soak(tmp_path):
    """A wider randomized seed band than the tier-1 matrix — the
    `full`-tier soak; benchmarks/chaos_soak.py runs the same episodes
    under a wall/episode budget for longer hunts."""
    red = []
    for seed in range(200, 240):
        kind = "serving" if seed % 2 == 0 else "training"
        res = chaos.run_episode(seed, kind, workdir=str(tmp_path))
        if not res.ok:
            red.append((seed, kind, res.violations))
    assert not red, red


# -- pinned seeds: the harness catches the PR-3 deferred bug classes ---
# Each test re-introduces the PRE-FIX code path and asserts the pinned
# seed's fault schedule drives the ledger red (the bug class is
# DETECTED), while the fixed code stays green on the same seed.

PINNED_SEED_BUG_A = 17      # deadline expiry in the step a decode
PINNED_SEED_BUG_B = 7       # fault lands in / fault mid-drain
# (re-pinned for the SPECULATIVE episode flow — the speculative-engine
# sampling, verify fault arm and repetitive pool prompts shifted every
# seed's schedule)


def test_pinned_seed_catches_lost_finished_on_failed_step(monkeypatch):
    """Deferred bug (a): pre-fix, a request that reached a terminal
    state inside a step that then faulted (deadline-cancel sweep +
    decode fault in the same step) lived only in step()'s local
    `finished` list and vanished with the raise."""
    from paddle_tpu.serving import ServingEngine
    orig_step = ServingEngine.step

    def prefix_step(self):
        n = len(self._undelivered)
        try:
            return orig_step(self)
        except Exception:
            del self._undelivered[n:]   # pre-fix: the list was a local
            raise

    monkeypatch.setattr(ServingEngine, "step", prefix_step)
    red = chaos.run_serving_episode(PINNED_SEED_BUG_A)
    assert not red.ok
    assert any("LOST" in v for v in red.violations), red.violations
    monkeypatch.setattr(ServingEngine, "step", orig_step)
    green = chaos.run_serving_episode(PINNED_SEED_BUG_A)
    assert green.ok, "\n".join(green.violations)


PINNED_SEED_PAGE_LEAK = 15  # paged-prefill fault mid-admission
# (re-pinned from 14 for the CHUNKED episode flow — seed 14 now draws
# a prefill_chunk budget on the chunk rng stream, which routes its
# mid-prefill fault through the chunk unwind instead of the
# monolithic abort path this pin exercises; 15 stays unchunked)


def test_pinned_seed_catches_leaked_pages_on_aborted_prefill(
        monkeypatch):
    """No-leaked-pages law (paged KV): a prefill that faults AFTER
    claiming pages must unwind them (abort_sequence). With the unwind
    disabled, the pinned seed's mid-prefill fault strands refcounts
    and the page-leak audit goes red; the real code stays green."""
    from paddle_tpu.serving.slot_cache import PagedKVCache
    orig = PagedKVCache.abort_sequence
    monkeypatch.setattr(PagedKVCache, "abort_sequence",
                        lambda self, slot, req: None)
    red = chaos.run_serving_episode(PINNED_SEED_PAGE_LEAK)
    assert not red.ok
    assert any("leaked page" in v or "reservation" in v
               for v in red.violations), red.violations
    monkeypatch.setattr(PagedKVCache, "abort_sequence", orig)
    green = chaos.run_serving_episode(PINNED_SEED_PAGE_LEAK)
    assert green.ok, "\n".join(green.violations)


PINNED_SEED_CHUNK_LOST = 1   # chunk fault mid-prefill (chunk=8)


def test_pinned_seed_catches_swallowed_chunk_fault(monkeypatch):
    """ISSUE-14 pinned red seed: a fault BETWEEN chunks of a
    PREFILLING request must unwind the slot (paged claims aborted,
    lease freed) AND requeue the request for a token-identical
    replay. With the pre-fix semantics — the faulted request is
    silently dropped on the floor, its slot/page claims torn down but
    nobody re-queued — the conservation ledger goes RED with a LOST
    request; the real unwind+requeue path stays green on the same
    seed and really fires the ``serving.prefill.chunk`` fault."""
    from paddle_tpu.serving import ServingEngine
    orig = ServingEngine._unwind_chunk

    def dropped(self, slot, req, requeue):
        # pre-fix: swallow the unwind's requeue half — the request
        # vanishes mid-prefill
        self._clear_chunk_state(slot, req)
        self.cache.release(slot)
        req.slot = None

    monkeypatch.setattr(ServingEngine, "_unwind_chunk", dropped)
    red = chaos.run_serving_episode(PINNED_SEED_CHUNK_LOST)
    assert not red.ok
    assert any("LOST" in v for v in red.violations), red.violations
    monkeypatch.setattr(ServingEngine, "_unwind_chunk", orig)
    green = chaos.run_serving_episode(PINNED_SEED_CHUNK_LOST)
    assert green.ok, "\n".join(green.violations)
    assert green.stats["prefill_chunk"] == 8
    assert green.fired.get("serving.prefill.chunk", 0) >= 1


PINNED_SEED_SHED = 321   # control-on overload: the brownout sheds


def test_pinned_seed_unaudited_shed_goes_lost(monkeypatch):
    """ISSUE-20 pinned red seed: a shed request that skips its audited
    rejection (the client still gets the typed ``Shed``, but the
    ledger never hears about it) must trip the admission law as LOST
    — brownout is load SHEDDING, never load losing. The real path
    (every shed flows through ``_reject`` -> ``on_rejected``) stays
    green on the same seed, and really sheds."""
    from paddle_tpu.serving.frontdoor import FrontDoor
    orig = FrontDoor._reject

    def silent_shed(self, tenant, reason, tier=0):
        if reason == "shed":
            return       # pre-fix semantics: refusal without audit
        orig(self, tenant, reason, tier)

    monkeypatch.setattr(FrontDoor, "_reject", silent_shed)
    red = chaos.run_frontdoor_episode(PINNED_SEED_SHED)
    assert not red.ok
    assert any("LOST" in v for v in red.violations), red.violations
    monkeypatch.setattr(FrontDoor, "_reject", orig)
    green = chaos.run_frontdoor_episode(PINNED_SEED_SHED)
    assert green.ok, "\n".join(green.violations)
    assert green.stats["sheds"] >= 1


PINNED_SEED_NO_FAILOVER = 306   # replica death with requests aboard


def test_pinned_seed_catches_disabled_failover(monkeypatch):
    """ISSUE-7 pinned red seed: with the router's failover path
    DISABLED (a dead replica's requests die with it — the pre-router
    world, where a dead engine took its requests along), the
    front-door ledger must go RED with LOST violations THROUGH the
    router; the real failover path stays green on the same seed."""
    from paddle_tpu.serving.router import ReplicaRouter
    orig = ReplicaRouter._failover

    def no_failover(self, rep):
        # pre-fix semantics: the replica's host state is gone and the
        # router forgets everything it had dispatched there
        eng = rep.engine
        gone = list(eng._undelivered) + eng.scheduler.pending() \
            + [eng.cache.slots[s] for s in eng.cache.active_slots()]
        for req in gone:
            self._inflight.pop(req.rid, None)
            self._owner.pop(req.rid, None)

    monkeypatch.setattr(ReplicaRouter, "_failover", no_failover)
    red = chaos.run_frontdoor_episode(PINNED_SEED_NO_FAILOVER)
    assert not red.ok
    assert any("LOST" in v for v in red.violations), red.violations
    monkeypatch.setattr(ReplicaRouter, "_failover", orig)
    green = chaos.run_frontdoor_episode(PINNED_SEED_NO_FAILOVER)
    assert green.ok, "\n".join(green.violations)
    assert green.stats["replica_deaths"] >= 1
    assert green.stats["failover_requests"] >= 1


PINNED_SEED_CLUSTER_LOST = 502   # worker killed with requests aboard


def test_pinned_seed_catches_disabled_cluster_failover(monkeypatch):
    """ISSUE-11 pinned red seed: with respawn disabled AND the
    router's failover path disabled, a REAL worker-process death takes
    its in-flight requests with it and the ledger goes RED with LOST
    — proof the cluster band is exercising actual cross-process
    recovery, not an in-process simulation of it. The real path stays
    green on the same seed with real deaths and real failovers."""
    if not _have_cluster():
        pytest.skip("native TCPStore extension unavailable")
    from paddle_tpu.serving.router import ReplicaRouter
    orig = ReplicaRouter._failover

    def no_failover(self, rep):
        # pre-fix semantics: the worker process is gone and the router
        # forgets everything it had dispatched there (RemoteEngine's
        # host-side mirrors expose the same shape as a live engine)
        eng = rep.engine
        gone = list(eng._undelivered) + eng.scheduler.pending() \
            + [eng.cache.slots[s] for s in eng.cache.active_slots()]
        for req in gone:
            self._inflight.pop(req.rid, None)
            self._owner.pop(req.rid, None)

    monkeypatch.setattr(ReplicaRouter, "_failover", no_failover)
    red = chaos.run_cluster_episode(PINNED_SEED_CLUSTER_LOST,
                                    respawn=False)
    assert not red.ok
    assert any("LOST" in v for v in red.violations), red.violations
    monkeypatch.setattr(ReplicaRouter, "_failover", orig)
    green = chaos.run_cluster_episode(PINNED_SEED_CLUSTER_LOST)
    assert green.ok, "\n".join(green.violations)
    assert green.stats["replica_deaths"] >= 1
    assert green.stats["failover_requests"] >= 1


def test_pinned_seed_catches_drain_discarding_done(monkeypatch):
    """Deferred bug (b): pre-fix, drain()'s step loop let a mid-drain
    exception propagate, discarding the already-finished `done` list
    — the caller lost every result the drain had collected."""
    from paddle_tpu.serving import ServingEngine
    from paddle_tpu.serving.errors import RequestCancelled
    orig_drain = ServingEngine.drain

    def prefix_drain(self, max_steps=None):
        self._closed = True
        done = []
        steps = 0
        self._in_drain = True
        try:
            while self.has_work():
                cutoff = "drain cutoff" if (
                    max_steps is not None and steps >= max_steps) \
                    else (f"drain on broken engine ({self._broken})"
                          if self._broken else None)
                if cutoff is not None:
                    for req in self.scheduler.drain():
                        req.finished, req.finish_reason = \
                            True, "cancelled"
                        req.error = RequestCancelled(req.rid, cutoff)
                        self.metrics.on_finished(req.rid)
                        done.append(req)
                    for s in self.cache.active_slots():
                        req = self.cache.slots[s]
                        req.finished, req.finish_reason = \
                            True, "cancelled"
                        req.error = RequestCancelled(req.rid, cutoff)
                        self._evict(s, req, done)
                    break
                done.extend(self.step())   # pre-fix: a raise here
                steps += 1                 # discards `done`
        finally:
            self._in_drain = False
        if self.auditor is not None:
            for r in done:
                self.auditor.on_delivered(r, via="drain")
        return done

    monkeypatch.setattr(ServingEngine, "drain", prefix_drain)
    red = chaos.run_serving_episode(PINNED_SEED_BUG_B)
    assert not red.ok
    assert any("LOST" in v for v in red.violations), red.violations
    monkeypatch.setattr(ServingEngine, "drain", orig_drain)
    green = chaos.run_serving_episode(PINNED_SEED_BUG_B)
    assert green.ok, "\n".join(green.violations)


PINNED_SEED_BROKEN_SPEC = 8   # speculative episode with real accepts
# (re-pinned 5 -> 6 for the ISSUE-9 verify GATE: no-draft steps now
# run the k=1 decode program, so the broken-acceptance patch only
# distorts steps that really carry drafts; re-pinned 6 -> 8 for the
# ISSUE-16 tier duty cycle: seed 6's tiered workload changed and its
# drafts now verify clean — seed 8 still has partially rejected
# drafts, which is exactly what the patch mis-emits)


def test_pinned_seed_catches_broken_speculative_acceptance(
        monkeypatch):
    """Speculative-mode pinned red seed (ISSUE 8): with the verify
    step's acceptance/rollback DELIBERATELY broken — the engine trusts
    the whole draft window instead of the in-program accepted length,
    i.e. rejected draft tokens are emitted as if verified — the token-
    identity audit must go RED (the stream carries tokens sequential
    greedy would never have produced). The real acceptance rule stays
    green on the same seed, with drafts genuinely accepted and the
    mid-verify kill arm genuinely fired — so the law is not green by
    vacuity."""
    from paddle_tpu.serving import ServingEngine
    orig = ServingEngine._emit_verified

    def trust_the_whole_draft(self, slot, req, greedy_row, acc,
                              logits_row, *a, **kw):
        return orig(self, slot, req, greedy_row, len(greedy_row),
                    logits_row, *a, **kw)

    monkeypatch.setattr(ServingEngine, "_emit_verified",
                        trust_the_whole_draft)
    red = chaos.run_serving_episode(PINNED_SEED_BROKEN_SPEC)
    assert not red.ok
    assert any("diverged" in v or "emitted" in v
               for v in red.violations), red.violations
    monkeypatch.setattr(ServingEngine, "_emit_verified", orig)
    green = chaos.run_serving_episode(PINNED_SEED_BROKEN_SPEC)
    assert green.ok, "\n".join(green.violations)
    assert green.stats["speculative"]
    assert green.stats["spec_accepted_drafts"] >= 1
    assert green.fired.get("serving.decode.verify", 0) >= 1


PINNED_SEED_DROPPED_HANDOFF = 412   # disagg episode, handoff kill


def test_pinned_seed_dropped_kv_handoff_goes_lost(monkeypatch):
    """ISSUE-9 pinned red seed: a DROPPED KV handoff must be detected.
    With the handoff failure SWALLOWED (the pre-fix shape: the engine
    eats the mid-handoff exception instead of routing it through the
    abort/requeue path, so the request is neither served nor
    returned), the conservation ledger must go RED with LOST on the
    pinned disaggregated seed; the real path — abort_sequence unwinds
    the decode-side page claims, the staged span dies with the frame,
    and the request requeues — stays green on the same seed, with the
    handoff kill arm genuinely fired (not green by vacuity)."""
    from paddle_tpu.resilience.faults import InjectedFault
    from paddle_tpu.serving import ServingEngine
    orig = ServingEngine._prefill

    def swallow_handoff_failure(self, slot, req):
        try:
            return orig(self, slot, req)
        except InjectedFault as e:
            if getattr(e, "point", "") != "serving.kv.handoff":
                raise
            return          # pre-fix: request dropped on the floor

    monkeypatch.setattr(ServingEngine, "_prefill",
                        swallow_handoff_failure)
    red = chaos.run_serving_episode(PINNED_SEED_DROPPED_HANDOFF,
                                    mesh_flavor="disagg")
    assert not red.ok
    assert any("LOST" in v for v in red.violations), red.violations
    monkeypatch.setattr(ServingEngine, "_prefill", orig)
    green = chaos.run_serving_episode(PINNED_SEED_DROPPED_HANDOFF,
                                      mesh_flavor="disagg")
    assert green.ok, "\n".join(green.violations)
    assert green.fired.get("serving.kv.handoff", 0) >= 1
    assert green.stats["mesh"] == "disagg"


PINNED_SEED_WIRE_LOST = 11   # disagg episode, wire arm past budget


def test_pinned_seed_swallowed_wire_handoff_goes_lost(monkeypatch):
    """ISSUE-18 pinned red seed: a wire KV handoff that fails PAST the
    retry budget must abort and requeue, never vanish. The pinned
    seed's ``cluster.kv.wire`` arm outlasts the transport's 3-attempt
    budget, so the typed :class:`KVWireError` surfaces mid-handoff
    (span staged, decode-side pages claimed). With that error
    SWALLOWED at the prefill boundary — the pre-fix shape: neither
    served nor requeued — the conservation ledger goes RED with LOST;
    the real path (staged span dropped, ``abort_sequence`` returns the
    page claims, request requeued and re-shipped on a fresh transfer
    id) stays green on the same seed, with the wire arm genuinely
    fired past budget and real handoffs genuinely round-tripping the
    socket (not green by vacuity)."""
    from paddle_tpu.serving import ServingEngine
    from paddle_tpu.serving.kv_wire import KVWireError
    orig = ServingEngine._prefill

    def swallow_wire_failure(self, slot, req):
        try:
            return orig(self, slot, req)
        except KVWireError:
            return          # pre-fix: request dropped on the floor

    monkeypatch.setattr(ServingEngine, "_prefill",
                        swallow_wire_failure)
    red = chaos.run_serving_episode(PINNED_SEED_WIRE_LOST)
    assert not red.ok
    assert any("LOST" in v for v in red.violations), red.violations
    monkeypatch.setattr(ServingEngine, "_prefill", orig)
    green = chaos.run_serving_episode(PINNED_SEED_WIRE_LOST)
    assert green.ok, "\n".join(green.violations)
    assert green.stats["mesh"] == "disagg"
    assert green.stats["kv_wired"]
    assert green.stats["wire_handoffs"] >= 1
    # past-budget: more fires than one ship's 3-attempt budget
    assert green.fired.get("cluster.kv.wire", 0) >= 4


PINNED_SEED_DROPPED_PROMOTION = 696   # tiered episode, promote kill


def test_pinned_seed_dropped_kv_promotion_goes_lost(monkeypatch):
    """ISSUE-16 pinned red seed: a DROPPED KV promotion must be
    detected. With the mid-promotion failure SWALLOWED at the prefill
    boundary (the pre-fix shape: the engine eats the exception after
    the request was staged and its dst pages claimed, so the request
    is neither served nor returned), the conservation ledger must go
    RED with LOST on the pinned tiered seed; the real path — the
    staged-promotion unwind pops the staging entry, returns the dst
    pages and the tier pins through ``abort_sequence``, and the
    request requeues and retries — stays green on the same seed, with
    the promote kill arm genuinely fired and real demotions AND
    promotions behind it (not green by vacuity)."""
    from paddle_tpu.resilience.faults import InjectedFault
    from paddle_tpu.serving import ServingEngine
    orig = ServingEngine._prefill

    def swallow_promotion_failure(self, slot, req):
        try:
            return orig(self, slot, req)
        except InjectedFault as e:
            if getattr(e, "point", "") != "serving.kv.promote":
                raise
            return          # pre-fix: request dropped on the floor

    monkeypatch.setattr(ServingEngine, "_prefill",
                        swallow_promotion_failure)
    red = chaos.run_serving_episode(PINNED_SEED_DROPPED_PROMOTION,
                                    watchtower=True)
    assert not red.ok
    assert any("LOST" in v for v in red.violations), red.violations
    # ISSUE-17: the watchtower detects the same drop LIVE — the
    # request the metrics plane still tracks but the engine forgot is
    # an orphan, attributed to the phase it was last seen in
    # (kv_promotion: on_promotion_start fired at staging, before the
    # kill point)
    assert ("request_orphaned", "kv_promotion") \
        in red.stats["incident_kinds"], red.stats
    monkeypatch.setattr(ServingEngine, "_prefill", orig)
    green = chaos.run_serving_episode(PINNED_SEED_DROPPED_PROMOTION,
                                      watchtower=True)
    assert green.ok, "\n".join(green.violations)
    assert green.fired.get("serving.kv.promote", 0) >= 1
    # the real path unwinds and requeues: nothing orphaned, no page
    assert green.stats["incidents"] == 0, green.stats
    assert green.stats["kv_tiered"]
    assert green.stats["demotions"] >= 1
    assert green.stats["promotions"] >= 1


# -- disarmed maybe_fail is (nearly) free ------------------------------

def test_maybe_fail_disarmed_path_is_lock_free(monkeypatch):
    """The zero-cost contract for every instrumented hot path
    (per-sample dataloader, per-op store, per-step engines): with no
    rule armed and no PTPU_FAULTS, ``maybe_fail`` is ONE cached bool
    plus one env probe — it never touches ``_lock`` and never bumps a
    counter. Arming a rule flips it onto the locked slow path; an env
    arm set mid-process (forked workers, monkeypatch) must still take
    effect on the very next evaluation."""

    class _CountingLock:
        def __init__(self, inner):
            self.inner = inner
            self.acquisitions = 0

        def __enter__(self):
            self.acquisitions += 1
            return self.inner.__enter__()

        def __exit__(self, *exc):
            return self.inner.__exit__(*exc)

    monkeypatch.delenv("PTPU_FAULTS", raising=False)
    probe = _CountingLock(faults._lock)
    monkeypatch.setattr(faults, "_lock", probe)

    assert faults._disarmed is True
    for _ in range(1000):
        faults.maybe_fail("serving.step.decode")
    assert probe.acquisitions == 0
    assert faults.hits("serving.step.decode") == 0  # no bookkeeping

    faults.inject("serving.step.decode", times=1)
    assert faults._disarmed is False
    before = probe.acquisitions
    with pytest.raises(faults.InjectedFault):
        faults.maybe_fail("serving.step.decode")
    assert probe.acquisitions > before       # armed = the locked walk
    assert faults.fired("serving.step.decode") == 1
    faults.clear()
    assert faults._disarmed is True

    # the env probe is the one read that cannot be cached away
    monkeypatch.setenv("PTPU_FAULTS", "serving.step.decode:1")
    with pytest.raises(faults.InjectedFault):
        faults.maybe_fail("serving.step.decode")
    monkeypatch.delenv("PTPU_FAULTS")
    faults.maybe_fail("serving.step.decode")  # disarms lazily, no raise
    assert faults._disarmed is True


PINNED_SEED_SPEC_RESAMPLE = 44   # sampled + draft episode, both spec
# kill points armed (found by scanning the rng6 stream: needs a
# speculative draw, a draft-proposer draw with an INDEPENDENT draft
# model — an oracle self-draft never rejects, so the residual resample
# never runs — a sampled-acceptance draw, and both arm draws hot)


def test_pinned_seed_spec_kill_points_fire():
    """ISSUE-19 coverage pin: both new fault points must genuinely
    fire inside one episode and stay CONTAINED. ``serving.spec.draft``
    kills a draft proposal mid-step (the row falls back to k=1, the
    proposer state for that rid is unwound); ``serving.spec.resample``
    kills between the first rejection and the residual draw (the
    step's already-accepted prefix survives, the bonus token is
    dropped, the request continues next step). The episode must end
    green with real residual resamples besides the killed ones —
    proof the sampled acceptance rule actually rejects on this seed
    rather than the kill point being the only thing exercised."""
    res = chaos.run_serving_episode(PINNED_SEED_SPEC_RESAMPLE)
    assert res.ok, "\n".join(res.violations)
    assert res.stats["spec_proposer"] == "draft", res.stats
    assert res.stats["spec_sampled"], res.stats
    assert res.fired.get("serving.spec.draft", 0) >= 1, res.fired
    assert res.fired.get("serving.spec.resample", 0) >= 1, res.fired
    assert res.stats["spec_draft_faults"] >= 1, res.stats
    assert res.stats["spec_resamples"] >= 1, res.stats


PINNED_SEED_SWALLOWED_DRAFT = 5   # draft episode, draft kill armed


def test_pinned_seed_swallowed_draft_fault_goes_lost(monkeypatch):
    """ISSUE-19 pinned red seed: a draft-model failure must be
    CONTAINED, never escalated. With the containment broken in the
    tempting-but-wrong direction — the engine treats a failed draft
    proposal as fatal to the REQUEST and evicts it unfinished (the
    pre-fix shape: finish it with a synthetic reason and throw away
    the tokens) — the conservation ledger goes RED with LOST on the
    pinned seed. The real path — ``_on_draft_fault`` unwinds the
    proposer's per-rid state, the row falls back to k=1 for that step,
    and target decoding proceeds — stays green on the same seed with
    the kill arm genuinely fired and real accepted drafts behind it
    (not green by vacuity)."""
    from paddle_tpu.serving import ServingEngine
    orig = ServingEngine._on_draft_fault

    def escalate_draft_fault(self, slot, req, proposer, exc):
        req.finished = True
        req.finish_reason = "draft_fault"
        self._evict(slot, req, [])   # pre-fix: tokens dropped on floor

    monkeypatch.setattr(ServingEngine, "_on_draft_fault",
                        escalate_draft_fault)
    red = chaos.run_serving_episode(PINNED_SEED_SWALLOWED_DRAFT)
    assert not red.ok
    assert any("LOST" in v for v in red.violations), red.violations
    monkeypatch.setattr(ServingEngine, "_on_draft_fault", orig)
    green = chaos.run_serving_episode(PINNED_SEED_SWALLOWED_DRAFT)
    assert green.ok, "\n".join(green.violations)
    assert green.stats["spec_proposer"] == "draft", green.stats
    assert green.fired.get("serving.spec.draft", 0) >= 1, green.fired
    assert green.stats["spec_draft_faults"] >= 1, green.stats
    assert green.stats["spec_accepted_drafts"] >= 1, green.stats
