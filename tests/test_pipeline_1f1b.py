"""On-device 1F1B pipeline schedule (distributed/pipeline.
pipeline_train_1f1b): numeric parity with the autodiff'd GPipe engine,
and the 1F1B memory property (O(S) not O(M) in-flight activations).

Reference: pipeline_scheduler_pass/pipeline_1f1b.py:39 and the dygraph
runtime fleet/meta_parallel/pipeline_parallel.py:575 — executed there
over NCCL p2p, here as one jitted SPMD scan with ppermute hops.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu.models.gpt import GPTConfig, GPTSpmdTrainer, build_mesh


def _mk(sched, microbatches=4, seed=0):
    cfg = GPTConfig(vocab_size=128, hidden_size=64, num_layers=4,
                    num_heads=4, max_seq_len=64, dtype=jnp.float32)
    mesh = build_mesh(n_devices=8, pipe=2, data=1, fsdp=2, sep=1,
                      model=2)
    # grad_clip effectively off: global-norm clipping normalizes away
    # uniform gradient-scale errors, which would mask an M-times
    # mis-scaled schedule — the exact historical bug
    return cfg, mesh, GPTSpmdTrainer(cfg, mesh,
                                     microbatches=microbatches,
                                     seed=seed, mixed_precision=False,
                                     grad_clip=1e9,
                                     pipeline_schedule=sched)


def test_1f1b_matches_gpipe_two_steps():
    rng = np.random.RandomState(0)
    ids = rng.randint(0, 128, (8, 64)).astype(np.int32)
    lab = rng.randint(0, 128, (8, 64)).astype(np.int32)
    losses = {}
    for sched in ("gpipe", "1f1b"):
        _, _, tr = _mk(sched)
        l0 = float(jax.device_get(tr.train_step(ids, lab)))
        l1 = float(jax.device_get(tr.train_step(ids, lab)))
        losses[sched] = (l0, l1)
    # step 1: identical math before any optimizer divergence
    assert abs(losses["gpipe"][0] - losses["1f1b"][0]) < 1e-4
    # step 2: loss after one identical AdamW update
    assert abs(losses["gpipe"][1] - losses["1f1b"][1]) < 5e-3


def test_1f1b_inflight_memory_is_O_S_not_O_M():
    """At M=16 microbatches the GPipe path must hold all 16 stage
    inputs for backward; 1F1B's ring buffer holds S=2. Compare the
    compiled programs' temp allocation."""
    rng = np.random.RandomState(0)
    ids = rng.randint(0, 128, (16, 64)).astype(np.int32)
    lab = rng.randint(0, 128, (16, 64)).astype(np.int32)
    temps = {}
    for sched in ("gpipe", "1f1b"):
        _, mesh, tr = _mk(sched, microbatches=16)
        fn = tr.build_step()
        with jax.set_mesh(mesh):
            compiled = fn.lower(tr.params, tr.opt_state, ids,
                                lab).compile()
        mem = compiled.memory_analysis()
        temps[sched] = getattr(mem, "temp_size_in_bytes", None)
    if not temps["gpipe"] or not temps["1f1b"]:
        pytest.skip("backend does not report memory analysis")
    assert temps["1f1b"] < temps["gpipe"], temps


def test_1f1b_rejects_unknown_schedule():
    cfg = GPTConfig(vocab_size=128, hidden_size=64, num_layers=2,
                    num_heads=4, max_seq_len=64, dtype=jnp.float32)
    mesh = build_mesh(n_devices=8, pipe=2, data=1, fsdp=2, sep=1,
                      model=2)
    with pytest.raises(ValueError):
        GPTSpmdTrainer(cfg, mesh, pipeline_schedule="zigzag")
