"""Distribution log_prob/entropy/KL checks against scipy.stats
(reference: test/distribution/test_distribution_*.py — per-distribution
numeric suites)."""
import numpy as np
import pytest
from scipy import stats

import paddle_tpu as paddle

D = paddle.distribution


def _lp(dist, value):
    return dist.log_prob(paddle.to_tensor(
        np.asarray(value, np.float32))).numpy()


CASES = [
    ("Normal", lambda: D.Normal(loc=1.0, scale=2.0),
     stats.norm(1.0, 2.0), np.linspace(-3, 5, 7)),
    ("Laplace", lambda: D.Laplace(loc=0.5, scale=1.5),
     stats.laplace(0.5, 1.5), np.linspace(-3, 4, 7)),
    ("Uniform", lambda: D.Uniform(low=-1.0, high=3.0),
     stats.uniform(-1.0, 4.0), np.linspace(-0.5, 2.5, 5)),
    ("Exponential", lambda: D.Exponential(rate=2.0),
     stats.expon(scale=0.5), np.linspace(0.1, 3, 5)),
    ("Beta", lambda: D.Beta(alpha=2.0, beta=3.0),
     stats.beta(2.0, 3.0), np.linspace(0.1, 0.9, 5)),
    ("Gamma", lambda: D.Gamma(concentration=2.0, rate=1.5),
     stats.gamma(2.0, scale=1 / 1.5), np.linspace(0.2, 4, 5)),
    ("Gumbel", lambda: D.Gumbel(loc=0.0, scale=1.0),
     stats.gumbel_r(0.0, 1.0), np.linspace(-2, 4, 5)),
    ("Cauchy", lambda: D.Cauchy(loc=0.0, scale=1.0),
     stats.cauchy(0.0, 1.0), np.linspace(-4, 4, 5)),
    ("StudentT", lambda: D.StudentT(df=5.0, loc=0.0, scale=1.0),
     stats.t(5.0), np.linspace(-3, 3, 5)),
    ("LogNormal", lambda: D.LogNormal(loc=0.0, scale=0.8),
     stats.lognorm(0.8, scale=1.0), np.linspace(0.2, 4, 5)),
]


@pytest.mark.parametrize("name,mk,sp,values", CASES,
                         ids=[c[0] for c in CASES])
def test_log_prob_matches_scipy(name, mk, sp, values):
    got = _lp(mk(), values)
    np.testing.assert_allclose(got, sp.logpdf(values), rtol=2e-4,
                               atol=2e-5)


def test_discrete_log_prob_matches_scipy():
    np.testing.assert_allclose(
        _lp(D.Bernoulli(probs=0.3), [0.0, 1.0]),
        stats.bernoulli(0.3).logpmf([0, 1]), rtol=1e-5)
    np.testing.assert_allclose(
        _lp(D.Poisson(rate=2.5), [0.0, 1.0, 4.0]),
        stats.poisson(2.5).logpmf([0, 1, 4]), rtol=1e-4)
    np.testing.assert_allclose(
        _lp(D.Geometric(probs=0.4), [1.0, 3.0]),
        stats.geom(0.4).logpmf([2, 4]), rtol=1e-4)


@pytest.mark.parametrize("name,mk,sp", [(c[0], c[1], c[2])
                                        for c in CASES[:6]],
                         ids=[c[0] for c in CASES[:6]])
def test_entropy_matches_scipy(name, mk, sp):
    got = float(np.asarray(mk().entropy().numpy()))
    np.testing.assert_allclose(got, sp.entropy(), rtol=2e-4, atol=2e-5)


def test_sample_moments():
    paddle.seed(0)
    n = D.Normal(loc=2.0, scale=0.5)
    s = n.sample([20000]).numpy()
    assert abs(s.mean() - 2.0) < 0.02
    assert abs(s.std() - 0.5) < 0.02
    b = D.Beta(alpha=2.0, beta=5.0)
    sb = b.sample([20000]).numpy()
    np.testing.assert_allclose(sb.mean(), 2 / 7, atol=0.01)


def test_kl_closed_forms_vs_monte_carlo():
    paddle.seed(0)
    pairs = [
        (D.Normal(loc=0.0, scale=1.0), D.Normal(loc=1.0, scale=2.0)),
        (D.Bernoulli(probs=0.3), D.Bernoulli(probs=0.6)),
        (D.Exponential(rate=2.0), D.Exponential(rate=1.0)),
    ]
    for p, q in pairs:
        kl = float(np.asarray(D.kl_divergence(p, q).numpy()))
        s = p.sample([40000])
        mc = float((p.log_prob(s) - q.log_prob(s)).mean())
        np.testing.assert_allclose(kl, mc, rtol=0.08, atol=0.01)


def test_rsample_grad_flows():
    """Reparameterized sampling must carry gradients to parameters."""
    loc = paddle.to_tensor(np.float32(0.0), stop_gradient=False)
    n = D.Normal(loc=loc, scale=1.0)
    paddle.seed(3)
    s = n.rsample([256])
    s.mean().backward()
    assert loc.grad is not None
    np.testing.assert_allclose(float(loc.grad.numpy()), 1.0, atol=1e-4)


def test_transformed_distribution_roundtrip():
    base = D.Normal(loc=0.0, scale=1.0)
    t = D.TransformedDistribution(base, [D.ExpTransform()])
    x = np.array([0.5, 1.0, 2.0], np.float32)
    ref = stats.lognorm(1.0, scale=1.0)
    np.testing.assert_allclose(_lp(t, x), ref.logpdf(x), rtol=1e-4)


def test_lognormal_rsample_support_and_grad():
    paddle.seed(0)
    ln = D.LogNormal(loc=0.0, scale=1.0)
    s = ln.rsample([2000])
    assert float(s.numpy().min()) > 0  # support (0, inf)
    loc = paddle.to_tensor(np.float32(0.0), stop_gradient=False)
    ln2 = D.LogNormal(loc=loc, scale=0.3)
    out = ln2.rsample([512])
    out.mean().backward()
    assert loc.grad is not None and float(loc.grad.numpy()) > 0


def test_chain_transform_mixed_event_rank_ldj():
    """Chain of reduced (StickBreaking) + elementwise (Affine) log-dets
    must align event ranks, not broadcast wrong shapes."""
    x = np.array([0.2, -0.3, 0.5], np.float32)
    chain = D.ChainTransform([D.StickBreakingTransform(),
                              D.AffineTransform(0.0, 2.0)])
    ld = chain.forward_log_det_jacobian(paddle.to_tensor(x)).numpy()
    assert ld.shape == ()  # one scalar per batch element
    sb = D.StickBreakingTransform()
    y = sb.forward(paddle.to_tensor(x))
    ref = (float(sb.forward_log_det_jacobian(
        paddle.to_tensor(x)).numpy())
        + 4 * np.log(2.0))  # affine over the 4-simplex coordinates
    np.testing.assert_allclose(float(ld), ref, rtol=1e-5)


def test_independent_transform_shape_delegation():
    t = D.IndependentTransform(
        D.ReshapeTransform((4,), (2, 2)), 1)
    assert t.forward_shape((3, 4)) == (3, 2, 2)
    assert t.inverse_shape((3, 2, 2)) == (3, 4)


def test_stickbreaking_roundtrip_and_simplex():
    x = np.array([[0.4, -1.0, 0.3]], np.float32)
    sb = D.StickBreakingTransform()
    y = sb.forward(paddle.to_tensor(x))
    assert y.shape == [1, 4]
    np.testing.assert_allclose(y.numpy().sum(-1), 1.0, rtol=1e-5)
    back = sb.inverse(y)
    np.testing.assert_allclose(back.numpy(), x, rtol=1e-4, atol=1e-5)


def test_tanh_sigmoid_transform_ldj():
    x = np.linspace(-2, 2, 5).astype(np.float32)
    for t, deriv in ((D.TanhTransform(), 1 - np.tanh(x) ** 2),
                     (D.SigmoidTransform(),
                      1 / (1 + np.exp(-x)) * (1 - 1 / (1 + np.exp(-x))))):
        ld = t.forward_log_det_jacobian(paddle.to_tensor(x)).numpy()
        np.testing.assert_allclose(ld, np.log(deriv), rtol=1e-4,
                                   atol=1e-5)


def test_normal_log_prob_differentiable_in_params():
    """Variational objectives need d log q(z)/d(loc, scale): a 120-step
    pathwise-gradient fit must recover the target (regression: log_prob
    used to detach parameters from the tape)."""
    paddle.seed(0)
    loc = paddle.to_tensor(np.float32(-1.0), stop_gradient=False)
    log_scale = paddle.to_tensor(np.float32(0.0), stop_gradient=False)
    target = D.Normal(loc=2.0, scale=0.5)
    opt = paddle.optimizer.Adam(learning_rate=0.1,
                                parameters=[loc, log_scale])
    for _ in range(120):
        qd = D.Normal(loc=loc, scale=log_scale.exp())
        z = qd.rsample([256])
        loss = (qd.log_prob(z) - target.log_prob(z)).mean()
        loss.backward()
        opt.step()
        opt.clear_grad()
    assert abs(float(loc) - 2.0) < 0.2, float(loc)
    assert abs(float(log_scale.exp()) - 0.5) < 0.2


def test_normal_accepts_list_params_and_values():
    """Raw Python containers keep working for params and values
    (regression: tape-recording rsample/log_prob broke list inputs)."""
    n = D.Normal(loc=[0.0, 1.0], scale=[1.0, 2.0])
    assert n.rsample([3]).shape == [3, 2]
    lp = n.log_prob([1.0, 2.0]).numpy()
    from scipy import stats as st
    np.testing.assert_allclose(
        lp, [st.norm(0, 1).logpdf(1.0), st.norm(1, 2).logpdf(2.0)],
        rtol=1e-5)
    assert np.isfinite(n.entropy().numpy()).all()


def test_bernoulli_categorical_policy_gradient():
    """REINFORCE-style: d log p / d params must flow for the discrete
    policy distributions (regression: log_prob detached params)."""
    paddle.seed(0)
    logits = paddle.to_tensor(np.zeros(3, np.float32),
                              stop_gradient=False)
    cat = D.Categorical(logits=logits)
    a = cat.sample([64])
    lp = cat.log_prob(a)
    # advantage: reward class 2
    reward = paddle.to_tensor((a.numpy() == 2).astype(np.float32))
    (-(lp * reward).mean()).backward()
    g = logits.grad.numpy()
    assert g is not None and np.isfinite(g).all()
    assert g[2] < 0  # pushing logits toward the rewarded class

    bl = paddle.to_tensor(np.float32(0.0), stop_gradient=False)
    bern = D.Bernoulli(logits=bl)
    s = bern.sample([128])
    lpb = bern.log_prob(s)
    (-(lpb * s).mean()).backward()
    assert bl.grad is not None and np.isfinite(float(bl.grad))

    # entropy regularization differentiates too
    logits2 = paddle.to_tensor(np.array([1.0, 0.0, -1.0], np.float32),
                               stop_gradient=False)
    D.Categorical(logits=logits2).entropy().backward()
    assert logits2.grad is not None


def test_categorical_trains_to_target():
    """A categorical policy trained with REINFORCE concentrates on the
    rewarded action."""
    paddle.seed(0)
    logits = paddle.to_tensor(np.zeros(4, np.float32),
                              stop_gradient=False)
    opt = paddle.optimizer.Adam(learning_rate=0.2, parameters=[logits])
    for _ in range(60):
        cat = D.Categorical(logits=logits)
        a = cat.sample([128])
        r = paddle.to_tensor((a.numpy() == 1).astype(np.float32))
        loss = -(cat.log_prob(a) * (r - 0.25)).mean()
        loss.backward()
        opt.step()
        opt.clear_grad()
    p = np.exp(np.asarray(
        D.Categorical(logits=logits).logits))
    assert p[1] > 0.8, p


def test_categorical_log_prob_broadcasting():
    """Values with size-1 dims broadcast against the batch (old
    take_along_axis behavior) and sample-shaped values broadcast against
    scalar batches."""
    lg = np.log(np.tile(np.array([[0.2, 0.3, 0.5]], np.float32), (3, 1)))
    c = D.Categorical(logits=paddle.to_tensor(lg))
    out = c.log_prob(paddle.to_tensor(np.array([2], np.int64)))
    np.testing.assert_allclose(out.numpy(), np.log([0.5] * 3), rtol=1e-5)
    c2 = D.Categorical(logits=paddle.to_tensor(
        np.log(np.array([0.2, 0.3, 0.5], np.float32))))
    out2 = c2.log_prob(paddle.to_tensor(np.array([0, 1, 2, 1],
                                                 np.int64)))
    np.testing.assert_allclose(out2.numpy(),
                               np.log([0.2, 0.3, 0.5, 0.3]), rtol=1e-5)
